"""Batched modular arithmetic on 16-bit limb arrays (JAX / XLA, TPU-first).

Representation: a field element is ``L`` little-endian 16-bit limbs held in
a ``uint32`` array of shape ``(..., L)``; every operation is batched over
the leading axes.  This is the device-side replacement for the scalar
field/group arithmetic the reference gets from ``curve25519-dalek``
(reference: src/traits.rs:142-238, src/groups.rs:11-90) — but batched: the
DKG protocol's hot loops are per-party/per-coefficient scalar ops
(reference: src/dkg/committee.rs:151-186, :292-296), which here become one
wide array op over all parties at once.

TPU constraints honoured:

* no 64-bit integer ops — all products are 16x16->32 in ``uint32`` lanes;
* no data-dependent control flow — carries/borrows via ``lax.scan`` over
  the (static-length) limb axis, conditionals via branchless selects;
* reduction picks the cheapest admissible lowering per field — pseudo-
  Mersenne fold, linear byte-matrix fold, or classic Barrett — all with
  compile-time constants (see spec.py) and bit-identical canonical output.

Overflow discipline (the invariants that make this correct):

* normalized limbs are < 2**16, stored in uint32;
* schoolbook product columns accumulate <= 2*L terms of < 2**16 each
  (after hi/lo split), so columns are < 2**21 for L<=24 — safely inside
  uint32 for the carry scan;
* Barrett remainder fits in L+1 limbs because r < 3p < b**(L+1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .spec import FieldSpec

# Plain int, not jnp.uint32: a module-level device constant would
# initialise the jax backend at import time, defeating hostmesh's
# platform forcing.  uint32-array ops with a Python int stay uint32.
MASK16 = 0xFFFF

_backend_cache: str | None = None


def _on_tpu() -> bool:
    """Lazy backend probe (never at import time — see hostmesh ordering).

    DKG_TPU_ASSUME_BACKEND overrides the probe: AOT-topology compiles
    (scripts/aot_lab.py, scripts/memproof_tpu.py) run in a CPU process
    but target the TPU compiler, and every backend-sensitive dispatch
    (fused kernels, MXU matmul, table width, RLC schedule) resolves at
    TRACE time — without the override they would compile a program the
    chip never runs.
    """
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_ASSUME_BACKEND", ("tpu", "cpu"),
        "backend the trace-time dispatches assume (AOT compiles)",
    )
    if env is not None:
        return env == "tpu"
    global _backend_cache
    if _backend_cache is None:
        try:
            _backend_cache = jax.default_backend()
        except Exception:  # pragma: no cover — backend init failure
            return False
    return _backend_cache == "tpu"


def fused_kernels_active() -> bool:
    """Whether the hot ops route to the fused Pallas kernels
    (ops/pallas_field.py, ops/pallas_point.py).  Default ON on a real
    TPU backend (Mosaic), OFF elsewhere (interpret mode inside the
    ladder scans would be pathologically slow on CPU);
    DKG_TPU_PALLAS=1/0 forces either way.  Resolved lazily at trace
    time so importing this module never initialises a JAX backend (see
    parallel/hostmesh.py ordering)."""
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_PALLAS", ("0", "1"), "fused Pallas kernel dispatch"
    )
    if env is not None:
        return env == "1"
    return _on_tpu()


def _u32(x) -> jax.Array:
    return jnp.asarray(x, dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# carry / borrow primitives
# ---------------------------------------------------------------------------


def carry_lookahead_active() -> bool:
    """Whether carry/borrow propagation lowers as a log-depth Kogge-Stone
    lookahead (``lax.associative_scan``) instead of the sequential
    ``lax.scan`` ripple.  Both are bit-exact.  Default: ripple scan —
    measured 2x faster than the lookahead on XLA:CPU (the associative
    scan lowers to slice/concat chains there), and the TPU path was
    designed around the lane-parallel scan.  DKG_TPU_CARRY=lookahead
    opts in on backends where log-depth wins."""
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_CARRY", ("scan", "lookahead"), "carry-propagation lowering"
    )
    return env == "lookahead"


def _carry_op(a, b):
    """Carry-lookahead combine: (generate, propagate) semigroup."""
    return b[0] | (b[1] & a[0]), a[1] & b[1]


def _shift_up(x: jax.Array) -> jax.Array:
    """Shift limbs one position up (towards higher significance),
    dropping the top limb; the last-dim length is preserved."""
    pad = [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    return jnp.pad(x, pad)[..., :-1]


def _normalize_lookahead(cols: jax.Array) -> jax.Array:
    # Two local rounds squeeze any uint32 columns to limbs <= 2**16 ...
    x = cols
    for _ in range(2):
        x = (x & MASK16) + _shift_up(x >> 16)
    # ... then one log-depth lookahead settles the +1 ripple carries:
    # carry out of limb j obeys c = g | (p & c_in) with g = "limb == b",
    # p = "limb == b-1", an associative combine.
    g = x >> 16  # in {0, 1}
    r = x & MASK16
    gp = (g, (r == MASK16).astype(jnp.uint32))
    cout, _ = lax.associative_scan(_carry_op, gp, axis=-1)
    return (r + _shift_up(cout)) & MASK16


def normalize(cols: jax.Array, out_len: int) -> jax.Array:
    """Carry-propagate accumulator columns into ``out_len`` 16-bit limbs.

    ``cols`` may hold values up to ``2**32 - 2**16`` per column (the scan
    adds an incoming carry of < 2**16, which must not wrap uint32); the
    result is taken mod ``2**(16*out_len)`` (truncation is intentional —
    callers use it for "mod b**k" semantics).
    """
    cols = _u32(cols)
    k = cols.shape[-1]
    if k < out_len:
        pad = [(0, 0)] * (cols.ndim - 1) + [(0, out_len - k)]
        cols = jnp.pad(cols, pad)
    cols = cols[..., :out_len]
    if carry_lookahead_active():
        return _normalize_lookahead(cols)
    xs = jnp.moveaxis(cols, -1, 0)

    def step(carry, col):
        s = col + carry
        return s >> 16, s & MASK16

    _, limbs = lax.scan(step, jnp.zeros(cols.shape[:-1], jnp.uint32), xs)
    return jnp.moveaxis(limbs, 0, -1)


def sub_with_borrow(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(a - b) mod 2**(16K) plus the final borrow flag (1 iff a < b).

    Both inputs must be normalized limb arrays of equal last-dim K.
    """
    a, b = jnp.broadcast_arrays(_u32(a), _u32(b))
    if carry_lookahead_active():
        d = (a - b) & MASK16  # per-limb difference mod b
        gp = ((a < b).astype(jnp.uint32), (a == b).astype(jnp.uint32))
        bout, _ = lax.associative_scan(_carry_op, gp, axis=-1)
        limbs = (d - _shift_up(bout)) & MASK16
        return limbs, bout[..., -1]
    xs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0))

    def step(borrow, ab):
        ai, bi = ab
        s = ai - bi - borrow  # uint32 wraparound encodes the sign
        return s >> 31, s & MASK16

    borrow, limbs = lax.scan(step, jnp.zeros(a.shape[:-1], jnp.uint32), xs)
    return jnp.moveaxis(limbs, 0, -1), borrow


def cond_sub(x: jax.Array, m) -> jax.Array:
    """Branchless ``x - m if x >= m else x`` on equal-length limb arrays."""
    m = _u32(m)
    d, borrow = sub_with_borrow(x, jnp.broadcast_to(m, x.shape))
    return jnp.where((borrow != 0)[..., None], x, d)


# ---------------------------------------------------------------------------
# wide multiply
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _antidiag_onehot(la: int, lb: int, shift: int) -> np.ndarray:
    """Constant one-hot tensor C[i,j,c] = 1 iff i+j+shift == c: collapses
    the schoolbook product grid into columns with one tensordot.

    float32, not uint32: XLA:CPU has no fast integer GEMM, so a uint32
    tensordot lowers to a scalar loop (~6x slower measured at the
    verify-round batch shape).  The contraction is still exact — every
    operand is an integer < 2**16 and every partial column sum is an
    integer < 2**22 (2L <= 48 terms of < 2**16), inside float32's 2**24
    exact-integer range, so the result round-trips to uint32 bit-exactly
    regardless of summation order."""
    out = np.zeros((la, lb, la + lb), np.float32)
    for i in range(la):
        for j in range(lb):
            out[i, j, i + j + shift] = 1.0
    return out


def _mul_columns(a: jax.Array, b: jax.Array) -> jax.Array:
    """Unnormalized schoolbook product columns: (..., La+Lb) uint32.

    Two backend-matched lowerings of the same column accumulation (bit-
    exact results either way):

    * TPU: product-scanning over a's limbs — each step is one
      (..., Lb)-wide multiply, a hi/lo 16-bit split, and two statically
      shifted adds into the (..., La+Lb) column accumulator.  Fully
      elementwise over the batch, so XLA fuses the chain and no
      (batch, La, Lb) product grid ever reaches HBM (7x faster than the
      tensordot form on v5e at large batches).
    * elsewhere: outer product + one antidiagonal one-hot tensordot,
      lowered as a float32 GEMM (exact — see _antidiag_onehot): XLA:CPU
      has no fast integer matmul, and the f32 form measures ~6x faster
      at the verify-round batch shape while staying bit-identical.

    Column sums stay < 2**22 for L<=24 (2L terms of < 2**16), safely
    inside uint32 (and float32's exact-integer range).
    """
    a, b = _u32(a), _u32(b)
    la, lb = a.shape[-1], b.shape[-1]
    nc = la + lb
    if _on_tpu():
        cols = None
        for i in range(la):
            p = a[..., i : i + 1] * b  # 16x16 -> 32, exact in uint32
            bpad = [(0, 0)] * (p.ndim - 1)
            row = jnp.pad(p & MASK16, bpad + [(i, nc - lb - i)]) + jnp.pad(
                p >> 16, bpad + [(i + 1, nc - lb - i - 1)]
            )
            cols = row if cols is None else cols + row
        return cols
    prod = a[..., :, None] * b[..., None, :]
    lo = (prod & MASK16).astype(jnp.float32)
    hi = (prod >> 16).astype(jnp.float32)
    cols = jnp.tensordot(lo, _antidiag_onehot(la, lb, 0), [[-2, -1], [0, 1]])
    cols = cols + jnp.tensordot(hi, _antidiag_onehot(la, lb, 1), [[-2, -1], [0, 1]])
    return cols.astype(jnp.uint32)


def mul_wide(a: jax.Array, b: jax.Array) -> jax.Array:
    """Full product of limb arrays: (..., La) x (..., Lb) -> (..., La+Lb).

    One carry normalize over the :func:`_mul_columns` accumulator —
    the workhorse under every classic field multiply (the fused GEMM
    twin :func:`_mul_gemm` skips this normalize entirely).
    """
    a, b = _u32(a), _u32(b)
    return normalize(_mul_columns(a, b), a.shape[-1] + b.shape[-1])


# ---------------------------------------------------------------------------
# Barrett reduction and the modular ops
# ---------------------------------------------------------------------------


def barrett_reduce(fs: FieldSpec, x: jax.Array) -> jax.Array:
    """Reduce a normalized 2L-limb value < b**(2L) to L limbs mod p.

    Classic Barrett (HAC Alg. 14.42) with base b = 2**16: the quotient
    estimate is off by at most 2, fixed by two branchless conditional
    subtractions.
    """
    L = fs.limbs
    mu = _u32(fs.barrett_mu)  # (L+1,)
    p_ext = _u32(fs.p_limbs_ext)  # (L+1,)
    q1 = x[..., L - 1 :]  # floor(x / b**(L-1)), L+1 limbs
    q2 = mul_wide(q1, mu)
    q3 = q2[..., L + 1 :]  # floor(q1*mu / b**(L+1)), L+1 limbs
    r1 = x[..., : L + 1]  # x mod b**(L+1)
    r2 = mul_wide(q3, p_ext)[..., : L + 1]  # q3*p mod b**(L+1)
    r, _ = sub_with_borrow(r1, r2)  # wraparound == +b**(L+1): r in [0, 3p)
    r = cond_sub(r, p_ext)
    r = cond_sub(r, p_ext)
    return r[..., :L]


def fold_reduce(fs: FieldSpec, x: jax.Array) -> jax.Array:
    """Pseudo-Mersenne reduction of a 2L-limb value to L limbs mod p.

    Requires ``fs.fold_limbs`` (c = b**L mod p, lc <= 4 limbs; spec.py
    guards admission).  Uses hi*b**L == hi*c (mod p) twice:

    * fold 1: y1 = lo + hi*c       < b**L + b**(L+lc)   (L+lc+1 limbs)
    * fold 2: y2 = lo' + hi'*c     < b**L + b**(2lc+1)  (L+1 limbs)
    * y2 < 3p (spec guard), so two conditional subtractions finish.

    Each fold is one L x lc mul_wide — far cheaper than Barrett's two
    (L+1) x (L+1) multiplies — and the result is the same canonical
    representative in [0, p), so swapping reducers is bit-exact.
    """
    L = fs.limbs
    c = _u32(fs.fold_limbs)
    lc = c.shape[-1]

    def fold(lo, hi, out_len):
        prod = mul_wide(hi, c)
        w = max(prod.shape[-1], lo.shape[-1])

        def pad_to(v):
            return jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(0, w - v.shape[-1])])

        # both operands are normalized limbs (< 2**16): columns < 2**17
        return normalize(pad_to(prod) + pad_to(lo), out_len)

    y1 = fold(x[..., :L], x[..., L:], L + lc + 1)
    y2 = fold(y1[..., :L], y1[..., L:], L + 1)
    p_ext = _u32(fs.p_limbs_ext)
    y2 = cond_sub(y2, p_ext)
    y2 = cond_sub(y2, p_ext)
    return y2[..., :L]


def linear_reduce(fs: FieldSpec, x: jax.Array) -> jax.Array:
    """Linear-fold reduction of a 2L-limb value to L limbs mod p.

    Exploits linearity of "mod p" over limb values (``fs.linred`` holds
    the constants, with every bound proved at admission time):

    1. The high L limbs, read as 2L bytes d_k, fold in ONE small float32
       contraction: hi * b**L = sum_k d_k * D_k (mod p) with
       D_k = 2**(8k+16L) mod p baked into a (2L, 2L) byte matrix —
       column sums < 2**22, so the f32 GEMM is exact.
    2. ``n_split`` scan-free column folds squeeze the remaining excess:
       split columns into lo/hi, shift hi up a limb, and multiply the
       top spill back in through c = b**L mod p.  Pure elementwise work.
    3. One carry normalize; the quotient then comes from a <= 2**13-entry
       table indexed by the value's top ~12 bits (estimate short by at
       most 1), is multiplied back in as q * (b**(L+1) - p) mod
       b**(L+1), and a single conditional subtraction lands in [0, p).

    Three carry passes and one tiny GEMM, versus Barrett's two
    (L+1)-limb multiplies and five carry passes; the canonical output is
    bit-identical, so swapping reducers never changes results.
    """
    lr = fs.linred
    if lr is None:
        raise ValueError(f"{fs.name} does not admit linear_reduce")
    L = fs.limbs
    x = _u32(x)
    if x.shape[-1] != 2 * L:
        raise ValueError("linear_reduce expects a full 2L-limb product")
    lo, hi = x[..., :L], x[..., L:]
    # step 1: byte-matrix fold of the high half
    d8 = jnp.stack([hi & 0xFF, hi >> 8], axis=-1).reshape(*hi.shape[:-1], 2 * L)
    cols8 = jnp.tensordot(d8.astype(jnp.float32), lr.fold8, [[-1], [0]])
    cols8 = cols8.astype(jnp.uint32).reshape(*hi.shape[:-1], L, 2)
    cols = lo + cols8[..., 0] + (cols8[..., 1] << 8)
    # step 2: scan-free column folds of the spill through c = b**L mod p
    c = _u32(lr.c_limbs)
    for _ in range(lr.n_split):
        hi16 = cols >> 16
        cols = (cols & MASK16) + _shift_up(hi16) + hi16[..., L - 1 :] * c
    # step 3: normalize, table quotient, one conditional subtraction
    v = normalize(cols, L + 1)
    u = (v[..., L - 1] >> lr.shift_e) | (v[..., L] << (16 - lr.shift_e))
    q = jnp.take(_u32(lr.qtable), u, axis=0)
    w = normalize(v + q[..., None] * _u32(lr.np_limbs), L + 1)
    return cond_sub(w, _u32(fs.p_limbs_ext))[..., :L]


def reduce_wide(fs: FieldSpec, x: jax.Array) -> jax.Array:
    """Reduce a normalized 2L-limb value to L limbs mod p, picking the
    cheapest admissible reducer: pseudo-Mersenne fold, then the linear
    fold, then Barrett.  All three produce the canonical representative,
    so the choice never changes results — only the op count.
    DKG_TPU_REDUCE=fold|linear|barrett forces one (raising at trace time
    if the field does not admit it), which is how the parity tests pin
    the reducers against each other."""
    from ..utils import envknobs

    forced = envknobs.choice(
        "DKG_TPU_REDUCE", ("fold", "linear", "barrett"), "wide-reduction dispatch"
    )
    if forced == "fold":
        if fs.fold_limbs is None:
            raise ValueError(f"{fs.name} does not admit fold_reduce")
        return fold_reduce(fs, x)
    if forced == "linear":
        return linear_reduce(fs, x)
    if forced == "barrett":
        return barrett_reduce(fs, x)
    if fs.fold_limbs is not None:
        return fold_reduce(fs, x)
    if fs.linred is not None:
        return linear_reduce(fs, x)
    return barrett_reduce(fs, x)


def zeros(fs: FieldSpec, batch: tuple = ()) -> jax.Array:
    return jnp.zeros(batch + (fs.limbs,), jnp.uint32)


def ones(fs: FieldSpec, batch: tuple = ()) -> jax.Array:
    return jnp.broadcast_to(
        jnp.concatenate([jnp.ones(1, jnp.uint32), jnp.zeros(fs.limbs - 1, jnp.uint32)]),
        batch + (fs.limbs,),
    )


def constant(fs: FieldSpec, value: int) -> jax.Array:
    """Embed a Python int as a compile-time limb constant."""
    from .spec import int_to_limbs

    return _u32(int_to_limbs(value % fs.modulus, fs.limbs))


def add(fs: FieldSpec, a: jax.Array, b: jax.Array) -> jax.Array:
    s = normalize(_u32(a) + _u32(b), fs.limbs + 1)  # limb sums < 2**17
    return cond_sub(s, _u32(fs.p_limbs_ext))[..., : fs.limbs]


def sub(fs: FieldSpec, a: jax.Array, b: jax.Array) -> jax.Array:
    # (a + p) - b avoids signed intermediates; result in [0, 2p) then one
    # conditional subtract.
    ap = normalize(_u32(a) + _u32(fs.p_limbs), fs.limbs + 1)
    b_ext = jnp.pad(_u32(b), [(0, 0)] * (jnp.ndim(b) - 1) + [(0, 1)])
    d, _ = sub_with_borrow(*jnp.broadcast_arrays(ap, b_ext))
    return cond_sub(d, _u32(fs.p_limbs_ext))[..., : fs.limbs]


def neg(fs: FieldSpec, a: jax.Array) -> jax.Array:
    return sub(fs, jnp.broadcast_to(zeros(fs), a.shape), a)


def _mul_gemm(fs: FieldSpec, a: jax.Array, b: jax.Array) -> jax.Array:
    """Fused multiply-reduce: schoolbook columns straight into the
    linear fold, with ONE lazy carry normalize at the very end.

    The classic leg runs mul_wide (2L-limb carry scan) then a reducer
    (more carry passes); here the reduction is applied to the
    UNNORMALIZED product columns (each < 2**22 — the mulred admission
    bound), so the 2L-limb normalize between them disappears:

    1. product columns via :func:`_mul_columns` (exact f32 GEMM on the
       XLA:CPU leg, product-scanning on TPU);
    2. the high-half columns split into three bytes each (byte 2 and
       the P_{L-1} spill are < 2**6), folded in ONE exact f32 GEMM
       against the baked (3L+1, 2L) matrix of 2**(16c+8t) mod p
       residues — ``fs.mulred.foldm``;
    3. ``n_split`` scan-free column folds squeeze the spill through
       c = b**L mod p, then the same normalize/quotient-table/cond_sub
       tail as :func:`linear_reduce` — the lazy carry happens here,
       once, over L+1 limbs instead of 2L.

    Every bound (digit caps, f32 exactness, column caps, table index
    range) is proved with exact ints in spec._build_mulred; fields
    without ``fs.mulred`` must use the classic leg.  Output is the
    canonical representative — bit-identical to the classic leg.
    """
    mr = fs.mulred
    if mr is None:
        raise ValueError(f"{fs.name} does not admit the fused GEMM mul")
    L = fs.limbs
    cols = _mul_columns(_u32(a), _u32(b))  # (..., 2L) unnormalized
    plo, phi = cols[..., :L], cols[..., L:]
    digits = jnp.concatenate(
        [phi & 0xFF, (phi >> 8) & 0xFF, phi >> 16, plo[..., L - 1 :] >> 16],
        axis=-1,
    ).astype(jnp.float32)  # (..., 3L+1) in the MulReduceSpec digit order
    cols8 = jnp.tensordot(digits, jnp.asarray(mr.foldm), [[-1], [0]])
    cols8 = cols8.astype(jnp.uint32).reshape(*phi.shape[:-1], L, 2)
    keep = jnp.concatenate([plo[..., : L - 1], plo[..., L - 1 :] & MASK16], axis=-1)
    cols = keep + cols8[..., 0] + (cols8[..., 1] << 8)
    c = _u32(mr.c_limbs)
    for _ in range(mr.n_split):
        hi16 = cols >> 16
        cols = (cols & MASK16) + _shift_up(hi16) + hi16[..., L - 1 :] * c
    v = normalize(cols, L + 1)
    u = (v[..., L - 1] >> mr.shift_e) | (v[..., L] << (16 - mr.shift_e))
    q = jnp.take(_u32(mr.qtable), u, axis=0)
    w = normalize(v + q[..., None] * _u32(mr.np_limbs), L + 1)
    return cond_sub(w, _u32(fs.p_limbs_ext))[..., :L]


def mul_dispatch_mode(fs: FieldSpec) -> str:
    """The ``fd.mul`` formulation active for this field: ``"gemm"``
    (the fused multiply-reduce, :func:`_mul_gemm`) or ``"classic"``
    (mul_wide + reduce_wide).  Both are bit-exact; the choice is pure
    op count.  ``DKG_TPU_MUL=gemm|classic`` forces one (raising at
    trace time when the field does not admit the GEMM form); auto
    takes the fused form wherever admissible on the XLA:CPU leg —
    measured faster on the 16-limb fields (up to 1.15x; the 2L-step
    carry scan it deletes is sequential cost) and neutral on BLS12-381
    base at every batch shape probed — and keeps the
    product-scanning classic form on TPU, where the elementwise chain
    fuses and the Pallas MXU kernel (ops/pallas_mxu.py) is the fused
    tier instead.  Resolved lazily at trace time (hostmesh ordering).
    """
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_MUL",
        ("auto", "gemm", "classic"),
        "fd.mul formulation: fused GEMM multiply-reduce vs classic",
    )
    if env == "gemm":
        if fs.mulred is None:
            raise ValueError(f"{fs.name} does not admit the fused GEMM mul")
        return "gemm"
    if env == "classic":
        return "classic"
    if fs.mulred is not None and not _on_tpu():
        return "gemm"
    return "classic"


def mul(fs: FieldSpec, a: jax.Array, b: jax.Array) -> jax.Array:
    if mul_dispatch_mode(fs) == "gemm":
        return _mul_gemm(fs, a, b)
    return reduce_wide(fs, mul_wide(a, b))


def square(fs: FieldSpec, a: jax.Array) -> jax.Array:
    return mul(fs, a, a)


def pow_const(fs: FieldSpec, x: jax.Array, e: int) -> jax.Array:
    """x**e mod p for a compile-time exponent, via an MSB-first bit scan.

    The exponent bits live in a tiny constant array and the square/multiply
    body is traced once (lax.scan), keeping compile time flat even for
    255-bit exponents (inverse = x**(p-2), Fermat).
    """
    if e < 0:
        raise ValueError("negative exponent")
    if e == 0:
        return jnp.broadcast_to(ones(fs), x.shape)
    bits = [int(b) for b in bin(e)[2:]]
    bits_arr = jnp.asarray(bits, dtype=jnp.uint32)

    def step(acc, bit):
        acc = mul(fs, acc, acc)
        acc_mul = mul(fs, acc, x)
        acc = jnp.where(bit != 0, acc_mul, acc)
        return acc, None

    # Seed with 1 so the first iteration computes x**bits[0] uniformly.
    init = jnp.broadcast_to(ones(fs), x.shape)
    acc, _ = lax.scan(step, init, bits_arr)
    return acc


def inv(fs: FieldSpec, x: jax.Array) -> jax.Array:
    """Fermat inverse x**(p-2); maps 0 -> 0 (callers guard zero)."""
    return pow_const(fs, x, fs.modulus - 2)


def batch_inv(fs: FieldSpec, x: jax.Array, axis: int = 0) -> jax.Array:
    """Montgomery-trick batched inversion along ``axis``.

    One Fermat inversion + 3(k-1) multiplies for k elements; used by
    Lagrange reconstruction (reference: src/polynomial.rs:162-184) when
    denominators are device-resident.  Zero inputs produce garbage in the
    affected lane only (protocol code never inverts zero).
    """
    x = jnp.moveaxis(x, axis, 0)
    k = x.shape[0]

    def fwd(carry, xi):
        nxt = mul(fs, carry, xi)
        return nxt, carry  # prefix EXCLUSIVE product

    total, prefix = lax.scan(fwd, jnp.broadcast_to(ones(fs), x.shape[1:]), x)
    inv_total = inv(fs, total)

    def bwd(carry, args):
        xi, pre = args
        out = mul(fs, carry, pre)  # = 1/xi
        carry = mul(fs, carry, xi)  # strip xi from the running inverse
        return carry, out

    _, invs = lax.scan(bwd, inv_total, (x, prefix), reverse=True)
    return jnp.moveaxis(invs, 0, axis)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a == b, axis=-1)


def is_zero(a: jax.Array) -> jax.Array:
    return jnp.all(a == 0, axis=-1)


def select(pred: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """Branchless limb-array select; pred shape == batch shape."""
    return jnp.where(pred[..., None], a, b)
