"""Modular matrix multiply on the MXU: limb matmuls in int8 systolic passes.

The ceremony's biggest FIELD workload is share evaluation — the n x n
share matrix s[d, i] = f_d(x_i) (reference hot loop #2,
committee.rs:163-186).  Written as a Vandermonde product

    s = C @ V^T  (mod p),   C[d, l] = coeff,  V[i, l] = x_i^l,

it is a modular matmul: contraction over t+1 coefficients for every
(dealer, recipient) pair.  The Horner formulation (poly.device.eval_many)
runs this as t+1 sequential full-width field multiplies on the VPU; this
module instead runs the whole contraction as int8 matmuls on the MXU —
the TPU's systolic array — and defers ALL modular reduction to one
Barrett pass per output element:

1. split every 16-bit limb into two 8-bit halves (base-256 digits);
2. zero-point shift to int8 (the MXU's native dtype) and dot over the
   contraction axis with int32 accumulation — exact integer arithmetic:
   |sum| <= K * 128^2, so K up to 2^17 never wraps int32;
3. undo the zero-point with rank-1 corrections (row/column digit sums);
4. antidiagonal-add the digit products into base-256 columns of the
   un-reduced integer sum_k a_k * b_k  (same schoolbook collapse as
   fields.device.mul_wide, one limb axis now paid by the MXU);
5. carry-normalize and fold the b^(2L)-and-up tail back with the
   precomputed constant 2^(32L) mod p, then one Barrett reduction.

Step 2 is where >99% of the multiplies happen, so the VPU work left per
output element is O(L) instead of O(K*L).

Used by poly.device.eval_many (share dealing) and dkg.ceremony._field_dot
(the scalar side of RLC batch verification) when ``mxu_matmul_active()``;
bit-exact against the Horner/scan paths by construction (tests:
tests/test_field_matmul.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import device as fd
from .spec import FieldSpec, int_to_limbs

# Contraction chunk: keeps every base-256 accumulator column strictly
# inside uint32 — worst case 2L terms/column * 255^2 * KCHUNK
# = 32 * 65025 * 1024 ~= 2.13e9 < 2^32 - 2^24 (normalize headroom).
KCHUNK = 1024

# Output blocking: bound the live (M, NB, 4L-1) uint32 column accumulator
# (plus one (M, NB*2L) int32 dot result) to a few hundred MB.
BLOCK_BYTES = 256 << 20

# Largest supported contraction: the 4L+2-byte accumulator holds values
# < 2^(32L+16) >= K * p^2 and the _reduce_block fold proof assumes
# K <= 2^14; dispatch sites fall back to the scan paths beyond this.
MAX_K = 16384


def mxu_matmul_active() -> bool:
    """Whether modular matmuls route to the MXU int8 formulation.

    DKG_TPU_MXU=1/0 forces; default follows the backend (ON for TPU —
    the int8 dot is exact on every backend, the MXU is just where it
    pays).  Resolved lazily at trace time, like fused_kernels_active().
    """
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_MXU", ("0", "1"), "MXU int8 matmul dispatch; default follows backend"
    )
    if env is not None:
        return env == "1"
    return fd._on_tpu()


@functools.lru_cache(maxsize=None)
def _fold_const(fs: FieldSpec) -> np.ndarray:
    """2^(32*L) mod p as L limbs: folds the b^(2L) tail of an over-wide
    accumulator back into Barrett range."""
    return np.asarray(int_to_limbs(pow(2, 32 * fs.limbs, fs.modulus), fs.limbs),
                      np.uint32)


def _normalize_base256(cols: jax.Array, out_len: int) -> jax.Array:
    """Carry-propagate uint32 base-256 columns into ``out_len`` 8-bit limbs."""
    cols = jnp.asarray(cols, jnp.uint32)
    k = cols.shape[-1]
    if k < out_len:
        cols = jnp.pad(cols, [(0, 0)] * (cols.ndim - 1) + [(0, out_len - k)])
    xs = jnp.moveaxis(cols[..., :out_len], -1, 0)

    def step(carry, col):
        s = col + carry
        return s >> 8, s & 0xFF

    _, limbs = lax.scan(step, jnp.zeros(cols.shape[:-1], jnp.uint32), xs)
    return jnp.moveaxis(limbs, 0, -1)


def _to_digits(a: jax.Array) -> jax.Array:
    """(..., L) 16-bit limbs -> (..., 2L) base-256 digits, little-endian."""
    lo = a & 0xFF
    hi = (a >> 8) & 0xFF
    return jnp.stack([lo, hi], axis=-1).reshape(a.shape[:-1] + (2 * a.shape[-1],))


def _block_cols(fs: FieldSpec, a_dig: jax.Array, b_dig: jax.Array) -> jax.Array:
    """Base-256 columns of sum_k a[m,k]*b[n,k] for one output block.

    a_dig (M, K, D), b_dig (NB, K, D) digits -> (M, NB, 4L+2) 8-bit
    limbs of the exact (un-reduced) integer sums.
    """
    m, k, d = a_dig.shape
    nb = b_dig.shape[0]
    l = d // 2
    w = 2 * d - 1
    nlimb8 = 4 * l + 2  # value < K * p^2 < 2^(32L + 14)
    acc8 = None
    for k0 in range(0, k, KCHUNK):
        a_c = a_dig[:, k0 : k0 + KCHUNK]
        b_c = b_dig[:, k0 : k0 + KCHUNK]
        kc = a_c.shape[1]
        a_s = (a_c.astype(jnp.int32) - 128).astype(jnp.int8)
        b_s = (b_c.astype(jnp.int32) - 128).astype(jnp.int8)
        # rank-1 zero-point corrections: sa[m,u] = sum_k a_s, sb[n,v]
        sa = jnp.sum(a_c.astype(jnp.int32), axis=1) - 128 * kc  # (M, D)
        sb = jnp.sum(b_c.astype(jnp.int32), axis=1) - 128 * kc  # (NB, D)
        b_flat = jnp.moveaxis(b_s, 1, 0).reshape(kc, nb * d)  # (K, NB*D)
        corr_b = (128 * sb.reshape(nb * d) + 16384 * kc)[None, :]
        cols = None
        for u in range(d):
            g = lax.dot_general(
                a_s[:, :, u], b_flat,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # (M, NB*D) exact shifted products
            g = (g + 128 * sa[:, u][:, None] + corr_b).astype(jnp.uint32)
            row = jnp.pad(
                g.reshape(m, nb, d), [(0, 0), (0, 0), (u, w - d - u)]
            )
            cols = row if cols is None else cols + row
        part = _normalize_base256(cols, nlimb8)
        acc8 = part if acc8 is None else acc8 + part
    # chunk partials are 8-bit limbs (< 256 each); one more carry pass
    return _normalize_base256(acc8, nlimb8) if k > KCHUNK else acc8


def _reduce_block(fs: FieldSpec, total8: jax.Array) -> jax.Array:
    """(..., 4L+2) 8-bit limbs -> (..., L) canonical field elements."""
    l = fs.limbs
    y = total8[..., 0::2] + (total8[..., 1::2] << 8)  # (..., 2L+1) 16-bit
    c = jnp.asarray(_fold_const(fs))
    # Two folds of the top limb with c = 2^(32L) mod p:
    #   y0 < 2^(32L+14)  ->  y1 = lo + top*c < b^(2L) + 2^16 * p
    #   ->  y2 < b^(2L)  (if y1's top limb is 1, its low part is < 2^16*p,
    #       so y2 < 2^16*p + p < b^(2L)).  Top limb provably 0 after.
    for _ in range(2):
        hi = y[..., 2 * l :]
        folded = fd.mul_wide(hi, jnp.broadcast_to(c, hi.shape[:-1] + (l,)))
        cols = jnp.pad(
            y[..., : 2 * l].astype(jnp.uint32),
            [(0, 0)] * (y.ndim - 1) + [(0, 1)],
        )
        fw = folded.shape[-1]
        cols = cols + jnp.pad(
            folded[..., : 2 * l + 1],
            [(0, 0)] * (y.ndim - 1) + [(0, max(0, 2 * l + 1 - fw))],
        )
        y = fd.normalize(cols, 2 * l + 1)
    # y is a normalized 2L-limb value: hand it to the per-field reducer
    # dispatch (fold / linear fold / Barrett — all canonical, bit-exact).
    return fd.reduce_wide(fs, y[..., : 2 * l])


def matmul_mod(fs: FieldSpec, a: jax.Array, b: jax.Array) -> jax.Array:
    """sum_k a[m, k] * b[n, k] mod p on the MXU.

    a (M, K, L), b (N, K, L) 16-bit-limb field elements ->
    (M, N, L) canonical (< p) results, bit-exact vs the scan/Horner
    formulations.  K <= 2^14 (the binding bound: the 4L+2-byte
    accumulator holds values < 2^(32L+16) >= K * p^2, and the
    _reduce_block fold proof assumes the same; covers n=16384, the
    largest BASELINE config).  The N axis is processed in blocks sized
    so the per-block accumulators stay a few hundred MB (lax.map: one
    traced body regardless of block count).
    """
    m, k, l = a.shape
    if k > MAX_K:
        raise ValueError(
            f"matmul_mod contraction K={k} exceeds the 2^14 accumulator "
            "bound; chunk the contraction and add partial sums mod p"
        )
    n = b.shape[0]
    a_dig = _to_digits(jnp.asarray(a, jnp.uint32))
    per_col = m * (4 * l - 1) * 4 + m * 2 * l * 4  # cols + dot bytes per n
    # + the block's own digit tensor (k * 2l u32 per column): digits are
    # materialised PER BLOCK inside the map, never for the full N — the
    # TPU compiler rejected the full-N digitization at the BLS n=16384
    # verify shape (u32[2048,16384,32] = 4 GB per operand, x2 operands
    # plus copies; MEMPROOF_TPU_verify_finalise_error.txt).
    per_col += k * 2 * l * 4
    nb = max(1, min(n, BLOCK_BYTES // per_col))

    def block(b_blk):
        return _reduce_block(fs, _block_cols(fs, a_dig, _to_digits(b_blk)))

    b = jnp.asarray(b, jnp.uint32)
    if nb >= n:
        return block(b)
    nblocks = -(-n // nb)
    pad = nblocks * nb - n
    if pad:
        b = jnp.pad(b, [(0, pad), (0, 0), (0, 0)])
    out = lax.map(block, b.reshape(nblocks, nb, k, l))
    return jnp.moveaxis(out, 0, 1).reshape(m, nblocks * nb, l)[:, :n]
