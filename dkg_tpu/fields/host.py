"""Host-side (Python-int) reference field arithmetic.

This is the bit-exact oracle the device path is tested against, and the
implementation used for cold-path host work (point (de)compression,
hash-to-group, Fiat-Shamir transcripts) where byte-twiddling is a poor TPU
fit.  It mirrors the role `curve25519-dalek`'s scalar/field code plays for
the reference (src/groups.rs:11-53).

All functions take a :class:`~dkg_tpu.fields.spec.FieldSpec` and plain
Python ints; batching helpers convert between ints and limb arrays.
"""

from __future__ import annotations

import numpy as np

from .spec import FieldSpec, int_to_limbs, limbs_to_int


def add(fs: FieldSpec, a: int, b: int) -> int:
    return (a + b) % fs.modulus


def sub(fs: FieldSpec, a: int, b: int) -> int:
    return (a - b) % fs.modulus


def mul(fs: FieldSpec, a: int, b: int) -> int:
    return (a * b) % fs.modulus


def neg(fs: FieldSpec, a: int) -> int:
    return (-a) % fs.modulus


def inv(fs: FieldSpec, a: int) -> int:
    if a % fs.modulus == 0:
        raise ZeroDivisionError("inverse of zero")
    return pow(a, fs.modulus - 2, fs.modulus)


def powmod(fs: FieldSpec, a: int, e: int) -> int:
    return pow(a, e, fs.modulus)


def to_bytes(fs: FieldSpec, a: int) -> bytes:
    """Canonical little-endian encoding (reference: traits.rs:162-164)."""
    return int(a % fs.modulus).to_bytes(fs.nbytes, "little")


def from_bytes(fs: FieldSpec, data: bytes) -> int | None:
    """Strict canonical decode; None on wrong length or value >= modulus.

    Length is enforced so every element has exactly one accepted encoding
    (wire-format non-malleability, as in the reference's fixed 32-byte
    scalar/point encodings, traits.rs:162-164).
    """
    if len(data) != fs.nbytes:
        return None
    x = int.from_bytes(data, "little")
    if x >= fs.modulus:
        return None
    return x


def from_bytes_mod_order_wide(fs: FieldSpec, data: bytes) -> int:
    """Reduce an oversized little-endian byte string mod p.

    Used for hash-to-scalar (reference: traits.rs hash_to_scalar via
    Blake2b, src/groups.rs:19-23): 64 uniform bytes reduced mod the group
    order give a near-uniform scalar.
    """
    return int.from_bytes(data, "little") % fs.modulus


# ---------------------------------------------------------------------------
# int <-> limb-array conversion (batched)
# ---------------------------------------------------------------------------


def encode(fs: FieldSpec, values) -> np.ndarray:
    """ints (scalar or nested list) -> uint32 limb array (..., L)."""
    arr = np.asarray(values, dtype=object)
    out = np.zeros(arr.shape + (fs.limbs,), dtype=np.uint32)
    for idx in np.ndindex(arr.shape):
        out[idx] = int_to_limbs(int(arr[idx]) % fs.modulus, fs.limbs)
    if arr.shape == ():
        return out.reshape(fs.limbs)
    return out


def decode(fs: FieldSpec, limbs) -> np.ndarray:
    """uint32 limb array (..., L) -> object array of Python ints."""
    limbs = np.asarray(limbs)
    batch = limbs.shape[:-1]
    out = np.empty(batch, dtype=object)
    for idx in np.ndindex(batch):
        out[idx] = limbs_to_int(limbs[idx])
    return out


def decode_int(fs: FieldSpec, limbs) -> int:
    """Single limb vector -> int."""
    return limbs_to_int(np.asarray(limbs))
