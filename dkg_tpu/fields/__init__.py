from . import device, host
from .spec import (
    ALL_FIELDS,
    BLS12_381_P,
    BLS12_381_R,
    L25519,
    P25519,
    SECP256K1_N,
    SECP256K1_P,
    FieldSpec,
    int_to_limbs,
    limbs_to_int,
)

__all__ = [
    "ALL_FIELDS",
    "BLS12_381_P",
    "BLS12_381_R",
    "L25519",
    "P25519",
    "SECP256K1_N",
    "SECP256K1_P",
    "FieldSpec",
    "device",
    "host",
    "int_to_limbs",
    "limbs_to_int",
]
