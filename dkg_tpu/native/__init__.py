"""ctypes bindings for the native host arithmetic runtime (native/).

Builds ``libdkg_native.so`` from source with g++ on first use (cached in
``build/``), and exposes batched field/curve/ChaCha20 ops on numpy
arrays.  Python-int host code (fields.host / groups.host) remains the
canonical oracle; this library is the fast host path for bulk work
(fixed-base table generation, oracle verification sweeps, bulk DEM).

Availability is optional: ``available()`` gates every use, so the
framework runs unchanged on hosts without a toolchain.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
from typing import Optional

import numpy as np

MAXL = 8
_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_SRC = _REPO / "native" / "dkg_native.cpp"
_LIB = _REPO / "build" / "libdkg_native.so"

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


class FieldCtxStruct(ctypes.Structure):
    _fields_ = [
        ("nlimbs", ctypes.c_uint64),
        ("p", ctypes.c_uint64 * (MAXL + 1)),
        ("mu", ctypes.c_uint64 * (MAXL + 2)),
    ]


class EdCtxStruct(ctypes.Structure):
    _fields_ = [("f", FieldCtxStruct), ("d2", ctypes.c_uint64 * MAXL)]


class WsCtxStruct(ctypes.Structure):
    _fields_ = [("f", FieldCtxStruct), ("b3", ctypes.c_uint64 * MAXL)]


def _build() -> bool:
    _LIB.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        str(_SRC), "-o", str(_LIB),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
        if not _build():
            _build_failed = True
            return None
    try:
        return _bind(ctypes.CDLL(str(_LIB)))
    except AttributeError:
        # stale library missing newly required symbols despite a fresh
        # mtime (same-second checkouts, archive extraction): rebuild
        # once from source before giving up.
        try:
            _LIB.unlink()
        except OSError:
            pass
        if _build():
            try:
                return _bind(ctypes.CDLL(str(_LIB)))
            except (OSError, AttributeError):
                pass
        _build_failed = True
        return None
    except OSError:
        # builds-but-won't-load (e.g. a MinGW DLL whose runtime deps are
        # not on the DLL search path): cache the failure so available()
        # gates every use, as promised — never raise out of the optional
        # runtime.
        _build_failed = True
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    global _lib
    u64p = ctypes.POINTER(ctypes.c_uint64)
    for name, argtypes in {
        "f_add_batch": [ctypes.c_void_p, u64p, u64p, u64p, ctypes.c_size_t],
        "f_sub_batch": [ctypes.c_void_p, u64p, u64p, u64p, ctypes.c_size_t],
        "f_mul_batch": [ctypes.c_void_p, u64p, u64p, u64p, ctypes.c_size_t],
        "f_pow": [ctypes.c_void_p, u64p, u64p, ctypes.c_uint64, u64p],
        "ed_add_batch": [ctypes.c_void_p, u64p, u64p, u64p, ctypes.c_size_t],
        "ed_scalar_mul_batch": [
            ctypes.c_void_p, u64p, ctypes.c_uint64, u64p, u64p, ctypes.c_size_t
        ],
        "ed_scalar_mul_ct_batch": [
            ctypes.c_void_p, u64p, ctypes.c_uint64, ctypes.c_uint64,
            u64p, u64p, ctypes.c_size_t,
        ],
        "ws_add_batch": [ctypes.c_void_p, u64p, u64p, u64p, ctypes.c_size_t],
        "ws_scalar_mul_batch": [
            ctypes.c_void_p, u64p, ctypes.c_uint64, u64p, u64p, ctypes.c_size_t
        ],
        "ws_scalar_mul_ct_batch": [
            ctypes.c_void_p, u64p, ctypes.c_uint64, ctypes.c_uint64,
            u64p, u64p, ctypes.c_size_t,
        ],
        "chacha20_xor": [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ],
    }.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = None
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# int <-> 64-bit limb conversion
# ---------------------------------------------------------------------------


def limbs64(x: int, n: int) -> np.ndarray:
    out = np.zeros(n, np.uint64)
    for i in range(n):
        out[i] = x & 0xFFFFFFFFFFFFFFFF
        x >>= 64
    if x:
        raise ValueError("does not fit")
    return out


def from_limbs64(a) -> int:
    acc = 0
    for i, v in enumerate(np.asarray(a, np.uint64).tolist()):
        acc |= int(v) << (64 * i)
    return acc


def nlimbs64(modulus: int) -> int:
    return (modulus.bit_length() + 63) // 64


class NativeField:
    """Batched field ops over a fixed prime (64-bit-limb Barrett)."""

    def __init__(self, modulus: int):
        self.modulus = modulus
        self.n = nlimbs64(modulus)
        if self.n > MAXL:
            raise ValueError("modulus too wide for native runtime")
        ctx = FieldCtxStruct()
        ctx.nlimbs = self.n
        for i, v in enumerate(limbs64(modulus, self.n + 1)):
            ctx.p[i] = int(v)
        mu = (1 << (128 * self.n)) // modulus
        for i, v in enumerate(limbs64(mu, self.n + 2)):
            ctx.mu[i] = int(v)
        self._ctx = ctx

    def _ptr(self):
        return ctypes.byref(self._ctx)

    def encode(self, vals) -> np.ndarray:
        vals = np.atleast_1d(np.asarray(vals, dtype=object))
        out = np.zeros((len(vals), self.n), np.uint64)
        for i, v in enumerate(vals):
            out[i] = limbs64(int(v) % self.modulus, self.n)
        return out

    def decode(self, arr) -> list[int]:
        arr = np.asarray(arr, np.uint64).reshape(-1, self.n)
        return [from_limbs64(row) for row in arr]

    def _binop(self, name, a, b):
        lib = _load()
        a = np.ascontiguousarray(a, np.uint64)
        b = np.ascontiguousarray(b, np.uint64)
        out = np.empty_like(a)
        count = a.size // self.n
        u64p = ctypes.POINTER(ctypes.c_uint64)
        getattr(lib, name)(
            self._ptr(),
            a.ctypes.data_as(u64p),
            b.ctypes.data_as(u64p),
            out.ctypes.data_as(u64p),
            count,
        )
        return out

    def add(self, a, b):
        return self._binop("f_add_batch", a, b)

    def sub(self, a, b):
        return self._binop("f_sub_batch", a, b)

    def mul(self, a, b):
        return self._binop("f_mul_batch", a, b)

    def pow(self, a, e: int):
        lib = _load()
        a = np.ascontiguousarray(a, np.uint64).reshape(self.n)
        el = np.ascontiguousarray(limbs64(e, (e.bit_length() + 63) // 64 or 1))
        out = np.empty(self.n, np.uint64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.f_pow(
            self._ptr(), a.ctypes.data_as(u64p), el.ctypes.data_as(u64p),
            len(el), out.ctypes.data_as(u64p),
        )
        return out

    def inv(self, a):
        return self.pow(a, self.modulus - 2)


class NativeCurve:
    """Batched point ops (edwards: 4 coords; weierstrass_a0: 3 coords)."""

    def __init__(self, kind: str, modulus: int, const: int):
        self.kind = kind
        self.field = NativeField(modulus)
        n = self.field.n
        if kind == "edwards":
            ctx = EdCtxStruct()
            tgt = ctx.d2
        elif kind == "weierstrass_a0":
            ctx = WsCtxStruct()
            tgt = ctx.b3
        else:
            raise ValueError(kind)
        ctx.f = self.field._ctx
        for i, v in enumerate(limbs64(const % modulus, n)):
            tgt[i] = int(v)
        self._ctx = ctx
        self.ncoords = 4 if kind == "edwards" else 3

    def encode_points(self, pts) -> np.ndarray:
        out = np.zeros((len(pts), self.ncoords, self.field.n), np.uint64)
        for i, p in enumerate(pts):
            for c in range(self.ncoords):
                out[i, c] = limbs64(int(p[c]) % self.field.modulus, self.field.n)
        return out

    def decode_points(self, arr) -> list[tuple]:
        arr = np.asarray(arr, np.uint64).reshape(-1, self.ncoords, self.field.n)
        return [tuple(from_limbs64(row[c]) for c in range(self.ncoords)) for row in arr]

    def add(self, p, q):
        lib = _load()
        p = np.ascontiguousarray(p, np.uint64)
        q = np.ascontiguousarray(q, np.uint64)
        out = np.empty_like(p)
        count = p.size // (self.ncoords * self.field.n)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        name = "ed_add_batch" if self.kind == "edwards" else "ws_add_batch"
        getattr(lib, name)(
            ctypes.byref(self._ctx), p.ctypes.data_as(u64p),
            q.ctypes.data_as(u64p), out.ctypes.data_as(u64p), count,
        )
        return out

    def _scalar_mul_impl(self, suffix, scalars, points, scalar_modulus, extra):
        """Shared marshalling for the vartime and constant-time ladders:
        scalar limb encoding, point layout, and the kind-based dispatch
        differ only by function-name suffix and the extra mid arguments."""
        lib = _load()
        sl = nlimbs64(scalar_modulus)
        ss = np.zeros((len(scalars), sl), np.uint64)
        for i, s in enumerate(scalars):
            ss[i] = limbs64(int(s) % scalar_modulus, sl)
        points = np.ascontiguousarray(points, np.uint64)
        out = np.empty_like(points)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        prefix = "ed" if self.kind == "edwards" else "ws"
        getattr(lib, f"{prefix}_scalar_mul{suffix}")(
            ctypes.byref(self._ctx),
            ss.ctypes.data_as(u64p),
            sl,
            *extra,
            points.ctypes.data_as(u64p),
            out.ctypes.data_as(u64p),
            len(scalars),
        )
        return out

    def scalar_mul(self, scalars, points, scalar_modulus: int):
        """Variable-time ladder; PUBLIC scalars only."""
        return self._scalar_mul_impl("_batch", scalars, points, scalar_modulus, ())

    def scalar_mul_ct(self, scalars, points, scalar_modulus: int):
        """Constant-structure ladder over the full scalar-field bit
        length — the secret-scalar path (wire-path KEM / dealing).
        Limb-exact match of HostGroup.scalar_mul's Python ladder."""
        return self._scalar_mul_impl(
            "_ct_batch", scalars, points, scalar_modulus,
            (scalar_modulus.bit_length(),),
        )


def chacha20_xor(key: bytes, nonce: bytes, data: bytes, counter: int = 0) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    out = ctypes.create_string_buffer(len(data))
    lib.chacha20_xor(key, nonce, counter, data, out, len(data))
    return out.raw
