"""The full 5-phase DKG protocol driven over a BroadcastChannel.

This is the deployment-shaped entry point the reference leaves to the
caller (its doctest hand-carries arrays between parties,
src/lib.rs:60-182): each party process calls ``run_party`` with a
channel; rounds are published/fetched as deterministic wire bytes
(utils.serde), malformed or missing messages degrade to the protocol's
silent-disqualification semantics (reference: committee.rs:844-853).

The wire boundary is a trust boundary.  Every peer payload is decoded
inside :func:`_decode_quarantined` (any decode failure -> ``None`` ->
the *sender* is silently disqualified, exactly as if it had never
published) and then shape/index-validated before it reaches the
committee state machine — a Byzantine peer must never be able to crash
an honest party with bytes alone (see docs/fault_model.md and the
regression suite in tests/test_chaos.py).  ``PartyResult`` counts what
the transport survived (quarantined peers, round timeouts, RPC
retries) and threads the counters into utils.tracing.

A party that hits a protocol-fatal error still publishes its complaint
evidence first (reference: committee.rs:340-347) and then publishes
empty payloads for the remaining rounds so peers never block on it.

Crash recovery: the ceremony is structured as resumable per-round
steps.  Each round r splits into a *head* (state transition, WAL
record, publish) and a *tail* (fetch + decode of round r).  With
``run_party(..., checkpoint=path)`` every head appends one durable
record to a :class:`~dkg_tpu.net.checkpoint.PartyWal` **before** its
publish — rounds 1–2 consume ``rng``, so a recomputed round would
publish different bytes (equivocation under first-publish-wins); the
write-ahead ordering guarantees published bytes are always durable and
recomputed rounds were never published.  A restarted process replays
the log, re-publishes the recorded rounds (idempotent: the channel
keeps the first publish), re-fetches closed rounds from the retained
mailboxes, and continues live from the first unfinished round — same
master key, zero consumed fault budget (docs/fault_model.md, "Crash
recovery").
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from ..dkg.committee import (
    DistributedKeyGeneration,
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from ..dkg.errors import DkgError
from ..dkg.procedure_keys import (
    MasterPublicKey,
    MemberCommunicationKey,
    MemberCommunicationPublicKey,
    MemberSecretShare,
)
from ..utils import metrics, obslog, serde
from ..utils.tracing import CeremonyTrace, phase_span
from .channel import BroadcastChannel
from .checkpoint import PartyWal


@dataclass
class PartyResult:
    index: int
    master: Optional[MasterPublicKey] = None
    share: Optional[MemberSecretShare] = None
    error: Optional[DkgError] = None
    # aggregate bare commitments (A_0..A_t) of the final sharing poly:
    # A_l = sum over qualified dealers of A_{j,l}, so A_0 == master and
    # g*share_i == eval(A, i).  The epoch subsystem (dkg_tpu.epoch)
    # seeds refresh/resharing from this.  None when any dealer's secret
    # was reconstructed (the disclosed-share path changes the effective
    # sharing polynomial, so the aggregate would be stale).
    commitments: Optional[tuple] = None
    # transport/robustness counters (mirrored into ``trace.counters``)
    quarantined: int = 0  # peer messages that failed decode/validation
    timeouts: int = 0  # rounds that closed before all n messages arrived
    retries: int = 0  # channel RPC retries (channels exposing .stats)
    resumes: int = 0  # times this party resumed from its checkpoint WAL
    wal_records: int = 0  # WAL records at completion (replayed + appended)
    replayed_rounds: int = 0  # rounds restored from the WAL at start
    trace: Optional[CeremonyTrace] = field(default=None, repr=False)

    @property
    def ok(self) -> bool:
        return self.error is None and self.master is not None


def _decode_quarantined(decoder, group, payload: bytes):
    """Decode one peer payload; ANY failure means ``None`` (the sender is
    silently disqualified, like a party that never published).  Malformed
    bytes from a Byzantine peer must never raise into ``run_party`` —
    scripts/lint_lite.py (DKG001) pins every net-layer decode to this
    quarantine."""
    try:
        return decoder(group, payload)
    except (ValueError, struct.error, IndexError, OverflowError):
        return None


def _index_ok(n: int, *indices: int) -> bool:
    return all(1 <= i <= n for i in indices)


def _valid_phase1(b, n: int) -> bool:
    # every recipient 1..n must appear exactly once: a dealing that omits
    # (or duplicates) recipients could otherwise make an honest party
    # abort with FETCHED_INVALID_DATA instead of disqualifying the dealer
    return sorted(es.recipient_index for es in b.encrypted_shares) == list(
        range(1, n + 1)
    )


def _valid_phase2(b, n: int) -> bool:
    return all(_index_ok(n, m.accused_index) for m in b.misbehaving_parties)


def _valid_phase4(b, n: int) -> bool:
    return all(_index_ok(n, m.accused_index) for m in b.misbehaving_parties)


def _valid_phase5(b, n: int) -> bool:
    return all(
        _index_ok(n, d.accused_index, d.holder_index) for d in b.disclosed_shares
    )


def _valid_any(b, n: int) -> bool:
    return True


# Per-round wire handling: decoder, validator, and the Fetched* wrapper
# the committee state machine consumes.
_ROUNDS = {
    1: (serde.decode_phase1, _valid_phase1,
        lambda env, j, b: FetchedPhase1.from_broadcast(env, j, b)),
    2: (serde.decode_phase2, _valid_phase2,
        lambda env, j, b: FetchedComplaints2(j, b)),
    3: (serde.decode_phase3, _valid_any,
        lambda env, j, b: FetchedPhase3.from_broadcast(env, j, b)),
    4: (serde.decode_phase4, _valid_phase4,
        lambda env, j, b: FetchedComplaints4(j, b)),
    5: (serde.decode_phase5, _valid_phase5,
        lambda env, j, b: FetchedPhase5(j, b)),
}


@dataclass(frozen=True)
class _FetchOutcome:
    """What one round's fetch+decode observed — recorded in the NEXT
    round's WAL record so a resumed party restores its counters and can
    reconstruct the exact decode view (present mask) it acted on."""

    present: tuple[int, ...]
    quarantined_delta: int
    timed_out: bool


def _publish(
    channel,
    round_no: int,
    my: int,
    payload: Optional[bytes],
    *,
    seq: Optional[int] = None,
    trace: Optional[CeremonyTrace] = None,
) -> None:
    # flight-recorder events carry LENGTHS only, never payload bytes —
    # round 1/5 payloads hold encrypted shares and disclosures.  ``seq``
    # is the party-local publish ordinal: together with the stamped
    # (ceremony_id, round, party) it is the correlation key fetch-side
    # events reference (docs/observability.md, "Causal flows").  Emitted
    # AFTER the channel call so the timestamp marks when the payload
    # became visible to peers — critical_path charges the straggler leg
    # up to this instant, and flow arrows always point forward in time.
    data = payload or b""
    channel.publish(round_no, my, data)
    obslog.emit_current("publish", round=round_no, bytes=len(data), seq=seq)
    if trace is not None:
        trace.bump("net.wire_bytes_out", len(data))


class _PartyRun:
    """One incarnation of one party: per-round head/tail steps over a
    channel, optionally journaled to (and resumed from) a PartyWal."""

    def __init__(self, channel, env, comm_key, pks, my, rng, timeout, trace, wal):
        self.channel = channel
        self.env = env
        self.group = env.group
        self.n = env.nr_members
        self.comm_key = comm_key
        self.pks = pks
        self.my = my
        self.rng = rng
        self.timeout = timeout
        self.trace = trace
        self.wal = wal
        self.others = [j for j in range(1, self.n + 1) if j != my]
        self.result = PartyResult(my, trace=trace)
        self.phase = None  # DkgPhase* driving the next transition
        self.fetched1 = None  # round-1 broadcasts (re-consumed by round 3)
        self.prev = None  # decoded messages the next head consumes
        self.last_outcome: Optional[_FetchOutcome] = None
        self.finished = False
        self.pub_seq = 0  # party-local publish ordinal (causal-flow key)

    # -- shared plumbing ----------------------------------------------------

    def _pub(self, round_no: int, payload: Optional[bytes]) -> None:
        seq = self.pub_seq
        self.pub_seq += 1
        _publish(
            self.channel, round_no, self.my, payload, seq=seq, trace=self.trace
        )

    def _decode_list(self, round_no: int, got: dict[int, bytes], counting: bool):
        decoder, validate, wrap = _ROUNDS[round_no]
        out = []
        for j in self.others:
            payload = got.get(j)
            b = None
            if payload:  # absent or explicit empty: silent disqualification
                b = _decode_quarantined(decoder, self.group, payload)
                if b is not None and not validate(b, self.n):
                    b = None
                if b is None and counting:
                    self.result.quarantined += 1
                    obslog.emit_current("quarantine", round=round_no, peer=j)
            out.append(wrap(self.env, j, b))
        return out

    def _tail(self, round_no: int):
        """Fetch + decode round ``round_no``; records the outcome for the
        next head's WAL record."""
        got = self.channel.fetch(round_no, self.n, self.timeout)
        timed_out = len(got) < self.n
        if timed_out:
            self.result.timeouts += 1
        q0 = self.result.quarantined
        lst = self._decode_list(round_no, got, counting=True)
        self.last_outcome = _FetchOutcome(
            tuple(sorted(got)), self.result.quarantined - q0, timed_out
        )
        if self.trace is not None:
            self.trace.bump(
                "net.wire_bytes_in", sum(len(v) for v in got.values())
            )
        obslog.emit_current(
            "round_tail",
            round=round_no,
            present=len(got),
            senders=sorted(got),
            quarantined_delta=self.result.quarantined - q0,
            timed_out=timed_out,
        )
        if round_no == 1:
            self.fetched1 = lst
        self.prev = lst

    def _record(self, round_no: int, payload: bytes, phase=None,
                error=None, drain_from: int = 0) -> None:
        """Append round ``round_no``'s WAL record.  MUST run before the
        round's publish: the write-ahead ordering is what makes resumed
        re-publishes byte-identical (module docstring)."""
        if self.wal is None:
            return
        o = self.last_outcome
        body = serde.encode_round_record(
            self.group, round_no, payload, phase,
            error=error, drain_from=drain_from,
            present=o.present if o else None,
            quarantined_delta=o.quarantined_delta if o else 0,
            timed_out=o.timed_out if o else False,
        )
        self.wal.append(body)
        self.result.wal_records += 1
        obslog.emit_current(
            "wal_record", round=round_no, bytes=len(body), terminal=error is not None
        )

    def _abort(self, err: DkgError, drain_from: int) -> None:
        # error KIND only — DkgError bodies can reference protocol state
        obslog.emit_current("abort", error=err.kind.name, drain_from=drain_from)
        self.result.error = err
        # publish empties for the remaining rounds so peers never block
        for r in range(drain_from, 6):
            self._pub(r, b"")
        self.finished = True

    def _finish(self) -> PartyResult:
        res = self.result
        stats = getattr(self.channel, "stats", None)
        if isinstance(stats, dict):
            res.retries = int(stats.get("retries", 0))
        if self.trace is not None:
            self.trace.bump("net.quarantined", res.quarantined)
            self.trace.bump("net.round_timeouts", res.timeouts)
            self.trace.bump("net.rpc_retries", res.retries)
            self.trace.bump("net.resumes", res.resumes)
            self.trace.bump("wal.records", res.wal_records)
            self.trace.bump("wal.replayed_rounds", res.replayed_rounds)
            self.trace.meta.setdefault("party_index", self.my)
        obslog.emit_current(
            "party_done",
            ok=res.ok,
            quarantined=res.quarantined,
            timeouts=res.timeouts,
            retries=res.retries,
            resumes=res.resumes,
            wal_records=res.wal_records,
            replayed_rounds=res.replayed_rounds,
        )
        metrics.observe_party_result(res)
        return res

    # -- per-round heads (transition, record, publish) ----------------------

    def _head1(self) -> None:
        phase1, b1 = DistributedKeyGeneration.init(
            self.env, self.rng, self.comm_key, self.pks, self.my
        )
        p1 = serde.encode_phase1(self.group, b1)
        self._record(1, p1, phase=phase1)
        self._pub(1, p1)
        self.phase = phase1

    def _head2(self) -> None:
        nxt, b2 = self.phase.proceed(self.fetched1, self.rng)
        p2 = serde.encode_phase2(self.group, b2) if b2 else b""
        if isinstance(nxt, DkgError):
            # complaint evidence is committed bytes too: pin it in a
            # terminal record before publishing (crash mid-drain must
            # not recompute the proofs with a fresh rng)
            self._record(2, p2, error=nxt, drain_from=3)
            self._pub(2, p2)
            self._abort(nxt, 3)
            return
        self._record(2, p2, phase=nxt)
        self._pub(2, p2)
        self.phase = nxt

    def _head3(self) -> None:
        nxt, b3 = self.phase.proceed(self.prev, self.fetched1)
        if isinstance(nxt, DkgError):
            self._record(3, b"", error=nxt, drain_from=3)
            self._abort(nxt, 3)
            return
        p3 = serde.encode_phase3(self.group, b3) if b3 else b""
        self._record(3, p3, phase=nxt)
        self._pub(3, p3)
        self.phase = nxt

    def _head4(self) -> None:
        nxt, b4 = self.phase.proceed(self.prev)
        p4 = serde.encode_phase4(self.group, b4) if b4 else b""
        if isinstance(nxt, DkgError):
            self._record(4, p4, error=nxt, drain_from=5)
            self._pub(4, p4)
            self._abort(nxt, 5)
            return
        self._record(4, p4, phase=nxt)
        self._pub(4, p4)
        self.phase = nxt

    def _head5(self) -> None:
        nxt, b5 = self.phase.proceed(self.prev)
        p5 = serde.encode_phase5(self.group, b5) if b5 else b""
        if isinstance(nxt, DkgError):
            self._record(5, p5, error=nxt, drain_from=6)
            self._pub(5, p5)
            self._abort(nxt, 6)
            return
        self._record(5, p5, phase=nxt)
        self._pub(5, p5)
        self.phase = nxt

    def _finalise(self) -> None:
        out, _ = self.phase.finalise(self.prev)
        if isinstance(out, DkgError):
            self.result.error = out
        else:
            self.result.master, self.result.share = out
            self.result.commitments = self._aggregate_commitments()
        self.finished = True

    def _aggregate_commitments(self) -> Optional[tuple]:
        """Pointwise sum of the qualified dealers' bare commitment
        tuples — the Feldman commitments of the AGGREGATE sharing
        polynomial the final shares lie on.  Only valid when no dealer
        went through share reconstruction (PartyResult.commitments)."""
        st = self.phase._state
        if st.reconstructable:
            return None
        qual = [j for j in range(1, self.n + 1) if st.qualified[j - 1]]
        if not qual or any(j not in st.bare_coeffs for j in qual):
            return None
        tlen = len(st.bare_coeffs[qual[0]])
        agg = []
        for lvl in range(tlen):
            acc = st.bare_coeffs[qual[0]][lvl]
            for j in qual[1:]:
                acc = self.group.add(acc, st.bare_coeffs[j][lvl])
            agg.append(acc)
        return tuple(agg)

    _HEADS = {1: _head1, 2: _head2, 3: _head3, 4: _head4, 5: _head5}

    # -- resume -------------------------------------------------------------

    def _replay_records(self):
        """Intact, contiguous WAL records 1..R (a terminal record, if
        any, is last) plus their raw bodies.  Anything after the first
        gap/corruption is a torn tail and is discarded — resume falls
        back to the previous round, which the write-ahead ordering
        makes safe.

        Forward compatibility: records whose magic is not ours (e.g.
        the epoch layer's b"DKGE" records, or record types a future
        version introduces) are SKIPPED — not interpreted, not treated
        as corruption — but their bodies are preserved so the torn-tail
        compaction below never deletes another layer's records."""
        records, bodies = [], []
        for body in self.wal.replay():
            if not body.startswith(serde.RECORD_MAGIC):
                bodies.append(body)  # foreign record: preserve, skip
                continue
            try:
                rec = serde.decode_round_record(self.group, body)
            except ValueError:
                break
            if rec.round_no != len(records) + 1:
                break
            records.append(rec)
            bodies.append(body)
            if rec.error is not None:
                break
        return records, bodies

    def _rebuild_fetched1(self, rec2) -> None:
        """Round 3 re-consumes the round-1 broadcasts; rebuild them from
        the retained mailbox filtered to the recorded present mask (late
        stragglers must not change the replayed view).  Decode failures
        were already counted in the record's quarantined_delta."""
        present = rec2.present or ()
        got = self.channel.fetch(1, len(present), self.timeout)
        got = {j: got[j] for j in present if j in got}
        self.fetched1 = self._decode_list(1, got, counting=False)

    def _resume(self) -> int:
        """Replay the WAL; returns the last recorded round R (0 = start
        fresh).  On return the run continues at round R's tail."""
        records, bodies = self._replay_records()
        if not records:
            # a log that exists but replays to nothing is unusable —
            # recreate it so fresh records don't land after garbage, and
            # run from round 1 (dropout semantics if the ceremony moved
            # on).  Foreign-magic records (another layer's, e.g. epoch)
            # are not ours to delete: compact to just those instead.
            if bodies:
                self.wal.rewrite(bodies)
            else:
                self.wal.reset()
            return 0
        # compact away any torn tail before appending new records: bytes
        # from a half-written frame would shadow everything after them
        # on the next replay (the double-crash case)
        self.wal.rewrite(bodies)
        with phase_span(self.trace, "net_resume", annotate_device=False):
            obslog.emit_current("wal_resume", replayed_rounds=len(records))
            res = self.result
            res.resumes = 1
            res.replayed_rounds = len(records)
            res.wal_records = len(records)
            for rec in records:
                if rec.present is not None:
                    res.quarantined += rec.quarantined_delta
                    if rec.timed_out:
                        res.timeouts += 1
            # re-publish every recorded round: first-publish-wins makes
            # this an idempotent no-op for rounds that already landed,
            # and delivers the exact recorded bytes for a publish the
            # crash interrupted
            for rec in records:
                self._pub(rec.round_no, rec.payload)
            last = records[-1]
            if last.error is not None:
                self._abort(last.error, last.drain_from)
                return last.round_no
            self.phase = last.phase
            if last.round_no == 2:
                self._rebuild_fetched1(records[1])
        return last.round_no

    # -- driver -------------------------------------------------------------

    def execute(self) -> PartyResult:
        resume_round = 0
        if self.wal is not None:
            resume_round = self._resume()
        if self.finished:
            return self._finish()
        for r in range(max(1, resume_round), 6):
            with phase_span(self.trace, f"net_round{r}", annotate_device=False):
                if r != resume_round:
                    obslog.emit_current("round_head", round=r)
                    self._HEADS[r](self)
                    if self.finished:
                        return self._finish()
                self._tail(r)
                if r == 5:
                    self._finalise()
        return self._finish()


def run_party(
    channel: BroadcastChannel,
    env: Environment,
    comm_key: MemberCommunicationKey,
    committee_pks: list[MemberCommunicationPublicKey],
    my: int,
    rng,
    timeout: float = 30.0,
    trace: Optional[CeremonyTrace] = None,
    checkpoint: Optional[object] = None,
    obs: Optional[obslog.ObsLog] = None,
) -> PartyResult:
    """Execute one party's side of the ceremony over ``channel``.

    ``my`` is the party's 1-based index in the byte-sorted committee
    (reference: committee.rs:134-135); returns the master public key and
    this party's secret share on success.  Pass a
    :class:`~dkg_tpu.utils.tracing.CeremonyTrace` to collect per-round
    wall-clock and the quarantine/timeout/retry counters.

    ``checkpoint`` (a path or :class:`~dkg_tpu.net.checkpoint.PartyWal`)
    enables durable crash recovery: protocol state is journaled before
    every publish, and a restarted process pointed at the same WAL
    resumes from the first unfinished round with the byte-identical
    outcome (module docstring; docs/fault_model.md, "Crash recovery").

    ``obs`` is this party's flight recorder; when None and the
    ``DKG_TPU_OBSLOG`` env knob names a directory, one is created with a
    JSONL sink there (``{ceremony_id}-p{my:03d}.jsonl``).  The recorder
    is bound as the thread's ambient log for the run, so channel retries
    and injected faults land in the same event stream.
    """
    wal = None
    if checkpoint is not None:
        wal = checkpoint if isinstance(checkpoint, PartyWal) else PartyWal(checkpoint)
    owned = None
    if obs is None:
        obs = owned = obslog.from_env(
            ceremony_id=obslog.ceremony_id_for(env), party=my
        )
    try:
        with obslog.use(obs):
            return _PartyRun(
                channel, env, comm_key, committee_pks, my, rng, timeout, trace, wal
            ).execute()
    finally:
        if owned is not None:
            owned.close()
