"""The full 5-phase DKG protocol driven over a BroadcastChannel.

This is the deployment-shaped entry point the reference leaves to the
caller (its doctest hand-carries arrays between parties,
src/lib.rs:60-182): each party process calls ``run_party`` with a
channel; rounds are published/fetched as deterministic wire bytes
(utils.serde), malformed or missing messages degrade to the protocol's
silent-disqualification semantics (reference: committee.rs:844-853).

A party that hits a protocol-fatal error still publishes its complaint
evidence first (reference: committee.rs:340-347) and then publishes
empty payloads for the remaining rounds so peers never block on it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..dkg.committee import (
    DistributedKeyGeneration,
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from ..dkg.errors import DkgError
from ..dkg.procedure_keys import (
    MasterPublicKey,
    MemberCommunicationKey,
    MemberCommunicationPublicKey,
    MemberSecretShare,
)
from ..utils import serde
from .channel import BroadcastChannel


@dataclass
class PartyResult:
    index: int
    master: Optional[MasterPublicKey] = None
    share: Optional[MemberSecretShare] = None
    error: Optional[DkgError] = None

    @property
    def ok(self) -> bool:
        return self.error is None and self.master is not None


def _publish(channel, round_no: int, my: int, payload: Optional[bytes]) -> None:
    channel.publish(round_no, my, payload or b"")


def _drain(channel, my: int, start_round: int, result: PartyResult) -> PartyResult:
    """Publish empties for the remaining rounds so peers don't block."""
    for r in range(start_round, 6):
        _publish(channel, r, my, b"")
    return result


def run_party(
    channel: BroadcastChannel,
    env: Environment,
    comm_key: MemberCommunicationKey,
    committee_pks: list[MemberCommunicationPublicKey],
    my: int,
    rng,
    timeout: float = 30.0,
) -> PartyResult:
    """Execute one party's side of the ceremony over ``channel``.

    ``my`` is the party's 1-based index in the byte-sorted committee
    (reference: committee.rs:134-135); returns the master public key and
    this party's secret share on success.
    """
    group = env.group
    n = env.nr_members
    others = [j for j in range(1, n + 1) if j != my]

    def fetch(round_no: int) -> dict[int, bytes]:
        return channel.fetch(round_no, n, timeout)

    # ---- round 1: dealing ------------------------------------------------
    phase1, b1 = DistributedKeyGeneration.init(env, rng, comm_key, committee_pks, my)
    _publish(channel, 1, my, serde.encode_phase1(group, b1))
    got1 = fetch(1)
    fetched1 = [
        FetchedPhase1.from_broadcast(
            env, j, serde.decode_phase1(group, got1[j]) if got1.get(j) else None
        )
        for j in others
    ]

    # ---- round 2: share verification + complaints ------------------------
    nxt, b2 = phase1.proceed(fetched1, rng)
    _publish(channel, 2, my, serde.encode_phase2(group, b2) if b2 else None)
    if isinstance(nxt, DkgError):
        return _drain(channel, my, 3, PartyResult(my, error=nxt))
    got2 = fetch(2)
    complaints2 = [
        FetchedComplaints2(
            j, serde.decode_phase2(group, got2[j]) if got2.get(j) else None
        )
        for j in others
    ]

    # ---- round 3: qualified set + bare commitments -----------------------
    nxt, b3 = nxt.proceed(complaints2, fetched1)
    if isinstance(nxt, DkgError):
        return _drain(channel, my, 3, PartyResult(my, error=nxt))
    _publish(channel, 3, my, serde.encode_phase3(group, b3) if b3 else None)
    got3 = fetch(3)
    fetched3 = [
        FetchedPhase3.from_broadcast(
            env, j, serde.decode_phase3(group, got3[j]) if got3.get(j) else None
        )
        for j in others
    ]

    # ---- round 4: re-verification + disclosure complaints ----------------
    nxt, b4 = nxt.proceed(fetched3)
    _publish(channel, 4, my, serde.encode_phase4(group, b4) if b4 else None)
    if isinstance(nxt, DkgError):
        return _drain(channel, my, 5, PartyResult(my, error=nxt))
    got4 = fetch(4)
    complaints4 = [
        FetchedComplaints4(
            j, serde.decode_phase4(group, got4[j]) if got4.get(j) else None
        )
        for j in others
    ]

    # ---- round 5: adjudication + share disclosure ------------------------
    nxt, b5 = nxt.proceed(complaints4)
    _publish(channel, 5, my, serde.encode_phase5(group, b5) if b5 else None)
    if isinstance(nxt, DkgError):
        return PartyResult(my, error=nxt)
    got5 = fetch(5)
    fetched5 = [
        FetchedPhase5(
            j, serde.decode_phase5(group, got5[j]) if got5.get(j) else None
        )
        for j in others
    ]

    out, _ = nxt.finalise(fetched5)
    if isinstance(out, DkgError):
        return PartyResult(my, error=out)
    master, share = out
    return PartyResult(my, master=master, share=share)
