"""The full 5-phase DKG protocol driven over a BroadcastChannel.

This is the deployment-shaped entry point the reference leaves to the
caller (its doctest hand-carries arrays between parties,
src/lib.rs:60-182): each party process calls ``run_party`` with a
channel; rounds are published/fetched as deterministic wire bytes
(utils.serde), malformed or missing messages degrade to the protocol's
silent-disqualification semantics (reference: committee.rs:844-853).

The wire boundary is a trust boundary.  Every peer payload is decoded
inside :func:`_decode_quarantined` (any decode failure -> ``None`` ->
the *sender* is silently disqualified, exactly as if it had never
published) and then shape/index-validated before it reaches the
committee state machine — a Byzantine peer must never be able to crash
an honest party with bytes alone (see docs/fault_model.md and the
regression suite in tests/test_chaos.py).  ``PartyResult`` counts what
the transport survived (quarantined peers, round timeouts, RPC
retries) and threads the counters into utils.tracing.

A party that hits a protocol-fatal error still publishes its complaint
evidence first (reference: committee.rs:340-347) and then publishes
empty payloads for the remaining rounds so peers never block on it.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from ..dkg.committee import (
    DistributedKeyGeneration,
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from ..dkg.errors import DkgError
from ..dkg.procedure_keys import (
    MasterPublicKey,
    MemberCommunicationKey,
    MemberCommunicationPublicKey,
    MemberSecretShare,
)
from ..utils import serde
from ..utils.tracing import CeremonyTrace, phase_span
from .channel import BroadcastChannel


@dataclass
class PartyResult:
    index: int
    master: Optional[MasterPublicKey] = None
    share: Optional[MemberSecretShare] = None
    error: Optional[DkgError] = None
    # transport/robustness counters (mirrored into ``trace.counters``)
    quarantined: int = 0  # peer messages that failed decode/validation
    timeouts: int = 0  # rounds that closed before all n messages arrived
    retries: int = 0  # channel RPC retries (channels exposing .stats)
    trace: Optional[CeremonyTrace] = field(default=None, repr=False)

    @property
    def ok(self) -> bool:
        return self.error is None and self.master is not None


def _decode_quarantined(decoder, group, payload: bytes):
    """Decode one peer payload; ANY failure means ``None`` (the sender is
    silently disqualified, like a party that never published).  Malformed
    bytes from a Byzantine peer must never raise into ``run_party`` —
    scripts/lint_lite.py (DKG001) pins every net-layer decode to this
    quarantine."""
    try:
        return decoder(group, payload)
    except (ValueError, struct.error, IndexError, OverflowError):
        return None


def _index_ok(n: int, *indices: int) -> bool:
    return all(1 <= i <= n for i in indices)


def _valid_phase1(b, n: int) -> bool:
    # every recipient 1..n must appear exactly once: a dealing that omits
    # (or duplicates) recipients could otherwise make an honest party
    # abort with FETCHED_INVALID_DATA instead of disqualifying the dealer
    return sorted(es.recipient_index for es in b.encrypted_shares) == list(
        range(1, n + 1)
    )


def _valid_phase2(b, n: int) -> bool:
    return all(_index_ok(n, m.accused_index) for m in b.misbehaving_parties)


def _valid_phase4(b, n: int) -> bool:
    return all(_index_ok(n, m.accused_index) for m in b.misbehaving_parties)


def _valid_phase5(b, n: int) -> bool:
    return all(
        _index_ok(n, d.accused_index, d.holder_index) for d in b.disclosed_shares
    )


def _publish(channel, round_no: int, my: int, payload: Optional[bytes]) -> None:
    channel.publish(round_no, my, payload or b"")


def _drain(channel, my: int, start_round: int, result: PartyResult) -> PartyResult:
    """Publish empties for the remaining rounds so peers don't block."""
    for r in range(start_round, 6):
        _publish(channel, r, my, b"")
    return result


def run_party(
    channel: BroadcastChannel,
    env: Environment,
    comm_key: MemberCommunicationKey,
    committee_pks: list[MemberCommunicationPublicKey],
    my: int,
    rng,
    timeout: float = 30.0,
    trace: Optional[CeremonyTrace] = None,
) -> PartyResult:
    """Execute one party's side of the ceremony over ``channel``.

    ``my`` is the party's 1-based index in the byte-sorted committee
    (reference: committee.rs:134-135); returns the master public key and
    this party's secret share on success.  Pass a
    :class:`~dkg_tpu.utils.tracing.CeremonyTrace` to collect per-round
    wall-clock and the quarantine/timeout/retry counters.
    """
    group = env.group
    n = env.nr_members
    others = [j for j in range(1, n + 1) if j != my]
    result = PartyResult(my, trace=trace)

    def fetch(round_no: int) -> dict[int, bytes]:
        got = channel.fetch(round_no, n, timeout)
        if len(got) < n:
            result.timeouts += 1
        return got

    def decoded(got: dict[int, bytes], j: int, decoder, validate):
        payload = got.get(j)
        if not payload:
            return None  # absent or explicit empty: silent disqualification
        b = _decode_quarantined(decoder, group, payload)
        if b is not None and not validate(b, n):
            b = None
        if b is None:
            result.quarantined += 1
        return b

    def finish(res: PartyResult) -> PartyResult:
        stats = getattr(channel, "stats", None)
        if isinstance(stats, dict):
            res.retries = int(stats.get("retries", 0))
        if trace is not None:
            trace.bump("net.quarantined", res.quarantined)
            trace.bump("net.round_timeouts", res.timeouts)
            trace.bump("net.rpc_retries", res.retries)
            trace.meta.setdefault("party_index", my)
        return res

    # ---- round 1: dealing ------------------------------------------------
    with phase_span(trace, "net_round1", annotate_device=False):
        phase1, b1 = DistributedKeyGeneration.init(env, rng, comm_key, committee_pks, my)
        _publish(channel, 1, my, serde.encode_phase1(group, b1))
        got1 = fetch(1)
        fetched1 = [
            FetchedPhase1.from_broadcast(
                env, j, decoded(got1, j, serde.decode_phase1, _valid_phase1)
            )
            for j in others
        ]

    # ---- round 2: share verification + complaints ------------------------
    with phase_span(trace, "net_round2", annotate_device=False):
        nxt, b2 = phase1.proceed(fetched1, rng)
        _publish(channel, 2, my, serde.encode_phase2(group, b2) if b2 else None)
        if isinstance(nxt, DkgError):
            result.error = nxt
            return finish(_drain(channel, my, 3, result))
        got2 = fetch(2)
        complaints2 = [
            FetchedComplaints2(j, decoded(got2, j, serde.decode_phase2, _valid_phase2))
            for j in others
        ]

    # ---- round 3: qualified set + bare commitments -----------------------
    with phase_span(trace, "net_round3", annotate_device=False):
        nxt, b3 = nxt.proceed(complaints2, fetched1)
        if isinstance(nxt, DkgError):
            result.error = nxt
            return finish(_drain(channel, my, 3, result))
        _publish(channel, 3, my, serde.encode_phase3(group, b3) if b3 else None)
        got3 = fetch(3)
        fetched3 = [
            FetchedPhase3.from_broadcast(
                env, j, decoded(got3, j, serde.decode_phase3, lambda b, n: True)
            )
            for j in others
        ]

    # ---- round 4: re-verification + disclosure complaints ----------------
    with phase_span(trace, "net_round4", annotate_device=False):
        nxt, b4 = nxt.proceed(fetched3)
        _publish(channel, 4, my, serde.encode_phase4(group, b4) if b4 else None)
        if isinstance(nxt, DkgError):
            result.error = nxt
            return finish(_drain(channel, my, 5, result))
        got4 = fetch(4)
        complaints4 = [
            FetchedComplaints4(j, decoded(got4, j, serde.decode_phase4, _valid_phase4))
            for j in others
        ]

    # ---- round 5: adjudication + share disclosure ------------------------
    with phase_span(trace, "net_round5", annotate_device=False):
        nxt, b5 = nxt.proceed(complaints4)
        _publish(channel, 5, my, serde.encode_phase5(group, b5) if b5 else None)
        if isinstance(nxt, DkgError):
            result.error = nxt
            return finish(result)
        got5 = fetch(5)
        fetched5 = [
            FetchedPhase5(j, decoded(got5, j, serde.decode_phase5, _valid_phase5))
            for j in others
        ]

        out, _ = nxt.finalise(fetched5)
    if isinstance(out, DkgError):
        result.error = out
        return finish(result)
    result.master, result.share = out
    return finish(result)
