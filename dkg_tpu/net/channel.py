"""Broadcast channels: publish-once / fetch-all per round.

Semantics mirror the reference's abstract channel (reference:
src/lib.rs:91-92, committee.rs:825-871): every party publishes at most
one message per round; everyone then fetches the full round.  A party
with nothing to say publishes the empty payload (the protocol's
``None`` broadcast); a party that never publishes is simply absent from
the fetch — both map to silent disqualification downstream.

``TcpHub`` is a minimal length-prefixed TCP mailbox for multi-process
ceremonies; authenticity/transport security is the deployment's job,
exactly as the reference assumes an *authenticated* channel.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Protocol

_OP_PUB = 1
_OP_FETCH = 2


class BroadcastChannel(Protocol):
    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        """Publish this party's round message (empty = explicit no-op)."""

    def fetch(
        self, round_no: int, expected: int, timeout: float = 30.0
    ) -> dict[int, bytes]:
        """Block until ``expected`` messages for the round arrived (or
        timeout); returns {sender_index: payload}.  On timeout returns
        whatever arrived — missing parties become silent dropouts."""


class InProcessChannel:
    """Shared-memory channel for in-process multi-party simulation —
    the reference's test transport (committee.rs:1337-1338) with real
    blocking semantics so threaded parties interleave correctly."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._rounds: dict[int, dict[int, bytes]] = {}

    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        with self._lock:
            self._rounds.setdefault(round_no, {})[sender] = payload
            self._lock.notify_all()

    def fetch(self, round_no: int, expected: int, timeout: float = 30.0) -> dict[int, bytes]:
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                got = self._rounds.get(round_no, {})
                if len(got) >= expected:
                    return dict(got)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return dict(got)
                self._lock.wait(remaining)


class _HubHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # one request per connection
        hub: "TcpHub" = self.server.hub  # type: ignore[attr-defined]
        try:
            op = _read_exact(self.rfile, 1)[0]
            if op == _OP_PUB:
                round_no, sender, ln = struct.unpack("<III", _read_exact(self.rfile, 12))
                payload = _read_exact(self.rfile, ln)
                hub.channel.publish(round_no, sender, payload)
                self.wfile.write(b"\x01")
            elif op == _OP_FETCH:
                round_no, expected, timeout_ms = struct.unpack(
                    "<III", _read_exact(self.rfile, 12)
                )
                got = hub.channel.fetch(round_no, expected, timeout_ms / 1000.0)
                out = [struct.pack("<I", len(got))]
                for sender, payload in sorted(got.items()):
                    out.append(struct.pack("<II", sender, len(payload)))
                    out.append(payload)
                self.wfile.write(b"".join(out))
        except (ConnectionError, EOFError):
            pass


def _read_exact(f, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    return buf


class TcpHub:
    """The mailbox server: one per ceremony, any party (or a neutral
    host) can run it.  Threaded: each publish/fetch is one connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.channel = InProcessChannel()
        self._server = _Server((host, port), _HubHandler)
        self._server.hub = self  # type: ignore[attr-defined]
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    def start(self) -> "TcpHub":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class TcpHubChannel:
    """Client side of TcpHub; satisfies BroadcastChannel."""

    def __init__(self, host: str, port: int) -> None:
        self._addr = (host, port)

    def _rpc(self, payload: bytes, read_reply) -> object:
        with socket.create_connection(self._addr, timeout=60.0) as s:
            s.sendall(payload)
            f = s.makefile("rb")
            return read_reply(f)

    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        msg = bytes([_OP_PUB]) + struct.pack("<III", round_no, sender, len(payload)) + payload
        self._rpc(msg, lambda f: _read_exact(f, 1))

    def fetch(self, round_no: int, expected: int, timeout: float = 30.0) -> dict[int, bytes]:
        msg = bytes([_OP_FETCH]) + struct.pack(
            "<III", round_no, expected, int(timeout * 1000)
        )

        def read_reply(f) -> dict[int, bytes]:
            (count,) = struct.unpack("<I", _read_exact(f, 4))
            out: dict[int, bytes] = {}
            for _ in range(count):
                sender, ln = struct.unpack("<II", _read_exact(f, 8))
                out[sender] = _read_exact(f, ln)
            return out

        return self._rpc(msg, read_reply)
