"""Broadcast channels: publish-once / fetch-all per round.

Semantics mirror the reference's abstract channel (reference:
src/lib.rs:91-92, committee.rs:825-871): every party publishes at most
one message per round; everyone then fetches the full round.  A party
with nothing to say publishes the empty payload (the protocol's
``None`` broadcast); a party that never publishes is simply absent from
the fetch — both map to silent disqualification downstream.

Robustness posture (see docs/fault_model.md):

* **First-publish-wins.**  A second, *different* publish for the same
  (round, sender) never replaces the first; it is recorded as an
  equivocation attempt so the ceremony operator can surface evidence.
  An identical re-publish is a no-op, which makes publish retries
  idempotent and safe.
* **Typed transport errors.**  Short reads raise
  :class:`TruncatedStream` (a :class:`TransportError`), never a bare
  ``EOFError``, so callers can retry transport faults without masking
  programming errors.
* **Retry with capped exponential backoff + jitter.**  Every
  ``TcpHubChannel`` RPC retries transient socket failures under
  configurable attempt/timeout budgets (``DKG_TPU_NET_*`` knobs via
  utils.envknobs).
* **Whole-ceremony RPC budget.**  ``TcpHubChannel`` can clamp every
  RPC — fetch waits, publish and evidence socket timeouts, retry
  eligibility — to the remainder of one ceremony-wide deadline instead
  of paying a flat per-round timeout for each silent party (or
  attempts x io_timeout per RPC against a hung hub).
* **Fail-fast hub frames.**  The hub answers unknown opcodes and
  malformed/short frames with an explicit error byte and bounds frame
  reads with a timeout, so a confused client fails immediately instead
  of hanging until its socket deadline.

``TcpHub`` is a minimal length-prefixed TCP mailbox for multi-process
ceremonies; authenticity/transport security is the deployment's job,
exactly as the reference assumes an *authenticated* channel.
"""

from __future__ import annotations

import random
import socket
import socketserver
import struct
import threading
import time
from typing import Optional, Protocol

from ..utils import envknobs, obslog
from ..utils.metrics import REGISTRY, SIZE_BUCKETS

_OP_PUB = 1
_OP_FETCH = 2
_OP_EVID = 3
_OP_NAMES = {_OP_PUB: "publish", _OP_FETCH: "fetch", _OP_EVID: "evidence"}

# Largest payload the length-prefixed wire format can carry: lengths are
# packed as little-endian u32 (`<I`/`<III`), so anything bigger must be
# rejected BEFORE packing — struct.error at pack time is opaque and, on
# the hub reply path, would tear the frame mid-stream.
WIRE_MAX_PAYLOAD = 0xFFFFFFFF

# How many distinct payloads (the original + alternates) to retain per
# equivocating (round, sender) as evidence before only counting.
_EVIDENCE_CAP = 8

# Ceiling for one backoff step, regardless of attempt count.
_BACKOFF_CAP_S = 2.0

# Socket-timeout floor for RPCs clamped by an exhausted ceremony budget:
# a healthy local hub answers a publish in well under a second, so the
# clamp bounds a hung hub's post-deadline cost without flaking working
# publishes (which peers' drains depend on).
_POST_BUDGET_IO_FLOOR_S = 1.0

# How long the hub waits for the rest of a frame once a connection
# opens; a well-behaved client sendall()s the whole frame before
# reading, so anything slower is a stalled or malformed sender.
_DEFAULT_FRAME_TIMEOUT_S = 5.0

_ACK_OK = b"\x01"
_ACK_ERR = b"\x00"

# Defaults for the DKG_TPU_NET_* knobs (see docs/fault_model.md).
_DEFAULT_IO_TIMEOUT_S = 60.0
_DEFAULT_ATTEMPTS = 4
_DEFAULT_BACKOFF_MS = 50.0


class TransportError(RuntimeError):
    """A transport-layer failure (retryable; never a protocol error)."""


class TruncatedStream(TransportError):
    """The peer closed the stream mid-message (short read)."""


class RetryBudgetExceeded(TransportError):
    """All RPC attempts failed; carries the last underlying error."""


class PayloadTooLarge(TransportError):
    """A payload exceeds the u32 length prefix of the wire format.

    Raised BEFORE packing (client publish and hub reply paths both
    guard), carrying the offending size — retrying cannot help, but the
    typed error lets callers distinguish "your message is impossible"
    from a transient socket fault."""

    def __init__(self, size: int, where: str) -> None:
        super().__init__(
            f"payload of {size} bytes exceeds the u32 wire limit "
            f"({WIRE_MAX_PAYLOAD}) at {where}"
        )
        self.size = size
        self.where = where


def _check_wire_size(size: int, where: str) -> None:
    if size > WIRE_MAX_PAYLOAD:
        raise PayloadTooLarge(size, where)


# -- counted wire helpers -----------------------------------------------------
#
# EVERY socket send and receive in this module flows through these (lint
# rule DKG012 pins that), so `net_wire_bytes_total{dir,op}` is the
# ground truth of what the data plane moved — the number ROADMAP item 4
# (constant-size commitments) must shrink.


def _count_wire(direction: str, op: str, n: int) -> None:
    REGISTRY.inc("net_wire_bytes_total", n, dir=direction, op=op)


def _observe_payload(op: str, n: int) -> None:
    """Per-message-type payload-size histogram (op distinguishes the
    message family, e.g. publish vs fetch reply entries)."""
    REGISTRY.observe("net_wire_payload_bytes", n, buckets=SIZE_BUCKETS, op=op)


def _wire_send(sock: socket.socket, data: bytes, op: str) -> None:
    """The counted send: the only sanctioned ``sendall`` in dkg_tpu/net/
    outside the WAL (DKG012)."""
    sock.sendall(data)
    _count_wire("out", op, len(data))


class _CountedReader:
    """File-like read wrapper counting bytes drained off a socket; the
    total is flushed into ``net_wire_bytes_total{dir="in"}`` by the RPC
    core once the reply is fully consumed."""

    def __init__(self, f) -> None:
        self._f = f
        self.n = 0

    def read(self, n: int) -> bytes:
        chunk = self._f.read(n)
        if chunk:
            self.n += len(chunk)
        return chunk


class BroadcastChannel(Protocol):
    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        """Publish this party's round message (empty = explicit no-op)."""

    def fetch(
        self, round_no: int, expected: int, timeout: float = 30.0
    ) -> dict[int, bytes]:
        """Block until ``expected`` messages for the round arrived (or
        timeout); returns {sender_index: payload}.  On timeout returns
        whatever arrived — missing parties become silent dropouts."""


class InProcessChannel:
    """Shared-memory channel for in-process multi-party simulation —
    the reference's test transport (committee.rs:1337-1338) with real
    blocking semantics so threaded parties interleave correctly.

    Publishes are first-write-wins: a conflicting second publish for
    the same (round, sender) is recorded in the equivocation log, not
    applied; an identical re-publish (a retry) is a silent no-op."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._rounds: dict[int, dict[int, bytes]] = {}
        # (round, sender) -> [first payload, alternate, ...] (capped)
        self._equivocations: dict[tuple[int, int], list[bytes]] = {}

    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        with self._lock:
            mailbox = self._rounds.setdefault(round_no, {})
            prev = mailbox.get(sender)
            if prev is None:
                mailbox[sender] = payload
                self._lock.notify_all()
            elif prev != payload:
                ev = self._equivocations.setdefault((round_no, sender), [prev])
                # evidence holds *distinct* payloads: a retry of an
                # already-recorded conflicting publish adds nothing
                if payload not in ev and len(ev) < _EVIDENCE_CAP:
                    ev.append(payload)

    def fetch(self, round_no: int, expected: int, timeout: float = 30.0) -> dict[int, bytes]:
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                got = self._rounds.get(round_no, {})
                if len(got) >= expected:
                    return dict(got)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return dict(got)
                self._lock.wait(remaining)

    def equivocation_evidence(self) -> dict[tuple[int, int], tuple[bytes, ...]]:
        """All observed equivocations: (round, sender) -> distinct payloads,
        first-published first.  Empty dict when every sender was consistent."""
        with self._lock:
            return {k: tuple(v) for k, v in self._equivocations.items()}


class _HubHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # one request per connection
        hub: "TcpHub" = self.server.hub  # type: ignore[attr-defined]
        t0 = time.perf_counter()
        op = None
        try:
            # a sender that opens a connection but never completes its
            # frame must not pin a handler thread forever
            self.connection.settimeout(hub.frame_timeout_s)
            op = _read_exact(self.rfile, 1)[0]
            if op == _OP_PUB:
                round_no, sender, ln = struct.unpack("<III", _read_exact(self.rfile, 12))
                payload = _read_exact(self.rfile, ln)
                _observe_payload("hub_publish", ln)
                hub.channel.publish(round_no, sender, payload)
                self.wfile.write(_ACK_OK)
                hub._observe_rpc("publish", time.perf_counter() - t0, 13 + ln, 1)
            elif op == _OP_FETCH:
                round_no, expected, timeout_ms = struct.unpack(
                    "<III", _read_exact(self.rfile, 12)
                )
                got = hub.channel.fetch(round_no, expected, timeout_ms / 1000.0)
                out = [struct.pack("<I", len(got))]
                for sender, payload in sorted(got.items()):
                    # hub reply path: guard BEFORE packing — a payload
                    # that slipped past the client guard (e.g. published
                    # straight into the backing InProcessChannel) must
                    # not tear the reply frame mid-stream
                    _check_wire_size(len(payload), "hub fetch reply")
                    _observe_payload("hub_fetch", len(payload))
                    out.append(struct.pack("<II", sender, len(payload)))
                    out.append(payload)
                reply = b"".join(out)
                self.wfile.write(reply)
                hub._observe_rpc("fetch", time.perf_counter() - t0, 13, len(reply))
            elif op == _OP_EVID:
                ev = hub.channel.equivocation_evidence()
                out = [struct.pack("<I", len(ev))]
                for (round_no, sender), payloads in sorted(ev.items()):
                    out.append(struct.pack("<III", round_no, sender, len(payloads)))
                reply = b"".join(out)
                self.wfile.write(reply)
                hub._observe_rpc("evidence", time.perf_counter() - t0, 1, len(reply))
            else:
                # unknown opcode: reply with an explicit error byte so
                # the client fails NOW, not at its socket timeout
                self.wfile.write(_ACK_ERR)
                hub._observe_junk("unknown_opcode")
        except (ConnectionError, TransportError, struct.error, OSError):
            # malformed/short/stalled frame: best-effort error byte, then
            # the connection closes — never a silent hang for the client
            hub._observe_junk("malformed_frame", op=op)
            self._best_effort_error()

    def _best_effort_error(self) -> None:
        try:
            self.wfile.write(_ACK_ERR)
            self.wfile.flush()
        except OSError:
            pass


def _read_exact(f, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            raise TruncatedStream(f"stream closed after {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def _read_ack(f) -> bytes:
    """Read a one-byte hub ack; the explicit error byte (malformed or
    unknown frame) is a retryable transport failure, not a success."""
    ack = _read_exact(f, 1)
    if ack != _ACK_OK:
        raise TransportError(f"hub replied with error ack {ack!r}")
    return ack


class TcpHub:
    """The mailbox server: one per ceremony, any party (or a neutral
    host) can run it.  Threaded: each publish/fetch is one connection.
    First-publish-wins and the equivocation log come from the backing
    :class:`InProcessChannel`.  ``frame_timeout_s`` bounds how long a
    handler waits for the rest of a frame once a connection opens —
    stalled or malformed senders get an error byte, not a pinned
    thread."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        frame_timeout_s: float = _DEFAULT_FRAME_TIMEOUT_S,
    ) -> None:
        self.frame_timeout_s = frame_timeout_s
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.channel = InProcessChannel()
        self._server = _Server((host, port), _HubHandler)
        self._server.hub = self  # type: ignore[attr-defined]
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        # hub-side flight recorder (file sink only when DKG_TPU_OBSLOG
        # is set); handler threads have no ambient party recorder, so
        # the hub owns its own log
        self.obs = obslog.from_env(party="hub")

    def start(self) -> "TcpHub":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self.obs is not None:
            self.obs.close()

    # -- hub-side observability (called from handler threads) ---------------

    def _observe_rpc(self, op: str, dt: float, n_in: int, n_out: int) -> None:
        REGISTRY.inc("dkg_hub_rpcs_total", op=op)
        REGISTRY.observe("dkg_hub_rpc_seconds", dt, op=op)
        REGISTRY.inc("dkg_hub_bytes_total", n_in, direction="in")
        REGISTRY.inc("dkg_hub_bytes_total", n_out, direction="out")
        # the hub's share of the wire ledger: ops are prefixed so the
        # client and hub contributions of one in-process test never
        # merge into a double-counted series
        _count_wire("in", f"hub_{op}", n_in)
        _count_wire("out", f"hub_{op}", n_out)
        if self.obs is not None:
            self.obs.emit("hub_rpc", op=op, dur_s=dt, bytes_in=n_in, bytes_out=n_out)

    def _observe_junk(self, reason: str, op: int | None = None) -> None:
        REGISTRY.inc("dkg_hub_junk_frames_total", reason=reason)
        if self.obs is not None:
            self.obs.emit("hub_junk_frame", reason=reason, op=op)


class TcpHubChannel:
    """Client side of TcpHub; satisfies BroadcastChannel.

    Transient socket failures are retried with capped exponential
    backoff + jitter; ``stats`` counts what happened so the party
    driver can surface it (net.party threads the counters into
    PartyResult / CeremonyTrace).

    Knobs (constructor arguments override; validated via
    utils.envknobs):

    * ``DKG_TPU_NET_TIMEOUT_S``  — per-RPC socket I/O timeout (default 60)
    * ``DKG_TPU_NET_ATTEMPTS``   — RPC attempts before giving up (default 4)
    * ``DKG_TPU_NET_BACKOFF_MS`` — base backoff between attempts (default 50)
    * ``DKG_TPU_NET_BUDGET_S``   — whole-ceremony RPC budget (default off)

    When the budget is set, the first operation arms one ceremony-wide
    deadline and EVERY RPC is clamped to the remaining budget: each
    ``fetch``'s hub-side wait shrinks to what is left (k silent parties
    cost one shared budget, not k full per-round timeouts), and
    ``publish``/``equivocation_counts`` socket timeouts are clamped too
    (floored at ~1s so working publishes still land), with no retries
    started past the deadline — a hung hub can no longer charge
    attempts x io_timeout per RPC after the budget is spent.  Every
    clamp is counted in ``stats["budget_clamps"]``.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        attempts: Optional[int] = None,
        io_timeout_s: Optional[float] = None,
        backoff_ms: Optional[float] = None,
        budget_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._addr = (host, port)
        if attempts is None:
            attempts = envknobs.pos_int(
                "DKG_TPU_NET_ATTEMPTS", "RPC attempts before giving up"
            )
        if io_timeout_s is None:
            io_timeout_s = envknobs.pos_float(
                "DKG_TPU_NET_TIMEOUT_S", "per-RPC socket timeout in seconds"
            )
        if backoff_ms is None:
            backoff_ms = envknobs.nonneg_float(
                "DKG_TPU_NET_BACKOFF_MS", "base retry backoff in milliseconds"
            )
        if budget_s is None:
            budget_s = envknobs.pos_float(
                "DKG_TPU_NET_BUDGET_S", "whole-ceremony fetch budget in seconds"
            )
        self._attempts = attempts if attempts is not None else _DEFAULT_ATTEMPTS
        self._io_timeout_s = (
            io_timeout_s if io_timeout_s is not None else _DEFAULT_IO_TIMEOUT_S
        )
        self._backoff_s = (
            backoff_ms if backoff_ms is not None else _DEFAULT_BACKOFF_MS
        ) / 1000.0
        self._budget_s = budget_s
        self._deadline: Optional[float] = None
        self._rng = rng if rng is not None else random.Random()
        self.stats: dict[str, int] = {"rpcs": 0, "retries": 0, "budget_clamps": 0}

    # -- deadline budget ----------------------------------------------------

    def _budget_remaining(self) -> Optional[float]:
        """Arm the ceremony deadline on first use; None when budget is off."""
        if self._budget_s is None:
            return None
        if self._deadline is None:
            self._deadline = time.monotonic() + self._budget_s
        return max(0.0, self._deadline - time.monotonic())

    # -- retrying RPC core --------------------------------------------------

    def _rpc(
        self,
        payload: bytes,
        read_reply,
        io_timeout: float,
        budget_clamp: bool = True,
        op: str = "rpc",
    ) -> object:
        """One RPC with retries.  With ``budget_clamp`` (every RPC except
        ``fetch``, which pre-clamps its hub-side wait itself) the
        per-attempt socket timeout is clamped to the remaining ceremony
        budget — a hung hub costs at most ~the floor per RPC after the
        deadline, not attempts x io_timeout — and no RETRY starts past
        the deadline (the first attempt always runs: peers' drains
        depend on publishes landing even at the buzzer)."""
        self.stats["rpcs"] += 1
        REGISTRY.inc("dkg_client_rpcs_total")
        last: Optional[Exception] = None
        for attempt in range(self._attempts):
            remaining = self._budget_remaining()
            if attempt:
                if remaining is not None and remaining <= 0.0:
                    raise RetryBudgetExceeded(
                        f"ceremony budget exhausted after {attempt} attempt(s) "
                        f"to {self._addr}: {last!r}"
                    )
                self.stats["retries"] += 1
                REGISTRY.inc("dkg_client_rpc_retries_total")
                step = min(_BACKOFF_CAP_S, self._backoff_s * (2 ** (attempt - 1)))
                backoff = step * (0.5 + self._rng.random())
                # backoff_s makes retry time attributable: forensics
                # (obslog.critical_path) charges it to the retry bucket
                # instead of leaving it inside the transport residual
                obslog.emit_current(
                    "rpc_retry", attempt=attempt, error=repr(last),
                    backoff_s=backoff, op=op,
                )
                time.sleep(backoff)
            timeout = io_timeout
            if budget_clamp and remaining is not None:
                clamped = min(io_timeout, max(remaining, _POST_BUDGET_IO_FLOOR_S))
                if clamped < timeout:
                    self.stats["budget_clamps"] += 1
                    REGISTRY.inc("dkg_client_budget_clamps_total")
                    obslog.emit_current("budget_clamp", where="rpc", timeout_s=clamped)
                    timeout = clamped
            try:
                with socket.create_connection(self._addr, timeout=timeout) as s:
                    _wire_send(s, payload, op)
                    f = _CountedReader(s.makefile("rb"))
                    try:
                        return read_reply(f)
                    finally:
                        _count_wire("in", op, f.n)
            except (OSError, TransportError) as exc:
                last = exc
        raise RetryBudgetExceeded(
            f"{self._attempts} attempt(s) to {self._addr} failed: {last!r}"
        )

    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        # guard BEFORE packing: an oversized payload must die as a typed
        # error carrying its size, not as an opaque struct.error
        _check_wire_size(len(payload), "client publish")
        _observe_payload("publish", len(payload))
        msg = bytes([_OP_PUB]) + struct.pack("<III", round_no, sender, len(payload)) + payload
        self._rpc(msg, _read_ack, self._io_timeout_s, op="publish")

    def fetch(self, round_no: int, expected: int, timeout: float = 30.0) -> dict[int, bytes]:
        remaining = self._budget_remaining()
        if remaining is not None and remaining < timeout:
            self.stats["budget_clamps"] += 1
            REGISTRY.inc("dkg_client_budget_clamps_total")
            obslog.emit_current(
                "budget_clamp", where="fetch", round=round_no, timeout_s=remaining
            )
            timeout = remaining
        timeout_ms = min(int(timeout * 1000), 0xFFFFFFFF)
        msg = bytes([_OP_FETCH]) + struct.pack("<III", round_no, expected, timeout_ms)

        def read_reply(f) -> dict[int, bytes]:
            (count,) = struct.unpack("<I", _read_exact(f, 4))
            out: dict[int, bytes] = {}
            for _ in range(count):
                sender, ln = struct.unpack("<II", _read_exact(f, 8))
                out[sender] = _read_exact(f, ln)
                _observe_payload("fetch", ln)
            return out

        # The hub blocks up to ``timeout`` before replying, so the socket
        # deadline must cover the wait *plus* normal I/O slack; the hub
        # wait was already clamped (and counted) above, so _rpc must not
        # clamp — or double-count — again.
        return self._rpc(
            msg, read_reply, timeout + self._io_timeout_s,
            budget_clamp=False, op="fetch",
        )

    def equivocation_counts(self) -> dict[tuple[int, int], int]:
        """(round, sender) -> number of distinct payloads the hub saw
        (>= 2 means the sender equivocated)."""
        msg = bytes([_OP_EVID])

        def read_reply(f) -> dict[tuple[int, int], int]:
            (count,) = struct.unpack("<I", _read_exact(f, 4))
            out: dict[tuple[int, int], int] = {}
            for _ in range(count):
                round_no, sender, n = struct.unpack("<III", _read_exact(f, 12))
                out[(round_no, sender)] = n
            return out

        return self._rpc(msg, read_reply, self._io_timeout_s, op="evidence")
