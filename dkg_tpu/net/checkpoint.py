"""Durable per-party checkpoint log: the write-ahead layer behind
``run_party(..., checkpoint=...)``.

GJKR treats a crashed party as a permanent dropout — survivors disclose
its shares and Lagrange-reconstruct its secret, burning one unit of the
``t`` fault budget forever.  At ROADMAP ceremony scales restarts are
routine, not Byzantine, so a party keeps a :class:`PartyWal`: before
each round's publish it appends one record (the exact wire payload, the
post-transition phase snapshot from utils.serde, and the decode outcome
of the previous round's fetch).  A restarted process replays the log,
re-publishes the recorded rounds (first-publish-wins makes that
idempotent), re-fetches closed rounds from the channel's retained
mailboxes, and continues live from the first unfinished round — ``ok``,
byte-identical master key, zero reconstructions.

Why write-*ahead*: rounds 1–2 consume the caller's ``rng`` (polynomial
sampling, complaint proofs), so a round recomputed after a crash would
publish *different* bytes — equivocation under first-publish-wins.
Appending record r before publishing round r guarantees that anything
ever published is durable, and anything recomputed was never published.

File format (version 1)::

    header  b"DKGWAL" <u8 version>
    record  <u32 body_len> <body> <16-byte BLAKE2b-128(body)>

Appends are a single ``os.write`` on an ``O_APPEND`` descriptor
followed by ``fsync``; the file is created ``0600`` because record
bodies carry secret share material (the phase snapshot includes the
party's received shares and final share).  Replay is torn-tail
tolerant: the first truncated or checksum-failing record ends the
replay and the valid prefix is returned — a crash mid-append costs at
most the round being written, and resume falls back to the previous
round.  A fully unusable log (bad header, unreadable file) replays to
nothing and the party simply runs fresh: recovery degrades to today's
dropout semantics, never a crash.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import struct
from typing import Optional, Union

from ..utils import envknobs

WAL_MAGIC = b"DKGWAL"
WAL_VERSION = 1
_HEADER = WAL_MAGIC + bytes([WAL_VERSION])
_DIGEST_LEN = 16  # BLAKE2b-128: torn/corrupt tail detection, not authentication


def _digest(body: bytes) -> bytes:
    return hashlib.blake2b(body, digest_size=_DIGEST_LEN).digest()


def default_checkpoint_dir() -> Optional[str]:
    """Operator override for where party WALs live (None = caller's
    choice); set ``DKG_TPU_CHECKPOINT_DIR`` (utils.envknobs: empty value
    means unset)."""
    return envknobs.string(
        "DKG_TPU_CHECKPOINT_DIR", "directory for party checkpoint WALs"
    )


def wal_path(directory: Union[str, os.PathLike], index: int) -> pathlib.Path:
    """Canonical WAL location for party ``index`` (1-based) under
    ``directory`` — one file per party so concurrent parties never share
    a descriptor."""
    return pathlib.Path(directory) / f"party{index:04d}.wal"


def service_wal_path(directory: Union[str, os.PathLike]) -> pathlib.Path:
    """Canonical WAL location for a ceremony-service journal
    (dkg_tpu.service.durable) under ``directory``.  One journal per
    server process — scheduler appends are already serialized, and a
    single file makes kill-and-restart recovery a single replay."""
    return pathlib.Path(directory) / "service.wal"


class PartyWal:
    """Append-only, checksummed, fsync'd record log at ``path``.

    The only sanctioned way to persist ceremony state from the net
    layer (scripts/lint_lite.py DKG005 bans raw file writes in
    ``dkg_tpu/net/``): every append is atomic-in-practice (one
    ``O_APPEND`` write + fsync) and every replay is torn-tail tolerant.
    """

    def __init__(self, path: Union[str, os.PathLike]) -> None:
        self.path = pathlib.Path(path)

    # -- writing ------------------------------------------------------------

    def append(self, body: bytes) -> None:
        """Durably append one record: length prefix, body, checksum —
        written as ONE os.write so a crash leaves either nothing or a
        torn tail that replay discards, then fsync'd before returning
        (the caller may publish the bytes only after this returns)."""
        frame = struct.pack("<I", len(body)) + body + _digest(body)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        try:
            if os.fstat(fd).st_size == 0:
                frame = _HEADER + frame
            os.write(fd, frame)
            os.fsync(fd)
        finally:
            os.close(fd)

    def rewrite(self, bodies: list[bytes]) -> None:
        """Atomically replace the log with exactly ``bodies`` (header +
        checksummed frames), via temp file + fsync + ``os.replace``.
        Resume compacts the log through this so a torn tail never
        lingers: new appends landing after torn bytes would be shadowed
        by them on every later replay."""
        frames = [_HEADER]
        for body in bodies:
            frames.append(struct.pack("<I", len(body)) + body + _digest(body))
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_name(self.path.name + ".tmp")
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, b"".join(frames))
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.path)

    def reset(self) -> None:
        """Recreate the log empty (0600).  run_party calls this when a
        log exists but replays to nothing — appending fresh records
        after unparseable bytes would poison every future replay."""
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
        )
        os.close(fd)

    # -- reading ------------------------------------------------------------

    def replay(self) -> list[bytes]:
        """All intact record bodies, in append order.  NEVER raises: a
        missing/unreadable file or bad header replays to ``[]``; the
        first truncated or checksum-failing record ends the replay and
        the valid prefix is returned (torn-tail tolerance)."""
        try:
            data = self.path.read_bytes()
        except OSError:
            return []
        if not data.startswith(_HEADER):
            return []
        out: list[bytes] = []
        pos = len(_HEADER)
        while pos < len(data):
            if pos + 4 > len(data):
                break  # torn length prefix
            (ln,) = struct.unpack("<I", data[pos : pos + 4])
            end = pos + 4 + ln + _DIGEST_LEN
            if end > len(data):
                break  # torn body/checksum
            body = data[pos + 4 : pos + 4 + ln]
            if data[pos + 4 + ln : end] != _digest(body):
                break  # corrupt record: discard it and everything after
            out.append(body)
            pos = end
        return out
