"""Deterministic fault injection for channel-driven ceremonies.

The engine has a fault hook already — ``BatchedCeremony.run(tamper=)``
corrupts device arrays after dealing (dkg_tpu/dkg/ceremony.py).  This
module is the wire-level analogue for the net layer: a seeded
:class:`FaultPlan` schedules byte-level and liveness faults against
specific (round, sender) messages, and :class:`FaultyChannel` applies
them on top of any :class:`~dkg_tpu.net.channel.BroadcastChannel`.

Every mutation is derived from ``(seed, round, sender, kind)`` only, so
a plan replays byte-for-byte: the same seed produces the same garbage,
the same flipped bit, and the same outcome — chaos tests are ordinary
deterministic tests (tests/test_chaos.py), and a failing soak seed from
scripts/chaos_storm.py reproduces locally.

Fault vocabulary (all scheduled per (round, sender)):

* ``drop``       — the publish never happens (silent dropout).
* ``delay``      — the publish lands late; peers that already fetched
                   treat it as missing.
* ``garbage``    — the payload is replaced with seeded random bytes.
* ``truncate``   — only a prefix of the payload is published.
* ``bitflip``    — one seeded bit of the payload is inverted.
* ``replace``    — the payload is replaced with caller-chosen bytes
                   (for handcrafted adversarial messages).
* ``duplicate``  — the same payload is published twice (an idempotent
                   retry; must NOT count as equivocation).
* ``equivocate`` — a second, different payload is also published; the
                   channel keeps the first and records evidence.
* ``crash``      — via :meth:`FaultPlan.crash_after`: the party dies
                   before any operation on a later round
                   (:class:`CrashFault` propagates out of run_party,
                   modelling a process crash).
* ``restart``    — the party dies mid-round (after publishing, while
                   fetching) and, when ``run_with_faults`` was given a
                   ``checkpoint_dir``, is re-spawned from its WAL with a
                   FRESH rng — recovery must depend only on the durable
                   checkpoint, never on replaying the random stream
                   (:class:`RestartFault`; net/checkpoint.py).  Without
                   a checkpoint_dir the restart is a terminal crash, so
                   the same schedule exercises the dropout/
                   reconstruction path instead.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import obslog
from ..utils.metrics import REGISTRY
from .channel import BroadcastChannel
from .checkpoint import wal_path
from .party import PartyResult, run_party

_KIND_CODES = {
    "drop": 1,
    "delay": 2,
    "garbage": 3,
    "truncate": 4,
    "bitflip": 5,
    "replace": 6,
    "duplicate": 7,
    "equivocate": 8,
}


def _note_fault(
    kind: str, round_no: int, sender: int, seconds: Optional[float] = None
) -> None:
    """Every injected fault is observable: a per-kind counter plus a
    flight-recorder event in the victim party's log, so a chaos failure
    can be replayed from its logs alone (module docstring).  Delay
    faults carry their injected ``seconds`` so forensics can attribute
    the lost wall-clock (obslog.critical_path)."""
    REGISTRY.inc("dkg_faults_injected_total", kind=kind)
    obslog.emit_current(
        "fault_injected", round=round_no, fault=kind, sender=sender,
        seconds=seconds,
    )


class CrashFault(RuntimeError):
    """Simulated process crash of one party (not a protocol error)."""


class RestartFault(CrashFault):
    """A crash the harness may recover from: the party died mid-round
    and should be re-spawned from its checkpoint WAL."""


class FaultPlan:
    """A seeded, replayable schedule of wire faults for one ceremony.

    Builder methods return ``self`` so plans chain::

        plan = (FaultPlan(seed=7)
                .garbage(1, sender=2)
                .equivocate(3, sender=5)
                .crash_after(sender=7, round_no=2))
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        # (round, sender) -> [(kind, arg), ...] in scheduling order
        self._faults: dict[tuple[int, int], list[tuple[str, object]]] = {}
        self._crash_after: dict[int, int] = {}  # sender -> last completed round
        self._restarts: dict[int, set[int]] = {}  # sender -> rounds it dies in
        # (sender, round) restarts already fired: each scheduled restart
        # kills exactly one incarnation, else respawn would loop forever
        self._restarts_fired: set[tuple[int, int]] = set()

    # -- builders -----------------------------------------------------------

    def _add(self, kind: str, round_no: int, sender: int, arg: object = None) -> "FaultPlan":
        self._faults.setdefault((round_no, sender), []).append((kind, arg))
        return self

    def drop(self, round_no: int, sender: int) -> "FaultPlan":
        return self._add("drop", round_no, sender)

    def delay(self, round_no: int, sender: int, seconds: float) -> "FaultPlan":
        return self._add("delay", round_no, sender, float(seconds))

    def garbage(self, round_no: int, sender: int, nbytes: Optional[int] = None) -> "FaultPlan":
        return self._add("garbage", round_no, sender, nbytes)

    def truncate(self, round_no: int, sender: int, keep: Optional[int] = None) -> "FaultPlan":
        return self._add("truncate", round_no, sender, keep)

    def bitflip(self, round_no: int, sender: int) -> "FaultPlan":
        return self._add("bitflip", round_no, sender)

    def replace(self, round_no: int, sender: int, payload: bytes) -> "FaultPlan":
        return self._add("replace", round_no, sender, bytes(payload))

    def duplicate(self, round_no: int, sender: int) -> "FaultPlan":
        return self._add("duplicate", round_no, sender)

    def equivocate(
        self, round_no: int, sender: int, alternate: Optional[bytes] = None
    ) -> "FaultPlan":
        return self._add("equivocate", round_no, sender, alternate)

    def crash_after(self, sender: int, round_no: int) -> "FaultPlan":
        """Party ``sender`` completes ``round_no`` and then dies: any
        publish/fetch for a later round raises :class:`CrashFault`."""
        self._crash_after[sender] = min(
            round_no, self._crash_after.get(sender, round_no)
        )
        return self

    def restart(self, sender: int, round_no: int) -> "FaultPlan":
        """Party ``sender`` dies mid-round ``round_no`` — after its
        publish landed, while fetching the round — raising
        :class:`RestartFault` exactly once per scheduled (sender, round).
        ``run_with_faults(checkpoint_dir=...)`` re-spawns the party from
        its WAL; without a checkpoint_dir the restart is terminal."""
        self._restarts.setdefault(sender, set()).add(round_no)
        return self

    # -- queries ------------------------------------------------------------

    def faults_for(self, round_no: int, sender: int) -> list[tuple[str, object]]:
        return list(self._faults.get((round_no, sender), ()))

    def crashes_at(self, sender: int, round_no: int) -> bool:
        last_ok = self._crash_after.get(sender)
        return last_ok is not None and round_no > last_ok

    def check_restart(self, sender: int, round_no: int) -> None:
        """Raise :class:`RestartFault` if a restart is scheduled here and
        has not fired yet (fire-once: later incarnations pass through)."""
        if round_no in self._restarts.get(sender, ()):
            key = (sender, round_no)
            if key not in self._restarts_fired:
                self._restarts_fired.add(key)
                raise RestartFault(
                    f"party {sender} restarted during round {round_no}"
                )

    def reset_runtime(self) -> None:
        """Forget fired restarts so the same plan object replays
        identically on a second ceremony (run_with_faults calls this)."""
        self._restarts_fired.clear()

    def as_dict(self) -> dict:
        """JSON-able description (for CHAOS.json / failure reports)."""
        return {
            "seed": self.seed,
            "faults": [
                {
                    "round": r,
                    "sender": s,
                    "kind": kind,
                    "arg": arg if not isinstance(arg, bytes) else arg.hex(),
                }
                for (r, s), lst in sorted(self._faults.items())
                for kind, arg in lst
            ],
            # string keys so the dict round-trips through JSON unchanged
            "crash_after": {str(s): r for s, r in sorted(self._crash_after.items())},
            "restarts": {
                str(s): sorted(rs) for s, rs in sorted(self._restarts.items())
            },
        }

    # -- deterministic mutation helpers -------------------------------------

    def _rng(self, round_no: int, sender: int, kind: str) -> random.Random:
        # Mix the coordinates into one integer seed; Python int hashing of
        # plain ints is stable, but avoid hash() anyway so the stream is
        # independent of PYTHONHASHSEED by construction.
        mixed = (
            (self.seed & 0xFFFFFFFF) << 32
            | (round_no & 0xFF) << 24
            | (sender & 0xFFFF) << 8
            | _KIND_CODES[kind]
        )
        return random.Random(mixed)

    def garbage_bytes(self, round_no: int, sender: int, nbytes: Optional[int]) -> bytes:
        rng = self._rng(round_no, sender, "garbage")
        n = nbytes if nbytes is not None else rng.randrange(1, 256)
        return rng.randbytes(n)

    def flip_one_bit(self, round_no: int, sender: int, payload: bytes) -> bytes:
        if not payload:
            return b"\x01"
        rng = self._rng(round_no, sender, "bitflip")
        pos = rng.randrange(len(payload) * 8)
        out = bytearray(payload)
        out[pos // 8] ^= 1 << (pos % 8)
        return bytes(out)

    def truncate_bytes(
        self, round_no: int, sender: int, payload: bytes, keep: Optional[int]
    ) -> bytes:
        if keep is None:
            keep = self._rng(round_no, sender, "truncate").randrange(max(1, len(payload)))
        return payload[:keep]


class FaultyChannel:
    """Apply a :class:`FaultPlan` on top of any broadcast channel.

    One wrapper serves one party (``party`` is its 1-based index): crash
    faults key off the party, payload faults off the publish's sender —
    which for a well-behaved driver is the same index.  Everything not
    scheduled passes straight through, and unknown attributes delegate
    to the wrapped channel (``stats``, ``equivocation_evidence``, ...).
    """

    def __init__(self, inner: BroadcastChannel, plan: FaultPlan, party: int) -> None:
        self._inner = inner
        self._plan = plan
        self._party = party

    def _check_crash(self, round_no: int) -> None:
        if self._plan.crashes_at(self._party, round_no):
            _note_fault("crash", round_no, self._party)
            raise CrashFault(f"party {self._party} crashed before round {round_no}")

    def publish(self, round_no: int, sender: int, payload: bytes) -> None:
        self._check_crash(round_no)
        plan = self._plan
        publishes = [payload]
        for kind, arg in plan.faults_for(round_no, sender):
            _note_fault(
                kind, round_no, sender,
                seconds=float(arg) if kind == "delay" else None,  # type: ignore[arg-type]
            )
            if kind == "drop":
                return
            elif kind == "delay":
                time.sleep(float(arg))  # type: ignore[arg-type]
            elif kind == "garbage":
                publishes = [plan.garbage_bytes(round_no, sender, arg)]  # type: ignore[arg-type]
            elif kind == "truncate":
                publishes = [
                    plan.truncate_bytes(round_no, sender, publishes[0], arg)  # type: ignore[arg-type]
                ]
            elif kind == "bitflip":
                publishes = [plan.flip_one_bit(round_no, sender, publishes[0])]
            elif kind == "replace":
                publishes = [arg]  # type: ignore[list-item]
            elif kind == "duplicate":
                publishes.append(publishes[-1])
            elif kind == "equivocate":
                alt = arg if arg is not None else plan.flip_one_bit(round_no, sender, publishes[-1])
                publishes.append(alt)  # type: ignore[arg-type]
        for p in publishes:
            self._inner.publish(round_no, sender, p)

    def fetch(self, round_no: int, expected: int, timeout: float = 30.0) -> dict[int, bytes]:
        self._check_crash(round_no)
        # a restart strikes mid-round: the publish already landed (and,
        # with checkpointing, its WAL record is durable), the fetch never
        # completes — the classic crash window recovery must cover
        try:
            self._plan.check_restart(self._party, round_no)
        except RestartFault:
            _note_fault("restart", round_no, self._party)
            raise
        return self._inner.fetch(round_no, expected, timeout)

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# chaos harness: threaded n-party ceremonies under a fault plan
# ---------------------------------------------------------------------------


def make_committee(group, n: int, t: int, seed: int, shared_string: bytes = b"chaos"):
    """Deterministic committee setup: (env, sorted keys, sorted pks)."""
    from ..dkg.committee import Environment
    from ..dkg.procedure_keys import MemberCommunicationKey, sort_committee

    rng = random.Random(seed)
    env = Environment.init(group, t, n, shared_string)
    keys = [MemberCommunicationKey.generate(group, rng) for _ in range(n)]
    pks = sort_committee(group, [k.public() for k in keys])
    by_pk = {group.encode(k.public().point): k for k in keys}
    sorted_keys = [by_pk[group.encode(p.point)] for p in pks]
    return env, sorted_keys, pks


def run_with_faults(
    env,
    keys,
    pks,
    plan: FaultPlan,
    channel_factory: Callable[[int], BroadcastChannel],
    timeout: float = 5.0,
    seed: int = 0,
    join_timeout: float = 300.0,
    checkpoint_dir: Optional[str] = None,
):
    """Run a full threaded ceremony with ``plan`` applied to every party.

    ``channel_factory(i)`` returns party ``i``'s (0-based) base channel —
    a shared :class:`InProcessChannel` or one ``TcpHubChannel`` each.
    Returns a list of per-party outcomes: :class:`PartyResult`, a
    :class:`CrashFault` for crashed parties, or the raised exception if
    a party died for any other reason (a harness bug, never expected).

    With ``checkpoint_dir`` set, every party journals to a WAL under it
    and a :class:`RestartFault` re-spawns the party from that WAL with a
    FRESH rng (seed mixed with the incarnation count) — proving recovery
    depends only on the durable checkpoint, not the random stream.
    Without it, restart faults are terminal crashes, so the identical
    schedule exercises today's dropout/reconstruction path instead.
    """
    n = env.nr_members
    results: list[object] = [None] * n
    plan.reset_runtime()

    def worker(i: int) -> None:
        incarnation = 0
        while True:
            chan = FaultyChannel(channel_factory(i), plan, party=i + 1)
            wal = (
                wal_path(checkpoint_dir, i + 1) if checkpoint_dir is not None else None
            )
            rng = random.Random(seed * 6151 + i + incarnation * 7919)
            try:
                res = run_party(
                    chan, env, keys[i], pks, i + 1, rng,
                    timeout=timeout, checkpoint=wal,
                )
                # run_party reports resumes=1 for any resumed incarnation;
                # the harness knows the true respawn count
                res.resumes = max(res.resumes, incarnation)
                results[i] = res
                return
            except RestartFault as rf:
                if checkpoint_dir is None:
                    results[i] = rf  # no WAL: a restart is a terminal crash
                    return
                incarnation += 1
            except CrashFault as cf:
                results[i] = cf
                return
            except Exception as exc:  # noqa: BLE001 — surfaced to the caller verbatim
                results[i] = exc
                return

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=join_timeout)
    return results


# ---------------------------------------------------------------------------
# epoch chaos harness: ceremony + refresh/reshare under churn and faults
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChurnSchedule:
    """Mid-sequence membership change for :func:`run_epochs_with_faults`:
    ``leavers`` (1-based OLD-committee indices) drop out of the reshare's
    new committee and ``joiners`` fresh members enter it.  Committee size
    is preserved when ``len(leavers) == joiners`` (the chaos storm's
    ``--churn K`` shape), but the harness does not require it."""

    leavers: tuple[int, ...]
    joiners: int

    @property
    def churn(self) -> int:
        return len(self.leavers) + self.joiners


def churn_schedule(seed: int, n: int, k: int) -> ChurnSchedule:
    """Seeded K-leave + K-join schedule over an n-party committee."""
    if not 0 <= k <= n:
        raise ValueError(f"churn {k} out of range for n={n}")
    rng = random.Random(seed * 9973 + n * 31 + k)
    return ChurnSchedule(tuple(sorted(rng.sample(range(1, n + 1), k))), k)


@dataclass
class EpochPartyOutcome:
    """One worker's end-to-end outcome across ceremony + epoch ops.

    ``party`` is the wrapper id crash/restart faults key on: the old
    1-based index for founding members, ``n_old + 1 + q`` for joiner
    ordinal ``q``.  ``masters`` collects ``group.encode(state.master)``
    after every epoch op this party completed with a share — the chaos
    assertion is that every entry, from every honest party, is
    bit-identical to the ceremony's master key.
    """

    party: int
    base: object = None  # PartyResult | exception | None (joiners)
    masters: list = field(default_factory=list)
    state: object = None  # final EpochState (None for leavers/failures)
    left: bool = False  # True when this party dealt and exited at the reshare
    error: object = None  # first exception that ended the worker, if any
    resumes: int = 0  # respawned incarnations (restart recovery)


def run_epochs_with_faults(
    env,
    keys,
    pks,
    plan: FaultPlan,
    channel_factory: Callable[[int], BroadcastChannel],
    *,
    churn: Optional[ChurnSchedule] = None,
    refreshes: int = 1,
    t_new: Optional[int] = None,
    timeout: float = 5.0,
    seed: int = 0,
    join_timeout: float = 600.0,
    checkpoint_dir: Optional[str] = None,
):
    """Run ceremony -> ``refreshes`` proactive refreshes -> one reshare
    (when ``churn`` is given) with ``plan`` applied to every party on
    EVERY round — ceremony rounds 1-5 and epoch rounds 6+ alike, since
    :class:`FaultyChannel` is round-number agnostic.

    Founding parties run the ceremony, seed epoch 0 from their
    PartyResult, and drive an :class:`~dkg_tpu.epoch.EpochManager` over
    the SAME wrapped channel and WAL.  Joiners (``churn.joiners`` of
    them, deterministic keys from ``seed``) participate only in the
    reshare, bootstrapping the previous aggregate from the deals'
    t+1-majority claim.  RestartFaults re-spawn the party from its WAL
    with a fresh rng exactly like :func:`run_with_faults`.

    Returns ``[EpochPartyOutcome]*(n_old + joiners)``, founding members
    first (index order), then joiners (ordinal order).
    """
    from ..dkg.procedure_keys import MemberCommunicationKey
    from ..epoch import EpochManager, EpochState, genesis_from_party_result

    group = env.group
    n = env.nr_members
    t2 = env.threshold if t_new is None else t_new
    sched = churn if churn is not None else ChurnSchedule((), 0)
    jrng = random.Random(seed * 7177 + 13)
    joiner_keys = [
        MemberCommunicationKey.generate(group, jrng) for _ in range(sched.joiners)
    ]
    new_pks = [
        p for i, p in enumerate(pks) if (i + 1) not in sched.leavers
    ] + [k.public() for k in joiner_keys]
    outcomes = [EpochPartyOutcome(party=i + 1) for i in range(n)] + [
        EpochPartyOutcome(party=n + 1 + q) for q in range(sched.joiners)
    ]
    plan.reset_runtime()

    def ops(mgr: "object", out: EpochPartyOutcome, founding: bool) -> None:
        # A respawned manager re-runs every op from its WAL records
        # (byte-identical republish, mask-filtered refetch), so each
        # incarnation simply replays the whole sequence.
        out.masters = []
        if founding:
            for _ in range(refreshes):
                st = mgr.refresh()
                out.masters.append(group.encode(st.master))
                out.state = st
        if churn is not None:
            st = mgr.reshare(new_pks, t2)
            if st is None:
                out.left = True
                out.state = None
            else:
                out.masters.append(group.encode(st.master))
                out.state = st

    def founding_worker(i: int) -> None:
        out = outcomes[i]
        incarnation = 0
        while True:
            chan = FaultyChannel(channel_factory(i), plan, party=i + 1)
            wal = (
                wal_path(checkpoint_dir, i + 1)
                if checkpoint_dir is not None
                else None
            )
            rng = random.Random(seed * 6151 + i + incarnation * 7919)
            try:
                res = run_party(
                    chan, env, keys[i], pks, i + 1, rng,
                    timeout=timeout, checkpoint=wal,
                )
                out.base = res
                mgr = EpochManager(
                    chan, group, genesis_from_party_result(env, res),
                    keys[i], pks, rng,
                    timeout=timeout, checkpoint=wal, max_churn=None,
                )
                # run_party's recorder is scoped to the ceremony; the
                # epoch ops need their own ambient binding or every
                # epoch_* emit is a no-op.  Same ceremony id, so the
                # per-party JSONL carries one merged stream.
                obs = obslog.from_env(
                    ceremony_id=obslog.ceremony_id_for(env), party=i + 1
                )
                try:
                    with obslog.use(obs):
                        ops(mgr, out, founding=True)
                finally:
                    if obs is not None:
                        obs.close()
                out.resumes = max(out.resumes, incarnation)
                return
            except RestartFault:
                if checkpoint_dir is None:
                    out.error = out.error or RestartFault(
                        f"party {i + 1} restarted without a checkpoint"
                    )
                    return
                incarnation += 1
            except Exception as exc:  # noqa: BLE001 — surfaced verbatim
                out.error = exc
                out.resumes = max(out.resumes, incarnation)
                return

    def joiner_worker(q: int) -> None:
        out = outcomes[n + q]
        party_id = n + 1 + q
        incarnation = 0
        while True:
            chan = FaultyChannel(channel_factory(n + q), plan, party=party_id)
            wal = (
                wal_path(checkpoint_dir, party_id)
                if checkpoint_dir is not None
                else None
            )
            rng = random.Random(seed * 6151 + (n + q) + incarnation * 7919)
            try:
                observer = EpochState(
                    epoch=refreshes, n=n, t=env.threshold,
                    index=None, share=None, commitments=None,
                )
                # the joiner's opening fetch must outlast the whole
                # preceding sequence: 5 ceremony rounds + 3 per earlier
                # epoch op, each of which may stall for one full timeout
                boot = min(join_timeout, timeout * (8 + 3 * refreshes) + 60.0)
                mgr = EpochManager(
                    chan, group, observer, joiner_keys[q], pks, rng,
                    timeout=timeout, first_fetch_timeout=boot,
                    checkpoint=wal, max_churn=None,
                    ops_done=refreshes,
                )
                obs = obslog.from_env(
                    ceremony_id=obslog.ceremony_id_for(env), party=party_id
                )
                try:
                    with obslog.use(obs):
                        ops(mgr, out, founding=False)
                finally:
                    if obs is not None:
                        obs.close()
                out.resumes = max(out.resumes, incarnation)
                return
            except RestartFault:
                if checkpoint_dir is None:
                    out.error = out.error or RestartFault(
                        f"joiner {party_id} restarted without a checkpoint"
                    )
                    return
                incarnation += 1
            except Exception as exc:  # noqa: BLE001 — surfaced verbatim
                out.error = exc
                out.resumes = max(out.resumes, incarnation)
                return

    threads = [
        threading.Thread(target=founding_worker, args=(i,)) for i in range(n)
    ] + [
        threading.Thread(target=joiner_worker, args=(q,))
        for q in range(sched.joiners)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=join_timeout)
    return outcomes


def honest_results(results, plan: FaultPlan) -> list[PartyResult]:
    """The PartyResults of parties the plan never touched (1-based
    untouched indices), in index order."""
    touched = (
        {s for (_, s) in plan._faults}
        | set(plan._crash_after)
        | set(plan._restarts)
    )
    return [
        r
        for i, r in enumerate(results)
        if (i + 1) not in touched and isinstance(r, PartyResult)
    ]
