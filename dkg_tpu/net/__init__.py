"""Host-side broadcast-channel backends + channel-driven party runner.

The reference deliberately has no communication layer: the protocol
assumes an external authenticated broadcast channel ("the blockchain",
reference src/lib.rs:91-92) and its tests pass message arrays by hand
(committee.rs:1337-1338).  This package supplies that missing piece as
a first-class subsystem: an abstract ``BroadcastChannel``, an
in-process implementation (the reference's test style, made explicit),
a TCP hub for real multi-process ceremonies, and ``run_party`` — the
full 5-phase protocol driven over a channel with the deterministic wire
encoding from utils.serde.

Device-mesh ceremonies (dkg_tpu.parallel) ride ICI/DCN collectives
instead; this layer is the host-side external-world boundary.

Robustness: transports are first-publish-wins with equivocation
evidence, TcpHubChannel retries with capped backoff under DKG_TPU_NET_*
knobs, run_party quarantines malformed peer bytes, and net.faults adds
a deterministic fault-injection harness (docs/fault_model.md).
net.checkpoint adds durable crash recovery: parties journal each round
to a write-ahead log and ``run_party(..., checkpoint=...)`` resumes a
restarted process mid-ceremony (docs/fault_model.md, "Crash recovery").
"""

from .channel import (  # noqa: F401
    BroadcastChannel,
    InProcessChannel,
    RetryBudgetExceeded,
    TcpHub,
    TcpHubChannel,
    TransportError,
    TruncatedStream,
)
from .checkpoint import (  # noqa: F401
    PartyWal,
    default_checkpoint_dir,
    wal_path,
)
from .faults import (  # noqa: F401
    CrashFault,
    FaultPlan,
    FaultyChannel,
    RestartFault,
)
from .party import PartyResult, run_party  # noqa: F401
