"""Multi-host ceremonies: one global mesh, DCN under the collectives.

The reference has no multi-node story at all (SURVEY §2: no sockets,
no MPI/NCCL); here scaling past one host is the SAME sharded program as
parallel.mesh — ``jax.distributed.initialize`` forms the global runtime,
``global_party_mesh`` lays every process's devices on the one party
axis, and XLA routes ``all_gather``/``all_to_all`` over ICI within a
host and DCN across hosts.  The external broadcast-channel boundary
(dkg_tpu.net) stays host-side, exactly as the reference leaves it to
the caller (src/lib.rs:91-92).

Deployment shape (one process per host):

    from dkg_tpu.parallel import multihost, mesh
    multihost.init_multihost(coordinator_address="host0:1234",
                             num_processes=4, process_id=rank)
    m = multihost.global_party_mesh()
    mesh.sharded_ceremony(cfg, m, ...)   # unchanged program
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import PARTY_AXIS


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: list[int] | None = None,
) -> None:
    """Join the multi-process JAX runtime; no-op for single-process runs
    so the same launcher works from a laptop to a pod slice."""
    if not num_processes or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def global_party_mesh() -> Mesh:
    """1-D party mesh over EVERY device in the (possibly multi-host)
    runtime — `jax.devices()` is global after init_multihost."""
    return Mesh(np.asarray(jax.devices()), (PARTY_AXIS,))


def process_party_block(n_parties: int, mesh: Mesh | None = None) -> tuple[int, int]:
    """This process's contiguous party block [start, stop) under the
    party-axis sharding (for host-side per-party work like DEM sealing
    that must track the device sharding).

    Derived from the devices' POSITIONS on the mesh's party axis — not
    from raw device ids, which a runtime may hand out non-contiguously
    or out of global order.  Mesh position p owns parties
    [p·per_dev, (p+1)·per_dev).  Raises when this process's devices do
    not form one contiguous run of positions (host-side per-party work
    would then need a per-position split, not one block) — loud failure
    instead of silently sealing the wrong parties' shares.
    """
    if mesh is not None:
        if mesh.devices.ndim != 1 or mesh.axis_names != (PARTY_AXIS,):
            raise ValueError(
                f"expected a 1-D ({PARTY_AXIS!r},) mesh, got axes "
                f"{mesh.axis_names} shape {mesh.devices.shape}: flat "
                "positions would not correspond to party-axis coordinates"
            )
        devs = list(mesh.devices.flat)
    else:
        devs = jax.devices()
    n_dev = len(devs)
    if n_parties % n_dev:
        raise ValueError(f"{n_parties} parties do not shard evenly over {n_dev} devices")
    per_dev = n_parties // n_dev
    local_ids = {d.id for d in jax.local_devices()}
    positions = sorted(i for i, d in enumerate(devs) if d.id in local_ids)
    if not positions:
        raise RuntimeError("this process owns no devices on the party mesh")
    if positions != list(range(positions[0], positions[-1] + 1)):
        raise RuntimeError(
            "this process's devices sit at non-contiguous party-axis positions "
            f"{positions}; lay the mesh out process-major (global_party_mesh "
            "does) or split host-side work per position"
        )
    return positions[0] * per_dev, (positions[-1] + 1) * per_dev
