"""Device-sharded steady sign lane: the folded sigma*H(m) ladder over a
mesh batch axis.

The scheduler's fast leg signs every unproved ticket's messages with
ONE ladder dispatch per rung (sign.partial.sign_folded) — the ladder is
batch-elementwise, so a rung-512/1024 shape shards embarrassingly over
the device axis.  This module owns the mesh handle and the shard_map
(lint rule DKG015 confines ``Mesh``/``PartitionSpec``/``shard_map``
construction to dkg_tpu/parallel/ — call sites take a mesh handle),
gated behind ``DKG_TPU_SIGN_MESH`` (``1`` = engage where sharding can
win, ``force`` = engage on any >=2-device mesh; validated via
utils.envknobs — the scheduler never reads the environment itself, per
DKG007).

Bit-exactness: sharding a batch-elementwise ladder changes nothing but
the device each row runs on, so the sharded rung is limb-identical to
the single-device rung — byte-checked against the host ``secret*H(m)``
oracle every ``scripts/sign_bench.py --steady`` run.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ..groups import device as gd
from ..utils import envknobs
from . import mesh as pm


def sign_mesh() -> "pm.Mesh | None":
    """The sign lane's device mesh, or None when the lane should stay
    single-device: knob off/unset, fewer than two devices visible, or
    (``1``, the auto setting) no parallel capacity behind the devices.

    The folded ladder is DEPTH-dominated — every shard pays the full
    rung-iteration chain while the batch rows ride the vector lanes
    nearly free — so sharding only wins where shards actually run
    concurrently.  On a real accelerator mesh they do; on a
    host-count-forced CPU mesh the virtual devices share the box's
    cores, and with a single core the 8 shard programs serialise into
    ~3x the single-device wall clock (measured: 1.0 s vs 0.38 s per
    width-64 rung).  ``1`` therefore engages only when the backend is
    an accelerator or the host has at least two cores; ``force``
    engages on any >=2-device mesh regardless — the setting
    byte-exactness checks and real-mesh runs use.

    Cheap enough to resolve per convoy (jax caches the device list), so
    the scheduler holds no stale handle across a hostmesh re-force.
    """
    knob = envknobs.choice(
        "DKG_TPU_SIGN_MESH",
        ("0", "1", "force"),
        "device-sharded folded sign ladder",
    )
    if knob not in ("1", "force"):
        return None
    n_dev = len(jax.devices())
    if n_dev < 2:
        return None
    if knob == "1" and jax.default_backend() == "cpu" and (
        os.cpu_count() or 1
    ) < 2:
        return None
    return pm.make_mesh(n_dev)


def sign_folded_sharded(curve: str, sigma_limbs, h_dev, mesh: pm.Mesh):
    """sign.partial.sign_folded over ``mesh``'s device axis.

    Pads the batch up to a multiple of the mesh size with zero rows
    (zero scalar bits leave the ladder accumulator at the identity; the
    phantom rows are sliced off before return), shards the (B, L)
    sigma rows and (B, C, L) H(m) points on the batch axis, and runs
    the ladder shard-locally — no collectives, pure map.  Returns the
    RAW device (B, C, L) result exactly like ``sign_folded``, so the
    scheduler's rung pipeline (``folded_collect`` after every rung is
    in flight) works unchanged.
    """
    cs = gd.ALL_CURVES[curve]
    hh = jnp.asarray(h_dev)
    kk = jnp.asarray(sigma_limbs)
    if kk.ndim == 1:
        kk = jnp.broadcast_to(kk[None, :], (hh.shape[0], kk.shape[-1]))
    b = hh.shape[0]
    n_dev = int(mesh.devices.size)
    pad = (-b) % n_dev
    if pad:
        kk = jnp.concatenate(
            [kk, jnp.zeros((pad,) + kk.shape[1:], kk.dtype)], axis=0
        )
        hh = jnp.concatenate(
            [hh, jnp.zeros((pad,) + hh.shape[1:], hh.dtype)], axis=0
        )

    out = _ladder_prog(curve, mesh, pm._knob_state())(kk, hh)
    return out[:b] if pad else out


@functools.lru_cache(maxsize=None)
def _ladder_prog(curve: str, mesh: "pm.Mesh", knobs: tuple):
    """Memoized, jitted sharded ladder — the steady lane dispatches one
    rung per call, so a per-call shard_map closure would retrace every
    rung (``knobs`` is cache key only, same discipline as mesh.py's
    program builders; jit's own cache covers varying rung widths)."""
    del knobs
    cs = gd.ALL_CURVES[curve]

    @jax.jit
    @functools.partial(
        pm._shard_map_nocheck,
        mesh=mesh,
        in_specs=(pm.P(pm.PARTY_AXIS), pm.P(pm.PARTY_AXIS)),
        out_specs=pm.P(pm.PARTY_AXIS),
    )
    def step(k, h):
        return gd.scalar_mul(cs, k, h)

    return step
