"""Multi-chip sharding (mesh + collectives at round boundaries)."""

import importlib

_SUBMODULES = ("hostmesh", "mesh", "multihost", "signmesh")


# Lazy (PEP 562): `from dkg_tpu.parallel.hostmesh import force_cpu_mesh`
# must not drag in mesh/multihost (and with them jax) first.
def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"dkg_tpu.parallel.{name}")
    raise AttributeError(f"module 'dkg_tpu.parallel' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
