"""Multi-chip sharding (mesh + collectives at round boundaries)."""

from . import mesh, multihost  # noqa: F401
