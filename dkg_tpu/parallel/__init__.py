"""Multi-chip sharding (mesh + collectives at round boundaries)."""

from . import mesh  # noqa: F401
