"""Force a virtual multi-device CPU backend for sharding tests/dryruns.

Multi-chip TPU hardware is not available in this environment; sharding
correctness is validated on an n-virtual-device CPU mesh.  The forcing
logic is ordering-sensitive and lives here ONCE — tests/conftest.py and
__graft_entry__.dryrun_multichip both call it.

Why each step is needed:

* ``jax.config.update("jax_platforms", "cpu")`` is the load-bearing
  platform switch.  An env var cannot do this job here: jax binds
  ``JAX_PLATFORMS`` into its config default at import time, and the
  driver image's sitecustomize both pins it to ``axon`` (the real TPU
  tunnel) and sets the jax_platforms *config* when registering the
  plugin.  The config-level update outranks all of that, and works
  even if jax is already imported (but not yet initialised).
* ``--xla_force_host_platform_device_count=N`` is read from
  ``XLA_FLAGS`` at backend initialisation (later than jax import, so
  setting it here still works); a stale count from a previous setting
  is REWRITTEN, not kept, so the mesh really has N devices.
* ``os.environ["JAX_PLATFORMS"] = "cpu"`` only matters for
  *subprocesses* this process spawns — for the current process the
  config update above is what forces the platform.

Only effective before the first backend initialisation (jax caches the
device list); ``mesh.make_mesh`` raises if the resulting device count
falls short of what a caller asked for.  ``tests/test_import_hygiene.py``
guards the prerequisite: importing ``dkg_tpu`` must never initialise a
backend (no module-level device constants).
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def force_cpu_mesh(n_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    repl = f"--{_FLAG}={n_devices}"
    if _FLAG in flags:
        flags = re.sub(rf"--{_FLAG}=\d+", repl, flags)
    else:
        flags = (flags + " " + repl).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
