"""Multi-chip DKG: participants sharded over a device mesh.

The reference leaves the broadcast channel abstract — callers shuttle
`Option<BroadcastPhaseN>` arrays between parties (reference:
committee.rs:825-871, lib.rs:91-92).  On a TPU pod slice that seam maps
onto XLA collectives over ICI (SURVEY §2 table, §5):

* round-1 "publish commitments, everyone fetches" -> ``all_gather`` of
  the commitment limb tensors across the party-sharded mesh axis;
* per-recipient encrypted-share delivery -> ``all_to_all`` of the
  (dealer, recipient) share matrix (dealer-sharded -> recipient-sharded);
* master-key assembly -> every shard reduces the gathered bare
  commitments (or a ``psum``-style tree on point limbs).

Multi-host ceremonies ride the same code: a global mesh over all hosts'
devices puts DCN under the same collectives, with the external
blockchain boundary staying host-side exactly like the reference leaves
it to the caller.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect

# jax renamed shard_map's replication-check kwarg (check_rep -> check_vma
# in 0.9), and newer versions drop it entirely (checked semantics became
# the only semantics).  Resolve the right name once so call sites stay
# stable; None means "no kwarg to pass" — every body in this module is
# collective-explicit, so it type-checks under the always-checked
# signature and the wrapper degrades to plain shard_map.
_SHARD_MAP_CHECK_KW = next(
    (k for k in ("check_vma", "check_rep") if k in inspect.signature(_shard_map).parameters),
    None,
)


def _shard_map_nocheck(f, *, mesh, in_specs, out_specs):
    """shard_map with the replication/VMA check disabled where the
    installed jax still exposes one (named so a future call site wanting
    jax's checked semantics doesn't silently get this wrapper)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **({_SHARD_MAP_CHECK_KW: False} if _SHARD_MAP_CHECK_KW else {}),
    )

from ..dkg import ceremony as ce
from ..groups import device as gd
from jax import lax

PARTY_AXIS = "parties"

# Env knobs whose values are read at TRACE time and baked into the
# compiled sharded programs (chunk widths, field mul/reduce/carry
# formulation, MSM/window schedule, fused tiers, digest dispatch).  The
# memoized program builders below put a snapshot of these values into
# their cache key, so flipping a knob between calls retraces — the
# semantics per-call eager tracing always had — while a steady-state
# rerun at stable knobs reuses the jitted executable instead of
# recompiling the whole sharded program set: before this cache the
# north-star warm run cost the same as the cold one (NORTHSTAR r01
# measured warm 135.6 s vs cold 126.0 s at (16, 5) on the CPU mesh —
# pure retrace).
_TRACE_KNOBS = (
    "DKG_TPU_DEAL_CHUNK",
    "DKG_TPU_VERIFY_CHUNK",
    "DKG_TPU_RLC_CHUNK",
    "DKG_TPU_MSM",
    "DKG_TPU_FB_WINDOW",
    "DKG_TPU_FUSED_MULTI",
    "DKG_TPU_ED_FUSED_LADDER",
    "DKG_TPU_ED_FUSED_DOUBLES",
    "DKG_TPU_PALLAS",
    "DKG_TPU_ASSUME_BACKEND",
    "DKG_TPU_REDUCE",
    "DKG_TPU_CARRY",
    "DKG_TPU_MUL",
    "DKG_TPU_MXU",
    "DKG_TPU_DIGEST",
)


def _knob_state() -> tuple:
    """Snapshot of the trace-relevant knobs (empty == unset, matching
    envknobs' convention) — the program builders' cache-key tail."""
    return tuple(os.environ.get(k) or None for k in _TRACE_KNOBS)


def _verify_env_chunk() -> int | None:
    """DKG_TPU_VERIFY_CHUNK (0 disables), validated by the shared knob
    parser in ceremony."""
    return ce._env_chunk("DKG_TPU_VERIFY_CHUNK")


def _verify_chunk_default(cfg: ce.CeremonyConfig, block: int) -> int:
    """Recipient-axis chunk width for the sharded verify/finalise body.

    The round-2 share delivery moves the (n, block, L) u32 share matrix
    through an ``all_to_all`` whose send AND recv buffers are live
    temps, and the same tensor is then copied into ``aggregate_shares``
    and padded by the MXU matmul digitizer — at BLS n=16384/8 devices
    each of those is ~2 GB, and the TPU buffer assigner fragmented them
    into a 48.62 G program (MEMPROOF_TPU round 4, vs 15.75 G HBM).
    Chunking the recipient axis bounds every one of those temps at once:
    per chunk the a2a moves (n, w, L), the aggregate carries (w, L),
    and the digitizer pads (w, n, L)-shaped operands.

    Budget: recv buffer n * w * L * 4 B <= 128 MiB, floored to a power
    of two so full chunks share one program, clamped to [1, block].
    """
    fs = cfg.cs.scalar
    per_recipient = cfg.n * fs.limbs * 4
    w = max(1, (128 << 20) // per_recipient)
    w = 1 << max(0, w.bit_length() - 1)
    return min(w, block)


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the party axis (v5e-8: 8 shards, 512 parties/shard
    at n=4096 — SURVEY §2 table row 4).

    Raises rather than truncating when fewer than ``n_devices`` devices
    exist (e.g. the backend initialised before hostmesh forcing took
    effect) — a silently smaller mesh would make sharding "tests" pass
    without exercising the collectives.
    """
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"requested a {n_devices}-device mesh but only {len(devs)} "
                "devices exist (was the jax backend initialised before "
                "hostmesh.force_cpu_mesh?)"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (PARTY_AXIS,))


def sharded_deal(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    coeffs_a: jax.Array,  # (n, t+1, L) global, sharded on axis 0
    coeffs_b: jax.Array,
    g_table: jax.Array,  # replicated
    h_table: jax.Array,
):
    """Round 1 over the mesh: local dealing, EVERYTHING dealer-sharded.

    Returns (a, e, s, r) all sharded on the dealer axis.  The round-1
    "broadcast" is deliberately NOT an allgather: replicating the
    commitment tensor is what caps committee size (at n=16384, t=5461
    the E tensor alone is ~17 GB — more than a v5e chip's HBM).  What
    verification actually consumes is (a) the rho-combined commitment
    columns, exchanged later as ndev partial point-RLCs of (t+1, C, L)
    each (sharded_verify_finalise), and (b) the transcript digest,
    exchanged as 32-byte per-dealer row digests
    (ce.sharded_transcript_digest) — both O(t + n), not O(n*t).
    """
    a, e = sharded_deal_commitments(cfg, mesh, coeffs_a, coeffs_b, g_table, h_table)
    s, r = sharded_deal_shares(cfg, mesh, coeffs_a, coeffs_b)
    return a, e, s, r


def sharded_deal_commitments(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    coeffs_a: jax.Array,
    coeffs_b: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
):
    """Round-1 commitment program: (A, E), dealer-sharded.

    Dealing runs as TWO sequential programs (this one, then
    :func:`sharded_deal_shares`) so the fixed-base scan's chunk carry
    is freed before the Horner share evaluation allocates its temps —
    the monolithic chunked deal keeps a ~6.5 G temp floor alive next
    to 12.2 G of its own inputs+outputs at BLS n=16384 over 8 devices
    (MEMPROOF_TPU round 5), which no chunk width can fit into a 16 GB
    v5e.  Callers wanting the memory bound must NOT wrap both halves
    in one outer jit — that fuses them back into one program.
    """
    _check_mesh(cfg, mesh)
    step = _deal_commitments_prog(cfg, mesh, _knob_state())
    return step(coeffs_a, coeffs_b, g_table, h_table)


@functools.lru_cache(maxsize=None)
def _deal_commitments_prog(cfg: ce.CeremonyConfig, mesh: Mesh, knobs: tuple):
    """Memoized, jitted round-1 commitment program (``knobs`` is cache
    key only — the trace below re-reads the environment)."""
    del knobs

    @jax.jit
    @functools.partial(
        _shard_map_nocheck,
        mesh=mesh,
        in_specs=(P(PARTY_AXIS), P(PARTY_AXIS), P(), P()),
        out_specs=(P(PARTY_AXIS), P(PARTY_AXIS)),
    )
    def step(ca, cb, gt, ht):
        # chunked in-trace (lax.map) so the fixed-base scan's padded
        # carry stays bounded per shard — the AOT TPU compile of the
        # one-shot body at BLS n=16384/8 devices was rejected at 21.3 GB
        return ce.deal_commitments_traced_chunked(cfg, ca, cb, gt, ht)

    return step


def sharded_deal_shares(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    coeffs_a: jax.Array,
    coeffs_b: jax.Array,
):
    """Round-1 share program: (s, r), dealer-sharded (second of the two
    sequential deal programs; see :func:`sharded_deal_commitments`)."""
    _check_mesh(cfg, mesh)
    return _deal_shares_prog(cfg, mesh, _knob_state())(coeffs_a, coeffs_b)


@functools.lru_cache(maxsize=None)
def _deal_shares_prog(cfg: ce.CeremonyConfig, mesh: Mesh, knobs: tuple):
    del knobs

    @jax.jit
    @functools.partial(
        _shard_map_nocheck,
        mesh=mesh,
        in_specs=(P(PARTY_AXIS), P(PARTY_AXIS)),
        out_specs=(P(PARTY_AXIS), P(PARTY_AXIS)),
    )
    def step(ca, cb):
        return ce.deal_shares_traced_chunked(cfg, ca, cb)

    return step


def sharded_verify_finalise(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    a0: jax.Array,  # (n, C, L) dealer-sharded BARE first columns A_{j,0}
    e: jax.Array,  # (n, t+1, C, L) dealer-sharded randomized commitments
    s: jax.Array,  # (n, n, L) dealer-sharded share matrix
    r: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
    rho: jax.Array,  # (n, L) replicated Fiat-Shamir randomizers
    rho_bits: int,
):
    """Round 2 + finalise over the mesh, commitments never replicated.

    Collectives per shard — O(ndev * t) for the gathered RLC partials
    and O(n * n/ndev) for the share all_to_all; crucially nothing is
    O(n * t), so the layout scales to the n=16384 BASELINE config where
    a replicated E tensor (~17 GB) would not fit in HBM:

    * share delivery dealer-sharded -> recipient-sharded: ``all_to_all``
      of the share/hiding matrices;
    * the rho-combined commitment columns D_l = sum_j rho_j E_{j,l}:
      each shard point-RLCs its OWN dealers with its slice of rho, then
      one ``all_gather`` of the ndev partial (t+1, C, L) column tensors
      + a local tree-add;
    * the master key: local tree-add of the shard's bare A_{j,0} +
      ``all_gather`` of ndev partial points.

    Takes only the BARE FIRST COLUMNS a0 = a[:, 0] (the master key's
    sole input, committee.rs:791-796) rather than the full (n, t+1)
    bare tensor: at BLS n=16384 that keeps a 3.22 G argument out of the
    round-2 program's working set, and lets the engine FREE the full
    bare tensor right after the transcript digest — the happy path
    never reads the other columns.

    Returns (ok, final_shares, master): ok/final_shares
    recipient-sharded, master replicated.
    """
    _check_mesh(cfg, mesh)
    step = _verify_finalise_prog(cfg, mesh, rho_bits, _knob_state())
    return step(a0, e, s, r, g_table, h_table, rho)


@functools.lru_cache(maxsize=None)
def _verify_finalise_prog(
    cfg: ce.CeremonyConfig, mesh: Mesh, rho_bits: int, knobs: tuple
):
    del knobs
    n_dev = mesh.devices.size
    cs = cfg.cs

    @jax.jit
    @functools.partial(
        _shard_map_nocheck,
        mesh=mesh,
        in_specs=(P(PARTY_AXIS), P(PARTY_AXIS), P(PARTY_AXIS), P(PARTY_AXIS), P(), P(), P()),
        out_specs=(P(PARTY_AXIS), P(PARTY_AXIS), P()),
    )
    def step(a0_sh, e_sh, s_sh, r_sh, gt, ht, rho_all):
        shard = lax.axis_index(PARTY_AXIS)
        block = cfg.n // n_dev
        first = shard * block + 1
        # --- combined commitment columns: partial RLC over local dealers,
        # then gather + tree-add the ndev partials (point sum, NOT psum:
        # limbs don't add elementwise)
        rho_local = lax.dynamic_slice_in_dim(rho_all, shard * block, block, 0)
        d_part = ce._point_rlc(cs, rho_local, e_sh, rho_bits)  # (t+1, C, L)
        d_all = lax.all_gather(d_part, PARTY_AXIS)  # (ndev, t+1, C, L)
        d_comm = gd._tree_reduce(cs, jnp.moveaxis(d_all, 0, -3), n_dev)
        # --- round 2 + aggregation, recipient-chunked: share delivery
        # (all_to_all), RLC batch verification, and the qualified-sum all
        # ride one bounded-width loop so no (n, block, L) temp ever
        # materialises (the round-4 MEMPROOF_TPU 48.6 G blow-up)
        qual = jnp.ones((cfg.n,), bool)  # blame re-finalises separately
        ok, finals = _verify_aggregate_chunked(
            cfg, n_dev, d_comm, s_sh, r_sh, rho_all, rho_bits, gt, ht,
            qual, first, block,
        )
        master = _master_shardlocal(cfg, n_dev, a0_sh, qual, shard, block)
        return ok, finals, master

    return step


def _master_shardlocal(cfg, n_dev, a0_sh, qual, shard, block):
    """Master key inside a shard_map body; a0_sh (block, C, L) are the
    shard's bare A_{j,0} columns.

    Masks them by the shard's slice of the qualified set before
    reducing — same semantics as the single-device
    master_key_from_bare, so the master key and the aggregated shares
    always cover the same dealer set.
    """
    cs = cfg.cs
    q_local = lax.dynamic_slice_in_dim(qual, shard * block, block, 0)
    a0 = gd.select(q_local, a0_sh, gd.identity(cs, (block,)))
    m_part = gd._tree_reduce(cs, a0, block)  # (C, L)
    m_all = lax.all_gather(m_part, PARTY_AXIS)  # (ndev, C, L)
    return gd._tree_reduce(cs, m_all, n_dev)


def _recipient_chunk(cfg, block: int) -> int:
    """Resolved recipient-chunk width: env override else budget default;
    0 / >= block means unchunked."""
    chunk = _verify_env_chunk()
    if chunk is None:
        chunk = _verify_chunk_default(cfg, block)
    return chunk


def _chunked_recipient_loop(n_dev, block: int, chunk: int, run, tensors):
    """Drive ``run(off, w, *slices)`` over recipient-axis chunks.

    ``tensors`` are dealer-sharded (block_d, n, L) arrays whose global
    recipient axis 1 is viewed as (n_dev, block); each chunk passes the
    [off, off+w) slice of EVERY destination's local block, reshaped to
    (block_d, n_dev*w, L) — exactly what a tiled ``all_to_all`` on axis
    1 expects.  The sequential-map/ragged-tail skeleton (and its
    never-unroll invariant) lives in utils.scanchunk.map_chunked;
    outputs are concatenated on the leading (recipient) axis.
    """
    from ..utils.scanchunk import map_chunked

    views = []
    for x in tensors:
        bd = x.shape[0]
        views.append(x.reshape((bd, n_dev, block) + tuple(x.shape[2:])))

    def call(off, w):
        sl = []
        for v in views:
            bd = v.shape[0]
            c = lax.dynamic_slice_in_dim(v, off, w, axis=2)
            sl.append(c.reshape((bd, n_dev * w) + tuple(v.shape[3:])))
        return run(off, w, *sl)

    return map_chunked(block, chunk, call)


def _verify_aggregate_chunked(
    cfg, n_dev, d_comm, s_sh, r_sh, rho, rho_bits, gt, ht, qual, first, block
):
    """Share delivery + RLC batch verify + qualified aggregation, in
    recipient chunks inside a shard_map body.

    One all_to_all per chunk delivers (n, w, L) share/hiding rows; the
    chunk is verified (same equations as ce.verify_batch, shard-local
    recipient indices) and aggregated immediately, so peak live temps
    scale with w, not block.  Bit-identical to the one-shot body: each
    recipient's check and final share read only that recipient's column.
    """
    cs = cfg.cs
    fs = cs.scalar

    def run(off, w, sc, rc):
        s_recv = lax.all_to_all(sc, PARTY_AXIS, split_axis=1, concat_axis=0, tiled=True)
        r_recv = lax.all_to_all(rc, PARTY_AXIS, split_axis=1, concat_axis=0, tiled=True)
        s_rlc = ce._field_dot(fs, rho, s_recv)  # (w, L)
        r_rlc = ce._field_dot(fs, rho, r_recv)
        xs = (first + off + jnp.arange(w, dtype=jnp.uint32)).astype(jnp.uint32)
        rhs = gd.eval_point_poly(cs, d_comm, xs, cfg.index_bits)
        lhs = gd.add(
            cs,
            gd.fixed_base_mul(cs, gt, s_rlc),
            gd.fixed_base_mul(cs, ht, r_rlc),
        )
        return gd.eq(cs, lhs, rhs), ce.aggregate_shares(cfg, s_recv, qual)

    chunk = _recipient_chunk(cfg, block)
    return _chunked_recipient_loop(n_dev, block, chunk, run, (s_sh, r_sh))


def _aggregate_chunked(cfg, n_dev, s_sh, qual, block):
    """Chunked share delivery + qualified aggregation only (the blame
    re-finalise path: verification already adjudicated)."""

    def run(off, w, sc):
        s_recv = lax.all_to_all(sc, PARTY_AXIS, split_axis=1, concat_axis=0, tiled=True)
        return (ce.aggregate_shares(cfg, s_recv, qual),)

    chunk = _recipient_chunk(cfg, block)
    (finals,) = _chunked_recipient_loop(n_dev, block, chunk, run, (s_sh,))
    return finals


def sharded_finalise(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    a0: jax.Array,  # (n, C, L) dealer-sharded bare first columns
    s: jax.Array,  # (n, n, L) dealer-sharded
    qualified: jax.Array,  # (n,) replicated dealer mask
):
    """Aggregation + master key only, over an adjudicated qualified set
    (the blame path re-finalise: no verification work — the pairwise
    checks already determined exactly which dealers are out)."""
    _check_mesh(cfg, mesh)
    return _finalise_prog(cfg, mesh, _knob_state())(a0, s, qualified)


@functools.lru_cache(maxsize=None)
def _finalise_prog(cfg: ce.CeremonyConfig, mesh: Mesh, knobs: tuple):
    del knobs
    n_dev = mesh.devices.size

    @jax.jit
    @functools.partial(
        _shard_map_nocheck,
        mesh=mesh,
        in_specs=(P(PARTY_AXIS), P(PARTY_AXIS), P()),
        out_specs=(P(PARTY_AXIS), P()),
    )
    def step(a0_sh, s_sh, qual):
        shard = lax.axis_index(PARTY_AXIS)
        block = cfg.n // n_dev
        finals = _aggregate_chunked(cfg, n_dev, s_sh, qual, block)
        master = _master_shardlocal(cfg, n_dev, a0_sh, qual, shard, block)
        return finals, master

    return step


def sharded_blame(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    e: jax.Array,  # (n, t+1, C, L) dealer-sharded
    s: jax.Array,  # (n, n, L) dealer-sharded
    r: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
):
    """Pairwise blame assignment on the mesh -> replicated (n, n) bools.

    The per-pair check g*s_ji + h*s'_ji == sum_l x_i^l E_{j,l} reads
    ONLY dealer-local data (each shard holds its dealers' commitments
    AND the share rows they dealt), so blame needs zero share movement:
    every shard re-checks its own dealers against all n recipients and
    one bool allgather assembles the verdict matrix (the mesh twin of
    ceremony.verify_pairwise / the reference complaint trigger,
    committee.rs:305-317).  Rare-path cost: O(n * n/ndev) fixed-base
    mults per shard.
    """
    _check_mesh(cfg, mesh)
    return _blame_prog(cfg, mesh, _knob_state())(e, s, r, g_table, h_table)


@functools.lru_cache(maxsize=None)
def _blame_prog(cfg: ce.CeremonyConfig, mesh: Mesh, knobs: tuple):
    del knobs

    @jax.jit
    @functools.partial(
        _shard_map_nocheck,
        mesh=mesh,
        in_specs=(P(PARTY_AXIS), P(PARTY_AXIS), P(PARTY_AXIS), P(), P()),
        out_specs=P(),
    )
    def step(e_sh, s_sh, r_sh, gt, ht):
        pw = ce.verify_pairwise(cfg, e_sh, s_sh, r_sh, gt, ht)  # (block, n)
        return lax.all_gather(pw, PARTY_AXIS, tiled=True)  # (n, n)

    return step


def sharded_ceremony(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    coeffs_a: jax.Array,
    coeffs_b: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
    rho_bits: int = 128,
    tamper=None,
):
    """Full ceremony, parties sharded over the mesh — blame included.

    Two device phases with a host Fiat-Shamir boundary between them —
    rho is derived from the digest of the COMPLETE round-1 transcript
    (commitments + delivered shares), never from a fixed string, so the
    batch check is sound against an adaptive dealer and publicly
    recomputable.  If the batch check fails anywhere, the engine drops
    to ``sharded_blame``, disqualifies guilty dealers, and re-finalises
    over the qualified set with ``sharded_finalise`` (aggregation +
    master key only — the pairwise checks already adjudicated, so no
    verification is repeated), mirroring BatchedCeremony.run's flow.

    Returns (ok, finals, master, qualified): ``ok`` is the
    PRE-adjudication per-recipient batch check (failures show which
    recipients received bad shares); ``qualified`` the final dealer
    mask.  Raises ``DkgError(MISBEHAVIOUR_HIGHER_THRESHOLD)`` when more
    than t dealers are disqualified (committee.rs:340-347 — the tuple
    API has no error slot, and proceeding would yield a key backed by
    fewer than t+1 honest dealers).  ``tamper(a, e, s, r) -> same`` is
    the fault-injection hook (arrays must keep their shardings);
    jit-compiled over the mesh; the driver's ``dryrun_multichip`` runs
    this on a virtual CPU mesh.
    """
    from ..dkg.errors import DkgError, DkgErrorKind

    a, e, s, r = sharded_deal(cfg, mesh, coeffs_a, coeffs_b, g_table, h_table)
    if tamper is not None:
        a, e, s, r = tamper(a, e, s, r)
    jax.block_until_ready(e)
    # multihost-safe: only 32-byte row digests cross process boundaries
    digest = ce.sharded_transcript_digest(cfg, a, e, s, r)
    rho = jnp.asarray(ce.fiat_shamir_rho(cfg, digest, rho_bits))
    # After the digest only the BARE FIRST COLUMNS are ever read (the
    # master key); dropping the full bare tensor here returns its HBM
    # (3.22 G at BLS n=16384) before the round-2 program runs.
    a0 = a[:, 0]
    del a
    ok, finals, master = sharded_verify_finalise(
        cfg, mesh, a0, e, s, r, g_table, h_table, rho, rho_bits
    )
    qualified = jnp.ones((cfg.n,), bool)
    if not bool(_host_global(ok).all()):
        # pw is replicated (out_specs P()), so plain asarray is
        # multihost-safe: every process holds a full copy
        pw = np.asarray(sharded_blame(cfg, mesh, e, s, r, g_table, h_table))
        guilty = ~pw.all(axis=1)
        if int(guilty.sum()) > cfg.t:
            raise DkgError(
                DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD,
                detail="guilty dealers (1-based): "
                + ", ".join(str(j + 1) for j in np.nonzero(guilty)[0]),
            )
        qualified = jnp.asarray(~guilty)
        finals, master = sharded_finalise(cfg, mesh, a0, s, qualified)
    return ok, finals, master, qualified


def place_sharded(mesh: Mesh, x, spec: P | None = None) -> jax.Array:
    """Place an array onto ``mesh`` under an EXPLICIT PartitionSpec
    (default: sharded on the party axis; pass ``P()`` for replicated
    operands like the fixed-base tables).

    ``jax.device_put`` with a NamedSharding is the one sanctioned way
    host buffers enter the sharded ceremony: committing the layout here
    (instead of letting the first shard_map infer-and-reshard) means
    the deal program's inputs are already dealer-blocked, so round 1
    starts with zero cross-device movement.  No-op when ``x`` already
    has that sharding.
    """
    from jax.sharding import NamedSharding

    return jax.device_put(
        x, NamedSharding(mesh, spec if spec is not None else P(PARTY_AXIS))
    )


def run_sharded_ceremony(
    cfg: ce.CeremonyConfig,
    mesh: Mesh,
    coeffs_a,
    coeffs_b,
    g_table,
    h_table,
    rho_bits: int = 128,
    tamper=None,
    seal=None,
    ceremony_id: str = "sharded",
    registry=None,
):
    """BatchedCeremony.run's mesh twin: the full instrumented ceremony,
    inputs placed with explicit PartitionSpecs, every phase timed and
    attributed per shard.

    The device flow is exactly :func:`sharded_ceremony`'s (bit-identical
    results — pinned by tests/test_parallel.py's subprocess oracle);
    what this driver adds is the operational envelope the north-star
    run publishes:

    * input placement via :func:`place_sharded` (coefficients
      dealer-sharded, tables replicated) so phase 0 starts aligned;
    * per-phase wall clocks -> ``phases_s`` and the
      ``mesh_collective_seconds{op}`` histogram;
    * per-shard readiness events in obslog's ``round_head`` /
      ``publish`` / ``round_tail`` schema (party = shard index), so
      ``obslog.critical_path`` decomposes a sharded barrier exactly the
      way it decomposes a networked one — the straggler it names is the
      last shard to produce its block.  Shards are blocked in mesh
      order, so a shard's publish timestamp includes any wait on the
      ones before it; the LAST publish (the straggler) is exact.
    * optionally, host-side DEM/transport overlapped per shard:
      ``seal=(group, pks_dev, r_enc)`` routes the dealt share matrix
      through ``dkg.hybrid_batch.seal_shares_mesh`` (the
      seal_shares_pipeline chunk overlap lifted to mesh shards), whose
      sealed broadcasts land in the result's ``broadcasts`` slot.

    Phases (the obslog round numbers): 0 deal-commitments,
    1 deal-shares, 2 transcript digest + Fiat-Shamir, 3 verify+finalise,
    4 blame/re-finalise (failed batch check only).

    Returns a BatchedCeremony.run-style dict: ``ok`` (pre-adjudication
    per-recipient batch check, recipient-sharded), ``final_shares``,
    ``master``, ``qualified``, ``rho``, plus ``phases_s``, ``events``,
    ``mesh_shape``/``n_devices``, and ``broadcasts`` (None unless
    ``seal`` was given).  Raises
    ``DkgError(MISBEHAVIOUR_HIGHER_THRESHOLD)`` past t disqualified
    dealers, like the tuple API.
    """
    from ..dkg.errors import DkgError, DkgErrorKind
    from ..utils import metrics as _metrics
    from ..utils import obslog

    reg = registry if registry is not None else _metrics.REGISTRY
    n_dev = _check_mesh(cfg, mesh)
    reg.inc("mesh_shards_total", n_dev)
    events: list[dict] = []
    phases: dict[str, float] = {}

    def _head(rd: int) -> float:
        now = time.time()
        events.append(
            {"kind": "round_head", "ceremony_id": ceremony_id, "round": rd, "ts": now}
        )
        obslog.emit_current("round_head", round=rd, ceremony_id=ceremony_id)
        return now

    def _publish_shards(rd: int, out) -> None:
        # host-observed per-shard readiness, blocked in mesh order: an
        # early shard's timestamp may include waiting on the scan, but
        # the last (the straggler critical_path names) is exact
        per = list(getattr(out, "addressable_shards", ()) or ())
        if len(per) == n_dev:
            per.sort(key=lambda sh: sh.index[0].start or 0)
            blocks = [sh.data for sh in per]
        else:  # replicated output, host array, or single-device run
            blocks = [out] * n_dev
        for i, blk in enumerate(blocks):
            jax.block_until_ready(blk)
            events.append(
                {
                    "kind": "publish",
                    "ceremony_id": ceremony_id,
                    "round": rd,
                    "party": i,
                    "ts": time.time(),
                }
            )
            obslog.emit_current(
                "publish", round=rd, party=i, ceremony_id=ceremony_id
            )

    def _tail(rd: int, op: str, t_open: float) -> None:
        now = time.time()
        events.append(
            {
                "kind": "round_tail",
                "ceremony_id": ceremony_id,
                "round": rd,
                "ts": now,
                "timed_out": False,
                "present": n_dev,
                "party": n_dev - 1,
            }
        )
        obslog.emit_current(
            "round_tail",
            round=rd,
            ceremony_id=ceremony_id,
            timed_out=False,
            present=n_dev,
        )
        phases[op] = phases.get(op, 0.0) + (now - t_open)
        reg.observe("mesh_collective_seconds", now - t_open, op=op)

    ca = place_sharded(mesh, coeffs_a)
    cb = place_sharded(mesh, coeffs_b)
    gt = place_sharded(mesh, g_table, P())
    ht = place_sharded(mesh, h_table, P())

    t0 = _head(0)
    a, e = sharded_deal_commitments(cfg, mesh, ca, cb, gt, ht)
    _publish_shards(0, e)
    _tail(0, "deal_commitments", t0)

    t0 = _head(1)
    s, r = sharded_deal_shares(cfg, mesh, ca, cb)
    _publish_shards(1, s)
    _tail(1, "deal_shares", t0)

    if tamper is not None:
        a, e, s, r = tamper(a, e, s, r)

    broadcasts = None
    if seal is not None:
        from ..dkg import hybrid_batch as hb

        group, pks_dev, r_enc = seal
        t0 = time.time()
        broadcasts = hb.seal_shares_mesh(
            group, cfg, mesh, s, r, pks_dev, r_enc, gt
        )
        phases["seal_transport"] = time.time() - t0
        reg.observe(
            "mesh_collective_seconds", phases["seal_transport"], op="seal_transport"
        )

    t0 = _head(2)
    digest = ce.sharded_transcript_digest(cfg, a, e, s, r)
    rho = jnp.asarray(ce.fiat_shamir_rho(cfg, digest, rho_bits))
    _publish_shards(2, rho)
    _tail(2, "transcript_digest", t0)

    # only the bare FIRST columns survive the digest (the master key's
    # sole input); dropping the full bare tensor returns its HBM before
    # the round-2 program runs (3.22 G at BLS n=16384)
    a0 = a[:, 0]
    del a

    t0 = _head(3)
    ok, finals, master = sharded_verify_finalise(
        cfg, mesh, a0, e, s, r, g_table=gt, h_table=ht, rho=rho, rho_bits=rho_bits
    )
    _publish_shards(3, finals)
    _tail(3, "verify_finalise", t0)

    qualified = jnp.ones((cfg.n,), bool)
    if not bool(_host_global(ok).all()):
        t0 = _head(4)
        pw = np.asarray(sharded_blame(cfg, mesh, e, s, r, gt, ht))
        guilty = ~pw.all(axis=1)
        if int(guilty.sum()) > cfg.t:
            _tail(4, "blame", t0)
            raise DkgError(
                DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD,
                detail="guilty dealers (1-based): "
                + ", ".join(str(j + 1) for j in np.nonzero(guilty)[0]),
            )
        qualified = jnp.asarray(~guilty)
        finals, master = sharded_finalise(cfg, mesh, a0, s, qualified)
        _publish_shards(4, finals)
        _tail(4, "blame", t0)

    return {
        "ok": ok,
        "final_shares": finals,
        "master": master,
        "qualified": qualified,
        "rho": rho,
        "broadcasts": broadcasts,
        "phases_s": phases,
        "events": events,
        "mesh_shape": tuple(mesh.devices.shape),
        "n_devices": n_dev,
    }


def _host_global(x: jax.Array) -> np.ndarray:
    """Global host value of a possibly mesh-sharded array; on multi-host
    meshes the shards are gathered across processes first (a direct
    np.asarray would fail: the array spans non-addressable devices)."""
    if jax.process_count() > 1:  # pragma: no cover — single-process CI
        from jax.experimental import multihost_utils as mhu

        return np.asarray(mhu.process_allgather(x, tiled=True))
    return np.asarray(x)


def _check_mesh(cfg: ce.CeremonyConfig, mesh: Mesh) -> int:
    n_dev = mesh.devices.size
    if cfg.n % n_dev != 0:
        raise ValueError("committee size must divide evenly over the mesh")
    return n_dev
