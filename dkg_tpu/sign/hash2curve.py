"""Hash-to-curve for message digests: H(m) as a group element.

Two bit-identical legs, same split as the transcript digest and the DEM
(host byte-plumbing, arrays for the wide work):

* :func:`hash_to_curve_host` — the per-message oracle, delegating to
  ``HostGroup.hash_to_group`` (try-and-increment with cofactor
  clearing; variable-time, but H(m) is public by definition).
* :func:`hash_to_curve_batch` — the batch leg: every candidate digest
  for a whole *block of counters x all pending messages* runs through
  ``crypto.blake2.blake2b_batch`` as ONE array call (the per-candidate
  cost that remains host-side is the quadratic-residue lift, which is a
  couple of big-int pows).  Candidates are consumed in the exact
  counter order of the host loop, so the selected points — and the
  device-canonical limb tensor built from them — are bit-identical to
  the oracle's.

The Weierstrass curves (secp256k1, BLS12-381 G1) take the batched
counter search; Ristretto's one-shot elligator map has no search to
batch and routes through the oracle per message.
"""

from __future__ import annotations

import numpy as np

import jax

from ..crypto.blake2 import blake2b_batch
from ..groups import device as gd
from ..groups import host as gh
from ..groups.host import _person

#: Domain tag for signing digests; distinct from the commitment-key
#: domain so H(m) can never collide with a ceremony generator.
SIGN_DOMAIN = b"dkg_tpu.sign.h2c"

#: Counters hashed per batched round; P(no quadratic residue in a
#: round) ~= 2**-8 per message, so one round nearly always suffices.
_CTR_BLOCK = 8


def hash_to_curve_host(group: gh.HostGroup, msg: bytes, domain: bytes = SIGN_DOMAIN):
    """Host big-int oracle: H(msg) as a host point tuple."""
    return group.hash_to_group(msg, domain)


def _batch_weierstrass(group, msgs, domain):
    """Counter-batched try-and-increment, bit-identical to the oracle."""
    nb = group.base_field.nbytes + 16
    person = _person(domain)
    found: list = [None] * len(msgs)
    # equal-length rows per blake2b_batch call: bucket by message length
    by_len: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        by_len.setdefault(len(m), []).append(i)
    for mlen, idxs in by_len.items():
        pending = list(idxs)
        ctr0 = 0
        while pending:
            rows = np.zeros((len(pending) * _CTR_BLOCK, mlen + 4), np.uint8)
            for r, i in enumerate(pending):
                body = np.frombuffer(msgs[i], dtype=np.uint8)
                for k in range(_CTR_BLOCK):
                    rows[r * _CTR_BLOCK + k, :mlen] = body
                    rows[r * _CTR_BLOCK + k, mlen:] = np.frombuffer(
                        (ctr0 + k).to_bytes(4, "little"), dtype=np.uint8
                    )
            digests = blake2b_batch(rows, digest_size=nb, person=person)
            still = []
            for r, i in enumerate(pending):
                for k in range(_CTR_BLOCK):
                    h = digests[r * _CTR_BLOCK + k].tobytes()
                    x = int.from_bytes(h, "little") % group.prime
                    y = group._lift_x(x, 0)
                    if y is None:
                        continue
                    pt = group._mul_int(group.cofactor, (x, y, 1))
                    if group.eq(pt, group.identity()):
                        continue
                    found[i] = pt
                    break
                else:
                    still.append(i)
            pending = still
            ctr0 += _CTR_BLOCK
    return found


def hash_to_curve_batch(
    curve: str, msgs: list[bytes], domain: bytes = SIGN_DOMAIN
) -> tuple[list, jax.Array]:
    """H(m) for a whole message batch: (host point tuples, device
    ``(B, C, L)`` canonical affine limbs), bit-identical to calling
    :func:`hash_to_curve_host` per message."""
    cs = gd.ALL_CURVES[curve]
    group = gh.ALL_GROUPS[curve]
    if isinstance(group, gh.WeierstrassGroup):
        pts = _batch_weierstrass(group, msgs, domain)
    else:
        pts = [group.hash_to_group(m, domain) for m in msgs]
    # canonical affine limbs (bit-identical to the device affine pass)
    dev = gd.affine_canon_host(cs, np.asarray(gd.from_host(cs, pts)))
    return pts, jax.numpy.asarray(dev)
