"""Lagrange aggregation of partial signatures at zero.

A threshold signature over shares s_i on nodes x_i is

    sig(m) = sum_i lambda_i(0) * sig_i(m),   sig_i(m) = s_i * H(m),

because interpolation at zero recovers the master secret in the
exponent: sum_i lambda_i(0) * s_i = f(0).  The coefficients come from
the batched device inversion (``poly.device.lagrange_at_zero_coeffs``,
one Fermat batch-inverse for the whole subset) and the point sum runs
as ONE Pippenger MSM with the message batch as a leading axis — B
messages x (t+1) partials in a single bucket pass, the same kernel the
ceremony's RLC verification uses.

``aggregate_host`` is the big-int oracle (host Lagrange coefficients +
host MSM) the device leg is pinned against; ``signature_encode``
produces the canonical wire bytes via ``groups.device.encode_batch``
(bit-identical to ``HostGroup.encode`` row by row).

Invariance across epochs: refresh/reshare (``dkg_tpu/epoch/``) changes
the share vector but not f(0), so aggregates from any qualified subset
of any epoch encode to the same signature bytes (tests/test_sign.py).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..poly import device as pd
from ..poly import host as ph
from .partial import PartialSignatures


def aggregate(
    ps: PartialSignatures,
    subset: list[int] | None = None,
    lam: np.ndarray | None = None,
) -> np.ndarray:
    """Aggregate a t+1 subset of partials into full signatures.

    ``subset``: positions into ``ps.indices`` (default: all signers the
    batch carries).  ``lam``: precomputed canonical ``(M, L)``
    Lagrange-at-zero limbs for the subset's x's (the sign lane caches
    them per (curve, quorum) — ``sign.cache.SignCache.lagrange_at_zero``
    is limb-identical to the device derivation, parity pinned in
    tests/test_sign.py); default derives them on device.  Returns
    ``(B, C, L)`` canonical affine limbs — the same currency as the
    partials, ready for :func:`signature_encode`.
    """
    cs = gd.ALL_CURVES[ps.curve]
    pos = list(range(len(ps.indices))) if subset is None else list(subset)
    sigs = jnp.asarray(ps.sigs[:, pos])  # (B, M, C, L)
    if lam is None:
        xs = [ps.indices[p] for p in pos]
        xs_limbs = jnp.asarray(fh.encode(cs.scalar, xs))  # (M, L)
        lam_arr = pd.lagrange_at_zero_coeffs(cs.scalar, xs_limbs)  # (M, L)
    else:
        lam_arr = jnp.asarray(lam)
    agg = gd.msm_pippenger(cs, lam_arr, sigs)  # (B, C, L)
    return gd.affine_canon_host(cs, np.asarray(agg))


def aggregate_host(
    group: gh.HostGroup, indices: list[int], sig_rows: list[list]
) -> list:
    """Big-int oracle: per-message Lagrange-weighted host MSM over the
    subset's partials.  ``sig_rows``: [message][signer] host tuples in
    ``indices`` order.  Compare to the device leg via ``group.encode``.
    """
    fs = group.scalar_field
    xs = [i % fs.modulus for i in indices]
    lams = [ph.lagrange_coefficient(fs, 0, i, xs) for i in range(len(xs))]
    return [group.msm(lams, row) for row in sig_rows]


def signature_encode(curve: str, sigs: np.ndarray) -> list[bytes]:
    """Canonical signature wire bytes for a ``(B, C, L)`` aggregate
    batch, bit-identical to ``HostGroup.encode`` per row."""
    enc = gd.encode_batch(gd.ALL_CURVES[curve], np.asarray(sigs))
    return [row.tobytes() for row in np.asarray(enc)]
