"""Threshold signing over the DKG'd key: the workload the keys are FOR.

``dkg_tpu/sign/`` turns the repo from "key generation" into a full
threshold-signature service, pairing-free by construction:

* :mod:`.hash2curve` — message -> curve point H(m): host big-int
  try-and-increment oracle plus a batch leg that pushes every candidate
  digest through the array BLAKE2b (``crypto.blake2.blake2b_batch``).
* :mod:`.partial` — batched partial signatures sig_i = s_i * H(m) for
  all signers x all messages in ONE device scalar-mul, with per-signer
  DLEQ proofs (log_g(pk_i) == log_{H(m)}(sig_i)) generated and verified
  through ``crypto.dleq_batch`` — partial verification needs no
  pairings.
* :mod:`.aggregate` — Lagrange aggregation at zero over any t+1 subset,
  one batched Pippenger MSM across all messages, cross-checked against
  a host big-int oracle.
* :mod:`.verify` — RLC-combined grid verification with bisecting blame:
  accept an all-honest grid in ONE combined check, locate Byzantine
  (message, signer) cells in O(log) further checks — the primitive
  behind the scheduler's signer quarantine.  ``rlc_verify_convoy``
  extends the same soundness argument across a whole convoy of proved
  grids: steady proved traffic pays one hash screen plus ONE RLC-MSM
  total, with screen-failing grids excluded up front and an
  undifferentiated combined failure routing every surviving grid back
  through the per-grid bisection path.
* :mod:`.cache` — the steady-state lane's warm-path caches: decoded
  share vectors per (ceremony, epoch), Lagrange-at-zero coefficients
  per (curve, quorum), per-quorum public keys, and the folded signing
  scalar behind :func:`partial.sign_folded`'s one-ladder fast path.

Service integration is ``service.scheduler.CeremonyScheduler.sign``
(synchronous submit+wait over the scheduler's convoy-batched sign
lane — see docs/signing.md "Steady-state lane").
Knobs (utils.envknobs, explicit arguments win): ``DKG_TPU_SIGN_BATCH``
(device message-chunk size), ``DKG_TPU_SIGN_DISPATCH`` (device|host),
``DKG_TPU_SIGN_RLC_DISPATCH`` (host|device RLC combine leg).
"""

from .aggregate import aggregate, aggregate_host, signature_encode
from .cache import CeremonyMaterial, SignCache
from .hash2curve import hash_to_curve_batch, hash_to_curve_host
from .partial import (
    PartialSignatures,
    folded_collect,
    partial_sign,
    partial_sign_host,
    public_keys,
    sign_folded,
    verify_partials,
)
from .verify import ConvoyReport, RlcReport, rlc_verify, rlc_verify_convoy

__all__ = [
    "CeremonyMaterial",
    "ConvoyReport",
    "PartialSignatures",
    "RlcReport",
    "SignCache",
    "aggregate",
    "aggregate_host",
    "folded_collect",
    "hash_to_curve_batch",
    "hash_to_curve_host",
    "partial_sign",
    "partial_sign_host",
    "public_keys",
    "rlc_verify",
    "rlc_verify_convoy",
    "sign_folded",
    "signature_encode",
    "verify_partials",
]
