"""RLC-combined partial-signature verification with bisecting blame.

``verify_partials`` (sign.partial) answers "is every cell good?" by
recomputing announcements for the whole grid — one batched MSM sized
2·(B·m).  This module answers the *serving* question: accept the whole
grid with ONE random-linear-combination check, and when it fails, find
the exact bad (message, signer) cells in O(log) further checks instead
of aborting the signing call (ROADMAP item: RLC batch verification
with bisect-on-failure as the blame primitive).

The check: each DLEQ cell i claims, with announcements (A1_i, A2_i)
carried from proving time (``PartialSignatures.announcements``),

    z_i·g    - e_i·pk_i  - A1_i == 0
    z_i·H_i  - e_i·sig_i - A2_i == 0 .

Drawing fresh random weights (u_i, v_i) per check, the combined sum

    (Σ u_i·z_i)·g + Σ [ -u_i·e_i·pk_i - u_i·A1_i
                        + v_i·z_i·H_i - v_i·e_i·sig_i - v_i·A2_i ]

is the identity iff every cell holds, except with probability ~k/q for
adversarially chosen bad cells (Schwartz–Zippel over the weights —
weights MUST be unpredictable to the prover, hence drawn after the
partials arrive).  The g terms collapse to one scalar, so a k-cell
check is one (5k+1)-point MSM.

Two stages before any MSM:

1. *hash screen* — recompute each cell's Fiat-Shamir challenge from the
   carried announcements.  e binds (g, H, pk, sig, A1, A2), so a
   tampered signature / public key / announcement fails HERE at pure
   host-hash cost and is blamed without a single group operation.  Only
   a tampered *response* z survives the screen (z is not hashed), which
   is exactly what the group check catches.
2. *RLC accept-all* — one combined check over the screen's survivors;
   the overwhelmingly common all-honest grid pays exactly one pass.

On failure, blame runs a per-bad-cell binary search: bisect into the
failing half (checking only the left half — if it passes, the bad cell
is on the right), remove the found cell, re-run accept-all, repeat.
Each bad cell costs ≤ ceil(log2(k)) + 1 extra passes (the search plus
the failing accept-all that triggered it), the bound the service storm
gates (scripts/service_storm.py, perf_regress.py).

Dispatch: ``host`` (default) folds the MSM with big-int arithmetic —
sign grids are (t+1)·B cells, tiny, and a host fold never compiles, so
the serving path stays off the jit cache.  ``device`` runs the padded
MSM kernel (``DKG_TPU_SIGN_RLC_DISPATCH``, validated via
utils.envknobs; tested behind the ``slow`` tier).
"""

from __future__ import annotations

import dataclasses
import random

import numpy as np

import jax.numpy as jnp

from ..crypto.dleq import _challenge
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..utils import envknobs
from .partial import PartialSignatures


def _rlc_dispatch(dispatch: str | None) -> str:
    """host|device: explicit argument wins, then the validated
    DKG_TPU_SIGN_RLC_DISPATCH knob, then host (no-compile default)."""
    if dispatch is not None:
        if dispatch not in ("host", "device"):
            raise ValueError(f"rlc dispatch must be host|device, got {dispatch!r}")
        return dispatch
    return (
        envknobs.choice(
            "DKG_TPU_SIGN_RLC_DISPATCH", ("host", "device"), "RLC combine leg"
        )
        or "host"
    )


@dataclasses.dataclass(frozen=True)
class RlcReport:
    """One rlc_verify outcome.

    ``bad_cells``: (message, signer) grid positions (positions into
    ``ps.h_points`` x ``ps.indices``) that failed, sorted row-major.
    ``passes``: group-level RLC checks performed (1 for an all-honest
    grid); hash-screen failures cost no passes.  ``grid``: total cells.
    """

    ok: bool
    bad_cells: tuple[tuple[int, int], ...]
    passes: int
    grid: int

    def pass_bound(self) -> int:
        """The gated ceiling: 1 accept-all pass plus
        ceil(log2(grid)) + 1 extra per bad group-detected cell."""
        logk = max(1, self.grid - 1).bit_length()
        return 1 + len(self.bad_cells) * (logk + 1)


def _cell_rows(ps: PartialSignatures) -> list[tuple]:
    """Per-cell verification data, row-major over (B, m):
    (e, z, h, pk, sig, a1, a2) with host point tuples."""
    group = gh.ALL_GROUPS[ps.curve]
    g = group.generator()
    b, m = ps.sigs.shape[:2]
    sigs_host = ps.sigs_host()
    rows = []
    for bi in range(b):
        for si in range(m):
            p = ps.proofs[bi * m + si]
            a1, a2 = ps.announcements[bi * m + si]
            rows.append(
                (p.challenge, p.response, ps.h_points[bi],
                 ps.pks[si], sigs_host[bi][si], a1, a2, g)
            )
    return rows


def _combine(group: gh.HostGroup, rows: list[tuple], rng) -> tuple[list, list]:
    """The RLC combine's (scalars, points), g terms collapsed."""
    q = group.scalar_field.modulus
    g = rows[0][7]
    g_acc = 0
    scalars: list[int] = []
    points: list = []
    for e, z, h, pk, sig, a1, a2, _ in rows:
        u = rng.randrange(1, q)
        v = rng.randrange(1, q)
        g_acc = (g_acc + u * z) % q
        scalars.extend(
            [(q - u * e % q) % q, q - u, v * z % q, (q - v * e % q) % q, q - v]
        )
        points.extend([pk, a1, h, sig, a2])
    scalars.append(g_acc)
    points.append(g)
    return scalars, points


def _rlc_check(
    group: gh.HostGroup, cs, rows: list[tuple], rng, dispatch: str
) -> bool:
    """One combined check over ``rows``; True iff the sum is identity."""
    scalars, points = _combine(group, rows, rng)
    if dispatch == "host":
        return group.is_identity(group.msm(scalars, points))
    pts = gd.from_host(cs, points)  # (5k+1, C, L)
    sc = jnp.asarray(fh.encode(cs.scalar, scalars))  # (5k+1, L)
    acc = gd.msm(cs, sc, pts)
    (host_pt,) = gd.to_host(cs, np.asarray(acc)[None])
    return group.is_identity(host_pt)


@dataclasses.dataclass(frozen=True)
class ConvoyReport:
    """One :func:`rlc_verify_convoy` outcome.

    ``grid_ok[i]`` is True iff grid i's every cell survived the hash
    screen AND the single combined RLC pass over all screen-surviving
    grids held.  When that combined pass fails, EVERY screen-surviving
    grid reports False — the check is an acceptance gate, not a blame
    primitive; callers route implicated grids through per-grid
    :func:`rlc_verify`, which owns bisection.  ``passes``: combined
    group-level checks performed (1 when any grid survived the screen,
    0 otherwise).  ``cells``: total cells across the convoy.
    """

    ok: bool
    grid_ok: tuple[bool, ...]
    passes: int
    cells: int


def rlc_verify_convoy(
    batch: list[PartialSignatures],
    *,
    rng=None,
    dispatch: str | None = None,
) -> ConvoyReport:
    """Accept a whole convoy of proved grids with ONE hash screen and
    ONE RLC-MSM.

    The per-grid path pays one (5k+1)-point MSM per *request*; steady
    proved traffic coalesced into a convoy shares the same soundness
    argument over the concatenated cell list (fresh per-cell weights
    make the combined sum identity iff every cell of every grid holds,
    Schwartz–Zippel as above), so the convoy pays one MSM total.  Grids
    with a screen-failing cell are excluded from the combined check and
    reported bad immediately — a tampered signature never costs the
    honest grids their single pass.

    ``rng`` draws the weights (default SystemRandom — weights must be
    unpredictable to the signers).  All grids must share one curve.
    """
    if not batch:
        return ConvoyReport(ok=True, grid_ok=(), passes=0, cells=0)
    curves = {ps.curve for ps in batch}
    if len(curves) > 1:
        raise ValueError(f"convoy spans curves {sorted(curves)}; expected one")
    for ps in batch:
        if ps.proofs is None or ps.announcements is None:
            raise ValueError(
                "rlc_verify_convoy needs proofs and announcements "
                "(partial_sign(..., prove=True))"
            )
    group = gh.ALL_GROUPS[batch[0].curve]
    cs = gd.ALL_CURVES[batch[0].curve]
    mode = _rlc_dispatch(dispatch)
    if rng is None:
        rng = random.SystemRandom()
    grid_ok = [True] * len(batch)
    survivors: list[tuple] = []
    cells = 0
    for gi, ps in enumerate(batch):
        rows = _cell_rows(ps)
        cells += len(rows)
        clean = True
        for e, _z, h, pk, sig, a1, a2, g in rows:
            if e != _challenge(group, g, h, pk, sig, a1, a2):
                clean = False
                break
        if clean:
            survivors.extend(rows)
        else:
            grid_ok[gi] = False
    passes = 0
    if survivors:
        passes = 1
        if not _rlc_check(group, cs, survivors, rng, mode):
            # undifferentiated failure: every surviving grid goes back
            # to the per-grid path, which bisects to the bad cells
            grid_ok = [False] * len(batch)
    return ConvoyReport(
        ok=all(grid_ok), grid_ok=tuple(grid_ok), passes=passes, cells=cells
    )


def rlc_verify(
    ps: PartialSignatures,
    *,
    rng=None,
    dispatch: str | None = None,
) -> RlcReport:
    """Accept-all-or-blame verification of a proved partial grid.

    ``rng`` draws the RLC weights (default SystemRandom — they must be
    unpredictable to the signers; seed only in tests/benchmarks).
    Requires proofs AND announcements (``partial_sign(prove=True)``).
    """
    if ps.proofs is None or ps.announcements is None:
        raise ValueError(
            "rlc_verify needs proofs and announcements "
            "(partial_sign(..., prove=True))"
        )
    group = gh.ALL_GROUPS[ps.curve]
    cs = gd.ALL_CURVES[ps.curve]
    mode = _rlc_dispatch(dispatch)
    if rng is None:
        rng = random.SystemRandom()
    b, m = ps.sigs.shape[:2]
    rows = _cell_rows(ps)
    cells = [(bi, si) for bi in range(b) for si in range(m)]
    # stage 1: hash screen — e binds everything except z
    live: list[int] = []
    bad: list[tuple[int, int]] = []
    for i, (e, _z, h, pk, sig, a1, a2, g) in enumerate(rows):
        if e == _challenge(group, g, h, pk, sig, a1, a2):
            live.append(i)
        else:
            bad.append(cells[i])
    # stage 2/3: accept-all, binary-search one bad cell per failure
    passes = 0
    while live:
        passes += 1
        if _rlc_check(group, cs, [rows[i] for i in live], rng, mode):
            break
        lo, hi = 0, len(live)  # live[lo:hi] contains >= 1 bad cell
        while hi - lo > 1:
            mid = (lo + hi) // 2
            passes += 1
            if _rlc_check(
                group, cs, [rows[i] for i in live[lo:mid]], rng, mode
            ):
                lo = mid  # left half clean -> culprit on the right
            else:
                hi = mid
        bad.append(cells[live[lo]])
        del live[lo]
    return RlcReport(
        ok=not bad,
        bad_cells=tuple(sorted(bad)),
        passes=passes,
        grid=b * m,
    )
