"""Warm-path signing caches: every quorum-stable derivation, done once.

SIGN_r01 showed the steady-state lane dominated not by curve math but
by re-derivation: every ``scheduler.sign()`` call decoded the whole
share vector (under the scheduler lock!), rebuilt the quorum's public
keys through the fixed-base tables, and recomputed the Lagrange-at-zero
coefficients on device (~seconds of warm wall per call at n=64).  None
of that depends on the *message* being signed — it depends only on the
ceremony's share epoch and the quorum's x-coordinates — so a serving
lane can derive it once and sign thousands of messages against it.

This module is the ONE sanctioned owner of that material (lint rule
DKG013 bans ``lagrange_*``/``public_keys`` calls in
``dkg_tpu/service/``): the scheduler's sign lane asks :class:`SignCache`
and never re-derives per request.

Three caches, three invalidation rules:

* **ceremony material** — the decoded share vector, keyed
  ``(ceremony_id, epoch)``.  The epoch CAS token the scheduler already
  bumps on refresh/reshare IS the invalidation: a bump changes the key,
  and inserting a new epoch proactively drops the ceremony's stale
  entries.  Decoding happens here, OUTSIDE the scheduler's condition
  lock — a slow sign can no longer stall admission or epoch ops.
* **Lagrange-at-zero coefficients** — keyed ``(curve, quorum x's)``.
  Host big-int (a t+1-point interpolation is microseconds on host;
  the batched device inversion is for ceremony-scale vectors), encoded
  to the same canonical limbs ``poly.device.lagrange_at_zero_coeffs``
  produces (parity pinned in tests/test_sign.py).
* **the folded signing scalar** — sigma = sum_i lambda_i(0) * s_i
  (mod q), keyed ``(ceremony_id, epoch)``.  By interpolation at zero
  this equals f(0) for EVERY honest quorum, so the fast lane signs a
  message with ONE ladder lane (``sign.partial.sign_folded``) instead
  of a (t+1)-wide grid plus an MSM — the work reduction behind the
  steady-state signatures/s floor (docs/signing.md).

Per-quorum public keys (for the proved grid path's DLEQ transcripts)
are cached inside each ceremony entry, keyed by the quorum tuple, at
the quorum shape the solo path always used — no new compile shapes.

Thread-safety: one lock around the maps; the heavy derivations run
outside it only when they touch the device (pk tables), so a lane
worker never blocks the scheduler and vice versa.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..fields import host as fh
from ..groups import host as gh
from ..poly import host as ph


def sigma_limb_count(curve: str) -> int:
    """Limb count of one folded-sigma row — the trailing dimension of
    the ``(B, L)`` rows :meth:`SignCache.fold_limbs` feeds the steady
    lane's ladder.  The AOT prebake (``scripts/aot_build.py``)
    synthesizes rung-shaped dummy rows from it so a fresh worker's
    sign-rung executables are already on disk."""
    return gh.ALL_GROUPS[curve].scalar_field.limbs


class CeremonyMaterial:
    """Everything quorum-stable about one (ceremony, epoch): the decoded
    share vector plus lazily-built per-quorum public keys and the folded
    signing scalar."""

    __slots__ = ("cid", "epoch", "curve", "shares", "_pks", "_fold", "_lock")

    def __init__(self, cid: str, epoch: int, curve: str, shares: tuple[int, ...]):
        self.cid = cid
        self.epoch = epoch
        self.curve = curve
        self.shares = shares  # full n-vector, index i holds share at x=i+1
        self._pks: OrderedDict[tuple[int, ...], tuple[np.ndarray, list]] = (
            OrderedDict()
        )
        self._fold: np.ndarray | None = None  # (L,) canonical sigma limbs
        self._lock = threading.Lock()


class SignCache:
    """LRU caches for the scheduler's sign lane (module docstring)."""

    def __init__(
        self,
        capacity: int = 32,
        lagrange_capacity: int = 256,
        pk_capacity: int = 64,
    ) -> None:
        self.capacity = capacity
        self.lagrange_capacity = lagrange_capacity
        self.pk_capacity = pk_capacity
        self._lock = threading.Lock()
        self._ceremonies: OrderedDict[tuple[str, int], CeremonyMaterial] = (
            OrderedDict()
        )
        # (curve, xs) -> (lambda ints tuple, (M, L) canonical limbs)
        self._lagrange: OrderedDict[tuple, tuple[tuple[int, ...], np.ndarray]] = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    # -- ceremony material ---------------------------------------------------

    def ceremony(
        self, cid: str, epoch: int, curve: str, final_shares
    ) -> CeremonyMaterial:
        """The decoded material for ``(cid, epoch)``.  ``final_shares``
        is the encoded limb array snapshotted from the held outcome
        (refresh replaces, never mutates, that array — holding the
        reference across the lock boundary is safe).  An epoch bump
        changes the key; inserting the new epoch drops the ceremony's
        stale entries."""
        key = (cid, epoch)
        with self._lock:
            mat = self._ceremonies.get(key)
            if mat is not None:
                self._ceremonies.move_to_end(key)
                self.hits += 1
                return mat
            self.misses += 1
        # decode OUTSIDE both this cache's lock and (crucially) the
        # scheduler's condition lock — the satellite bugfix: rebuilding
        # n Python ints per sign call under self._cond stalled
        # admission and epoch ops for the whole decode
        fs = gh.ALL_GROUPS[curve].scalar_field
        shares = tuple(int(v) for v in fh.decode(fs, final_shares))
        mat = CeremonyMaterial(cid, epoch, curve, shares)
        with self._lock:
            won = self._ceremonies.setdefault(key, mat)
            if won is mat:
                for k in [
                    k for k in self._ceremonies if k[0] == cid and k != key
                ]:
                    del self._ceremonies[k]  # stale epochs of this ceremony
                while len(self._ceremonies) > self.capacity:
                    self._ceremonies.popitem(last=False)
            return won

    # -- Lagrange-at-zero ----------------------------------------------------

    def lagrange_at_zero(
        self, curve: str, xs: tuple[int, ...]
    ) -> tuple[tuple[int, ...], np.ndarray]:
        """(lambda ints, canonical (M, L) limbs) for interpolation at
        zero over nodes ``xs`` — host big-int, cached per (curve, xs),
        limb-identical to the device leg (parity test in test_sign)."""
        key = (curve, xs)
        with self._lock:
            hit = self._lagrange.get(key)
            if hit is not None:
                self._lagrange.move_to_end(key)
                self.hits += 1
                return hit
            self.misses += 1
        fs = gh.ALL_GROUPS[curve].scalar_field
        nodes = [x % fs.modulus for x in xs]
        lams = tuple(
            ph.lagrange_coefficient(fs, 0, i, nodes) for i in range(len(nodes))
        )
        limbs = np.asarray(fh.encode(fs, list(lams)))
        entry = (lams, limbs)
        with self._lock:
            self._lagrange[key] = entry
            while len(self._lagrange) > self.lagrange_capacity:
                self._lagrange.popitem(last=False)
        return entry

    # -- the folded signing scalar -------------------------------------------

    def fold_limbs(self, mat: CeremonyMaterial, quorum: list[int]) -> np.ndarray:
        """Canonical limbs of sigma = sum lambda_i(0) * s_i over
        ``quorum`` (1-based indices into the ceremony's share vector).
        Cached once per (ceremony, epoch): by Lagrange-at-zero algebra
        sigma == f(0) for every honest quorum, so the first quorum's
        fold serves all later ones bit-identically."""
        with mat._lock:
            if mat._fold is not None:
                return mat._fold
        fs = gh.ALL_GROUPS[mat.curve].scalar_field
        lams, _ = self.lagrange_at_zero(mat.curve, tuple(quorum))
        sigma = 0
        for lam, x in zip(lams, quorum):
            sigma = (sigma + lam * mat.shares[x - 1]) % fs.modulus
        limbs = np.asarray(fh.encode(fs, [sigma]))[0]
        with mat._lock:
            if mat._fold is None:
                mat._fold = limbs
            return mat._fold

    # -- per-quorum public keys ----------------------------------------------

    def quorum_pks(
        self, mat: CeremonyMaterial, quorum: list[int]
    ) -> tuple[np.ndarray, list]:
        """``(canonical (m, C, L) limbs, host tuples)`` of the quorum's
        public keys, through the persistent fixed-base tables — built at
        the quorum shape the solo path always compiled (no new shapes),
        then cached per quorum tuple inside the ceremony entry."""
        from .partial import public_keys

        key = tuple(quorum)
        with mat._lock:
            hit = mat._pks.get(key)
            if hit is not None:
                mat._pks.move_to_end(key)
                return hit
        pks = public_keys(mat.curve, [mat.shares[x - 1] for x in quorum])
        with mat._lock:
            mat._pks[key] = pks
            while len(mat._pks) > self.pk_capacity:
                mat._pks.popitem(last=False)
        return pks
