"""Batched partial signatures: every signer x every message at once.

A partial signature is ``sig_i = s_i * H(m)`` — per (message, signer)
pair one scalar multiplication.  The reference shape would be a double
loop; here the whole ``(B messages, m signers)`` grid is ONE batched
device ladder call (scalars broadcast along the message axis, bases
along the signer axis), chunked over messages only to bound live
memory (``DKG_TPU_SIGN_BATCH``).  Public keys ``pk_i = s_i * g`` ride
the persistent fixed-base comb tables (``groups.precompute``).

Partial verification is pairing-free: each signer proves
``log_g(pk_i) == log_{H(m)}(sig_i)`` with a DLEQ proof, and a verifier
checks the whole grid in ONE ``crypto.dleq_batch.verify_batch`` pass
(one batched m=2 MSM + host Fiat-Shamir digests).

``partial_sign_host`` is the per-share big-int oracle the device leg is
pinned against (tests/test_sign.py); it is the allowlisted exception to
lint rule DKG009 (no per-message scalar_mul loops in sign/ hot paths).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

from ..crypto import dleq_batch
from ..crypto.dleq import DleqZkp
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..groups import precompute
from ..utils import envknobs


def _sign_chunk(chunk: int | None) -> int:
    """Device message-chunk size: explicit argument wins, then the
    validated DKG_TPU_SIGN_BATCH knob, then 256 (a (256, t+1) lane grid
    keeps the 381-bit ladder's live set comfortably in memory)."""
    if chunk is not None:
        if chunk < 1:
            raise ValueError(f"sign chunk must be positive, got {chunk}")
        return chunk
    return envknobs.pos_int("DKG_TPU_SIGN_BATCH", "sign message-chunk size") or 256


def _sign_dispatch(dispatch: str | None) -> str:
    """device|host: explicit argument wins, then DKG_TPU_SIGN_DISPATCH."""
    if dispatch is not None:
        if dispatch not in ("device", "host"):
            raise ValueError(f"sign dispatch must be device|host, got {dispatch!r}")
        return dispatch
    return (
        envknobs.choice(
            "DKG_TPU_SIGN_DISPATCH", ("device", "host"), "partial-sign leg"
        )
        or "device"
    )


@dataclasses.dataclass
class PartialSignatures:
    """One batch of partial signatures over a signer subset.

    ``sigs`` holds canonical affine limbs (``(B, m, C, L)`` uint32) so
    downstream aggregation/encoding never re-canonicalises; host point
    tuples for the DLEQ transcripts are derived lazily.
    """

    curve: str
    indices: tuple[int, ...]  # 1-based signer indices, len m
    h_points: list  # host H(m) tuples, len B
    sigs: np.ndarray  # (B, m, C, L) canonical affine limbs
    pks: list  # host pk_i tuples, len m
    proofs: list[DleqZkp] | None = None  # row-major over (B, m)
    # (a1, a2) host announcement pairs matching ``proofs`` row-major;
    # carried so sign.verify.rlc_verify can group-check z against them
    # instead of recomputing announcements per cell
    announcements: list[tuple] | None = None

    def sigs_host(self) -> list[list[tuple]]:
        """Host point tuples, [message][signer] — memoized: the prove
        leg and rlc_verify both need the same conversion, and on the
        steady-state lane paying the limb->int walk twice per convoy is
        pure waste.  ``dataclasses.replace`` (how tampers fork a batch)
        drops the memo with the instance, so forged copies re-derive."""
        memo = getattr(self, "_host_rows", None)
        if memo is not None:
            return memo
        b, m = self.sigs.shape[:2]
        flat = gd.to_host(
            gd.ALL_CURVES[self.curve], self.sigs.reshape(b * m, *self.sigs.shape[2:])
        )
        rows = [flat[i * m : (i + 1) * m] for i in range(b)]
        self._host_rows = rows
        return rows


def public_keys(curve: str, shares: list[int]) -> tuple[np.ndarray, list]:
    """pk_i = s_i * g for every share, through the persistent comb
    tables: (canonical affine limbs (m, C, L), host tuples)."""
    cs = gd.ALL_CURVES[curve]
    table = precompute.generator_table(cs)
    k = jnp.asarray(fh.encode(cs.scalar, shares))
    pts = gd.fixed_base_mul(cs, table, k)
    canon = gd.affine_canon_host(cs, np.asarray(pts))
    return canon, gd.to_host(cs, canon)


def sign_folded(curve: str, sigma_limbs: np.ndarray, h_dev):
    """Steady-state fast path: sign a message batch with the folded
    quorum scalar in ONE ladder dispatch.

    ``sigma_limbs``: canonical limbs of
    sigma = sum_i lambda_i(0) * s_i (``sign.cache.SignCache.fold_limbs``)
    — ``(L,)`` for one shared scalar, or ``(B, L)`` per-message rows (a
    cross-ceremony convoy folds a different sigma per ticket).  By
    interpolation at zero sigma is f(0), so ``sigma * H(m)`` IS the
    aggregate signature, bit-identical to the partial-grid + MSM path
    (pinned in tests/test_sign.py and asserted per steady-state bench
    run).  ``h_dev``: ``(B, C, L)`` H(m) limbs (device or host array).

    Returns the RAW device result — callers (the scheduler's sign lane)
    keep rungs in flight and block/canonicalise per rung, overlapping
    hashing of the next rung with the ladder of this one.  Unproved
    shapes only: the grid path still serves ``prove=True`` traffic,
    whose DLEQ transcripts need per-signer partials.
    """
    cs = gd.ALL_CURVES[curve]
    hh = jnp.asarray(h_dev)
    kk = jnp.asarray(sigma_limbs)
    if kk.ndim == 1:
        kk = jnp.broadcast_to(kk[None, :], (hh.shape[0], kk.shape[-1]))
    # noqa-rationale: one call signs the whole (B,) batch — no loop.
    return gd.scalar_mul(cs, kk, hh)  # noqa: DKG009


def folded_collect(curve: str, pending: list) -> np.ndarray:
    """Block on a list of in-flight :func:`sign_folded` dispatches and
    canonicalise the lot: ``(sum of B's, C, L)`` affine limbs, ready for
    ``aggregate.signature_encode``.  Split from :func:`sign_folded` so
    the lane can keep every rung's ladder in flight before the first
    host conversion blocks."""
    cs = gd.ALL_CURVES[curve]
    parts = [np.asarray(out) for out in pending]
    return gd.affine_canon_host(cs, np.concatenate(parts, axis=0))


def partial_sign_host(group: gh.HostGroup, shares: list[int], h_point) -> list[tuple]:
    """Per-share big-int oracle: [s_i * H(m)] as host point tuples
    (projective; compare via ``group.encode``).  The bit-exactness
    reference for the batched device leg (and the DKG009 allowlisted
    host path)."""
    return [group.scalar_mul_vartime(s, h_point) for s in shares]


def partial_sign(
    curve: str,
    shares: list[int],
    indices: list[int],
    h_points: list,
    *,
    rng=None,
    prove: bool = False,
    dispatch: str | None = None,
    chunk: int | None = None,
    pks: tuple[np.ndarray, list] | None = None,
) -> PartialSignatures:
    """Sign every message with every share: ``(B, m)`` partials.

    ``h_points``: host H(m) tuples (from hash2curve).  ``prove=True``
    attaches per-(message, signer) DLEQ proofs (requires ``rng``).  The
    device leg runs the whole grid as one broadcast ladder per message
    chunk; the host leg is the oracle loop (cross-checks, tiny batches).
    ``pks``: the ``(canon, host)`` pair :func:`public_keys` would
    return, when the caller already holds it (``sign.cache.SignCache``
    keeps them per quorum) — must match ``shares`` exactly.
    """
    if len(shares) != len(indices):
        raise ValueError("shares and indices must pair up")
    if prove and rng is None:
        raise ValueError("prove=True requires rng")
    cs = gd.ALL_CURVES[curve]
    group = gh.ALL_GROUPS[curve]
    mode = _sign_dispatch(dispatch)
    b, m = len(h_points), len(shares)
    if mode == "host":
        rows = [partial_sign_host(group, shares, h) for h in h_points]
        flat = gd.from_host(cs, [p for row in rows for p in row])
        sigs = gd.affine_canon_host(
            cs, np.asarray(flat).reshape(b, m, cs.ncoords, cs.field.limbs)
        )
    else:
        k = jnp.asarray(fh.encode(cs.scalar, shares))  # (m, L)
        h_dev = gd.from_host(cs, h_points)  # (B, C, L)
        csize = _sign_chunk(chunk)
        pending = []
        for b0 in range(0, b, csize):
            blk = h_dev[b0 : b0 + csize]
            bc = blk.shape[0]
            # (B', m) lanes in ONE ladder: scalars broadcast over
            # messages, bases over signers — no per-message loop.
            kk = jnp.broadcast_to(k[None, :, :], (bc, m, k.shape[-1]))
            pp = jnp.broadcast_to(blk[:, None, :, :], (bc, m) + blk.shape[-2:])
            # noqa-rationale: each call covers a whole (B', m) grid —
            # the loop is DKG_TPU_SIGN_BATCH memory chunking over
            # messages, not a per-message mult.
            pending.append(gd.scalar_mul(cs, kk, pp))  # noqa: DKG009
        # dispatch-ahead (seal_shares_pipeline style): every chunk's
        # ladder is in flight before the first np.asarray blocks, so
        # host conversion of chunk k overlaps device work on k+1.
        parts = [np.asarray(out) for out in pending]
        sigs = gd.affine_canon_host(cs, np.concatenate(parts, axis=0))
    if pks is None:
        pks = public_keys(curve, shares)
    pks_canon, pks = pks
    ps = PartialSignatures(
        curve=curve,
        indices=tuple(int(i) for i in indices),
        h_points=list(h_points),
        sigs=sigs,
        pks=pks,
    )
    if prove:
        g = group.generator()
        statements = []
        sigs_host = ps.sigs_host()
        for bi in range(b):
            for si in range(m):
                statements.append(
                    (g, h_points[bi], pks[si], sigs_host[bi][si], shares[si])
                )
        ps.proofs, ps.announcements = dleq_batch.generate_batch(
            group, cs, statements, rng, return_announcements=True
        )
    return ps


def verify_partials(ps: PartialSignatures) -> np.ndarray:
    """Check every partial's DLEQ proof in ONE batched pass ->
    ``(B, m)`` bool.  Pairing-free: a valid proof pins
    log_{H(m)}(sig_i) to log_g(pk_i), which is s_i by the ceremony's
    public commitments."""
    if ps.proofs is None:
        raise ValueError("PartialSignatures carries no proofs (prove=False)")
    cs = gd.ALL_CURVES[ps.curve]
    group = gh.ALL_GROUPS[ps.curve]
    g = group.generator()
    b, m = ps.sigs.shape[:2]
    sigs_host = ps.sigs_host()
    statements = []
    for bi in range(b):
        for si in range(m):
            statements.append((g, ps.h_points[bi], ps.pks[si], sigs_host[bi][si]))
    ok = dleq_batch.verify_batch(group, cs, ps.proofs, statements)
    return np.asarray(ok).reshape(b, m)
