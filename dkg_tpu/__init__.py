"""dkg_tpu — a TPU-native distributed key generation (DKG) framework.

A from-scratch JAX/XLA implementation of the Gennaro-Jarecki-Krawczyk-Rabin
DKG with hybrid-encrypted share delivery (capability parity with the
reference Rust crate `dkg`, see SURVEY.md), redesigned TPU-first:

* field/curve arithmetic as batched 16-bit-limb uint32 tensor ops
  (``dkg_tpu.fields``, ``dkg_tpu.groups``);
* per-party protocol loops turned into whole-committee batched kernels
  (``dkg_tpu.ops``);
* crypto building blocks — Pedersen commitments, lifted/hybrid ElGamal,
  DLEQ NIZKs (``dkg_tpu.crypto``);
* the five-round protocol state machine (``dkg_tpu.dkg``);
* participant-axis sharding over a device mesh (``dkg_tpu.parallel``).
"""

import importlib

__version__ = "0.1.0"

_SUBMODULES = ("crypto", "dkg", "fields", "groups", "native", "net", "ops",
               "parallel", "poly", "utils")


# Lazy submodule loading (PEP 562): importing `dkg_tpu` must stay free of
# jax work so platform forcing (parallel/hostmesh.py) can run first.
def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"dkg_tpu.{name}")
    raise AttributeError(f"module 'dkg_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
