"""Multi-tenant ceremony service: many concurrent DKGs, one warm runtime.

The production shape of "heavy traffic from millions of users" is not
one giant ceremony — it is thousands of small/medium ceremonies
(per-group threshold keys, per-session signing committees) arriving as
traffic.  This package turns the batched engine (dkg.ceremony) into a
service:

* :mod:`~dkg_tpu.service.buckets` — the shape-bucketing policy: every
  requested ``(n, t)`` is padded up to a small ladder of canonical
  shapes so the jit compile cache hits instead of compiling one program
  set per distinct committee size.
* :mod:`~dkg_tpu.service.engine` — the warm execution lane: shared
  precompute tables, pad-and-mask execution of single ceremonies, and a
  *stacked* lane that vmaps whole convoys of same-bucket ceremonies over
  a leading ceremony axis.
* :mod:`~dkg_tpu.service.scheduler` — the admission queue and worker
  pool: bounded queue with reject-on-full (503) backpressure,
  per-ceremony deadlines, convoy formation, a two-deep start/finish
  pipeline generalizing ``seal_shares_pipeline``'s host/device overlap,
  and optional WAL-backed durability.
* :mod:`~dkg_tpu.service.durable` — per-ceremony WAL journaling
  (reusing :class:`~dkg_tpu.net.checkpoint.PartyWal`) so a restarted
  server resumes in-flight ceremonies, with a replay-count crash-loop
  guard poisoning requests that keep taking the process down.
* :mod:`~dkg_tpu.service.errors` — the typed failure taxonomy
  (poison vs transient vs backpressure vs signer starvation) the
  scheduler's isolation machinery branches on (lint DKG010).
* :mod:`~dkg_tpu.service.faultsvc` — seeded chaos injection for all of
  the above (scripts/service_storm.py is the harness).
* :mod:`~dkg_tpu.service.httpobs` — the localhost scrape surface
  (``/metrics``, ``/healthz``, ``/slo``), off unless a port is
  configured.
* :mod:`~dkg_tpu.service.slo` — the rolling SLO evaluator (latency
  quantiles + error-budget burn) behind ``/slo`` and
  ``scripts/slo_gate.py``.

Entry points: :class:`~dkg_tpu.service.scheduler.CeremonyScheduler`,
:class:`~dkg_tpu.service.engine.CeremonyRequest`.  Knobs (all through
``utils.envknobs``): ``DKG_TPU_SERVICE_CONCURRENCY``,
``DKG_TPU_SERVICE_QUEUE_DEPTH``, ``DKG_TPU_SERVICE_BATCH_MAX``,
``DKG_TPU_SERVICE_DEADLINE_S``, ``DKG_TPU_SERVICE_WAL_DIR``,
``DKG_TPU_SERVICE_RETRIES``, ``DKG_TPU_SERVICE_RETRY_BACKOFF_S``,
``DKG_TPU_SERVICE_MAX_REPLAYS``, ``DKG_TPU_SERVICE_HTTP_PORT``,
``DKG_TPU_SLO_WINDOW_S`` / ``DKG_TPU_SLO_ERROR_BUDGET`` /
``DKG_TPU_SLO_CEREMONY_P99_S`` / ``DKG_TPU_SLO_SIGN_P99_S`` (and
``DKG_TPU_RUNTIMEOBS`` via utils.runtimeobs).
See docs/service.md for the architecture and the bucketing/backpressure
semantics, docs/fault_model.md for the service fault model, and
scripts/fleet_bench.py for the throughput benchmark.
"""

from .buckets import Bucket, bucket_for, split_widths
from .engine import CeremonyOutcome, CeremonyRequest, WarmRuntime
from .errors import (
    InsufficientSigners,
    PoisonedRequest,
    QueueFullError,
    ServiceError,
    TransientEngineError,
)
from .faultsvc import ServiceFaultPlan, WorkerCrash, corrupt_journal
from .scheduler import CeremonyScheduler

__all__ = [
    "Bucket",
    "bucket_for",
    "split_widths",
    "CeremonyOutcome",
    "CeremonyRequest",
    "WarmRuntime",
    "CeremonyScheduler",
    "ServiceError",
    "QueueFullError",
    "PoisonedRequest",
    "TransientEngineError",
    "InsufficientSigners",
    "ServiceFaultPlan",
    "WorkerCrash",
    "corrupt_journal",
]
