"""Multi-process fleet front door: K scheduler workers behind one HTTP
surface, with SLO-driven shedding and scaling.

One warm process saturates at one device queue; "millions of users"
means horizontal scale-out, which the AOT executable store
(:mod:`~dkg_tpu.service.aot`) finally makes affordable — a fresh worker
process deserializes its programs in seconds instead of recompiling for
minutes.  This module is the control plane over those workers:

* **Workers** — K child processes (stdlib ``multiprocessing``, spawn
  start method so each child initializes its own JAX runtime), each
  running one :class:`~dkg_tpu.service.scheduler.CeremonyScheduler`
  over its own :class:`~dkg_tpu.service.engine.WarmRuntime`.  AOT
  artifacts, fixed-base tables and compile caches are shared through
  the on-disk stores (the environment — ``DKG_TPU_AOT_DIR`` included —
  is inherited), so worker N+1 warms from worker 0's bake.  Parent and
  child speak length-framed pickles over a ``Pipe``; one request, one
  reply, serialized per worker by a parent-side lock, every request
  tagged with an id the reply must echo — a late reply to an op the
  parent already timed out on is discarded, never served to the next
  caller as its answer.
* **Routing** — requests land on a worker by their shape bucket
  (BLAKE2b of ``(curve, bucket.n, bucket.t)`` mod alive workers), so a
  bucket's convoys keep stacking inside one scheduler instead of
  fragmenting across the fleet.
* **Front door** — the :class:`~dkg_tpu.service.httpobs.ObsHttpServer`
  scrape surface promoted to a real API via its ``router`` hook:
  ``POST /submit``, ``GET /poll?cid=``, ``GET /result?cid=``,
  ``POST /sign``, ``GET /fleet``, alongside the existing
  ``/metrics`` ``/healthz`` ``/slo`` routes.  Queue-full and fleet
  shedding both answer the existing 503 path.
* **Control loop** — a parent thread samples every worker's
  :meth:`~dkg_tpu.service.scheduler.CeremonyScheduler.slo_report` (PR
  13's :class:`~dkg_tpu.service.slo.SloEvaluator`) and ``health()``:
  error-budget burn or a p99 breach turns on load-shedding (new
  submissions 503) and scales up toward ``k_max``; sustained idleness
  (empty queues, objectives met, ``idle_rounds_down`` consecutive
  samples) scales down toward ``k_min``.  Decisions are observable:
  ``fleet_workers``, ``fleet_scale_total{direction}``,
  ``fleet_shed_total``, ``fleet_requests_total{route}``.

This module is deliberately **device-free**: it never imports jax, and
lint rule DKG016 bans ``jax.jit`` tracing entry points here — every
executable a request touches lives in a worker, loaded from the AOT
store or compiled under the worker's ``WarmRuntime``.  DKG007
sanctions this module (with scheduler/httpobs) as a service spawn
site; the worker factory is injectable so tests drive routing, shed
and scale decisions with in-process fakes in milliseconds.

Knobs (all via utils.envknobs): ``DKG_TPU_FLEET_PROCS`` (initial K),
``DKG_TPU_FLEET_MIN`` / ``DKG_TPU_FLEET_MAX`` (scale range),
``DKG_TPU_FLEET_CONTROL_S`` (control-loop period),
``DKG_TPU_FLEET_HTTP_PORT`` (front-door port; 0 = ephemeral, unset =
python API only).
"""

from __future__ import annotations

import hashlib
import multiprocessing
import threading
import time

from ..utils import envknobs
from ..utils.metrics import REGISTRY
from . import buckets, errors
from .httpobs import ObsHttpServer

#: Per-op parent->worker reply budget (seconds) for control-plane ops.
_CONTROL_TIMEOUT_S = 30.0


class WorkerUnavailable(RuntimeError):
    """The routed worker died or timed out mid-request."""


class WorkerBusy(WorkerUnavailable):
    """The worker is alive but its pipe is serving a long data-plane op
    (e.g. a blocking ``result`` wait) — control-plane callers that asked
    for a bounded lock wait report it busy instead of stalling."""

#: How long a control-plane op (health/slo) waits for a worker's pipe
#: lock before reporting the worker busy instead of blocking behind a
#: long data-plane call.
_BUSY_LOCK_TIMEOUT_S = 1.0


def _outcome_wire(out) -> dict:
    """JSON-able public view of a CeremonyOutcome — ``final_shares``
    (secret) never crosses the pipe."""
    return {
        "ceremony_id": out.ceremony_id,
        "status": out.status,
        "curve": out.curve,
        "n": out.n,
        "t": out.t,
        "bucket_n": out.bucket_n,
        "bucket_t": out.bucket_t,
        "master": out.master.hex(),
        "qualified": list(out.qualified),
        "complaints": [list(c) for c in out.complaints],
        "error": out.error,
        "seconds": out.seconds,
        "epoch": out.epoch,
    }


def _proc_worker_main(conn, cfg: dict) -> None:
    """Child entry: one WarmRuntime + one CeremonyScheduler, driven by
    a request/reply loop over ``conn``.  Runs in a spawned process —
    imports happen here, after the fork-free start."""
    t0 = time.monotonic()
    from . import aot as _aot
    from . import engine as _engine
    from .scheduler import CeremonyScheduler

    runtime = _engine.WarmRuntime()
    for w in cfg.get("warm", ()):
        req = _engine.CeremonyRequest(
            curve=w["curve"], n=w["n"], t=w["t"],
            rho_bits=w.get("rho_bits", 128), seed=0,
        )
        runtime.warmup(req, widths=tuple(w.get("widths", (1,))))
    sched = CeremonyScheduler(runtime=runtime, **cfg.get("scheduler", {}))
    conn.send({"op": "ready", "warmup_s": time.monotonic() - t0})
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        op = msg.get("op")
        rid = msg.get("rid")
        try:
            if op == "submit":
                req = _engine.CeremonyRequest(**msg["req"])
                reply = {"ok": True, "cid": sched.submit(req)}
            elif op == "poll":
                reply = {"ok": True, "status": sched.poll(msg["cid"])}
            elif op == "result":
                out = sched.result(msg["cid"], timeout=msg.get("wait_s"))
                reply = {"ok": True, "outcome": _outcome_wire(out)}
            elif op == "sign":
                sigs = sched.sign(
                    msg["cid"],
                    [bytes.fromhex(m) for m in msg["msgs"]],
                    prove=bool(msg.get("prove", False)),
                    seed=msg.get("seed"),
                )
                reply = {"ok": True, "sigs": [s.hex() for s in sigs]}
            elif op == "health":
                reply = {"ok": True, "health": sched.health()}
            elif op == "slo":
                reply = {"ok": True, "slo": sched.slo_report()}
            elif op == "stats":
                reply = {"ok": True, "aot": _aot.stats()}
            elif op == "close":
                sched.close(drain=bool(msg.get("drain", True)))
                conn.send({"ok": True, "rid": rid})
                break
            else:
                reply = {"ok": False, "error": f"unknown op {op!r}"}
        except errors.QueueFullError as exc:
            reply = {"ok": False, "error": "queue_full", "detail": str(exc)}
        except Exception as exc:  # worker must answer, never die silent
            REGISTRY.inc("fleet_worker_errors_total")
            reply = {"ok": False, "error": type(exc).__name__, "detail": str(exc)}
        reply["rid"] = rid
        try:
            conn.send(reply)
        except (BrokenPipeError, OSError):
            break


class _ProcWorker:
    """Parent-side handle for one spawned scheduler process."""

    def __init__(self, index: int, cfg: dict) -> None:
        self.index = index
        self.warmup_s: float | None = None
        self._lock = threading.Lock()
        self._next_rid = 0
        ctx = multiprocessing.get_context("spawn")
        self._conn, child = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(
            target=_proc_worker_main,
            args=(child, cfg),
            name=f"dkg-fleet-{index}",
            daemon=True,
        )
        self._proc.start()
        child.close()

    def alive(self) -> bool:
        return self._proc.is_alive()

    def call(
        self,
        op: str,
        timeout: float | None = None,
        lock_timeout: float | None = None,
        **kw,
    ) -> dict:
        if lock_timeout is None:
            self._lock.acquire()
        elif not self._lock.acquire(timeout=lock_timeout):
            raise WorkerBusy(
                f"worker {self.index}: pipe busy, lock not free "
                f"within {lock_timeout}s"
            )
        try:
            # Request ids keep the one-request-one-reply framing honest
            # across op timeouts: a reply to an op the parent already
            # gave up on (WorkerUnavailable) still lands in the pipe
            # later, and must never be handed to the NEXT caller.
            self._next_rid += 1
            rid = self._next_rid
            try:
                self._conn.send({"op": op, "rid": rid, **kw})
                while True:
                    if timeout is not None and not self._conn.poll(timeout):
                        raise WorkerUnavailable(
                            f"worker {self.index}: no reply to {op!r} "
                            f"within {timeout}s"
                        )
                    reply = self._conn.recv()
                    # the ready banner may precede the first reply
                    if isinstance(reply, dict) and reply.get("op") == "ready":
                        self.warmup_s = reply["warmup_s"]
                        continue
                    if isinstance(reply, dict) and reply.get("rid") != rid:
                        continue  # stale reply to a timed-out op
                    return reply
            except (EOFError, OSError, BrokenPipeError) as exc:
                raise WorkerUnavailable(
                    f"worker {self.index} died mid-{op}: {exc}"
                ) from exc
        finally:
            self._lock.release()

    def wait_ready(self, timeout: float) -> float | None:
        """Block until the worker's ready banner (its warmup seconds),
        or None on timeout/death."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.warmup_s is None:
                left = deadline - time.monotonic()
                if left <= 0 or not self._conn.poll(min(left, 0.25)):
                    if time.monotonic() >= deadline or not self.alive():
                        return None
                    continue
                try:
                    reply = self._conn.recv()
                except (EOFError, OSError):
                    return None
                if isinstance(reply, dict) and reply.get("op") == "ready":
                    self.warmup_s = reply["warmup_s"]
        return self.warmup_s

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        try:
            if self.alive():
                self.call("close", timeout=timeout, drain=drain)
        except WorkerUnavailable:
            pass
        self._proc.join(timeout=5.0)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5.0)
        self._conn.close()


class FleetServer:
    """The fleet: worker pool + router + control loop + front door.

    ``worker_factory(index) -> worker`` is injectable for tests; a
    worker exposes ``call(op, timeout=, **kw)``, ``alive()``,
    ``stop(drain=)``, ``index`` and ``warmup_s``.  The default factory
    spawns :class:`_ProcWorker` processes configured with this fleet's
    scheduler kwargs and warm list.
    """

    def __init__(
        self,
        *,
        procs: int | None = None,
        k_min: int | None = None,
        k_max: int | None = None,
        control_interval_s: float | None = None,
        idle_rounds_down: int = 3,
        http_port: int | None = None,
        scheduler_kwargs: dict | None = None,
        warm: list | None = None,
        worker_factory=None,
        metrics=REGISTRY,
        op_timeout_s: float = 600.0,
    ) -> None:
        self.metrics = metrics
        self.k_init = procs if procs is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_PROCS", "initial fleet worker count")
            or 1
        )
        self.k_min = k_min if k_min is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_MIN", "fleet scale-down floor") or 1
        )
        self.k_max = k_max if k_max is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_MAX", "fleet scale-up ceiling")
            or max(self.k_init, self.k_min)
        )
        if not (self.k_min <= self.k_init <= self.k_max):
            raise ValueError(
                f"fleet size range: need k_min <= procs <= k_max, got "
                f"{self.k_min} <= {self.k_init} <= {self.k_max}"
            )
        if control_interval_s is None:
            control_interval_s = envknobs.pos_float(
                "DKG_TPU_FLEET_CONTROL_S", "fleet control-loop period"
            )
        self.control_interval_s = control_interval_s
        self.idle_rounds_down = idle_rounds_down
        self.op_timeout_s = op_timeout_s
        self._cfg = {
            "scheduler": dict(scheduler_kwargs or {}),
            "warm": list(warm or ()),
        }
        self._factory = worker_factory or (
            lambda idx: _ProcWorker(idx, self._cfg)
        )
        self._lock = threading.RLock()
        self._workers: list = []
        #: cid -> [worker, result_fetched].  Entries live as long as
        #: their worker does (sign keeps routing to it after the result
        #: is fetched) and are evicted when the worker is reaped,
        #: drained or closed — the map never outlives the pool.
        self._placed: dict[str, list] = {}
        self._next_index = 0
        self._shedding = False
        self._idle_rounds = 0
        self._closing = False
        for _ in range(self.k_init):
            self._spawn()
        self._http = None
        if http_port is None:
            http_port = envknobs.nonneg_int(
                "DKG_TPU_FLEET_HTTP_PORT",
                "fleet front-door port (0 = ephemeral; unset = off)",
            )
        if http_port is not None:
            self._http = ObsHttpServer(
                registry=metrics,
                health_fn=self.health,
                slo_fn=self.slo_report,
                router=self._route,
                port=http_port,
            )
        self._control_thread = None
        if control_interval_s:
            self._control_thread = threading.Thread(
                target=self._control_loop, name="dkg-fleet-control", daemon=True
            )
            self._control_thread.start()

    # -- worker pool ---------------------------------------------------------

    def _spawn(self):
        w = self._factory(self._next_index)
        self._next_index += 1
        self._workers.append(w)
        self.metrics.set_gauge("fleet_workers", len(self._workers))
        return w

    def _alive(self) -> list:
        return [w for w in self._workers if w.alive()]

    def wait_ready(self, timeout: float = 600.0) -> list:
        """Block until every current worker reported its warmup banner;
        returns their warmup seconds (None per straggler)."""
        with self._lock:
            ws = list(self._workers)
        out = []
        deadline = time.monotonic() + timeout
        for w in ws:
            left = max(deadline - time.monotonic(), 0.0)
            out.append(
                w.wait_ready(left) if hasattr(w, "wait_ready") else w.warmup_s
            )
        return out

    # -- data plane ----------------------------------------------------------

    def _worker_for(self, curve: str, n: int, t: int):
        b = buckets.bucket_for(n, t)
        with self._lock:
            ws = self._alive()
            if not ws:
                raise errors.QueueFullError("fleet has no live workers")
            tag = hashlib.blake2b(
                f"{curve}:{b.n}:{b.t}".encode(), digest_size=4
            ).digest()
            return ws[int.from_bytes(tag, "big") % len(ws)]

    def submit(self, req: dict) -> str:
        """Route one ceremony request (JSON-able dict of
        CeremonyRequest fields) to its bucket's worker.  Raises
        QueueFullError on shed/full (the HTTP 503 path) and ValueError
        on a malformed request."""
        with self._lock:
            if self._shedding:
                self.metrics.inc("fleet_shed_total")
                raise errors.QueueFullError(
                    "fleet is shedding load (SLO breach)"
                )
        try:
            curve, n, t = req["curve"], int(req["n"]), int(req["t"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ValueError(f"submit needs curve/n/t: {exc}") from exc
        w = self._worker_for(curve, n, t)
        try:
            reply = w.call("submit", req=dict(req), timeout=self.op_timeout_s)
        except WorkerUnavailable as exc:
            self.metrics.inc("fleet_worker_errors_total")
            raise errors.QueueFullError(str(exc)) from exc
        if not reply.get("ok"):
            if reply.get("error") == "queue_full":
                self.metrics.inc("fleet_shed_total")
                raise errors.QueueFullError(reply.get("detail", "queue full"))
            raise ValueError(reply.get("detail") or reply.get("error", "submit failed"))
        cid = reply["cid"]
        with self._lock:
            self._placed[cid] = [w, False]
        return cid

    def _placed_worker(self, cid: str):
        with self._lock:
            entry = self._placed.get(cid)
            return entry[0] if entry is not None else None

    def _evict_placed(self, workers) -> None:
        """Drop placement entries for workers leaving the pool.  Caller
        holds ``self._lock``."""
        gone = set(map(id, workers))
        for cid in [c for c, e in self._placed.items() if id(e[0]) in gone]:
            del self._placed[cid]

    def poll(self, cid: str) -> str:
        w = self._placed_worker(cid)
        if w is None or not w.alive():
            return "unknown"
        reply = w.call("poll", cid=cid, timeout=self.op_timeout_s)
        return reply.get("status", "unknown") if reply.get("ok") else "unknown"

    def result(self, cid: str, timeout: float | None = None) -> dict:
        w = self._placed_worker(cid)
        if w is None:
            raise KeyError(f"unknown ceremony {cid!r}")
        # the scheduler wait rides IN the message; the pipe budget is
        # strictly larger, so a slow ceremony surfaces as the worker's
        # clean TimeoutError reply, never a parent-side pipe timeout
        budget = timeout if timeout is not None else self.op_timeout_s
        reply = w.call("result", cid=cid, wait_s=budget, timeout=budget + 10.0)
        if not reply.get("ok"):
            detail = reply.get("detail") or reply.get("error")
            if reply.get("error") == "TimeoutError":
                raise TimeoutError(detail)
            raise errors.ServiceError(detail)
        with self._lock:
            entry = self._placed.get(cid)
            if entry is not None:
                entry[1] = True
        return reply["outcome"]

    def sign(self, cid: str, msgs: list[bytes], **kw) -> list[bytes]:
        w = self._placed_worker(cid)
        if w is None:
            raise KeyError(f"unknown ceremony {cid!r}")
        reply = w.call(
            "sign", cid=cid, msgs=[m.hex() for m in msgs],
            timeout=self.op_timeout_s, **kw,
        )
        if not reply.get("ok"):
            raise errors.ServiceError(reply.get("detail") or reply.get("error"))
        return [bytes.fromhex(s) for s in reply["sigs"]]

    # -- observability + control plane ---------------------------------------

    def health(self) -> dict:
        with self._lock:
            ws = list(self._workers)
            shedding = self._shedding
        per = []
        for w in ws:
            if not w.alive():
                per.append({"worker": w.index, "ok": False, "alive": False})
                continue
            try:
                h = w.call(
                    "health",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                per.append(
                    {"worker": w.index, "alive": True, **h.get("health", {})}
                )
            except WorkerBusy:
                # pipe held by a long data-plane op: alive, just busy —
                # /healthz must answer now, not after that op drains
                per.append({"worker": w.index, "ok": True, "alive": True,
                            "busy": True})
            except WorkerUnavailable:
                per.append({"worker": w.index, "ok": False, "alive": False})
        alive = [p for p in per if p.get("alive")]
        return {
            "ok": bool(alive) and not shedding,
            "shedding": shedding,
            "workers_alive": len(alive),
            "workers_total": len(per),
            "workers": per,
        }

    def slo_report(self) -> dict:
        with self._lock:
            ws = self._alive()
        per = []
        for w in ws:
            try:
                r = w.call(
                    "slo",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                if r.get("ok"):
                    per.append({"worker": w.index, **r["slo"]})
            except WorkerUnavailable:  # includes WorkerBusy
                continue
        violations = [
            v for r in per for v in r.get("violations", ())
        ]
        return {
            "ok": all(r.get("ok", True) for r in per),
            "violations": violations,
            "workers": per,
        }

    def describe(self) -> dict:
        with self._lock:
            return {
                "workers": len(self._workers),
                "alive": len(self._alive()),
                "k_min": self.k_min,
                "k_max": self.k_max,
                "shedding": self._shedding,
                "warmup_s": [w.warmup_s for w in self._workers],
                "placed": len(self._placed),
            }

    def _control_once(self) -> dict:
        """One SLO-driven control decision; called by the loop thread
        and directly by tests.  Returns the decision record."""
        with self._lock:
            ws = list(self._workers)
            # reap workers that died (crash, OOM-kill): routing already
            # skips them, this trims the pool, frees the pipe, and
            # forgets placements nobody can serve anymore
            dead = [w for w in ws if not w.alive()]
            for w in dead:
                self._workers.remove(w)
                self.metrics.inc("fleet_worker_restarts_total")
            self._evict_placed(dead)
            # keep the pool at the floor: a crashed worker is replaced
            # even in a healthy window
            while len(self._workers) < self.k_min and not self._closing:
                self._spawn()
            ws = list(self._workers)
        reports, healths = [], []
        for w in ws:
            try:
                r = w.call(
                    "slo",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                h = w.call(
                    "health",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
            except WorkerUnavailable:  # includes WorkerBusy
                continue
            if r.get("ok"):
                reports.append(r["slo"])
            if h.get("ok"):
                healths.append(h["health"])
        breach = any(not r.get("ok", True) for r in reports)
        burn = 0.0
        for r in reports:
            err = r.get("errors") or {}
            burn = max(burn, float(err.get("burn") or 0.0))
        depth = sum(int(h.get("queue_depth", 0)) for h in healths)
        decision = "hold"
        with self._lock:
            alive = len(self._alive())
            if breach or burn > 1.0:
                self._shedding = True
                self._idle_rounds = 0
                if alive < self.k_max and not self._closing:
                    self._spawn()
                    decision = "up"
                    self.metrics.inc("fleet_scale_total", direction="up")
            else:
                self._shedding = False
                if depth == 0 and reports:
                    self._idle_rounds += 1
                else:
                    self._idle_rounds = 0
                victim = None
                if (
                    self._idle_rounds >= self.idle_rounds_down
                    and alive > self.k_min
                    and not self._closing
                ):
                    # drain only a worker whose completed-but-unfetched
                    # results nobody is still owed: stopping the process
                    # would lose them (poll -> unknown, result -> 409)
                    unfetched = {
                        id(e[0]) for e in self._placed.values() if not e[1]
                    }
                    for cand in reversed(self._workers):
                        if id(cand) not in unfetched:
                            victim = cand
                            break
                if victim is not None:
                    self._workers.remove(victim)
                    self._evict_placed([victim])
                    decision = "down"
                    self._idle_rounds = 0
                    self.metrics.inc("fleet_scale_total", direction="down")
            self.metrics.set_gauge("fleet_workers", len(self._workers))
            self.metrics.set_gauge("fleet_shedding", 1.0 if self._shedding else 0.0)
        if decision == "down":
            victim.stop(drain=True)
        return {
            "decision": decision,
            "shedding": self._shedding,
            "breach": breach,
            "burn": burn,
            "queue_depth": depth,
            "workers": len(ws),
        }

    def _control_loop(self) -> None:
        while not self._closing:
            time.sleep(self.control_interval_s)
            if self._closing:
                return
            try:
                self._control_once()
            except Exception:
                self.metrics.inc("fleet_control_errors_total")

    # -- HTTP front door -----------------------------------------------------

    def _route(self, method: str, path: str, query: dict, body):
        if method == "POST" and path == "/submit":
            self.metrics.inc("fleet_requests_total", route="submit")
            try:
                cid = self.submit(body or {})
                return 200, {"ceremony_id": cid}
            except errors.QueueFullError as exc:
                return 503, {"error": "unavailable", "detail": str(exc)}
            except (TypeError, ValueError) as exc:
                return 400, {"error": "bad request", "detail": str(exc)}
        if method == "GET" and path == "/poll":
            self.metrics.inc("fleet_requests_total", route="poll")
            cid = query.get("cid", "")
            return 200, {"ceremony_id": cid, "status": self.poll(cid)}
        if method == "GET" and path == "/result":
            self.metrics.inc("fleet_requests_total", route="result")
            cid = query.get("cid", "")
            try:
                timeout = float(query["timeout"]) if "timeout" in query else None
                return 200, self.result(cid, timeout=timeout)
            except KeyError:
                return 404, {"error": "unknown ceremony", "ceremony_id": cid}
            except TimeoutError as exc:
                return 504, {"error": "timeout", "detail": str(exc),
                             "ceremony_id": cid}
            except (RuntimeError, ValueError) as exc:
                return 409, {"error": str(exc), "ceremony_id": cid}
        if method == "POST" and path == "/sign":
            self.metrics.inc("fleet_requests_total", route="sign")
            body = body or {}
            cid = body.get("cid", "")
            try:
                msgs = [bytes.fromhex(m) for m in body.get("msgs", [])]
                sigs = self.sign(
                    cid, msgs,
                    prove=bool(body.get("prove", False)),
                    seed=body.get("seed"),
                )
                return 200, {
                    "ceremony_id": cid,
                    "signatures": [s.hex() for s in sigs],
                }
            except KeyError:
                return 404, {"error": "unknown ceremony", "ceremony_id": cid}
            except (RuntimeError, ValueError) as exc:
                return 409, {"error": str(exc), "ceremony_id": cid}
        if method == "GET" and path == "/fleet":
            self.metrics.inc("fleet_requests_total", route="fleet")
            return 200, self.describe()
        return None

    @property
    def port(self) -> int | None:
        return self._http.port if self._http is not None else None

    def close(self, drain: bool = True) -> None:
        self._closing = True
        if self._control_thread is not None:
            self._control_thread.join(
                timeout=(self.control_interval_s or 0) + 5.0
            )
        if self._http is not None:
            self._http.close()
        with self._lock:
            ws = list(self._workers)
            self._workers.clear()
            self._placed.clear()
        for w in ws:
            w.stop(drain=drain)
