"""Multi-process fleet front door: K scheduler workers behind one HTTP
surface, with SLO-driven shedding and scaling.

One warm process saturates at one device queue; "millions of users"
means horizontal scale-out, which the AOT executable store
(:mod:`~dkg_tpu.service.aot`) finally makes affordable — a fresh worker
process deserializes its programs in seconds instead of recompiling for
minutes.  This module is the control plane over those workers:

* **Workers** — K child processes (stdlib ``multiprocessing``, spawn
  start method so each child initializes its own JAX runtime), each
  running one :class:`~dkg_tpu.service.scheduler.CeremonyScheduler`
  over its own :class:`~dkg_tpu.service.engine.WarmRuntime`.  AOT
  artifacts, fixed-base tables and compile caches are shared through
  the on-disk stores (the environment — ``DKG_TPU_AOT_DIR`` included —
  is inherited), so worker N+1 warms from worker 0's bake.  Parent and
  child speak length-framed pickles over a ``Pipe``; one request, one
  reply, serialized per worker by a parent-side lock, every request
  tagged with an id the reply must echo — a late reply to an op the
  parent already timed out on is discarded, never served to the next
  caller as its answer.
* **Routing** — requests land on a worker by their shape bucket
  (BLAKE2b of ``(curve, bucket.n, bucket.t)`` mod alive workers), so a
  bucket's convoys keep stacking inside one scheduler instead of
  fragmenting across the fleet.
* **Front door** — the :class:`~dkg_tpu.service.httpobs.ObsHttpServer`
  scrape surface promoted to a real API via its ``router`` hook:
  ``POST /submit``, ``GET /poll?cid=``, ``GET /result?cid=``,
  ``POST /sign``, ``GET /fleet``, alongside the existing
  ``/metrics`` ``/healthz`` ``/slo`` routes.  Queue-full and fleet
  shedding both answer the existing 503 path.
* **Control loop** — a parent thread samples every worker's
  :meth:`~dkg_tpu.service.scheduler.CeremonyScheduler.slo_report` (PR
  13's :class:`~dkg_tpu.service.slo.SloEvaluator`) and ``health()``:
  error-budget burn or a p99 breach turns on load-shedding (new
  submissions 503) and scales up toward ``k_max``; sustained idleness
  (empty queues, objectives met, ``idle_rounds_down`` consecutive
  samples) scales down toward ``k_min``.  Decisions are observable:
  ``fleet_workers``, ``fleet_scale_total{direction}``,
  ``fleet_shed_total``, ``fleet_requests_total{route}``.

* **Failover** — worker death is a non-event for clients when a
  journal root (``DKG_TPU_FLEET_WAL_DIR`` / ``wal_root=``) is set.
  Workers are pinned to **slots**; each slot owns a private journal
  directory (``<root>/slotNNN``) its scheduler journals durable work
  into.  When a slot's worker dies, its placements become *orphans*
  (``poll`` → ``recovering``) instead of being evicted; the
  replacement worker boots from the same slot journal — the
  scheduler's existing recovery re-runs seeded pending ceremonies
  under their ORIGINAL ids and re-serves terminal outcomes — and the
  parent asks it for a ``manifest`` (every cid it knows) to repopulate
  ``_placed``, so ``poll``/``result``/``sign`` survive the crash with
  the original cid.  Respawn is per-slot with capped exponential
  backoff; a slot that dies ``DKG_TPU_FLEET_RESPAWN_MAX`` times inside
  ``DKG_TPU_FLEET_RESPAWN_WINDOW_S`` is quarantined (the crash-loop
  guard — the fleet mirror of ``DKG_TPU_SERVICE_MAX_REPLAYS``) and its
  placements get a typed terminal outcome naming
  :class:`~dkg_tpu.service.errors.FleetSlotQuarantined`.  Without a
  journal root the pre-failover behavior stands: reaped workers'
  placements are evicted (``poll`` → ``unknown``).

This module is deliberately **device-free**: it never imports jax, and
lint rule DKG016 bans ``jax.jit`` tracing entry points here — every
executable a request touches lives in a worker, loaded from the AOT
store or compiled under the worker's ``WarmRuntime``.  DKG007
sanctions this module (with scheduler/httpobs) as a service spawn
site; the worker factory is injectable so tests drive routing, shed
and scale decisions with in-process fakes in milliseconds.  Lint
DKG017 guards the placement map: only the eviction/manifest helpers
(``_evict_placed`` / ``_adopt_manifest`` / ``_tombstone_slot`` /
``close``) may remove ``_placed`` entries — no silent placement drops.

Knobs (all via utils.envknobs): ``DKG_TPU_FLEET_PROCS`` (initial K),
``DKG_TPU_FLEET_MIN`` / ``DKG_TPU_FLEET_MAX`` (scale range),
``DKG_TPU_FLEET_CONTROL_S`` (control-loop period),
``DKG_TPU_FLEET_HTTP_PORT`` (front-door port; 0 = ephemeral, unset =
python API only), ``DKG_TPU_FLEET_WAL_DIR`` (per-slot journal root;
unset = no worker recovery), ``DKG_TPU_FLEET_RESPAWN_BACKOFF_S`` /
``DKG_TPU_FLEET_RESPAWN_MAX`` / ``DKG_TPU_FLEET_RESPAWN_WINDOW_S``
(crash-loop containment), ``DKG_TPU_FLEET_SUBMIT_RETRY_S`` (submit
failover backoff).
"""

from __future__ import annotations

import hashlib
import multiprocessing
import os
import threading
import time

from ..utils import envknobs
from ..utils.metrics import REGISTRY
from . import buckets, errors
from .httpobs import ObsHttpServer

#: Per-op parent->worker reply budget (seconds) for control-plane ops.
_CONTROL_TIMEOUT_S = 30.0


class WorkerUnavailable(RuntimeError):
    """The routed worker died or timed out mid-request."""


class WorkerBusy(WorkerUnavailable):
    """The worker is alive but its pipe is serving a long data-plane op
    (e.g. a blocking ``result`` wait) — control-plane callers that asked
    for a bounded lock wait report it busy instead of stalling."""

#: How long a control-plane op (health/slo) waits for a worker's pipe
#: lock before reporting the worker busy instead of blocking behind a
#: long data-plane call.
_BUSY_LOCK_TIMEOUT_S = 1.0

#: Ceiling on the per-slot respawn backoff, whatever the doubling says.
_RESPAWN_BACKOFF_CAP_S = 30.0

#: Pipe budget for one ``manifest`` ask against a replacement worker.
#: Deliberately short: a still-warming replacement reports unavailable
#: and the control loop (or the next poll/result) simply retries.
_MANIFEST_TIMEOUT_S = 2.0


def _outcome_wire(out) -> dict:
    """JSON-able public view of a CeremonyOutcome — ``final_shares``
    (secret) never crosses the pipe."""
    return {
        "ceremony_id": out.ceremony_id,
        "status": out.status,
        "curve": out.curve,
        "n": out.n,
        "t": out.t,
        "bucket_n": out.bucket_n,
        "bucket_t": out.bucket_t,
        "master": out.master.hex(),
        "qualified": list(out.qualified),
        "complaints": [list(c) for c in out.complaints],
        "error": out.error,
        "seconds": out.seconds,
        "epoch": out.epoch,
    }


def _proc_worker_main(conn, cfg: dict) -> None:
    """Child entry: one WarmRuntime + one CeremonyScheduler, driven by
    a request/reply loop over ``conn``.  Runs in a spawned process —
    imports happen here, after the fork-free start."""
    t0 = time.monotonic()
    # chaos rides in as a plain dict (ServiceFaultPlan holds a lock and
    # cannot cross the spawn pickle); the child builds its own plan.
    # boot_fail dies before the backend imports: a crash-looping binary
    # burns its respawn budget fast, it doesn't warm up first.
    fault_cfg = cfg.get("fault") or {}
    if fault_cfg.get("boot_fail"):
        raise SystemExit(3)  # injected boot crash (storm quarantine leg)
    from . import aot as _aot
    from . import engine as _engine
    from .scheduler import CeremonyScheduler

    plan = None
    if fault_cfg:
        from .faultsvc import ServiceFaultPlan

        plan = ServiceFaultPlan(seed=int(fault_cfg.get("seed", 0)))
        if fault_cfg.get("slow_times"):
            plan.slow(
                float(fault_cfg.get("slow_s", 0.0)),
                times=int(fault_cfg["slow_times"]),
            )
        if fault_cfg.get("transient_times"):
            plan.transient(times=int(fault_cfg["transient_times"]))

    runtime = _engine.WarmRuntime()
    for w in cfg.get("warm", ()):
        req = _engine.CeremonyRequest(
            curve=w["curve"], n=w["n"], t=w["t"],
            rho_bits=w.get("rho_bits", 128), seed=0,
        )
        runtime.warmup(req, widths=tuple(w.get("widths", (1,))))
    sched = CeremonyScheduler(
        runtime=runtime, fault_plan=plan, **cfg.get("scheduler", {})
    )
    conn.send({"op": "ready", "warmup_s": time.monotonic() - t0})
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        except Exception:
            # a garbled/truncated frame (corrupted IPC writer, chaos
            # injection) must not kill the worker: note it, keep
            # serving — the sender's op times out and the rid framing
            # keeps later replies honest
            REGISTRY.inc("fleet_pipe_garbage_total")
            continue
        if not isinstance(msg, dict):
            REGISTRY.inc("fleet_pipe_garbage_total")
            continue
        op = msg.get("op")
        rid = msg.get("rid")
        try:
            if op == "submit":
                req = _engine.CeremonyRequest(**msg["req"])
                reply = {"ok": True, "cid": sched.submit(req)}
            elif op == "poll":
                reply = {"ok": True, "status": sched.poll(msg["cid"])}
            elif op == "result":
                out = sched.result(msg["cid"], timeout=msg.get("wait_s"))
                reply = {"ok": True, "outcome": _outcome_wire(out)}
            elif op == "sign":
                sigs = sched.sign(
                    msg["cid"],
                    [bytes.fromhex(m) for m in msg["msgs"]],
                    prove=bool(msg.get("prove", False)),
                    seed=msg.get("seed"),
                )
                reply = {"ok": True, "sigs": [s.hex() for s in sigs]}
            elif op == "manifest":
                # post-recovery inventory: every cid this scheduler
                # knows (recovered or fresh), for parent placement repair
                reply = {"ok": True, "ceremonies": sched.manifest()}
            elif op == "health":
                reply = {"ok": True, "health": sched.health()}
            elif op == "slo":
                reply = {"ok": True, "slo": sched.slo_report()}
            elif op == "stats":
                reply = {"ok": True, "aot": _aot.stats()}
            elif op == "close":
                sched.close(drain=bool(msg.get("drain", True)))
                conn.send({"ok": True, "rid": rid})
                break
            else:
                reply = {"ok": False, "error": f"unknown op {op!r}"}
        except errors.QueueFullError as exc:
            reply = {"ok": False, "error": "queue_full", "detail": str(exc)}
        except Exception as exc:  # worker must answer, never die silent
            REGISTRY.inc("fleet_worker_errors_total")
            reply = {"ok": False, "error": type(exc).__name__, "detail": str(exc)}
        reply["rid"] = rid
        try:
            conn.send(reply)
        except (BrokenPipeError, OSError):
            break


class _ProcWorker:
    """Parent-side handle for one spawned scheduler process."""

    def __init__(self, index: int, cfg: dict) -> None:
        self.index = index
        self.slot: int | None = None  # stamped by FleetServer._spawn
        self.warmup_s: float | None = None
        self._lock = threading.Lock()
        self._next_rid = 0
        ctx = multiprocessing.get_context("spawn")
        self._conn, child = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(
            target=_proc_worker_main,
            args=(child, cfg),
            name=f"dkg-fleet-{index}",
            daemon=True,
        )
        self._proc.start()
        child.close()

    def alive(self) -> bool:
        return self._proc.is_alive()

    def kill(self) -> None:
        """Hard-kill the child (SIGKILL) — chaos injection for the
        fleet storm; the control plane never calls this."""
        self._proc.kill()

    def inject_garbage(self, payload: bytes = b"\x80\x04garbage") -> bool:
        """Write one garbled frame into the worker's pipe — models a
        corrupted IPC writer (fleet storm's pipe-garbage fault).  The
        frame is length-complete but unpicklable, so the child's recv
        guard counts it and keeps serving.  Returns False when the pipe
        is busy or already broken (nothing injected)."""
        if not self._lock.acquire(timeout=1.0):
            return False
        try:
            self._conn.send_bytes(payload)
            return True
        except (BrokenPipeError, OSError):
            return False
        finally:
            self._lock.release()

    def call(
        self,
        op: str,
        timeout: float | None = None,
        lock_timeout: float | None = None,
        **kw,
    ) -> dict:
        if lock_timeout is None:
            self._lock.acquire()
        elif not self._lock.acquire(timeout=lock_timeout):
            raise WorkerBusy(
                f"worker {self.index}: pipe busy, lock not free "
                f"within {lock_timeout}s"
            )
        try:
            # Request ids keep the one-request-one-reply framing honest
            # across op timeouts: a reply to an op the parent already
            # gave up on (WorkerUnavailable) still lands in the pipe
            # later, and must never be handed to the NEXT caller.
            self._next_rid += 1
            rid = self._next_rid
            try:
                self._conn.send({"op": op, "rid": rid, **kw})
                while True:
                    if timeout is not None and not self._conn.poll(timeout):
                        raise WorkerUnavailable(
                            f"worker {self.index}: no reply to {op!r} "
                            f"within {timeout}s"
                        )
                    reply = self._conn.recv()
                    # the ready banner may precede the first reply
                    if isinstance(reply, dict) and reply.get("op") == "ready":
                        self.warmup_s = reply["warmup_s"]
                        continue
                    if isinstance(reply, dict) and reply.get("rid") != rid:
                        continue  # stale reply to a timed-out op
                    return reply
            except (EOFError, OSError, BrokenPipeError) as exc:
                raise WorkerUnavailable(
                    f"worker {self.index} died mid-{op}: {exc}"
                ) from exc
        finally:
            self._lock.release()

    def wait_ready(self, timeout: float) -> float | None:
        """Block until the worker's ready banner (its warmup seconds),
        or None on timeout/death."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.warmup_s is None:
                left = deadline - time.monotonic()
                if left <= 0 or not self._conn.poll(min(left, 0.25)):
                    if time.monotonic() >= deadline or not self.alive():
                        return None
                    continue
                try:
                    reply = self._conn.recv()
                except (EOFError, OSError):
                    return None
                if isinstance(reply, dict) and reply.get("op") == "ready":
                    self.warmup_s = reply["warmup_s"]
        return self.warmup_s

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        try:
            if self.alive():
                self.call("close", timeout=timeout, drain=drain)
        except WorkerUnavailable:
            pass
        self._proc.join(timeout=5.0)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5.0)
        self._conn.close()


class _SlotState:
    """One worker slot's failover bookkeeping: which worker currently
    fills it, its crash history inside the rolling window, when the
    next respawn is allowed, and whether the crash-loop guard tripped.
    Slots — not workers — own journal directories: worker N+1 of slot 3
    recovers from the same ``slot003`` journal worker N wrote."""

    __slots__ = (
        "slot", "worker", "deaths", "respawn_at", "quarantined",
        "needs_manifest",
    )

    def __init__(self, slot: int) -> None:
        self.slot = slot
        self.worker = None
        self.deaths: list[float] = []  # reap timestamps inside window
        self.respawn_at = 0.0
        self.quarantined = False
        self.needs_manifest = False


class FleetServer:
    """The fleet: worker pool + router + control loop + front door.

    ``worker_factory(index) -> worker`` is injectable for tests; a
    worker exposes ``call(op, timeout=, **kw)``, ``alive()``,
    ``stop(drain=)``, ``index`` and ``warmup_s``.  The default factory
    spawns :class:`_ProcWorker` processes configured with this fleet's
    scheduler kwargs and warm list.
    """

    def __init__(
        self,
        *,
        procs: int | None = None,
        k_min: int | None = None,
        k_max: int | None = None,
        control_interval_s: float | None = None,
        idle_rounds_down: int = 3,
        http_port: int | None = None,
        scheduler_kwargs: dict | None = None,
        warm: list | None = None,
        worker_factory=None,
        metrics=REGISTRY,
        op_timeout_s: float = 600.0,
        wal_root: str | None = None,
        respawn_backoff_s: float | None = None,
        respawn_max: int | None = None,
        respawn_window_s: float | None = None,
        submit_retry_backoff_s: float | None = None,
        fault_plan=None,
        worker_fault: dict | None = None,
    ) -> None:
        self.metrics = metrics
        self.k_init = procs if procs is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_PROCS", "initial fleet worker count")
            or 1
        )
        self.k_min = k_min if k_min is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_MIN", "fleet scale-down floor") or 1
        )
        self.k_max = k_max if k_max is not None else (
            envknobs.pos_int("DKG_TPU_FLEET_MAX", "fleet scale-up ceiling")
            or max(self.k_init, self.k_min)
        )
        if not (self.k_min <= self.k_init <= self.k_max):
            raise ValueError(
                f"fleet size range: need k_min <= procs <= k_max, got "
                f"{self.k_min} <= {self.k_init} <= {self.k_max}"
            )
        if control_interval_s is None:
            control_interval_s = envknobs.pos_float(
                "DKG_TPU_FLEET_CONTROL_S", "fleet control-loop period"
            )
        self.control_interval_s = control_interval_s
        self.idle_rounds_down = idle_rounds_down
        self.op_timeout_s = op_timeout_s
        if wal_root is None:
            wal_root = envknobs.string(
                "DKG_TPU_FLEET_WAL_DIR",
                "per-slot fleet journal root (unset = no worker recovery)",
            )
        self.wal_root = wal_root
        if respawn_backoff_s is None:
            respawn_backoff_s = envknobs.nonneg_float(
                "DKG_TPU_FLEET_RESPAWN_BACKOFF_S",
                "second-respawn backoff, doubling per death (first is free)",
            )
        self.respawn_backoff_s = (
            0.5 if respawn_backoff_s is None else respawn_backoff_s
        )
        if respawn_max is None:
            respawn_max = envknobs.pos_int(
                "DKG_TPU_FLEET_RESPAWN_MAX",
                "slot deaths inside the window before quarantine",
            ) or 3
        self.respawn_max = respawn_max
        if respawn_window_s is None:
            respawn_window_s = envknobs.pos_float(
                "DKG_TPU_FLEET_RESPAWN_WINDOW_S",
                "crash-loop window the death count rolls over",
            ) or 60.0
        self.respawn_window_s = respawn_window_s
        if submit_retry_backoff_s is None:
            submit_retry_backoff_s = envknobs.nonneg_float(
                "DKG_TPU_FLEET_SUBMIT_RETRY_S",
                "pause before the one submit retry after WorkerUnavailable",
            )
        self.submit_retry_backoff_s = (
            0.05 if submit_retry_backoff_s is None else submit_retry_backoff_s
        )
        self._fault_plan = fault_plan
        self._cfg = {
            "scheduler": dict(scheduler_kwargs or {}),
            "warm": list(warm or ()),
        }
        if worker_fault:
            self._cfg["fault"] = dict(worker_fault)
        self._factory = worker_factory or (
            lambda idx: _ProcWorker(idx, self._slot_cfg(self._spawning_slot))
        )
        self._lock = threading.RLock()
        self._workers: list = []
        #: cid -> [worker, result_fetched].  Entries live as long as
        #: their worker does (sign keeps routing to it after the result
        #: is fetched) and leave the map ONLY through the sanctioned
        #: helpers (lint DKG017): reap-eviction, manifest adoption,
        #: slot tombstoning, close.  With a journal root a reaped
        #: worker's entries become orphans (worker=None) awaiting the
        #: replacement's manifest instead of being dropped.
        self._placed: dict[str, list] = {}
        #: cid -> slot, for placements whose worker died and whose slot
        #: journal should resurrect them ("recovering" to pollers).
        self._orphans: dict[str, int] = {}
        #: cid -> terminal outcome dict, for placements lost to a
        #: quarantined (crash-looping) slot.
        self._tombstones: dict[str, dict] = {}
        self._slots: dict[int, _SlotState] = {}
        self._next_slot = 0
        self._spawning_slot: int | None = None
        self._next_index = 0
        self._shedding = False
        self._idle_rounds = 0
        self._closing = False
        for _ in range(self.k_init):
            self._spawn()
        self._http = None
        if http_port is None:
            http_port = envknobs.nonneg_int(
                "DKG_TPU_FLEET_HTTP_PORT",
                "fleet front-door port (0 = ephemeral; unset = off)",
            )
        if http_port is not None:
            self._http = ObsHttpServer(
                registry=metrics,
                health_fn=self.health,
                slo_fn=self.slo_report,
                router=self._route,
                port=http_port,
            )
        self._control_thread = None
        if control_interval_s:
            self._control_thread = threading.Thread(
                target=self._control_loop, name="dkg-fleet-control", daemon=True
            )
            self._control_thread.start()

    # -- worker pool ---------------------------------------------------------

    def _slot_wal_dir(self, slot: int) -> str | None:
        """The journal directory slot ``slot``'s workers share across
        respawns; None when the fleet runs journal-less."""
        if not self.wal_root:
            return None
        return os.path.join(str(self.wal_root), f"slot{slot:03d}")

    def _slot_cfg(self, slot: int) -> dict:
        """Worker cfg with the slot's journal directory wired into the
        scheduler kwargs (PartyWal mkdirs it on first append)."""
        cfg = dict(self._cfg)
        cfg["scheduler"] = dict(cfg["scheduler"])
        wal = self._slot_wal_dir(slot)
        if wal is not None:
            cfg["scheduler"]["wal_dir"] = wal
        return cfg

    def _spawn(self, slot: int | None = None):
        """Spawn a worker into ``slot`` (a fresh slot when None).
        Caller holds ``self._lock`` (or is the constructor)."""
        if slot is None:
            slot = self._next_slot
            self._next_slot += 1
        st = self._slots.get(slot)
        if st is None:
            st = self._slots[slot] = _SlotState(slot)
        self._spawning_slot = slot
        try:
            w = self._factory(self._next_index)
        finally:
            self._spawning_slot = None
        self._next_index += 1
        try:
            w.slot = slot
        except AttributeError:
            pass  # exotic fake without settable attrs: slot state still tracks it
        st.worker = w
        # journaling fleets always ask a fresh worker what it recovered:
        # a replacement reports the slot journal's ceremonies, a brand
        # new worker reports {} (and a restarted front door re-adopts)
        st.needs_manifest = bool(self.wal_root)
        self._workers.append(w)
        self.metrics.set_gauge("fleet_workers", len(self._workers))
        return w

    def _alive(self) -> list:
        return [w for w in self._workers if w.alive()]

    def wait_ready(self, timeout: float = 600.0) -> list:
        """Block until every current worker reported its warmup banner;
        returns their warmup seconds (None per straggler)."""
        with self._lock:
            ws = list(self._workers)
        out = []
        deadline = time.monotonic() + timeout
        for w in ws:
            left = max(deadline - time.monotonic(), 0.0)
            out.append(
                w.wait_ready(left) if hasattr(w, "wait_ready") else w.warmup_s
            )
        return out

    # -- failover ------------------------------------------------------------

    def _note_death_locked(self, w, now: float) -> None:
        """Bookkeep one reaped worker: crash history, backoff, orphan
        or evict its placements, quarantine on a crash loop.  Caller
        holds ``self._lock``."""
        slot = getattr(w, "slot", None)
        st = self._slots.get(slot) if slot is not None else None
        if st is None:
            self._evict_placed([w])  # untracked (pre-slot fake): old behavior
            return
        st.worker = None
        st.deaths = [d for d in st.deaths if now - d < self.respawn_window_s]
        st.deaths.append(now)
        d = len(st.deaths)
        if d >= self.respawn_max:
            st.quarantined = True
            self.metrics.inc("fleet_worker_quarantined_total")
            self._tombstone_slot(st, w)
            return
        # first death respawns immediately (a lone crash should not
        # delay recovery); repeats back off exponentially under the cap
        st.respawn_at = now + (
            0.0 if d == 1 else min(
                _RESPAWN_BACKOFF_CAP_S,
                self.respawn_backoff_s * (2.0 ** (d - 2)),
            )
        )
        if self.wal_root:
            self._orphan_placed(w, st.slot)
        else:
            self._evict_placed([w])

    def _orphan_placed(self, w, slot: int) -> None:
        """Detach ``w``'s placements without dropping them: the slot
        journal can resurrect them.  Caller holds ``self._lock``."""
        for cid, e in self._placed.items():
            if e[0] is w:
                e[0] = None
                self._orphans[cid] = slot

    def _tombstone_slot(self, st: _SlotState, w=None) -> None:
        """Terminal-fail every placement a quarantined slot held — the
        typed outcome clients see instead of an eternal "recovering".
        Caller holds ``self._lock``.  A sanctioned ``_placed`` remover
        (lint DKG017)."""
        err = (
            f"FleetSlotQuarantined: slot {st.slot} died {len(st.deaths)}x "
            f"within {self.respawn_window_s:g}s"
        )
        cids = [c for c, s in self._orphans.items() if s == st.slot]
        if w is not None:
            cids += [c for c, e in self._placed.items() if e[0] is w]
        for cid in cids:
            self._orphans.pop(cid, None)
            self._placed.pop(cid, None)
            self._tombstones[cid] = {
                "ceremony_id": cid,
                "status": "failed",
                "error": err,
            }

    def _respawn_due_locked(self, now: float) -> list:
        """Respawn dead slots whose backoff expired; retire dead slots
        nobody needs.  Returns ``[(slot_state, worker), ...]`` spawned.
        Caller holds ``self._lock``."""
        spawned = []
        if self._closing:
            return spawned
        orphan_slots = set(self._orphans.values())
        for st in sorted(self._slots.values(), key=lambda s: s.slot):
            if st.worker is not None or st.quarantined:
                continue
            alive = sum(1 for w in self._workers if w.alive())
            if alive >= self.k_min and st.slot not in orphan_slots:
                del self._slots[st.slot]  # spare capacity: retire the slot
                continue
            if now < st.respawn_at:
                continue
            spawned.append((st, self._spawn(slot=st.slot)))
        return spawned

    def _reap_and_respawn(self) -> list:
        """Remove dead workers from the pool and respawn their slots
        (backoff permitting).  Shared by the control loop and the data
        plane's failure paths; safe to call from any thread."""
        with self._lock:
            now = time.monotonic()
            for w in [w for w in self._workers if not w.alive()]:
                self._workers.remove(w)
                self.metrics.inc("fleet_worker_restarts_total")
                self._note_death_locked(w, now)
            spawned = self._respawn_due_locked(now)
            self.metrics.set_gauge("fleet_workers", len(self._workers))
        for st, w in spawned:
            if self._fault_plan is not None:
                # the storm's kill-during-recovery hook
                try:
                    self._fault_plan.on_respawn(self, st.slot, w)
                except Exception:
                    self.metrics.inc("fleet_control_errors_total")
        return spawned

    def _try_manifest(
        self, st: _SlotState, w, timeout: float = _MANIFEST_TIMEOUT_S
    ) -> bool:
        """Ask a worker for its ceremony inventory and adopt it.  False
        when the worker is still warming/busy (caller retries later;
        the rid framing discards the eventual stale reply)."""
        try:
            reply = w.call(
                "manifest", timeout=timeout, lock_timeout=_BUSY_LOCK_TIMEOUT_S
            )
        except WorkerUnavailable:
            return False
        if not reply.get("ok"):
            return False
        self._adopt_manifest(st, w, reply.get("ceremonies") or {})
        return True

    def _adopt_manifest(self, st: _SlotState, w, ceremonies: dict) -> None:
        """Repopulate ``_placed`` from what a replacement worker
        actually recovered.  Orphans of this slot present in the
        manifest are re-placed under their ORIGINAL cid; orphans absent
        from it (non-durable, or lost to journal corruption) are
        reported lost.  A sanctioned ``_placed`` remover (DKG017)."""
        with self._lock:
            for cid in [c for c, s in self._orphans.items() if s == st.slot]:
                del self._orphans[cid]
                if cid in ceremonies:
                    self._placed[cid] = [w, False]
                    self.metrics.inc("fleet_placements_recovered_total")
                else:
                    self._placed.pop(cid, None)
                    self.metrics.inc("fleet_placements_lost_total")
            # ceremonies the worker knows that nobody placed (front door
            # itself restarted over a populated journal root): adopt them
            for cid in ceremonies:
                if cid not in self._placed and cid not in self._tombstones:
                    self._placed[cid] = [w, False]
            st.needs_manifest = False

    def _adopt_pending_manifests(self) -> None:
        """Collect manifests from every live worker still owing one."""
        with self._lock:
            pend = [
                (st, st.worker)
                for st in self._slots.values()
                if st.needs_manifest
                and st.worker is not None
                and st.worker.alive()
            ]
        for st, w in pend:
            self._try_manifest(st, w)

    def _try_adopt(self, cid: str, timeout: float) -> None:
        """Data-plane nudge for one orphan: respawn its slot if due and
        ask the replacement for its manifest — so a poll/result hitting
        a recovering cid converges without waiting for a control tick."""
        self._reap_and_respawn()
        with self._lock:
            slot = self._orphans.get(cid)
            st = self._slots.get(slot) if slot is not None else None
            w = st.worker if st is not None else None
        if st is not None and w is not None and w.alive():
            self._try_manifest(st, w, timeout=timeout)

    # -- data plane ----------------------------------------------------------

    def _worker_for(self, curve: str, n: int, t: int, exclude=None):
        b = buckets.bucket_for(n, t)
        with self._lock:
            ws = self._alive()
            if exclude is not None and len(ws) > 1:
                # submit failover: re-route around the worker that just
                # failed — ring-next lands one step over in the same ring
                ws = [w for w in ws if w is not exclude]
            if not ws:
                raise errors.QueueFullError("fleet has no live workers")
            tag = hashlib.blake2b(
                f"{curve}:{b.n}:{b.t}".encode(), digest_size=4
            ).digest()
            return ws[int.from_bytes(tag, "big") % len(ws)]

    def submit(self, req: dict) -> str:
        """Route one ceremony request (JSON-able dict of
        CeremonyRequest fields) to its bucket's worker.  Raises
        QueueFullError on shed/full (the HTTP 503 path) and ValueError
        on a malformed request.  A routed worker dying mid-submit gets
        ONE retry against the replacement or ring-next worker after a
        short backoff (``fleet_submit_retries_total``) before the 503."""
        with self._lock:
            if self._shedding:
                self.metrics.inc("fleet_shed_total")
                raise errors.QueueFullError(
                    "fleet is shedding load (SLO breach)"
                )
        try:
            curve, n, t = req["curve"], int(req["n"]), int(req["t"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ValueError(f"submit needs curve/n/t: {exc}") from exc
        if req.get("durable"):
            # fail fast at the front door with the scheduler's typed
            # messages — not deep in a worker after queueing
            if req.get("seed") is None:
                raise ValueError(
                    "durable ceremonies must be seeded: the journal "
                    "replays the seed, not the coefficients"
                )
            if not self.wal_root and not self._cfg["scheduler"].get("wal_dir"):
                raise ValueError(
                    "durable ceremony submitted but the fleet has no "
                    "journal root (DKG_TPU_FLEET_WAL_DIR / wal_root=)"
                )
        w = self._worker_for(curve, n, t)
        try:
            reply = w.call("submit", req=dict(req), timeout=self.op_timeout_s)
        except WorkerUnavailable as exc:
            self.metrics.inc("fleet_worker_errors_total")
            self.metrics.inc("fleet_submit_retries_total")
            self._reap_and_respawn()
            if self.submit_retry_backoff_s:
                time.sleep(self.submit_retry_backoff_s)
            w = self._worker_for(curve, n, t, exclude=w)
            try:
                reply = w.call(
                    "submit", req=dict(req), timeout=self.op_timeout_s
                )
            except WorkerUnavailable as exc2:
                self.metrics.inc("fleet_worker_errors_total")
                raise errors.QueueFullError(str(exc2)) from exc2
        if not reply.get("ok"):
            if reply.get("error") == "queue_full":
                self.metrics.inc("fleet_shed_total")
                raise errors.QueueFullError(reply.get("detail", "queue full"))
            raise ValueError(reply.get("detail") or reply.get("error", "submit failed"))
        cid = reply["cid"]
        with self._lock:
            self._placed[cid] = [w, False]
        return cid

    def _placed_worker(self, cid: str):
        with self._lock:
            entry = self._placed.get(cid)
            return entry[0] if entry is not None else None

    def _evict_placed(self, workers) -> None:
        """Drop placement entries for workers leaving the pool.  Caller
        holds ``self._lock``."""
        gone = set(map(id, workers))
        for cid in [c for c, e in self._placed.items() if id(e[0]) in gone]:
            del self._placed[cid]

    def poll(self, cid: str) -> str:
        """Status for ``cid`` — including the failover statuses:
        ``recovering`` while an orphan waits for its replacement
        worker, ``failed`` (from the tombstone) after quarantine."""
        with self._lock:
            tomb = self._tombstones.get(cid)
            if tomb is not None:
                return tomb["status"]
            orphan = cid in self._orphans
            entry = self._placed.get(cid)
        if orphan:
            self._try_adopt(cid, timeout=0.2)
            with self._lock:
                tomb = self._tombstones.get(cid)
                if tomb is not None:
                    return tomb["status"]
                if cid in self._orphans:
                    return "recovering"
                entry = self._placed.get(cid)
        w = entry[0] if entry is not None else None
        if w is None or not w.alive():
            self._reap_and_respawn()  # the death may orphan it right now
            with self._lock:
                if cid in self._orphans:
                    return "recovering"
                tomb = self._tombstones.get(cid)
                if tomb is not None:
                    return tomb["status"]
            return "unknown"
        try:
            reply = w.call("poll", cid=cid, timeout=self.op_timeout_s)
        except WorkerUnavailable:
            self.metrics.inc("fleet_worker_errors_total")
            self._reap_and_respawn()
            with self._lock:
                if cid in self._orphans:
                    return "recovering"
            return "unknown"
        return reply.get("status", "unknown") if reply.get("ok") else "unknown"

    def result(self, cid: str, timeout: float | None = None) -> dict:
        """Block for ``cid``'s outcome.  Orphaned placements wait for
        their replacement worker inside the same budget; a quarantined
        slot's tombstone is returned as the typed terminal outcome."""
        # the scheduler wait rides IN the message; the pipe budget is
        # strictly larger, so a slow ceremony surfaces as the worker's
        # clean TimeoutError reply, never a parent-side pipe timeout
        budget = timeout if timeout is not None else self.op_timeout_s
        deadline = time.monotonic() + budget
        first = True
        while True:
            with self._lock:
                tomb = self._tombstones.get(cid)
                if tomb is not None:
                    return dict(tomb)
                orphan = cid in self._orphans
                entry = self._placed.get(cid)
            if entry is None and not orphan:
                raise KeyError(f"unknown ceremony {cid!r}")
            w = entry[0] if entry is not None else None
            if orphan or w is None or not w.alive():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"ceremony {cid} still recovering after {budget}s"
                    )
                if orphan:
                    self._try_adopt(
                        cid, timeout=min(2.0, max(0.1, remaining))
                    )
                    time.sleep(0.05)
                else:
                    self._reap_and_respawn()
                continue
            wait_s = budget if first else max(
                0.1, deadline - time.monotonic()
            )
            first = False
            try:
                reply = w.call(
                    "result", cid=cid, wait_s=wait_s, timeout=wait_s + 10.0
                )
            except WorkerUnavailable as exc:
                self.metrics.inc("fleet_worker_errors_total")
                self._reap_and_respawn()
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"ceremony {cid}: worker lost mid-result ({exc})"
                    ) from exc
                continue
            if not reply.get("ok"):
                detail = reply.get("detail") or reply.get("error")
                if reply.get("error") == "TimeoutError":
                    raise TimeoutError(detail)
                raise errors.ServiceError(detail)
            with self._lock:
                entry = self._placed.get(cid)
                if entry is not None:
                    entry[1] = True
            return reply["outcome"]

    def sign(self, cid: str, msgs: list[bytes], **kw) -> list[bytes]:
        with self._lock:
            tomb = self._tombstones.get(cid)
            orphan = cid in self._orphans
        if tomb is not None:
            raise errors.FleetSlotQuarantined(tomb["error"])
        if orphan:
            self._try_adopt(cid, timeout=2.0)
            with self._lock:
                tomb = self._tombstones.get(cid)
                orphan = cid in self._orphans
            if tomb is not None:
                raise errors.FleetSlotQuarantined(tomb["error"])
            if orphan:
                raise errors.TransientEngineError(
                    f"ceremony {cid} is recovering on a replacement "
                    f"worker; retry"
                )
        w = self._placed_worker(cid)
        if w is None:
            raise KeyError(f"unknown ceremony {cid!r}")
        try:
            reply = w.call(
                "sign", cid=cid, msgs=[m.hex() for m in msgs],
                timeout=self.op_timeout_s, **kw,
            )
        except WorkerUnavailable as exc:
            self.metrics.inc("fleet_worker_errors_total")
            self._reap_and_respawn()
            raise errors.TransientEngineError(
                f"worker lost mid-sign for {cid}; retry after recovery: {exc}"
            ) from exc
        if not reply.get("ok"):
            raise errors.ServiceError(reply.get("detail") or reply.get("error"))
        return [bytes.fromhex(s) for s in reply["sigs"]]

    # -- observability + control plane ---------------------------------------

    def health(self) -> dict:
        with self._lock:
            ws = list(self._workers)
            shedding = self._shedding
        per = []
        for w in ws:
            if not w.alive():
                per.append({"worker": w.index, "ok": False, "alive": False})
                continue
            try:
                h = w.call(
                    "health",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                per.append(
                    {"worker": w.index, "alive": True, **h.get("health", {})}
                )
            except WorkerBusy:
                # pipe held by a long data-plane op: alive, just busy —
                # /healthz must answer now, not after that op drains
                per.append({"worker": w.index, "ok": True, "alive": True,
                            "busy": True})
            except WorkerUnavailable:
                per.append({"worker": w.index, "ok": False, "alive": False})
        alive = [p for p in per if p.get("alive")]
        return {
            "ok": bool(alive) and not shedding,
            "shedding": shedding,
            "workers_alive": len(alive),
            "workers_total": len(per),
            "workers": per,
        }

    def slo_report(self) -> dict:
        with self._lock:
            ws = self._alive()
        per = []
        for w in ws:
            try:
                r = w.call(
                    "slo",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                if r.get("ok"):
                    per.append({"worker": w.index, **r["slo"]})
            except WorkerUnavailable:  # includes WorkerBusy
                continue
        violations = [
            v for r in per for v in r.get("violations", ())
        ]
        return {
            "ok": all(r.get("ok", True) for r in per),
            "violations": violations,
            "workers": per,
        }

    def describe(self) -> dict:
        with self._lock:
            now = time.monotonic()
            slots = []
            for st in sorted(self._slots.values(), key=lambda s: s.slot):
                live = st.worker is not None and st.worker.alive()
                slots.append({
                    "slot": st.slot,
                    "state": (
                        "quarantined" if st.quarantined
                        else "live" if live
                        else "down"
                    ),
                    "deaths": len(st.deaths),
                    "respawn_in_s": (
                        max(0.0, st.respawn_at - now)
                        if not live and not st.quarantined
                        else 0.0
                    ),
                    "worker": st.worker.index if st.worker is not None else None,
                    "wal_dir": self._slot_wal_dir(st.slot),
                })
            return {
                "workers": len(self._workers),
                "alive": len(self._alive()),
                "k_min": self.k_min,
                "k_max": self.k_max,
                "shedding": self._shedding,
                "warmup_s": [w.warmup_s for w in self._workers],
                "placed": len(self._placed),
                "slots": slots,
                "orphans": len(self._orphans),
                "tombstones": len(self._tombstones),
                "quarantined": sum(
                    1 for st in self._slots.values() if st.quarantined
                ),
            }

    def _control_once(self) -> dict:
        """One SLO-driven control decision; called by the loop thread
        and directly by tests.  Returns the decision record."""
        # reap dead workers and respawn their slots under per-slot
        # backoff (never the old unconditional toward-k_min hot loop: a
        # worker dying at boot backs off and eventually quarantines
        # instead of spawn/reap spinning forever), then collect what
        # the replacements recovered from their slot journals
        self._reap_and_respawn()
        self._adopt_pending_manifests()
        with self._lock:
            ws = list(self._workers)
        reports, healths = [], []
        for w in ws:
            try:
                r = w.call(
                    "slo",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
                h = w.call(
                    "health",
                    timeout=_CONTROL_TIMEOUT_S,
                    lock_timeout=_BUSY_LOCK_TIMEOUT_S,
                )
            except WorkerUnavailable:  # includes WorkerBusy
                continue
            if r.get("ok"):
                reports.append(r["slo"])
            if h.get("ok"):
                healths.append(h["health"])
        breach = any(not r.get("ok", True) for r in reports)
        burn = 0.0
        for r in reports:
            err = r.get("errors") or {}
            burn = max(burn, float(err.get("burn") or 0.0))
        depth = sum(int(h.get("queue_depth", 0)) for h in healths)
        decision = "hold"
        with self._lock:
            alive = len(self._alive())
            if breach or burn > 1.0:
                self._shedding = True
                self._idle_rounds = 0
                if alive < self.k_max and not self._closing:
                    self._spawn()
                    decision = "up"
                    self.metrics.inc("fleet_scale_total", direction="up")
            else:
                self._shedding = False
                if depth == 0 and reports:
                    self._idle_rounds += 1
                else:
                    self._idle_rounds = 0
                victim = None
                if (
                    self._idle_rounds >= self.idle_rounds_down
                    and alive > self.k_min
                    and not self._closing
                ):
                    # drain only a worker whose completed-but-unfetched
                    # results nobody is still owed: stopping the process
                    # would lose them (poll -> unknown, result -> 409)
                    unfetched = {
                        id(e[0]) for e in self._placed.values() if not e[1]
                    }
                    for cand in reversed(self._workers):
                        if id(cand) not in unfetched:
                            victim = cand
                            break
                if victim is not None:
                    self._workers.remove(victim)
                    self._evict_placed([victim])
                    self._slots.pop(getattr(victim, "slot", None), None)
                    decision = "down"
                    self._idle_rounds = 0
                    self.metrics.inc("fleet_scale_total", direction="down")
            self.metrics.set_gauge("fleet_workers", len(self._workers))
            self.metrics.set_gauge("fleet_shedding", 1.0 if self._shedding else 0.0)
        if decision == "down":
            victim.stop(drain=True)
        return {
            "decision": decision,
            "shedding": self._shedding,
            "breach": breach,
            "burn": burn,
            "queue_depth": depth,
            "workers": len(ws),
        }

    def _control_loop(self) -> None:
        while not self._closing:
            time.sleep(self.control_interval_s)
            if self._closing:
                return
            try:
                self._control_once()
            except Exception:
                self.metrics.inc("fleet_control_errors_total")

    # -- HTTP front door -----------------------------------------------------

    def _route(self, method: str, path: str, query: dict, body):
        if method == "POST" and path == "/submit":
            self.metrics.inc("fleet_requests_total", route="submit")
            try:
                cid = self.submit(body or {})
                return 200, {"ceremony_id": cid}
            except errors.QueueFullError as exc:
                return 503, {"error": "unavailable", "detail": str(exc)}
            except (TypeError, ValueError) as exc:
                return 400, {"error": "bad request", "detail": str(exc)}
        if method == "GET" and path == "/poll":
            self.metrics.inc("fleet_requests_total", route="poll")
            cid = query.get("cid", "")
            return 200, {"ceremony_id": cid, "status": self.poll(cid)}
        if method == "GET" and path == "/result":
            self.metrics.inc("fleet_requests_total", route="result")
            cid = query.get("cid", "")
            try:
                timeout = float(query["timeout"]) if "timeout" in query else None
                return 200, self.result(cid, timeout=timeout)
            except KeyError:
                return 404, {"error": "unknown ceremony", "ceremony_id": cid}
            except TimeoutError as exc:
                return 504, {"error": "timeout", "detail": str(exc),
                             "ceremony_id": cid}
            except (RuntimeError, ValueError) as exc:
                return 409, {"error": str(exc), "ceremony_id": cid}
        if method == "POST" and path == "/sign":
            self.metrics.inc("fleet_requests_total", route="sign")
            body = body or {}
            cid = body.get("cid", "")
            try:
                msgs = [bytes.fromhex(m) for m in body.get("msgs", [])]
                sigs = self.sign(
                    cid, msgs,
                    prove=bool(body.get("prove", False)),
                    seed=body.get("seed"),
                )
                return 200, {
                    "ceremony_id": cid,
                    "signatures": [s.hex() for s in sigs],
                }
            except KeyError:
                return 404, {"error": "unknown ceremony", "ceremony_id": cid}
            except (RuntimeError, ValueError) as exc:
                return 409, {"error": str(exc), "ceremony_id": cid}
        if method == "GET" and path == "/fleet":
            self.metrics.inc("fleet_requests_total", route="fleet")
            return 200, self.describe()
        return None

    @property
    def port(self) -> int | None:
        return self._http.port if self._http is not None else None

    def close(self, drain: bool = True) -> None:
        self._closing = True
        if self._control_thread is not None:
            self._control_thread.join(
                timeout=(self.control_interval_s or 0) + 5.0
            )
        if self._http is not None:
            self._http.close()
        with self._lock:
            ws = list(self._workers)
            self._workers.clear()
            self._placed.clear()
            self._orphans.clear()
            self._tombstones.clear()
            self._slots.clear()
        for w in ws:
            w.stop(drain=drain)
