"""Warm execution lane: pad-and-mask ceremonies over shared runtime state.

One process serves MANY ceremonies, so everything shape- or
curve-dependent is shared and warm:

* fixed-base tables come from :mod:`dkg_tpu.groups.precompute` (one
  process-wide cache, persisted to disk) via :class:`WarmRuntime`, which
  additionally caches the per-``shared_string`` Pedersen commitment key
  and its ``h`` table;
* every request's ``(n, t)`` is padded to its :func:`~dkg_tpu.service.
  buckets.bucket_for` bucket, so all requests in a bucket reuse ONE set
  of jitted executables (the compile cache is keyed by static shape);
* same-bucket requests stack on a leading *ceremony axis* and run
  through vmapped twins of the round kernels (``_deal_stack`` etc.) —
  the kernels in dkg.ceremony are already array-shaped, so stacking is
  a natural lift that amortizes per-dispatch overhead across the convoy
  (the dominant cost for small committees on CPU/single-core hosts).

Bit-exactness: phantom lanes are zero-coefficient dealers (zero shares,
identity commitments) and every round-1 kernel is elementwise along the
dealer/ceremony axes, so a real lane's outputs — wire bytes included —
are bit-identical whether it runs unpadded, padded, or stacked
(tests/test_service.py oracle tests, both curves).  The Fiat-Shamir
randomizers ``rho`` DO differ between the padded and unpadded legs (the
transcript digest binds the padded tensors); that changes only which
random linear combination checks the same set of pair equations, never
the dealt values, the qualified set on honest runs, or the master key.

The start/finish split (:func:`start_convoy` / :func:`finish_convoy`)
generalizes ``hybrid_batch.seal_shares_pipeline``'s overlap trick to
whole ceremonies: ``start`` only *dispatches* device work (JAX dispatch
is asynchronous), so a scheduler worker can start convoy k+1 before
doing convoy k's host-side transcript/DEM work under the device's
dispatch shadow.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import random
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.commitment import CommitmentKey
from ..dkg import ceremony as ce
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..groups import precompute as gp
from . import aot, buckets
from .errors import PoisonedRequest

#: Default domain-separation string for service ceremonies (requests may
#: override; the commitment key h derives from it).
DEFAULT_SHARED_STRING = b"dkg-tpu-service"


@dataclasses.dataclass(frozen=True)
class CeremonyRequest:
    """One ceremony-as-a-service request.

    ``seed`` pins the coefficient stream (``random.Random(seed)``, drawn
    in exactly :class:`~dkg_tpu.dkg.ceremony.BatchedCeremony`'s order) so
    results are reproducible and WAL replay after a crash re-deals
    byte-identical polynomials; ``None`` uses ``random.SystemRandom``
    (non-durable requests only).  ``deadline_s`` is a relative budget
    from admission; a ceremony past its deadline is EXPIRED rather than
    started (and rather than *finished*, if it expires mid-flight).
    """

    curve: str
    n: int
    t: int
    shared_string: bytes = DEFAULT_SHARED_STRING
    seed: int | None = None
    rho_bits: int = 128
    deadline_s: float | None = None
    durable: bool = False
    tag: str = ""

    def bucket(self) -> buckets.Bucket:
        return buckets.bucket_for(self.n, self.t)

    def convoy_key(self) -> tuple:
        """Requests sharing this key may stack into one convoy: same
        curve, bucket, randomizer width and commitment key."""
        b = self.bucket()
        return (self.curve, b.n, b.t, self.rho_bits, self.shared_string)


def request_id(req: CeremonyRequest, seq: int = 0) -> str:
    """Deterministic short ceremony id: request identity + admission
    sequence number (submitting the same request twice is two
    ceremonies).  Mirrors obslog.ceremony_id_for's blake2b-48 shape."""
    h = hashlib.blake2b(digest_size=6)
    h.update(
        f"{req.curve}|{req.n}|{req.t}|{req.seed}|{req.rho_bits}|{seq}|".encode()
    )
    h.update(req.shared_string)
    return h.hexdigest()


@dataclasses.dataclass
class CeremonyOutcome:
    """Public result of one ceremony.  ``master`` is the canonical
    encoded master public key; ``final_shares`` (secret!) stays in
    process memory only — the durability journal persists everything
    here EXCEPT it (dkg_tpu.service.durable)."""

    ceremony_id: str
    status: str  # "done" | "failed"
    curve: str = ""
    n: int = 0
    t: int = 0
    bucket_n: int = 0
    bucket_t: int = 0
    master: bytes = b""
    qualified: tuple = ()
    complaints: tuple = ()
    error: str = ""
    #: engine wall-clock attributed to this ceremony: its convoy's
    #: runtime divided by the convoy width
    seconds: float = 0.0
    #: time.monotonic() stamp set by the scheduler when the outcome was
    #: recorded — lets clients compute queue-to-completion latency
    completed_at: float = 0.0
    #: epoch counter of the held sharing: 0 at the ceremony, +1 per
    #: completed refresh/reshare against this outcome (the scheduler's
    #: epoch methods CAS on it).  ``master`` never changes with it.
    epoch: int = 0
    final_shares: np.ndarray | None = dataclasses.field(
        default=None, repr=False, compare=False
    )


class WarmRuntime:
    """Shared warm state for all ceremonies in a process: fixed-base
    tables (via groups.precompute's process+disk cache) and per
    ``(curve, shared_string)`` commitment keys.  Thread-safe; every
    scheduler worker holds one reference."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ck: dict = {}

    def commitment(self, curve: str, shared_string: bytes):
        """(CommitmentKey, g_table, h_table) for a ceremony environment,
        cached.  The g table is shared curve-wide; h derives from the
        shared string."""
        key = (curve, shared_string)
        with self._lock:
            hit = self._ck.get(key)
        if hit is not None:
            return hit
        cs = gd.ALL_CURVES[curve]
        group = gh.ALL_GROUPS[curve]
        ck = CommitmentKey.generate(group, shared_string)
        # precompute has its own build-once lock; taking self._lock over
        # these (multi-second, possibly-compiling) builds would serialize
        # unrelated curves behind one warmer
        g_table = gp.generator_table(cs)
        h_table = gp.base_table(cs, ck.h)
        entry = (ck, g_table, h_table)
        with self._lock:
            self._ck.setdefault(key, entry)
        return entry

    def warmup(self, req: CeremonyRequest, widths: tuple = (1,)) -> None:
        """Compile the request's bucket programs ahead of traffic by
        running one throwaway convoy per width (results discarded).

        With the AOT store enabled (``DKG_TPU_AOT_DIR``), prebaked
        executables deserialize into the process instead: the largest
        requested width — the steady convoy shape — gets its
        deal/verify pair preloaded eagerly, and any width whose deal
        program is on disk skips its throwaway convoy entirely, leaving
        the long tail (finalise, straggler widths, sign rungs) to lazy
        dispatch-time loads.  Loads are seconds, compiles are minutes:
        on a one-core host the store deserializes at ~5 MB/s, so eager
        preloading everything would itself blow the warmup budget.  A
        width missing from the store still runs its convoy (and, via
        the dispatch seams, persists its executables for the next
        process)."""
        b = req.bucket()
        if aot.enabled():
            # tables + commitment key first: convoy-free warmup must
            # leave the runtime as ready as the compiling path does
            self.commitment(req.curve, req.shared_string)
            w_hot = max(widths)
            aot.preload_prefixes(
                [
                    ("deal", req.curve, b.n, b.t, w_hot),
                    ("verify", req.curve, b.n, b.t, w_hot),
                ]
            )
        for w in widths:
            if aot.enabled() and aot.disk_has_prefix(
                ("deal", req.curve, b.n, b.t, w)
            ):
                continue
            reqs = [
                dataclasses.replace(req, seed=(req.seed or 0) + i)
                for i in range(w)
            ]
            finish_convoy(self, start_convoy(self, reqs))


# ---------------------------------------------------------------------------
# AOT executable dispatch
# ---------------------------------------------------------------------------


def _specs(args: tuple) -> tuple:
    return tuple(
        jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(np.shape(leaf), leaf.dtype), a
        )
        for a in args
    )


def _aot_dispatch(key_prefix: tuple, args: tuple, lower, fallback):
    """Serve one program dispatch from the AOT executable store when
    it is enabled, else the ordinary jitted twin.  ``lower`` maps a
    tuple of ShapeDtypeStruct specs to a ``jax.stages.Lowered`` (statics
    baked in); the compiled result is persisted for every later process.
    A store failure of any kind degrades to ``fallback`` — a request
    must never die on a cache problem."""
    if not aot.enabled():
        return fallback()
    try:
        key = key_prefix + (aot.spec_sig(args),)
        fn = aot.get_or_build(key, lambda: lower(_specs(args)).compile())
        return fn(*args)
    except Exception:
        aot.note_error()
        return fallback()


def aot_sign_folded(curve: str, sigma_limbs: np.ndarray, h_dev):
    """AOT twin of :func:`dkg_tpu.sign.partial.sign_folded`: same
    broadcast semantics, same raw device result (pure uint32 limb math,
    so the serialized ladder is bit-identical to the jit path), but the
    rung executable comes from the store — a fresh worker's first sign
    flush skips the ladder compile."""
    from .. import sign as signing

    if not aot.enabled():
        return signing.sign_folded(curve, sigma_limbs, h_dev)
    cs = gd.ALL_CURVES[curve]
    hh = jnp.asarray(h_dev)
    kk = jnp.asarray(sigma_limbs)
    if kk.ndim == 1:
        kk = jnp.broadcast_to(kk[None, :], (hh.shape[0], kk.shape[-1]))
    args = (kk, hh)
    return _aot_dispatch(
        ("sign_folded", curve, int(hh.shape[0])),
        args,
        lambda sp: _sign_ladder.lower(cs, *sp),
        lambda: signing.sign_folded(curve, sigma_limbs, h_dev),
    )


@functools.partial(jax.jit, static_argnums=0)
def _sign_ladder(cs, kk, hh):
    """Traced twin of the steady lane's folded ladder (scalar_mul's
    eager entry inlines its core under trace; rung batches are already
    power-of-two so the eager pad is a no-op)."""
    return gd.scalar_mul(cs, kk, hh)


# ---------------------------------------------------------------------------
# stacked (ceremony-axis) twins of the round kernels
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=0)
def _deal_stack(cfg, coeffs_a, coeffs_b, g_table, h_table):
    """(k, n, t+1, L) coefficient stacks -> stacked round-1 tensors."""

    def one(ca, cb):
        return ce.deal(cfg, ca, cb, g_table, h_table)

    return jax.vmap(one)(coeffs_a, coeffs_b)


@functools.partial(jax.jit, static_argnums=(0, 5))
def _verify_stack(cfg, e_comm, shares, hidings, rho, rho_bits, g_table, h_table):
    def one(e1, s1, r1, rho1):
        return ce.verify_batch(cfg, e1, s1, r1, rho1, rho_bits, g_table, h_table)

    return jax.vmap(one)(e_comm, shares, hidings, rho)


@functools.partial(jax.jit, static_argnums=0)
def _finalise_stack(cfg, a_comm, shares, qualified):
    def one(a1, s1, q1):
        return (
            ce.aggregate_shares(cfg, s1, q1),
            ce.master_key_from_bare(cfg, a1, q1),
        )

    return jax.vmap(one)(a_comm, shares, qualified)


# ---------------------------------------------------------------------------
# coefficient drawing + padding
# ---------------------------------------------------------------------------


def draw_coeffs(cfg: ce.CeremonyConfig, rng) -> tuple[np.ndarray, np.ndarray]:
    """The REAL coefficient tensors, drawn in exactly
    :class:`~dkg_tpu.dkg.ceremony.BatchedCeremony`'s order so a seeded
    service ceremony and a fresh single-ceremony run of the same seed
    deal byte-identical polynomials."""
    fs = cfg.cs.scalar
    n, t = cfg.n, cfg.t
    a = fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(n)])
    b = fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(n)])
    return a, b


def pad_coeffs(coeffs: np.ndarray, n_pad: int, t_pad: int) -> np.ndarray:
    """Zero-pad a real ``(n, t+1, L)`` coefficient tensor to the bucket
    shape ``(n_pad, t_pad+1, L)``: phantom dealers are all-zero
    polynomials, real dealers gain zero high-order coefficients — both
    inert under the pad-and-mask contract."""
    n, tc, limbs = coeffs.shape
    out = np.zeros((n_pad, t_pad + 1, limbs), np.uint32)
    out[:n, :tc] = coeffs
    return out


def rng_for(req: CeremonyRequest):
    if req.seed is None:
        return random.SystemRandom()
    return random.Random(req.seed)


def derive_rho_convoy(
    cfg: ce.CeremonyConfig, a, e, s, r, rho_bits: int
) -> np.ndarray:
    """Per-ceremony Fiat-Shamir randomizers for a whole convoy, (k, n,
    L) — bit-identical to calling :func:`dkg_tpu.dkg.ceremony.
    derive_rho` on each ceremony's slice.

    The transcript row digests are per-dealer and row-independent, so
    the convoy's (k, n, ...) tensors fold into ONE (k*n, ...) row-digest
    pass — one dispatch per tensor family instead of 3*k — and only the
    outer fold (3 small arrays through one blake2b) stays per ceremony.
    This is the digest's share of the dispatch amortization that makes
    the stacked lane pay: per-ceremony digest calls were ~40% of a small
    convoy's wall clock.
    """
    k, n = s.shape[0], s.shape[1]
    if k == 1:
        return ce.derive_rho(cfg, a[0], e[0], s[0], r[0], rho_bits)[None]
    rows_a, rows_e, rows_sr = ce._dealer_rows_device(
        cfg,
        np.reshape(a, (k * n,) + a.shape[2:]),
        np.reshape(e, (k * n,) + e.shape[2:]),
        np.reshape(s, (k * n,) + s.shape[2:]),
        np.reshape(r, (k * n,) + r.shape[2:]),
    )
    rows_a = np.asarray(rows_a).reshape(k, n, -1)
    rows_e = np.asarray(rows_e).reshape(k, n, -1)
    rows_sr = np.asarray(rows_sr).reshape(k, n, -1)
    return np.stack(
        [
            ce.fiat_shamir_rho(
                cfg,
                ce._fold_digest_device(cfg, rows_a[i], rows_e[i], rows_sr[i]),
                rho_bits,
            )
            for i in range(k)
        ]
    )


# ---------------------------------------------------------------------------
# convoy execution: start (device dispatch) / finish (host + device tail)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class InFlight:
    """A dispatched convoy: device round-1 tensors not yet consumed."""

    reqs: list
    ids: list
    cfg_pad: ce.CeremonyConfig
    g_table: jax.Array
    h_table: jax.Array
    a: jax.Array  # (k, n_pad, t_pad+1, C, L)
    e: jax.Array
    s: jax.Array  # (k, n_pad, n_pad, L)
    r: jax.Array


def start_convoy(
    runtime: WarmRuntime, reqs: list, ids: list | None = None
) -> InFlight:
    """Draw + pad coefficients for a same-key convoy and *dispatch* the
    stacked deal.  Returns without blocking on device work (width-1
    convoys reuse the plain :func:`dkg_tpu.dkg.ceremony.deal`
    executable; wider convoys use the vmapped twin)."""
    key = reqs[0].convoy_key()
    if any(r.convoy_key() != key for r in reqs):
        raise ValueError("start_convoy: mixed convoy keys")
    req0 = reqs[0]
    b = req0.bucket()
    cfg_pad = ce.CeremonyConfig(req0.curve, req0.n, req0.t).padded(b.n, b.t)
    _, g_table, h_table = runtime.commitment(req0.curve, req0.shared_string)
    ca, cb = [], []
    for req in reqs:
        cfg_real = ce.CeremonyConfig(req.curve, req.n, req.t)
        a_real, b_real = draw_coeffs(cfg_real, rng_for(req))
        ca.append(pad_coeffs(a_real, b.n, b.t))
        cb.append(pad_coeffs(b_real, b.n, b.t))
    if len(reqs) == 1:
        args = (jnp.asarray(ca[0]), jnp.asarray(cb[0]), g_table, h_table)
        a, e, s, r = _aot_dispatch(
            ("deal", req0.curve, b.n, b.t, 1, 0),
            args,
            lambda sp: ce.deal.lower(cfg_pad, *sp),
            lambda: ce.deal(cfg_pad, *args),
        )
        a, e, s, r = a[None], e[None], s[None], r[None]
    else:
        args = (
            jnp.asarray(np.stack(ca)), jnp.asarray(np.stack(cb)),
            g_table, h_table,
        )
        a, e, s, r = _aot_dispatch(
            ("deal", req0.curve, b.n, b.t, len(reqs), 0),
            args,
            lambda sp: _deal_stack.lower(cfg_pad, *sp),
            lambda: _deal_stack(cfg_pad, *args),
        )
    if ids is None:
        ids = [request_id(req, i) for i, req in enumerate(reqs)]
    return InFlight(list(reqs), list(ids), cfg_pad, g_table, h_table, a, e, s, r)


def finish_convoy(runtime: WarmRuntime, fl: InFlight) -> list[CeremonyOutcome]:
    """Host transcript work + stacked verify/finalise for a dispatched
    convoy.  The first ``np.asarray`` blocks on the deal dispatched by
    :func:`start_convoy` — everything before this call overlaps it."""
    del runtime  # tables travel on the InFlight
    cfg_pad = fl.cfg_pad
    k = len(fl.reqs)
    n_pad = cfg_pad.n
    rho_bits = fl.reqs[0].rho_bits
    a_h, e_h = np.asarray(fl.a), np.asarray(fl.e)
    s_h, r_h = np.asarray(fl.s), np.asarray(fl.r)
    rho = derive_rho_convoy(cfg_pad, a_h, e_h, s_h, r_h, rho_bits)
    curve = fl.reqs[0].curve
    if k == 1:
        args = (
            fl.e[0], fl.s[0], fl.r[0], jnp.asarray(rho[0]),
            fl.g_table, fl.h_table,
        )
        ok = _aot_dispatch(
            ("verify", curve, n_pad, cfg_pad.t, 1, rho_bits),
            args,
            lambda sp: ce.verify_batch.lower(
                cfg_pad, sp[0], sp[1], sp[2], sp[3], rho_bits, sp[4], sp[5]
            ),
            lambda: ce.verify_batch(
                cfg_pad, args[0], args[1], args[2], args[3], rho_bits,
                args[4], args[5],
            ),
        )[None]
    else:
        args = (fl.e, fl.s, fl.r, jnp.asarray(rho), fl.g_table, fl.h_table)
        ok = _aot_dispatch(
            ("verify", curve, n_pad, cfg_pad.t, k, rho_bits),
            args,
            lambda sp: _verify_stack.lower(
                cfg_pad, sp[0], sp[1], sp[2], sp[3], rho_bits, sp[4], sp[5]
            ),
            lambda: _verify_stack(
                cfg_pad, args[0], args[1], args[2], args[3], rho_bits,
                args[4], args[5],
            ),
        )
    ok_h = np.asarray(ok)

    qualified = np.zeros((k, n_pad), bool)
    complaints: list[list[tuple[int, int]]] = [[] for _ in range(k)]
    errors: list[str] = [""] * k
    for i, req in enumerate(fl.reqs):
        qualified[i, : req.n] = True
        if not ok_h[i, : req.n].all():
            # rare blame path, per ceremony: the engine holds the
            # plaintext share matrix, so re-checking IS adjudication
            # (mirrors BatchedCeremony.run)
            pw = np.asarray(
                ce.verify_pairwise(
                    cfg_pad, fl.e[i], fl.s[i], fl.r[i], fl.g_table, fl.h_table
                )
            )[: req.n, : req.n]
            guilty = ~pw.all(axis=1)
            complaints[i] = [
                (int(rcp) + 1, int(dlr) + 1) for dlr, rcp in zip(*np.nonzero(~pw))
            ]
            qualified[i, : req.n] = ~guilty
            if int(guilty.sum()) > req.t:
                errors[i] = "MISBEHAVIOUR_HIGHER_THRESHOLD"

    if k == 1:
        # width-1 lanes reuse the plain executables (shared with
        # BatchedCeremony and the rest of the suite's compile cache)
        q0 = jnp.asarray(qualified[0])
        final_shares = _aot_dispatch(
            ("aggregate", curve, n_pad, cfg_pad.t, 1, 0),
            (fl.s[0], q0),
            lambda sp: ce.aggregate_shares.lower(cfg_pad, *sp),
            lambda: ce.aggregate_shares(cfg_pad, fl.s[0], q0),
        )[None]
        master = _aot_dispatch(
            ("master", curve, n_pad, cfg_pad.t, 1, 0),
            (fl.a[0], q0),
            lambda sp: ce.master_key_from_bare.lower(cfg_pad, *sp),
            lambda: ce.master_key_from_bare(cfg_pad, fl.a[0], q0),
        )[None]
    else:
        qd = jnp.asarray(qualified)
        final_shares, master = _aot_dispatch(
            ("finalise", curve, n_pad, cfg_pad.t, k, 0),
            (fl.a, fl.s, qd),
            lambda sp: _finalise_stack.lower(cfg_pad, *sp),
            lambda: _finalise_stack(cfg_pad, fl.a, fl.s, qd),
        )
    shares_h = np.asarray(final_shares)
    master_enc = gd.encode_batch(cfg_pad.cs, np.asarray(master))

    out = []
    for i, req in enumerate(fl.reqs):
        failed = bool(errors[i])
        out.append(
            CeremonyOutcome(
                ceremony_id=fl.ids[i],
                status="failed" if failed else "done",
                curve=req.curve,
                n=req.n,
                t=req.t,
                bucket_n=cfg_pad.n,
                bucket_t=cfg_pad.t,
                master=b"" if failed else master_enc[i].tobytes(),
                qualified=tuple(bool(q) for q in qualified[i, : req.n]),
                complaints=tuple(complaints[i]),
                error=errors[i],
                final_shares=None if failed else shares_h[i, : req.n],
            )
        )
    return out


def run_convoy(runtime: WarmRuntime, reqs: list) -> list[CeremonyOutcome]:
    """start + finish in one call (the unpipelined entry point)."""
    return finish_convoy(runtime, start_convoy(runtime, reqs))


def run_single_reference(req: CeremonyRequest) -> bytes:
    """A FRESH unpadded single-ceremony run of ``req`` (the oracle the
    service legs are compared against): BatchedCeremony with the same
    seeded rng, master key canonically encoded."""
    c = ce.BatchedCeremony(
        req.curve, req.n, req.t, req.shared_string, rng_for(req)
    )
    out = c.run(rho_bits=req.rho_bits)
    if "master" not in out:
        raise PoisonedRequest(f"reference ceremony failed: {out.get('error')}")
    cs = c.cfg.cs
    return gd.encode_batch(cs, np.asarray(out["master"])[None])[0].tobytes()


# ---------------------------------------------------------------------------
# wire-format leg (padded KEM/DEM, real-lane slice)
# ---------------------------------------------------------------------------


def wire_broadcasts(
    runtime: WarmRuntime,
    req: CeremonyRequest,
    fl: InFlight,
    lane: int,
    pks: list,
    rng_enc,
) -> list[bytes]:
    """Wire-format ``BroadcastPhase1`` bytes for one convoy lane, sealed
    to the ``req.n`` recipient communication keys ``pks``.

    The KEM runs at the BUCKET shape so it shares executables with every
    other ceremony in the bucket: encryption randomness is drawn for the
    real ``(n, n)`` block (same draw order as the unpadded leg) and
    padded with ones, phantom recipient keys with the generator — then
    the real sub-block of the sealed output is packaged.  Byte-identical
    to the unpadded ``seal_shares_pipeline`` leg (oracle test)."""
    from ..dkg.hybrid_batch import broadcasts_from_batch, seal_shares_pipeline
    from ..utils import serde

    cfg_pad = fl.cfg_pad
    cs = cfg_pad.cs
    fs = cs.scalar
    group = gh.ALL_GROUPS[req.curve]
    n, n_pad = req.n, cfg_pad.n
    r_real = fh.encode(
        fs, [[fs.rand_int(rng_enc) for _ in range(n)] for _ in range(n)]
    )
    r_pad = np.zeros((n_pad, n_pad, fs.limbs), np.uint32)
    r_pad[..., 0] = 1  # phantom lanes: r=1 (a zero KEM scalar has no inverse)
    r_pad[:n, :n] = r_real
    pks_dev = gd.from_host(cs, list(pks) + [group.generator()] * (n_pad - n))
    sealed = seal_shares_pipeline(
        group, cfg_pad, np.asarray(fl.s[lane]), np.asarray(fl.r[lane]),
        pks_dev, jnp.asarray(r_pad), fl.g_table,
    )
    real_rows = [row[:n] for row in sealed[:n]]
    # slice the coefficient axis too: a real dealer's padded high
    # coefficients are commitments to zero (identity points) that the
    # unpadded wire message does not carry
    bcasts = broadcasts_from_batch(
        group, cfg_pad, np.asarray(fl.e[lane])[:n, : req.t + 1], real_rows
    )
    return [serde.encode_phase1(group, b) for b in bcasts]
