"""Typed service/signing error taxonomy (lint DKG010).

The serving path must never amplify a single fault into an opaque
``RuntimeError`` that callers cannot classify: the scheduler's retry /
bisect / quarantine machinery branches on *what kind* of failure it is
looking at, and an HTTP front-end maps each type to a distinct status
code.  Lint rule DKG010 (scripts/lint_lite.py) therefore bans bare
``raise RuntimeError`` in ``dkg_tpu/service/`` and ``dkg_tpu/sign/`` —
everything raised there is one of these.

Taxonomy:

* :class:`TransientEngineError` — the ONLY class the scheduler retries.
  A fault is transient exactly when the raiser says so (device resets,
  injected chaos); arbitrary exceptions are never *guessed* transient,
  because retrying a poisoned request just re-poisons the convoy.
* :class:`PoisonedRequest` — a request that fails on its own at width
  1: bisection has excluded convoy-mates as the cause.  Surfaced as the
  ``poisoned`` terminal status (the outcome's ``error`` names this
  type), and raised directly by single-request paths.
* :class:`InsufficientSigners` — signing cannot reach a t+1 quorum of
  honest qualified signers (quarantine ate the margin).  Subclasses
  ``ValueError`` too: the pre-quarantine precondition check raised
  ValueError, and existing catch sites keep working.
* :class:`QueueFullError` — admission backpressure (HTTP 503); lives
  here with the rest of the taxonomy, re-exported by
  ``service.scheduler`` where it historically lived.
* :class:`FleetSlotQuarantined` — a fleet worker slot crash-looped past
  ``DKG_TPU_FLEET_RESPAWN_MAX`` deaths inside its window and was
  quarantined; every placement it held is terminal-failed with this
  type's name in the outcome error (the fleet-level mirror of
  ``PoisonedRequest``'s replay limit).
"""

from __future__ import annotations


class ServiceError(RuntimeError):
    """Base of every typed serving-path error.  Subclasses RuntimeError
    so pre-taxonomy catch sites (``except RuntimeError``) still work."""


class QueueFullError(ServiceError):
    """Admission queue at capacity — the caller should back off and
    retry (HTTP 503).  Raised instead of blocking: a DKG client can
    retry cheaply, while an unbounded queue turns overload into
    unbounded latency for everyone already queued."""


class TransientEngineError(ServiceError):
    """An engine fault the raiser asserts is worth retrying (the whole
    convoy re-runs, bounded by ``DKG_TPU_SERVICE_RETRIES`` with
    exponential backoff).  Nothing else is retried: transiency is a
    claim only the fault's origin can make."""


class PoisonedRequest(ServiceError):
    """A request that fails deterministically on its own — convoy
    bisection has run it at width 1, so healthy convoy-mates are
    exonerated.  Its outcome is terminal status ``poisoned``; retrying
    it anywhere (including journal replay, see
    ``DKG_TPU_SERVICE_MAX_REPLAYS``) is wasted work."""


class FleetSlotQuarantined(ServiceError):
    """A fleet worker slot died too many times within its crash-loop
    window (``DKG_TPU_FLEET_RESPAWN_MAX`` / ``.._WINDOW_S``) and was
    quarantined: no further respawns, and every ceremony placed on it
    gets a typed terminal outcome naming this class.  Retrying the same
    submission elsewhere is the caller's call — the fleet will not
    silently re-run work a crash-looping slot may have half-done."""


class InsufficientSigners(ServiceError, ValueError):
    """Fewer than t+1 honest qualified signers remain for a ceremony —
    either the qualification set was too small to begin with, or signer
    quarantine (Byzantine partials caught by RLC blame) consumed the
    substitution margin.  ValueError subclass for backward
    compatibility with the pre-quarantine precondition error."""
