"""AOT-serialized executable store: kill the per-process cold start.

A fresh serving process pays minutes of XLA compiles before its first
ceremony (FLEET_r01: 222.6s of warmup) even though every hot program is
static per (curve, bucket shape, convoy width, sign rung).  This module
persists the *compiled executables themselves* — lowered + compiled once
via ``jax.jit(...).lower(specs).compile()``, serialized with
:mod:`jax.experimental.serialize_executable` — beside the fixed-base
table cache, exactly on :mod:`dkg_tpu.groups.precompute`'s store
contract:

* process-level cache first (RLock-guarded dict), then a validated disk
  load, then build-and-persist;
* atomic writes (``mkstemp`` + ``os.replace``) so concurrent worker
  processes never observe a torn file;
* every artifact carries a BLAKE2b digest over a header binding the
  format version, jax/jaxlib versions, backend, knob tier and the full
  program key — corruption, truncation or version skew all fail the
  digest check and fall through to a silent rebuild (counted in
  :func:`stats`), never a crash and never a stale program.

The store is OFF unless ``DKG_TPU_AOT_DIR`` is set (the engine then
dispatches through its jitted twins exactly as before): XLA:CPU's
*compilation-cache* writer has corrupted entries on some images
(tests/conftest.py), so opting into executable persistence is an
explicit deployment decision.  ``serialize_executable`` takes a
different path (PjRt executable serialize + pickle) and round-trips this
package's large CPU executables bit-identically, but the loaded blob is
a pickle: the digest check guards *integrity*, not *trust* — point
``DKG_TPU_AOT_DIR`` only at a directory you would also trust as a JAX
compilation cache.

Key shape: ``(kind, curve, n, t, width, rho_bits, specsig)`` for
ceremony programs, ``("sign_folded", curve, rung, specsig)`` for the
steady sign lane's folded ladder rungs — ``specsig`` pins every operand
shape/dtype (tables included, so a fixed-base window change keys new
artifacts).  :func:`preload` deserializes every valid artifact in the
store into the process cache so a fresh worker warms in seconds;
:func:`has_prefix` lets :meth:`WarmRuntime.warmup
<dkg_tpu.service.engine.WarmRuntime.warmup>` skip its throwaway convoy
when a bucket's programs are already resident.
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
import tempfile
import threading
import time

import jax
import numpy as np
from jax.experimental import serialize_executable as _se

from ..utils import envknobs
from ..utils.metrics import REGISTRY

#: Bump when the artifact layout changes; old files fail the digest
#: check and silently rebuild.
_FORMAT_VERSION = 1

#: Knobs that change the traced program at fixed shapes: two processes
#: with different tiers must never serve each other's executables, so
#: the resolved tier string is bound into every artifact digest.
_TIER_KNOBS = (
    "DKG_TPU_REDUCE",
    "DKG_TPU_CARRY",
    "DKG_TPU_MUL",
    "DKG_TPU_MXU",
    "DKG_TPU_PALLAS",
    "DKG_TPU_FUSED_MULTI",
    "DKG_TPU_ED_FUSED_LADDER",
    "DKG_TPU_ED_FUSED_DOUBLES",
    "DKG_TPU_MSM",
    "DKG_TPU_FB_WINDOW",
    "DKG_TPU_DIGEST",
    "DKG_TPU_DEAL_CHUNK",
    "DKG_TPU_VERIFY_CHUNK",
    "DKG_TPU_RLC",
    "DKG_TPU_RLC_CHUNK",
    "DKG_TPU_DEM",
    "DKG_TPU_DEM_CHUNK",
)

_LOCK = threading.RLock()
#: Per-key build/load locks: the global lock only guards the maps, so a
#: minutes-long XLA compile for one program never stalls an unrelated
#: key's lookup (e.g. the steady sign lane behind a ceremony build).
_KEY_LOCKS: dict[tuple, threading.Lock] = {}
_PROC: dict[tuple, object] = {}
_STATS = {
    "builds": 0,
    "build_s": 0.0,
    "disk_loads": 0,
    "load_s": 0.0,
    "disk_rejects": 0,
    "proc_hits": 0,
    "errors": 0,
}
_PRELOADED = False
#: Lazy {key: path} disk index (``_scan_disk``); None until first scan.
_DISK: dict | None = None


def enabled() -> bool:
    """True when the store is active (``DKG_TPU_AOT_DIR`` set)."""
    return envknobs.string("DKG_TPU_AOT_DIR", "AOT executable store directory") is not None


def cache_dir() -> str:
    """The artifact directory: ``DKG_TPU_AOT_DIR``, else beside the JAX
    compilation cache, else the system temp dir (mirrors
    precompute.cache_dir so the two stores land together)."""
    override = envknobs.string("DKG_TPU_AOT_DIR", "AOT executable store directory")
    if override:
        return override
    base = jax.config.jax_compilation_cache_dir or tempfile.gettempdir()
    return os.path.join(base, "dkg_tpu_aot_store")


def knob_tier() -> str:
    """Canonical ``k=v`` string of every set program-shaping knob."""
    parts = []
    for name in _TIER_KNOBS:
        v = envknobs.string(name, "program-shaping knob (AOT tier)")
        if v is not None:
            parts.append(f"{name}={v}")
    return ",".join(parts)


def spec_sig(args: tuple) -> tuple:
    """Shape/dtype signature of a tuple of (pytree) operands — part of
    every key, so executables are only ever served to calls with the
    exact operand layout they were compiled for."""
    out = []
    for a in args:
        for leaf in jax.tree_util.tree_leaves(a):
            out.append((tuple(np.shape(leaf)), str(leaf.dtype)))
    return tuple(out)


def _header(key: tuple) -> bytes:
    import jaxlib

    return (
        f"aot|{_FORMAT_VERSION}|{jax.__version__}|{jaxlib.__version__}|"
        f"{jax.default_backend()}|{knob_tier()}|{key!r}"
    ).encode()


def _digest(header: bytes, blob: bytes) -> bytes:
    h = hashlib.blake2b(digest_size=32)
    h.update(header)
    h.update(blob)
    return h.digest()


def _path(key: tuple) -> str:
    tag = hashlib.blake2b(repr(key).encode(), digest_size=8).hexdigest()
    return os.path.join(cache_dir(), f"aot_v{_FORMAT_VERSION}_{key[0]}_{tag}.npz")


def _load_blob(path: str, key: tuple):
    """Deserialize one artifact; None on ANY failure (missing, torn,
    digest mismatch, version skew, unloadable executable)."""
    t0 = time.perf_counter()
    try:
        with np.load(path, allow_pickle=False) as z:
            blob = z["blob"].tobytes()
            digest = z["digest"].tobytes()
            stored_key = z["key"].tobytes().decode()
        if stored_key != repr(key):
            raise ValueError("key mismatch")
        if digest != _digest(_header(key), blob):
            raise ValueError("digest mismatch")
        fn = _se.deserialize_and_load(*pickle.loads(blob))
    except FileNotFoundError:
        return None
    except Exception:
        with _LOCK:  # may run outside the global lock (get_or_build)
            _STATS["disk_rejects"] += 1
        REGISTRY.inc("aot_disk_rejects_total")
        return None
    dt = time.perf_counter() - t0
    with _LOCK:
        _STATS["disk_loads"] += 1
        _STATS["load_s"] += dt
    REGISTRY.inc("aot_disk_loads_total")
    REGISTRY.observe("aot_load_seconds", dt)
    return fn


def _persist(path: str, key: tuple, blob: bytes) -> None:
    """Atomic npz write; an unwritable store degrades silently (the
    freshly compiled executable still serves this process)."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), suffix=".tmp.npz"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(
                    f,
                    blob=np.frombuffer(blob, np.uint8),
                    digest=np.frombuffer(_digest(_header(key), blob), np.uint8),
                    key=np.frombuffer(repr(key).encode(), np.uint8),
                )
            os.replace(tmp, path)
            with _LOCK:
                if _DISK is not None:
                    _DISK[key] = path
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except OSError:
        pass


def get_or_build(key: tuple, build):
    """The store's one lookup: process cache -> validated disk load ->
    ``build()`` (a thunk returning a ``jax.stages.Compiled``) + persist.
    Returns a loaded executable callable with the program's dynamic
    (non-static) operands."""
    with _LOCK:
        hit = _PROC.get(key)
        if hit is not None:
            _STATS["proc_hits"] += 1
            return hit
        klock = _KEY_LOCKS.setdefault(key, threading.Lock())
    # the slow path (deserialize or compile) runs under the KEY's lock
    # only: concurrent lookups of other keys proceed, concurrent
    # lookups of this key wait and then hit the cache
    with klock:
        with _LOCK:
            hit = _PROC.get(key)
            if hit is not None:
                _STATS["proc_hits"] += 1
                return hit
        path = _path(key)
        fn = _load_blob(path, key)
        if fn is None:
            t0 = time.perf_counter()
            fn = build()
            dt = time.perf_counter() - t0
            with _LOCK:
                _STATS["builds"] += 1
                _STATS["build_s"] += dt
            REGISTRY.inc("aot_builds_total")
            REGISTRY.observe("aot_build_seconds", dt)
            try:
                blob = pickle.dumps(_se.serialize(fn), protocol=4)
                _persist(path, key, blob)
            except Exception:
                # some backends can't serialize; the compiled program
                # still serves this process
                with _LOCK:
                    _STATS["errors"] += 1
                REGISTRY.inc("aot_errors_total")
        with _LOCK:
            _PROC[key] = fn
        return fn


def _scan_disk() -> dict:
    """{key: path} of every parseable artifact in the store (one cheap
    directory scan; only the small ``key`` member of each npz is read,
    never the executable blob).  Cached per process; :func:`_persist`
    keeps it current for this process's own writes."""
    global _DISK
    with _LOCK:
        if _DISK is not None:
            return _DISK
        disk: dict = {}
        try:
            names = sorted(os.listdir(cache_dir()))
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("aot_v") and name.endswith(".npz")):
                continue
            path = os.path.join(cache_dir(), name)
            try:
                with np.load(path, allow_pickle=False) as z:
                    key = ast.literal_eval(z["key"].tobytes().decode())
            except Exception:
                _STATS["disk_rejects"] += 1
                REGISTRY.inc("aot_disk_rejects_total")
                continue
            if isinstance(key, tuple) and key and isinstance(key[0], str):
                disk[key] = path
        _DISK = disk
        return disk


def disk_has_prefix(prefix: tuple) -> bool:
    """True when the store holds an artifact whose key starts with
    ``prefix`` — resident or not.  Lets warmup skip its throwaway
    convoy (the compile) while leaving the deserialize to first
    dispatch (lazy loads are seconds; compiles are minutes)."""
    if has_prefix(prefix):
        return True
    return any(k[: len(prefix)] == prefix for k in _scan_disk())


def preload_prefixes(prefixes) -> int:
    """Deserialize only the artifacts matching ``prefixes`` into the
    process cache — the warmup path's targeted load.  On a one-core
    host the full store deserializes at ~6 MB/s, so a worker preloads
    just its steady convoy shape and lets the long tail load lazily.
    Returns how many executables became resident."""
    prefixes = [tuple(p) for p in prefixes]
    loaded = 0
    for key, path in sorted(_scan_disk().items()):
        if not any(key[: len(p)] == p for p in prefixes):
            continue
        with _LOCK:
            if key in _PROC:
                continue
            fn = _load_blob(path, key)
            if fn is not None:
                _PROC[key] = fn
                loaded += 1
            REGISTRY.set_gauge("aot_resident_executables", len(_PROC))
    return loaded


def preload(max_seconds: float | None = None) -> int:
    """Deserialize every valid artifact in the store into the process
    cache (idempotent; at most once per process unless :func:`reset`).
    Returns the number of resident executables.  ``max_seconds`` bounds
    the scan so a worker's warmup budget is respected — remaining
    artifacts load lazily on first dispatch."""
    global _PRELOADED
    with _LOCK:
        if _PRELOADED:
            return len(_PROC)
        t0 = time.perf_counter()
        try:
            names = sorted(os.listdir(cache_dir()))
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("aot_v") and name.endswith(".npz")):
                continue
            if max_seconds is not None and time.perf_counter() - t0 > max_seconds:
                break
            path = os.path.join(cache_dir(), name)
            try:
                with np.load(path, allow_pickle=False) as z:
                    key = ast.literal_eval(z["key"].tobytes().decode())
            except Exception:
                _STATS["disk_rejects"] += 1
                REGISTRY.inc("aot_disk_rejects_total")
                continue
            if not (isinstance(key, tuple) and key and isinstance(key[0], str)):
                _STATS["disk_rejects"] += 1
                REGISTRY.inc("aot_disk_rejects_total")
                continue
            if key in _PROC:
                continue
            fn = _load_blob(path, key)
            if fn is not None:
                _PROC[key] = fn
        _PRELOADED = True
        REGISTRY.set_gauge("aot_resident_executables", len(_PROC))
        return len(_PROC)


def has_prefix(prefix: tuple) -> bool:
    """True when some resident executable's key starts with ``prefix``
    — lets warmup skip a bucket whose programs already loaded."""
    with _LOCK:
        return any(k[: len(prefix)] == prefix for k in _PROC)


def note_error() -> None:
    """Count one store failure (the caller degraded to its jit path)."""
    with _LOCK:
        _STATS["errors"] += 1
    REGISTRY.inc("aot_errors_total")


def stats() -> dict:
    with _LOCK:
        return dict(_STATS, resident=len(_PROC))


def reset(clear_disk: bool = False) -> None:
    """Forget process state (tests); optionally delete the store."""
    global _PRELOADED, _DISK
    with _LOCK:
        _PROC.clear()
        _KEY_LOCKS.clear()
        _PRELOADED = False
        _DISK = None
        for k in _STATS:
            _STATS[k] = 0 if isinstance(_STATS[k], int) else 0.0
        if clear_disk:
            try:
                for name in os.listdir(cache_dir()):
                    if name.startswith("aot_v") and name.endswith(".npz"):
                        os.unlink(os.path.join(cache_dir(), name))
            except OSError:
                pass
