"""WAL-backed ceremony durability: a restarted server resumes traffic.

One journal per server process (``net.checkpoint.service_wal_path``),
built on :class:`~dkg_tpu.net.checkpoint.PartyWal` — the same
append-only, checksummed, fsync'd, torn-tail-tolerant record log the
party runtime checkpoints into, so the service inherits its crash
semantics for free.

Three record kinds, all JSON bodies with a ``kind`` tag:

* ``req`` — appended at ADMISSION, before submit() returns the ceremony
  id.  Carries the full :class:`~dkg_tpu.service.engine.CeremonyRequest`
  (durable requests must be seeded: the journal stores the seed, not
  the coefficients, and the re-dealt polynomials are byte-identical by
  the engine's deterministic draw order).
* ``done`` — appended at COMPLETION (any terminal status: done, failed,
  expired, poisoned).  Carries the PUBLIC outcome only — master key,
  qualified set, complaints.  Share material NEVER touches the journal;
  a recovered terminal ceremony re-serves its public result, while its
  secret shares live only in the process that ran it.
* ``replay`` — appended each time RECOVERY re-queues a pending
  ceremony, carrying its cumulative replay count.  This is the
  crash-loop guard's memory: a request that keeps being mid-flight when
  the process dies is the prime suspect for WHY it dies, and without a
  persisted count the restart loop would re-run it forever.  The
  scheduler poisons a pending ceremony whose count reaches
  ``DKG_TPU_SERVICE_MAX_REPLAYS`` instead of re-queueing it.

Recovery (:meth:`ServiceJournal.replay`) partitions replayed ids into
*pending* (req without done — resubmitted and re-run from the seed) and
*terminal* (req+done — their outcomes re-served directly), plus the
*replays* count map.  The scheduler compacts the journal on recovery
via ``PartyWal.rewrite`` so a torn tail never shadows post-restart
appends.

The fleet (service/fleet.py) reuses this machinery unchanged for
worker failover: each worker SLOT gets its own journal directory
(``DKG_TPU_FLEET_WAL_DIR/slotNNN``), and the replacement worker
spawned for a dead slot simply constructs its scheduler over the same
directory — this module's recovery re-runs the dead worker's pending
seeded ceremonies under their original ids and re-serves its terminal
outcomes, no fleet-specific journal code at all.
"""

from __future__ import annotations

import base64
import json

from ..net.checkpoint import PartyWal, service_wal_path
from .engine import CeremonyOutcome, CeremonyRequest

__all__ = ["ServiceJournal", "service_wal_path"]


def _req_body(cid: str, seq: int, req: CeremonyRequest) -> bytes:
    return json.dumps(
        {
            "kind": "req",
            "id": cid,
            "seq": seq,
            "curve": req.curve,
            "n": req.n,
            "t": req.t,
            "shared_string": base64.b64encode(req.shared_string).decode(),
            "seed": req.seed,
            "rho_bits": req.rho_bits,
            "deadline_s": req.deadline_s,
            "tag": req.tag,
        },
        sort_keys=True,
    ).encode()


def _done_body(out: CeremonyOutcome) -> bytes:
    return json.dumps(
        {
            "kind": "done",
            "id": out.ceremony_id,
            "status": out.status,
            "curve": out.curve,
            "n": out.n,
            "t": out.t,
            "bucket_n": out.bucket_n,
            "bucket_t": out.bucket_t,
            "master": out.master.hex(),
            "qualified": list(out.qualified),
            "complaints": [list(c) for c in out.complaints],
            "error": out.error,
        },
        sort_keys=True,
    ).encode()


def _replay_body(cid: str, count: int) -> bytes:
    return json.dumps(
        {"kind": "replay", "id": cid, "count": count}, sort_keys=True
    ).encode()


class ServiceJournal:
    """The scheduler's durability sink.  All writes happen under the
    scheduler's own locks (admission lock for ``record_request``, the
    completing worker for ``record_done``, recovery for
    ``record_replay``), so the journal itself needs no locking beyond
    PartyWal's single-write appends."""

    def __init__(self, directory) -> None:
        self.wal = PartyWal(service_wal_path(directory))

    def record_request(self, cid: str, seq: int, req: CeremonyRequest) -> None:
        self.wal.append(_req_body(cid, seq, req))

    def record_done(self, out: CeremonyOutcome) -> None:
        self.wal.append(_done_body(out))

    def record_replay(self, cid: str, count: int) -> None:
        """Persist that ``cid`` is being re-queued for the ``count``-th
        time (crash-loop guard; see module docstring)."""
        self.wal.append(_replay_body(cid, count))

    def replay(self):
        """(pending, terminal, replays): ``pending`` maps ceremony id ->
        ``(seq, CeremonyRequest)`` for admitted-but-unfinished
        ceremonies; ``terminal`` maps id -> public
        :class:`CeremonyOutcome`; ``replays`` maps id -> cumulative
        recovery re-queue count (later records win — counts only grow).
        Unparseable bodies are skipped (the frame checksum already
        passed, so these are version skew, not corruption — better to
        recover the rest than refuse)."""
        pending: dict = {}
        terminal: dict = {}
        replays: dict = {}
        for body in self.wal.replay():
            try:
                rec = json.loads(body)
                kind = rec["kind"]
            except (ValueError, KeyError):
                continue
            if kind == "req":
                try:
                    req = CeremonyRequest(
                        curve=rec["curve"],
                        n=rec["n"],
                        t=rec["t"],
                        shared_string=base64.b64decode(rec["shared_string"]),
                        seed=rec["seed"],
                        rho_bits=rec["rho_bits"],
                        deadline_s=rec["deadline_s"],
                        durable=True,
                        tag=rec.get("tag", ""),
                    )
                except (KeyError, ValueError):
                    continue
                pending[rec["id"]] = (rec.get("seq", 0), req)
            elif kind == "done":
                cid = rec.get("id")
                if cid is None:
                    continue
                pending.pop(cid, None)
                terminal[cid] = CeremonyOutcome(
                    ceremony_id=cid,
                    status=rec.get("status", "done"),
                    curve=rec.get("curve", ""),
                    n=rec.get("n", 0),
                    t=rec.get("t", 0),
                    bucket_n=rec.get("bucket_n", 0),
                    bucket_t=rec.get("bucket_t", 0),
                    master=bytes.fromhex(rec.get("master", "")),
                    qualified=tuple(rec.get("qualified", ())),
                    complaints=tuple(
                        tuple(c) for c in rec.get("complaints", ())
                    ),
                    error=rec.get("error", ""),
                )
            elif kind == "replay":
                cid = rec.get("id")
                if cid is None:
                    continue
                try:
                    count = int(rec.get("count", 0))
                except (TypeError, ValueError):
                    continue
                replays[cid] = max(replays.get(cid, 0), count)
        return pending, terminal, replays

    def compact(
        self, pending: dict, terminal: dict, replays: dict | None = None
    ) -> None:
        """Rewrite the journal to exactly the replayed state (pending
        reqs + their replay counts + terminal dones — a ``done`` record
        is self-contained, so terminal ceremonies need no ``req`` twin),
        dropping any torn tail so post-restart appends cannot be
        shadowed by it.  Replay counts for non-pending ids are dropped:
        the guard only ever consults them for pending ceremonies."""
        bodies = [
            _req_body(cid, seq, req) for cid, (seq, req) in pending.items()
        ]
        if replays:
            bodies.extend(
                _replay_body(cid, count)
                for cid, count in replays.items()
                if cid in pending
            )
        bodies.extend(_done_body(out) for out in terminal.values())
        self.wal.rewrite(bodies)
