"""Seeded service-layer fault injection: chaos for the serving path.

``net/faults.py`` storms the PROTOCOL (dropped shares, forged proofs,
crashing parties); this module storms the SERVICE built on top of it —
the admission queue, convoy pipeline, worker pool, and journal that
PRs 7-9 added.  Same philosophy: a :class:`ServiceFaultPlan` is a
seeded, declarative builder, every injection is observable (metric +
flight-recorder event), and the harness (scripts/service_storm.py)
asserts the service DEGRADES instead of amplifying — healthy requests
complete bit-identically to a fault-free run while the faults are
contained by the scheduler's isolation machinery
(docs/fault_model.md "Service fault model").

Fault kinds:

* *poison* — any convoy start containing a tagged request raises
  :class:`PoisonFault`.  Deliberately a GENERIC exception, not
  ``errors.PoisonedRequest``: the scheduler must *discover* which
  member is poisoned by bisection, not be told.
* *transient* — the next ``times`` starts raise
  :class:`~dkg_tpu.service.errors.TransientEngineError` (the one type
  the scheduler retries; models device resets / allocator hiccups).
* *slow* — the next ``times`` starts sleep ``seconds`` first (models
  compile storms / contended devices; exercises deadline enforcement).
* *worker-crash* — the N-th start call raises :class:`WorkerCrash`, a
  ``BaseException`` that sails through the worker's
  ``except Exception`` and kills the THREAD — exactly the failure the
  scheduler's watchdog exists for.
* *journal corruption* — :func:`corrupt_journal` appends garbage to the
  service WAL (PartyWal's checksummed frames make this a torn tail the
  next recovery must shrug off).

The plan plugs into :class:`~dkg_tpu.service.scheduler.CeremonyScheduler`
via its ``fault_plan=`` constructor hook: the scheduler routes every
engine start/finish through :meth:`on_start` / :meth:`on_finish`, so
injection composes with monkeypatched fake engines (tests) and the real
one (the storm) alike.

One layer up, :class:`FleetFaultPlan` storms the FLEET
(service/fleet.py): seeded schedules of worker kills (SIGKILL
mid-ceremony; kill-during-recovery via the fleet's ``fault_plan=``
respawn hook), pipe garbage, and per-slot journal tail corruption —
the process-level faults scripts/fleet_storm.py drives.  A
ServiceFaultPlan cannot cross the spawn pickle (it holds a lock), so
in-worker faults (slow/transient) ship to fleet children as the plain
``worker_fault=`` dict the child rebuilds a plan from.
"""

from __future__ import annotations

import random
import threading
import time

from ..net.checkpoint import service_wal_path
from ..utils import obslog
from ..utils.metrics import REGISTRY
from . import errors


class WorkerCrash(BaseException):
    """Kills a worker THREAD, not just a convoy: subclasses
    BaseException so the worker loop's ``except Exception`` cannot
    contain it — the thread dies and only the scheduler's watchdog
    brings the capacity back."""


class PoisonFault(RuntimeError):
    """The injected deterministic per-request failure.  Generic on
    purpose (see module docstring): the scheduler's bisection must
    locate the culprit without type hints."""


class ServiceFaultPlan:
    """Declarative, seeded fault schedule for one scheduler.

    Builder methods return ``self`` for chaining::

        plan = (ServiceFaultPlan(seed=7)
                .poison("req-3", "req-19")
                .transient(times=2)
                .slow(0.05, times=1)
                .crash_worker(at_start=5))

    Thread-safe: the scheduler's M workers consume the schedule
    concurrently; counters live under one lock.  ``injected`` and
    :meth:`as_dict` expose the ground truth the storm's blame-accuracy
    check compares the scheduler's verdicts against.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self._lock = threading.Lock()
        self._poison_tags: set[str] = set()
        self._transient_budget = 0
        self._slow_s = 0.0
        self._slow_budget = 0
        self._crash_at: set[int] = set()
        self._start_calls = 0
        self.injected: dict[str, int] = {}

    # -- builders -----------------------------------------------------------

    def poison(self, *tags: str) -> "ServiceFaultPlan":
        """Every start whose convoy contains a request with one of these
        ``tag`` values raises :class:`PoisonFault` — deterministic, so
        bisection re-runs keep failing until the culprit is alone."""
        self._poison_tags.update(tags)
        return self

    def transient(self, times: int = 1) -> "ServiceFaultPlan":
        """The next ``times`` starts raise TransientEngineError."""
        self._transient_budget += times
        return self

    def slow(self, seconds: float, times: int = 1) -> "ServiceFaultPlan":
        """The next ``times`` starts sleep ``seconds`` before running."""
        self._slow_s = seconds
        self._slow_budget += times
        return self

    def crash_worker(self, at_start: int) -> "ServiceFaultPlan":
        """The ``at_start``-th start call (1-based, across all workers)
        raises :class:`WorkerCrash`."""
        self._crash_at.add(at_start)
        return self

    # -- the scheduler-facing hook ------------------------------------------

    def _note(self, kind: str) -> None:
        """Every injection is observable: per-kind counter + ambient
        flight-recorder event (the net/faults.py contract)."""
        with self._lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1
        REGISTRY.inc("service_faults_injected_total", kind=kind)
        log = obslog.current()
        if log is not None:
            log.emit("service_fault_injected", fault=kind)

    def on_start(self, reqs) -> None:
        """Called by the scheduler before every convoy start (primary,
        retry, and bisection runs alike).  Raises the scheduled fault."""
        with self._lock:
            self._start_calls += 1
            ncall = self._start_calls
            slow = 0.0
            if self._slow_budget > 0:
                self._slow_budget -= 1
                slow = self._slow_s
            crash = ncall in self._crash_at
            transient = False
            if not crash and not slow and self._transient_budget > 0:
                self._transient_budget -= 1
                transient = True
            poisoned = sum(1 for r in reqs if r.tag in self._poison_tags)
        if slow:
            self._note("slow")
            time.sleep(slow)
        if crash:
            self._note("worker_crash")
            raise WorkerCrash(f"injected worker crash at start #{ncall}")
        if transient:
            self._note("transient")
            raise errors.TransientEngineError("injected transient engine fault")
        if poisoned:
            self._note("poison")
            raise PoisonFault(f"injected poison ({poisoned} tagged member(s))")

    def on_finish(self, reqs) -> None:
        """Finish-side hook (no kinds scheduled here today; the seam
        exists so finish-phase faults need no scheduler change)."""

    # -- reporting ----------------------------------------------------------

    @property
    def poisoned_tags(self) -> frozenset[str]:
        """Ground truth for blame-accuracy checks."""
        return frozenset(self._poison_tags)

    def as_dict(self) -> dict:
        """JSON-able schedule + injection counts (storm artifacts)."""
        with self._lock:
            return {
                "seed": self.seed,
                "poison_tags": sorted(self._poison_tags),
                "crash_at_starts": sorted(self._crash_at),
                "slow_s": self._slow_s,
                "start_calls": self._start_calls,
                "injected": dict(self.injected),
            }


class FleetFaultPlan:
    """Seeded, declarative fault schedule for one fleet — the
    process-level mirror of :class:`ServiceFaultPlan`.

    Builder methods return ``self`` for chaining::

        plan = (FleetFaultPlan(seed=11)
                .kill_worker(at_submit=30)          # SIGKILL mid-ceremony
                .kill_on_respawn(times=1)           # ...and mid-recovery
                .garble_pipe(at_submit=50)
                .corrupt_slot_journal(at_submit=70))

    Two hooks fire it: the storm harness calls :meth:`on_submit` after
    every accepted submission (kills/garbage/corruption keyed on the
    submission count), and the fleet's ``_reap_and_respawn`` calls
    :meth:`on_respawn` for every replacement worker it spawns (the
    kill-during-recovery leg).  Every injection lands in ``injected``,
    the ``service_faults_injected_total`` metric and the flight
    recorder — the ground truth the storm's floors compare against.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self._lock = threading.Lock()
        self._kill_at: set[int] = set()
        self._garble_at: set[int] = set()
        self._corrupt_at: set[int] = set()
        self._recovery_kills = 0
        self.killed_cids: list[str] = []
        self.injected: dict[str, int] = {}

    # -- builders -----------------------------------------------------------

    def kill_worker(self, at_submit: int) -> "FleetFaultPlan":
        """SIGKILL the worker holding the ``at_submit``-th accepted
        submission (1-based) — mid-ceremony, queue and all."""
        self._kill_at.add(at_submit)
        return self

    def kill_on_respawn(self, times: int = 1) -> "FleetFaultPlan":
        """SIGKILL the next ``times`` replacement workers the fleet
        spawns — the crash lands while the replacement is recovering
        the slot journal, the hardest failover window."""
        self._recovery_kills += times
        return self

    def garble_pipe(self, at_submit: int) -> "FleetFaultPlan":
        """Inject one unpicklable frame into the routed worker's pipe
        after the ``at_submit``-th accepted submission."""
        self._garble_at.add(at_submit)
        return self

    def corrupt_slot_journal(self, at_submit: int) -> "FleetFaultPlan":
        """Append seeded garbage to the routed worker's slot journal
        after the ``at_submit``-th accepted submission — the torn tail
        the NEXT recovery on that slot must compact past."""
        self._corrupt_at.add(at_submit)
        return self

    # -- hooks ---------------------------------------------------------------

    def _note(self, kind: str) -> None:
        with self._lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1
        REGISTRY.inc("service_faults_injected_total", kind=kind)
        log = obslog.current()
        if log is not None:
            log.emit("service_fault_injected", fault=kind)

    def on_submit(self, fleet, nsubmit: int, cid: str) -> None:
        """Harness hook: fire whatever this submission count schedules
        against the worker the fleet placed ``cid`` on."""
        with self._lock:
            garble = nsubmit in self._garble_at
            corrupt = nsubmit in self._corrupt_at
            kill = nsubmit in self._kill_at
        if not (garble or corrupt or kill):
            return
        w = fleet._placed_worker(cid)
        if w is None:
            return
        if garble and hasattr(w, "inject_garbage") and w.inject_garbage():
            self._note("fleet_pipe_garbage")
        if corrupt:
            wal = fleet._slot_wal_dir(getattr(w, "slot", 0) or 0)
            if wal is not None:
                corrupt_journal(wal, seed=self.seed ^ nsubmit)
                self._note("fleet_journal_tail")
        if kill and hasattr(w, "kill"):
            with fleet._lock:
                doomed = [
                    c for c, e in fleet._placed.items() if e[0] is w
                ]
            with self._lock:
                self.killed_cids.extend(doomed)
            self._note("fleet_kill")
            w.kill()

    def on_respawn(self, fleet, slot: int, worker) -> None:
        """Fleet hook: called for every replacement spawn; consumes the
        kill-during-recovery budget."""
        with self._lock:
            if self._recovery_kills <= 0:
                return
            self._recovery_kills -= 1
        self._note("fleet_kill_recovery")
        if hasattr(worker, "kill"):
            worker.kill()

    # -- reporting ----------------------------------------------------------

    def as_dict(self) -> dict:
        """JSON-able schedule + injection counts (storm artifacts)."""
        with self._lock:
            return {
                "seed": self.seed,
                "kill_at_submits": sorted(self._kill_at),
                "garble_at_submits": sorted(self._garble_at),
                "corrupt_at_submits": sorted(self._corrupt_at),
                "recovery_kills_left": self._recovery_kills,
                "killed_cids": list(self.killed_cids),
                "injected": dict(self.injected),
            }


def corrupt_journal(wal_dir, seed: int = 0, nbytes: int = 48) -> str:
    """Append ``nbytes`` of seeded garbage to the service WAL in
    ``wal_dir`` — a torn/corrupted tail the next recovery's checksummed
    replay must skip without losing the intact prefix.  Returns the WAL
    path written."""
    path = service_wal_path(wal_dir)
    rng = random.Random(seed)
    with open(path, "ab") as f:  # noqa: DKG006 — deliberate WAL corruption
        f.write(bytes(rng.randrange(256) for _ in range(nbytes)))
    return str(path)
