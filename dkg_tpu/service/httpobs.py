"""Scrape surface for the serving fleet: /metrics, /healthz, /slo.

One stdlib ``http.server`` thread (no dependencies, no frameworks)
exposing what the process already knows:

* ``/metrics`` — ``registry.prometheus_text()``, the text exposition a
  Prometheus/Grafana stack scrapes (includes the runtimeobs ``jax_*``
  series when the introspection layer is installed);
* ``/healthz`` — the scheduler's liveness dict (workers alive, queue
  depth, WAL ok) as JSON; 200 when ``ok`` else 503, so a load balancer
  can probe it directly;
* ``/slo`` — the rolling :mod:`~dkg_tpu.service.slo` report as JSON;
  200 when the window is inside its objectives else 503.

**Off by default.**  The server starts only when a port is configured —
``DKG_TPU_SERVICE_HTTP_PORT`` via utils.envknobs (0 binds an ephemeral
port, handy for tests) or the scheduler's ``http_port`` argument.  Binds
localhost only: this is an operator scrape surface, not a public API —
anything wider is a deployment's reverse-proxy decision.

Redaction stance: every byte served here comes from the metrics
registry (names, labels, numbers) or the scheduler's health/SLO dicts
(statuses, counts, latencies) — never from ceremony payloads.  Key
material cannot transit this surface; tests/test_runtimeobs.py greps
the responses for the obslog redaction contract.

The handler thread is spawned here rather than in scheduler.py; lint
DKG007 sanctions exactly this module, the scheduler and the fleet as
service thread/process-spawn sites.

**Front-door promotion.**  :mod:`~dkg_tpu.service.fleet` reuses this
server as a real request surface: the optional ``router`` callback
receives ``(method, path, query, body)`` for any request the scrape
routes don't claim and returns ``(status, payload)`` — POST bodies are
parsed as JSON here so route owners never touch the socket.  The scrape
surface semantics above are unchanged when no router is installed.
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse

from ..utils import envknobs
from ..utils.metrics import REGISTRY

#: Seconds close() waits for the serve thread to drain.
_JOIN_TIMEOUT_S = 5.0


class ObsHttpServer:
    """Owns the listening socket and the one daemon serve thread.

    ``health_fn`` / ``slo_fn`` are zero-arg callables returning
    JSON-able dicts (the scheduler passes its bound methods); either may
    be None, which 404s that route.  A callback that raises is recorded
    (``service_http_errors_total``) and answered 500 — a broken probe
    must read as unhealthy, not kill the serve thread.

    ``router`` (the fleet front door) is consulted for any GET the
    scrape routes don't claim and for every POST:
    ``router(method, path, query, body) -> (status, payload) | None``,
    with ``query`` a flat str->str dict and ``body`` the parsed JSON
    object of a POST (None for GETs / empty bodies).  ``None`` falls
    through to 404; exceptions follow the 500-and-count contract above.
    """

    def __init__(
        self,
        *,
        registry=None,
        health_fn=None,
        slo_fn=None,
        router=None,
        log=None,
        port: int = 0,
        host: str = "127.0.0.1",
    ) -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.health_fn = health_fn
        self.slo_fn = slo_fn
        self.router = router
        self.log = log
        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # stdlib default logs every request to stderr (DKG006:
            # telemetry goes through obslog/metrics, not raw streams)
            def log_message(self, fmt, *args):  # noqa: A003
                del fmt, args

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload: dict) -> None:
                self._send(
                    code,
                    json.dumps(payload, sort_keys=True).encode(),
                    "application/json",
                )

            def _query(self) -> dict:
                raw = self.path.split("?", 1)
                if len(raw) < 2:
                    return {}
                return {
                    k: v[-1]
                    for k, v in urllib.parse.parse_qs(raw[1]).items()
                }

            def _route(self, method: str, path: str, body) -> None:
                if server.router is None:
                    self._send_json(404, {"error": "not found", "path": path})
                    return
                routed = server.router(method, path, self._query(), body)
                if routed is None:
                    self._send_json(404, {"error": "not found", "path": path})
                    return
                self._send_json(routed[0], routed[1])

            def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            server.registry.prometheus_text().encode(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/healthz" and server.health_fn is not None:
                        health = server.health_fn()
                        self._send_json(
                            200 if health.get("ok") else 503, health
                        )
                    elif path == "/slo" and server.slo_fn is not None:
                        report = server.slo_fn()
                        self._send_json(200 if report.get("ok") else 503, report)
                    else:
                        self._route("GET", path, None)
                except Exception as exc:
                    server._note(path, exc)
                    try:
                        self._send_json(500, {"error": type(exc).__name__})
                    except Exception as exc2:
                        # client already gone mid-response; count it too
                        server._note(path, exc2)

            def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler API)
                path = self.path.split("?", 1)[0]
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                    if body is not None and not isinstance(body, dict):
                        self._send_json(400, {"error": "body must be a JSON object"})
                        return
                    self._route("POST", path, body)
                except json.JSONDecodeError:
                    self._send_json(400, {"error": "invalid JSON body"})
                except Exception as exc:
                    server._note(path, exc)
                    try:
                        self._send_json(500, {"error": type(exc).__name__})
                    except Exception as exc2:
                        server._note(path, exc2)

        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="dkg-svc-http",
            daemon=True,
        )
        self._thread.start()

    def _note(self, path: str, exc: BaseException) -> None:
        self.registry.inc("service_http_errors_total", path=path)
        if self.log is not None:
            self.log.emit(
                "http_error", path=path, kind=type(exc).__name__, error=str(exc)
            )

    @property
    def port(self) -> int:
        """The bound port (the ephemeral one when constructed with 0)."""
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=_JOIN_TIMEOUT_S)


def maybe_start(
    *,
    registry=None,
    health_fn=None,
    slo_fn=None,
    log=None,
    port: int | None = None,
) -> ObsHttpServer | None:
    """Start a server iff a port is configured: the explicit ``port``
    argument wins, else ``DKG_TPU_SERVICE_HTTP_PORT``; both unset means
    the surface stays off and this returns None."""
    if port is None:
        port = envknobs.nonneg_int(
            "DKG_TPU_SERVICE_HTTP_PORT",
            "observability HTTP port (0 = ephemeral; unset = off)",
        )
    if port is None:
        return None
    return ObsHttpServer(
        registry=registry,
        health_fn=health_fn,
        slo_fn=slo_fn,
        log=log,
        port=port,
    )
