"""Shape-bucketing policy: pad requested ``(n, t)`` into a small ladder.

Every distinct ``(n, t)`` jitted at its exact shape is one more program
set in the compile cache — and a compile on this workload costs minutes,
not milliseconds (a cold n=16 secp256k1 ceremony compiles for ~2 min on
a laptop-class CPU while the warm run takes half a second).  A service
facing arbitrary committee sizes therefore cannot jit per request: it
pads every request up to a canonical *bucket* so thousands of distinct
shapes share a handful of executables.

Policy (deliberately tiny, so the whole ladder stays warm):

* ``n`` rounds up to the next power of two, floored at
  :data:`MIN_BUCKET_N` — committee sizes 9..16 share one program set,
  17..32 the next, and so on.
* ``t`` rounds up to the smallest rung of ``n_pad/4``, ``n_pad/3``,
  ``(n_pad-1)/2`` — the three threshold regimes real deployments use
  (light, standard ~n/3, maximal honest-majority).  A ``t`` beyond the
  maximal rung (degenerate, but legal in the engine) escalates to the
  next ``n`` bucket.
* convoy widths (how many same-bucket ceremonies stack on the ceremony
  axis) come from the fixed ladder :data:`WIDTHS`; ragged convoys are
  split greedily (k=7 -> 4+2+1) instead of padded with phantom
  ceremonies, so batching never wastes compute — only compiles from the
  ladder exist.

Correctness of padding is the engine's pad-and-mask contract
(:meth:`dkg_tpu.dkg.ceremony.CeremonyConfig.padded`): phantom lanes are
zero-coefficient dealers whose shares are zero and whose commitments
are the identity; the real lanes' outputs are bit-identical to the
unpadded run (oracle tests in tests/test_service.py).
"""

from __future__ import annotations

import dataclasses

#: Smallest n bucket: ceremonies below this pad up to it.  Eight lanes
#: is already enough to keep the batched kernels' vector shapes sane.
MIN_BUCKET_N = 8

#: Largest n bucket the policy will emit.  Requests beyond this are the
#: north-star single-ceremony regime (sharded engine), not service
#: traffic.
MAX_BUCKET_N = 4096

#: Stacked-lane width ladder (descending).  Only these convoy widths
#: ever compile; see :func:`split_widths`.
WIDTHS = (8, 4, 2, 1)

#: Stacking crossover: buckets at or above this ``n`` run width-1
#: convoys.  Stacking pays while per-dispatch overhead is a meaningful
#: fraction of one ceremony's compute; fleet calibration (single-core
#: CPU, secp256k1, width 8) measured 1.65x at the (16,5) bucket, 1.27x
#: at (32,8), and a 0.95x LOSS at (64,16), where compute dominates and
#: the vmapped lane only adds overhead.  Capping also halves the warm
#: compile set for the heavy buckets (no stacked programs to build).
WIDTH_CAP_N = 64


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One canonical padded shape.  Hashable — used as a compile/convoy
    key together with the curve."""

    n: int
    t: int


def _next_pow2(v: int) -> int:
    return 1 << max(v - 1, 1).bit_length()


def t_rungs(n_pad: int) -> tuple[int, ...]:
    """The threshold rungs available at an ``n`` bucket, ascending."""
    return tuple(sorted({n_pad // 4, n_pad // 3, (n_pad - 1) // 2}))


def bucket_for(n: int, t: int) -> Bucket:
    """The canonical bucket dominating ``(n, t)``.

    Raises ValueError for shapes no bucket dominates (n out of range, or
    t >= n which no DKG admits).
    """
    if n < 2 or n > MAX_BUCKET_N:
        raise ValueError(f"bucket_for: n={n} outside [2, {MAX_BUCKET_N}]")
    if t < 1 or t >= n:
        raise ValueError(f"bucket_for: t={t} outside [1, n-1] for n={n}")
    n_pad = max(MIN_BUCKET_N, _next_pow2(n))
    while n_pad <= MAX_BUCKET_N:
        for rung in t_rungs(n_pad):
            if rung >= t:
                return Bucket(n_pad, rung)
        n_pad *= 2
    raise ValueError(f"bucket_for: no bucket dominates (n={n}, t={t})")


def width_cap(b: Bucket) -> int:
    """Largest convoy width worth stacking for ``b`` (a ladder value).

    The scheduler takes ``min(batch_max, width_cap(bucket))`` when it
    pops a convoy, so operators tune ``batch_max`` downward only —
    the cap already excludes the shapes where stacking is a measured
    loss (see :data:`WIDTH_CAP_N`).
    """
    return 1 if b.n >= WIDTH_CAP_N else WIDTHS[0]


#: Message-count rungs for the sign lane (descending).  Only these
#: batch shapes ever enter the ladder/MSM executables, so mixed sign
#: traffic from any ceremony shares one warm program per (curve, rung).
#: The ladder deliberately includes the small rungs (2, 1): existing
#: callers with tiny batches keep their exact compiled shapes — a
#: convoy of 2 runs as [2], not [1, 1] — and tail slices of big convoys
#: reuse them instead of padding with phantom messages (a phantom
#: message costs a full ladder lane; an extra warm narrow dispatch is
#: microseconds).
SIGN_RUNGS = (256, 64, 16, 4, 2, 1)


def sign_rung_slices(total: int, batch_max: int = SIGN_RUNGS[0]) -> list[tuple[int, int]]:
    """Greedy ``(start, stop)`` decomposition of ``total`` queued sign
    messages into :data:`SIGN_RUNGS` shapes, each at most ``batch_max``
    (total=21 -> [(0, 16), (16, 20), (20, 21)]).  The sign-lane analogue
    of :func:`split_widths`, over the message axis instead of the
    ceremony axis."""
    if total < 0:
        raise ValueError(f"sign_rung_slices: total={total} < 0")
    out: list[tuple[int, int]] = []
    at = 0
    for w in SIGN_RUNGS:
        if w > batch_max:
            continue
        while total - at >= w:
            out.append((at, at + w))
            at += w
    return out


def split_widths(k: int, batch_max: int = WIDTHS[0]) -> list[int]:
    """Greedy decomposition of a convoy of ``k`` ceremonies into ladder
    widths, each at most ``batch_max`` (k=7 -> [4, 2, 1]).  Splitting
    instead of padding: a phantom ceremony costs a full ceremony's
    compute, while one extra (already-compiled) narrower program costs
    only its dispatch."""
    if k < 0:
        raise ValueError(f"split_widths: k={k} < 0")
    out: list[int] = []
    for w in WIDTHS:
        if w > batch_max:
            continue
        while k >= w:
            out.append(w)
            k -= w
    return out
