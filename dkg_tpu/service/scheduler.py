"""Admission queue + worker pool: the multi-tenant ceremony front door.

Concurrency model — THREADS, not asyncio, and deliberately so: the
work units are JAX dispatches (release the GIL inside XLA), host
transcript digests (hashlib releases the GIL), and numpy transfers —
all of which overlap fine under threads, while an asyncio design would
have to push every one of those blocking calls to an executor *anyway*
(JAX has no awaitable dispatch API) and would gain nothing but an event
loop to babysit.  The pool here and the scrape-server thread in
service/httpobs.py are the only sanctioned thread-spawn sites in this
package (scripts/lint_lite.py DKG007); everything else in
``dkg_tpu/service/`` must stay thread-free so the concurrency story has
few owners.

Flow:

* :meth:`CeremonyScheduler.submit` admits a request into a BOUNDED
  queue — full queue raises :class:`QueueFullError` immediately (the
  HTTP mapping is 503 + Retry-After; see examples/serve.py).  Admission
  is the durability point: with a WAL dir configured, the request
  record is fsync'd before submit returns the ceremony id.
* workers pop *convoys*: the queue head plus up to ``batch_max - 1``
  more QUEUED requests sharing its convoy key (curve, bucket, rho_bits,
  shared string), truncated to the width ladder so only ladder-width
  programs ever compile.  Same-bucket traffic thus amortizes one
  dispatch across the whole convoy — on hosts where per-op dispatch
  overhead dominates small ceremonies, this is where the throughput is.
* each worker runs a TWO-DEEP pipeline generalizing
  ``hybrid_batch.seal_shares_pipeline``: it *starts* (dispatches) convoy
  k+1 before *finishing* (host transcript + verify + finalise) convoy
  k, so host work rides under the device's dispatch shadow.
* deadlines are enforced at pop (an expired ceremony never starts) and
  at finish (a ceremony that expired mid-flight reports ``expired``,
  not ``done``).

Blast-radius isolation (docs/fault_model.md "Service fault model"): a
convoy failure no longer dooms its width-W members wholesale.
:class:`~dkg_tpu.service.errors.TransientEngineError` retries the whole
convoy (bounded, exponential backoff); anything else BISECTS down the
width ladder — healthy halves complete normally, and the request that
still fails alone at width 1 gets the terminal ``poisoned`` status
(error names :class:`~dkg_tpu.service.errors.PoisonedRequest`).  A
watchdog thread respawns workers killed by non-``Exception`` escapes
and re-queues (once) the convoys they held.  Signing survives Byzantine
partials via RLC blame + per-ceremony signer quarantine (:meth:`sign`).

Knobs (all validated through utils.envknobs; constructor arguments
win): ``DKG_TPU_SERVICE_CONCURRENCY`` (workers, default 4),
``DKG_TPU_SERVICE_QUEUE_DEPTH`` (admission bound, default 256),
``DKG_TPU_SERVICE_BATCH_MAX`` (max convoy width, default 8, capped by
the bucket ladder), ``DKG_TPU_SERVICE_DEADLINE_S`` (default per-request
deadline, unset = none), ``DKG_TPU_SERVICE_WAL_DIR`` (durability
journal directory, unset = durability off), ``DKG_TPU_SERVICE_RETRIES``
(transient-fault convoy retries, default 2, 0 disables),
``DKG_TPU_SERVICE_RETRY_BACKOFF_S`` (first backoff, doubling, default
0.05), ``DKG_TPU_SERVICE_MAX_REPLAYS`` (journal crash-loop guard,
default 3 — see service.durable), ``DKG_TPU_SERVICE_HTTP_PORT``
(observability scrape surface — service/httpobs; unset = off),
``DKG_TPU_RUNTIMEOBS`` (JAX compile/memory telemetry —
utils/runtimeobs), ``DKG_TPU_SLO_*`` (rolling SLO objectives —
service/slo).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time

import numpy as np

from ..epoch import inprocess as epoch_inprocess
from ..fields import host as fh
from ..groups import host as gh
from ..utils import envknobs, obslog, runtimeobs
from ..utils.metrics import REGISTRY
from . import buckets, errors, httpobs
from .durable import ServiceJournal
from .slo import SloEvaluator
from .engine import (
    CeremonyOutcome,
    CeremonyRequest,
    WarmRuntime,
    finish_convoy,
    request_id,
    start_convoy,
)
from .errors import QueueFullError  # noqa: F401 — historical home, re-exported

#: How many times a convoy orphaned by a crashed worker is re-queued
#: before its members fail with WORKER_CRASH.  One: the convoy itself
#: may be what killed the worker, so unbounded re-queueing would turn a
#: poisoned request into a worker crash-loop.
_MAX_CRASH_REQUEUES = 1


class _Pending:
    __slots__ = ("cid", "seq", "req", "deadline_at", "crashes")

    def __init__(self, cid, seq, req, deadline_at):
        self.cid = cid
        self.seq = seq
        self.req = req
        self.deadline_at = deadline_at
        self.crashes = 0  # worker-crash orphanings survived so far


class CeremonyScheduler:
    """Bounded-admission ceremony scheduler over one warm runtime.

    Use as a context manager or call :meth:`close`.  Thread-safe: any
    thread may submit/poll/result concurrently.
    """

    def __init__(
        self,
        *,
        concurrency: int | None = None,
        queue_depth: int | None = None,
        batch_max: int | None = None,
        deadline_s: float | None = None,
        wal_dir: str | None = None,
        retries: int | None = None,
        retry_backoff_s: float | None = None,
        max_replays: int | None = None,
        watchdog_interval_s: float = 0.5,
        fault_plan=None,
        log=None,
        runtime: WarmRuntime | None = None,
        metrics=REGISTRY,
        http_port: int | None = None,
        slo_policy=None,
    ) -> None:
        if concurrency is None:
            concurrency = envknobs.pos_int(
                "DKG_TPU_SERVICE_CONCURRENCY", "scheduler worker threads"
            ) or 4
        if queue_depth is None:
            queue_depth = envknobs.pos_int(
                "DKG_TPU_SERVICE_QUEUE_DEPTH", "admission queue bound"
            ) or 256
        if batch_max is None:
            batch_max = envknobs.pos_int(
                "DKG_TPU_SERVICE_BATCH_MAX", "max stacked-convoy width"
            ) or buckets.WIDTHS[0]
        if deadline_s is None:
            deadline_s = envknobs.pos_float(
                "DKG_TPU_SERVICE_DEADLINE_S", "default per-ceremony deadline"
            )
        if wal_dir is None:
            wal_dir = envknobs.string(
                "DKG_TPU_SERVICE_WAL_DIR", "service durability journal directory"
            )
        if retries is None:
            retries = envknobs.nonneg_int(
                "DKG_TPU_SERVICE_RETRIES",
                "transient-fault convoy retries (0 disables)",
            )
            retries = 2 if retries is None else retries
        if retry_backoff_s is None:
            retry_backoff_s = envknobs.nonneg_float(
                "DKG_TPU_SERVICE_RETRY_BACKOFF_S",
                "first transient-retry backoff, doubling per attempt",
            )
            retry_backoff_s = 0.05 if retry_backoff_s is None else retry_backoff_s
        if max_replays is None:
            max_replays = envknobs.pos_int(
                "DKG_TPU_SERVICE_MAX_REPLAYS",
                "journal replays before a pending ceremony is poisoned",
            ) or 3
        self.concurrency = concurrency
        self.queue_depth = queue_depth
        self.batch_max = min(batch_max, buckets.WIDTHS[0])
        self.default_deadline_s = deadline_s
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        self.max_replays = max_replays
        self.runtime = runtime if runtime is not None else WarmRuntime()
        self.metrics = metrics
        self._fault_plan = fault_plan
        self._own_log = log is None
        self._log = log if log is not None else obslog.from_env()
        self._cond = threading.Condition()
        self._queue: list[_Pending] = []
        self._results: dict[str, CeremonyOutcome] = {}
        self._status: dict[str, str] = {}
        self._quarantine: dict[str, set[int]] = {}
        self._held: dict[int, list] = {}  # worker slot -> convoys in hand
        self._seq = 0
        self._gen = 0  # respawn generation, for unique thread names
        self._running = True
        self._draining = False
        self._watchdog_interval_s = watchdog_interval_s
        self._journal = ServiceJournal(wal_dir) if wal_dir else None
        if self._journal is not None:
            self._recover()
        # the one sanctioned thread-spawn site in dkg_tpu/service/
        # (lint DKG007): daemon so a crashed main thread never hangs on
        # ceremony workers
        self._workers = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"dkg-svc-{i}", daemon=True
            )
            for i in range(concurrency)
        ]
        for w in self._workers:
            w.start()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="dkg-svc-watchdog", daemon=True
        )
        self._watchdog.start()
        # runtime introspection (knob-gated: DKG_TPU_RUNTIMEOBS=on — a
        # no-op returning False otherwise) and the scrape surface (off
        # unless http_port / DKG_TPU_SERVICE_HTTP_PORT is configured)
        runtimeobs.install(registry=metrics, log=self._log)
        self.slo = SloEvaluator(registry=metrics, policy=slo_policy)
        self._http = httpobs.maybe_start(
            registry=metrics,
            health_fn=self.health,
            slo_fn=self.slo_report,
            log=self._log,
            port=http_port,
        )

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))

    def close(self, drain: bool = True) -> None:
        """Stop the workers.  ``drain`` finishes everything already
        admitted first; otherwise still-queued ceremonies complete as
        ``failed`` with a shutdown error (durable ones stay pending in
        the journal and are resubmitted on the next recovery)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            if drain:
                while self._queue:
                    self._cond.wait(timeout=0.1)
            self._running = False
            dropped = list(self._queue)
            self._queue.clear()
            for p in dropped:
                # durable drops are NOT journalled as done: they stay
                # pending in the WAL and the next recovery resubmits them
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error="SHUTDOWN",
                    ),
                )
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout=60)
        self._watchdog.join(timeout=60)
        if self._http is not None:
            self._http.close()
        if self._own_log and self._log is not None:
            self._log.close()

    def _recover(self) -> None:
        """Replay the journal: re-serve terminal outcomes, resubmit
        pending (admitted-but-unfinished) ceremonies under their
        original ids, and compact the log.

        Crash-loop guard: a pending ceremony already replayed
        ``max_replays`` times is the likely CAUSE of the crashes it
        keeps surviving — it completes as ``poisoned`` instead of being
        re-queued for another round of taking the process down."""
        pending, terminal, replays = self._journal.replay()
        self._journal.compact(pending, terminal, replays)
        for cid, out in terminal.items():
            self._results[cid] = out
            self._status[cid] = out.status
        now = time.monotonic()
        recovered = 0
        for cid, (seq, req) in pending.items():
            self._seq = max(self._seq, seq + 1)
            count = replays.get(cid, 0)
            if count >= self.max_replays:
                self.metrics.inc("service_poisoned_total")
                self._emit(
                    "service_replay_poisoned", ceremony=cid, replays=count
                )
                out = CeremonyOutcome(
                    ceremony_id=cid,
                    status="poisoned",
                    curve=req.curve,
                    n=req.n,
                    t=req.t,
                    error=(
                        f"PoisonedRequest: REPLAY_LIMIT "
                        f"(replayed {count}x, max {self.max_replays})"
                    ),
                )
                self._journal.record_done(out)
                self._results[cid] = out
                self._status[cid] = out.status
                continue
            self._journal.record_replay(cid, count + 1)
            deadline = (
                now + req.deadline_s if req.deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline))
            self._status[cid] = "queued"
            recovered += 1
        self.metrics.set_gauge("service_queue_depth", len(self._queue))
        if recovered:
            self.metrics.inc("service_recovered_total", recovered)

    def _emit(self, kind: str, **fields) -> None:
        """Flight-recorder event, KIND-only error attribution — never a
        message payload (redaction contract: an exception string may
        embed share/seed material; the emitted stream must not)."""
        if self._log is not None:
            self._log.emit(kind, **fields)

    # -- client surface -----------------------------------------------------

    def submit(self, req: CeremonyRequest) -> str:
        """Admit a ceremony; returns its id or raises
        :class:`QueueFullError` (backpressure) / ``ValueError`` (bad
        request — including unbucketable shapes and unseeded durable
        requests, both rejected before touching the queue)."""
        buckets.bucket_for(req.n, req.t)  # validates; raises ValueError
        if req.durable and req.seed is None:
            raise ValueError(
                "durable ceremonies must be seeded: the journal replays "
                "the seed, not the coefficients"
            )
        if req.durable and self._journal is None:
            raise ValueError(
                "durable ceremony submitted but the scheduler has no WAL "
                "dir (DKG_TPU_SERVICE_WAL_DIR / wal_dir=)"
            )
        deadline_s = (
            req.deadline_s
            if req.deadline_s is not None
            else self.default_deadline_s
        )
        with self._cond:
            if not self._running or self._draining:
                self.metrics.inc("service_rejected_total")
                self._emit("service_rejected", error_kind="SHUTTING_DOWN")
                raise QueueFullError("scheduler is shutting down")
            if len(self._queue) >= self.queue_depth:
                self.metrics.inc("service_rejected_total")
                self._emit("service_rejected", error_kind="QUEUE_FULL")
                raise QueueFullError(
                    f"admission queue full ({self.queue_depth})"
                )
            seq = self._seq
            self._seq += 1
            cid = request_id(req, seq)
            if req.durable:
                self._journal.record_request(cid, seq, req)
            deadline_at = (
                time.monotonic() + deadline_s if deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline_at))
            self._status[cid] = "queued"
            self.metrics.inc("service_submitted_total")
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self._cond.notify()
        return cid

    def health(self) -> dict:
        """Liveness dict (the ``/healthz`` payload — service/httpobs):
        ``ok`` means accepting work with a live pool.  Dead workers are
        watchdog-respawned, so the bar is "any worker alive", not "all";
        a fully dead pool or a closed/draining scheduler reads not-ok."""
        with self._cond:
            alive = sum(1 for w in self._workers if w.is_alive())
            total = len(self._workers)
            depth = len(self._queue)
            running = self._running
            draining = self._draining
        return {
            "ok": bool(running and not draining and alive > 0),
            "running": running,
            "draining": draining,
            "workers_alive": alive,
            "workers_total": total,
            "queue_depth": depth,
            "queue_capacity": self.queue_depth,
            "wal": "ok" if self._journal is not None else "off",
        }

    def slo_report(self) -> dict:
        """Rolling-window SLO judgment (the ``/slo`` payload — see
        service/slo.py for the window/quantile/error-budget math)."""
        return self.slo.report()

    def poll(self, cid: str) -> str:
        """Current status: queued | running | done | failed | expired |
        poisoned — or ``unknown`` for an id this scheduler never
        admitted."""
        with self._cond:
            return self._status.get(cid, "unknown")

    def result(self, cid: str, timeout: float | None = None) -> CeremonyOutcome:
        """Block until ``cid`` reaches a terminal status and return its
        outcome (TimeoutError on timeout, KeyError for unknown ids)."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._cond:
            if cid not in self._status:
                raise KeyError(f"unknown ceremony id {cid!r}")
            while cid not in self._results:
                remain = None
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        raise TimeoutError(
                            f"ceremony {cid} still {self._status[cid]}"
                        )
                self._cond.wait(timeout=remain)
            return self._results[cid]

    def quarantined(self, cid: str) -> frozenset[int]:
        """The 1-based signer indices quarantined for ceremony ``cid``
        (Byzantine partials caught by :meth:`sign`'s RLC blame)."""
        with self._cond:
            return frozenset(self._quarantine.get(cid, ()))

    # -- epoch operations against a held outcome ----------------------------

    def _held_outcome(self, cid: str) -> CeremonyOutcome:
        """The live, share-holding outcome for an epoch op.  KeyError for
        unknown ids, ValueError for non-terminal / failed / share-less
        (journal-recovered or retired) outcomes — callers see exactly
        which precondition failed."""
        out = self._results.get(cid)
        if out is None:
            if cid in self._status:
                raise ValueError(
                    f"ceremony {cid} is still {self._status[cid]}"
                )
            raise KeyError(f"unknown ceremony id {cid!r}")
        if out.status != "done":
            raise ValueError(f"ceremony {cid} is {out.status}, not done")
        if out.final_shares is None:
            raise ValueError(
                f"ceremony {cid} holds no shares (journal-recovered "
                "outcomes and retired epochs serve results only)"
            )
        return out

    def refresh(self, cid: str, seed: int | None = None) -> int:
        """Proactively refresh the held shares of ceremony ``cid`` in
        place: every share changes, the master key (and the outcome's
        public surface) does not.  Returns the new epoch number.

        Runs on the caller's thread — the work is one batched device
        evaluation (dkg_tpu.epoch.inprocess), far below convoy cost, so
        it does not compete through the admission queue.  Concurrent
        epoch ops on the same ceremony are detected by an epoch-counter
        CAS and rejected with ValueError.
        """
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.refresh_shares(fs, out.n, out.t, shares, rng)
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = np.asarray(fh.encode(fs, new))
            out.epoch = token + 1
        self.metrics.inc("service_epochs_total", kind="refresh")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="refresh"
        )
        return token + 1

    def reshare(
        self,
        cid: str,
        n_new: int,
        t_new: int,
        seed: int | None = None,
    ) -> str:
        """Reshare ceremony ``cid``'s secret into a fresh (n_new, t_new)
        sharing held under a NEW ceremony id (returned).  The source
        outcome is RETIRED — its shares are dropped (proactive security:
        two live sharings of one secret double the exposure) and further
        epoch ops on it fail; its public result stays served.  The new
        outcome carries the same master key, ``epoch`` advanced by one.
        """
        if not (1 <= t_new < (n_new + 1) / 2):
            raise ValueError(
                f"threshold must satisfy 1 <= t < (n+1)/2, got "
                f"t={t_new} n={n_new}"
            )
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.reshare_shares(
            fs, out.n, out.t, shares, n_new, t_new, rng
        )
        h = hashlib.blake2b(digest_size=6)
        h.update(f"reshare|{cid}|{n_new}|{t_new}|{token + 1}".encode())
        new_cid = h.hexdigest()
        new_out = CeremonyOutcome(
            ceremony_id=new_cid,
            status="done",
            curve=out.curve,
            n=n_new,
            t=t_new,
            master=out.master,
            qualified=(True,) * n_new,
            epoch=token + 1,
            final_shares=np.asarray(fh.encode(fs, new)),
        )
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = None  # retire the old sharing
            out.epoch = token + 1
            self._record(new_out)
        self.metrics.inc("service_epochs_total", kind="reshare")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="reshare"
        )
        return new_cid

    def sign(
        self,
        cid: str,
        msgs: list[bytes],
        *,
        prove: bool = True,
        seed: int | None = None,
        tamper=None,
    ) -> list[bytes]:
        """Threshold-sign a whole message batch under ceremony ``cid``:
        one canonical signature encoding per message.

        The workload the keys are FOR: all B messages hash to the curve
        in one counter-batched pass (sign.hash2curve), all B x (t+1)
        partials run as one batched ladder (sign.partial), and the
        aggregation is one Pippenger MSM with the message batch as a
        leading axis (sign.aggregate).

        Byzantine tolerance (``prove=True``, the default): the quorum is
        a seed-derived rotation over the ELIGIBLE signers (qualified
        minus this ceremony's quarantine), the whole partial grid is
        checked with ONE RLC-combined pass (sign.verify.rlc_verify), and
        a failing grid is bisected to the exact bad (message, signer)
        cells — the blamed signers join the per-ceremony quarantine and
        the batch transparently re-signs with substitute signers.  By
        Lagrange-at-zero algebra every honest quorum encodes the SAME
        signature bytes, so substitution is invisible to the caller.
        :class:`~dkg_tpu.service.errors.InsufficientSigners` (a
        ValueError) is raised only when eligible signers drop below t+1.

        ``tamper`` is the chaos hook (mirrors ``BatchedCeremony.run``'s):
        called with each attempt's PartialSignatures before
        verification; tests and scripts/service_storm.py use it to play
        the Byzantine signer.

        Like refresh/reshare this runs on the caller's thread against a
        snapshot of the held shares; it never mutates the outcome, so
        concurrent epoch ops are safe (and by share-refresh algebra the
        signatures they produce are identical).
        """
        from .. import sign as signing
        from ..sign import verify as sign_verify

        if not msgs:
            return []
        t0 = time.monotonic()
        ts0 = time.time()
        with self._cond:
            out = self._held_outcome(cid)
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
            qualified = out.qualified
            curve, t = out.curve, out.t
            quarantined = set(self._quarantine.get(cid, ()))
        eligible = [
            i + 1
            for i, q in enumerate(qualified)
            if q and (i + 1) not in quarantined
        ]
        h_points, _ = signing.hash_to_curve_batch(curve, list(msgs))
        t_hash = time.monotonic()
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        passes = 0
        resigns = 0
        while True:
            if len(eligible) < t + 1:
                self.metrics.inc("sign_starved_total", ceremony=cid)
                self._emit(
                    "sign_starved", ceremony=cid,
                    eligible=len(eligible), need=t + 1,
                )
                raise errors.InsufficientSigners(
                    f"ceremony {cid} has {len(eligible)} eligible "
                    f"qualified signers, needs t+1={t + 1}"
                )
            # seed-derived quorum rotation: never always-first-t+1, so
            # load (and exposure) spreads across the qualified set
            quorum = sorted(rng.sample(eligible, t + 1))
            ps = signing.partial_sign(
                curve,
                [shares[i - 1] for i in quorum],
                quorum,
                h_points,
                rng=rng,
                prove=prove,
            )
            if tamper is not None:
                ps = tamper(ps) or ps
            if not prove:
                break
            report = sign_verify.rlc_verify(ps, rng=rng)
            passes += report.passes
            if report.ok:
                break
            blamed = sorted({quorum[si] for (_bi, si) in report.bad_cells})
            resigns += 1
            with self._cond:
                self._quarantine.setdefault(cid, set()).update(blamed)
            self.metrics.inc(
                "sign_quarantined_total", len(blamed), ceremony=cid
            )
            self.metrics.inc("sign_resigns_total", ceremony=cid)
            self._emit(
                "sign_blame",
                ceremony=cid,
                blamed=blamed,
                cells=[list(c) for c in report.bad_cells],
                passes=report.passes,
            )
            eligible = [i for i in eligible if i not in blamed]
        t_partial = time.monotonic()
        sigs = signing.signature_encode(curve, signing.aggregate(ps))
        dt = time.monotonic() - t0
        self.metrics.inc("sign_requests_total", ceremony=cid)
        self.metrics.inc("sign_messages_total", len(msgs), ceremony=cid)
        if passes:
            self.metrics.inc("sign_rlc_passes_total", passes, ceremony=cid)
        self.metrics.observe("sign_seconds", dt, ceremony=cid)
        log = obslog.current()
        if log is not None:
            log.emit_span(
                "sign",
                ts0=ts0,
                mono0=t0,
                dur_s=dt,
                subs={
                    "hash_s": t_hash - t0,
                    "partial_s": t_partial - t_hash,
                    "aggregate_s": time.monotonic() - t_partial,
                },
                ceremony=cid,
                curve=curve,
                messages=len(msgs),
                signers=len(quorum),
                proved=prove,
                rlc_passes=passes,
                resigns=resigns,
            )
        return sigs

    # -- worker side --------------------------------------------------------

    def _pop_convoy(self, block: bool) -> list[_Pending] | None:
        """Head-of-queue convoy: the oldest QUEUED request plus up to
        ``batch_max - 1`` others sharing its convoy key, truncated to
        the largest ladder width that fits (never phantom-padded).
        Returns None when idle (non-blocking) or shut down."""
        with self._cond:
            while True:
                if not self._running or (self._draining and not self._queue):
                    return None
                expired = [
                    p
                    for p in self._queue
                    if p.deadline_at is not None
                    and time.monotonic() > p.deadline_at
                ]
                for p in expired:
                    self._queue.remove(p)
                    self.metrics.inc("service_expired_total", where="queued")
                    self._emit(
                        "service_expired", ceremony=p.cid, where="queued"
                    )
                    self._finish_one(
                        CeremonyOutcome(
                            ceremony_id=p.cid,
                            status="expired",
                            curve=p.req.curve,
                            n=p.req.n,
                            t=p.req.t,
                            error="DEADLINE_EXCEEDED",
                        ),
                        durable=p.req.durable,
                    )
                if self._queue:
                    break
                if not block:
                    return None
                self._cond.wait(timeout=0.2)
            head = self._queue[0]
            key = head.req.convoy_key()
            mates = [p for p in self._queue if p.req.convoy_key() == key]
            cap = min(self.batch_max, buckets.width_cap(head.req.bucket()))
            width = next(
                w for w in buckets.WIDTHS if w <= min(len(mates), cap)
            )
            convoy = mates[:width]
            for p in convoy:
                self._queue.remove(p)
                self._status[p.cid] = "running"
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self.metrics.inc("service_convoys_total")
            self._cond.notify_all()
            return convoy

    def _engine_start(self, reqs, cids):
        """Dispatch a convoy, routing through the chaos hook when a
        fault plan is installed (service.faultsvc)."""
        if self._fault_plan is not None:
            self._fault_plan.on_start(reqs)
        return start_convoy(self.runtime, reqs, cids)

    def _engine_finish(self, fl, reqs):
        if self._fault_plan is not None:
            self._fault_plan.on_finish(reqs)
        return finish_convoy(self.runtime, fl)

    def _run_once(self, convoy):
        """Synchronous start+finish of a (sub-)convoy — the bisection /
        retry lane, off the two-deep pipeline."""
        reqs = [p.req for p in convoy]
        fl = self._engine_start(reqs, [p.cid for p in convoy])
        return self._engine_finish(fl, reqs)

    def _hold(self, slot: int, convoy) -> None:
        with self._cond:
            self._held.setdefault(slot, []).append(convoy)

    def _release(self, slot: int, convoy) -> None:
        with self._cond:
            held = self._held.get(slot, [])
            if convoy in held:
                held.remove(convoy)

    def _worker(self, slot: int) -> None:
        inflight = None  # (convoy, InFlight, t_start)
        while True:
            convoy = self._pop_convoy(block=inflight is None)
            if convoy is not None:
                self._hold(slot, convoy)
                t0 = time.monotonic()
                try:
                    fl = self._engine_start(
                        [p.req for p in convoy], [p.cid for p in convoy]
                    )
                except Exception as exc:  # noqa: BLE001 — worker must survive
                    self._isolate(convoy, exc, t0)
                    self._release(slot, convoy)
                    continue
                if inflight is not None:
                    self._finish(*inflight)
                    self._release(slot, inflight[0])
                inflight = (convoy, fl, t0)
                continue
            if inflight is not None:
                self._finish(*inflight)
                self._release(slot, inflight[0])
                inflight = None
                continue
            with self._cond:
                if not self._running or (self._draining and not self._queue):
                    return

    def _watchdog_loop(self) -> None:
        """Detect and respawn dead workers (non-``Exception`` escapes or
        bookkeeping bugs kill a thread silently — without this the pool
        just shrinks until the service deadlocks).  Convoys the dead
        worker held are re-queued once, then failed: the convoy may be
        what killed it (see :data:`_MAX_CRASH_REQUEUES`)."""
        while True:
            with self._cond:
                self._cond.wait(timeout=self._watchdog_interval_s)
                if not self._running:
                    return
                for i, w in enumerate(self._workers):
                    if w.is_alive():
                        continue
                    orphans = self._held.pop(i, [])
                    self._gen += 1
                    nw = threading.Thread(
                        target=self._worker,
                        args=(i,),
                        name=f"dkg-svc-{i}r{self._gen}",
                        daemon=True,
                    )
                    self._workers[i] = nw
                    nw.start()
                    self.metrics.inc("service_worker_restarts_total")
                    self._emit("service_worker_restart", slot=i)
                    for convoy in orphans:
                        for p in convoy:
                            p.crashes += 1
                            if p.crashes > _MAX_CRASH_REQUEUES:
                                self._emit(
                                    "service_worker_crash_failed",
                                    ceremony=p.cid,
                                )
                                self.metrics.inc(
                                    "service_failed_total",
                                    kind="WORKER_CRASH",
                                )
                                self._finish_one(
                                    CeremonyOutcome(
                                        ceremony_id=p.cid,
                                        status="failed",
                                        curve=p.req.curve,
                                        n=p.req.n,
                                        t=p.req.t,
                                        error=(
                                            "WORKER_CRASH: worker died "
                                            f"{p.crashes}x holding this "
                                            "request"
                                        ),
                                    ),
                                    durable=p.req.durable,
                                )
                            else:
                                self._queue.insert(0, p)
                                self._status[p.cid] = "queued"
                                self.metrics.inc("service_requeued_total")
                    self.metrics.set_gauge(
                        "service_queue_depth", len(self._queue)
                    )
                    self._cond.notify_all()

    def _finish(self, convoy, fl, t0) -> None:
        try:
            outcomes = self._engine_finish(fl, [p.req for p in convoy])
        except Exception as exc:  # noqa: BLE001 — worker must survive
            self._isolate(convoy, exc, t0)
            return
        self._finish_outcomes(convoy, outcomes, t0)

    def _finish_outcomes(self, convoy, outcomes, t0) -> None:
        dt = time.monotonic() - t0
        # per-ceremony attribution: a width-w convoy's wall clock is
        # shared by w ceremonies (the whole-convoy time goes to the
        # service_convoy_seconds histogram below)
        share = dt / max(1, len(convoy))
        for p, out in zip(convoy, outcomes):
            out.seconds = share
            if (
                p.deadline_at is not None
                and time.monotonic() > p.deadline_at
            ):
                self.metrics.inc("service_expired_total", where="inflight")
                self._emit(
                    "service_expired", ceremony=p.cid, where="inflight"
                )
                out = CeremonyOutcome(
                    ceremony_id=out.ceremony_id,
                    status="expired",
                    curve=out.curve,
                    n=out.n,
                    t=out.t,
                    error="DEADLINE_EXCEEDED",
                    seconds=share,
                )
            with self._cond:
                self._finish_one(out, durable=p.req.durable)
        self.metrics.observe(
            "service_convoy_seconds", dt, width=str(len(convoy))
        )
        # device/host memory watermarks at the convoy boundary (no-op
        # unless runtimeobs is installed; internally throttled)
        runtimeobs.maybe_sample(phase="convoy_finish")

    # -- blast-radius isolation ---------------------------------------------

    def _isolate(self, convoy, exc, t0) -> None:
        """A (sub-)convoy raised ``exc``: contain the blast radius.

        Typed :class:`~dkg_tpu.service.errors.TransientEngineError`
        retries the WHOLE convoy (bounded, exponential backoff) — the
        work is presumed good, the engine hiccuped.  Everything else is
        presumed poison and bisected down the width ladder: healthy
        halves complete bit-identically to an undisturbed run, and the
        request still failing alone at width 1 is the culprit."""
        if isinstance(exc, errors.TransientEngineError):
            exc = self._retry_transient(convoy, exc, t0)
            if exc is None:
                return  # recovered; outcomes already recorded
            if isinstance(exc, errors.TransientEngineError):
                self._fail_convoy(convoy, exc)  # retries exhausted
                return
            # a retry surfaced a non-transient fault: bisect it
        if len(convoy) == 1:
            self._poison_one(convoy[0], exc)
            return
        self.metrics.inc("service_convoy_bisections_total")
        self._emit(
            "service_convoy_bisect",
            width=len(convoy),
            error_kind=type(exc).__name__,
        )
        mid = len(convoy) // 2
        for half in (convoy[:mid], convoy[mid:]):
            t1 = time.monotonic()
            try:
                outs = self._run_once(half)
            except Exception as e2:  # noqa: BLE001 — isolation must conclude
                self._isolate(half, e2, t1)
            else:
                self._finish_outcomes(half, outs, t1)

    def _retry_transient(self, convoy, exc, t0):
        """Bounded whole-convoy retry for a transient engine fault.
        Returns None when a retry succeeded (outcomes recorded), the
        last TransientEngineError when retries are exhausted, or a
        non-transient exception a retry surfaced (caller bisects)."""
        last = exc
        for attempt in range(1, self.retries + 1):
            self.metrics.inc("service_retries_total")
            self._emit(
                "service_retry", attempt=attempt, width=len(convoy),
                error_kind=type(last).__name__,
            )
            time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            try:
                outs = self._run_once(convoy)
            except errors.TransientEngineError as e2:
                last = e2
                self._emit(
                    "service_retry_failed", attempt=attempt,
                    error_kind=type(e2).__name__,
                )
                continue
            except Exception as e2:  # noqa: BLE001 — classified by caller
                self._emit(
                    "service_retry_surfaced", attempt=attempt,
                    error_kind=type(e2).__name__,
                )
                return e2
            self._finish_outcomes(convoy, outs, t0)
            return None
        return last

    def _poison_one(self, p, exc) -> None:
        """Width-1 failure: the request is the culprit — typed poisoned
        outcome, convoy-mates (if any) already completed elsewhere."""
        self.metrics.inc("service_poisoned_total")
        self._emit(
            "service_poisoned", ceremony=p.cid, error_kind=type(exc).__name__
        )
        self._finish_one(
            CeremonyOutcome(
                ceremony_id=p.cid,
                status="poisoned",
                curve=p.req.curve,
                n=p.req.n,
                t=p.req.t,
                error=f"PoisonedRequest: {type(exc).__name__}: {exc}",
            ),
            durable=p.req.durable,
        )

    def _fail_convoy(self, convoy, exc) -> None:
        """Terminal whole-convoy failure (transient retries exhausted,
        shutdown races): every member fails with the error KIND
        metric-labelled and obslog'd — no silent outcomes."""
        kind = type(exc).__name__
        self.metrics.inc("service_failed_total", len(convoy), kind=kind)
        self._emit("service_convoy_failed", width=len(convoy), error_kind=kind)
        with self._cond:
            for p in convoy:
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error=f"{type(exc).__name__}: {exc}",
                    ),
                    durable=p.req.durable,
                )

    def _finish_one(
        self,
        out: CeremonyOutcome,
        durable: bool = False,
    ) -> None:
        """Record a terminal outcome.  Journal the public outcome for
        durable ceremonies so recovery re-serves instead of re-running.
        The condition's lock is reentrant, so callers already holding it
        just re-enter."""
        if durable and self._journal is not None:
            self._journal.record_done(out)
        with self._cond:
            self._record(out)

    def _record(self, out: CeremonyOutcome) -> None:
        out.completed_at = time.monotonic()
        self._results[out.ceremony_id] = out
        self._status[out.ceremony_id] = out.status
        self.metrics.inc("service_completed_total", status=out.status)
        if out.seconds:
            # bucket label, not ceremony_id: a server runs unboundedly
            # many ceremonies and histogram series must stay bounded
            # (per-ceremony attribution goes through obslog/tracing)
            self.metrics.observe(
                "service_ceremony_seconds", out.seconds,
                bucket=f"{out.bucket_n}x{out.bucket_t}" if out.bucket_n else "none",
            )
        self._cond.notify_all()
