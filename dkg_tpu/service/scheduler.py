"""Admission queue + worker pool: the multi-tenant ceremony front door.

Concurrency model — THREADS, not asyncio, and deliberately so: the
work units are JAX dispatches (release the GIL inside XLA), host
transcript digests (hashlib releases the GIL), and numpy transfers —
all of which overlap fine under threads, while an asyncio design would
have to push every one of those blocking calls to an executor *anyway*
(JAX has no awaitable dispatch API) and would gain nothing but an event
loop to babysit.  The pool is the ONE sanctioned thread-spawn site in
this package (scripts/lint_lite.py DKG007); everything else in
``dkg_tpu/service/`` must stay thread-free so the concurrency story has
a single owner.

Flow:

* :meth:`CeremonyScheduler.submit` admits a request into a BOUNDED
  queue — full queue raises :class:`QueueFullError` immediately (the
  HTTP mapping is 503 + Retry-After; see examples/serve.py).  Admission
  is the durability point: with a WAL dir configured, the request
  record is fsync'd before submit returns the ceremony id.
* workers pop *convoys*: the queue head plus up to ``batch_max - 1``
  more QUEUED requests sharing its convoy key (curve, bucket, rho_bits,
  shared string), truncated to the width ladder so only ladder-width
  programs ever compile.  Same-bucket traffic thus amortizes one
  dispatch across the whole convoy — on hosts where per-op dispatch
  overhead dominates small ceremonies, this is where the throughput is.
* each worker runs a TWO-DEEP pipeline generalizing
  ``hybrid_batch.seal_shares_pipeline``: it *starts* (dispatches) convoy
  k+1 before *finishing* (host transcript + verify + finalise) convoy
  k, so host work rides under the device's dispatch shadow.
* deadlines are enforced at pop (an expired ceremony never starts) and
  at finish (a ceremony that expired mid-flight reports ``expired``,
  not ``done``).

Knobs (all validated through utils.envknobs; constructor arguments
win): ``DKG_TPU_SERVICE_CONCURRENCY`` (workers, default 4),
``DKG_TPU_SERVICE_QUEUE_DEPTH`` (admission bound, default 256),
``DKG_TPU_SERVICE_BATCH_MAX`` (max convoy width, default 8, capped by
the bucket ladder), ``DKG_TPU_SERVICE_DEADLINE_S`` (default per-request
deadline, unset = none), ``DKG_TPU_SERVICE_WAL_DIR`` (durability
journal directory, unset = durability off).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time

import numpy as np

from ..epoch import inprocess as epoch_inprocess
from ..fields import host as fh
from ..groups import host as gh
from ..utils import envknobs, obslog
from ..utils.metrics import REGISTRY
from . import buckets
from .durable import ServiceJournal
from .engine import (
    CeremonyOutcome,
    CeremonyRequest,
    WarmRuntime,
    finish_convoy,
    request_id,
    start_convoy,
)


class QueueFullError(RuntimeError):
    """Admission queue at capacity — the caller should back off and
    retry (HTTP 503).  Raised instead of blocking: a DKG client can
    retry cheaply, while an unbounded queue turns overload into
    unbounded latency for everyone already queued."""


class _Pending:
    __slots__ = ("cid", "seq", "req", "deadline_at")

    def __init__(self, cid, seq, req, deadline_at):
        self.cid = cid
        self.seq = seq
        self.req = req
        self.deadline_at = deadline_at


class CeremonyScheduler:
    """Bounded-admission ceremony scheduler over one warm runtime.

    Use as a context manager or call :meth:`close`.  Thread-safe: any
    thread may submit/poll/result concurrently.
    """

    def __init__(
        self,
        *,
        concurrency: int | None = None,
        queue_depth: int | None = None,
        batch_max: int | None = None,
        deadline_s: float | None = None,
        wal_dir: str | None = None,
        runtime: WarmRuntime | None = None,
        metrics=REGISTRY,
    ) -> None:
        if concurrency is None:
            concurrency = envknobs.pos_int(
                "DKG_TPU_SERVICE_CONCURRENCY", "scheduler worker threads"
            ) or 4
        if queue_depth is None:
            queue_depth = envknobs.pos_int(
                "DKG_TPU_SERVICE_QUEUE_DEPTH", "admission queue bound"
            ) or 256
        if batch_max is None:
            batch_max = envknobs.pos_int(
                "DKG_TPU_SERVICE_BATCH_MAX", "max stacked-convoy width"
            ) or buckets.WIDTHS[0]
        if deadline_s is None:
            deadline_s = envknobs.pos_float(
                "DKG_TPU_SERVICE_DEADLINE_S", "default per-ceremony deadline"
            )
        if wal_dir is None:
            wal_dir = envknobs.string(
                "DKG_TPU_SERVICE_WAL_DIR", "service durability journal directory"
            )
        self.concurrency = concurrency
        self.queue_depth = queue_depth
        self.batch_max = min(batch_max, buckets.WIDTHS[0])
        self.default_deadline_s = deadline_s
        self.runtime = runtime if runtime is not None else WarmRuntime()
        self.metrics = metrics
        self._cond = threading.Condition()
        self._queue: list[_Pending] = []
        self._results: dict[str, CeremonyOutcome] = {}
        self._status: dict[str, str] = {}
        self._seq = 0
        self._running = True
        self._draining = False
        self._journal = ServiceJournal(wal_dir) if wal_dir else None
        if self._journal is not None:
            self._recover()
        # the one sanctioned thread-spawn site in dkg_tpu/service/
        # (lint DKG007): daemon so a crashed main thread never hangs on
        # ceremony workers
        self._workers = [
            threading.Thread(
                target=self._worker, name=f"dkg-svc-{i}", daemon=True
            )
            for i in range(concurrency)
        ]
        for w in self._workers:
            w.start()

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))

    def close(self, drain: bool = True) -> None:
        """Stop the workers.  ``drain`` finishes everything already
        admitted first; otherwise still-queued ceremonies complete as
        ``failed`` with a shutdown error (durable ones stay pending in
        the journal and are resubmitted on the next recovery)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            if drain:
                while self._queue:
                    self._cond.wait(timeout=0.1)
            self._running = False
            dropped = list(self._queue)
            self._queue.clear()
            for p in dropped:
                # durable drops are NOT journalled as done: they stay
                # pending in the WAL and the next recovery resubmits them
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error="SHUTDOWN",
                    ),
                )
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout=60)

    def _recover(self) -> None:
        """Replay the journal: re-serve terminal outcomes, resubmit
        pending (admitted-but-unfinished) ceremonies under their
        original ids, and compact the log."""
        pending, terminal = self._journal.replay()
        self._journal.compact(pending, terminal)
        for cid, out in terminal.items():
            self._results[cid] = out
            self._status[cid] = out.status
        now = time.monotonic()
        for cid, (seq, req) in pending.items():
            self._seq = max(self._seq, seq + 1)
            deadline = (
                now + req.deadline_s if req.deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline))
            self._status[cid] = "queued"
        self.metrics.set_gauge("service_queue_depth", len(self._queue))
        if pending:
            self.metrics.inc("service_recovered_total", len(pending))

    # -- client surface -----------------------------------------------------

    def submit(self, req: CeremonyRequest) -> str:
        """Admit a ceremony; returns its id or raises
        :class:`QueueFullError` (backpressure) / ``ValueError`` (bad
        request — including unbucketable shapes and unseeded durable
        requests, both rejected before touching the queue)."""
        buckets.bucket_for(req.n, req.t)  # validates; raises ValueError
        if req.durable and req.seed is None:
            raise ValueError(
                "durable ceremonies must be seeded: the journal replays "
                "the seed, not the coefficients"
            )
        if req.durable and self._journal is None:
            raise ValueError(
                "durable ceremony submitted but the scheduler has no WAL "
                "dir (DKG_TPU_SERVICE_WAL_DIR / wal_dir=)"
            )
        deadline_s = (
            req.deadline_s
            if req.deadline_s is not None
            else self.default_deadline_s
        )
        with self._cond:
            if not self._running or self._draining:
                raise QueueFullError("scheduler is shutting down")
            if len(self._queue) >= self.queue_depth:
                self.metrics.inc("service_rejected_total")
                raise QueueFullError(
                    f"admission queue full ({self.queue_depth})"
                )
            seq = self._seq
            self._seq += 1
            cid = request_id(req, seq)
            if req.durable:
                self._journal.record_request(cid, seq, req)
            deadline_at = (
                time.monotonic() + deadline_s if deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline_at))
            self._status[cid] = "queued"
            self.metrics.inc("service_submitted_total")
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self._cond.notify()
        return cid

    def poll(self, cid: str) -> str:
        """Current status: queued | running | done | failed | expired —
        or ``unknown`` for an id this scheduler never admitted."""
        with self._cond:
            return self._status.get(cid, "unknown")

    def result(self, cid: str, timeout: float | None = None) -> CeremonyOutcome:
        """Block until ``cid`` reaches a terminal status and return its
        outcome (TimeoutError on timeout, KeyError for unknown ids)."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._cond:
            if cid not in self._status:
                raise KeyError(f"unknown ceremony id {cid!r}")
            while cid not in self._results:
                remain = None
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        raise TimeoutError(
                            f"ceremony {cid} still {self._status[cid]}"
                        )
                self._cond.wait(timeout=remain)
            return self._results[cid]

    # -- epoch operations against a held outcome ----------------------------

    def _held_outcome(self, cid: str) -> CeremonyOutcome:
        """The live, share-holding outcome for an epoch op.  KeyError for
        unknown ids, ValueError for non-terminal / failed / share-less
        (journal-recovered or retired) outcomes — callers see exactly
        which precondition failed."""
        out = self._results.get(cid)
        if out is None:
            if cid in self._status:
                raise ValueError(
                    f"ceremony {cid} is still {self._status[cid]}"
                )
            raise KeyError(f"unknown ceremony id {cid!r}")
        if out.status != "done":
            raise ValueError(f"ceremony {cid} is {out.status}, not done")
        if out.final_shares is None:
            raise ValueError(
                f"ceremony {cid} holds no shares (journal-recovered "
                "outcomes and retired epochs serve results only)"
            )
        return out

    def refresh(self, cid: str, seed: int | None = None) -> int:
        """Proactively refresh the held shares of ceremony ``cid`` in
        place: every share changes, the master key (and the outcome's
        public surface) does not.  Returns the new epoch number.

        Runs on the caller's thread — the work is one batched device
        evaluation (dkg_tpu.epoch.inprocess), far below convoy cost, so
        it does not compete through the admission queue.  Concurrent
        epoch ops on the same ceremony are detected by an epoch-counter
        CAS and rejected with ValueError.
        """
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.refresh_shares(fs, out.n, out.t, shares, rng)
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = np.asarray(fh.encode(fs, new))
            out.epoch = token + 1
        self.metrics.inc("service_epochs_total", kind="refresh")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="refresh"
        )
        return token + 1

    def reshare(
        self,
        cid: str,
        n_new: int,
        t_new: int,
        seed: int | None = None,
    ) -> str:
        """Reshare ceremony ``cid``'s secret into a fresh (n_new, t_new)
        sharing held under a NEW ceremony id (returned).  The source
        outcome is RETIRED — its shares are dropped (proactive security:
        two live sharings of one secret double the exposure) and further
        epoch ops on it fail; its public result stays served.  The new
        outcome carries the same master key, ``epoch`` advanced by one.
        """
        if not (1 <= t_new < (n_new + 1) / 2):
            raise ValueError(
                f"threshold must satisfy 1 <= t < (n+1)/2, got "
                f"t={t_new} n={n_new}"
            )
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.reshare_shares(
            fs, out.n, out.t, shares, n_new, t_new, rng
        )
        h = hashlib.blake2b(digest_size=6)
        h.update(f"reshare|{cid}|{n_new}|{t_new}|{token + 1}".encode())
        new_cid = h.hexdigest()
        new_out = CeremonyOutcome(
            ceremony_id=new_cid,
            status="done",
            curve=out.curve,
            n=n_new,
            t=t_new,
            master=out.master,
            qualified=(True,) * n_new,
            epoch=token + 1,
            final_shares=np.asarray(fh.encode(fs, new)),
        )
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = None  # retire the old sharing
            out.epoch = token + 1
            self._record(new_out)
        self.metrics.inc("service_epochs_total", kind="reshare")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="reshare"
        )
        return new_cid

    def sign(
        self,
        cid: str,
        msgs: list[bytes],
        *,
        prove: bool = True,
        seed: int | None = None,
    ) -> list[bytes]:
        """Threshold-sign a whole message batch under ceremony ``cid``:
        one canonical signature encoding per message.

        The workload the keys are FOR: all B messages hash to the curve
        in one counter-batched pass (sign.hash2curve), all B x (t+1)
        partials run as one batched ladder (sign.partial), and the
        aggregation is one Pippenger MSM with the message batch as a
        leading axis (sign.aggregate).  With ``prove`` (the default)
        each partial carries a DLEQ proof and the whole grid is checked
        in one ``dleq_batch.verify_batch`` pass before aggregation — a
        corrupted partial raises instead of producing a bad signature.

        Like refresh/reshare this runs on the caller's thread against a
        snapshot of the held shares; it never mutates the outcome, so
        concurrent epoch ops are safe (and by share-refresh algebra the
        signatures they produce are identical).
        """
        from .. import sign as signing

        if not msgs:
            return []
        t0 = time.monotonic()
        ts0 = time.time()
        with self._cond:
            out = self._held_outcome(cid)
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
            qualified = out.qualified
            curve, t = out.curve, out.t
        indices = [i + 1 for i, q in enumerate(qualified) if q]
        if len(indices) < t + 1:
            raise ValueError(
                f"ceremony {cid} has {len(indices)} qualified signers, "
                f"needs t+1={t + 1}"
            )
        indices = indices[: t + 1]
        signer_shares = [shares[i - 1] for i in indices]
        h_points, _ = signing.hash_to_curve_batch(curve, list(msgs))
        t_hash = time.monotonic()
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        ps = signing.partial_sign(
            curve, signer_shares, indices, h_points, rng=rng, prove=prove
        )
        if prove:
            ok = signing.verify_partials(ps)
            if not ok.all():
                bad = int((~ok).sum())
                raise RuntimeError(
                    f"{bad} partial signature(s) failed DLEQ verification "
                    f"for ceremony {cid}"
                )
        t_partial = time.monotonic()
        sigs = signing.signature_encode(curve, signing.aggregate(ps))
        dt = time.monotonic() - t0
        self.metrics.inc("sign_requests_total", ceremony=cid)
        self.metrics.inc("sign_messages_total", len(msgs), ceremony=cid)
        self.metrics.observe("sign_seconds", dt, ceremony=cid)
        log = obslog.current()
        if log is not None:
            log.emit_span(
                "sign",
                ts0=ts0,
                mono0=t0,
                dur_s=dt,
                subs={
                    "hash_s": t_hash - t0,
                    "partial_s": t_partial - t_hash,
                    "aggregate_s": time.monotonic() - t_partial,
                },
                ceremony=cid,
                curve=curve,
                messages=len(msgs),
                signers=len(indices),
                proved=prove,
            )
        return sigs

    # -- worker side --------------------------------------------------------

    def _pop_convoy(self, block: bool) -> list[_Pending] | None:
        """Head-of-queue convoy: the oldest QUEUED request plus up to
        ``batch_max - 1`` others sharing its convoy key, truncated to
        the largest ladder width that fits (never phantom-padded).
        Returns None when idle (non-blocking) or shut down."""
        with self._cond:
            while True:
                if not self._running or (self._draining and not self._queue):
                    return None
                expired = [
                    p
                    for p in self._queue
                    if p.deadline_at is not None
                    and time.monotonic() > p.deadline_at
                ]
                for p in expired:
                    self._queue.remove(p)
                    self._finish_one(
                        CeremonyOutcome(
                            ceremony_id=p.cid,
                            status="expired",
                            curve=p.req.curve,
                            n=p.req.n,
                            t=p.req.t,
                            error="DEADLINE_EXCEEDED",
                        ),
                        durable=p.req.durable,
                    )
                if self._queue:
                    break
                if not block:
                    return None
                self._cond.wait(timeout=0.2)
            head = self._queue[0]
            key = head.req.convoy_key()
            mates = [p for p in self._queue if p.req.convoy_key() == key]
            cap = min(self.batch_max, buckets.width_cap(head.req.bucket()))
            width = next(
                w for w in buckets.WIDTHS if w <= min(len(mates), cap)
            )
            convoy = mates[:width]
            for p in convoy:
                self._queue.remove(p)
                self._status[p.cid] = "running"
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self.metrics.inc("service_convoys_total")
            self._cond.notify_all()
            return convoy

    def _worker(self) -> None:
        inflight = None  # (convoy, InFlight, t_start)
        while True:
            convoy = self._pop_convoy(block=inflight is None)
            if convoy is not None:
                t0 = time.monotonic()
                try:
                    fl = start_convoy(
                        self.runtime,
                        [p.req for p in convoy],
                        [p.cid for p in convoy],
                    )
                except Exception as exc:  # noqa: BLE001 — worker must survive
                    self._fail_convoy(convoy, exc)
                    continue
                if inflight is not None:
                    self._finish(*inflight)
                inflight = (convoy, fl, t0)
                continue
            if inflight is not None:
                self._finish(*inflight)
                inflight = None
                continue
            with self._cond:
                if not self._running or (self._draining and not self._queue):
                    return

    def _finish(self, convoy, fl, t0) -> None:
        try:
            outcomes = finish_convoy(self.runtime, fl)
        except Exception as exc:  # noqa: BLE001 — worker must survive
            self._fail_convoy(convoy, exc)
            return
        dt = time.monotonic() - t0
        # per-ceremony attribution: a width-w convoy's wall clock is
        # shared by w ceremonies (the whole-convoy time goes to the
        # service_convoy_seconds histogram below)
        share = dt / max(1, len(convoy))
        for p, out in zip(convoy, outcomes):
            out.seconds = share
            if (
                p.deadline_at is not None
                and time.monotonic() > p.deadline_at
            ):
                out = CeremonyOutcome(
                    ceremony_id=out.ceremony_id,
                    status="expired",
                    curve=out.curve,
                    n=out.n,
                    t=out.t,
                    error="DEADLINE_EXCEEDED",
                    seconds=share,
                )
            with self._cond:
                self._finish_one(out, durable=p.req.durable)
        self.metrics.observe(
            "service_convoy_seconds", dt, width=str(len(convoy))
        )

    def _fail_convoy(self, convoy, exc) -> None:
        with self._cond:
            for p in convoy:
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error=f"{type(exc).__name__}: {exc}",
                    ),
                    durable=p.req.durable,
                )

    def _finish_one(
        self,
        out: CeremonyOutcome,
        durable: bool = False,
    ) -> None:
        """Record a terminal outcome.  Journal the public outcome for
        durable ceremonies so recovery re-serves instead of re-running.
        The condition's lock is reentrant, so callers already holding it
        just re-enter."""
        if durable and self._journal is not None:
            self._journal.record_done(out)
        with self._cond:
            self._record(out)

    def _record(self, out: CeremonyOutcome) -> None:
        out.completed_at = time.monotonic()
        self._results[out.ceremony_id] = out
        self._status[out.ceremony_id] = out.status
        self.metrics.inc("service_completed_total", status=out.status)
        if out.seconds:
            # bucket label, not ceremony_id: a server runs unboundedly
            # many ceremonies and histogram series must stay bounded
            # (per-ceremony attribution goes through obslog/tracing)
            self.metrics.observe(
                "service_ceremony_seconds", out.seconds,
                bucket=f"{out.bucket_n}x{out.bucket_t}" if out.bucket_n else "none",
            )
        self._cond.notify_all()
