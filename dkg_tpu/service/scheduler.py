"""Admission queue + worker pool: the multi-tenant ceremony front door.

Concurrency model — THREADS, not asyncio, and deliberately so: the
work units are JAX dispatches (release the GIL inside XLA), host
transcript digests (hashlib releases the GIL), and numpy transfers —
all of which overlap fine under threads, while an asyncio design would
have to push every one of those blocking calls to an executor *anyway*
(JAX has no awaitable dispatch API) and would gain nothing but an event
loop to babysit.  The pool here and the scrape-server thread in
service/httpobs.py are the only sanctioned thread-spawn sites in this
package (scripts/lint_lite.py DKG007); everything else in
``dkg_tpu/service/`` must stay thread-free so the concurrency story has
few owners.

Flow:

* :meth:`CeremonyScheduler.submit` admits a request into a BOUNDED
  queue — full queue raises :class:`QueueFullError` immediately (the
  HTTP mapping is 503 + Retry-After; see examples/serve.py).  Admission
  is the durability point: with a WAL dir configured, the request
  record is fsync'd before submit returns the ceremony id.
* workers pop *convoys*: the queue head plus up to ``batch_max - 1``
  more QUEUED requests sharing its convoy key (curve, bucket, rho_bits,
  shared string), truncated to the width ladder so only ladder-width
  programs ever compile.  Same-bucket traffic thus amortizes one
  dispatch across the whole convoy — on hosts where per-op dispatch
  overhead dominates small ceremonies, this is where the throughput is.
* each worker runs a TWO-DEEP pipeline generalizing
  ``hybrid_batch.seal_shares_pipeline``: it *starts* (dispatches) convoy
  k+1 before *finishing* (host transcript + verify + finalise) convoy
  k, so host work rides under the device's dispatch shadow.
* deadlines are enforced at pop (an expired ceremony never starts) and
  at finish (a ceremony that expired mid-flight reports ``expired``,
  not ``done``).

Blast-radius isolation (docs/fault_model.md "Service fault model"): a
convoy failure no longer dooms its width-W members wholesale.
:class:`~dkg_tpu.service.errors.TransientEngineError` retries the whole
convoy (bounded, exponential backoff); anything else BISECTS down the
width ladder — healthy halves complete normally, and the request that
still fails alone at width 1 gets the terminal ``poisoned`` status
(error names :class:`~dkg_tpu.service.errors.PoisonedRequest`).  A
watchdog thread respawns workers killed by non-``Exception`` escapes
and re-queues (once) the convoys they held.  Signing survives Byzantine
partials via RLC blame + per-ceremony signer quarantine (:meth:`sign`).

The sign lane (docs/signing.md "Steady-state lane"): a deployed DKG
signs orders of magnitude more than it runs ceremonies, so signing gets
its own queue and worker.  :meth:`sign` is submit+wait over the lane
(:meth:`sign_submit` / :meth:`sign_wait`); queued requests from ANY
ceremony coalesce into per-(curve, proved) *sign convoys*, flushed when
``sign_batch_max`` messages are queued or the head request has waited
``sign_flush_ms`` — so mixed tenants share one warm executable per
(curve, message rung) instead of one cold pipeline per caller.
Unproved traffic runs the folded-scalar fast path (one ladder dispatch
per ``buckets.SIGN_RUNGS`` slice, hashing rung k+1 under rung k's
dispatch shadow); proved traffic keeps the per-request grid loop —
identical rng stream, blame, and quarantine semantics to the
pre-lane path — against the warm caches in ``sign.cache.SignCache``
(decoded shares and pk ladders per (ceremony, epoch) — the epoch CAS
bump IS the invalidation — Lagrange coefficients per (curve, quorum)).
Either leg produces signature bytes bit-identical to the pre-lane
single-call path.  A request failing alone is ``PoisonedRequest``;
convoy-mates are exonerated by bisection, exactly like ceremonies.

Knobs (all validated through utils.envknobs; constructor arguments
win): ``DKG_TPU_SERVICE_CONCURRENCY`` (workers, default 4),
``DKG_TPU_SERVICE_QUEUE_DEPTH`` (admission bound, default 256),
``DKG_TPU_SERVICE_BATCH_MAX`` (max convoy width, default 8, capped by
the bucket ladder), ``DKG_TPU_SERVICE_DEADLINE_S`` (default per-request
deadline, unset = none), ``DKG_TPU_SERVICE_WAL_DIR`` (durability
journal directory, unset = durability off), ``DKG_TPU_SERVICE_RETRIES``
(transient-fault convoy retries, default 2, 0 disables),
``DKG_TPU_SERVICE_RETRY_BACKOFF_S`` (first backoff, doubling, default
0.05), ``DKG_TPU_SERVICE_MAX_REPLAYS`` (journal crash-loop guard,
default 3 — see service.durable), ``DKG_TPU_SERVICE_HTTP_PORT``
(observability scrape surface — service/httpobs; unset = off),
``DKG_TPU_RUNTIMEOBS`` (JAX compile/memory telemetry —
utils/runtimeobs), ``DKG_TPU_SLO_*`` (rolling SLO objectives —
service/slo), ``DKG_TPU_SIGN_FLUSH_MS`` (sign-lane deadline flush,
default 25), ``DKG_TPU_SIGN_BATCH_MAX`` (max messages per sign convoy,
default ``buckets.SIGN_RUNGS[0]``).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time

import numpy as np

from ..epoch import inprocess as epoch_inprocess
from ..fields import host as fh
from ..groups import host as gh
from ..utils import envknobs, obslog, runtimeobs
from ..utils.metrics import REGISTRY
from . import buckets, errors, httpobs
from .durable import ServiceJournal
from .slo import SloEvaluator
from .engine import (
    CeremonyOutcome,
    CeremonyRequest,
    WarmRuntime,
    aot_sign_folded,
    finish_convoy,
    request_id,
    start_convoy,
)
from .errors import QueueFullError  # noqa: F401 — historical home, re-exported

#: How many times a convoy orphaned by a crashed worker is re-queued
#: before its members fail with WORKER_CRASH.  One: the convoy itself
#: may be what killed the worker, so unbounded re-queueing would turn a
#: poisoned request into a worker crash-loop.
_MAX_CRASH_REQUEUES = 1


class _Pending:
    __slots__ = ("cid", "seq", "req", "deadline_at", "crashes")

    def __init__(self, cid, seq, req, deadline_at):
        self.cid = cid
        self.seq = seq
        self.req = req
        self.deadline_at = deadline_at
        self.crashes = 0  # worker-crash orphanings survived so far


class _SignPending:
    """One queued sign request: the lane's ticket.  ``done`` flips under
    ``_sign_cond`` once ``sigs`` (success) or ``error`` (typed failure,
    re-raised by :meth:`CeremonyScheduler.sign_wait`) is set."""

    __slots__ = (
        "cid", "curve", "msgs", "prove", "seed", "tamper", "enqueued_at",
        "sigs", "error", "done", "rlc_passes", "resigns", "signers",
    )

    def __init__(self, cid, curve, msgs, prove, seed, tamper):
        self.cid = cid
        self.curve = curve
        self.msgs = msgs
        self.prove = prove
        self.seed = seed
        self.tamper = tamper
        self.enqueued_at = time.monotonic()
        self.sigs = None
        self.error = None
        self.done = False
        self.rlc_passes = 0
        self.resigns = 0
        self.signers = 0


class CeremonyScheduler:
    """Bounded-admission ceremony scheduler over one warm runtime.

    Use as a context manager or call :meth:`close`.  Thread-safe: any
    thread may submit/poll/result concurrently.
    """

    def __init__(
        self,
        *,
        concurrency: int | None = None,
        queue_depth: int | None = None,
        batch_max: int | None = None,
        deadline_s: float | None = None,
        wal_dir: str | None = None,
        retries: int | None = None,
        retry_backoff_s: float | None = None,
        max_replays: int | None = None,
        sign_flush_ms: float | None = None,
        sign_batch_max: int | None = None,
        sign_cache=None,
        watchdog_interval_s: float = 0.5,
        fault_plan=None,
        log=None,
        runtime: WarmRuntime | None = None,
        metrics=REGISTRY,
        http_port: int | None = None,
        slo_policy=None,
    ) -> None:
        if concurrency is None:
            concurrency = envknobs.pos_int(
                "DKG_TPU_SERVICE_CONCURRENCY", "scheduler worker threads"
            ) or 4
        if queue_depth is None:
            queue_depth = envknobs.pos_int(
                "DKG_TPU_SERVICE_QUEUE_DEPTH", "admission queue bound"
            ) or 256
        if batch_max is None:
            batch_max = envknobs.pos_int(
                "DKG_TPU_SERVICE_BATCH_MAX", "max stacked-convoy width"
            ) or buckets.WIDTHS[0]
        if deadline_s is None:
            deadline_s = envknobs.pos_float(
                "DKG_TPU_SERVICE_DEADLINE_S", "default per-ceremony deadline"
            )
        if wal_dir is None:
            wal_dir = envknobs.string(
                "DKG_TPU_SERVICE_WAL_DIR", "service durability journal directory"
            )
        if retries is None:
            retries = envknobs.nonneg_int(
                "DKG_TPU_SERVICE_RETRIES",
                "transient-fault convoy retries (0 disables)",
            )
            retries = 2 if retries is None else retries
        if retry_backoff_s is None:
            retry_backoff_s = envknobs.nonneg_float(
                "DKG_TPU_SERVICE_RETRY_BACKOFF_S",
                "first transient-retry backoff, doubling per attempt",
            )
            retry_backoff_s = 0.05 if retry_backoff_s is None else retry_backoff_s
        if max_replays is None:
            max_replays = envknobs.pos_int(
                "DKG_TPU_SERVICE_MAX_REPLAYS",
                "journal replays before a pending ceremony is poisoned",
            ) or 3
        if sign_flush_ms is None:
            sign_flush_ms = envknobs.nonneg_float(
                "DKG_TPU_SIGN_FLUSH_MS",
                "sign-lane deadline flush in milliseconds (0 = immediate)",
            )
            sign_flush_ms = 25.0 if sign_flush_ms is None else sign_flush_ms
        if sign_batch_max is None:
            sign_batch_max = envknobs.pos_int(
                "DKG_TPU_SIGN_BATCH_MAX", "max messages per sign convoy"
            ) or buckets.SIGN_RUNGS[0]
        from ..sign.cache import SignCache  # lazy like the sign() leg

        self.concurrency = concurrency
        self.queue_depth = queue_depth
        self.batch_max = min(batch_max, buckets.WIDTHS[0])
        self.sign_flush_s = sign_flush_ms / 1000.0
        self.sign_batch_max = sign_batch_max
        self.sign_cache = sign_cache if sign_cache is not None else SignCache()
        self.default_deadline_s = deadline_s
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        self.max_replays = max_replays
        self.runtime = runtime if runtime is not None else WarmRuntime()
        self.metrics = metrics
        self._fault_plan = fault_plan
        self._own_log = log is None
        self._log = log if log is not None else obslog.from_env()
        self._cond = threading.Condition()
        self._queue: list[_Pending] = []
        self._results: dict[str, CeremonyOutcome] = {}
        self._status: dict[str, str] = {}
        self._quarantine: dict[str, set[int]] = {}
        self._held: dict[int, list] = {}  # worker slot -> convoys in hand
        self._seq = 0
        self._gen = 0  # respawn generation, for unique thread names
        self._running = True
        self._draining = False
        # sign lane state: its OWN condition so coalescing/waking sign
        # traffic never contends with ceremony admission.  Lock order:
        # _cond may be taken while holding nothing; _sign_cond likewise;
        # _cond -> _sign_cond is allowed (watchdog), _sign_cond -> _cond
        # is FORBIDDEN — lane code snapshots under _cond first, releases,
        # then takes _sign_cond to deliver.
        self._sign_cond = threading.Condition()
        self._sign_queue: list[_SignPending] = []
        self._sign_inflight: list[_SignPending] = []
        self._sign_gen = 0
        self._watchdog_interval_s = watchdog_interval_s
        self._journal = ServiceJournal(wal_dir) if wal_dir else None
        if self._journal is not None:
            self._recover()
        # the one sanctioned thread-spawn site in dkg_tpu/service/
        # (lint DKG007): daemon so a crashed main thread never hangs on
        # ceremony workers
        self._workers = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"dkg-svc-{i}", daemon=True
            )
            for i in range(concurrency)
        ]
        for w in self._workers:
            w.start()
        self._sign_thread = threading.Thread(
            target=self._sign_worker, name="dkg-svc-sign", daemon=True
        )
        self._sign_thread.start()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="dkg-svc-watchdog", daemon=True
        )
        self._watchdog.start()
        # runtime introspection (knob-gated: DKG_TPU_RUNTIMEOBS=on — a
        # no-op returning False otherwise) and the scrape surface (off
        # unless http_port / DKG_TPU_SERVICE_HTTP_PORT is configured)
        runtimeobs.install(registry=metrics, log=self._log)
        self.slo = SloEvaluator(registry=metrics, policy=slo_policy)
        self._http = httpobs.maybe_start(
            registry=metrics,
            health_fn=self.health,
            slo_fn=self.slo_report,
            log=self._log,
            port=http_port,
        )

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))

    def close(self, drain: bool = True) -> None:
        """Stop the workers.  ``drain`` finishes everything already
        admitted first; otherwise still-queued ceremonies complete as
        ``failed`` with a shutdown error (durable ones stay pending in
        the journal and are resubmitted on the next recovery)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            if drain:
                while self._queue:
                    self._cond.wait(timeout=0.1)
        # drain the sign lane BEFORE flipping _running: the lane flushes
        # immediately once _draining is up, and queued tickets complete
        # normally (drain) instead of failing
        with self._sign_cond:
            self._sign_cond.notify_all()
            if drain:
                while self._sign_queue or self._sign_inflight:
                    self._sign_cond.wait(timeout=0.1)
        with self._cond:
            self._running = False
            dropped = list(self._queue)
            self._queue.clear()
            for p in dropped:
                # durable drops are NOT journalled as done: they stay
                # pending in the WAL and the next recovery resubmits them
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error="SHUTDOWN",
                    ),
                )
            self._cond.notify_all()
        with self._sign_cond:
            for p in self._sign_queue:
                if not p.done:
                    p.error = QueueFullError("scheduler is shutting down")
                    p.done = True
            self._sign_queue.clear()
            self._sign_cond.notify_all()
        for w in self._workers:
            w.join(timeout=60)
        self._watchdog.join(timeout=60)
        self._sign_thread.join(timeout=60)
        if self._http is not None:
            self._http.close()
        if self._own_log and self._log is not None:
            self._log.close()

    def _recover(self) -> None:
        """Replay the journal: re-serve terminal outcomes, resubmit
        pending (admitted-but-unfinished) ceremonies under their
        original ids, and compact the log.

        Crash-loop guard: a pending ceremony already replayed
        ``max_replays`` times is the likely CAUSE of the crashes it
        keeps surviving — it completes as ``poisoned`` instead of being
        re-queued for another round of taking the process down."""
        pending, terminal, replays = self._journal.replay()
        self._journal.compact(pending, terminal, replays)
        for cid, out in terminal.items():
            self._results[cid] = out
            self._status[cid] = out.status
        now = time.monotonic()
        recovered = 0
        for cid, (seq, req) in pending.items():
            self._seq = max(self._seq, seq + 1)
            count = replays.get(cid, 0)
            if count >= self.max_replays:
                self.metrics.inc("service_poisoned_total")
                self._emit(
                    "service_replay_poisoned", ceremony=cid, replays=count
                )
                out = CeremonyOutcome(
                    ceremony_id=cid,
                    status="poisoned",
                    curve=req.curve,
                    n=req.n,
                    t=req.t,
                    error=(
                        f"PoisonedRequest: REPLAY_LIMIT "
                        f"(replayed {count}x, max {self.max_replays})"
                    ),
                )
                self._journal.record_done(out)
                self._results[cid] = out
                self._status[cid] = out.status
                continue
            self._journal.record_replay(cid, count + 1)
            deadline = (
                now + req.deadline_s if req.deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline))
            self._status[cid] = "queued"
            recovered += 1
        self.metrics.set_gauge("service_queue_depth", len(self._queue))
        if recovered:
            self.metrics.inc("service_recovered_total", recovered)

    def _emit(self, kind: str, **fields) -> None:
        """Flight-recorder event, KIND-only error attribution — never a
        message payload (redaction contract: an exception string may
        embed share/seed material; the emitted stream must not)."""
        if self._log is not None:
            self._log.emit(kind, **fields)

    # -- client surface -----------------------------------------------------

    def submit(self, req: CeremonyRequest) -> str:
        """Admit a ceremony; returns its id or raises
        :class:`QueueFullError` (backpressure) / ``ValueError`` (bad
        request — including unbucketable shapes and unseeded durable
        requests, both rejected before touching the queue)."""
        buckets.bucket_for(req.n, req.t)  # validates; raises ValueError
        if req.durable and req.seed is None:
            raise ValueError(
                "durable ceremonies must be seeded: the journal replays "
                "the seed, not the coefficients"
            )
        if req.durable and self._journal is None:
            raise ValueError(
                "durable ceremony submitted but the scheduler has no WAL "
                "dir (DKG_TPU_SERVICE_WAL_DIR / wal_dir=)"
            )
        deadline_s = (
            req.deadline_s
            if req.deadline_s is not None
            else self.default_deadline_s
        )
        with self._cond:
            if not self._running or self._draining:
                self.metrics.inc("service_rejected_total")
                self._emit("service_rejected", error_kind="SHUTTING_DOWN")
                raise QueueFullError("scheduler is shutting down")
            if len(self._queue) >= self.queue_depth:
                self.metrics.inc("service_rejected_total")
                self._emit("service_rejected", error_kind="QUEUE_FULL")
                raise QueueFullError(
                    f"admission queue full ({self.queue_depth})"
                )
            seq = self._seq
            self._seq += 1
            cid = request_id(req, seq)
            if req.durable:
                self._journal.record_request(cid, seq, req)
            deadline_at = (
                time.monotonic() + deadline_s if deadline_s is not None else None
            )
            self._queue.append(_Pending(cid, seq, req, deadline_at))
            self._status[cid] = "queued"
            self.metrics.inc("service_submitted_total")
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self._cond.notify()
        return cid

    def health(self) -> dict:
        """Liveness dict (the ``/healthz`` payload — service/httpobs):
        ``ok`` means accepting work with a live pool.  Dead workers are
        watchdog-respawned, so the bar is "any worker alive", not "all";
        a fully dead pool or a closed/draining scheduler reads not-ok."""
        with self._cond:
            alive = sum(1 for w in self._workers if w.is_alive())
            total = len(self._workers)
            depth = len(self._queue)
            running = self._running
            draining = self._draining
        return {
            "ok": bool(running and not draining and alive > 0),
            "running": running,
            "draining": draining,
            "workers_alive": alive,
            "workers_total": total,
            "queue_depth": depth,
            "queue_capacity": self.queue_depth,
            "wal": "ok" if self._journal is not None else "off",
        }

    def slo_report(self) -> dict:
        """Rolling-window SLO judgment (the ``/slo`` payload — see
        service/slo.py for the window/quantile/error-budget math)."""
        return self.slo.report()

    def poll(self, cid: str) -> str:
        """Current status: queued | running | done | failed | expired |
        poisoned — or ``unknown`` for an id this scheduler never
        admitted."""
        with self._cond:
            return self._status.get(cid, "unknown")

    def manifest(self) -> dict[str, str]:
        """Every ceremony id this scheduler knows, with its current
        status — the post-recovery inventory a fleet parent uses to
        repopulate its placement map after respawning a worker from a
        slot journal (service/fleet.py's ``manifest`` pipe op).  Covers
        queued/running work and terminal outcomes alike; an id absent
        here after a journal recovery was genuinely never accepted (or
        was non-durable) and is reported lost, not resurrected."""
        with self._cond:
            return dict(self._status)

    def result(self, cid: str, timeout: float | None = None) -> CeremonyOutcome:
        """Block until ``cid`` reaches a terminal status and return its
        outcome (TimeoutError on timeout, KeyError for unknown ids)."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._cond:
            if cid not in self._status:
                raise KeyError(f"unknown ceremony id {cid!r}")
            while cid not in self._results:
                remain = None
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        raise TimeoutError(
                            f"ceremony {cid} still {self._status[cid]}"
                        )
                self._cond.wait(timeout=remain)
            return self._results[cid]

    def quarantined(self, cid: str) -> frozenset[int]:
        """The 1-based signer indices quarantined for ceremony ``cid``
        (Byzantine partials caught by :meth:`sign`'s RLC blame)."""
        with self._cond:
            return frozenset(self._quarantine.get(cid, ()))

    # -- epoch operations against a held outcome ----------------------------

    def _held_outcome(self, cid: str) -> CeremonyOutcome:
        """The live, share-holding outcome for an epoch op.  KeyError for
        unknown ids, ValueError for non-terminal / failed / share-less
        (journal-recovered or retired) outcomes — callers see exactly
        which precondition failed."""
        out = self._results.get(cid)
        if out is None:
            if cid in self._status:
                raise ValueError(
                    f"ceremony {cid} is still {self._status[cid]}"
                )
            raise KeyError(f"unknown ceremony id {cid!r}")
        if out.status != "done":
            raise ValueError(f"ceremony {cid} is {out.status}, not done")
        if out.final_shares is None:
            raise ValueError(
                f"ceremony {cid} holds no shares (journal-recovered "
                "outcomes and retired epochs serve results only)"
            )
        return out

    def refresh(self, cid: str, seed: int | None = None) -> int:
        """Proactively refresh the held shares of ceremony ``cid`` in
        place: every share changes, the master key (and the outcome's
        public surface) does not.  Returns the new epoch number.

        Runs on the caller's thread — the work is one batched device
        evaluation (dkg_tpu.epoch.inprocess), far below convoy cost, so
        it does not compete through the admission queue.  Concurrent
        epoch ops on the same ceremony are detected by an epoch-counter
        CAS and rejected with ValueError.
        """
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.refresh_shares(fs, out.n, out.t, shares, rng)
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = np.asarray(fh.encode(fs, new))
            out.epoch = token + 1
        self.metrics.inc("service_epochs_total", kind="refresh")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="refresh"
        )
        return token + 1

    def reshare(
        self,
        cid: str,
        n_new: int,
        t_new: int,
        seed: int | None = None,
    ) -> str:
        """Reshare ceremony ``cid``'s secret into a fresh (n_new, t_new)
        sharing held under a NEW ceremony id (returned).  The source
        outcome is RETIRED — its shares are dropped (proactive security:
        two live sharings of one secret double the exposure) and further
        epoch ops on it fail; its public result stays served.  The new
        outcome carries the same master key, ``epoch`` advanced by one.
        """
        if not (1 <= t_new < (n_new + 1) / 2):
            raise ValueError(
                f"threshold must satisfy 1 <= t < (n+1)/2, got "
                f"t={t_new} n={n_new}"
            )
        t0 = time.monotonic()
        with self._cond:
            out = self._held_outcome(cid)
            token = out.epoch
            fs = gh.ALL_GROUPS[out.curve].scalar_field
            shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        rng = random.Random(seed) if seed is not None else random.SystemRandom()
        new = epoch_inprocess.reshare_shares(
            fs, out.n, out.t, shares, n_new, t_new, rng
        )
        h = hashlib.blake2b(digest_size=6)
        h.update(f"reshare|{cid}|{n_new}|{t_new}|{token + 1}".encode())
        new_cid = h.hexdigest()
        new_out = CeremonyOutcome(
            ceremony_id=new_cid,
            status="done",
            curve=out.curve,
            n=n_new,
            t=t_new,
            master=out.master,
            qualified=(True,) * n_new,
            epoch=token + 1,
            final_shares=np.asarray(fh.encode(fs, new)),
        )
        with self._cond:
            if self._results.get(cid) is not out or out.epoch != token:
                raise ValueError(f"concurrent epoch operation on {cid}")
            out.final_shares = None  # retire the old sharing
            out.epoch = token + 1
            self._record(new_out)
        self.metrics.inc("service_epochs_total", kind="reshare")
        self.metrics.observe(
            "service_epoch_seconds", time.monotonic() - t0, kind="reshare"
        )
        return new_cid

    def sign(
        self,
        cid: str,
        msgs: list[bytes],
        *,
        prove: bool = True,
        seed: int | None = None,
        tamper=None,
    ) -> list[bytes]:
        """Threshold-sign a whole message batch under ceremony ``cid``:
        one canonical signature encoding per message.

        The workload the keys are FOR: all B messages hash to the curve
        in one counter-batched pass (sign.hash2curve), all B x (t+1)
        partials run as one batched ladder (sign.partial), and the
        aggregation is one Pippenger MSM with the message batch as a
        leading axis (sign.aggregate).

        Byzantine tolerance (``prove=True``, the default): the quorum is
        a seed-derived rotation over the ELIGIBLE signers (qualified
        minus this ceremony's quarantine), the whole partial grid is
        checked with ONE RLC-combined pass (sign.verify.rlc_verify), and
        a failing grid is bisected to the exact bad (message, signer)
        cells — the blamed signers join the per-ceremony quarantine and
        the batch transparently re-signs with substitute signers.  By
        Lagrange-at-zero algebra every honest quorum encodes the SAME
        signature bytes, so substitution is invisible to the caller.
        :class:`~dkg_tpu.service.errors.InsufficientSigners` (a
        ValueError) is raised only when eligible signers drop below t+1.

        ``tamper`` is the chaos hook (mirrors ``BatchedCeremony.run``'s):
        called with each attempt's PartialSignatures before
        verification; tests and scripts/service_storm.py use it to play
        the Byzantine signer.

        Since the steady-state lane landed this is submit+wait over the
        sign queue (:meth:`sign_submit` / :meth:`sign_wait`): the
        request may coalesce with other callers' into one warm convoy,
        but the bytes, the rng-derived quorum rotation, the blame /
        quarantine behaviour, and every raised type are identical to
        running alone.  It never mutates the outcome, so concurrent
        epoch ops are safe (and by share-refresh algebra the signatures
        they produce are identical).
        """
        if not msgs:
            return []
        return self.sign_wait(
            self.sign_submit(cid, msgs, prove=prove, seed=seed, tamper=tamper)
        )

    def sign_submit(
        self,
        cid: str,
        msgs: list[bytes],
        *,
        prove: bool = True,
        seed: int | None = None,
        tamper=None,
    ) -> _SignPending:
        """Enqueue a sign request on the lane and return its ticket
        (pass to :meth:`sign_wait`).  Raises here, on the caller's
        thread, for the same preconditions the synchronous path raised
        for: KeyError (unknown ceremony), ValueError (not done /
        share-less), :class:`QueueFullError` (shutting down)."""
        with self._cond:
            out = self._held_outcome(cid)
            curve = out.curve
        p = _SignPending(cid, curve, list(msgs), prove, seed, tamper)
        with self._sign_cond:
            if not self._running or self._draining:
                raise QueueFullError("scheduler is shutting down")
            self._sign_queue.append(p)
            self.metrics.set_gauge(
                "sign_queue_depth",
                sum(len(q.msgs) for q in self._sign_queue),
            )
            self._sign_cond.notify_all()
        return p

    def sign_wait(
        self, ticket: _SignPending, timeout: float | None = None
    ) -> list[bytes]:
        """Block until the lane finishes ``ticket``; returns the
        signature bytes or re-raises the request's typed failure
        (TimeoutError on timeout, with the request still in flight)."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._sign_cond:
            while not ticket.done:
                remain = None
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        raise TimeoutError(
                            f"sign request for {ticket.cid} still in the lane"
                        )
                self._sign_cond.wait(timeout=remain)
        if ticket.error is not None:
            raise ticket.error
        return ticket.sigs

    # -- sign lane (worker side) ---------------------------------------------

    def _pop_sign_convoy(self):
        """Wait for a flush condition and pop one sign convoy: the head
        ticket plus queued mates sharing its (curve, proved) key, capped
        at ``sign_batch_max`` total messages (a lone over-wide ticket
        still pops alone — rung slicing inside the leg bounds the device
        shapes).  Flush fires when the cap is reached (``full``), when
        the head has waited ``sign_flush_ms`` (``deadline``), or
        immediately on drain/shutdown.  Returns (convoy, reason), or
        None when shut down and empty."""
        with self._sign_cond:
            while True:
                if not self._running:
                    if not self._sign_queue:
                        return None
                elif not self._sign_queue:
                    self._sign_cond.wait(timeout=0.2)
                    continue
                head = self._sign_queue[0]
                key = (head.curve, head.prove)
                mates = [
                    p
                    for p in self._sign_queue
                    if (p.curve, p.prove) == key
                ]
                total = sum(len(p.msgs) for p in mates)
                age = time.monotonic() - head.enqueued_at
                if (
                    self._running
                    and not self._draining
                    and total < self.sign_batch_max
                    and age < self.sign_flush_s
                ):
                    # more traffic may coalesce: sleep to the deadline
                    self._sign_cond.wait(timeout=self.sign_flush_s - age)
                    continue
                reason = "full" if total >= self.sign_batch_max else "deadline"
                convoy: list[_SignPending] = []
                taken = 0
                for p in mates:
                    if convoy and taken + len(p.msgs) > self.sign_batch_max:
                        break
                    convoy.append(p)
                    taken += len(p.msgs)
                for p in convoy:
                    self._sign_queue.remove(p)
                self._sign_inflight = list(convoy)
                self.metrics.inc("sign_flush_total", reason=reason)
                self.metrics.set_gauge(
                    "sign_queue_depth",
                    sum(len(q.msgs) for q in self._sign_queue),
                )
                return convoy, reason

    def _sign_worker(self) -> None:
        while True:
            popped = self._pop_sign_convoy()
            if popped is None:
                return
            convoy, reason = popped
            self._run_sign_convoy(convoy, reason)

    def _run_sign_convoy(self, convoy, reason) -> None:
        t0 = time.monotonic()
        ts0 = time.time()
        subs = {
            "hash_s": 0.0, "partial_s": 0.0,
            "verify_s": 0.0, "aggregate_s": 0.0,
        }
        try:
            self._sign_execute(convoy, subs)
        except Exception as exc:  # noqa: BLE001 — the lane must survive
            self._isolate_sign(convoy, exc, subs)
        dt = time.monotonic() - t0
        self.metrics.inc("sign_convoys_total")
        if self._log is not None:
            # the lane thread has no ambient obslog context
            # (obslog.current() is a contextvar on the caller's thread)
            # so the convoy span goes to the scheduler's own recorder
            self._log.emit_span(
                "sign_convoy",
                ts0=ts0,
                mono0=t0,
                dur_s=dt,
                subs=subs,
                curve=convoy[0].curve,
                requests=len(convoy),
                messages=sum(len(p.msgs) for p in convoy),
                ceremonies=len({p.cid for p in convoy}),
                proved=convoy[0].prove,
                reason=reason,
                errors=sum(1 for p in convoy if p.error is not None),
            )
        self._deliver_sign(convoy)

    def _deliver_sign(self, convoy) -> None:
        """Per-ticket terminal accounting (success metrics mirror the
        pre-lane synchronous path, ceremony-labelled) and waiter wakeup."""
        now = time.monotonic()
        for p in convoy:
            if p.error is None and p.sigs is None:
                # every path below should have concluded the ticket;
                # a fake/monkeypatched engine that forgot one must not
                # strand its waiter forever
                p.error = errors.TransientEngineError(
                    "SIGN_LANE_LOST: convoy concluded without a result"
                )
            if p.error is None:
                self.metrics.inc("sign_requests_total", ceremony=p.cid)
                self.metrics.inc(
                    "sign_messages_total", len(p.msgs), ceremony=p.cid
                )
                if p.rlc_passes:
                    self.metrics.inc(
                        "sign_rlc_passes_total", p.rlc_passes, ceremony=p.cid
                    )
                self.metrics.observe(
                    "sign_seconds", now - p.enqueued_at, ceremony=p.cid
                )
        with self._sign_cond:
            for p in convoy:
                p.done = True
            self._sign_inflight = []
            self._sign_cond.notify_all()

    def _sign_execute(self, convoy, subs) -> None:
        """Compute every still-live ticket in ``convoy``: the lane's
        engine surface (tests fake it the way engine tests fake
        start/finish_convoy).  Unproved, untampered tickets take the
        folded fast leg together; proved (or tampered) tickets run the
        per-request grid loop — same rng stream as the pre-lane path, so
        bytes/blame/metrics are identical.  Per-ticket failures land on
        the ticket; only convoy-shared failures raise (caller bisects).
        """
        fast, grid = [], []
        for p in convoy:
            if p.error is not None or p.sigs is not None:
                continue
            snap = self._sign_snapshot(p)
            if snap is None:
                continue  # precondition failure already on the ticket
            if p.prove or p.tamper is not None:
                grid.append((p, snap))
            else:
                fast.append((p, snap))
        self._sign_fast_leg(fast, subs)
        # proved steady traffic coalesces into ONE convoy acceptance
        # (one hash screen + one RLC-MSM, sign.verify.rlc_verify_convoy)
        # instead of a per-ticket MSM.  Seeded and tampered tickets keep
        # the per-ticket grid verbatim: a seeded request must produce
        # the same bytes, blame, and pass counts it always did.
        convoyable = [
            (p, snap)
            for p, snap in grid
            if p.prove and p.tamper is None and p.seed is None
        ]
        solo = grid
        if len(convoyable) >= 2:
            self._sign_convoy_rlc(convoyable, subs)
            taken = {id(p) for p, _snap in convoyable}
            solo = [(p, snap) for p, snap in grid if id(p) not in taken]
        for p, snap in solo:
            try:
                p.sigs = self._sign_grid_one(p, snap, subs)
            except errors.ServiceError as exc:
                p.error = exc  # typed (InsufficientSigners...): solo parity
            except Exception as exc:  # noqa: BLE001 — lane must conclude
                self._poison_sign_one(p, exc)

    def _sign_convoy_rlc(self, tickets, subs) -> None:
        """Proved-traffic convoy acceptance: every ticket draws its
        quorum and signs its grid exactly as the per-ticket path would,
        then ONE combined hash screen + RLC-MSM accepts the whole
        convoy.  Tickets the combined check cannot vouch for (a
        screen-failing cell, or an undifferentiated combined failure)
        replay on :meth:`_sign_grid_one` from scratch — the per-ticket
        path owns bisecting blame and quarantine, so fault semantics
        are untouched; only the overwhelmingly common all-honest convoy
        pays the single pass.  The convoy's pass count lands on the
        first accepted ticket (totals across tickets stay equal to
        MSM passes actually performed)."""
        from .. import sign as signing
        from ..sign import verify as sign_verify

        prepared = []  # (p, snap, ps, quorum)
        for p, snap in tickets:
            mat, t, qualified = snap
            try:
                eligible = self._sign_eligible(p, qualified)
                if len(eligible) < t + 1:
                    raise self._sign_starved(p, eligible, t + 1)
                th0 = time.monotonic()
                h_points, _ = signing.hash_to_curve_batch(
                    mat.curve, list(p.msgs)
                )
                subs["hash_s"] += time.monotonic() - th0
                rng = random.SystemRandom()
                quorum = sorted(rng.sample(eligible, t + 1))
                tp0 = time.monotonic()
                ps = signing.partial_sign(
                    mat.curve,
                    [mat.shares[i - 1] for i in quorum],
                    quorum,
                    h_points,
                    rng=rng,
                    prove=True,
                    pks=self.sign_cache.quorum_pks(mat, quorum),
                )
                subs["partial_s"] += time.monotonic() - tp0
                prepared.append((p, snap, ps, quorum))
            except errors.ServiceError as exc:
                p.error = exc
            except Exception as exc:  # noqa: BLE001 — lane must conclude
                self._poison_sign_one(p, exc)
        if not prepared:
            return
        tv0 = time.monotonic()
        report = sign_verify.rlc_verify_convoy(
            [ps for _p, _snap, ps, _q in prepared]
        )
        subs["verify_s"] += time.monotonic() - tv0
        self.metrics.inc(
            "sign_convoy_rlc_total",
            result="ok" if report.ok else "fallback",
        )
        credited = False
        for k, (p, snap, ps, quorum) in enumerate(prepared):
            if not report.grid_ok[k]:
                try:
                    p.sigs = self._sign_grid_one(p, snap, subs)
                except errors.ServiceError as exc:
                    p.error = exc
                except Exception as exc:  # noqa: BLE001 — lane must conclude
                    self._poison_sign_one(p, exc)
                continue
            try:
                ta0 = time.monotonic()
                curve = ps.curve
                lam = self.sign_cache.lagrange_at_zero(curve, tuple(quorum))[1]
                p.sigs = signing.signature_encode(
                    curve, signing.aggregate(ps, lam=lam)
                )
                subs["aggregate_s"] += time.monotonic() - ta0
                p.rlc_passes = 0 if credited else report.passes
                credited = True
                p.signers = len(quorum)
            except Exception as exc:  # noqa: BLE001 — lane must conclude
                self._poison_sign_one(p, exc)

    def _sign_snapshot(self, p):
        """(CeremonyMaterial, t, qualified) for a ticket — the held
        outcome is snapshotted under ``_cond`` but decoded OUTSIDE it,
        behind the per-(ceremony, epoch) cache: a slow sign no longer
        stalls admission or epoch ops.  Records precondition failures
        (unknown / not-done / retired ceremony) on the ticket."""
        try:
            with self._cond:
                out = self._held_outcome(p.cid)
                curve, t, qualified = out.curve, out.t, out.qualified
                epoch, final_shares = out.epoch, out.final_shares
        except (KeyError, ValueError) as exc:
            p.error = exc
            return None
        mat = self.sign_cache.ceremony(p.cid, epoch, curve, final_shares)
        return mat, t, qualified

    def _sign_eligible(self, p, qualified) -> list[int]:
        with self._cond:
            quarantined = set(self._quarantine.get(p.cid, ()))
        return [
            i + 1
            for i, q in enumerate(qualified)
            if q and (i + 1) not in quarantined
        ]

    def _sign_starved(self, p, eligible, need) -> errors.InsufficientSigners:
        self.metrics.inc("sign_starved_total", ceremony=p.cid)
        self._emit(
            "sign_starved", ceremony=p.cid,
            eligible=len(eligible), need=need,
        )
        return errors.InsufficientSigners(
            f"ceremony {p.cid} has {len(eligible)} eligible "
            f"qualified signers, needs t+1={need}"
        )

    def _sign_fast_leg(self, fast, subs) -> None:
        """The steady-state throughput path: every unproved ticket's
        messages, from ANY ceremony, signed by ONE folded ladder per
        ``buckets.SIGN_RUNGS`` slice.  sigma = f(0) per ceremony comes
        from the cache, so per-ticket work is a quorum draw and a row of
        precomputed limbs; hashing of rung k+1 runs under rung k's
        dispatch shadow, and nothing blocks until every rung is in
        flight (``sign.folded_collect``)."""
        from .. import sign as signing

        live = []
        for p, (mat, t, qualified) in fast:
            eligible = self._sign_eligible(p, qualified)
            if len(eligible) < t + 1:
                p.error = self._sign_starved(p, eligible, t + 1)
                continue
            # seed-derived quorum rotation, as in the grid leg — the
            # fold makes the draw byte-irrelevant (sigma == f(0) for
            # every honest quorum) but keeps rotation observability
            rng = (
                random.Random(p.seed)
                if p.seed is not None
                else random.SystemRandom()
            )
            quorum = sorted(rng.sample(eligible, t + 1))
            p.signers = len(quorum)
            live.append((p, self.sign_cache.fold_limbs(mat, quorum)))
        if not live:
            return
        curve = live[0][0].curve
        msgs: list[bytes] = []
        rows = []
        for p, sigma in live:
            msgs.extend(p.msgs)
            rows.extend([sigma] * len(p.msgs))
        rows = np.asarray(rows)  # (B, L)
        # DKG_TPU_SIGN_MESH=1: the rung ladder shards over the device
        # axis (parallel.signmesh owns the mesh and the shard_map; the
        # lane just routes) — limb-identical to the single-device rung,
        # byte-checked against the host oracle by sign_bench --steady
        from ..parallel import signmesh

        mesh = signmesh.sign_mesh()
        if mesh is not None:
            self.metrics.set_gauge("sign_mesh_devices", mesh.devices.size)
        pending = []
        t_partial = 0.0
        for a, b in buckets.sign_rung_slices(len(msgs), self.sign_batch_max):
            th0 = time.monotonic()
            _, h_dev = signing.hash_to_curve_batch(curve, msgs[a:b])
            tp0 = time.monotonic()
            subs["hash_s"] += tp0 - th0
            if mesh is not None:
                self.metrics.inc("sign_mesh_rungs_total")
                pending.append(
                    signmesh.sign_folded_sharded(curve, rows[a:b], h_dev, mesh)
                )
            else:
                # AOT-aware twin: bit-identical to sign_folded, but the
                # rung executable deserializes from the store when
                # DKG_TPU_AOT_DIR is set (fresh workers skip the
                # ladder compile)
                pending.append(aot_sign_folded(curve, rows[a:b], h_dev))
            t_partial += time.monotonic() - tp0
        ta0 = time.monotonic()
        wire = signing.signature_encode(
            curve, signing.folded_collect(curve, pending)
        )
        subs["partial_s"] += t_partial
        subs["aggregate_s"] += time.monotonic() - ta0
        at = 0
        for p, _sigma in live:
            p.sigs = wire[at : at + len(p.msgs)]
            at += len(p.msgs)

    def _sign_grid_one(self, p, snap, subs) -> list[bytes]:
        """The pre-lane per-request loop, verbatim semantics, minus the
        re-derivation: shares/pks come from the (ceremony, epoch) cache,
        Lagrange coefficients from the (curve, quorum) cache.  rng
        consumption order (quorum draw -> DLEQ nonces -> RLC challenges)
        matches the old synchronous path exactly, so a seeded request
        produces the same bytes, blame, and pass counts it always did."""
        from .. import sign as signing
        from ..sign import verify as sign_verify

        mat, t, qualified = snap
        curve = mat.curve
        eligible = self._sign_eligible(p, qualified)
        th0 = time.monotonic()
        h_points, _ = signing.hash_to_curve_batch(curve, list(p.msgs))
        subs["hash_s"] += time.monotonic() - th0
        rng = (
            random.Random(p.seed)
            if p.seed is not None
            else random.SystemRandom()
        )
        passes = 0
        while True:
            if len(eligible) < t + 1:
                raise self._sign_starved(p, eligible, t + 1)
            # seed-derived quorum rotation: never always-first-t+1, so
            # load (and exposure) spreads across the qualified set
            quorum = sorted(rng.sample(eligible, t + 1))
            tp0 = time.monotonic()
            ps = signing.partial_sign(
                curve,
                [mat.shares[i - 1] for i in quorum],
                quorum,
                h_points,
                rng=rng,
                prove=p.prove,
                pks=self.sign_cache.quorum_pks(mat, quorum),
            )
            subs["partial_s"] += time.monotonic() - tp0
            if p.tamper is not None:
                ps = p.tamper(ps) or ps
            if not p.prove:
                break
            tv0 = time.monotonic()
            report = sign_verify.rlc_verify(ps, rng=rng)
            subs["verify_s"] += time.monotonic() - tv0
            passes += report.passes
            if report.ok:
                break
            blamed = sorted({quorum[si] for (_bi, si) in report.bad_cells})
            p.resigns += 1
            with self._cond:
                self._quarantine.setdefault(p.cid, set()).update(blamed)
            self.metrics.inc(
                "sign_quarantined_total", len(blamed), ceremony=p.cid
            )
            self.metrics.inc("sign_resigns_total", ceremony=p.cid)
            self._emit(
                "sign_blame",
                ceremony=p.cid,
                blamed=blamed,
                cells=[list(c) for c in report.bad_cells],
                passes=report.passes,
            )
            eligible = [i for i in eligible if i not in blamed]
        ta0 = time.monotonic()
        lam = self.sign_cache.lagrange_at_zero(curve, tuple(quorum))[1]
        sigs = signing.signature_encode(
            curve, signing.aggregate(ps, lam=lam)
        )
        subs["aggregate_s"] += time.monotonic() - ta0
        p.rlc_passes = passes
        p.signers = len(quorum)
        return sigs

    def _poison_sign_one(self, p, exc) -> None:
        """Width-1 sign failure: the ticket is the culprit.  Typed
        ServiceErrors pass through (callers branch on them); anything
        else surfaces as :class:`PoisonedRequest`."""
        self.metrics.inc("sign_poisoned_total", ceremony=p.cid)
        self._emit(
            "sign_poisoned", ceremony=p.cid, error_kind=type(exc).__name__
        )
        if isinstance(exc, errors.ServiceError):
            p.error = exc
        else:
            p.error = errors.PoisonedRequest(f"{type(exc).__name__}: {exc}")

    def _isolate_sign(self, convoy, exc, subs) -> None:
        """A sign (sub-)convoy raised outside any single ticket's own
        guarded leg: bisect, exactly like ceremony convoys — healthy
        halves re-run and complete bit-identically to signing alone,
        and the ticket still failing by itself is poisoned."""
        live = [p for p in convoy if p.error is None and p.sigs is None]
        if not live:
            return
        if len(live) == 1:
            self._poison_sign_one(live[0], exc)
            return
        self.metrics.inc("sign_bisections_total")
        self._emit(
            "sign_convoy_bisect",
            width=len(live),
            error_kind=type(exc).__name__,
        )
        mid = len(live) // 2
        for half in (live[:mid], live[mid:]):
            try:
                self._sign_execute(half, subs)
            except Exception as e2:  # noqa: BLE001 — isolation must conclude
                self._isolate_sign(half, e2, subs)

    # -- worker side --------------------------------------------------------

    def _pop_convoy(self, block: bool) -> list[_Pending] | None:
        """Head-of-queue convoy: the oldest QUEUED request plus up to
        ``batch_max - 1`` others sharing its convoy key, truncated to
        the largest ladder width that fits (never phantom-padded).
        Returns None when idle (non-blocking) or shut down."""
        with self._cond:
            while True:
                if not self._running or (self._draining and not self._queue):
                    return None
                expired = [
                    p
                    for p in self._queue
                    if p.deadline_at is not None
                    and time.monotonic() > p.deadline_at
                ]
                for p in expired:
                    self._queue.remove(p)
                    self.metrics.inc("service_expired_total", where="queued")
                    self._emit(
                        "service_expired", ceremony=p.cid, where="queued"
                    )
                    self._finish_one(
                        CeremonyOutcome(
                            ceremony_id=p.cid,
                            status="expired",
                            curve=p.req.curve,
                            n=p.req.n,
                            t=p.req.t,
                            error="DEADLINE_EXCEEDED",
                        ),
                        durable=p.req.durable,
                    )
                if self._queue:
                    break
                if not block:
                    return None
                self._cond.wait(timeout=0.2)
            head = self._queue[0]
            key = head.req.convoy_key()
            mates = [p for p in self._queue if p.req.convoy_key() == key]
            cap = min(self.batch_max, buckets.width_cap(head.req.bucket()))
            width = next(
                w for w in buckets.WIDTHS if w <= min(len(mates), cap)
            )
            convoy = mates[:width]
            for p in convoy:
                self._queue.remove(p)
                self._status[p.cid] = "running"
            self.metrics.set_gauge("service_queue_depth", len(self._queue))
            self.metrics.inc("service_convoys_total")
            self._cond.notify_all()
            return convoy

    def _engine_start(self, reqs, cids):
        """Dispatch a convoy, routing through the chaos hook when a
        fault plan is installed (service.faultsvc)."""
        if self._fault_plan is not None:
            self._fault_plan.on_start(reqs)
        return start_convoy(self.runtime, reqs, cids)

    def _engine_finish(self, fl, reqs):
        if self._fault_plan is not None:
            self._fault_plan.on_finish(reqs)
        return finish_convoy(self.runtime, fl)

    def _run_once(self, convoy):
        """Synchronous start+finish of a (sub-)convoy — the bisection /
        retry lane, off the two-deep pipeline."""
        reqs = [p.req for p in convoy]
        fl = self._engine_start(reqs, [p.cid for p in convoy])
        return self._engine_finish(fl, reqs)

    def _hold(self, slot: int, convoy) -> None:
        with self._cond:
            self._held.setdefault(slot, []).append(convoy)

    def _release(self, slot: int, convoy) -> None:
        with self._cond:
            held = self._held.get(slot, [])
            if convoy in held:
                held.remove(convoy)

    def _worker(self, slot: int) -> None:
        inflight = None  # (convoy, InFlight, t_start)
        while True:
            convoy = self._pop_convoy(block=inflight is None)
            if convoy is not None:
                self._hold(slot, convoy)
                t0 = time.monotonic()
                try:
                    fl = self._engine_start(
                        [p.req for p in convoy], [p.cid for p in convoy]
                    )
                except Exception as exc:  # noqa: BLE001 — worker must survive
                    self._isolate(convoy, exc, t0)
                    self._release(slot, convoy)
                    continue
                if inflight is not None:
                    self._finish(*inflight)
                    self._release(slot, inflight[0])
                inflight = (convoy, fl, t0)
                continue
            if inflight is not None:
                self._finish(*inflight)
                self._release(slot, inflight[0])
                inflight = None
                continue
            with self._cond:
                if not self._running or (self._draining and not self._queue):
                    return

    def _watchdog_loop(self) -> None:
        """Detect and respawn dead workers (non-``Exception`` escapes or
        bookkeeping bugs kill a thread silently — without this the pool
        just shrinks until the service deadlocks).  Convoys the dead
        worker held are re-queued once, then failed: the convoy may be
        what killed it (see :data:`_MAX_CRASH_REQUEUES`)."""
        while True:
            with self._cond:
                self._cond.wait(timeout=self._watchdog_interval_s)
                if not self._running:
                    return
                self._watch_pool()
            # outside the _cond block: the sign check takes _sign_cond,
            # and holding _cond across it is legal (_cond -> _sign_cond
            # order) but pointless contention
            self._maybe_respawn_sign_worker()

    def _watch_pool(self) -> None:
        """One watchdog sweep over the ceremony worker pool (caller
        holds ``_cond``)."""
        for i, w in enumerate(self._workers):
            if w.is_alive():
                continue
            orphans = self._held.pop(i, [])
            self._gen += 1
            nw = threading.Thread(
                target=self._worker,
                args=(i,),
                name=f"dkg-svc-{i}r{self._gen}",
                daemon=True,
            )
            self._workers[i] = nw
            nw.start()
            self.metrics.inc("service_worker_restarts_total")
            self._emit("service_worker_restart", slot=i)
            for convoy in orphans:
                for p in convoy:
                    p.crashes += 1
                    if p.crashes > _MAX_CRASH_REQUEUES:
                        self._emit(
                            "service_worker_crash_failed",
                            ceremony=p.cid,
                        )
                        self.metrics.inc(
                            "service_failed_total",
                            kind="WORKER_CRASH",
                        )
                        self._finish_one(
                            CeremonyOutcome(
                                ceremony_id=p.cid,
                                status="failed",
                                curve=p.req.curve,
                                n=p.req.n,
                                t=p.req.t,
                                error=(
                                    "WORKER_CRASH: worker died "
                                    f"{p.crashes}x holding this "
                                    "request"
                                ),
                            ),
                            durable=p.req.durable,
                        )
                    else:
                        self._queue.insert(0, p)
                        self._status[p.cid] = "queued"
                        self.metrics.inc("service_requeued_total")
            self.metrics.set_gauge(
                "service_queue_depth", len(self._queue)
            )
            self._cond.notify_all()

    def _maybe_respawn_sign_worker(self) -> None:
        """Watchdog leg for the sign lane: respawn a dead sign worker.
        Tickets it held in flight fail as TransientEngineError — the
        convoy may be what killed it, so re-running is the caller's
        call, not the lane's."""
        with self._sign_cond:
            if not self._running or self._sign_thread.is_alive():
                return
            orphans = list(self._sign_inflight)
            self._sign_inflight = []
            self._sign_gen += 1
            nt = threading.Thread(
                target=self._sign_worker,
                name=f"dkg-svc-sign-r{self._sign_gen}",
                daemon=True,
            )
            self._sign_thread = nt
            nt.start()
            self.metrics.inc("service_worker_restarts_total")
            self._emit("sign_worker_restart")
            for p in orphans:
                if not p.done:
                    p.error = errors.TransientEngineError(
                        "SIGN_WORKER_CRASH: sign worker died holding "
                        "this request"
                    )
                    p.done = True
            self._sign_cond.notify_all()

    def _finish(self, convoy, fl, t0) -> None:
        try:
            outcomes = self._engine_finish(fl, [p.req for p in convoy])
        except Exception as exc:  # noqa: BLE001 — worker must survive
            self._isolate(convoy, exc, t0)
            return
        self._finish_outcomes(convoy, outcomes, t0)

    def _finish_outcomes(self, convoy, outcomes, t0) -> None:
        dt = time.monotonic() - t0
        # per-ceremony attribution: a width-w convoy's wall clock is
        # shared by w ceremonies (the whole-convoy time goes to the
        # service_convoy_seconds histogram below)
        share = dt / max(1, len(convoy))
        for p, out in zip(convoy, outcomes):
            out.seconds = share
            if (
                p.deadline_at is not None
                and time.monotonic() > p.deadline_at
            ):
                self.metrics.inc("service_expired_total", where="inflight")
                self._emit(
                    "service_expired", ceremony=p.cid, where="inflight"
                )
                out = CeremonyOutcome(
                    ceremony_id=out.ceremony_id,
                    status="expired",
                    curve=out.curve,
                    n=out.n,
                    t=out.t,
                    error="DEADLINE_EXCEEDED",
                    seconds=share,
                )
            with self._cond:
                self._finish_one(out, durable=p.req.durable)
        self.metrics.observe(
            "service_convoy_seconds", dt, width=str(len(convoy))
        )
        # device/host memory watermarks at the convoy boundary (no-op
        # unless runtimeobs is installed; internally throttled)
        runtimeobs.maybe_sample(phase="convoy_finish")

    # -- blast-radius isolation ---------------------------------------------

    def _isolate(self, convoy, exc, t0) -> None:
        """A (sub-)convoy raised ``exc``: contain the blast radius.

        Typed :class:`~dkg_tpu.service.errors.TransientEngineError`
        retries the WHOLE convoy (bounded, exponential backoff) — the
        work is presumed good, the engine hiccuped.  Everything else is
        presumed poison and bisected down the width ladder: healthy
        halves complete bit-identically to an undisturbed run, and the
        request still failing alone at width 1 is the culprit."""
        if isinstance(exc, errors.TransientEngineError):
            exc = self._retry_transient(convoy, exc, t0)
            if exc is None:
                return  # recovered; outcomes already recorded
            if isinstance(exc, errors.TransientEngineError):
                self._fail_convoy(convoy, exc)  # retries exhausted
                return
            # a retry surfaced a non-transient fault: bisect it
        if len(convoy) == 1:
            self._poison_one(convoy[0], exc)
            return
        self.metrics.inc("service_convoy_bisections_total")
        self._emit(
            "service_convoy_bisect",
            width=len(convoy),
            error_kind=type(exc).__name__,
        )
        mid = len(convoy) // 2
        for half in (convoy[:mid], convoy[mid:]):
            t1 = time.monotonic()
            try:
                outs = self._run_once(half)
            except Exception as e2:  # noqa: BLE001 — isolation must conclude
                self._isolate(half, e2, t1)
            else:
                self._finish_outcomes(half, outs, t1)

    def _retry_transient(self, convoy, exc, t0):
        """Bounded whole-convoy retry for a transient engine fault.
        Returns None when a retry succeeded (outcomes recorded), the
        last TransientEngineError when retries are exhausted, or a
        non-transient exception a retry surfaced (caller bisects)."""
        last = exc
        for attempt in range(1, self.retries + 1):
            self.metrics.inc("service_retries_total")
            self._emit(
                "service_retry", attempt=attempt, width=len(convoy),
                error_kind=type(last).__name__,
            )
            time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            try:
                outs = self._run_once(convoy)
            except errors.TransientEngineError as e2:
                last = e2
                self._emit(
                    "service_retry_failed", attempt=attempt,
                    error_kind=type(e2).__name__,
                )
                continue
            except Exception as e2:  # noqa: BLE001 — classified by caller
                self._emit(
                    "service_retry_surfaced", attempt=attempt,
                    error_kind=type(e2).__name__,
                )
                return e2
            self._finish_outcomes(convoy, outs, t0)
            return None
        return last

    def _poison_one(self, p, exc) -> None:
        """Width-1 failure: the request is the culprit — typed poisoned
        outcome, convoy-mates (if any) already completed elsewhere."""
        self.metrics.inc("service_poisoned_total")
        self._emit(
            "service_poisoned", ceremony=p.cid, error_kind=type(exc).__name__
        )
        self._finish_one(
            CeremonyOutcome(
                ceremony_id=p.cid,
                status="poisoned",
                curve=p.req.curve,
                n=p.req.n,
                t=p.req.t,
                error=f"PoisonedRequest: {type(exc).__name__}: {exc}",
            ),
            durable=p.req.durable,
        )

    def _fail_convoy(self, convoy, exc) -> None:
        """Terminal whole-convoy failure (transient retries exhausted,
        shutdown races): every member fails with the error KIND
        metric-labelled and obslog'd — no silent outcomes."""
        kind = type(exc).__name__
        self.metrics.inc("service_failed_total", len(convoy), kind=kind)
        self._emit("service_convoy_failed", width=len(convoy), error_kind=kind)
        with self._cond:
            for p in convoy:
                self._finish_one(
                    CeremonyOutcome(
                        ceremony_id=p.cid,
                        status="failed",
                        curve=p.req.curve,
                        n=p.req.n,
                        t=p.req.t,
                        error=f"{type(exc).__name__}: {exc}",
                    ),
                    durable=p.req.durable,
                )

    def _finish_one(
        self,
        out: CeremonyOutcome,
        durable: bool = False,
    ) -> None:
        """Record a terminal outcome.  Journal the public outcome for
        durable ceremonies so recovery re-serves instead of re-running.
        The condition's lock is reentrant, so callers already holding it
        just re-enter."""
        if durable and self._journal is not None:
            self._journal.record_done(out)
        with self._cond:
            self._record(out)

    def _record(self, out: CeremonyOutcome) -> None:
        out.completed_at = time.monotonic()
        self._results[out.ceremony_id] = out
        self._status[out.ceremony_id] = out.status
        self.metrics.inc("service_completed_total", status=out.status)
        if out.seconds:
            # bucket label, not ceremony_id: a server runs unboundedly
            # many ceremonies and histogram series must stay bounded
            # (per-ceremony attribution goes through obslog/tracing)
            self.metrics.observe(
                "service_ceremony_seconds", out.seconds,
                bucket=f"{out.bucket_n}x{out.bucket_t}" if out.bucket_n else "none",
            )
        self._cond.notify_all()
