"""Rolling SLO evaluation over the process-wide metrics registry.

The scheduler's histograms (``service_ceremony_seconds``,
``sign_seconds``) and typed-failure counters
(``service_completed_total{status=...}``) already carry everything an
SLO needs; this module turns them into judgments without any new
instrumentation:

* :func:`evaluate` — pure function from one registry snapshot (or a
  windowed delta of two) to a report: merged p50/p99 ceremony and sign
  latency (bucket-interpolated quantiles), error-budget burn over the
  terminal-status counters, and per-objective ``ok`` verdicts.
* :class:`SloEvaluator` — the rolling form: keeps timestamped registry
  snapshots and evaluates the **windowed delta** (newest minus the
  oldest snapshot still inside the window), so a long-lived server is
  judged on its recent behaviour, not its lifetime averages.  Backs the
  scheduler's ``/slo`` endpoint (service/httpobs.py).
* ``scripts/slo_gate.py`` — the offline form: the same
  :func:`evaluate` over the metrics snapshots embedded in
  FLEET/SVCSTORM/SIGN rounds, wired into ``scripts/perf_regress.py``.

Error-budget accounting uses only ``service_completed_total``: every
terminal outcome increments it with a ``status`` label, so the failure
ratio is ``(completed - done) / completed`` with no second counter to
drift out of sync.  Burn is ``ratio / budget`` — 1.0 means the window
consumed exactly its budget.

Knobs (validated via utils.envknobs, constructor arguments win):
``DKG_TPU_SLO_WINDOW_S`` (rolling window, default 300),
``DKG_TPU_SLO_CEREMONY_P99_S`` / ``DKG_TPU_SLO_SIGN_P99_S`` (latency
objectives; unset = latency reported but not judged),
``DKG_TPU_SLO_ERROR_BUDGET`` (allowed failure ratio, default 0.01).
"""

from __future__ import annotations

import re
import time
from collections import deque
from dataclasses import dataclass

from ..utils import envknobs
from ..utils.metrics import REGISTRY

#: Default rolling window (seconds) and error budget (failure ratio).
DEFAULT_WINDOW_S = 300.0
DEFAULT_ERROR_BUDGET = 0.01

#: How many timestamped snapshots the rolling evaluator retains; at the
#: scheduler's scrape cadence this comfortably covers the window.
_MAX_TICKS = 256

_SERIES_RE = re.compile(r'^(?P<name>[^{]+)(\{(?P<labels>.*)\})?$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_series(series: str) -> tuple[str, dict[str, str]]:
    """``'name{k="v"}'`` -> ``("name", {"k": "v"})`` (the rendered-key
    form snapshot() exports)."""
    m = _SERIES_RE.match(series)
    if m is None:
        return series, {}
    labels = {
        k: v.replace('\\"', '"').replace("\\\\", "\\").replace("\\n", "\n")
        for k, v in _LABEL_RE.findall(m.group("labels") or "")
    }
    return m.group("name"), labels


@dataclass
class SloPolicy:
    """The objectives one evaluation judges against.  ``None`` latency
    targets report the quantile without judging it."""

    window_s: float = DEFAULT_WINDOW_S
    ceremony_p99_s: float | None = None
    sign_p99_s: float | None = None
    error_budget: float = DEFAULT_ERROR_BUDGET

    @classmethod
    def from_env(cls) -> "SloPolicy":
        window = envknobs.pos_float(
            "DKG_TPU_SLO_WINDOW_S", "rolling SLO evaluation window"
        )
        budget = envknobs.nonneg_float(
            "DKG_TPU_SLO_ERROR_BUDGET",
            "allowed failure ratio per window (0 = zero tolerance)",
        )
        return cls(
            window_s=DEFAULT_WINDOW_S if window is None else window,
            ceremony_p99_s=envknobs.pos_float(
                "DKG_TPU_SLO_CEREMONY_P99_S", "ceremony p99 latency objective"
            ),
            sign_p99_s=envknobs.pos_float(
                "DKG_TPU_SLO_SIGN_P99_S", "sign p99 latency objective"
            ),
            error_budget=DEFAULT_ERROR_BUDGET if budget is None else budget,
        )


# -- histogram algebra --------------------------------------------------------


def merge_histograms(snapshot: dict, name: str) -> dict | None:
    """Sum every histogram series of base ``name`` (any labels) into one
    ``{"buckets": {le: cum}, "sum": s, "count": c}``; None when absent.
    Buckets are cumulative Prometheus ``le`` counts, merged by key —
    sound because each metric name pins one bucket layout (metrics.py
    fixes layouts at first observation)."""
    merged_buckets: dict[str, int] = {}
    total = 0.0
    count = 0
    found = False
    for series, h in (snapshot.get("histograms") or {}).items():
        base, _labels = parse_series(series)
        if base != name or not isinstance(h, dict):
            continue
        found = True
        for le, c in (h.get("buckets") or {}).items():
            merged_buckets[le] = merged_buckets.get(le, 0) + int(c)
        total += float(h.get("sum", 0.0))
        count += int(h.get("count", 0))
    if not found:
        return None
    return {"buckets": merged_buckets, "sum": total, "count": count}


def quantile(hist: dict, q: float) -> float | None:
    """Bucket-interpolated quantile of a merged cumulative histogram.

    Rank ``q * count`` lands in the first bucket whose cumulative count
    reaches it; the value interpolates linearly between the bucket's
    bounds (lower bound = previous finite ``le``, 0 for the first).  A
    rank landing in the ``+Inf`` bucket returns the largest finite bound
    — the honest answer a fixed-layout histogram can give.  None for an
    empty histogram.
    """
    count = int(hist.get("count", 0))
    if count <= 0:
        return None
    items = sorted(
        ((le, int(c)) for le, c in hist["buckets"].items() if le != "+Inf"),
        key=lambda kv: float(kv[0]),
    )
    target = q * count
    lo = 0.0
    prev_cum = 0
    for le, cum in items:
        hi = float(le)
        if cum >= target and cum > prev_cum:
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + (hi - lo) * max(0.0, min(1.0, frac))
        lo, prev_cum = hi, cum
    # rank beyond every finite bucket: the +Inf overflow
    return items[-1] and float(items[-1][0]) if items else None


def delta_snapshot(new: dict, old: dict) -> dict:
    """``new - old`` over cumulative series (counters and histogram
    buckets/sums/counts; gauges keep their newest value).  Series absent
    from ``old`` count from zero; negative deltas clamp to zero (a
    registry reset between snapshots must not produce negative rates)."""
    counters = {}
    for series, v in (new.get("counters") or {}).items():
        counters[series] = max(
            0.0, float(v) - float((old.get("counters") or {}).get(series, 0.0))
        )
    hists = {}
    for series, h in (new.get("histograms") or {}).items():
        oh = (old.get("histograms") or {}).get(series) or {}
        old_buckets = oh.get("buckets") or {}
        hists[series] = {
            "buckets": {
                le: max(0, int(c) - int(old_buckets.get(le, 0)))
                for le, c in (h.get("buckets") or {}).items()
            },
            "sum": max(0.0, float(h.get("sum", 0.0)) - float(oh.get("sum", 0.0))),
            "count": max(0, int(h.get("count", 0)) - int(oh.get("count", 0))),
        }
    return {
        "counters": counters,
        "gauges": dict(new.get("gauges") or {}),
        "histograms": hists,
    }


# -- evaluation ---------------------------------------------------------------


def _latency_leg(
    snapshot: dict, name: str, target_p99_s: float | None
) -> dict | None:
    merged = merge_histograms(snapshot, name)
    if merged is None or merged["count"] <= 0:
        return None
    leg = {
        "count": merged["count"],
        "mean_s": round(merged["sum"] / merged["count"], 6),
        "p50_s": round(quantile(merged, 0.50), 6),
        "p99_s": round(quantile(merged, 0.99), 6),
        "target_p99_s": target_p99_s,
    }
    leg["ok"] = target_p99_s is None or leg["p99_s"] <= target_p99_s
    return leg


def evaluate(
    snapshot: dict,
    policy: SloPolicy | None = None,
    window_s: float | None = None,
) -> dict:
    """Judge one snapshot (cumulative or windowed delta) against a
    policy.  Always returns a report; objectives whose series are absent
    are reported ``null`` and do not fail the evaluation (a freshly
    started server has no traffic to violate an SLO with)."""
    pol = policy if policy is not None else SloPolicy()
    report: dict = {
        "window_s": window_s if window_s is not None else pol.window_s,
        "ceremony": _latency_leg(
            snapshot, "service_ceremony_seconds", pol.ceremony_p99_s
        ),
        "sign": _latency_leg(snapshot, "sign_seconds", pol.sign_p99_s),
    }
    completed = 0.0
    failed = 0.0
    by_status: dict[str, float] = {}
    for series, v in (snapshot.get("counters") or {}).items():
        base, labels = parse_series(series)
        if base != "service_completed_total":
            continue
        status = labels.get("status", "unknown")
        by_status[status] = by_status.get(status, 0.0) + float(v)
        completed += float(v)
        if status != "done":
            failed += float(v)
    ratio = failed / completed if completed > 0 else 0.0
    if pol.error_budget > 0:
        burn = ratio / pol.error_budget
    else:
        burn = 0.0 if failed == 0 else float("inf")
    errors = {
        "completed": completed,
        "failed": failed,
        "by_status": by_status,
        "ratio": round(ratio, 6),
        "budget": pol.error_budget,
        "burn": round(burn, 4) if burn != float("inf") else "inf",
        "ok": ratio <= pol.error_budget,
    }
    report["errors"] = errors
    violations = []
    for leg_name in ("ceremony", "sign"):
        leg = report[leg_name]
        if leg is not None and not leg["ok"]:
            violations.append(
                f"{leg_name}_p99 {leg['p99_s']}s > target "
                f"{leg['target_p99_s']}s"
            )
    if not errors["ok"]:
        violations.append(
            f"error ratio {errors['ratio']} > budget {pol.error_budget}"
        )
    report["violations"] = violations
    report["ok"] = not violations
    return report


class SloEvaluator:
    """Rolling windowed evaluation over a live registry.

    :meth:`tick` snapshots the registry with a timestamp; :meth:`report`
    ticks, then evaluates ``newest - oldest_within_window``.  With one
    tick (fresh process) the cumulative snapshot is evaluated over its
    actual age — better a short-window judgment than none.  Thread-safe
    through the GIL-atomic deque append; callers (the scheduler, the
    HTTP thread) may tick/report concurrently.
    """

    def __init__(
        self,
        registry=None,
        policy: SloPolicy | None = None,
        clock=time.monotonic,
    ) -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.policy = policy if policy is not None else SloPolicy.from_env()
        self._clock = clock
        self._ticks: deque[tuple[float, dict]] = deque(maxlen=_MAX_TICKS)

    def tick(self) -> None:
        """Record one timestamped snapshot (call at scrape/phase
        cadence; report() also ticks)."""
        self._ticks.append((self._clock(), self.registry.snapshot()))

    def report(self) -> dict:
        self.tick()
        ticks = list(self._ticks)
        now, head = ticks[-1]
        base_t, base = None, None
        for t, snap in ticks[:-1]:
            if now - t <= self.policy.window_s:
                base_t, base = t, snap
                break
        if base is None:
            # no in-window predecessor: judge the cumulative snapshot
            # over its true age (bounded below to dodge divide-by-zero
            # style degeneracy in consumers computing rates)
            age = now - ticks[0][0] if len(ticks) > 1 else self.policy.window_s
            return evaluate(head, self.policy, window_s=max(age, 1e-9))
        return evaluate(
            delta_snapshot(head, base), self.policy, window_s=now - base_t
        )
