"""Broadcast-channel message types + complaint/evidence verification.

Functional parity with the reference (reference: src/dkg/broadcast.rs):
every message that crosses the abstract authenticated broadcast channel
("the blockchain", reference lib.rs:91-92) in rounds 1-5, the complaint
types, and `ProofOfMisbehaviour` with third-party-verifiable evidence.

Deliberate deviations from the reference (SURVEY §5 quirks, decided):
* quirk 2 — the misbehaviour-proof share check uses the canonical base
  order g*share + h*randomness everywhere (the reference swaps bases in
  broadcast.rs:257-274 relative to committee.rs:292-294; swapped bases
  still bind, but canonical order keeps host/device kernels identical).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..crypto.commitment import CommitmentKey
from ..crypto.correct_decryption import CorrectHybridDecrKeyZkp
from ..crypto.elgamal import (
    PERSON_SHARE,
    HybridCiphertext,
    SymmetricKey,
    hybrid_decrypt_with_key,
    rand_person,
    recover_symmetric_key,
)
from ..groups.host import HostGroup
from .errors import DkgError, DkgErrorKind
from .procedure_keys import MemberCommunicationKey, MemberCommunicationPublicKey


# ---------------------------------------------------------------------------
# share-vs-commitment checks (the protocol's two verification equations)
# ---------------------------------------------------------------------------


def check_randomized_share(
    group: HostGroup, ck: CommitmentKey, index: int, share: int, rand: int, coeffs
) -> bool:
    """g*s + h*s' == sum_l index^l * E_l (reference: committee.rs:292-296).

    The received share is still secret when a recipient runs this check
    (it only becomes public if a complaint is filed), so the left side
    uses the constant-structure ladder; the Horner side is public data.
    """
    lhs = group.add(
        group.scalar_mul(share, group.generator()), group.scalar_mul(rand, ck.h)
    )
    return group.eq(lhs, _eval_comm(group, index, coeffs))


def check_bare_share(group: HostGroup, index: int, share: int, coeffs) -> bool:
    """g*s == sum_l index^l * A_l (reference: committee.rs:532-541)."""
    return group.eq(
        group.scalar_mul(share, group.generator()), _eval_comm(group, index, coeffs)
    )


def _eval_comm(group: HostGroup, index: int, coeffs):
    """Horner evaluation of a point-coefficient polynomial at ``index``
    (public commitments and a public party index: vartime is fine)."""
    acc = group.identity()
    for c in reversed(coeffs):
        acc = group.add(group.scalar_mul_vartime(index, acc), c)
    return acc


# ---------------------------------------------------------------------------
# round-1 message (dealing)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EncryptedShares:
    """Hybrid-encrypted (share, commitment-randomness) pair for one
    recipient (reference: broadcast.rs:16-20)."""

    recipient_index: int  # 1-based
    share_ct: HybridCiphertext
    randomness_ct: HybridCiphertext


@dataclass(frozen=True)
class BroadcastPhase1:
    """Randomized coefficient commitments E_l = g*a_l + h*b_l plus one
    EncryptedShares per committee member (reference: broadcast.rs:155-160,
    built at committee.rs:206-215)."""

    committed_coefficients: tuple  # (t+1) points
    encrypted_shares: tuple  # n EncryptedShares, recipient order

    def shares_for(self, index: int) -> Optional[EncryptedShares]:
        for es in self.encrypted_shares:
            if es.recipient_index == index:
                return es
        return None


# ---------------------------------------------------------------------------
# misbehaviour evidence (round 2 complaints)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ProofOfMisbehaviour:
    """Disclosed KEM keys + correctness proofs so any third party can
    re-decrypt the accused's ciphertexts and re-run the share check
    (reference: broadcast.rs:181-282)."""

    symm_key_share: SymmetricKey
    symm_key_rand: SymmetricKey
    proof_share: CorrectHybridDecrKeyZkp
    proof_rand: CorrectHybridDecrKeyZkp

    @classmethod
    def generate(
        cls,
        group: HostGroup,
        shares: EncryptedShares,
        comm_key: MemberCommunicationKey,
        rng,
    ) -> "ProofOfMisbehaviour":
        """(reference: broadcast.rs:189-225)"""
        k1 = recover_symmetric_key(group, comm_key.sk, shares.share_ct)
        k2 = recover_symmetric_key(group, comm_key.sk, shares.randomness_ct)
        pk = comm_key.public().point
        p1 = CorrectHybridDecrKeyZkp.generate(
            group, shares.share_ct, pk, k1, comm_key.sk, rng
        )
        p2 = CorrectHybridDecrKeyZkp.generate(
            group, shares.randomness_ct, pk, k2, comm_key.sk, rng
        )
        return cls(k1, k2, p1, p2)

    def decrypt_scalars(
        self, group: HostGroup, shares: EncryptedShares
    ) -> tuple[Optional[int], Optional[int]]:
        fs = group.scalar_field
        rp = rand_person(group, shares.share_ct, shares.randomness_ct)
        out = []
        for key, ct, person in (
            (self.symm_key_share, shares.share_ct, PERSON_SHARE),
            (self.symm_key_rand, shares.randomness_ct, rp),
        ):
            pt = hybrid_decrypt_with_key(group, key, ct, person)
            v = int.from_bytes(pt, "little") if len(pt) == fs.nbytes else None
            out.append(v if v is None or v < fs.modulus else None)
        return out[0], out[1]


@dataclass(frozen=True)
class MisbehavingPartiesRound1:
    """Round-2 complaint: accused dealer index, claimed error, evidence
    (reference: broadcast.rs:38-42)."""

    accused_index: int  # 1-based
    error: DkgErrorKind
    proof: ProofOfMisbehaviour

    def verify(
        self,
        group: HostGroup,
        ck: CommitmentKey,
        accuser_index: int,
        accuser_pk: MemberCommunicationPublicKey,
        accused_broadcast: BroadcastPhase1,
    ) -> bool:
        """True iff the accusation is upheld (the accused misbehaved)
        (reference: broadcast.rs:50-98)."""
        return (
            self.check(group, ck, accuser_index, accuser_pk, accused_broadcast)
            is None
        )

    def check(
        self,
        group: HostGroup,
        ck: CommitmentKey,
        accuser_index: int,
        accuser_pk: MemberCommunicationPublicKey,
        accused_broadcast: BroadcastPhase1,
    ) -> Optional[DkgError]:
        """None iff the accusation is upheld; otherwise the reason it is
        rejected, using the reference's taxonomy (broadcast.rs:50-98,
        226-281).  Steps: locate the ciphertexts addressed to the
        accuser, verify both disclosed-KEM-key proofs, re-decrypt, and
        re-run the commitment check with the accuser's index.

        Deliberate deviation: a non-decodable decrypted scalar UPHOLDS
        the complaint (the dealer sent garbage — committee.rs:318-331's
        ScalarOutOfBounds complaint kind), where the reference's
        evidence verifier instead rejects with DecodingToScalarFailed
        (broadcast.rs:260-267), leaving a garbage-dealing dealer
        unpunishable via that path.
        """
        # NB: a rejected complaint blames the ACCUSER (they filed bad
        # evidence / a false claim), so rejection errors carry
        # index=accuser_index — the adjudicator's blame target.
        shares = accused_broadcast.shares_for(accuser_index)
        if shares is None:
            return DkgError(
                DkgErrorKind.INVALID_PROOF_OF_MISBEHAVIOUR,
                index=accuser_index,
                detail="no ciphertext addressed to the accuser",
            )
        if not self.proof.proof_share.verify(
            group, shares.share_ct, accuser_pk.point, self.proof.symm_key_share
        ) or not self.proof.proof_rand.verify(
            group, shares.randomness_ct, accuser_pk.point, self.proof.symm_key_rand
        ):
            # the disclosed-KEM-key DLEQ proofs are the evidence; a bad
            # proof is a ZKP failure surfaced as an invalid complaint
            # (reference maps both to InvalidProofOfMisbehaviour,
            # broadcast.rs:252-254)
            return DkgError(
                DkgErrorKind.INVALID_PROOF_OF_MISBEHAVIOUR,
                index=accuser_index,
                detail=DkgErrorKind.ZKP_VERIFICATION_FAILED.value,
            )
        s, r = self.proof.decrypt_scalars(group, shares)
        if s is None or r is None:
            # upheld: dealer's plaintext does not decode to a scalar
            return None
        if check_randomized_share(
            group, ck, accuser_index, s, r, accused_broadcast.committed_coefficients
        ):
            # the share actually verifies: the claimed inequality is
            # false (reference: broadcast.rs:94)
            return DkgError(
                DkgErrorKind.FALSE_CLAIMED_INEQUALITY, index=accuser_index
            )
        return None


@dataclass(frozen=True)
class BroadcastPhase2:
    """(reference: broadcast.rs:162-165)"""

    misbehaving_parties: tuple  # MisbehavingPartiesRound1


# ---------------------------------------------------------------------------
# rounds 3-5
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BroadcastPhase3:
    """Bare coefficient commitments A_l = g*a_l (reference:
    broadcast.rs:167-170, committed at committee.rs:447-451)."""

    committed_coefficients: tuple  # (t+1) points


@dataclass(frozen=True)
class MisbehavingPartiesRound3:
    """Round-4 complaint: the accuser discloses the (share, randomness)
    received from the accused so third parties can see the bare
    commitments are inconsistent (reference: broadcast.rs:104-108)."""

    accused_index: int
    share: int
    randomness: int

    def verify(
        self,
        group: HostGroup,
        ck: CommitmentKey,
        accuser_index: int,
        randomized_coeffs,
        bare_coeffs: Optional[tuple],
    ) -> bool:
        """Upheld iff the disclosed pair matches the round-1 randomized
        commitments (so it is the genuinely dealt share) AND the round-3
        bare commitments fail (or are missing) for it
        (reference: broadcast.rs:111-143)."""
        return (
            self.check(group, ck, accuser_index, randomized_coeffs, bare_coeffs)
            is None
        )

    def check(
        self,
        group: HostGroup,
        ck: CommitmentKey,
        accuser_index: int,
        randomized_coeffs,
        bare_coeffs: Optional[tuple],
    ) -> Optional[DkgError]:
        """None iff upheld; otherwise why the complaint is rejected
        (reference taxonomy, broadcast.rs:111-143)."""
        if not check_randomized_share(
            group, ck, accuser_index, self.share, self.randomness, randomized_coeffs
        ):
            # the disclosed pair is not the genuinely dealt share: the
            # claimed round-1 equality is false (reference:
            # broadcast.rs:138).  Blame the accuser, who lied.
            return DkgError(
                DkgErrorKind.FALSE_CLAIMED_EQUALITY, index=accuser_index
            )
        if bare_coeffs is not None and check_bare_share(
            group, accuser_index, self.share, bare_coeffs
        ):
            # the bare commitments verify too: the claimed round-3
            # inequality is false (reference: broadcast.rs:140)
            return DkgError(
                DkgErrorKind.FALSE_CLAIMED_INEQUALITY, index=accuser_index
            )
        return None


@dataclass(frozen=True)
class BroadcastPhase4:
    """(reference: broadcast.rs:172-174, type alias :148)"""

    misbehaving_parties: tuple  # MisbehavingPartiesRound3


@dataclass(frozen=True)
class DisclosedShare:
    """A share of ``accused_index``'s polynomial held by ``holder_index``,
    published for reconstruction (reference: committee.rs:662-669)."""

    accused_index: int
    holder_index: int
    share: int


@dataclass(frozen=True)
class BroadcastPhase5:
    """(reference: broadcast.rs:176-178)"""

    disclosed_shares: tuple  # DisclosedShare
