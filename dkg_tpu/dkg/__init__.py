"""DKG protocol layer (reference: src/dkg/)."""

from .broadcast import (  # noqa: F401
    BroadcastPhase1,
    BroadcastPhase2,
    BroadcastPhase3,
    BroadcastPhase4,
    BroadcastPhase5,
    DisclosedShare,
    EncryptedShares,
    MisbehavingPartiesRound1,
    MisbehavingPartiesRound3,
    ProofOfMisbehaviour,
)
from .committee import (  # noqa: F401
    DistributedKeyGeneration,
    DkgPhase1,
    DkgPhase2,
    DkgPhase3,
    DkgPhase4,
    DkgPhase5,
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from . import complaints_batch, committee_batch, hybrid_batch  # noqa: F401
from .errors import DkgError, DkgErrorKind, ProofError  # noqa: F401
from .procedure_keys import (  # noqa: F401
    MasterPublicKey,
    MemberCommunicationKey,
    MemberCommunicationPublicKey,
    MemberPublicShare,
    MemberSecretShare,
    sort_committee,
)
