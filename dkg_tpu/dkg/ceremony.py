"""Batched device ceremony engine: arrays-of-parties as the primitive.

The reference drives one party at a time through the phases and spends
~all cycles in per-pair scalar ops (SURVEY §3).  This engine inverts the
shape TPU-first: the ceremony state is struct-of-arrays limb tensors for
*all parties at once*, and each round is one jitted batched kernel:

* ``deal``   — coefficient commitments A/E for all n dealers' t+1
  coefficients via fixed-base window tables (reference hot loop #1,
  committee.rs:151-159), and the full n×n share matrix via one batched
  Horner scan (hot loop #2, committee.rs:163-186).
* ``verify_batch`` — random-linear-combination batch verification: with
  Fiat-Shamir randomizers rho_j, each recipient checks

      g·(sum_j rho_j s_ji) + h·(sum_j rho_j s'_ji)
          == sum_l x_i^l · (sum_j rho_j E_jl)

  One n-sized point-RLC + one point-Horner per recipient replaces the
  n·(n-1) individual (t+1)-MSMs of the reference (committee.rs:292-296)
  — ~100x fewer point-ops at n=4096 — while ``verify_pairwise`` remains
  for blame assignment when the batch check fails (soundness: a cheating
  dealer passes the batch check w.p. 2^-rho_bits).
* ``verify_pairwise`` — the direct per-(recipient, dealer) check, used
  on the rare failure path and as the parity oracle.

Secrets discipline: coefficients/shares live on device as scalar limb
arrays; randomness is generated host-side (CSPRNG) and uploaded — the
device path is branchless/batched so secret-dependent control flow never
arises (SURVEY §6 hard part d).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.commitment import CommitmentKey
from ..fields import device as fd
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..poly import device as pdev


@dataclasses.dataclass(frozen=True)
class CeremonyConfig:
    """Static ceremony shape: hashable, jit-static."""

    curve: str  # name in gd.ALL_CURVES
    n: int  # committee size
    t: int  # threshold (polynomial degree)

    @property
    def cs(self) -> gd.CurveSpec:
        return gd.ALL_CURVES[self.curve]

    @property
    def index_bits(self) -> int:
        """Bit width of party indices 1..n."""
        return max(int(self.n).bit_length(), 1)

    def padded(self, n_pad: int, t_pad: int) -> "CeremonyConfig":
        """The shape-bucketed twin of this config: same curve, lanes
        padded to ``(n_pad, t_pad)`` so many ceremonies of nearby shapes
        share ONE set of jitted executables (dkg_tpu.service).

        Pad-and-mask contract: the caller zero-pads the coefficient
        tensors (phantom dealers are all-zero polynomials; real dealers
        gain zero high-order coefficients).  Zero coefficients deal zero
        shares and identity commitments, and every round-1 kernel is
        lane-elementwise along the dealer axis, so the REAL lanes of the
        padded run are bit-identical to the unpadded run — proven by the
        padded-vs-unpadded oracle tests (tests/test_service.py) on both
        curves.  Phantom dealers must be masked out of ``qualified``
        before aggregation/master-key (adding their zero shares is a
        no-op, but they are not protocol participants).
        """
        if n_pad < self.n or t_pad < self.t:
            raise ValueError(
                f"padded({n_pad}, {t_pad}): bucket must dominate the real "
                f"shape (n={self.n}, t={self.t})"
            )
        return CeremonyConfig(self.curve, n_pad, t_pad)


# ---------------------------------------------------------------------------
# round-1 dealing kernels
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=0)
def deal(
    cfg: CeremonyConfig,
    coeffs_a: jax.Array,  # (n, t+1, L) sharing-poly coefficients (secret)
    coeffs_b: jax.Array,  # (n, t+1, L) hiding-poly coefficients (secret)
    g_table: jax.Array,  # (NW, 16, C, L) fixed-base table for g
    h_table: jax.Array,  # (NW, 16, C, L) fixed-base table for h
):
    """All dealers' round-1 outputs in one shot.

    Returns (A, E, s, r):
      A (n, t+1, C, L) bare commitments g·a_l      (committee.rs:151-159)
      E (n, t+1, C, L) randomized A + h·b_l
      s (n, n, L)  share matrix s[j, i] = f_j(i+1)  (committee.rs:163-186)
      r (n, n, L)  hiding shares f'_j(i+1)
    """
    a_pub, e_comm = deal_commitments(cfg, coeffs_a, coeffs_b, g_table, h_table)
    shares, hidings = deal_shares(cfg, coeffs_a, coeffs_b)
    return a_pub, e_comm, shares, hidings


def deal_commitments(cfg, coeffs_a, coeffs_b, g_table, h_table):
    """Commitment half of dealing: (A, E) only (committee.rs:151-159)."""
    cs = cfg.cs
    a_pub = gd.fixed_base_mul(cs, g_table, coeffs_a)  # (m, t+1, C, L)
    b_hid = gd.fixed_base_mul(cs, h_table, coeffs_b)
    return a_pub, gd.add(cs, a_pub, b_hid)


def deal_shares(cfg, coeffs_a, coeffs_b):
    """Share half of dealing: the full share/hiding matrices
    (committee.rs:163-186)."""
    fs = cfg.cs.scalar
    xs = jnp.arange(1, cfg.n + 1, dtype=jnp.uint32)
    xs_limbs = jnp.zeros((cfg.n, fs.limbs), jnp.uint32).at[:, 0].set(xs)
    shares = pdev.eval_many(fs, coeffs_a, xs_limbs)  # (m, n, L)
    hidings = pdev.eval_many(fs, coeffs_b, xs_limbs)
    return shares, hidings


def _deal_chunk_default(cfg: CeremonyConfig, m: int | None = None) -> int:
    """Dealer-axis chunk size that keeps deal()'s TPU peak in budget.

    The fixed-base scan carries an (n_chunk, t+1, C, L) accumulator
    whose minor (C, L) dims are tile-padded to (8, 128) by the TPU
    layout (AOT compile at n=4096 t=1365: "Unpadded (3.39G) Padded
    (15.51G)", an HBM OOM on a 16 GB v5e).  Temps scale with the
    dealer chunk, and at RUNTIME they must coexist with the phase's own
    inputs (coefficients) and outputs (a, e, s, r for the ``m`` rows
    being dealt) — at BLS n=16384 over 8 devices those are 12.2 GB by
    themselves, so a fixed temp budget cannot be right for every shape.
    The budget is therefore what remains of a 15 GiB usable device
    after inputs + outputs (floored at 1 GiB so tiny devices still
    make progress, capped at 6.25 GiB — the AOT-measured sweet spot at
    the north-star shape: chunk=1024, peak 8.18 GB, ~2x headroom under
    the verify phase that follows).

    chunk = budget / ((t+1) * 8 * 128 * 4 B) padded-carry bytes per
    dealer, floored to a power of two so all full chunks share one
    compiled program (a ragged last chunk compiles once more).
    """
    if m is None:
        m = cfg.n
    cs = cfg.cs
    pt_bytes = cs.ncoords * cs.field.limbs * 4
    sc_bytes = cs.scalar.limbs * 4
    io_bytes = (
        2 * m * (cfg.t + 1) * sc_bytes  # coeffs_a + coeffs_b in
        + 2 * m * (cfg.t + 1) * pt_bytes  # a + e out
        + 2 * m * cfg.n * sc_bytes  # shares + hidings out
    )
    budget = min(25 << 28, max(1 << 30, (15 << 30) - io_bytes))
    per_dealer = (cfg.t + 1) * 8 * 128 * 4
    chunk = max(1, budget // per_dealer)
    return 1 << max(0, chunk.bit_length() - 1)


def _env_chunk(name: str) -> int | None:
    """A validated chunk-size env knob: None when unset, else an int >= 0
    (0 disables chunking).  Shared by DKG_TPU_DEAL_CHUNK and
    DKG_TPU_RLC_CHUNK here and DKG_TPU_VERIFY_CHUNK (parallel/mesh)."""
    from ..utils import envknobs

    return envknobs.nonneg_int(name, "0 disables chunking")


def _deal_env_chunk() -> int | None:
    return _env_chunk("DKG_TPU_DEAL_CHUNK")


def deal_chunked(
    cfg: CeremonyConfig,
    coeffs_a: jax.Array,
    coeffs_b: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
    chunk: int | None = None,
):
    """``deal`` in dealer-axis chunks (host loop of identical jit calls).

    Outputs are concatenated on the dealer axis and bit-identical to a
    one-shot ``deal`` (each dealer's row is independent).  Chunking
    exists purely to bound the TPU scan-carry padding described in
    :func:`_deal_chunk_default`; when the caller does not pin a chunk,
    ``DKG_TPU_DEAL_CHUNK`` forces the size (0 disables chunking) —
    an explicit ``chunk`` argument always wins.
    """
    if chunk is None:
        chunk = _deal_env_chunk()
        if chunk is None:
            chunk = _deal_chunk_default(cfg, coeffs_a.shape[0]) if fd._on_tpu() else 0
    # chunk over the rows actually supplied — callers may deal for a
    # LOCAL subset of dealers (committee_batch: m <= n rows)
    n_rows = coeffs_a.shape[0]
    if not chunk or chunk >= n_rows:
        return deal(cfg, coeffs_a, coeffs_b, g_table, h_table)
    outs = [
        deal(cfg, coeffs_a[c0 : c0 + chunk], coeffs_b[c0 : c0 + chunk], g_table, h_table)
        for c0 in range(0, n_rows, chunk)
    ]
    return tuple(jnp.concatenate(parts, axis=0) for parts in zip(*outs))


def _shares_chunk_default(cfg: CeremonyConfig, m: int) -> int:
    """Dealer-axis chunk for the STANDALONE shares program
    (:func:`deal_shares_traced_chunked`).

    Its Horner carry is (w, n, L) u32 with the minor (n, L) dims
    tile-padded — per dealer ~n * 128 * 4 B per matrix, two matrices.
    The budget is what remains of 15 GiB after the program's arguments
    (coefficients), its outputs (both share matrices), AND the
    commitment tensors left RESIDENT by the first deal program — the
    whole point of the two-program split is that the commitment scan's
    temps are freed by then, so only real state is charged.
    """
    cs = cfg.cs
    pt_bytes = cs.ncoords * cs.field.limbs * 4
    sc_bytes = cs.scalar.limbs * 4
    io_bytes = (
        2 * m * (cfg.t + 1) * sc_bytes  # coeffs in
        + 2 * m * (cfg.t + 1) * pt_bytes  # resident a + e
        + 2 * m * cfg.n * sc_bytes  # shares + hidings out
    )
    budget = min(25 << 28, max(1 << 30, (15 << 30) - io_bytes))
    per_dealer = 2 * cfg.n * 128 * 4
    chunk = max(1, budget // per_dealer)
    return 1 << max(0, chunk.bit_length() - 1)


def deal_commitments_traced_chunked(cfg, coeffs_a, coeffs_b, g_table, h_table):
    """In-trace dealer-chunked commitment half (A, E) for sharded
    bodies — the first of the two sequential deal programs (the split
    lets XLA free this program's fixed-base scan carry before the
    shares program allocates its Horner temps; the MONOLITHIC chunked
    deal has a ~6.5 G temp floor that cannot coexist with its own
    12.2 G of inputs+outputs at BLS n=16384 over 8 devices)."""
    from ..utils.scanchunk import map_chunked

    m = int(coeffs_a.shape[0])
    chunk = _deal_env_chunk()
    if chunk is None:
        chunk = _deal_chunk_default(cfg, m)

    def call(off, w):
        ca = lax.dynamic_slice_in_dim(coeffs_a, off, w, 0)
        cb = lax.dynamic_slice_in_dim(coeffs_b, off, w, 0)
        return deal_commitments(cfg, ca, cb, g_table, h_table)

    return map_chunked(m, chunk, call)


def deal_shares_traced_chunked(cfg, coeffs_a, coeffs_b):
    """In-trace dealer-chunked share half (s, r) — the second deal
    program; see :func:`deal_commitments_traced_chunked`."""
    from ..utils.scanchunk import map_chunked

    m = int(coeffs_a.shape[0])
    chunk = _deal_env_chunk()
    if chunk is None:
        chunk = _shares_chunk_default(cfg, m)

    def call(off, w):
        ca = lax.dynamic_slice_in_dim(coeffs_a, off, w, 0)
        cb = lax.dynamic_slice_in_dim(coeffs_b, off, w, 0)
        return deal_shares(cfg, ca, cb)

    return map_chunked(m, chunk, call)


# ---------------------------------------------------------------------------
# verification kernels
# ---------------------------------------------------------------------------


def _field_dot(fs, weights: jax.Array, values: jax.Array) -> jax.Array:
    """sum_j weights[j] * values[j, ...] over axis 0, mod p.

    weights (m, L), values (m, ..., L) -> (..., L).
    """
    from ..fields import matmul as fmm

    if (fmm.mxu_matmul_active() and values.ndim == 3
            and weights.shape[0] <= fmm.MAX_K):
        # one-row modular matmul on the MXU (contraction over dealers)
        return fmm.matmul_mod(fs, weights[None], jnp.swapaxes(values, 0, 1))[0]
    prods = fd.mul(fs, weights.reshape((weights.shape[0],) + (1,) * (values.ndim - 2) + (weights.shape[-1],)), values)

    def step(acc, v):
        return fd.add(fs, acc, v), None

    acc, _ = lax.scan(step, fd.zeros(fs, values.shape[1:-1]), prods)
    return acc


def _point_rlc(cs, weights: jax.Array, points: jax.Array, nbits: int) -> jax.Array:
    """sum_j weights[j]·P[j, ...] for nbits-wide public weights.

    weights (m, L) limb arrays with only the low nbits set;
    points (m, ..., C, L) -> (..., C, L).

    Three schedules, same sum:

    * **Bucket Pippenger** (:func:`groups.device.msm_pippenger`) — no
      per-point tables; points scatter into 2**c buckets per window,
      c chosen from the batch shape.  Default off-TPU: it avoids the
      per-lane Straus table build + gathers that dominate the CPU
      lowering, and its three scan bodies keep compiles light.
    * **Windowed Straus (w = 4)** — per-point 16-entry tables, then
      ceil(nbits/4) rounds of (gather + tree-add + one 4-double window
      step), ~2.8x fewer point-adds than bit-at-a-time.  Default on
      TPU; the window step is the fused Pallas kernel when those are
      active, a plain XLA 4-double+add otherwise — so the conservative
      (no-Pallas) TPU configuration still gets the cheaper schedule.
    * **Bit-at-a-time ladder** — the compile-cheapest schedule, kept as
      the cross-platform parity leg (bench parity_check).

    ``DKG_TPU_RLC=straus|bits|pippenger`` (validated via envknobs)
    forces a schedule on any backend (the cross-schedule parity tests
    use this).  Like every feature flag here, it is read at TRACE time:
    a jitted caller (verify_batch) caches its executable per static
    shape, so flipping the env var after a same-shape call reuses the
    already-traced schedule — set flags before the first call of a
    process (the bench's child-per-rung design exists exactly for this).
    """
    from ..utils import envknobs

    m = points.shape[0]
    mode = envknobs.choice(
        "DKG_TPU_RLC",
        ("straus", "bits", "pippenger"),
        "a typo would silently measure the wrong schedule",
    )
    fused = gd.fused_multi_active(cs)
    if mode is None:
        mode = (
            "straus"
            if gd.fused_kernels_active() or fd._on_tpu()
            else "pippenger"
        )
    if mode != "bits" and points.ndim > 3:
        # Chunk the first trailing batch axis so the per-chunk temps
        # (per-point Straus tables / Pippenger buckets) stay under
        # ~256 MB regardless of (m, t); any FURTHER batch axes multiply
        # the per-chunk size too.  The chunks MUST run through a
        # sequential lax.map: the round-4 unrolled concatenate loop let
        # the TPU buffer assigner overlap ~196 live 252 MB chunk tables
        # at BLS n=16384 (MEMPROOF_TPU: 26.5 G fragmentation on 6 G of
        # real temps).  DKG_TPU_RLC_CHUNK overrides the budget
        # (tests force tiny chunks; 0 disables chunking).
        if mode == "straus":
            per_col = m * 16 * cs.ncoords * cs.field.limbs * 4
        else:
            pwin = gd.pippenger_window(m, cs.name)
            nw = -(-nbits // pwin)
            per_col = nw * (1 << pwin) * cs.ncoords * cs.field.limbs * 4
        for extra in points.shape[2:-2]:
            per_col *= extra
        chunk = _env_chunk("DKG_TPU_RLC_CHUNK")
        if chunk is None:
            chunk = max(1, (256 << 20) // per_col)
        ncols = points.shape[1]
        if chunk and ncols > chunk:
            from ..utils.scanchunk import map_chunked

            def col_chunk(off, w):
                cols = lax.dynamic_slice_in_dim(points, off, w, axis=1)
                return _point_rlc(cs, weights, cols, nbits)

            return map_chunked(ncols, chunk, col_chunk)

    if mode == "pippenger":
        # weights broadcast over the column axes; the m axis moves last
        # to match the MSM kernel's (..., m, C, L) convention
        return gd.msm_pippenger(
            cs, weights, jnp.moveaxis(points, 0, -3), nbits=nbits
        )

    if mode == "straus":
        window = gd.WINDOW
        nd = -(-nbits // window)  # windows that can be non-zero
        table = gd._build_table(cs, points)  # (m, ..., 16, C, L)
        digits = gd.scalar_windows(cs, weights, window)[:, :nd]  # (m, nd)
        digits_rev = jnp.moveaxis(digits, -1, 0)[::-1]  # (nd, m) MSB first

        def step(acc, dig):
            shape = (m,) + (1,) * (points.ndim - 3)
            contribs = gd._gather_table(
                table, jnp.broadcast_to(dig.reshape(shape), points.shape[:-2])
            )  # (m, ..., C, L)
            total = gd._tree_reduce(cs, jnp.moveaxis(contribs, 0, -3), m)
            return gd.window_step(cs, acc, total, window, fused), None

        init = gd.identity(cs, points.shape[1:-2])
        acc, _ = lax.scan(step, init, digits_rev)
        return acc

    # bits (m, nbits) from the 16-bit limbs, then MSB-first rows
    idx = jnp.arange(nbits)
    limbs = weights[:, idx // 16]  # (m, nbits)
    bits = (limbs >> (idx % 16).astype(jnp.uint32)) & 1
    bits_rev = jnp.moveaxis(bits, -1, 0)[::-1]

    def step_bin(acc, bit_row):
        acc = gd._double_xla(cs, acc)
        shape = (m,) + (1,) * (points.ndim - 3)
        sel = gd.select(
            (bit_row.reshape(shape) != 0) | jnp.zeros(points.shape[:-2], bool),
            points,
            gd.identity(cs, points.shape[:-2]),
        )
        total = gd._tree_reduce(cs, jnp.moveaxis(sel, 0, -3), m)
        return gd._add_xla(cs, acc, total), None

    init = gd.identity(cs, points.shape[1:-2])
    acc, _ = lax.scan(step_bin, init, bits_rev)
    return acc


@functools.partial(jax.jit, static_argnums=(0, 5))
def verify_batch(
    cfg: CeremonyConfig,
    e_comm: jax.Array,  # (n, t+1, C, L) all dealers' randomized commitments
    shares: jax.Array,  # (n, n, L) s[j, i] as received by recipient i
    hidings: jax.Array,  # (n, n, L)
    rho: jax.Array,  # (n, L) Fiat-Shamir randomizers (low rho_bits bits)
    rho_bits: int,
    g_table: jax.Array,
    h_table: jax.Array,
) -> jax.Array:
    """RLC batch share-verification; returns (n,) bool per recipient.

    Sound up to 2^-rho_bits per cheating dealer; on False the caller
    falls back to ``verify_pairwise`` rows for blame assignment
    (mirrors the complaint path, committee.rs:305-317).
    """
    cs = cfg.cs
    fs = cs.scalar

    # per-recipient scalar RLCs over dealers:  (n_recipients, L)
    s_rlc = _field_dot(fs, rho, shares)  # sum_j rho_j s_{j,i}
    r_rlc = _field_dot(fs, rho, hidings)

    # combined commitment columns D_l = sum_j rho_j E_{j,l}: (t+1, C, L)
    # (the fused path chunks the column axis internally to bound its
    # Straus-table memory)
    d_comm = _point_rlc(cs, rho, e_comm, rho_bits)

    # RHS_i = sum_l x_i^l D_l via small-x point Horner: (n, C, L)
    xs = jnp.arange(1, cfg.n + 1, dtype=jnp.uint32)
    rhs = gd.eval_point_poly(cs, d_comm, xs, cfg.index_bits)

    # LHS_i = g·s_rlc + h·r_rlc
    lhs = gd.add(
        cs,
        gd.fixed_base_mul(cs, g_table, s_rlc),
        gd.fixed_base_mul(cs, h_table, r_rlc),
    )
    return gd.eq(cs, lhs, rhs)


@functools.partial(jax.jit, static_argnums=0)
def verify_pairwise(
    cfg: CeremonyConfig,
    e_comm: jax.Array,  # (n_dealers, t+1, C, L)
    shares: jax.Array,  # (n_dealers, n_recipients, L)
    hidings: jax.Array,
    g_table: jax.Array,
    h_table: jax.Array,
) -> jax.Array:
    """Direct per-(dealer, recipient) checks -> (n_dealers, n_recipients)
    bool.  The reference's equation exactly (committee.rs:292-296), as
    one wide batched op; used for blame assignment + as parity oracle.
    """
    cs = cfg.cs
    lhs = gd.add(
        cs,
        gd.fixed_base_mul(cs, g_table, shares),
        gd.fixed_base_mul(cs, h_table, hidings),
    )  # (n_d, n_r, C, L)
    xs = jnp.arange(1, shares.shape[1] + 1, dtype=jnp.uint32)[None, :]
    rhs = gd.eval_point_poly(
        cs, e_comm[:, None], jnp.broadcast_to(xs, shares.shape[:2]), cfg.index_bits
    )
    return gd.eq(cs, lhs, rhs)


@functools.partial(jax.jit, static_argnums=0)
def aggregate_shares(cfg: CeremonyConfig, shares: jax.Array, qualified: jax.Array):
    """Final share per recipient: sum of qualified dealers' shares
    (committee.rs:453-462).  shares (n_dealers, n_recip, L),
    qualified (n_dealers,) bool -> (n_recip, L)."""
    fs = cfg.cs.scalar
    masked = fd.select(
        jnp.broadcast_to(qualified[:, None], shares.shape[:-1]),
        shares,
        fd.zeros(fs, shares.shape[:-1]),
    )

    def step(acc, row):
        return fd.add(fs, acc, row), None

    acc, _ = lax.scan(step, fd.zeros(fs, shares.shape[1:-1]), masked)
    return acc


@functools.partial(jax.jit, static_argnums=0)
def master_key_from_bare(cfg: CeremonyConfig, a_comm: jax.Array, qualified: jax.Array):
    """Master public key = sum over qualified dealers of A_{j,0}
    (committee.rs:791-796).  a_comm (n, t+1, C, L) -> (C, L)."""
    cs = cfg.cs
    a0 = a_comm[:, 0]  # (n, C, L)
    masked = gd.select(
        jnp.broadcast_to(qualified, a0.shape[:-2]), a0, gd.identity(cs, a0.shape[:-2])
    )
    return gd._tree_reduce(cs, masked, masked.shape[0])


# ---------------------------------------------------------------------------
# host-facing orchestration
# ---------------------------------------------------------------------------


def _dealer_row_digests(shares_rows: np.ndarray, hidings_rows: np.ndarray) -> np.ndarray:
    """Per-dealer digests of the delivered share/hiding rows.

    (k, n, L) x2 -> (k, 32) uint8.  Dealer position is bound by the
    order in which the caller folds these into the outer digest."""
    out = np.zeros((len(shares_rows), 32), np.uint8)
    for i in range(len(shares_rows)):
        h = hashlib.blake2b(digest_size=32, person=b"dkgtpu-row")
        h.update(np.ascontiguousarray(shares_rows[i]))
        h.update(np.ascontiguousarray(hidings_rows[i]))
        out[i] = np.frombuffer(h.digest(), np.uint8)
    return out


def _fold_digest(cfg: CeremonyConfig, a_np: np.ndarray, e_np: np.ndarray,
                 row_digests: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=32, person=b"dkgtpu-tr")
    h.update(f"{cfg.curve}|{cfg.n}|{cfg.t}|".encode())
    for arr in (a_np, e_np):
        a = np.ascontiguousarray(arr)
        h.update(str(a.shape).encode() + str(a.dtype).encode())
        h.update(a)  # streamed: no bytes() copy of ~100 MB tensors
    h.update(np.ascontiguousarray(row_digests))
    return h.digest()


def _fold_digest_device(cfg: CeremonyConfig, rows_a, rows_e, rows_sr) -> bytes:
    """Outer fold shared by the flat and sharded device digests: binds
    the three per-dealer row-digest arrays in dealer order."""
    h = hashlib.blake2b(digest_size=32, person=b"dkgtpu-trd")
    h.update(f"{cfg.curve}|{cfg.n}|{cfg.t}|".encode())
    for rows in (rows_a, rows_e, rows_sr):
        h.update(np.ascontiguousarray(np.asarray(rows, np.uint32)))
    return h.digest()


def _dealer_rows_device(cfg: CeremonyConfig, a_comm, e_comm, shares, hidings,
                        dispatch: str | None = None):
    """Per-dealer BLAKE2s row digests of all four round-1 tensors:
    (k, ...) local-dealer slices -> three (k, 8) uint32 arrays.

    Every array is row-digested along the dealer axis (never tree-hashed
    flat), so EVERY part of the transcript is shard-foldable — a mesh
    that keeps commitments dealer-sharded (no allgather) still derives
    the canonical digest by exchanging 3 x 32 bytes per dealer.

    Backend-dispatched (``device_hash.digest_dispatch``): the device leg
    canonicalises and Merkle-hashes on device (one jitted program per
    tensor shape); the host leg moves the tensors once and runs the
    big-int canonicalisation (``gd.affine_canon_host``) plus the batched
    numpy tree — on CPU that replaces the XLA per-op-overhead path that
    made fiat_shamir the slowest ceremony phase.  Both legs produce the
    SAME three row-digest arrays bit for bit.
    """
    from ..crypto import device_hash as dh

    if dispatch is None:
        dispatch = dh.digest_dispatch()
    k = shares.shape[0]
    # Commitments are digested in CANONICAL affine form: projective Z
    # scale depends on the addition schedule (platform/flags), and rho
    # must be a function of the logical transcript, not of which kernel
    # computed it (gd.affine_canon's docstring has the full argument).
    if dispatch == "host":
        a_canon = gd.affine_canon_host(cfg.cs, np.asarray(a_comm))
        e_canon = gd.affine_canon_host(cfg.cs, np.asarray(e_comm))
        sr = np.concatenate(
            [
                np.asarray(shares).reshape(k, -1),
                np.asarray(hidings).reshape(k, -1),
            ],
            axis=-1,
        )
    else:
        a_canon = gd.affine_canon(cfg.cs, jnp.asarray(a_comm))
        e_canon = gd.affine_canon(cfg.cs, jnp.asarray(e_comm))
        sr = jnp.concatenate(
            [
                jnp.asarray(shares, jnp.uint32).reshape(k, -1),
                jnp.asarray(hidings, jnp.uint32).reshape(k, -1),
            ],
            axis=-1,
        )
    rows_a = dh.row_digests(a_canon.reshape(k, -1), domain=1, dispatch=dispatch)
    rows_e = dh.row_digests(e_canon.reshape(k, -1), domain=2, dispatch=dispatch)
    rows_sr = dh.row_digests(sr, domain=3, dispatch=dispatch)
    return rows_a, rows_e, rows_sr


def transcript_digest_device(
    cfg: CeremonyConfig, a_comm, e_comm, shares, hidings
) -> bytes:
    """THE canonical engine transcript digest (device-resident).

    Same binding guarantee as the byte-level :func:`transcript_digest`
    (every limb of all four round-1 tensors), different digest function:
    the tensors are hashed where they live with the BLAKE2s Merkle tree
    (crypto.device_hash) and only (n, 32)-byte dealer row digests reach
    the host — instead of shipping ~2 GB of share matrices at n=4096.
    Fully shard-foldable along the dealer axis (commitments included),
    so a mesh never needs the replicated tensors just to hash them
    (:func:`sharded_transcript_digest` computes this exact value from
    dealer-sharded arrays).
    """
    return _fold_digest_device(
        cfg, *_dealer_rows_device(cfg, a_comm, e_comm, shares, hidings)
    )


def transcript_digest(cfg: CeremonyConfig, a_comm, e_comm, shares, hidings) -> bytes:
    """Digest of the COMPLETE round-1 broadcast transcript.

    Binds every limb of all four round-1 tensors — bare commitments A,
    randomized commitments E, and the delivered share/hiding matrices
    (the engine's stand-ins for the public broadcast: in the wire
    protocol the encrypted shares are public and determine s/r,
    reference committee.rs:163-186).  An adaptive dealer cannot change
    any part of its round-1 output without changing the derived batch
    randomizers.

    Structure is canonical and byte-level — the wire/audit alternative
    to the canonical engine digest (:func:`transcript_digest_device`);
    callers must pick ONE digest family per ceremony, and every engine
    path (BatchedCeremony, bench, sharded, driver entry) uses the
    device family via :func:`derive_rho`'s default.
    """
    rows = _dealer_row_digests(np.asarray(shares), np.asarray(hidings))
    # Same canonical-form discipline as the device digest family: the
    # audit digest must agree for the same logical transcript no matter
    # which schedule produced the projective coordinates.
    a_canon = np.asarray(gd.affine_canon(cfg.cs, jnp.asarray(a_comm)))
    e_canon = np.asarray(gd.affine_canon(cfg.cs, jnp.asarray(e_comm)))
    return _fold_digest(cfg, a_canon, e_canon, rows)


def sharded_transcript_digest(cfg: CeremonyConfig, a, e, s, r) -> bytes:
    """transcript_digest_device over mesh-sharded round-1 output.

    ALL FOUR tensors are dealer-sharded (the scalable mesh layout never
    replicates the commitments).  Each process Merkle-hashes its local
    dealer rows ON DEVICE; only 3 x 32 bytes per dealer cross process
    boundaries, so this works on multi-host meshes where
    ``np.asarray(s)`` would fail (shards on non-addressable devices).
    Bit-identical to ``transcript_digest_device`` on the unsharded
    arrays — the sharded and single-chip engines derive the SAME rho
    from the same transcript.  All four tensors must share ONE dealer
    layout: either all dealer-sharded identically or all replicated
    (mixed layouts fail the identical-sharding assertion).
    """
    rows = [np.zeros((cfg.n, 8), np.uint32) for _ in range(3)]
    per = []
    for t in (a, e, s, r):
        shards = sorted(
            t.addressable_shards, key=lambda sh: sh.index[0].start or 0
        )
        per.append(shards)
    seen = set()
    for sh_a, sh_e, sh_s, sh_r in zip(*per):
        sl = sh_s.index[0]
        if not (sh_r.index[0] == sl and sh_a.index[0] == sl and sh_e.index[0] == sl):
            # typed, not an assert: a mixed dealer layout would silently
            # fold the WRONG rows into the digest under ``python -O``
            # (asserts compile away) — and a wrong-but-valid rho is a
            # soundness bug, not a crash.
            raise ValueError(
                "sharded_transcript_digest: round-1 tensors must share one "
                "dealer-axis layout (all dealer-sharded identically or all "
                f"replicated); got a/e/s/r slices "
                f"{sh_a.index[0]}/{sh_e.index[0]}/{sl}/{sh_r.index[0]}"
            )
        if (sl.start, sl.stop) in seen:  # replicated shard copy
            continue
        seen.add((sl.start, sl.stop))
        ra, re, rsr = _dealer_rows_device(
            cfg, sh_a.data, sh_e.data, sh_s.data, sh_r.data
        )
        for dst, src in zip(rows, (ra, re, rsr)):
            dst[sl] = np.asarray(src)
    if jax.process_count() > 1:  # pragma: no cover — single-process CI
        from jax.experimental import multihost_utils as mhu

        gathered = np.asarray(mhu.process_allgather(jnp.asarray(np.stack(rows))))
        # each dealer row is owned by exactly one process; others are 0
        rows = list(np.bitwise_or.reduce(gathered, axis=0))
    return _fold_digest_device(cfg, *rows)


def fiat_shamir_rho(cfg: CeremonyConfig, transcript: bytes, rho_bits: int) -> np.ndarray:
    """Public batch-verification randomizers derived from the round-1
    transcript (publicly recomputable, so the batch check is itself
    verifiable).  ``transcript`` must be a binding digest of the full
    round-1 broadcast — use :func:`transcript_digest`.  Returns (n, L)
    uint32 limbs with rho_bits entropy.

    One ``crypto.blake2.blake2b_batch`` call derives all n lanes — at
    n=4096 the former per-dealer ``hashlib`` loop was 4096 sequential
    host hashes; now it is one (n, 36)-byte array op, byte-identical
    per lane (tests/test_digest_dispatch.py pins pre-vectorization
    golden outputs)."""
    from ..crypto.blake2 import blake2b_batch

    fs = cfg.cs.scalar
    nbytes = (rho_bits + 7) // 8
    # mask to EXACTLY rho_bits: the point side (_point_rlc) consumes only
    # the low rho_bits, while the field side (_field_dot) consumes every
    # set bit — they must see the same weights for any rho_bits.
    mask = (1 << rho_bits) - 1
    tlen = len(transcript)
    msgs = np.zeros((cfg.n, tlen + 4), np.uint8)
    msgs[:, :tlen] = np.frombuffer(transcript, np.uint8)
    msgs[:, tlen:] = (
        np.arange(cfg.n, dtype="<u4").reshape(cfg.n, 1).view(np.uint8)
    )
    dig = blake2b_batch(msgs, digest_size=nbytes, person=b"dkgtpu-rlc")
    out = np.zeros((cfg.n, fs.limbs), np.uint32)
    if (1 << rho_bits) > fs.modulus:
        # masked value may exceed the scalar modulus: reduce per lane
        # exactly as fh.encode always has (rare — rho_bits at/above the
        # field size; the vector path below must not re-implement the
        # reduction)
        for j in range(cfg.n):
            out[j] = fh.encode(
                fs, int.from_bytes(dig[j].tobytes(), "little") & mask
            )
        return out
    # little-endian bytes -> 16-bit limbs, masked to exactly rho_bits
    nlimb = min((nbytes + 1) // 2, fs.limbs)
    buf = np.zeros((cfg.n, nlimb * 2), np.uint8)
    buf[:, :nbytes] = dig
    limbs16 = np.ascontiguousarray(buf).view("<u2").astype(np.uint32)
    full, rem = divmod(rho_bits, 16)
    if rem and full < nlimb:
        limbs16[:, full] &= (1 << rem) - 1
    if full + (1 if rem else 0) < nlimb:
        limbs16[:, full + (1 if rem else 0):] = 0
    out[:, :nlimb] = limbs16
    return out


def derive_rho(
    cfg: CeremonyConfig, a_comm, e_comm, shares, hidings, rho_bits: int,
    *, device: bool = True, trace=None,
) -> np.ndarray:
    """rho from the real round-1 transcript — the only sound way to get
    batch randomizers (every caller path: engine, bench, sharded,
    driver entry).

    Binds ALL FOUR round-1 tensors.  The bare commitments A must be
    bound too: they feed ``master_key_from_bare`` and (in the reference,
    round 4) the second share check, so a dealer must not be able to
    pick A after seeing rho any more than E/s/r.

    ``device=True`` (default) hashes the tensors with the Merkle family
    (:func:`transcript_digest_device`), whose backend leg — jitted
    device tree vs numpy batch — is picked by
    ``crypto.device_hash.digest_dispatch`` (DKG_TPU_DIGEST knob);
    ``device=False`` uses the byte-level host audit digest.

    Pass a :class:`dkg_tpu.utils.tracing.CeremonyTrace` to split the
    fiat_shamir span into ``digest`` / ``rho`` sub-timings and record
    which digest leg ran (``digest_dispatch`` meta field).
    """
    from ..crypto import device_hash as dh

    dispatch = dh.digest_dispatch() if device else "audit"
    digest_fn = transcript_digest_device if device else transcript_digest
    t0 = time.perf_counter()
    transcript = digest_fn(cfg, a_comm, e_comm, shares, hidings)
    t1 = time.perf_counter()
    rho = fiat_shamir_rho(cfg, transcript, rho_bits)
    if trace is not None:
        trace.record_sub("fiat_shamir", "digest", t1 - t0)
        trace.record_sub("fiat_shamir", "rho", time.perf_counter() - t1)
        trace.meta["digest_dispatch"] = dispatch
    return rho


class BatchedCeremony:
    """Single-host happy-path ceremony over device arrays: deal, batch
    verify, aggregate, master key.  The complaint path drops to the
    per-party host state machine (dkg_tpu.dkg.committee) which this
    engine mirrors kernel-for-equation."""

    def __init__(self, curve: str, n: int, t: int, shared_string: bytes, rng):
        import time as _time

        from ..groups import precompute as gp

        self.cfg = CeremonyConfig(curve, n, t)
        cs = self.cfg.cs
        self.group = gh.ALL_GROUPS[curve]
        self.ck = CommitmentKey.generate(self.group, shared_string)
        # g/h tables come from the persistent precompute cache: the
        # second ceremony in a process (and, via the disk cache, the
        # second process) pays zero table-build cost.  The stats delta
        # is kept so run() can attribute table-build vs steady-state
        # time in the trace (bench.py's `warm` flag reads it).
        before = gp.stats()
        t0 = _time.perf_counter()
        self.g_table = gp.generator_table(cs)
        self.h_table = gp.base_table(cs, self.ck.h)
        self.table_seconds = _time.perf_counter() - t0
        after = gp.stats()
        self.table_stats = {
            k: after[k] - before[k] for k in after if isinstance(after[k], int)
        }
        self.rng = rng
        fs = cs.scalar
        self.coeffs_a = jnp.asarray(
            fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(n)])
        )
        self.coeffs_b = jnp.asarray(
            fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(n)])
        )

    def run(self, rho_bits: int = 128, trace=None, tamper=None):
        """Full ceremony over device arrays, including the blame path.

        Happy path: one RLC batch verification covers all n·(n-1) pair
        relations.  If ANY recipient's batch check fails, the engine
        drops to per-pair blame assignment (``verify_pairwise`` — the
        reference's complaint trigger, committee.rs:305-317), records
        one complaint per failing (recipient, dealer) pair, disqualifies
        the guilty dealers (the engine is its own adjudicator: it holds
        the plaintext share matrix, so re-checking IS adjudication —
        the wire path's evidence/DLEQ machinery lives in
        complaints_batch.adjudicate_round1_batch), and completes the
        ceremony over the qualified set (committee.rs:369-398, 453-462).

        Aborts with DkgError(MISBEHAVIOUR_HIGHER_THRESHOLD) when more
        than t dealers are disqualified (committee.rs:340-347).

        Returns a dict of device results; ``complaints`` is a list of
        (accuser_recipient_index, accused_dealer_index) 1-based pairs
        (empty on the happy path) and ``qualified`` the final dealer
        mask.  Pass a :class:`dkg_tpu.utils.tracing.CeremonyTrace` to
        collect per-phase wall-clock + device profiler annotations.

        ``tamper`` is a fault-injection hook for tests: called as
        ``tamper(a, e, s, r) -> (a, e, s, r)`` after dealing, it plays
        the role of the reference tests' hand-corrupted broadcasts
        (committee.rs:1127-1128, 1188).
        """
        import jax as _jax

        from ..utils.tracing import phase_span
        from .errors import DkgError, DkgErrorKind

        cfg = self.cfg
        if trace is not None:
            # table acquisition happened in __init__; record it as its
            # own phase so deal/verify numbers are steady-state
            trace.record("tables", self.table_seconds)
            trace.meta["table_cache"] = dict(self.table_stats)
        with phase_span(trace, "deal"):
            a, e, s, r = deal_chunked(
                cfg, self.coeffs_a, self.coeffs_b, self.g_table, self.h_table
            )
            _jax.block_until_ready(e)
        if tamper is not None:
            a, e, s, r = tamper(a, e, s, r)
        with phase_span(trace, "fiat_shamir"):
            rho = jnp.asarray(derive_rho(cfg, a, e, s, r, rho_bits, trace=trace))
        with phase_span(trace, "verify"):
            ok = verify_batch(cfg, e, s, r, rho, rho_bits, self.g_table, self.h_table)
            _jax.block_until_ready(ok)

        qualified = jnp.ones((cfg.n,), bool)
        complaints: list[tuple[int, int]] = []
        if not bool(np.asarray(ok).all()):
            with phase_span(trace, "blame"):
                pw = np.asarray(
                    verify_pairwise(cfg, e, s, r, self.g_table, self.h_table)
                )  # (n_dealers, n_recipients)
                guilty = ~pw.all(axis=1)
                complaints = [
                    (int(i) + 1, int(j) + 1)
                    for j, i in zip(*np.nonzero(~pw))
                ]
                qualified = jnp.asarray(~guilty)
            if int(guilty.sum()) > cfg.t:
                if trace is not None:
                    trace.meta.update(
                        {"curve": cfg.curve, "n": cfg.n, "t": cfg.t}
                    )
                return {
                    "bare": a,
                    "randomized": e,
                    "shares": s,
                    "hidings": r,
                    "ok": ok,
                    "qualified": qualified,
                    "complaints": complaints,
                    "error": DkgError(DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD),
                }

        with phase_span(trace, "finalise"):
            final_shares = aggregate_shares(cfg, s, qualified)
            master = master_key_from_bare(cfg, a, qualified)
            _jax.block_until_ready(master)
        if trace is not None:
            trace.meta.update({"curve": cfg.curve, "n": cfg.n, "t": cfg.t})
        return {
            "bare": a,
            "randomized": e,
            "shares": s,
            "hidings": r,
            "ok": ok,
            "qualified": qualified,
            "complaints": complaints,
            "final_shares": final_shares,
            "master": master,
        }
