"""Protocol error taxonomy (reference: src/errors.rs:4-74).

Errors are *returned*, not raised, by phase transitions: a party whose own
transition fails may still have complaint data to broadcast (reference
design note src/lib.rs:17-22), so transitions yield
``(result | DkgError, broadcast | None)``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class DkgErrorKind(enum.Enum):
    # (reference: errors.rs:13-68)
    SHARE_VALIDITY_FAILED = "share validity check failed"
    FETCHED_INVALID_DATA = "fetched data addressed to a different recipient"
    SCALAR_OUT_OF_BOUNDS = "decrypted share is not a canonical scalar"
    MISBEHAVIOUR_HIGHER_THRESHOLD = "more misbehaving parties than threshold"
    NOT_ENOUGH_MEMBERS = "fewer honest members than threshold requires"
    INSUFFICIENT_SHARES_FOR_RECOVERY = "not enough disclosed shares to recover"
    INVALID_PROOF_OF_MISBEHAVIOUR = "proof of misbehaviour failed to verify"
    DUPLICATE_SENDER = "two broadcasts claim the same sender index"


@dataclass(frozen=True)
class DkgError(Exception):
    kind: DkgErrorKind
    # index the error refers to, when meaningful (reference: errors.rs:42
    # InsufficientSharesForRecovery carries the failed party index)
    index: int | None = None
    detail: str = field(default="")

    def __str__(self) -> str:  # pragma: no cover
        where = f" (party {self.index})" if self.index is not None else ""
        return f"{self.kind.value}{where}{': ' + self.detail if self.detail else ''}"


@dataclass(frozen=True)
class ProofError(Exception):
    """ZKP verification failure (reference: errors.rs:4-8)."""

    detail: str = ""
