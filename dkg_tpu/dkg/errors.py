"""Protocol error taxonomy (reference: src/errors.rs:4-74).

Errors are *returned*, not raised, by phase transitions: a party whose own
transition fails may still have complaint data to broadcast (reference
design note src/lib.rs:17-22), so transitions yield
``(result | DkgError, broadcast | None)``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class DkgErrorKind(enum.Enum):
    # Full taxonomy parity with the reference (reference: errors.rs:13-68),
    # plus DUPLICATE_SENDER (a broadcast-layer condition the reference
    # leaves to a todo, errors.rs:76).
    SHARE_VALIDITY_FAILED = "share validity check failed"
    FETCHED_INVALID_DATA = "fetched data addressed to a different recipient"
    SCALAR_OUT_OF_BOUNDS = "decrypted share is not a canonical scalar"
    MISBEHAVIOUR_HIGHER_THRESHOLD = "more misbehaving parties than threshold"
    NOT_ENOUGH_MEMBERS = "fewer honest members than threshold requires"
    INSUFFICIENT_SHARES_FOR_RECOVERY = "not enough disclosed shares to recover"
    INVALID_PROOF_OF_MISBEHAVIOUR = "proof of misbehaviour failed to verify"
    DUPLICATE_SENDER = "two broadcasts claim the same sender index"
    # ZKP verification failed (reference: errors.rs:29-31; ProofError
    # converts into this via From, errors.rs:70-74 — here via
    # DkgError.from_proof).
    ZKP_VERIFICATION_FAILED = "zkp verification failed"
    # Byte-string -> scalar parse failure (reference: errors.rs:32-35,
    # raised at broadcast.rs:260-267).
    DECODING_TO_SCALAR_FAILED = "decoding bytes to scalar failed"
    # Local master key disagrees with the public state (reference:
    # errors.rs:44-47; used by callers cross-checking finalise output,
    # committee.rs:1634, lib.rs:176).
    INCONSISTENT_MASTER_KEY = "inconsistent master key generation"
    # Complaint claims an inequality/equality that does not hold
    # (reference: errors.rs:48-60, raised at broadcast.rs:94,138-140).
    FALSE_CLAIMED_EQUALITY = "complaint verification: false claimed equality"
    FALSE_CLAIMED_INEQUALITY = "complaint verification: false claimed inequality"
    # A qualified-set member should have been dismissed earlier
    # (reference: errors.rs:61-68 — defined there but never constructed;
    # kept for taxonomy parity).
    PARTY_SHOULD_BE_DISQUALIFIED = "qualified member should have been dismissed"


@dataclass(frozen=True)
class DkgError(Exception):
    kind: DkgErrorKind
    # index the error refers to, when meaningful (reference: errors.rs:42
    # InsufficientSharesForRecovery carries the failed party index)
    index: int | None = None
    detail: str = field(default="")

    def __str__(self) -> str:  # pragma: no cover
        where = f" (party {self.index})" if self.index is not None else ""
        return f"{self.kind.value}{where}{': ' + self.detail if self.detail else ''}"

    @classmethod
    def from_proof(cls, err: "ProofError") -> "DkgError":
        """ProofError -> DkgError conversion (reference: errors.rs:70-74)."""
        return cls(DkgErrorKind.ZKP_VERIFICATION_FAILED, detail=err.detail)


@dataclass(frozen=True)
class ProofError(Exception):
    """ZKP verification failure (reference: errors.rs:4-8)."""

    detail: str = ""
