"""Device-accelerated round-1 dealing for co-located committee members.

``DistributedKeyGeneration.init`` (committee.py) is the per-party wire
path: serial host scalar-mults per coefficient and per recipient
(mirroring reference committee.rs:124-216).  When a host drives many
parties — the sharded-ceremony deployment, or any simulation — dealing
for all of them at once is a batched device job:

* commitments A_l / E_l for every local dealer: two fixed-base batch
  mults (ceremony.deal; reference hot loop #1, committee.rs:151-159);
* the share matrix via batched Horner (reference hot loop #2,
  committee.rs:163-186 / polynomial.rs:68-74);
* KEM points for every (dealer, recipient) pair: two batched ladder
  calls (hybrid_batch.kem_batch; reference elgamal.rs:134-145);
* DEM sealing + wire packaging host-side (hybrid_batch.seal_shares).

The result is bit-identical in structure to n independent ``init``
calls: each local party gets a ``DkgPhase1`` whose state machine then
proceeds through phases 2-5 exactly as the host path — so the fast
dealing path and the reference-parity protocol logic compose.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..fields import host as fh
from ..groups import device as gd
from .committee import DkgPhase1, Environment, _State
from .hybrid_batch import broadcasts_from_batch, kem_batch, seal_shares
from .broadcast import BroadcastPhase1
from .ceremony import CeremonyConfig, deal
from .procedure_keys import MemberCommunicationKey, sort_committee


def batched_dealing(
    env: Environment,
    rng,
    comm_keys: list[MemberCommunicationKey],
    members: list[int] | None = None,
) -> list[tuple[DkgPhase1, BroadcastPhase1]]:
    """Round-1 dealing for the local parties ``members`` (1-based sorted
    indices; default: every committee member, the in-process-simulation
    case).  ``comm_keys`` holds the full committee's keys in unsorted
    order; each local party must have its key present.

    Returns one (phase1, broadcast) pair per local party, in ``members``
    order — drop-in for per-party ``DistributedKeyGeneration.init``.
    """
    group = env.group
    cs = gd.ALL_CURVES[group.name]
    fs = group.scalar_field
    n, t = env.nr_members, env.threshold
    if len(comm_keys) != n:
        raise ValueError("committee size does not match environment")
    pks = sort_committee(group, [k.public() for k in comm_keys])
    key_by_enc = {group.encode(k.public().point): k for k in comm_keys}
    sorted_keys = [key_by_enc[group.encode(p.point)] for p in pks]
    if members is None:
        members = list(range(1, n + 1))
    m = len(members)

    cfg = CeremonyConfig(group.name, n, t)
    g_table = gd.fixed_base_table(cs, group.generator())
    h_table = gd.fixed_base_table(cs, env.commitment_key.h)

    # secret sampling stays host-side CSPRNG (SURVEY §7 hard part f)
    coeffs_a = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(m)])
    )
    coeffs_b = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(m)])
    )
    bare_dev, rand_dev, shares_dev, hidings_dev = deal(
        cfg, coeffs_a, coeffs_b, g_table, h_table
    )

    # device KEM for all (dealer, recipient) pairs
    pks_dev = gd.from_host(cs, [p.point for p in pks])
    r_enc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(m)])
    )
    c1, kem = kem_batch(cfg, pks_dev, r_enc, g_table)
    sealed = seal_shares(
        group, cfg, np.asarray(shares_dev), np.asarray(hidings_dev),
        np.asarray(c1), np.asarray(kem),
    )
    broadcasts = broadcasts_from_batch(group, cfg, np.asarray(rand_dev), sealed)

    shares_host = fh.decode(fs, np.asarray(shares_dev))
    hidings_host = fh.decode(fs, np.asarray(hidings_dev))
    bare_host = [gd.to_host(cs, np.asarray(bare_dev[d])) for d in range(m)]
    rand_host = [gd.to_host(cs, np.asarray(rand_dev[d])) for d in range(m)]

    out = []
    for d, my in enumerate(members):
        state = _State(env, my, sorted_keys[my - 1], pks)
        state.bare_coeff_points = tuple(bare_host[d])
        state.randomized_coeff_points = tuple(rand_host[d])
        state.bare_coeffs[my] = state.bare_coeff_points
        state.randomized_coeffs[my] = state.randomized_coeff_points
        state.received_shares[my] = (
            int(shares_host[d, my - 1]),
            int(hidings_host[d, my - 1]),
        )
        out.append((DkgPhase1(state), broadcasts[d]))
    return out
