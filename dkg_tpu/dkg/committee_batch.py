"""Device-accelerated round-1 dealing for co-located committee members.

``DistributedKeyGeneration.init`` (committee.py) is the per-party wire
path: serial host scalar-mults per coefficient and per recipient
(mirroring reference committee.rs:124-216).  When a host drives many
parties — the sharded-ceremony deployment, or any simulation — dealing
for all of them at once is a batched device job:

* commitments A_l / E_l for every local dealer: two fixed-base batch
  mults (ceremony.deal; reference hot loop #1, committee.rs:151-159);
* the share matrix via batched Horner (reference hot loop #2,
  committee.rs:163-186 / polynomial.rs:68-74);
* KEM points for every (dealer, recipient) pair: two batched ladder
  calls (hybrid_batch.kem_batch; reference elgamal.rs:134-145);
* DEM sealing + wire packaging host-side (hybrid_batch.seal_shares).

The result is bit-identical in structure to n independent ``init``
calls: each local party gets a ``DkgPhase1`` whose state machine then
proceeds through phases 2-5 exactly as the host path — so the fast
dealing path and the reference-parity protocol logic compose.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..crypto.elgamal import SymmetricKey, open_pair_with_kems
from ..fields import host as fh
from ..groups import device as gd
from ..groups import precompute
from ..utils.tracing import CeremonyTrace, phase_span
from .committee import DkgPhase1, DkgPhase2, Environment, FetchedPhase1, _State
from .hybrid_batch import broadcasts_from_batch, seal_shares_pipeline
from .broadcast import (
    BroadcastPhase1,
    BroadcastPhase2,
    MisbehavingPartiesRound1,
    ProofOfMisbehaviour,
)
from .ceremony import CeremonyConfig, deal_chunked
from .errors import DkgError, DkgErrorKind
from .procedure_keys import (
    MemberCommunicationKey,
    decode_scalar_pair,
    sort_committee,
)


def batched_dealing(
    env: Environment,
    rng,
    comm_keys: list[MemberCommunicationKey],
    members: list[int] | None = None,
    trace: CeremonyTrace | None = None,
) -> list[tuple[DkgPhase1, BroadcastPhase1]]:
    """Round-1 dealing for the local parties ``members`` (1-based sorted
    indices; default: every committee member, the in-process-simulation
    case).  ``comm_keys`` holds the full committee's keys in unsorted
    order; each local party must have its key present.

    Returns one (phase1, broadcast) pair per local party, in ``members``
    order — drop-in for per-party ``DistributedKeyGeneration.init``.
    ``trace`` records ``deal`` (engine polynomials + commitments) and
    ``seal`` (KEM + DEM, with a ``pairs_sealed`` counter) separately so
    traces show deal vs seal vs verify time.
    """
    group = env.group
    cs = gd.ALL_CURVES[group.name]
    fs = group.scalar_field
    n, t = env.nr_members, env.threshold
    if len(comm_keys) != n:
        raise ValueError("committee size does not match environment")
    pks = sort_committee(group, [k.public() for k in comm_keys])
    key_by_enc = {k.public().sort_key(group): k for k in comm_keys}
    sorted_keys = [key_by_enc[p.sort_key(group)] for p in pks]
    if members is None:
        members = list(range(1, n + 1))
    m = len(members)

    cfg = CeremonyConfig(group.name, n, t)
    g_table = precompute.generator_table(cs)
    h_table = precompute.base_table(cs, env.commitment_key.h)

    # secret sampling stays host-side CSPRNG (SURVEY §7 hard part f)
    coeffs_a = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(m)])
    )
    coeffs_b = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(t + 1)] for _ in range(m)])
    )
    with phase_span(trace, "deal"):
        bare_dev, rand_dev, shares_dev, hidings_dev = deal_chunked(
            cfg, coeffs_a, coeffs_b, g_table, h_table
        )

    # device KEM + DEM for all (dealer, recipient) pairs, chunk-
    # pipelined so host sealing overlaps the next chunk's kernels
    pks_dev = gd.from_host(cs, [p.point for p in pks])
    r_enc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(m)])
    )
    with phase_span(trace, "seal"):
        sealed = seal_shares_pipeline(
            group, cfg, shares_dev, hidings_dev, pks_dev, r_enc, g_table
        )
        if trace is not None:
            trace.bump("pairs_sealed", m * n)
    broadcasts = broadcasts_from_batch(group, cfg, np.asarray(rand_dev), sealed)

    shares_host = fh.decode(fs, np.asarray(shares_dev))
    hidings_host = fh.decode(fs, np.asarray(hidings_dev))
    bare_host = [gd.to_host(cs, np.asarray(bare_dev[d])) for d in range(m)]
    rand_host = [gd.to_host(cs, np.asarray(rand_dev[d])) for d in range(m)]

    out = []
    for d, my in enumerate(members):
        state = _State(env, my, sorted_keys[my - 1], pks)
        state.bare_coeff_points = tuple(bare_host[d])
        state.randomized_coeff_points = tuple(rand_host[d])
        state.bare_coeffs[my] = state.bare_coeff_points
        state.randomized_coeffs[my] = state.randomized_coeff_points
        state.received_shares[my] = (
            int(shares_host[d, my - 1]),
            int(hidings_host[d, my - 1]),
        )
        out.append((DkgPhase1(state), broadcasts[d]))
    return out


def batched_share_verification(
    phase1s: list[DkgPhase1],
    fetched: list[FetchedPhase1],
    rng,
) -> list[tuple["DkgPhase2 | DkgError", BroadcastPhase2 | None]]:
    """Round-2 share verification for many co-located parties at once.

    Semantics are EXACTLY per-party ``DkgPhase1.proceed(fetched, rng)``
    (reference hot loop committee.rs:273-317) — same state mutations,
    complaints (sender order preserved), error returns, and threshold
    abort — but the two per-pair device costs run as bulk kernels over
    all (recipient, dealer) pairs:

    * KEM recovery sk_i * e1 (one per distinct pair e1): one batched
      ``scalar_mul`` call instead of n*(n-1) host ladder walks;
    * the commitment check g*s + h*s' == sum_l x_i^l E_{j,l}: two
      fixed-base batch mults + one batched point-Horner
      (committee.rs:292-296 as one wide op).

    ChaCha DEM decode, scalar decoding, and the (rare) complaint
    evidence generation stay host-side.  ``fetched`` is the shared
    broadcast-channel view every local party consumes — the in-process
    simulation seam (reference: committee.rs:1337-1338).
    """
    if not phase1s:
        return []
    sts = [p._state for p in phase1s]
    env, group = sts[0].env, sts[0].group
    cs = gd.ALL_CURVES[group.name]
    fs = group.scalar_field
    sender_order = [f.sender_index for f in fetched]

    # --- stage 1: host triage in fetched order (dropouts, misaddressed
    # data), collecting one KEM exponentiation per distinct pair e1
    kem_sks: list[int] = []
    kem_pts: list[tuple] = []
    jobs: list[tuple[int, int, object, int, int]] = []
    errors: list[DkgError | None] = [None] * len(sts)
    for i, st in enumerate(sts):
        for f in fetched:
            j = f.sender_index
            if j == st.index:
                continue
            if f.broadcast is None:
                st.disqualify(j)  # silent dropout (committee.rs:332-337)
                continue
            mine = f.broadcast.shares_for(st.index)
            if mine is None or mine.recipient_index != st.index:
                errors[i] = DkgError(DkgErrorKind.FETCHED_INVALID_DATA, index=j)
                break
            k1 = len(kem_sks)
            kem_sks.append(st.comm_key.sk)
            kem_pts.append(mine.share_ct.e1)
            if group.eq(mine.share_ct.e1, mine.randomness_ct.e1):
                k2 = k1  # canonical sealed-pair layout: one KEM for both
            else:
                k2 = len(kem_sks)
                kem_sks.append(st.comm_key.sk)
                kem_pts.append(mine.randomness_ct.e1)
            jobs.append((i, j, mine, k1, k2))

    # --- stage 2: all KEM exponentiations as one device batch
    kem_host: list = []
    if kem_sks:
        kem_dev = gd.scalar_mul(
            cs, jnp.asarray(fh.encode(fs, kem_sks)), gd.from_host(cs, kem_pts)
        )
        kem_host = gd.to_host(cs, np.asarray(kem_dev))

    # --- stage 3: host DEM decode; failures become complaints, decodable
    # pairs queue for the batched commitment check
    complaint_at: dict[tuple[int, int], MisbehavingPartiesRound1] = {}
    share_jobs: list[tuple[int, int, object, int, int]] = []
    for i, j, mine, k1, k2 in jobs:
        st = sts[i]
        pt1, pt2 = open_pair_with_kems(
            group,
            SymmetricKey(kem_host[k1]),
            SymmetricKey(kem_host[k2]),
            mine.share_ct,
            mine.randomness_ct,
        )
        (s, r), kind = decode_scalar_pair(group, pt1, pt2)
        if s is None or r is None:
            st.disqualify(j)  # committee.rs:318-331
            complaint_at[(i, j)] = MisbehavingPartiesRound1(
                j,
                kind or DkgErrorKind.SCALAR_OUT_OF_BOUNDS,
                ProofOfMisbehaviour.generate(group, mine, st.comm_key, rng),
            )
            continue
        share_jobs.append((i, j, mine, s, r))

    # --- stage 4: every commitment check as one device batch (the
    # shared implementation complaint adjudication also uses; dealer
    # commitments converted host->device once per dealer, not per pair)
    if share_jobs:
        from .complaints_batch import check_randomized_shares_limbs

        s_limbs = jnp.asarray(fh.encode(fs, [x[3] for x in share_jobs]))
        r_limbs = jnp.asarray(fh.encode(fs, [x[4] for x in share_jobs]))
        by_sender = {f.sender_index: f.broadcast for f in fetched}
        coeff_np: dict[int, np.ndarray] = {}
        for _, j, *_ in share_jobs:
            if j not in coeff_np:
                coeff_np[j] = np.asarray(
                    gd.from_host(cs, list(by_sender[j].committed_coefficients))
                )
        cpts = jnp.asarray(np.stack([coeff_np[j] for _, j, *_ in share_jobs]))
        idx = jnp.asarray([sts[i].index for i, *_ in share_jobs], dtype=jnp.uint32)
        nbits = max(2, int(env.nr_members).bit_length())
        ok = check_randomized_shares_limbs(
            group, cs, env.commitment_key, idx, s_limbs, r_limbs, cpts, nbits
        )
        for (i, j, mine, s, r), good in zip(share_jobs, ok):
            st = sts[i]
            if bool(good):
                st.received_shares[j] = (s, r)
                st.randomized_coeffs[j] = tuple(
                    by_sender[j].committed_coefficients
                )
            else:
                st.disqualify(j)  # committee.rs:305-317
                complaint_at[(i, j)] = MisbehavingPartiesRound1(
                    j,
                    DkgErrorKind.SHARE_VALIDITY_FAILED,
                    ProofOfMisbehaviour.generate(group, mine, st.comm_key, rng),
                )

    # --- stage 5: per-party assembly, complaints in fetched sender order
    results: list[tuple[DkgPhase2 | DkgError, BroadcastPhase2 | None]] = []
    for i, st in enumerate(sts):
        if errors[i] is not None:
            results.append((errors[i], None))
            continue
        comps = tuple(
            complaint_at[(i, j)] for j in sender_order if (i, j) in complaint_at
        )
        broadcast = BroadcastPhase2(comps) if comps else None
        if len(comps) > env.threshold:
            results.append(
                (DkgError(DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD), broadcast)
            )
        else:
            results.append((DkgPhase2(st), broadcast))
    return results
