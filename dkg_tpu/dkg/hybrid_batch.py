"""Batched hybrid share encryption: device KEM + host DEM.

Bridges the batched ceremony engine to the real wire protocol: the
reference hybrid-encrypts each (share, hiding) pair per recipient inside
the dealing loop (reference: committee.rs:163-186 → elgamal.rs:134-145).
Here the KEM scalar-mults for *all* (dealer, recipient) pairs run as two
batched device kernels:

    c1[d, i]  = g·r[d, i]          (fixed-base table)
    kem[d, i] = pk_i · r[d, i]     (batched variable-base)

and only the byte-level tail (point compression -> Blake2b KDF ->
ChaCha20) stays host-side, using the native C++ runtime when available
(SURVEY §7 step 4: DEM off the hot path).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..crypto.elgamal import (
    PERSON_RAND,
    PERSON_SHARE,
    HybridCiphertext,
    keystream_from_kem_bytes,
)
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from .broadcast import BroadcastPhase1, EncryptedShares


def _chacha():
    try:
        from .. import native

        if native.available():
            return native.chacha20_xor
    except Exception:  # pragma: no cover
        pass
    from ..crypto.chacha import chacha20_xor

    return chacha20_xor


def kem_batch(cfg, pks_dev: jnp.ndarray, r_limbs: jnp.ndarray, g_table: jnp.ndarray):
    """Device KEM for all pairs.

    pks_dev  (n_recipients, C, L) — recipient communication public keys
    r_limbs  (..., n_recipients, L) — fresh encryption randomness
    returns (c1, kem), each (..., n_recipients, C, L).
    """
    cs = cfg.cs
    c1 = gd.fixed_base_mul(cs, g_table, r_limbs)
    kem = gd.scalar_mul(cs, r_limbs, jnp.broadcast_to(pks_dev, r_limbs.shape[:-1] + pks_dev.shape[-2:]))
    return c1, kem


def seal_shares(
    group: gh.HostGroup,
    cfg,
    shares: np.ndarray,  # (n_dealers, n_recipients, L) scalar limbs
    hidings: np.ndarray,
    c1: np.ndarray,  # (n_dealers, n_recipients, C, L) from kem_batch
    kem: np.ndarray,
) -> list[list[tuple[HybridCiphertext, HybridCiphertext]]]:
    """Host DEM: compress KEM points, KDF, stream-cipher the scalars.

    The same KEM point seals both ciphertexts of a pair with distinct
    KDF personalisation, matching one ElGamal exponentiation per
    recipient on the device side.
    """
    xor = _chacha()
    cs = cfg.cs
    fs = cs.scalar
    n_d, n_r = shares.shape[:2]
    out = []
    for d in range(n_d):
        c1_pts = gd.to_host(cs, c1[d])
        kem_pts = gd.to_host(cs, kem[d])
        row = []
        for i in range(n_r):
            kem_bytes = group.encode(kem_pts[i])
            e1 = c1_pts[i]
            cts = []
            for tag, limbs in ((PERSON_SHARE, shares[d, i]), (PERSON_RAND, hidings[d, i])):
                key, nonce = keystream_from_kem_bytes(kem_bytes, tag)
                msg = int(fh.decode_int(fs, limbs)).to_bytes(fs.nbytes, "little")
                cts.append(HybridCiphertext(e1, xor(key, nonce, msg)))
            row.append((cts[0], cts[1]))
        out.append(row)
    return out


def open_share(
    group: gh.HostGroup,
    sk: int,
    pair: tuple[HybridCiphertext, HybridCiphertext],
) -> tuple[int | None, int | None]:
    """Recipient-side decryption of a sealed (share, hiding) pair."""
    xor = _chacha()
    fs = group.scalar_field
    share_ct, hiding_ct = pair
    kem_bytes = group.encode(group.scalar_mul(sk, share_ct.e1))
    out = []
    for tag, ct in ((PERSON_SHARE, share_ct), (PERSON_RAND, hiding_ct)):
        key, nonce = keystream_from_kem_bytes(kem_bytes, tag)
        pt = xor(key, nonce, ct.ciphertext)
        v = int.from_bytes(pt, "little") if len(pt) == fs.nbytes else None
        out.append(v if v is None or v < fs.modulus else None)
    return out[0], out[1]


def broadcasts_from_batch(
    group: gh.HostGroup,
    cfg,
    randomized: np.ndarray,  # (n_dealers, t+1, C, L)
    sealed: list[list[tuple[HybridCiphertext, HybridCiphertext]]],
) -> list[BroadcastPhase1]:
    """Package device-dealt commitments + sealed shares as wire-format
    BroadcastPhase1 messages, one per dealer."""
    cs = cfg.cs
    out = []
    for d, row in enumerate(sealed):
        coeffs = tuple(gd.to_host(cs, randomized[d]))
        enc = tuple(
            EncryptedShares(i + 1, share_ct, hiding_ct)
            for i, (share_ct, hiding_ct) in enumerate(row)
        )
        out.append(BroadcastPhase1(coeffs, enc))
    return out
