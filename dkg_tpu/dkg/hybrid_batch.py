"""Batched hybrid share encryption: device KEM + host DEM.

Bridges the batched ceremony engine to the real wire protocol: the
reference hybrid-encrypts each (share, hiding) pair per recipient inside
the dealing loop (reference: committee.rs:163-186 → elgamal.rs:134-145).
Here the KEM scalar-mults for *all* (dealer, recipient) pairs run as two
batched device kernels:

    c1[d, i]  = g·r[d, i]          (fixed-base table)
    kem[d, i] = pk_i · r[d, i]     (batched variable-base)

and the byte-level DEM tail (point compression -> Blake2b KDF ->
ChaCha20) is array-shaped too (:func:`seal_shares_batch`): one batched
affine-encode per ceremony (``groups.device.encode_batch``), one
``(N, 16)``-u64 Blake2b compression batch (``crypto.blake2``) and one
``(2·N, 16)``-u32 ChaCha20 state batch (``crypto.chacha``) replace the
per-pair Python loop.  :func:`seal_shares` survives as the scalar
reference leg — ``DKG_TPU_DEM=scalar|batch`` selects, and both legs
produce bit-identical wire bytes (tests/test_dem_batch.py).
:func:`seal_shares_pipeline` chunks deal->KEM->DEM so the host DEM of
chunk k overlaps the device dispatch of chunk k+1
(docs/perf.md "Dealing pipeline").
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..crypto.elgamal import (
    PERSON_RAND,
    PERSON_SHARE,
    HybridCiphertext,
    keystream_from_kem_bytes,
)
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from .broadcast import BroadcastPhase1, EncryptedShares


def _chacha():
    try:
        from .. import native

        if native.available():
            return native.chacha20_xor
    except Exception:  # pragma: no cover
        pass
    from ..crypto.chacha import chacha20_xor

    return chacha20_xor


def kem_batch(cfg, pks_dev: jnp.ndarray, r_limbs: jnp.ndarray, g_table: jnp.ndarray):
    """Device KEM for all pairs.

    pks_dev  (n_recipients, C, L) — recipient communication public keys
    r_limbs  (..., n_recipients, L) — fresh encryption randomness
    returns (c1, kem), each (..., n_recipients, C, L).
    """
    cs = cfg.cs
    c1 = gd.fixed_base_mul(cs, g_table, r_limbs)
    kem = gd.scalar_mul(cs, r_limbs, jnp.broadcast_to(pks_dev, r_limbs.shape[:-1] + pks_dev.shape[-2:]))
    return c1, kem


def seal_shares(
    group: gh.HostGroup,
    cfg,
    shares: np.ndarray,  # (n_dealers, n_recipients, L) scalar limbs
    hidings: np.ndarray,
    c1: np.ndarray,  # (n_dealers, n_recipients, C, L) from kem_batch
    kem: np.ndarray,
) -> list[list[tuple[HybridCiphertext, HybridCiphertext]]]:
    """Host DEM: compress KEM points, KDF, stream-cipher the scalars.

    The same KEM point seals both ciphertexts of a pair with distinct
    KDF personalisation, matching one ElGamal exponentiation per
    recipient on the device side.
    """
    xor = _chacha()
    cs = cfg.cs
    fs = cs.scalar
    n_d, n_r = shares.shape[:2]
    out = []
    for d in range(n_d):
        c1_pts = gd.to_host(cs, c1[d])
        kem_pts = gd.to_host(cs, kem[d])
        row = []
        for i in range(n_r):
            kem_bytes = group.encode(kem_pts[i])
            e1 = c1_pts[i]
            cts = []
            for tag, limbs in ((PERSON_SHARE, shares[d, i]), (PERSON_RAND, hidings[d, i])):
                key, nonce = keystream_from_kem_bytes(kem_bytes, tag)
                msg = int(fh.decode_int(fs, limbs)).to_bytes(fs.nbytes, "little")
                cts.append(HybridCiphertext(e1, xor(key, nonce, msg)))
            row.append((cts[0], cts[1]))
        out.append(row)
    return out


def dem_mode() -> str:
    """Which DEM leg seals dealing rounds: ``DKG_TPU_DEM=scalar|batch``
    (validated), default ``batch``.  ``scalar`` is the per-pair
    reference leg the batched path is byte-equivalence-tested against."""
    from ..utils import envknobs

    return (
        envknobs.choice(
            "DKG_TPU_DEM",
            ("scalar", "batch"),
            "DEM sealing path; 'scalar' is the per-pair reference leg",
        )
        or "batch"
    )


def _le_bytes(arr: np.ndarray, nbytes: int) -> np.ndarray:
    """16-bit limb rows ``(N, L)`` -> little-endian byte rows
    ``(N, nbytes)`` (the scalar wire encoding), fully vectorized."""
    le = np.ascontiguousarray(arr.astype("<u2")).view(np.uint8)
    return le[:, :nbytes]


def _host_points(cs, pts: np.ndarray) -> list:
    """Point limb batch ``(N, C, L)`` -> host point tuples (same ints as
    ``gd.to_host``), via one vectorized limbs->bytes pass instead of the
    per-limb Python loop."""
    le = np.ascontiguousarray(pts.astype("<u2")).view(np.uint8)
    return [
        tuple(
            int.from_bytes(le[i, c].tobytes(), "little")
            for c in range(cs.ncoords)
        )
        for i in range(pts.shape[0])
    ]


def seal_shares_batch(
    group: gh.HostGroup,
    cfg,
    shares: np.ndarray,  # (n_dealers, n_recipients, L) scalar limbs
    hidings: np.ndarray,
    c1: np.ndarray,  # (n_dealers, n_recipients, C, L) from kem_batch
    kem: np.ndarray,
) -> list[list[tuple[HybridCiphertext, HybridCiphertext]]]:
    """Array-shaped :func:`seal_shares`: same sealed pairs, bit-identical
    ciphertext and e1 wire bytes, computed by batch entry points —
    ``gd.encode_batch`` (one Montgomery-trick inversion + one transfer
    for every KEM point), ``crypto.blake2.kdf_batch`` (one u64 Blake2b
    compression batch per tag) and ``crypto.chacha.chacha20_xor_batch``
    (every sealed scalar fits one keystream block, so the whole round is
    a single (2·n², 16)-u32 state batch).

    The returned ``e1`` tuples are the same projective tuples the scalar
    leg emits (``gd.to_host`` of the KEM kernel output) — only the KEM
    points need canonicalisation (their *encoding* keys the KDF), so the
    e1 leg skips the inversion entirely.
    """
    from ..crypto.blake2 import kdf_batch
    from ..crypto.chacha import chacha20_xor_batch

    cs = cfg.cs
    fs = cs.scalar
    n_d, n_r = shares.shape[:2]
    n_pairs = n_d * n_r
    shape = (n_pairs, cs.ncoords, cs.field.limbs)
    kem_enc = gd.encode_batch(cs, kem).reshape(n_pairs, -1)
    e1s = _host_points(cs, np.asarray(c1).reshape(shape))
    msg_s = _le_bytes(shares.reshape(n_pairs, -1), fs.nbytes)
    msg_h = _le_bytes(hidings.reshape(n_pairs, -1), fs.nbytes)
    k1, nonce1 = kdf_batch(kem_enc, PERSON_SHARE)
    k2, nonce2 = kdf_batch(kem_enc, PERSON_RAND)
    ct_s = chacha20_xor_batch(k1, nonce1, msg_s)
    ct_h = chacha20_xor_batch(k2, nonce2, msg_h)
    out = []
    for d in range(n_d):
        row = []
        for i in range(n_r):
            j = d * n_r + i
            row.append(
                (
                    HybridCiphertext(e1s[j], ct_s[j].tobytes()),
                    HybridCiphertext(e1s[j], ct_h[j].tobytes()),
                )
            )
        out.append(row)
    return out


def seal_shares_pipeline(
    group: gh.HostGroup,
    cfg,
    shares,  # (n_dealers, n_recipients, L) limbs, device or host
    hidings,
    pks_dev: jnp.ndarray,
    r_enc: jnp.ndarray,  # (n_dealers, n_recipients, L) encryption randomness
    g_table: jnp.ndarray,
    chunk: int | None = None,
) -> list[list[tuple[HybridCiphertext, HybridCiphertext]]]:
    """KEM + DEM for a whole dealing round, chunked over dealers so the
    host DEM of chunk k overlaps the device dispatch of chunk k+1 (JAX
    dispatch is asynchronous; the DEM's single transfer per chunk is
    what blocks, and only on its own chunk's kernels).

    ``DKG_TPU_DEM_CHUNK`` pins dealers per chunk (0 disables chunking);
    the default targets ~4096 pairs per chunk.  The DEM leg follows
    ``DKG_TPU_DEM`` (:func:`dem_mode`).  Output is bit-identical to an
    unchunked ``kem_batch`` + seal: chunks are independent dealer rows.
    """
    from ..utils import envknobs

    n_d, n_r = r_enc.shape[0], r_enc.shape[1]
    if chunk is None:
        chunk = envknobs.nonneg_int(
            "DKG_TPU_DEM_CHUNK", "dealers per DEM chunk; 0 disables chunking"
        )
        if chunk is None:
            chunk = max(1, 4096 // max(1, n_r))
    seal = seal_shares if dem_mode() == "scalar" else seal_shares_batch
    shares = np.asarray(shares)
    hidings = np.asarray(hidings)
    if not chunk or chunk >= n_d:
        c1, kem = kem_batch(cfg, pks_dev, r_enc, g_table)
        return seal(group, cfg, shares, hidings, np.asarray(c1), np.asarray(kem))
    spans = [(a, min(a + chunk, n_d)) for a in range(0, n_d, chunk)]
    nxt = kem_batch(cfg, pks_dev, r_enc[spans[0][0] : spans[0][1]], g_table)
    out: list[list[tuple[HybridCiphertext, HybridCiphertext]]] = []
    for k, (a, b) in enumerate(spans):
        cur = nxt
        # dispatch chunk k+1 BEFORE blocking on chunk k's transfer
        nxt = (
            kem_batch(
                cfg, pks_dev, r_enc[spans[k + 1][0] : spans[k + 1][1]], g_table
            )
            if k + 1 < len(spans)
            else None
        )
        out.extend(
            seal(
                group, cfg, shares[a:b], hidings[a:b],
                np.asarray(cur[0]), np.asarray(cur[1]),
            )
        )
    return out


def _mesh_slabs(x, spans):
    """Per-shard views of a (possibly mesh-sharded) dealer-major array.

    When ``x`` is a jax array actually sharded over the dealer axis the
    slabs are its resident per-device blocks (``addressable_shards``,
    ordered by global offset) — fetching one never materialises the
    whole array on the host.  Host arrays and replicated/single-device
    layouts fall back to plain slices, so the pipeline below works
    unchanged in unsharded tests.
    """
    import jax as _jax

    per = list(getattr(x, "addressable_shards", ()) or ())
    if isinstance(x, _jax.Array) and len(per) == len(spans):
        per.sort(key=lambda sh: sh.index[0].start or 0)
        starts = [sh.index[0].start or 0 for sh in per]
        if starts == [a for a, _b in spans]:
            return [sh.data for sh in per]
    return [x[a:b] for a, b in spans]


def seal_shares_mesh(
    group: gh.HostGroup,
    cfg,
    mesh,
    shares,  # (n_dealers, n_recipients, L) limbs, mesh-sharded or host
    hidings,
    pks_dev: jnp.ndarray,
    r_enc,  # (n_dealers, n_recipients, L) encryption randomness (host)
    g_table: jnp.ndarray,
    chunk: int | None = None,
) -> list[list[tuple[HybridCiphertext, HybridCiphertext]]]:
    """:func:`seal_shares_pipeline`'s chunk overlap lifted to mesh
    shards: the dealer axis is walked shard block by shard block, so

    * the host only ever materialises ONE shard's (n/ndev, n, L) share
      slab at a time — peak host bytes are O(n^2/ndev), not O(n^2),
      which is what keeps the n=16384 dealing round inside a host
      (scripts/memproof_stream.py records the bound);
    * shard k+1's device->host transfer (``copy_to_host_async``) runs
      under shard k's host DEM, and within a shard the per-chunk
      KEM-dispatch-ahead pipeline runs unchanged.

    Shard blocks are independent dealer rows, so output is bit-identical
    to one ``seal_shares_pipeline`` over the whole round (pinned by
    tests/test_hybrid_batch.py).
    """
    n_dev = int(mesh.devices.size)
    n_d = r_enc.shape[0]
    if n_d % n_dev != 0:
        raise ValueError("dealer count must divide evenly over the mesh")
    block = n_d // n_dev
    spans = [(k * block, (k + 1) * block) for k in range(n_dev)]
    slabs_s = _mesh_slabs(shares, spans)
    slabs_h = _mesh_slabs(hidings, spans)
    for t in (slabs_s[0], slabs_h[0]):
        if hasattr(t, "copy_to_host_async"):
            t.copy_to_host_async()
    out: list[list[tuple[HybridCiphertext, HybridCiphertext]]] = []
    for k, (a, b) in enumerate(spans):
        if k + 1 < n_dev:
            # start shard k+1's transfer BEFORE shard k's DEM blocks
            for t in (slabs_s[k + 1], slabs_h[k + 1]):
                if hasattr(t, "copy_to_host_async"):
                    t.copy_to_host_async()
        out.extend(
            seal_shares_pipeline(
                group, cfg,
                np.asarray(slabs_s[k]), np.asarray(slabs_h[k]),
                pks_dev, r_enc[a:b], g_table, chunk=chunk,
            )
        )
    return out


def open_share(
    group: gh.HostGroup,
    sk: int,
    pair: tuple[HybridCiphertext, HybridCiphertext],
) -> tuple[int | None, int | None]:
    """Recipient-side decryption of a sealed (share, hiding) pair."""
    xor = _chacha()
    fs = group.scalar_field
    share_ct, hiding_ct = pair
    kem_bytes = group.encode(group.scalar_mul(sk, share_ct.e1))
    out = []
    for tag, ct in ((PERSON_SHARE, share_ct), (PERSON_RAND, hiding_ct)):
        key, nonce = keystream_from_kem_bytes(kem_bytes, tag)
        pt = xor(key, nonce, ct.ciphertext)
        v = int.from_bytes(pt, "little") if len(pt) == fs.nbytes else None
        out.append(v if v is None or v < fs.modulus else None)
    return out[0], out[1]


def open_shares_batch(
    group: gh.HostGroup,
    cfg,
    sk: int,
    pairs: list[tuple[HybridCiphertext, HybridCiphertext]],
) -> list[tuple[int | None, int | None]]:
    """Recipient-side :func:`open_share` for all dealers' pairs at once:
    the KEM recoveries ``sk·e1`` run as ONE batched device scalar-mult,
    point compression as one ``gd.encode_batch``, and the KDF/ChaCha
    tail as one batch per tag.  Element semantics match
    :func:`open_share` exactly (shared-KEM pair layout: ``share_ct.e1``
    keys both tags; wrong-length or out-of-range payloads -> None).
    """
    from ..crypto.blake2 import kdf_batch
    from ..crypto.chacha import chacha20_xor_batch

    cs = cfg.cs
    fs = group.scalar_field
    n = len(pairs)
    if n == 0:
        return []
    sk_limbs = jnp.asarray(fh.encode(fs, [sk] * n))
    kem_dev = gd.scalar_mul(
        cs, sk_limbs, gd.from_host(cs, [p[0].e1 for p in pairs])
    )
    kem_enc = gd.encode_batch(cs, np.asarray(kem_dev))
    vals: list[list[int | None]] = [[None, None] for _ in range(n)]
    for col, tag in ((0, PERSON_SHARE), (1, PERSON_RAND)):
        cts = [p[col].ciphertext for p in pairs]
        rows = [i for i, ct in enumerate(cts) if len(ct) == fs.nbytes]
        if not rows:
            continue
        data = np.frombuffer(
            b"".join(cts[i] for i in rows), dtype=np.uint8
        ).reshape(len(rows), fs.nbytes)
        key, nonce = kdf_batch(kem_enc[rows], tag)
        pt = chacha20_xor_batch(key, nonce, data)
        for r, i in enumerate(rows):
            v = int.from_bytes(pt[r].tobytes(), "little")
            vals[i][col] = v if v < fs.modulus else None
    return [(a, b) for a, b in vals]


def broadcasts_from_batch(
    group: gh.HostGroup,
    cfg,
    randomized: np.ndarray,  # (n_dealers, t+1, C, L)
    sealed: list[list[tuple[HybridCiphertext, HybridCiphertext]]],
) -> list[BroadcastPhase1]:
    """Package device-dealt commitments + sealed shares as wire-format
    BroadcastPhase1 messages, one per dealer."""
    cs = cfg.cs
    out = []
    for d, row in enumerate(sealed):
        coeffs = tuple(gd.to_host(cs, randomized[d]))
        enc = tuple(
            EncryptedShares(i + 1, share_ct, hiding_ct)
            for i, (share_ct, hiding_ct) in enumerate(row)
        )
        out.append(BroadcastPhase1(coeffs, enc))
    return out
