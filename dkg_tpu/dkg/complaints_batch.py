"""Batched adjudication of round-2 complaint storms.

The host state machine verifies complaints one at a time
(committee.DkgPhase2.proceed -> MisbehavingPartiesRound1.verify;
reference: committee.rs:369-398 -> broadcast.rs:50-98): per complaint
that is 2 DLEQ verifications (8 scalar mults) plus a Pedersen/MSM share
re-check.  Under a storm of k complaints (the adversarial worst case the
threshold bound t admits), the serial path does O(k) ladder calls; here
the DLEQ legs of ALL complaints run as one batched device call
(crypto.dleq_batch.verify_batch) and the share re-checks as one more,
with only Blake2b transcript hashing and bookkeeping left on host.

Semantics match the serial path exactly — tests assert equality of the
upheld/rejected verdicts per complaint.

Measured reality (STORM.json): the batch court wins only when ladders
run wide on an accelerator; on a 1-core CPU backend the serial host
court (native C++ ladder) is ~25x faster.  Callers should therefore go
through :func:`adjudicate_round1`, which routes by active backend.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..crypto.commitment import CommitmentKey
from ..crypto import dleq_batch
from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from ..groups import precompute
from .broadcast import BroadcastPhase1, MisbehavingPartiesRound1
from .procedure_keys import MemberCommunicationPublicKey


def check_randomized_shares_batch(
    group: gh.HostGroup,
    cs,
    ck: CommitmentKey,
    indices: list[int],
    shares: list[int],
    rands: list[int],
    coeffs_list: list[tuple],
) -> np.ndarray:
    """Batched g*s + h*s' == sum_l idx^l E_l over k independent checks.

    One fixed-base double-mult batch + one batched point-Horner replaces
    k serial MSMs (the check at reference committee.rs:292-296 / its
    re-run inside broadcast.rs:50-98).
    """
    if not indices:
        return np.zeros((0,), dtype=bool)
    fs = group.scalar_field
    k = len(indices)
    tp1 = len(coeffs_list[0])
    s_limbs = jnp.asarray(fh.encode(fs, shares))
    r_limbs = jnp.asarray(fh.encode(fs, rands))
    flat_coeffs = [c for coeffs in coeffs_list for c in coeffs]
    cpts = gd.from_host(cs, flat_coeffs).reshape(k, tp1, cs.ncoords, cs.field.limbs)
    idx = jnp.asarray(indices, dtype=jnp.uint32)
    nbits = max(2, int(max(indices)).bit_length())
    return check_randomized_shares_limbs(
        group, cs, ck, idx, s_limbs, r_limbs, cpts, nbits
    )


def check_randomized_shares_limbs(
    group: gh.HostGroup,
    cs,
    ck: CommitmentKey,
    idx: jnp.ndarray,  # (k,) uint32 recipient indices
    s_limbs: jnp.ndarray,  # (k, L)
    r_limbs: jnp.ndarray,  # (k, L)
    cpts: jnp.ndarray,  # (k, t+1, C, L) dealer commitment points
    nbits: int,
) -> np.ndarray:
    """Device core of the batched check, on pre-encoded limb arrays —
    THE single implementation of g*s + h*s' == sum_l idx^l E_l shared by
    complaint adjudication and the batched round-2
    (committee_batch.batched_share_verification)."""
    g_tab = precompute.generator_table(cs)
    h_tab = precompute.base_table(cs, ck.h)
    lhs = gd.add(
        cs,
        gd.fixed_base_mul(cs, g_tab, s_limbs),
        gd.fixed_base_mul(cs, h_tab, r_limbs),
    )
    rhs = gd.eval_point_poly(cs, cpts, idx, nbits)
    return np.asarray(gd.eq(cs, lhs, rhs))


def adjudicate_round1_serial(
    group: gh.HostGroup,
    ck: CommitmentKey,
    fetched_complaints: list[tuple[int, MemberCommunicationPublicKey, MisbehavingPartiesRound1]],
    round1_by_sender: dict[int, BroadcastPhase1 | None],
) -> list[bool]:
    """Serial host court: one ``MisbehavingPartiesRound1.verify`` per
    complaint, the reference's own loop (broadcast.rs:50-98,
    committee.rs:369-398), riding the native C++ ladder when built.

    Verdict semantics identical to :func:`adjudicate_round1_batch`
    (tests assert equality); exists because on CPU backends the serial
    court is the FASTER one — see :func:`adjudicate_round1`.
    """
    verdicts = []
    for accuser_idx, accuser_pk, m in fetched_complaints:
        b = round1_by_sender.get(m.accused_index)
        if b is None:
            verdicts.append(False)  # accused never dealt: nothing to uphold
            continue
        verdicts.append(m.verify(group, ck, accuser_idx, accuser_pk, b))
    return verdicts


def adjudicate_round1(
    group: gh.HostGroup,
    cs,
    ck: CommitmentKey,
    fetched_complaints: list[tuple[int, MemberCommunicationPublicKey, MisbehavingPartiesRound1]],
    round1_by_sender: dict[int, BroadcastPhase1 | None],
    timings: dict | None = None,
) -> list[bool]:
    """Backend-aware court dispatch.

    The batched device court only pays when the ladders run wide on an
    accelerator; on a CPU backend the XLA limb arithmetic serialises
    and the host court with the native C++ ladder wins by ~25x at a
    t-sized storm (STORM.json, n=256 t=85: 37.75/s serial host vs 1.5/s
    batched XLA:CPU).  Verdicts are identical either way (tested), so
    route by the active backend.

    On the serial route ``timings`` gains a single ``serial_s`` entry
    (the per-stage dleq/decrypt/recheck split only exists in the batch
    court).
    """
    import time as _time

    import jax

    if jax.default_backend() == "cpu":
        _t = _time.perf_counter()
        out = adjudicate_round1_serial(group, ck, fetched_complaints, round1_by_sender)
        if timings is not None:
            timings["serial_s"] = _time.perf_counter() - _t
        return out
    return adjudicate_round1_batch(
        group, cs, ck, fetched_complaints, round1_by_sender, timings=timings
    )


def adjudicate_round1_batch(
    group: gh.HostGroup,
    cs,
    ck: CommitmentKey,
    fetched_complaints: list[tuple[int, MemberCommunicationPublicKey, MisbehavingPartiesRound1]],
    round1_by_sender: dict[int, BroadcastPhase1 | None],
    timings: dict | None = None,
) -> list[bool]:
    """Adjudicate (accuser_index, accuser_pk, complaint) triples at once.

    Returns one upheld/rejected verdict per triple, equal to running
    ``MisbehavingPartiesRound1.verify`` serially (broadcast.rs:50-98):
    a complaint is upheld iff both disclosed-KEM-key proofs verify AND
    the re-decrypted pair is undecodable or fails the commitment check.

    ``timings``, if given, gains per-stage wall-clock seconds
    (``dleq_s`` batched proof verify, ``decrypt_s`` host KEM/DEM
    re-decryption, ``recheck_s`` batched commitment re-check) so the
    storm bench can attribute where adjudication time goes.
    """
    import time as _time

    k = len(fetched_complaints)
    verdicts = [False] * k
    # stage 1: gather DLEQ statements for complaints whose target dealt
    dleq_stmts, dleq_proofs, owner = [], [], []
    located = {}
    for i, (accuser_idx, accuser_pk, m) in enumerate(fetched_complaints):
        b = round1_by_sender.get(m.accused_index)
        shares = b.shares_for(accuser_idx) if b is not None else None
        if shares is None:
            continue  # accused never dealt to the accuser: reject here
        located[i] = shares
        gpt = group.generator()
        dleq_stmts.append((gpt, shares.share_ct.e1, accuser_pk.point, m.proof.symm_key_share.point))
        dleq_proofs.append(m.proof.proof_share.proof)
        owner.append(i)
        dleq_stmts.append((gpt, shares.randomness_ct.e1, accuser_pk.point, m.proof.symm_key_rand.point))
        dleq_proofs.append(m.proof.proof_rand.proof)
        owner.append(i)
    _t = _time.perf_counter()
    ok = dleq_batch.verify_batch(group, cs, dleq_proofs, dleq_stmts)
    if timings is not None:
        timings["dleq_s"] = _time.perf_counter() - _t
    proof_ok = {i: True for i in located}
    for j, i in enumerate(owner):
        proof_ok[i] = proof_ok[i] and bool(ok[j])

    # stage 2: re-decrypt + batched commitment re-check for survivors
    _t = _time.perf_counter()
    recheck = []  # (i, idx, s, r, coeffs)
    for i, shares in located.items():
        if not proof_ok[i]:
            continue
        accuser_idx, _, m = fetched_complaints[i]
        s, r = m.proof.decrypt_scalars(group, shares)
        if s is None or r is None:
            verdicts[i] = True  # ScalarOutOfBounds: upheld
            continue
        coeffs = round1_by_sender[m.accused_index].committed_coefficients
        recheck.append((i, accuser_idx, s, r, coeffs))
    if timings is not None:
        timings["decrypt_s"] = _time.perf_counter() - _t
    _t = _time.perf_counter()
    if recheck:
        share_ok = check_randomized_shares_batch(
            group,
            cs,
            ck,
            [x[1] for x in recheck],
            [x[2] for x in recheck],
            [x[3] for x in recheck],
            [x[4] for x in recheck],
        )
        for (i, *_), good in zip(recheck, share_ok):
            verdicts[i] = not bool(good)  # upheld iff the check FAILS
    if timings is not None:
        timings["recheck_s"] = _time.perf_counter() - _t
    return verdicts
