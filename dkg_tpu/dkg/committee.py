"""GJKR-style DKG committee state machine (phases 1-5).

Functional parity with the reference's typestate protocol driver
(reference: src/dkg/committee.rs, the crate's heart): dealing (init,
:124-216), share verification (Phase1::proceed, :260-366), qualified-set
computation + bare commitments (Phase2::proceed, :369-476), commitment
re-verification (Phase3::proceed, :508-581), complaint adjudication +
share disclosure (Phase4::proceed, :625-688), and master-key assembly
with Lagrange reconstruction (Phase5::finalise, :726-805).

Rust's compile-time typestate becomes runtime phase objects here: each
phase class exposes exactly one ``proceed``/``finalise`` and transitions
return ``(next_phase_or_DkgError, broadcast_or_None)`` — errors are
values, not exceptions, because a failing party may still have complaint
data to publish (reference: src/lib.rs:17-22, committee.rs:340-347).

Deliberate fixes of reference quirks (SURVEY §5, decided not copied):
* quirk 1 — the phase-2 threshold check counts *actually qualified*
  members (the reference compares the constant-length qualified vec,
  committee.rs:443, which can never fire).
* quirk 3 — reconstruction requires >= t+1 disclosed points (degree-t
  polynomial; the reference accepts t, committee.rs:779).
* quirk 5 — ``init`` verifies the caller-supplied index matches the
  sorted-committee position instead of trusting it (committee.rs:123).

The network is the caller's problem, exactly as in the reference: phase
transitions consume ``Fetched*`` views of other parties' broadcasts
(reference: committee.rs:812-1023).  In the TPU-sharded engine the same
seam becomes an ICI allgather (see dkg_tpu.parallel).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..crypto.commitment import CommitmentKey
from ..groups.host import HostGroup
from ..poly.host import Polynomial, lagrange_interpolation
from .broadcast import (
    BroadcastPhase1,
    BroadcastPhase2,
    BroadcastPhase3,
    BroadcastPhase4,
    BroadcastPhase5,
    DisclosedShare,
    EncryptedShares,
    MisbehavingPartiesRound1,
    MisbehavingPartiesRound3,
    ProofOfMisbehaviour,
    check_bare_share,
    check_randomized_share,
)
from .errors import DkgError, DkgErrorKind
from .procedure_keys import (
    MasterPublicKey,
    MemberCommunicationKey,
    MemberCommunicationPublicKey,
    MemberPublicShare,
    MemberSecretShare,
    decrypt_shares_detailed,
    sort_committee,
)


@dataclass(frozen=True)
class Environment:
    """Ceremony parameters (reference: committee.rs:24-28, init :72-82)."""

    group: HostGroup
    threshold: int
    nr_members: int
    commitment_key: CommitmentKey

    @classmethod
    def init(
        cls, group: HostGroup, threshold: int, nr_members: int, shared_string: bytes
    ) -> "Environment":
        if threshold < 1 or nr_members < 1:
            raise ValueError("threshold and committee size must be positive")
        # honest majority: t < (n+1)/2  (reference assert, committee.rs:79)
        if not threshold < (nr_members + 1) / 2:
            raise ValueError("threshold must satisfy t < (n+1)/2")
        return cls(
            group, threshold, nr_members, CommitmentKey.generate(group, shared_string)
        )


# ---------------------------------------------------------------------------
# fetched-broadcast views (reference: committee.rs:812-1023)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FetchedPhase1:
    """One counterparty's round-1 message; ``None`` payload == missing or
    malformed == silent disqualification (reference: committee.rs:825-871,
    shape checks :844-853)."""

    sender_index: int
    broadcast: Optional[BroadcastPhase1]

    @classmethod
    def from_broadcast(
        cls, env: Environment, sender_index: int, b: Optional[BroadcastPhase1]
    ) -> "FetchedPhase1":
        if b is not None and (
            len(b.committed_coefficients) != env.threshold + 1
            or len(b.encrypted_shares) != env.nr_members
        ):
            b = None
        return cls(sender_index, b)


@dataclass(frozen=True)
class FetchedComplaints2:
    """(reference: committee.rs:886-908)"""

    accuser_index: int
    broadcast: Optional[BroadcastPhase2]


@dataclass(frozen=True)
class FetchedPhase3:
    """(reference: committee.rs:921-961, shape check :940-946)"""

    sender_index: int
    broadcast: Optional[BroadcastPhase3]

    @classmethod
    def from_broadcast(
        cls, env: Environment, sender_index: int, b: Optional[BroadcastPhase3]
    ) -> "FetchedPhase3":
        if b is not None and len(b.committed_coefficients) != env.threshold + 1:
            b = None
        return cls(sender_index, b)


@dataclass(frozen=True)
class FetchedComplaints4:
    """(reference: committee.rs:1027-1066)"""

    accuser_index: int
    broadcast: Optional[BroadcastPhase4]


@dataclass(frozen=True)
class FetchedPhase5:
    """(reference: committee.rs:1001-1023)"""

    sender_index: int
    broadcast: Optional[BroadcastPhase5]


class _State:
    """Mutable per-party protocol state (reference IndividualState,
    committee.rs:32-45)."""

    def __init__(
        self,
        env: Environment,
        index: int,
        comm_key: MemberCommunicationKey,
        members_pks: list[MemberCommunicationPublicKey],
    ):
        self.env = env
        self.index = index  # 1-based position in the sorted committee
        self.comm_key = comm_key
        self.members_pks = members_pks
        # own dealing
        self.bare_coeff_points: tuple = ()  # A_l = g*a_l
        self.randomized_coeff_points: tuple = ()  # E_l = g*a_l + h*b_l
        # per-sender data accumulated across rounds (1-based keys)
        self.received_shares: dict[int, tuple[int, int]] = {}
        self.randomized_coeffs: dict[int, tuple] = {}
        self.bare_coeffs: dict[int, tuple] = {}
        self.qualified: list[int] = [1] * env.nr_members
        self.reconstructable: set[int] = set()
        self.phase3_accused: set[int] = set()
        self.final_share: Optional[int] = None
        self.public_share: Optional[tuple] = None

    @property
    def group(self) -> HostGroup:
        return self.env.group

    def qualified_count(self) -> int:
        return sum(self.qualified)

    def disqualify(self, index: int) -> None:
        self.qualified[index - 1] = 0


class DistributedKeyGeneration:
    """Entry point: run round-1 dealing and obtain Phase1
    (reference: committee.rs:124-216)."""

    @staticmethod
    def init(
        env: Environment,
        rng,
        comm_key: MemberCommunicationKey,
        committee_pks: list[MemberCommunicationPublicKey],
        my: int,
    ) -> tuple["DkgPhase1", BroadcastPhase1]:
        group = env.group
        if len(committee_pks) != env.nr_members:
            raise ValueError("committee size does not match environment")
        pks = sort_committee(group, committee_pks)
        # verify (not trust) the claimed index — fix of SURVEY §5 quirk 5
        if not group.eq(pks[my - 1].point, comm_key.public().point):
            raise ValueError("`my` does not match this key's sorted position")

        state = _State(env, my, comm_key, pks)
        t = env.threshold
        fs = group.scalar_field

        sharing = Polynomial.random(fs, t, rng)  # f   (committee.rs:143-146)
        hiding = Polynomial.random(fs, t, rng)  # f'

        # hot loop #1 (committee.rs:151-159): coefficient commitments
        bare, randomized = [], []
        for a_l, b_l in zip(sharing.coeffs, hiding.coeffs):
            apub = group.scalar_mul(a_l, group.generator())
            bare.append(apub)
            randomized.append(group.add(group.scalar_mul(b_l, env.commitment_key.h), apub))
        state.bare_coeff_points = tuple(bare)
        state.randomized_coeff_points = tuple(randomized)
        state.randomized_coeffs[my] = tuple(randomized)
        state.bare_coeffs[my] = tuple(bare)

        # hot loop #2 (committee.rs:163-186): per-recipient eval + encrypt.
        # One KEM exponentiation seals both payloads (elgamal.seal_pair)
        # — the reference performs two (procedure_keys.rs:113-119).
        from ..crypto.elgamal import seal_pair

        encrypted = []
        for i in range(1, env.nr_members + 1):
            s_i = sharing.evaluate(i)
            r_i = hiding.evaluate(i)
            if i == my:
                state.received_shares[my] = (s_i, r_i)
            pk_i = pks[i - 1].point
            share_ct, rand_ct = seal_pair(
                group,
                pk_i,
                group.scalar_to_bytes(s_i),
                group.scalar_to_bytes(r_i),
                rng,
            )
            encrypted.append(EncryptedShares(i, share_ct, rand_ct))

        broadcast = BroadcastPhase1(tuple(randomized), tuple(encrypted))
        return DkgPhase1(state), broadcast


class DkgPhase1:
    """Holds round-1 output; ``proceed`` = round-2 share verification
    (reference: committee.rs:260-366)."""

    def __init__(self, state: _State):
        self._state = state

    def proceed(
        self, fetched: list[FetchedPhase1], rng
    ) -> tuple["DkgPhase2 | DkgError", Optional[BroadcastPhase2]]:
        st = self._state
        group, env = st.group, st.env
        complaints: list[MisbehavingPartiesRound1] = []

        for f in fetched:
            j = f.sender_index
            if j == st.index:
                continue
            if f.broadcast is None:
                st.disqualify(j)  # silent dropout (committee.rs:332-337)
                continue
            mine = f.broadcast.shares_for(st.index)
            if mine is None or mine.recipient_index != st.index:
                # caller handed us data not addressed to us
                return (
                    DkgError(DkgErrorKind.FETCHED_INVALID_DATA, index=j),
                    None,
                )
            (s, r), bad_kind = decrypt_shares_detailed(
                group, st.comm_key, mine.share_ct, mine.randomness_ct
            )
            if s is None or r is None:
                # undecodable scalar -> complaint (committee.rs:318-331);
                # the complaint carries the precise reason: malformed
                # bytes (DECODING_TO_SCALAR_FAILED) vs value >= order
                # (SCALAR_OUT_OF_BOUNDS)
                st.disqualify(j)
                complaints.append(
                    MisbehavingPartiesRound1(
                        j,
                        bad_kind or DkgErrorKind.SCALAR_OUT_OF_BOUNDS,
                        ProofOfMisbehaviour.generate(group, mine, st.comm_key, rng),
                    )
                )
                continue
            coeffs = f.broadcast.committed_coefficients
            if not check_randomized_share(
                group, env.commitment_key, st.index, s, r, coeffs
            ):
                # invalid share -> complaint w/ evidence (committee.rs:305-317)
                st.disqualify(j)
                complaints.append(
                    MisbehavingPartiesRound1(
                        j,
                        DkgErrorKind.SHARE_VALIDITY_FAILED,
                        ProofOfMisbehaviour.generate(group, mine, st.comm_key, rng),
                    )
                )
                continue
            st.received_shares[j] = (s, r)
            st.randomized_coeffs[j] = tuple(coeffs)

        broadcast = BroadcastPhase2(tuple(complaints)) if complaints else None
        if len(complaints) > env.threshold:
            # abort but still publish evidence (committee.rs:340-347)
            return (
                DkgError(DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD),
                broadcast,
            )
        return DkgPhase2(st), broadcast


class DkgPhase2:
    """``proceed`` = round-3: adjudicate round-2 complaints into the
    qualified set, aggregate the final share, publish bare commitments
    (reference: committee.rs:369-476)."""

    def __init__(self, state: _State):
        self._state = state

    def proceed(
        self,
        complaints: list[FetchedComplaints2],
        round1_broadcasts: list[FetchedPhase1],
    ) -> tuple["DkgPhase3 | DkgError", Optional[BroadcastPhase3]]:
        st = self._state
        group, env = st.group, st.env
        by_sender = {f.sender_index: f.broadcast for f in round1_broadcasts}

        # compute_qualified_set (committee.rs:369-398): one upheld
        # complaint disqualifies the accused.
        for fc in complaints:
            if fc.broadcast is None:
                continue
            accuser_pk = st.members_pks[fc.accuser_index - 1]
            for m in fc.broadcast.misbehaving_parties:
                accused_b = by_sender.get(m.accused_index)
                if accused_b is None:
                    # accused never dealt; already disqualified by silence
                    st.disqualify(m.accused_index)
                    continue
                if m.verify(
                    group, env.commitment_key, fc.accuser_index, accuser_pk, accused_b
                ):
                    st.disqualify(m.accused_index)

        # threshold check on the *actual* qualified count — fix of
        # SURVEY §5 quirk 1 (reference's check, committee.rs:443, is dead)
        if st.qualified_count() < env.threshold + 1:
            return DkgError(DkgErrorKind.NOT_ENOUGH_MEMBERS), None

        # final share = sum of qualified dealers' shares (committee.rs:453-467)
        fs_mod = group.scalar_field.modulus
        total = 0
        for j in range(1, env.nr_members + 1):
            if st.qualified[j - 1] and j in st.received_shares:
                total = (total + st.received_shares[j][0]) % fs_mod
        st.final_share = total
        st.public_share = group.scalar_mul(total, group.generator())

        # publish the bare coefficient commitments A_l (committee.rs:447-451)
        return DkgPhase3(st), BroadcastPhase3(st.bare_coeff_points)


class DkgPhase3:
    """``proceed`` = round-4: re-verify shares against the bare
    commitments (reference: committee.rs:508-581)."""

    def __init__(self, state: _State):
        self._state = state

    def proceed(
        self, fetched: list[FetchedPhase3]
    ) -> tuple["DkgPhase4 | DkgError", Optional[BroadcastPhase4]]:
        st = self._state
        group = st.group
        complaints: list[MisbehavingPartiesRound3] = []
        by_sender = {f.sender_index: f.broadcast for f in fetched}

        for j in range(1, st.env.nr_members + 1):
            if j == st.index or not st.qualified[j - 1]:
                continue
            if j not in st.received_shares:
                continue
            s, r = st.received_shares[j]
            b = by_sender.get(j)
            if b is None:
                # qualified party went silent -> disclose their share
                # (committee.rs:541-557; full scenario committee.rs:1316-1516)
                complaints.append(MisbehavingPartiesRound3(j, s, r))
                st.phase3_accused.add(j)
                continue
            coeffs = b.committed_coefficients
            st.bare_coeffs[j] = tuple(coeffs)
            if not check_bare_share(group, st.index, s, coeffs):
                complaints.append(MisbehavingPartiesRound3(j, s, r))
                st.phase3_accused.add(j)

        honest = st.qualified_count() - len(st.phase3_accused)
        if honest < st.env.threshold + 1:
            return (
                DkgError(DkgErrorKind.NOT_ENOUGH_MEMBERS),
                BroadcastPhase4(tuple(complaints)) if complaints else None,
            )
        broadcast = BroadcastPhase4(tuple(complaints)) if complaints else None
        return DkgPhase4(st), broadcast


class DkgPhase4:
    """``proceed`` = round-5: adjudicate round-4 complaints; mark upheld
    accusations for reconstruction and disclose held shares
    (reference: committee.rs:625-688)."""

    def __init__(self, state: _State):
        self._state = state

    def proceed(
        self, complaints: list[FetchedComplaints4]
    ) -> tuple["DkgPhase5 | DkgError", Optional[BroadcastPhase5]]:
        st = self._state
        group, env = st.group, st.env

        for fc in complaints:
            if fc.broadcast is None:
                continue
            for m in fc.broadcast.misbehaving_parties:
                j = m.accused_index
                if not st.qualified[j - 1]:
                    continue
                randomized = st.randomized_coeffs.get(j)
                if randomized is None:
                    continue
                bare = st.bare_coeffs.get(j)
                if m.verify(
                    group,
                    env.commitment_key,
                    fc.accuser_index,
                    randomized,
                    bare,
                ):
                    # two-MSM adjudication (broadcast.rs:111-143): the
                    # accused stays in the final key but their secret is
                    # reconstructed by survivors (committee.rs:662-669)
                    st.reconstructable.add(j)

        st.reconstructable |= st.phase3_accused

        honest = st.qualified_count() - len(st.reconstructable)
        if honest < env.threshold + 1:
            return DkgError(DkgErrorKind.NOT_ENOUGH_MEMBERS), None

        disclosures = tuple(
            DisclosedShare(j, st.index, st.received_shares[j][0])
            for j in sorted(st.reconstructable)
            if j in st.received_shares
        )
        broadcast = BroadcastPhase5(disclosures) if disclosures else None
        return DkgPhase5(st), broadcast


class DkgPhase5:
    """``finalise`` = master-key assembly with Lagrange reconstruction of
    reconstructable parties' secrets (reference: committee.rs:726-805)."""

    def __init__(self, state: _State):
        self._state = state

    def finalise(
        self, fetched: list[FetchedPhase5]
    ) -> tuple[tuple[MasterPublicKey, MemberSecretShare] | DkgError, None]:
        st = self._state
        group, env = st.group, st.env
        fs = group.scalar_field

        # gather disclosed shares: accused -> {holder_index: share}
        points: dict[int, dict[int, int]] = {j: {} for j in st.reconstructable}
        for j in st.reconstructable:
            if j in st.received_shares:
                points[j][st.index] = st.received_shares[j][0]
        for f in fetched:
            if f.broadcast is None:
                continue
            for d in f.broadcast.disclosed_shares:
                if d.accused_index in points:
                    points[d.accused_index][d.holder_index] = d.share

        master = group.identity()
        for j in range(1, env.nr_members + 1):
            if not st.qualified[j - 1]:
                continue
            if j in st.reconstructable:
                xs = sorted(points[j])
                ys = [points[j][x] for x in xs]
                # need >= t+1 points for a degree-t polynomial — fix of
                # SURVEY §5 quirk 3 (reference requires only t, :779)
                if len(xs) < env.threshold + 1:
                    return (
                        DkgError(
                            DkgErrorKind.INSUFFICIENT_SHARES_FOR_RECOVERY, index=j
                        ),
                        None,
                    )
                recovered = lagrange_interpolation(fs, 0, ys, xs)
                master = group.add(
                    master, group.scalar_mul(recovered, group.generator())
                )
            else:
                coeffs = st.bare_coeffs.get(j)
                if coeffs is None:
                    return (
                        DkgError(DkgErrorKind.NOT_ENOUGH_MEMBERS, index=j),
                        None,
                    )
                # master += A_{j,0} = g*a_{j,0} (committee.rs:791-796)
                master = group.add(master, coeffs[0])

        assert st.final_share is not None
        return (MasterPublicKey(master), MemberSecretShare(st.final_share)), None

    # convenience accessors (reference exposes these on the state)
    @property
    def public_share(self) -> MemberPublicShare:
        return MemberPublicShare(self._state.public_share)

    @property
    def qualified_set(self) -> list[int]:
        return list(self._state.qualified)
