"""The five key roles of the DKG procedure + canonical committee order.

Functional parity with the reference (reference:
src/dkg/procedure_keys.rs): `MemberSecretShare` (:10),
`MemberPublicShare` (:14), `MemberCommunicationKey` (:19),
`MemberCommunicationPublicKey` (:24), `MasterPublicKey` (:50),
byte-lexicographic ordering of communication public keys (:26-46),
share decryption (:88-103), and master-key assembly (:121-129).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..crypto.elgamal import (
    HybridCiphertext,
    Keypair,
    open_pair,
)
from ..groups.host import HostGroup


@dataclass(frozen=True)
class MemberSecretShare:
    """The party's final secret share x_i (reference: procedure_keys.rs:10)."""

    value: int


@dataclass(frozen=True)
class MemberPublicShare:
    """g * x_i (reference: procedure_keys.rs:14)."""

    point: tuple


@dataclass(frozen=True)
class MemberCommunicationKey:
    """Long-term communication keypair used for share delivery
    (reference: procedure_keys.rs:19-22)."""

    keypair: Keypair

    @classmethod
    def generate(cls, group: HostGroup, rng) -> "MemberCommunicationKey":
        return cls(Keypair.generate(group, rng))

    @property
    def sk(self) -> int:
        return self.keypair.sk

    def public(self) -> "MemberCommunicationPublicKey":
        return MemberCommunicationPublicKey(self.keypair.pk)


@dataclass(frozen=True)
class MemberCommunicationPublicKey:
    point: tuple

    def sort_key(self, group: HostGroup) -> bytes:
        """Canonical committee order = byte-lexicographic on the encoded
        pk (reference: procedure_keys.rs:26-46)."""
        return group.encode(self.point)


def sort_committee(
    group: HostGroup, pks: list[MemberCommunicationPublicKey]
) -> list[MemberCommunicationPublicKey]:
    """Sorted committee; all parties derive identical indexing
    (reference: committee.rs:134-135)."""
    return sorted(pks, key=lambda k: k.sort_key(group))


def decrypt_shares(
    group: HostGroup,
    sk: MemberCommunicationKey,
    share_ct: HybridCiphertext,
    randomness_ct: HybridCiphertext,
) -> tuple[Optional[int], Optional[int]]:
    """Decrypt the (share, commitment-randomness) pair addressed to us;
    ``None`` entries signal non-canonical scalars (reference:
    procedure_keys.rs:88-103 -> ScalarOutOfBounds handling
    committee.rs:318-331)."""
    (s, r), _ = decrypt_shares_detailed(group, sk, share_ct, randomness_ct)
    return s, r


def decrypt_shares_detailed(
    group: HostGroup,
    sk: MemberCommunicationKey,
    share_ct: HybridCiphertext,
    randomness_ct: HybridCiphertext,
):
    """Like :func:`decrypt_shares` but also reports WHY a value failed:
    DECODING_TO_SCALAR_FAILED for a malformed byte string (reference:
    errors.rs:32-35, broadcast.rs:260-267) vs SCALAR_OUT_OF_BOUNDS for
    well-formed bytes encoding a value >= the group order (reference:
    errors.rs:15-18).  Returns ((s|None, r|None), kind|None)."""
    pt1, pt2 = open_pair(group, sk.sk, share_ct, randomness_ct)
    return decode_scalar_pair(group, pt1, pt2)


def decode_scalar_pair(group: HostGroup, pt1: bytes, pt2: bytes):
    """Byte->scalar decoding + failure classification shared by the
    serial and batched decryption paths.  Returns
    ((s|None, r|None), kind|None)."""
    from .errors import DkgErrorKind

    fs = group.scalar_field
    kind = None
    out = []
    for pt in (pt1, pt2):
        if len(pt) != fs.nbytes:
            out.append(None)
            kind = kind or DkgErrorKind.DECODING_TO_SCALAR_FAILED
            continue
        v = int.from_bytes(pt, "little")
        if v >= fs.modulus:
            out.append(None)
            kind = kind or DkgErrorKind.SCALAR_OUT_OF_BOUNDS
            continue
        out.append(v)
    return (out[0], out[1]), kind


@dataclass(frozen=True)
class MasterPublicKey:
    """The ceremony output: sum of qualified parties' public shares
    (reference: procedure_keys.rs:50, :121-129)."""

    point: tuple

    @classmethod
    def from_shares(cls, group: HostGroup, shares: list) -> "MasterPublicKey":
        acc = group.identity()
        for p in shares:
            acc = group.add(acc, p.point if isinstance(p, MemberPublicShare) else p)
        return cls(acc)

    def check_consistent(self, group: HostGroup, others: list):
        """Cross-check this master key against other parties' finalise
        outputs; returns a DkgError(INCONSISTENT_MASTER_KEY) on mismatch,
        None when consistent.  The caller-side check the reference's
        walkthrough performs after finalise (reference: lib.rs:172-177,
        committee.rs:1631-1635; error errors.rs:44-47)."""
        from .errors import DkgError, DkgErrorKind

        for i, other in enumerate(others):
            pt = other.point if isinstance(other, MasterPublicKey) else other
            if not group.eq(self.point, pt):
                return DkgError(DkgErrorKind.INCONSISTENT_MASTER_KEY, index=i)
        return None

    def check_reproduced_by(self, group: HostGroup, scalar: int):
        """Cross-check that g*scalar reproduces this master key (the
        interpolated-secret oracle, reference: committee.rs:1503-1515);
        DkgError(INCONSISTENT_MASTER_KEY) on mismatch, None when it
        matches."""
        from .errors import DkgError, DkgErrorKind

        if not group.eq(self.point, group.scalar_mul(scalar, group.generator())):
            return DkgError(DkgErrorKind.INCONSISTENT_MASTER_KEY)
        return None
