"""The five key roles of the DKG procedure + canonical committee order.

Functional parity with the reference (reference:
src/dkg/procedure_keys.rs): `MemberSecretShare` (:10),
`MemberPublicShare` (:14), `MemberCommunicationKey` (:19),
`MemberCommunicationPublicKey` (:24), `MasterPublicKey` (:50),
byte-lexicographic ordering of communication public keys (:26-46),
share decryption (:88-103), and master-key assembly (:121-129).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..crypto.elgamal import (
    HybridCiphertext,
    Keypair,
    open_pair,
)
from ..groups.host import HostGroup


@dataclass(frozen=True)
class MemberSecretShare:
    """The party's final secret share x_i (reference: procedure_keys.rs:10)."""

    value: int


@dataclass(frozen=True)
class MemberPublicShare:
    """g * x_i (reference: procedure_keys.rs:14)."""

    point: tuple


@dataclass(frozen=True)
class MemberCommunicationKey:
    """Long-term communication keypair used for share delivery
    (reference: procedure_keys.rs:19-22)."""

    keypair: Keypair

    @classmethod
    def generate(cls, group: HostGroup, rng) -> "MemberCommunicationKey":
        return cls(Keypair.generate(group, rng))

    @property
    def sk(self) -> int:
        return self.keypair.sk

    def public(self) -> "MemberCommunicationPublicKey":
        return MemberCommunicationPublicKey(self.keypair.pk)


@dataclass(frozen=True)
class MemberCommunicationPublicKey:
    point: tuple

    def sort_key(self, group: HostGroup) -> bytes:
        """Canonical committee order = byte-lexicographic on the encoded
        pk (reference: procedure_keys.rs:26-46)."""
        return group.encode(self.point)


def sort_committee(
    group: HostGroup, pks: list[MemberCommunicationPublicKey]
) -> list[MemberCommunicationPublicKey]:
    """Sorted committee; all parties derive identical indexing
    (reference: committee.rs:134-135)."""
    return sorted(pks, key=lambda k: k.sort_key(group))


def decrypt_shares(
    group: HostGroup,
    sk: MemberCommunicationKey,
    share_ct: HybridCiphertext,
    randomness_ct: HybridCiphertext,
) -> tuple[Optional[int], Optional[int]]:
    """Decrypt the (share, commitment-randomness) pair addressed to us;
    ``None`` entries signal non-canonical scalars (reference:
    procedure_keys.rs:88-103 -> ScalarOutOfBounds handling
    committee.rs:318-331)."""
    fs = group.scalar_field
    pt1, pt2 = open_pair(group, sk.sk, share_ct, randomness_ct)
    s = int.from_bytes(pt1, "little") if len(pt1) == fs.nbytes else None
    r = int.from_bytes(pt2, "little") if len(pt2) == fs.nbytes else None
    if s is not None and s >= fs.modulus:
        s = None
    if r is not None and r >= fs.modulus:
        r = None
    return s, r


@dataclass(frozen=True)
class MasterPublicKey:
    """The ceremony output: sum of qualified parties' public shares
    (reference: procedure_keys.rs:50, :121-129)."""

    point: tuple

    @classmethod
    def from_shares(cls, group: HostGroup, shares: list) -> "MasterPublicKey":
        acc = group.identity()
        for p in shares:
            acc = group.add(acc, p.point if isinstance(p, MemberPublicShare) else p)
        return cls(acc)
