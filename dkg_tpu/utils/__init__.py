"""Cross-cutting utilities: serialization/checkpointing, tracing."""

from . import serde, tracing  # noqa: F401
