"""Cross-cutting utilities: serialization/checkpointing, tracing,
metrics, and the flight-recorder event log."""

from . import metrics, obslog, serde, tracing  # noqa: F401
