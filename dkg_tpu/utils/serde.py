"""Deterministic binary serialization: wire messages + phase snapshots.

The reference has byte codecs only at the scalar/point level
(reference: traits.rs:162-164, :230-232) and no message or state
serialization at all (no serde anywhere — SURVEY §5 checkpoint/resume).
Real ceremonies are asynchronous: parties go away between rounds.  Here
every broadcast message and the full per-party protocol state are
serializable, so a party can checkpoint after any phase and resume.

Format: fixed-width little-endian integers, length-prefixed byte
strings, fixed-size point/scalar encodings from the group backend.  No
pickle — decoding untrusted bytes must never execute anything.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

from ..crypto.correct_decryption import CorrectHybridDecrKeyZkp
from ..crypto.dleq import DleqZkp
from ..crypto.elgamal import HybridCiphertext, Keypair, SymmetricKey
from ..dkg import broadcast as bc
from ..dkg import committee as cm
from ..dkg.errors import DkgError, DkgErrorKind
from ..dkg.procedure_keys import MemberCommunicationKey, MemberCommunicationPublicKey
from ..groups.host import HostGroup

_ERR_CODES = {k: i for i, k in enumerate(DkgErrorKind)}
_ERR_FROM = {i: k for k, i in _ERR_CODES.items()}

MAGIC = b"DKGT"
VERSION = 1


class Writer:
    def __init__(self, group: HostGroup):
        self.g = group
        self.buf = bytearray()

    def u8(self, v: int):
        self.buf.append(v & 0xFF)

    def u16(self, v: int):
        self.buf += struct.pack("<H", v)

    def u32(self, v: int):
        self.buf += struct.pack("<I", v)

    def raw(self, b: bytes):
        self.buf += b

    def lp(self, b: bytes):
        self.u32(len(b))
        self.raw(b)

    def point(self, p):
        self.raw(self.g.encode(p))

    def scalar(self, s: int):
        self.raw(self.g.scalar_to_bytes(s))

    def bytes(self) -> bytes:
        return bytes(self.buf)


class Reader:
    class Bad(ValueError):
        pass

    def __init__(self, group: HostGroup, data: bytes):
        self.g = group
        self.data = data
        self.pos = 0
        self._point_len = len(group.encode(group.identity()))
        self._scalar_len = group.scalar_field.nbytes

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise Reader.Bad("truncated")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def lp(self) -> bytes:
        return self.take(self.u32())

    def point(self):
        p = self.g.decode(self.take(self._point_len))
        if p is None:
            raise Reader.Bad("invalid point encoding")
        return p

    def scalar(self) -> int:
        s = self.g.scalar_from_bytes(self.take(self._scalar_len))
        if s is None:
            raise Reader.Bad("non-canonical scalar")
        return s

    def done(self):
        if self.pos != len(self.data):
            raise Reader.Bad("trailing bytes")


# ---------------------------------------------------------------------------
# wire-message codecs
# ---------------------------------------------------------------------------


def _w_hybrid(w: Writer, c: HybridCiphertext):
    w.point(c.e1)
    w.lp(c.ciphertext)


def _r_hybrid(r: Reader) -> HybridCiphertext:
    return HybridCiphertext(r.point(), r.lp())


def _w_shares(w: Writer, es: bc.EncryptedShares):
    w.u16(es.recipient_index)
    _w_hybrid(w, es.share_ct)
    _w_hybrid(w, es.randomness_ct)


def _r_shares(r: Reader) -> bc.EncryptedShares:
    return bc.EncryptedShares(r.u16(), _r_hybrid(r), _r_hybrid(r))


def _w_dleq(w: Writer, p: DleqZkp):
    w.scalar(p.challenge)
    w.scalar(p.response)


def _r_dleq(r: Reader) -> DleqZkp:
    return DleqZkp(r.scalar(), r.scalar())


def _w_proof(w: Writer, p: bc.ProofOfMisbehaviour):
    w.point(p.symm_key_share.point)
    w.point(p.symm_key_rand.point)
    _w_dleq(w, p.proof_share.proof)
    _w_dleq(w, p.proof_rand.proof)


def _r_proof(r: Reader) -> bc.ProofOfMisbehaviour:
    return bc.ProofOfMisbehaviour(
        SymmetricKey(r.point()),
        SymmetricKey(r.point()),
        CorrectHybridDecrKeyZkp(_r_dleq(r)),
        CorrectHybridDecrKeyZkp(_r_dleq(r)),
    )


def phase1_wire_bytes(group: HostGroup, n: int, t: int) -> int:
    """Exact encoded size of one fault-free ``BroadcastPhase1`` for
    (group, n, t) — the analytic twin of :func:`encode_phase1`, kept in
    byte-lockstep with it by tests/test_serde.py.  Wire accounting uses
    it where no channel exists (bench.py runs the crypto phases only)
    and to cross-check the counted live path."""
    point = len(group.encode(group.identity()))
    scalar = group.scalar_field.nbytes
    # HybridCiphertext: e1 point + u32-length-prefixed stream ciphertext
    # (ChaCha20: ciphertext length == plaintext scalar length)
    hybrid = point + 4 + scalar
    # u16 coeff count + (t+1) commitment points, then u16 share count +
    # n entries of (u16 recipient + share ct + randomness ct)
    return 2 + (t + 1) * point + 2 + n * (2 + 2 * hybrid)


def phase3_wire_bytes(group: HostGroup, n: int, t: int) -> int:
    """Exact encoded size of one ``BroadcastPhase3`` (the bare
    commitments every qualified dealer reveals): u16 count + (t+1)
    points.  Published by every party in every ceremony, faults or
    not."""
    point = len(group.encode(group.identity()))
    return 2 + (t + 1) * point


def party_wire_bytes(group: HostGroup, n: int, t: int) -> int:
    """Payload bytes ONE party publishes across a fault-free ceremony:
    its phase-1 dealing plus its phase-3 bare commitments; rounds 2, 4,
    and 5 publish empty payloads (no complaints, no disclosures)."""
    return phase1_wire_bytes(group, n, t) + phase3_wire_bytes(group, n, t)


def ceremony_wire_bytes(group: HostGroup, n: int, t: int) -> int:
    """Total payload bytes PUBLISHED across one fault-free ceremony (all
    n parties).  Framing/RPC overhead is excluded — this is the payload
    number ``net.wire_bytes_out`` sums to across the committee, and what
    bench.py/fleet_bench.py report as ``wire_bytes``."""
    return n * party_wire_bytes(group, n, t)


def encode_phase1(group: HostGroup, b: bc.BroadcastPhase1) -> bytes:
    w = Writer(group)
    w.u16(len(b.committed_coefficients))
    for p in b.committed_coefficients:
        w.point(p)
    w.u16(len(b.encrypted_shares))
    for es in b.encrypted_shares:
        _w_shares(w, es)
    return w.bytes()


def decode_phase1(group: HostGroup, data: bytes) -> Optional[bc.BroadcastPhase1]:
    try:
        r = Reader(group, data)
        coeffs = tuple(r.point() for _ in range(r.u16()))
        shares = tuple(_r_shares(r) for _ in range(r.u16()))
        r.done()
        return bc.BroadcastPhase1(coeffs, shares)
    except (ValueError, struct.error):  # Reader.Bad is a ValueError
        return None


def encode_phase2(group: HostGroup, b: bc.BroadcastPhase2) -> bytes:
    w = Writer(group)
    w.u16(len(b.misbehaving_parties))
    for m in b.misbehaving_parties:
        w.u16(m.accused_index)
        w.u8(_ERR_CODES[m.error])
        _w_proof(w, m.proof)
    return w.bytes()


def decode_phase2(group: HostGroup, data: bytes) -> Optional[bc.BroadcastPhase2]:
    try:
        r = Reader(group, data)
        ms = []
        for _ in range(r.u16()):
            idx = r.u16()
            err = _ERR_FROM.get(r.u8())
            if err is None:
                raise Reader.Bad("unknown error code")
            ms.append(bc.MisbehavingPartiesRound1(idx, err, _r_proof(r)))
        r.done()
        return bc.BroadcastPhase2(tuple(ms))
    except (ValueError, struct.error):  # Reader.Bad is a ValueError
        return None


def encode_phase3(group: HostGroup, b: bc.BroadcastPhase3) -> bytes:
    w = Writer(group)
    w.u16(len(b.committed_coefficients))
    for p in b.committed_coefficients:
        w.point(p)
    return w.bytes()


def decode_phase3(group: HostGroup, data: bytes) -> Optional[bc.BroadcastPhase3]:
    try:
        r = Reader(group, data)
        coeffs = tuple(r.point() for _ in range(r.u16()))
        r.done()
        return bc.BroadcastPhase3(coeffs)
    except (ValueError, struct.error):  # Reader.Bad is a ValueError
        return None


def encode_phase4(group: HostGroup, b: bc.BroadcastPhase4) -> bytes:
    w = Writer(group)
    w.u16(len(b.misbehaving_parties))
    for m in b.misbehaving_parties:
        w.u16(m.accused_index)
        w.scalar(m.share)
        w.scalar(m.randomness)
    return w.bytes()


def decode_phase4(group: HostGroup, data: bytes) -> Optional[bc.BroadcastPhase4]:
    try:
        r = Reader(group, data)
        ms = tuple(
            bc.MisbehavingPartiesRound3(r.u16(), r.scalar(), r.scalar())
            for _ in range(r.u16())
        )
        r.done()
        return bc.BroadcastPhase4(ms)
    except (ValueError, struct.error):  # Reader.Bad is a ValueError
        return None


def encode_phase5(group: HostGroup, b: bc.BroadcastPhase5) -> bytes:
    w = Writer(group)
    w.u16(len(b.disclosed_shares))
    for d in b.disclosed_shares:
        w.u16(d.accused_index)
        w.u16(d.holder_index)
        w.scalar(d.share)
    return w.bytes()


def decode_phase5(group: HostGroup, data: bytes) -> Optional[bc.BroadcastPhase5]:
    try:
        r = Reader(group, data)
        ds = tuple(
            bc.DisclosedShare(r.u16(), r.u16(), r.scalar()) for _ in range(r.u16())
        )
        r.done()
        return bc.BroadcastPhase5(ds)
    except (ValueError, struct.error):  # Reader.Bad is a ValueError
        return None


# ---------------------------------------------------------------------------
# phase snapshots (checkpoint / resume)
# ---------------------------------------------------------------------------

_PHASES = {
    "phase1": cm.DkgPhase1,
    "phase2": cm.DkgPhase2,
    "phase3": cm.DkgPhase3,
    "phase4": cm.DkgPhase4,
    "phase5": cm.DkgPhase5,
}
_PHASE_NAMES = {v: k for k, v in _PHASES.items()}


def checkpoint(group: HostGroup, phase) -> bytes:
    """Serialize a phase object (+ its full state) to bytes."""
    st: cm._State = phase._state
    w = Writer(group)
    w.raw(MAGIC)
    w.u8(VERSION)
    name = _PHASE_NAMES[type(phase)].encode()
    w.lp(name)
    w.u16(st.env.threshold)
    w.u16(st.env.nr_members)
    w.point(st.env.commitment_key.h)
    w.u16(st.index)
    w.scalar(st.comm_key.sk)
    for pk in st.members_pks:
        w.point(pk.point)
    w.u16(len(st.bare_coeff_points))
    for p in st.bare_coeff_points:
        w.point(p)
    for p in st.randomized_coeff_points:
        w.point(p)

    def w_coeff_map(m: dict):
        w.u16(len(m))
        for j in sorted(m):
            w.u16(j)
            w.u16(len(m[j]))
            for p in m[j]:
                w.point(p)

    w.u16(len(st.received_shares))
    for j in sorted(st.received_shares):
        w.u16(j)
        s, r = st.received_shares[j]
        w.scalar(s)
        w.scalar(r)
    w_coeff_map(st.randomized_coeffs)
    w_coeff_map(st.bare_coeffs)
    for q in st.qualified:
        w.u8(q)
    for group_set in (st.reconstructable, st.phase3_accused):
        w.u16(len(group_set))
        for j in sorted(group_set):
            w.u16(j)
    has_final = st.final_share is not None
    w.u8(1 if has_final else 0)
    if has_final:
        w.scalar(st.final_share)
    return w.bytes()


def restore(group: HostGroup, data: bytes):
    """Rebuild the phase object from a checkpoint; raises ValueError on
    malformed input."""
    from ..crypto.commitment import CommitmentKey

    r = Reader(group, data)
    if r.take(4) != MAGIC:
        raise ValueError("bad magic")
    if r.u8() != VERSION:
        raise ValueError("unsupported version")
    name = r.lp().decode()
    if name not in _PHASES:
        raise ValueError("unknown phase")
    t = r.u16()
    n = r.u16()
    ck = CommitmentKey(r.point())
    env = cm.Environment(group, t, n, ck)
    index = r.u16()
    sk = r.scalar()
    comm_key = MemberCommunicationKey(Keypair.from_secret(group, sk))
    pks = [MemberCommunicationPublicKey(r.point()) for _ in range(n)]
    st = cm._State(env, index, comm_key, pks)
    ncoeff = r.u16()
    st.bare_coeff_points = tuple(r.point() for _ in range(ncoeff))
    st.randomized_coeff_points = tuple(r.point() for _ in range(ncoeff))

    def r_coeff_map() -> dict:
        out = {}
        for _ in range(r.u16()):
            j = r.u16()
            out[j] = tuple(r.point() for _ in range(r.u16()))
        return out

    st.received_shares = {}
    for _ in range(r.u16()):
        j = r.u16()
        st.received_shares[j] = (r.scalar(), r.scalar())
    st.randomized_coeffs = r_coeff_map()
    st.bare_coeffs = r_coeff_map()
    st.qualified = [r.u8() for _ in range(n)]
    st.reconstructable = {r.u16() for _ in range(r.u16())}
    st.phase3_accused = {r.u16() for _ in range(r.u16())}
    if r.u8():
        st.final_share = r.scalar()
        st.public_share = group.scalar_mul(st.final_share, group.generator())
    r.done()
    return _PHASES[name](st)


# ---------------------------------------------------------------------------
# WAL round records (net.checkpoint — durable crash recovery)
# ---------------------------------------------------------------------------

RECORD_MAGIC = b"DKGR"

# Record kinds: a *state* record snapshots the phase object that drives
# the next round; a *terminal* record pins an error-path publish (e.g.
# complaint evidence broadcast alongside a DkgError) so a crash during
# the drain can never recompute — and equivocate on — committed bytes.
_REC_STATE = 1
_REC_TERMINAL = 2


@dataclass(frozen=True)
class RoundRecord:
    """One replayed WAL record (see net.checkpoint / net.party).

    ``payload`` is the exact wire bytes published for ``round_no``
    (possibly empty).  State records carry ``phase`` (the restored
    DkgPhase* for the next round); terminal records carry ``error`` and
    ``drain_from`` instead.  ``present`` is the sender set observed in
    ``fetch(round_no - 1)`` (None for round 1): re-decoding those same
    mailbox entries is deterministic, so the mask alone reconstructs the
    original decode view even if stragglers landed later.
    """

    round_no: int
    payload: bytes
    phase: object | None
    error: Optional[DkgError]
    drain_from: int
    present: Optional[tuple[int, ...]]
    quarantined_delta: int
    timed_out: bool


def encode_round_record(
    group: HostGroup,
    round_no: int,
    payload: bytes,
    phase=None,
    *,
    error: Optional[DkgError] = None,
    drain_from: int = 0,
    present: Optional[tuple[int, ...]] = None,
    quarantined_delta: int = 0,
    timed_out: bool = False,
) -> bytes:
    """Serialize one WAL round record (exactly one of phase/error set)."""
    if (phase is None) == (error is None):
        raise ValueError("round record needs exactly one of phase or error")
    w = Writer(group)
    w.raw(RECORD_MAGIC)
    w.u8(VERSION)
    w.u8(round_no)
    w.lp(payload)
    if error is None:
        w.u8(_REC_STATE)
        w.lp(checkpoint(group, phase))
    else:
        w.u8(_REC_TERMINAL)
        w.u8(_ERR_CODES[error.kind])
        w.u16(0 if error.index is None else error.index)
        w.u8(1 if error.index is not None else 0)
        w.lp(error.detail.encode())
        w.u8(drain_from)
    w.u8(1 if present is not None else 0)
    if present is not None:
        w.u16(len(present))
        for j in present:
            w.u16(j)
    w.u32(quarantined_delta)
    w.u8(1 if timed_out else 0)
    return w.bytes()


def decode_round_record(group: HostGroup, data: bytes) -> RoundRecord:
    """Rebuild one WAL round record; raises ValueError on malformed
    input (the replay loop in net.party treats that as a torn tail)."""
    r = Reader(group, data)
    if r.take(4) != RECORD_MAGIC:
        raise ValueError("bad record magic")
    if r.u8() != VERSION:
        raise ValueError("unsupported record version")
    round_no = r.u8()
    payload = r.lp()
    kind = r.u8()
    phase = None
    error = None
    drain_from = 0
    if kind == _REC_STATE:
        phase = restore(group, r.lp())
    elif kind == _REC_TERMINAL:
        err_kind = _ERR_FROM.get(r.u8())
        if err_kind is None:
            raise ValueError("unknown error code in terminal record")
        index = r.u16()
        has_index = r.u8()
        detail = r.lp().decode()
        drain_from = r.u8()
        error = DkgError(err_kind, index if has_index else None, detail)
    else:
        raise ValueError("unknown record kind")
    present: Optional[tuple[int, ...]] = None
    if r.u8():
        present = tuple(r.u16() for _ in range(r.u16()))
    quarantined_delta = r.u32()
    timed_out = bool(r.u8())
    r.done()
    return RoundRecord(
        round_no, payload, phase, error, drain_from,
        present, quarantined_delta, timed_out,
    )


# ---------------------------------------------------------------------------
# WAL epoch records (dkg_tpu.epoch — proactive refresh / resharing)
# ---------------------------------------------------------------------------

EPOCH_RECORD_MAGIC = b"DKGE"

# Epoch-op steps (one WAL record per step, written BEFORE the step's
# publish — the same write-ahead contract as round records): 1 = deal,
# 2 = complaints, 3 = confirm.  The step-3 record optionally pins the
# resulting EpochState bytes (absent for leavers, who deal but hold no
# share in the new committee).
EPOCH_STEP_DEAL = 1
EPOCH_STEP_COMPLAINTS = 2
EPOCH_STEP_CONFIRM = 3


@dataclass(frozen=True)
class EpochRecord:
    """One replayed epoch WAL record (see dkg_tpu.epoch.manager).

    ``payload`` is the exact wire bytes published for this step (empty
    for steps the party does not publish, e.g. a joiner's deal step).
    ``present`` is the sender set observed in the PREVIOUS step's fetch
    (None for the deal step) — re-decoding those mailbox entries is
    deterministic, so the mask reconstructs the original view.
    ``state_bytes`` is the serialized EpochState the confirm step
    produced (None otherwise); the epoch layer owns its codec — this
    record treats both byte fields as opaque, which is exactly what
    keeps pre-epoch readers able to SKIP these records by magic alone
    (net.party forward-compatibility).
    """

    op_seq: int
    step: int
    kind: int
    payload: bytes
    present: Optional[tuple[int, ...]]
    state_bytes: Optional[bytes]


def encode_epoch_record(
    group: HostGroup,
    op_seq: int,
    step: int,
    kind: int,
    payload: bytes,
    *,
    present: Optional[tuple[int, ...]] = None,
    state_bytes: Optional[bytes] = None,
) -> bytes:
    """Serialize one epoch WAL record (magic b"DKGE", version-tagged)."""
    w = Writer(group)
    w.raw(EPOCH_RECORD_MAGIC)
    w.u8(VERSION)
    w.u16(op_seq)
    w.u8(step)
    w.u8(kind)
    w.lp(payload)
    w.u8(1 if present is not None else 0)
    if present is not None:
        w.u16(len(present))
        for j in present:
            w.u16(j)
    w.u8(1 if state_bytes is not None else 0)
    if state_bytes is not None:
        w.lp(state_bytes)
    return w.bytes()


def decode_epoch_record(group: HostGroup, data: bytes) -> EpochRecord:
    """Rebuild one epoch WAL record; raises ValueError on malformed
    input (torn tail, same contract as decode_round_record)."""
    r = Reader(group, data)
    if r.take(4) != EPOCH_RECORD_MAGIC:
        raise ValueError("bad epoch record magic")
    if r.u8() != VERSION:
        raise ValueError("unsupported epoch record version")
    op_seq = r.u16()
    step = r.u8()
    kind = r.u8()
    if step not in (EPOCH_STEP_DEAL, EPOCH_STEP_COMPLAINTS, EPOCH_STEP_CONFIRM):
        raise ValueError("unknown epoch record step")
    payload = r.lp()
    present: Optional[tuple[int, ...]] = None
    if r.u8():
        present = tuple(r.u16() for _ in range(r.u16()))
    state_bytes: Optional[bytes] = None
    if r.u8():
        state_bytes = r.lp()
    r.done()
    return EpochRecord(op_seq, step, kind, payload, present, state_bytes)
