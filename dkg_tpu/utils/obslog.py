"""Ceremony flight recorder: structured JSONL events + Chrome trace export.

Every interesting transition in a ceremony — round head/tail, publish,
RPC retry, quarantine, timeout, WAL replay, injected fault — is one
JSON object with monotonic (``mono``) and wall (``ts``) timestamps and
``ceremony_id``/``party``/``round`` identity fields.  Events land in a
bounded in-memory ring (:class:`ObsLog`) and, when the ``DKG_TPU_OBSLOG``
env knob names a directory, in one append-mode JSONL file per party so a
chaos failure can be replayed from its logs alone.

Redaction is structural, not best-effort: the recorder NEVER accepts
share or key material — instrumentation sites only pass lengths, counts,
indices, and error kinds — and as belt-and-braces every ``bytes`` value
reaching :meth:`ObsLog.emit` is replaced by its length before
serialization.  ``tests/test_obslog.py`` greps the emitted bytes of a
live ceremony for the committee's secrets to prove it.

Channel and fault code run deep inside transport internals where no
recorder handle exists; they emit through an *ambient* recorder
(:func:`use` / :func:`emit_current`) that ``run_party`` binds for the
duration of its party thread.  The binding is a
:class:`contextvars.ContextVar`, not a ``threading.local``: threaded
callers see identical behavior (each thread starts from the unbound
default), but an async scheduler multiplexing many ceremonies on ONE
event loop (dkg_tpu.service) gets per-task isolation for free —
``asyncio`` snapshots the context per task, so two interleaved
ceremonies on the same thread cannot cross-contaminate each other's
streams (tests/test_obslog.py interleaves two recorders on one thread
to pin this).

:func:`to_chrome_trace` merges any number of per-party logs into one
Chrome/Perfetto trace-event JSON: one process per ceremony, one thread
per party, ``phase_span`` spans as complete ("X") slices with
``subtimings_s`` nested under them, and point events as instants.
``scripts/trace_viz.py`` is the CLI wrapper.
"""

from __future__ import annotations

import contextvars
import gzip
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Iterable

from . import envknobs

# The ambient recorder binding.  A ContextVar instead of threading.local:
# identical semantics for plain threads (every thread starts unbound),
# but copyable per asyncio task / contextvars.Context, so one scheduler
# thread interleaving several ceremonies keeps their streams separate.
_AMBIENT: contextvars.ContextVar["ObsLog | None"] = contextvars.ContextVar(
    "dkg_tpu_obslog", default=None
)


def _sanitize(value: Any) -> Any:
    """Replace bytes payloads with their length, recursively.  The
    instrumentation contract is lengths-only already; this makes an
    accidental ``payload=raw`` emit a harmless ``"bytes:N"``."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"bytes:{len(value)}"
    if isinstance(value, dict):
        return {str(k): _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


class ObsLog:
    """Bounded ring of structured events with an optional JSONL file sink.

    ``ceremony_id`` and ``party`` bind once at construction and stamp
    every event; ``party`` is an int member index or ``"hub"``.
    """

    def __init__(
        self,
        capacity: int = 4096,
        path: str | os.PathLike | None = None,
        ceremony_id: str | None = None,
        party: int | str | None = None,
    ) -> None:
        self.ceremony_id = ceremony_id
        self.party = party
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._path = os.fspath(path) if path is not None else None
        self._fh = open(self._path, "a", encoding="utf-8") if self._path else None

    # -- recording ----------------------------------------------------------

    def emit(self, kind: str, *, round: int | None = None, **fields) -> dict:
        """Record one event; returns the event dict (tests poke at it)."""
        ev: dict[str, Any] = {
            "ts": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
        }
        if self.ceremony_id is not None:
            ev["ceremony_id"] = self.ceremony_id
        if self.party is not None:
            ev["party"] = self.party
        if round is not None:
            ev["round"] = round
        for k, v in fields.items():
            ev[k] = _sanitize(v)
        with self._lock:
            self._ring.append(ev)
            if self._fh is not None:
                self._fh.write(json.dumps(ev, sort_keys=True) + "\n")
                self._fh.flush()
        return ev

    def emit_span(
        self,
        name: str,
        *,
        ts0: float,
        mono0: float,
        dur_s: float,
        subs: dict[str, float] | None = None,
        **fields,
    ) -> dict:
        """Record a completed span (``phase_span`` feeds these): start
        timestamps, duration, and optional sub-phase seconds that the
        trace export renders as nested slices."""
        span_fields: dict[str, Any] = {
            "name": name,
            "ts0": ts0,
            "mono0": mono0,
            "dur_s": dur_s,
        }
        if subs:
            span_fields["subs"] = {k: float(v) for k, v in subs.items()}
        span_fields.update(fields)
        return self.emit("span", **span_fields)

    # -- access -------------------------------------------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def path(self) -> str | None:
        return self._path

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "ObsLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- ambient (context-local) recorder ----------------------------------------


class _Use:
    """Context manager binding ``log`` as the current context's ambient
    recorder; ``use(None)`` is a no-op binding (events are dropped).
    Bindings nest: exit restores whatever was bound on entry."""

    def __init__(self, log: ObsLog | None) -> None:
        self._log = log
        self._token: contextvars.Token | None = None

    def __enter__(self) -> ObsLog | None:
        self._token = _AMBIENT.set(self._log)
        return self._log

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _AMBIENT.reset(self._token)
            self._token = None


def use(log: ObsLog | None) -> _Use:
    return _Use(log)


def current() -> ObsLog | None:
    """The current context's ambient recorder, or None."""
    return _AMBIENT.get()


def emit_current(kind: str, *, round: int | None = None, **fields) -> dict | None:
    """Emit into the ambient recorder if one is bound; else drop."""
    log = current()
    if log is None:
        return None
    return log.emit(kind, round=round, **fields)


# -- construction helpers ----------------------------------------------------


def ceremony_id_for(env) -> str:
    """Deterministic short id for a ceremony Environment: all parties of
    one ceremony derive the same id from the (group, n, t, commitment
    key) tuple, so their logs merge onto one timeline."""
    import hashlib

    h = hashlib.blake2b(digest_size=6)
    h.update(env.group.name.encode())
    h.update(f":{env.nr_members}:{env.threshold}:".encode())
    h.update(env.group.encode(env.commitment_key.h))
    return h.hexdigest()


def from_env(
    *,
    ceremony_id: str | None = None,
    party: int | str | None = None,
    capacity: int = 4096,
) -> ObsLog | None:
    """An :class:`ObsLog` with a file sink under the ``DKG_TPU_OBSLOG``
    directory, or None when the knob is unset.  File name is
    ``{ceremony_id}-p{party:03d}.jsonl`` (``-hub.jsonl`` for the hub)."""
    root = envknobs.string("DKG_TPU_OBSLOG", "flight-recorder log directory")
    if root is None:
        return None
    os.makedirs(root, exist_ok=True)
    cid = ceremony_id if ceremony_id is not None else "proc"
    tag = f"p{party:03d}" if isinstance(party, int) else str(party or "proc")
    path = os.path.join(root, f"{cid}-{tag}.jsonl")
    return ObsLog(capacity=capacity, path=path, ceremony_id=ceremony_id, party=party)


# -- event schema ------------------------------------------------------------

#: The pinned flight-recorder event schema (docs/observability.md, "Event
#: schema").  Every event carries the base fields ``ts``/``mono``/``kind``
#: (plus ``ceremony_id``/``party``/``round`` when bound); per-kind entries
#: list the REQUIRED payload fields and the OPTIONAL extras.  ``None`` for
#: the optional set marks an open kind (runtimeobs and service events whose
#: payloads vary by probe).  scripts/forensics.py and to_chrome_trace parse
#: exactly this schema — tests/test_obslog.py conformance-checks a live
#: ceremony's stream against it, so an emit-site drift fails loudly.
EVENT_SCHEMA: dict[str, dict[str, tuple | None]] = {
    # ceremony data plane (net.party / net.channel / net.faults)
    "round_head": {"required": ("round",), "optional": ()},
    "publish": {"required": ("round", "bytes", "seq"), "optional": ()},
    "round_tail": {
        "required": (
            "round", "present", "senders", "quarantined_delta", "timed_out",
        ),
        "optional": (),
    },
    "quarantine": {"required": ("round", "peer"), "optional": ()},
    "rpc_retry": {
        "required": ("attempt", "error", "backoff_s", "op"), "optional": (),
    },
    "budget_clamp": {"required": ("where", "timeout_s"), "optional": ("round",)},
    "fault_injected": {
        "required": ("round", "fault", "sender"), "optional": ("seconds",),
    },
    "abort": {"required": ("error", "drain_from"), "optional": ()},
    "party_done": {
        "required": (
            "ok", "quarantined", "timeouts", "retries", "resumes",
            "wal_records", "replayed_rounds",
        ),
        "optional": (),
    },
    # durability (net.checkpoint via net.party)
    "wal_record": {"required": ("round", "bytes", "terminal"), "optional": ()},
    "wal_resume": {"required": ("replayed_rounds",), "optional": ()},
    # epoch data plane (epoch.manager) — publish/tail mirror the ceremony
    # kinds field-for-field so forensics parses one format
    "epoch_head": {
        "required": ("round", "op", "step", "op_kind"), "optional": (),
    },
    "epoch_publish": {"required": ("round", "bytes", "seq"), "optional": ()},
    "epoch_tail": {
        "required": ("round", "present", "senders", "timed_out"),
        "optional": (),
    },
    "epoch_quarantine": {"required": ("round", "peer"), "optional": ()},
    "epoch_wal_record": {"required": ("op", "step", "bytes"), "optional": ()},
    "epoch_done": {
        "required": ("op", "op_kind", "status"), "optional": ("epoch",),
    },
    # hub side (net.channel TcpHub)
    "hub_rpc": {
        "required": ("op", "dur_s", "bytes_in", "bytes_out"), "optional": (),
    },
    "hub_junk_frame": {"required": ("reason",), "optional": ("op",)},
    # spans (tracing.phase_span / service scheduler).  The sign lane's
    # ``sign_convoy`` spans annotate the convoy composition: curve,
    # request/message/ceremony counts, proved flag, flush reason, and
    # how many tickets ended in error.
    "span": {
        "required": ("name", "ts0", "mono0", "dur_s"),
        "optional": (
            "subs", "curve", "requests", "messages", "ceremonies",
            "proved", "reason", "errors",
        ),
    },
    # open kinds: payload varies by probe/deployment (utils.runtimeobs,
    # dkg_tpu.service) — base-field conformance only
    "jax_compile": {"required": (), "optional": None},
    "counter_sample": {"required": (), "optional": None},
    "jax_cost_probe": {"required": (), "optional": None},
    "http_error": {"required": (), "optional": None},
    "service_fault_injected": {"required": (), "optional": None},
}

#: Base fields every event may carry regardless of kind.
_SCHEMA_BASE = ("ts", "mono", "kind", "ceremony_id", "party", "round")


def validate_events(
    events: Iterable[dict], *, allow_unknown: bool = False
) -> list[str]:
    """Check events against :data:`EVENT_SCHEMA`; returns a list of
    human-readable problems (empty = conformant).  Unknown kinds are
    errors unless ``allow_unknown`` (service deployments add their own
    ``service_*`` kinds); ``None``-valued fields satisfy presence (e.g.
    ``fault_injected.seconds`` for non-delay faults)."""
    problems: list[str] = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event #{i}: not a dict")
            continue
        kind = ev.get("kind")
        where = f"event #{i} ({kind!r})"
        for base in ("ts", "mono", "kind"):
            if base not in ev:
                problems.append(f"{where}: missing base field {base!r}")
        spec = EVENT_SCHEMA.get(kind) if isinstance(kind, str) else None
        if spec is None:
            if not allow_unknown:
                problems.append(f"{where}: unknown kind")
            continue
        for req in spec["required"]:
            if req not in ev:
                problems.append(f"{where}: missing required field {req!r}")
        optional = spec["optional"]
        if optional is None:
            continue  # open kind: any extras allowed
        allowed = set(_SCHEMA_BASE) | set(spec["required"]) | set(optional)
        for k in ev:
            if k not in allowed:
                problems.append(f"{where}: unexpected field {k!r}")
    return problems


# -- timeline export ---------------------------------------------------------


def load_jsonl(path: str | os.PathLike) -> list[dict]:
    """Events from one JSONL log; malformed lines are skipped (a crash
    mid-write must not poison the whole timeline).  ``.gz`` paths are
    read through gzip — chaos/fleet runs compress their sinks."""
    p = os.fspath(path)
    opener = gzip.open if p.endswith(".gz") else open
    out: list[dict] = []
    with opener(p, "rt", encoding="utf-8") as fh:
        try:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
                if isinstance(ev, dict):
                    out.append(ev)
        except (EOFError, OSError, zlib.error):
            pass  # torn gzip tail: keep every line that decoded
    return out


#: Dedicated thread id for the per-process "jax compile" track — far
#: above any real party index so it sorts last in the timeline.
_JAX_COMPILE_TID = 9999


def _tid(ev: dict) -> int:
    party = ev.get("party")
    return party + 1 if isinstance(party, int) else 0


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Merge flight-recorder events (any number of parties/ceremonies)
    into Chrome trace-event JSON (load via chrome://tracing or Perfetto).

    Mapping: one *process* per ceremony_id, one *thread* per party (the
    hub is tid 0); ``span`` events become complete ("X") slices with
    their ``subs`` rendered as nested child slices laid out sequentially
    from the parent's start; runtimeobs ``jax_compile`` events become
    "X" slices on a dedicated per-process "jax compile" thread (so
    compiles visibly overlap — or starve — ceremony phases);
    ``counter_sample`` events become Chrome counter ("C") tracks; every
    other kind becomes an instant ("i").  Wall-clock timestamps align
    events across OS processes — parties of one chaos restart run land
    on one coherent timeline.
    """
    events = [ev for ev in events if isinstance(ev, dict)]
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def wall0(ev: dict) -> float:
        # spans carry their start time; point events their emit time
        return ev.get("ts0", ev.get("ts", 0.0))

    t0 = min(wall0(ev) for ev in events)
    pids: dict[str, int] = {}
    compile_tids: set[int] = set()
    trace: list[dict] = []
    for ev in events:
        cid = str(ev.get("ceremony_id", "proc"))
        if cid not in pids:
            pids[cid] = len(pids) + 1
            trace.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pids[cid],
                    "tid": 0,
                    "args": {"name": f"ceremony {cid}"},
                }
            )
        pid, tid = pids[cid], _tid(ev)
        args = {
            k: v
            for k, v in ev.items()
            if k
            not in ("ts", "mono", "ts0", "mono0", "dur_s", "kind", "name",
                    "ceremony_id", "party", "subs")
        }
        if ev.get("kind") == "span":
            start_us = (wall0(ev) - t0) * 1e6
            dur_us = float(ev.get("dur_s", 0.0)) * 1e6
            trace.append(
                {
                    "name": str(ev.get("name", "span")),
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": start_us,
                    "dur": dur_us,
                    "args": args,
                }
            )
            # nested sub-slices laid out back-to-back from the parent start
            sub_ts = start_us
            for sub, sec in (ev.get("subs") or {}).items():
                sub_dur = float(sec) * 1e6
                trace.append(
                    {
                        "name": f"{ev.get('name', 'span')}.{sub}",
                        "ph": "X",
                        "pid": pid,
                        "tid": tid,
                        "ts": sub_ts,
                        "dur": sub_dur,
                        "args": {},
                    }
                )
                sub_ts += sub_dur
        elif ev.get("kind") == "jax_compile":
            # runtimeobs compile-stage events: their own thread per
            # process, so recompiles read as a parallel track next to
            # the ceremony phases they delay
            if pid not in compile_tids:
                compile_tids.add(pid)
                trace.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": _JAX_COMPILE_TID,
                        "args": {"name": "jax compile"},
                    }
                )
            trace.append(
                {
                    "name": f"compile/{ev.get('stage', '?')}",
                    "ph": "X",
                    "pid": pid,
                    "tid": _JAX_COMPILE_TID,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "dur": float(ev.get("dur_s", 0.0)) * 1e6,
                    "args": args,
                }
            )
        elif ev.get("kind") == "counter_sample":
            # runtimeobs memory watermarks (and any future sampled
            # gauges): Chrome counter tracks, one per counter name
            trace.append(
                {
                    "name": str(ev.get("counter", "counter")),
                    "ph": "C",
                    "pid": pid,
                    "tid": 0,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "args": {"value": ev.get("value", 0)},
                }
            )
        else:
            trace.append(
                {
                    "name": str(ev.get("kind", "event")),
                    "ph": "i",
                    "pid": pid,
                    "tid": tid,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "s": "t",
                    "args": args,
                }
            )
    trace.extend(_flow_events(events, pids, t0))
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def _flow_events(events: list[dict], pids: dict[str, int], t0: float) -> list[dict]:
    """Synthesize Perfetto flow events (``ph: s/f``) linking each publish
    to every fetch of it: a ``round_tail``/``epoch_tail`` lists the
    ``senders`` it received, so one start anchored at the publish plus
    one finish per fetching tail renders the (ceremony_id, round,
    sender, seq) correlation key as arrows in the timeline.  Synthesized
    at export time — live emission would cost O(n^2) events per round."""
    pubkinds = {"publish": "round_tail", "epoch_publish": "epoch_tail"}
    # (cid, tailkind, round, party) -> publish event; first wins, matching
    # the channel's first-publish-wins semantics (resume republishes)
    pubs: dict[tuple, dict] = {}
    for ev in events:
        tailkind = pubkinds.get(ev.get("kind"))
        if tailkind is None or not isinstance(ev.get("party"), int):
            continue
        key = (
            str(ev.get("ceremony_id", "proc")), tailkind, ev.get("round"),
            ev["party"],
        )
        pubs.setdefault(key, ev)
    out: list[dict] = []
    for ev in events:
        if ev.get("kind") not in ("round_tail", "epoch_tail"):
            continue
        cid = str(ev.get("ceremony_id", "proc"))
        for sender in ev.get("senders") or ():
            pub = pubs.get((cid, ev["kind"], ev.get("round"), sender))
            if pub is None:
                continue  # log set missing this publisher's sink
            # one flow (unique id) per (publish, fetcher) pair — a chrome
            # flow id binds exactly one start to one finish
            fid = (
                f"{cid}:{ev['kind']}:{ev.get('round')}:{sender}"
                f":{pub.get('seq')}->{ev.get('party')}"
            )
            common = {
                "name": f"r{ev.get('round')} publish p{sender}",
                "cat": "flow",
                "pid": pids[cid],
                "id": fid,
            }
            out.append(
                {
                    **common,
                    "ph": "s",
                    "tid": _tid(pub),
                    "ts": (pub.get("ts", 0.0) - t0) * 1e6,
                }
            )
            out.append(
                {
                    **common,
                    "ph": "f",
                    "bp": "e",
                    "tid": _tid(ev),
                    "ts": (ev.get("ts", 0.0) - t0) * 1e6,
                }
            )
    return out


# -- critical-path forensics -------------------------------------------------


def _round_windows(evs: list[dict]) -> dict[int, dict]:
    """Per-round raw material for one ceremony's merged event list:
    head/tail/publish timestamps plus the per-party retry and
    injected-delay attributions."""
    rounds: dict[int, dict] = {}

    def bucket(r) -> dict | None:
        if not isinstance(r, int):
            return None
        return rounds.setdefault(
            r, {"heads": [], "tails": [], "pubs": {}, "timed_out": False}
        )

    for ev in evs:
        kind = ev.get("kind")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "round_head":
            b = bucket(ev.get("round"))
            if b is not None:
                b["heads"].append(ts)
        elif kind == "round_tail":
            b = bucket(ev.get("round"))
            if b is not None:
                b["tails"].append(ev)
                if ev.get("timed_out"):
                    b["timed_out"] = True
        elif kind == "publish":
            b = bucket(ev.get("round"))
            party = ev.get("party")
            if b is not None and isinstance(party, int):
                # first-publish-wins, matching the channel semantics
                b["pubs"].setdefault(party, ts)
    return rounds


def _attributed(
    evs: list[dict], party, lo: float, hi: float, round_no: int
) -> tuple[float, float]:
    """(retry_s, fault_s) chargeable to ``party`` inside the wall-clock
    window [lo, hi]: recorded RPC backoff sleeps plus injected delay
    faults for this round.  Each sum is clamped to the window width —
    attribution can never exceed the time it is explaining."""
    width = max(0.0, hi - lo)
    retry = fault = 0.0
    for ev in evs:
        if ev.get("party") != party:
            continue
        kind = ev.get("kind")
        ts = ev.get("ts", 0.0)
        if kind == "rpc_retry" and lo <= ts <= hi:
            retry += float(ev.get("backoff_s") or 0.0)
        elif (
            kind == "fault_injected"
            and ev.get("fault") == "delay"
            and ev.get("round") == round_no
            and ev.get("seconds") is not None
        ):
            fault += float(ev.get("seconds"))
    retry = min(retry, width)
    fault = min(fault, width - retry)
    return retry, fault


def critical_path(events: Iterable[dict], registry=None) -> list[dict]:
    """Reconstruct each ceremony round's barrier and attribute it.

    Merges any number of per-party logs (wall-clock ``ts`` aligns them,
    as in :func:`to_chrome_trace`) and reports, per (ceremony_id, round):

    * ``barrier_s`` — first ``round_head`` to last ``round_tail``;
    * ``straggler`` — the last party to publish (or the absent party a
      timed-out round waited for), with ``straggler_lag_s`` = how long
      the round waited for it (round open -> its publish);
    * a decomposition ``compute_s + transport_s + retry_s +
      quarantine_s == barrier_s`` **exactly** (the buckets partition the
      barrier by construction): the leg up to the straggler's publish is
      compute time net of its recorded retries and injected delays, the
      leg after it is fetch/transport time net of the closing fetcher's
      retries; retry backoffs land in ``retry_s``, injected-fault delays
      and time spent waiting on an absent (crashed/timed-out) straggler
      land in ``quarantine_s``.

    ``registry`` (a MetricsRegistry) receives one
    ``net_round_straggler_lag_seconds{ceremony_id,round,straggler}``
    gauge per round for the SLO layer.  scripts/forensics.py is the CLI.
    """
    by_cid: dict[str, list[dict]] = {}
    for ev in events:
        if isinstance(ev, dict):
            by_cid.setdefault(str(ev.get("ceremony_id", "proc")), []).append(ev)
    report: list[dict] = []
    for cid in sorted(by_cid):
        evs = by_cid[cid]
        committee = {
            ev["party"] for ev in evs if isinstance(ev.get("party"), int)
        }
        for r, b in sorted(_round_windows(evs).items()):
            if not b["tails"]:
                continue  # round never closed anywhere: no barrier to explain
            t_close = max(ev["ts"] for ev in b["tails"])
            closer = max(b["tails"], key=lambda ev: ev["ts"]).get("party")
            opens = b["heads"] or list(b["pubs"].values())
            if not opens:
                continue
            t_open = min(opens)
            barrier = max(0.0, t_close - t_open)
            absent = sorted(committee - set(b["pubs"]))
            if b["pubs"]:
                last_pub = max(b["pubs"], key=lambda p: b["pubs"][p])
            else:
                last_pub = None
            if absent and b["timed_out"]:
                # the round closed on timeout waiting for a party that
                # never published: IT is the straggler, and the whole
                # wait is chargeable to its absence
                straggler, s_absent, pub_ts = absent[0], True, t_close
            elif last_pub is None:
                continue
            else:
                straggler, s_absent = last_pub, False
                pub_ts = min(max(b["pubs"][last_pub], t_open), t_close)
            # leg 1: round open -> straggler publish (its compute path)
            retry1, fault1 = _attributed(evs, straggler, t_open, pub_ts, r)
            leg1 = pub_ts - t_open
            resid1 = max(0.0, leg1 - retry1 - fault1)
            # leg 2: straggler publish -> slowest fetcher's close
            retry2, fault2 = _attributed(evs, closer, pub_ts, t_close, r)
            leg2 = t_close - pub_ts
            resid2 = max(0.0, leg2 - retry2 - fault2)
            entry = {
                "ceremony_id": cid,
                "round": r,
                "barrier_s": barrier,
                "straggler": straggler,
                "straggler_absent": s_absent,
                "straggler_lag_s": leg1,
                "compute_s": 0.0 if s_absent else resid1,
                "transport_s": resid2,
                "retry_s": retry1 + retry2,
                "quarantine_s": fault1 + fault2 + (resid1 if s_absent else 0.0),
                "timed_out": b["timed_out"],
                "present": max(ev.get("present", 0) for ev in b["tails"]),
                "expected": len(committee),
            }
            report.append(entry)
            if registry is not None:
                registry.set_gauge(
                    "net_round_straggler_lag_seconds",
                    leg1,
                    ceremony_id=cid,
                    round=r,
                    straggler=straggler,
                )
    return report
