"""Ceremony flight recorder: structured JSONL events + Chrome trace export.

Every interesting transition in a ceremony — round head/tail, publish,
RPC retry, quarantine, timeout, WAL replay, injected fault — is one
JSON object with monotonic (``mono``) and wall (``ts``) timestamps and
``ceremony_id``/``party``/``round`` identity fields.  Events land in a
bounded in-memory ring (:class:`ObsLog`) and, when the ``DKG_TPU_OBSLOG``
env knob names a directory, in one append-mode JSONL file per party so a
chaos failure can be replayed from its logs alone.

Redaction is structural, not best-effort: the recorder NEVER accepts
share or key material — instrumentation sites only pass lengths, counts,
indices, and error kinds — and as belt-and-braces every ``bytes`` value
reaching :meth:`ObsLog.emit` is replaced by its length before
serialization.  ``tests/test_obslog.py`` greps the emitted bytes of a
live ceremony for the committee's secrets to prove it.

Channel and fault code run deep inside transport internals where no
recorder handle exists; they emit through an *ambient* recorder
(:func:`use` / :func:`emit_current`) that ``run_party`` binds for the
duration of its party thread.  The binding is a
:class:`contextvars.ContextVar`, not a ``threading.local``: threaded
callers see identical behavior (each thread starts from the unbound
default), but an async scheduler multiplexing many ceremonies on ONE
event loop (dkg_tpu.service) gets per-task isolation for free —
``asyncio`` snapshots the context per task, so two interleaved
ceremonies on the same thread cannot cross-contaminate each other's
streams (tests/test_obslog.py interleaves two recorders on one thread
to pin this).

:func:`to_chrome_trace` merges any number of per-party logs into one
Chrome/Perfetto trace-event JSON: one process per ceremony, one thread
per party, ``phase_span`` spans as complete ("X") slices with
``subtimings_s`` nested under them, and point events as instants.
``scripts/trace_viz.py`` is the CLI wrapper.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterable

from . import envknobs

# The ambient recorder binding.  A ContextVar instead of threading.local:
# identical semantics for plain threads (every thread starts unbound),
# but copyable per asyncio task / contextvars.Context, so one scheduler
# thread interleaving several ceremonies keeps their streams separate.
_AMBIENT: contextvars.ContextVar["ObsLog | None"] = contextvars.ContextVar(
    "dkg_tpu_obslog", default=None
)


def _sanitize(value: Any) -> Any:
    """Replace bytes payloads with their length, recursively.  The
    instrumentation contract is lengths-only already; this makes an
    accidental ``payload=raw`` emit a harmless ``"bytes:N"``."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"bytes:{len(value)}"
    if isinstance(value, dict):
        return {str(k): _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


class ObsLog:
    """Bounded ring of structured events with an optional JSONL file sink.

    ``ceremony_id`` and ``party`` bind once at construction and stamp
    every event; ``party`` is an int member index or ``"hub"``.
    """

    def __init__(
        self,
        capacity: int = 4096,
        path: str | os.PathLike | None = None,
        ceremony_id: str | None = None,
        party: int | str | None = None,
    ) -> None:
        self.ceremony_id = ceremony_id
        self.party = party
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._path = os.fspath(path) if path is not None else None
        self._fh = open(self._path, "a", encoding="utf-8") if self._path else None

    # -- recording ----------------------------------------------------------

    def emit(self, kind: str, *, round: int | None = None, **fields) -> dict:
        """Record one event; returns the event dict (tests poke at it)."""
        ev: dict[str, Any] = {
            "ts": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
        }
        if self.ceremony_id is not None:
            ev["ceremony_id"] = self.ceremony_id
        if self.party is not None:
            ev["party"] = self.party
        if round is not None:
            ev["round"] = round
        for k, v in fields.items():
            ev[k] = _sanitize(v)
        with self._lock:
            self._ring.append(ev)
            if self._fh is not None:
                self._fh.write(json.dumps(ev, sort_keys=True) + "\n")
                self._fh.flush()
        return ev

    def emit_span(
        self,
        name: str,
        *,
        ts0: float,
        mono0: float,
        dur_s: float,
        subs: dict[str, float] | None = None,
        **fields,
    ) -> dict:
        """Record a completed span (``phase_span`` feeds these): start
        timestamps, duration, and optional sub-phase seconds that the
        trace export renders as nested slices."""
        span_fields: dict[str, Any] = {
            "name": name,
            "ts0": ts0,
            "mono0": mono0,
            "dur_s": dur_s,
        }
        if subs:
            span_fields["subs"] = {k: float(v) for k, v in subs.items()}
        span_fields.update(fields)
        return self.emit("span", **span_fields)

    # -- access -------------------------------------------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def path(self) -> str | None:
        return self._path

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "ObsLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- ambient (context-local) recorder ----------------------------------------


class _Use:
    """Context manager binding ``log`` as the current context's ambient
    recorder; ``use(None)`` is a no-op binding (events are dropped).
    Bindings nest: exit restores whatever was bound on entry."""

    def __init__(self, log: ObsLog | None) -> None:
        self._log = log
        self._token: contextvars.Token | None = None

    def __enter__(self) -> ObsLog | None:
        self._token = _AMBIENT.set(self._log)
        return self._log

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _AMBIENT.reset(self._token)
            self._token = None


def use(log: ObsLog | None) -> _Use:
    return _Use(log)


def current() -> ObsLog | None:
    """The current context's ambient recorder, or None."""
    return _AMBIENT.get()


def emit_current(kind: str, *, round: int | None = None, **fields) -> dict | None:
    """Emit into the ambient recorder if one is bound; else drop."""
    log = current()
    if log is None:
        return None
    return log.emit(kind, round=round, **fields)


# -- construction helpers ----------------------------------------------------


def ceremony_id_for(env) -> str:
    """Deterministic short id for a ceremony Environment: all parties of
    one ceremony derive the same id from the (group, n, t, commitment
    key) tuple, so their logs merge onto one timeline."""
    import hashlib

    h = hashlib.blake2b(digest_size=6)
    h.update(env.group.name.encode())
    h.update(f":{env.nr_members}:{env.threshold}:".encode())
    h.update(env.group.encode(env.commitment_key.h))
    return h.hexdigest()


def from_env(
    *,
    ceremony_id: str | None = None,
    party: int | str | None = None,
    capacity: int = 4096,
) -> ObsLog | None:
    """An :class:`ObsLog` with a file sink under the ``DKG_TPU_OBSLOG``
    directory, or None when the knob is unset.  File name is
    ``{ceremony_id}-p{party:03d}.jsonl`` (``-hub.jsonl`` for the hub)."""
    root = envknobs.string("DKG_TPU_OBSLOG", "flight-recorder log directory")
    if root is None:
        return None
    os.makedirs(root, exist_ok=True)
    cid = ceremony_id if ceremony_id is not None else "proc"
    tag = f"p{party:03d}" if isinstance(party, int) else str(party or "proc")
    path = os.path.join(root, f"{cid}-{tag}.jsonl")
    return ObsLog(capacity=capacity, path=path, ceremony_id=ceremony_id, party=party)


# -- timeline export ---------------------------------------------------------


def load_jsonl(path: str | os.PathLike) -> list[dict]:
    """Events from one JSONL log; malformed lines are skipped (a crash
    mid-write must not poison the whole timeline)."""
    out: list[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                out.append(ev)
    return out


#: Dedicated thread id for the per-process "jax compile" track — far
#: above any real party index so it sorts last in the timeline.
_JAX_COMPILE_TID = 9999


def _tid(ev: dict) -> int:
    party = ev.get("party")
    return party + 1 if isinstance(party, int) else 0


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Merge flight-recorder events (any number of parties/ceremonies)
    into Chrome trace-event JSON (load via chrome://tracing or Perfetto).

    Mapping: one *process* per ceremony_id, one *thread* per party (the
    hub is tid 0); ``span`` events become complete ("X") slices with
    their ``subs`` rendered as nested child slices laid out sequentially
    from the parent's start; runtimeobs ``jax_compile`` events become
    "X" slices on a dedicated per-process "jax compile" thread (so
    compiles visibly overlap — or starve — ceremony phases);
    ``counter_sample`` events become Chrome counter ("C") tracks; every
    other kind becomes an instant ("i").  Wall-clock timestamps align
    events across OS processes — parties of one chaos restart run land
    on one coherent timeline.
    """
    events = [ev for ev in events if isinstance(ev, dict)]
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def wall0(ev: dict) -> float:
        # spans carry their start time; point events their emit time
        return ev.get("ts0", ev.get("ts", 0.0))

    t0 = min(wall0(ev) for ev in events)
    pids: dict[str, int] = {}
    compile_tids: set[int] = set()
    trace: list[dict] = []
    for ev in events:
        cid = str(ev.get("ceremony_id", "proc"))
        if cid not in pids:
            pids[cid] = len(pids) + 1
            trace.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pids[cid],
                    "tid": 0,
                    "args": {"name": f"ceremony {cid}"},
                }
            )
        pid, tid = pids[cid], _tid(ev)
        args = {
            k: v
            for k, v in ev.items()
            if k
            not in ("ts", "mono", "ts0", "mono0", "dur_s", "kind", "name",
                    "ceremony_id", "party", "subs")
        }
        if ev.get("kind") == "span":
            start_us = (wall0(ev) - t0) * 1e6
            dur_us = float(ev.get("dur_s", 0.0)) * 1e6
            trace.append(
                {
                    "name": str(ev.get("name", "span")),
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": start_us,
                    "dur": dur_us,
                    "args": args,
                }
            )
            # nested sub-slices laid out back-to-back from the parent start
            sub_ts = start_us
            for sub, sec in (ev.get("subs") or {}).items():
                sub_dur = float(sec) * 1e6
                trace.append(
                    {
                        "name": f"{ev.get('name', 'span')}.{sub}",
                        "ph": "X",
                        "pid": pid,
                        "tid": tid,
                        "ts": sub_ts,
                        "dur": sub_dur,
                        "args": {},
                    }
                )
                sub_ts += sub_dur
        elif ev.get("kind") == "jax_compile":
            # runtimeobs compile-stage events: their own thread per
            # process, so recompiles read as a parallel track next to
            # the ceremony phases they delay
            if pid not in compile_tids:
                compile_tids.add(pid)
                trace.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": _JAX_COMPILE_TID,
                        "args": {"name": "jax compile"},
                    }
                )
            trace.append(
                {
                    "name": f"compile/{ev.get('stage', '?')}",
                    "ph": "X",
                    "pid": pid,
                    "tid": _JAX_COMPILE_TID,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "dur": float(ev.get("dur_s", 0.0)) * 1e6,
                    "args": args,
                }
            )
        elif ev.get("kind") == "counter_sample":
            # runtimeobs memory watermarks (and any future sampled
            # gauges): Chrome counter tracks, one per counter name
            trace.append(
                {
                    "name": str(ev.get("counter", "counter")),
                    "ph": "C",
                    "pid": pid,
                    "tid": 0,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "args": {"value": ev.get("value", 0)},
                }
            )
        else:
            trace.append(
                {
                    "name": str(ev.get("kind", "event")),
                    "ph": "i",
                    "pid": pid,
                    "tid": tid,
                    "ts": (wall0(ev) - t0) * 1e6,
                    "s": "t",
                    "args": args,
                }
            )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}
