"""Validated environment-knob parsing shared across modules.

Every DKG_TPU_* knob that silently mis-parsing could turn into a wrong
(possibly OOM or wrong-kernel) compiled program goes through here, so
the validate-and-raise behavior cannot drift between copies (knobs:
DKG_TPU_DEAL_CHUNK / DKG_TPU_VERIFY_CHUNK / DKG_TPU_RLC_CHUNK via
dkg.ceremony._env_chunk, DKG_TPU_DEM / DKG_TPU_DEM_CHUNK via
dkg.hybrid_batch, DKG_TPU_RLC via dkg.ceremony._point_rlc,
DKG_TPU_MSM / DKG_TPU_FB_WINDOW / DKG_TPU_FUSED_MULTI /
DKG_TPU_ED_FUSED_LADDER / DKG_TPU_ED_FUSED_DOUBLES via groups.device,
DKG_TPU_PALLAS / DKG_TPU_ASSUME_BACKEND / DKG_TPU_REDUCE
(fold|linear|barrett — force a wide-reduction algorithm; inadmissible
choices raise at trace time) / DKG_TPU_CARRY (scan|lookahead carry
propagation in normalize) / DKG_TPU_MUL (auto|gemm|classic — the
fd.mul formulation: fused GEMM multiply-reduce twin vs
mul_wide+reduce_wide; gemm raises at trace time on fields that fail
the spec.mulred admission proofs) via fields.device,
DKG_TPU_MXU via fields.matmul, DKG_TPU_TABLE_CACHE via
groups.precompute, DKG_TPU_NET_* transport knobs via net.channel,
DKG_TPU_SIGN_BATCH (device message-chunk size) and
DKG_TPU_SIGN_DISPATCH (device|host partial-signature leg) via
sign.partial — lint rule DKG009 bans raw environment access and
per-message scalar-mul loops in dkg_tpu/sign/ hot paths,
DKG_TPU_CHECKPOINT_DIR via net.checkpoint,
DKG_TPU_DIGEST via crypto.device_hash.digest_dispatch,
DKG_TPU_OBSLOG flight-recorder log directory via utils.obslog,
DKG_TPU_SERVICE_CONCURRENCY / DKG_TPU_SERVICE_QUEUE_DEPTH /
DKG_TPU_SERVICE_BATCH_MAX / DKG_TPU_SERVICE_DEADLINE_S /
DKG_TPU_SERVICE_WAL_DIR / DKG_TPU_SERVICE_RETRIES (transient-fault
convoy retries, 0 disables) / DKG_TPU_SERVICE_RETRY_BACKOFF_S (first
backoff, doubling) / DKG_TPU_SERVICE_MAX_REPLAYS (journal crash-loop
guard) scheduler knobs via service.scheduler — lint rule DKG007 bans
any other environment access in dkg_tpu/service/,
DKG_TPU_RUNTIMEOBS (on|off — JAX compile/cache/memory introspection)
via utils.runtimeobs,
DKG_TPU_SERVICE_HTTP_PORT (observability HTTP port; 0 binds an
ephemeral port, unset keeps the scrape surface off) via
service.httpobs,
DKG_TPU_SLO_WINDOW_S / DKG_TPU_SLO_ERROR_BUDGET /
DKG_TPU_SLO_CEREMONY_P99_S / DKG_TPU_SLO_SIGN_P99_S (rolling SLO
window, allowed failure ratio, and latency objectives) via
service.slo,
DKG_TPU_SIGN_RLC_DISPATCH (host|device RLC combine leg) via
sign.verify,
DKG_TPU_SIGN_MESH (0|1|force — shard the steady lane's folded sign
ladder over the device mesh; 1 engages only where shards run
concurrently (accelerator backend or a multi-core host), force on any
>=2-device mesh; the Mesh handle and shard_map live in
parallel.signmesh, per lint rule DKG015) via parallel.signmesh,
DKG_TPU_NORTH_STAR (bench.py: 1 forces the north-star sharded rung on
any platform, 0 skips it; read by the driver scripts, not dkg_tpu/),
DKG_TPU_EPOCH_MAX_CHURN (leave+join budget a reshare accepts; 0
refuses any membership change) and DKG_TPU_EPOCH_DEADLINE_S
(per-epoch-round fetch timeout) via dkg_tpu.epoch.manager — lint
rule DKG008 likewise bans raw environment access in dkg_tpu/epoch/,
DKG_TPU_AOT_DIR (AOT-serialized executable store directory; unset
keeps the store off) via service.aot — also read by scripts/aot_lab.py
as its compile-cache location,
DKG_TPU_AOT_TOPOLOGY (chip-less topology scripts/aot_lab.py compiles
against, default v5e:2x2),
DKG_TPU_FLEET_PROCS (initial worker-process count) /
DKG_TPU_FLEET_MIN / DKG_TPU_FLEET_MAX (autoscale floor/ceiling) /
DKG_TPU_FLEET_CONTROL_S (control-loop period; unset disables the
loop) / DKG_TPU_FLEET_HTTP_PORT (front-door port; 0 binds an
ephemeral port, unset keeps the fleet python-API only) via
service.fleet,
DKG_TPU_FLEET_WAL_DIR (per-slot fleet journal root: slot NNN's workers
journal into <root>/slotNNN and a replacement worker recovers from it;
unset disables worker failover — reaped workers' placements are
evicted) / DKG_TPU_FLEET_RESPAWN_BACKOFF_S (backoff before a slot's
SECOND respawn, doubling per further death, capped; the first respawn
is immediate; default 0.5) / DKG_TPU_FLEET_RESPAWN_MAX (deaths within
the window before a slot is quarantined instead of respawned, default
3 — the fleet mirror of DKG_TPU_SERVICE_MAX_REPLAYS) /
DKG_TPU_FLEET_RESPAWN_WINDOW_S (rolling crash-loop window, default
60) / DKG_TPU_FLEET_SUBMIT_RETRY_S (pause before submit's one retry
against the replacement or ring-next worker, default 0.05) via
service.fleet).

An EMPTY value is everywhere treated as unset: ``DKG_TPU_X= cmd`` is
the shell idiom for clearing a knob on one invocation, and must select
the default path, not raise.
"""

from __future__ import annotations

import os


def choice(name: str, options: tuple, what: str) -> str | None:
    """None when ``name`` is unset (or empty), else its value validated
    against ``options`` (a tuple of accepted strings).

    Raises ValueError on anything else — enum knobs select compiled
    kernel paths (MSM algorithm, RLC schedule, fused dispatch), where a
    typo must fail loudly rather than silently run the default path.
    """
    env = os.environ.get(name)
    if not env:
        return None
    if env not in options:
        raise ValueError(
            f"{name}={env!r}: expected one of "
            f"{', '.join(repr(o) for o in options)} ({what})"
        )
    return env


def nonneg_int(name: str, what: str) -> int | None:
    """None when ``name`` is unset, else its value as an int >= 0.

    Raises ValueError on anything else — a typo must fail loudly, never
    silently select a default.  ``what`` explains the zero semantics in
    the error message (e.g. "0 disables chunking").
    """
    env = os.environ.get(name)
    if not env:
        return None
    try:
        v = int(env)
    except ValueError:
        v = -1
    if v < 0:
        raise ValueError(
            f"{name}={env!r}: expected a non-negative integer ({what})"
        )
    return v


def pos_int(name: str, what: str) -> int | None:
    """None when ``name`` is unset, else its value as an int >= 1."""
    env = os.environ.get(name)
    if not env:
        return None
    try:
        v = int(env)
    except ValueError:
        v = 0
    if v < 1:
        raise ValueError(f"{name}={env!r}: expected a positive integer ({what})")
    return v


def pos_float(name: str, what: str) -> float | None:
    """None when ``name`` is unset, else its value as a finite float > 0."""
    env = os.environ.get(name)
    if not env:
        return None
    try:
        v = float(env)
    except ValueError:
        v = -1.0
    if not v > 0 or v != v or v == float("inf"):
        raise ValueError(f"{name}={env!r}: expected a positive finite number ({what})")
    return v


def string(name: str, what: str) -> str | None:
    """None when ``name`` is unset or empty, else its raw value.

    For free-form knobs (paths, labels) where any non-empty string is
    valid; exists so every DKG_TPU_* parse shares the one empty-is-unset
    convention instead of re-implementing ``if env:`` truthiness.
    ``what`` documents the knob for grep (e.g. "table cache directory").
    """
    del what  # documentation-only, kept for signature parity
    return os.environ.get(name) or None


def nonneg_float(name: str, what: str) -> float | None:
    """None when ``name`` is unset, else its value as a finite float >= 0."""
    env = os.environ.get(name)
    if not env:
        return None
    try:
        v = float(env)
    except ValueError:
        v = -1.0
    if not v >= 0 or v == float("inf"):
        raise ValueError(
            f"{name}={env!r}: expected a non-negative finite number ({what})"
        )
    return v
