"""Ceremony observability: per-phase wall-clock, counters, profiler hooks.

The reference has no tracing/metrics/logging of any kind (SURVEY §5 —
errors are the only signal).  Here observability is first-class:

* :class:`CeremonyTrace` — structured per-phase timings + protocol
  counters (complaints filed/upheld, disqualifications, reconstructions),
  rendered as one JSON-able dict.
* :func:`phase_span` — context manager timing one phase; nests under a
  trace and (optionally) a ``jax.profiler.TraceAnnotation`` so device
  kernels show up named in TPU profiles.
* :func:`profile_to` — whole-ceremony ``jax.profiler`` capture helper.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field


@dataclass
class CeremonyTrace:
    """Mutable trace of one ceremony run."""

    timings_s: dict = field(default_factory=dict)  # phase -> seconds
    counters: dict = field(default_factory=dict)  # name -> int
    meta: dict = field(default_factory=dict)
    # phase -> {sub -> seconds}; finer-grained than timings_s and kept
    # OUT of it so rates()/total_s never double-count a phase
    subtimings_s: dict = field(default_factory=dict)

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def record(self, phase: str, seconds: float) -> None:
        self.timings_s[phase] = self.timings_s.get(phase, 0.0) + seconds

    def record_sub(self, phase: str, sub: str, seconds: float) -> None:
        """Accumulate a sub-timing under ``phase`` (e.g. the fiat_shamir
        phase splits into ``digest`` and ``rho``)."""
        subs = self.subtimings_s.setdefault(phase, {})
        subs[sub] = subs.get(sub, 0.0) + seconds

    @property
    def total_s(self) -> float:
        return sum(self.timings_s.values())

    def rates(self, units: float) -> dict:
        """units/second for every recorded phase (zero-duration phases
        omitted) — e.g. ``trace.rates(n * (n - 1))`` gives per-phase
        pair-verify rates; one-off phases like ``tables`` (table-build,
        recorded by BatchedCeremony) are naturally separated from the
        steady-state ones by having their own key."""
        return {ph: units / s for ph, s in self.timings_s.items() if s > 0}

    def as_dict(self) -> dict:
        return {
            "timings_s": dict(self.timings_s),
            "subtimings_s": {k: dict(v) for k, v in self.subtimings_s.items()},
            "total_s": self.total_s,
            "counters": dict(self.counters),
            "meta": dict(self.meta),
        }

    def json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)


@contextlib.contextmanager
def phase_span(trace: CeremonyTrace | None, phase: str, annotate_device: bool = True):
    """Time a phase; also annotates the device profile when jax has a
    profiler available (no-op overhead otherwise)."""
    ann = contextlib.nullcontext()
    if annotate_device:
        try:
            import jax.profiler

            ann = jax.profiler.TraceAnnotation(f"dkg/{phase}")
        except Exception:  # pragma: no cover - profiler unavailable
            pass
    t0 = time.perf_counter()
    with ann:
        yield
    if trace is not None:
        trace.record(phase, time.perf_counter() - t0)


@contextlib.contextmanager
def profile_to(logdir: str):
    """Capture a jax profiler trace for the enclosed ceremony section."""
    import jax.profiler

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
