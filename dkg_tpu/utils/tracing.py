"""Ceremony observability: per-phase wall-clock, counters, profiler hooks.

The reference has no tracing/metrics/logging of any kind (SURVEY §5 —
errors are the only signal).  Here observability is first-class:

* :class:`CeremonyTrace` — structured per-phase timings + protocol
  counters (complaints filed/upheld, disqualifications, reconstructions),
  rendered as one JSON-able dict.
* :func:`phase_span` — context manager timing one phase; nests under a
  trace and (optionally) a ``jax.profiler.TraceAnnotation`` so device
  kernels show up named in TPU profiles.  Every completed span also
  observes the process-wide ``dkg_phase_seconds`` histogram
  (:mod:`~dkg_tpu.utils.metrics`) and, when the calling thread has an
  ambient flight recorder bound (:mod:`~dkg_tpu.utils.obslog`), emits a
  span event carrying the sub-timings accumulated during the phase.
* :func:`profile_to` — whole-ceremony ``jax.profiler`` capture helper.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field

from . import metrics, obslog, runtimeobs


@dataclass
class CeremonyTrace:
    """Mutable trace of one ceremony run."""

    timings_s: dict = field(default_factory=dict)  # phase -> seconds
    counters: dict = field(default_factory=dict)  # name -> int
    meta: dict = field(default_factory=dict)
    # phase -> {sub -> seconds}; finer-grained than timings_s and kept
    # OUT of it so rates()/total_s never double-count a phase
    subtimings_s: dict = field(default_factory=dict)

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def record(self, phase: str, seconds: float) -> None:
        self.timings_s[phase] = self.timings_s.get(phase, 0.0) + seconds

    def record_sub(self, phase: str, sub: str, seconds: float) -> None:
        """Accumulate a sub-timing under ``phase`` (e.g. the fiat_shamir
        phase splits into ``digest`` and ``rho``)."""
        subs = self.subtimings_s.setdefault(phase, {})
        subs[sub] = subs.get(sub, 0.0) + seconds

    @property
    def total_s(self) -> float:
        return sum(self.timings_s.values())

    def rates(self, units: float) -> dict:
        """units/second for every recorded phase (zero-duration phases
        omitted) — e.g. ``trace.rates(n * (n - 1))`` gives per-phase
        pair-verify rates; one-off phases like ``tables`` (table-build,
        recorded by BatchedCeremony) are naturally separated from the
        steady-state ones by having their own key."""
        return {ph: units / s for ph, s in self.timings_s.items() if s > 0}

    def as_dict(self) -> dict:
        out = {
            "timings_s": dict(self.timings_s),
            "subtimings_s": {k: dict(v) for k, v in self.subtimings_s.items()},
            "total_s": self.total_s,
            "counters": dict(self.counters),
            "meta": dict(self.meta),
        }
        units = self.meta.get("units")
        if isinstance(units, (int, float)) and not isinstance(units, bool) and units > 0:
            out["rates_per_s"] = self.rates(units)
        wire = self.wire_summary()
        if wire is not None:
            out["wire"] = wire
        return out

    def wire_summary(self) -> dict | None:
        """Per-ceremony wire totals derived from the ``net.wire_bytes_*``
        counters the party/epoch publish-and-fetch paths bump, or None
        when this trace saw no transport.  ``bytes_per_pair`` normalizes
        the published payload by the n*(n-1) dealer->recipient pairs
        (meta ``n``) — the unit the O(n*t) data-plane scaling work is
        judged in (ROADMAP item 4)."""
        out_b = self.counters.get("net.wire_bytes_out")
        in_b = self.counters.get("net.wire_bytes_in")
        if out_b is None and in_b is None:
            return None
        wire: dict = {
            "wire_bytes_out": int(out_b or 0),
            "wire_bytes_in": int(in_b or 0),
            "wire_bytes": int(out_b or 0) + int(in_b or 0),
        }
        n = self.meta.get("n")
        if isinstance(n, int) and n > 1:
            wire["bytes_per_pair"] = (out_b or 0) / (n * (n - 1))
        return wire

    def json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)


# jax.profiler availability, probed once per process: None = unprobed,
# False = unavailable, else the TraceAnnotation class.  phase_span runs
# per round in tight loops; the per-span import-and-try was measurable
# overhead and buried the one-time ImportError cost inside hot paths.
_ANNOTATION_CLS = None


def _annotation_cls():
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is None:
        try:
            import jax.profiler

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # pragma: no cover - profiler unavailable
            _ANNOTATION_CLS = False
    return _ANNOTATION_CLS


@contextlib.contextmanager
def phase_span(trace: CeremonyTrace | None, phase: str, annotate_device: bool = True):
    """Time a phase; also annotates the device profile when jax has a
    profiler available (no-op overhead otherwise)."""
    ann = contextlib.nullcontext()
    if annotate_device:
        cls = _annotation_cls()
        if cls:
            ann = cls(f"dkg/{phase}")
    recorder = obslog.current()
    if recorder is not None and trace is not None:
        subs0 = dict(trace.subtimings_s.get(phase) or {})
    ts0 = time.time()
    t0 = time.perf_counter()
    with ann:
        yield
    dt = time.perf_counter() - t0
    if trace is not None:
        trace.record(phase, dt)
    metrics.REGISTRY.observe("dkg_phase_seconds", dt, phase=phase)
    # device/host memory watermark at the phase boundary (no-op unless
    # runtimeobs is installed; internally throttled)
    runtimeobs.maybe_sample(phase=phase)
    if recorder is not None:
        subs = None
        if trace is not None:
            now = trace.subtimings_s.get(phase) or {}
            subs = {
                k: v - subs0.get(k, 0.0)
                for k, v in now.items()
                if v - subs0.get(k, 0.0) > 0
            }
        recorder.emit_span(phase, ts0=ts0, mono0=t0, dur_s=dt, subs=subs or None)


@contextlib.contextmanager
def profile_to(logdir: str):
    """Capture a jax profiler trace for the enclosed ceremony section."""
    import jax.profiler

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
