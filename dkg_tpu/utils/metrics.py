"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The flight recorder (utils.obslog) answers "what happened in THIS
ceremony"; this module answers "what is this PROCESS doing" — the
aggregate substrate a multi-tenant ceremony service scrapes (ROADMAP
item 1).  Everything funnels into one :data:`REGISTRY`:

* :func:`~dkg_tpu.utils.tracing.phase_span` observes every completed
  phase into the ``dkg_phase_seconds`` histogram, so concurrent
  ceremonies aggregate naturally;
* ``net.party`` feeds each finished :class:`PartyResult`'s transport
  counters (quarantined, timeouts, retries, resumes, wal.*) via
  :func:`observe_party_result`;
* the TcpHub handler and client feed per-opcode RPC counts, latency,
  byte totals, junk frames, and budget clamps (net/channel.py);
* fault injection counts per-kind via ``dkg_faults_injected_total``
  (net/faults.py).

Exports: :meth:`MetricsRegistry.snapshot` (one JSON-able dict — what
bench.py and chaos_storm.py embed in their artifacts) and
:meth:`MetricsRegistry.prometheus_text` (the text exposition format, for
scraping).  All operations are thread-safe; labels are plain keyword
strings and series are keyed by the rendered ``name{k="v"}`` form so
snapshots read like the exposition they export to.
"""

from __future__ import annotations

import bisect
import threading

# Latency buckets (seconds): spans ~1 ms RPCs to ~minute-long phases.
# Fixed so concurrent ceremonies and successive processes aggregate —
# a histogram with drifting buckets cannot be merged or compared.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Payload-size buckets (bytes): empty-round publishes (~13 B framed) up
# to north-star round-1 dealings (tens of MB).  Fixed for the same
# aggregation reason as DEFAULT_BUCKETS — wire-accounting histograms
# from different processes must merge.
SIZE_BUCKETS = (
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
)


def _labelitems(labels: dict) -> tuple:
    return tuple(
        sorted((str(k), str(v)) for k, v in labels.items() if v is not None)
    )


def _escape(value: str) -> str:
    """Prometheus label-value escaping (backslash, double quote, newline)
    — a ceremony_id or error-kind label must never be able to break the
    exposition format, whatever bytes it carries."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _series(name: str, labelitems: tuple) -> str:
    if not labelitems:
        return name
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labelitems)
    return f"{name}{{{inner}}}"


def _fmt(v: float) -> str:
    """Prometheus-style number: integers without a trailing ``.0``."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class MetricsRegistry:
    """Thread-safe counter/gauge/histogram store with text + JSON export."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (name, labelitems) -> float
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # (name, labelitems) -> [buckets, per-bucket counts (+overflow), sum, count]
        self._hists: dict[tuple[str, tuple], list] = {}

    # -- writes -------------------------------------------------------------

    def inc(self, name: str, by: float = 1, **labels) -> None:
        key = (name, _labelitems(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, _labelitems(labels))
        with self._lock:
            self._gauges[key] = value

    def observe(
        self, name: str, value: float, buckets: tuple = DEFAULT_BUCKETS, **labels
    ) -> None:
        """Record ``value`` into the fixed-bucket histogram ``name``.
        The bucket layout is pinned at a series' first observation."""
        key = (name, _labelitems(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = [tuple(buckets), [0] * (len(buckets) + 1), 0.0, 0]
                self._hists[key] = h
            h[1][bisect.bisect_left(h[0], value)] += 1
            h[2] += value
            h[3] += 1

    def reset(self) -> None:
        """Drop every series (tests and per-run isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- exports ------------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-able dict of every series.  Histogram buckets are
        cumulative (Prometheus ``le`` semantics) so the snapshot and the
        text exposition describe the identical distribution."""
        with self._lock:
            counters = {_series(n, li): v for (n, li), v in self._counters.items()}
            gauges = {_series(n, li): v for (n, li), v in self._gauges.items()}
            hists = {}
            for (n, li), (buckets, counts, total, count) in self._hists.items():
                cum, acc = {}, 0
                for le, c in zip(buckets, counts):
                    acc += c
                    cum[_fmt(float(le))] = acc
                cum["+Inf"] = acc + counts[-1]
                hists[_series(n, li)] = {
                    "buckets": cum,
                    "sum": total,
                    "count": count,
                }
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    def prometheus_text(self) -> str:
        """Prometheus text exposition (``# TYPE`` headers, cumulative
        ``_bucket{le=...}`` series, ``_sum``/``_count``)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            # deep-copy histogram state INSIDE the lock: the dict values
            # are the live mutable [buckets, counts, sum, count] lists
            # observe() mutates, so reading them field-by-field after
            # release can render a bucket row from one observation and
            # the sum/count from another (the +Inf bucket would disagree
            # with _count in the same exposition)
            hists = [
                ((name, li), (buckets, list(counts), total, count))
                for (name, li), (buckets, counts, total, count)
                in sorted(self._hists.items())
            ]
        lines: list[str] = []
        seen: set[str] = set()
        for (name, li), v in counters:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{_series(name, li)} {_fmt(float(v))}")
        for (name, li), v in gauges:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{_series(name, li)} {_fmt(float(v))}")
        for (name, li), (buckets, counts, total, count) in hists:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} histogram")
            acc = 0
            for le, c in zip(buckets, counts):
                acc += c
                lines.append(
                    f"{_series(name + '_bucket', li + (('le', _fmt(float(le))),))} {acc}"
                )
            lines.append(
                f"{_series(name + '_bucket', li + (('le', '+Inf'),))} {acc + counts[-1]}"
            )
            lines.append(f"{_series(name + '_sum', li)} {_fmt(total)}")
            lines.append(f"{_series(name + '_count', li)} {count}")
        return "\n".join(lines) + "\n"


#: The process-wide registry every instrumentation site writes to.
REGISTRY = MetricsRegistry()


def observe_trace(
    trace,
    registry: MetricsRegistry | None = None,
    ceremony_id: str | None = None,
) -> None:
    """Feed one :class:`~dkg_tpu.utils.tracing.CeremonyTrace` (phases,
    sub-phases, protocol counters) into the registry.

    ``ceremony_id`` labels every emitted series so M concurrent
    ceremonies (dkg_tpu.service) keep distinct series instead of
    clobbering one another; ``None`` (single-tenant callers: bench,
    chaos_storm) keeps the unlabeled legacy series.

    For traces assembled OUTSIDE ``phase_span`` (e.g. bench.py builds one
    from child-process timings): spans that ran through ``phase_span``
    already observed ``dkg_phase_seconds`` live, so calling this on such
    a trace double-counts the phase histogram.
    """
    reg = registry if registry is not None else REGISTRY
    cid = ceremony_id
    for phase, seconds in trace.timings_s.items():
        reg.observe("dkg_phase_seconds", seconds, phase=phase, ceremony_id=cid)
    for phase, subs in trace.subtimings_s.items():
        for sub, seconds in subs.items():
            reg.observe(
                "dkg_subphase_seconds", seconds, phase=phase, sub=sub,
                ceremony_id=cid,
            )
    for counter, value in trace.counters.items():
        reg.inc(
            "dkg_ceremony_counter_total", value, counter=counter, ceremony_id=cid
        )
    reg.inc("dkg_ceremonies_total", ceremony_id=cid)


def observe_party_result(
    result,
    registry: MetricsRegistry | None = None,
    ceremony_id: str | None = None,
) -> None:
    """Feed one finished :class:`~dkg_tpu.net.party.PartyResult`'s
    transport/robustness counters into the registry (called by
    ``net.party`` at the end of every ``run_party``).  ``ceremony_id``
    labels every series when given (multi-tenant callers)."""
    reg = registry if registry is not None else REGISTRY
    cid = ceremony_id
    reg.inc(
        "dkg_parties_total",
        outcome="ok" if result.ok else "error",
        ceremony_id=cid,
    )
    reg.inc("dkg_party_quarantined_total", result.quarantined, ceremony_id=cid)
    reg.inc("dkg_party_round_timeouts_total", result.timeouts, ceremony_id=cid)
    reg.inc("dkg_party_rpc_retries_total", result.retries, ceremony_id=cid)
    reg.inc("dkg_party_resumes_total", result.resumes, ceremony_id=cid)
    reg.inc("dkg_wal_records_total", result.wal_records, ceremony_id=cid)
    reg.inc("dkg_wal_replayed_rounds_total", result.replayed_rounds, ceremony_id=cid)
