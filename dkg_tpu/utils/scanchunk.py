"""Sequential chunked mapping over a traced axis.

THE one implementation of the "k full chunks through lax.map + one
ragged tail call" pattern used by every memory-bounded loop in the
package (dealer-axis dealing, recipient-axis share delivery/verify,
Straus point-RLC columns).  The load-bearing invariant lives here:
chunks MUST run through a sequential ``lax.map`` — an unrolled Python
loop lets the TPU buffer assigner overlap the chunks' temp buffers,
defeating the memory bound entirely (round 4: ~196 overlapped 252 MB
point-RLC tables produced 26.5 G of fragmentation on 6 G of real
temps).  The ragged remainder becomes ONE smaller tail call — never a
fallback to the unchunked body, and never a collapse to a pathological
chunk=1 scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def map_chunked(total: int, chunk: int, call):
    """Run ``call(offset, width)`` over ``total`` items in ``chunk``-wide
    sequential pieces, concatenating outputs on their leading axis.

    ``call`` must return a pytree of arrays whose leading axis is
    ``width``; ``offset`` is a traced int32 for the full chunks (use
    ``lax.dynamic_slice_in_dim``) and a Python int for the tail.
    ``chunk`` <= 0 or >= ``total`` degenerates to one direct call.
    """
    if not chunk or chunk >= total:
        return call(0, total)
    k, rem = divmod(total, chunk)
    offs = jnp.arange(k, dtype=jnp.int32) * chunk
    outs = lax.map(lambda off: call(off, chunk), offs)
    outs = jax.tree_util.tree_map(
        lambda o: o.reshape((k * chunk,) + tuple(o.shape[2:])), outs
    )
    if rem:
        tail = call(k * chunk, rem)
        outs = jax.tree_util.tree_map(
            lambda o, t: jnp.concatenate([o, t], axis=0), outs, tail
        )
    return outs
