"""JAX runtime introspection: compile, cache, memory, and cost telemetry.

The obslog/metrics layer records what OUR code does; this module makes
the JAX runtime underneath it observable — the telemetry that separates
"the ceremony took 30 s" from "the ceremony took 0.8 s and sat behind a
29 s recompile".  Three legs, all feeding the existing process-wide
:data:`~dkg_tpu.utils.metrics.REGISTRY` and the ambient flight recorder:

* **compile telemetry** — ``jax.monitoring`` listeners (registered once
  per process; :func:`install` is idempotent) turn the runtime's
  compile-stage duration events into the ``jax_compile_seconds{stage=}``
  histogram and ``jax_compiles_total`` counter, and the persistent
  compile-cache events into ``jax_compile_cache_total{outcome=hit|miss}``
  — the counter that distinguishes a warm second process from one
  silently recompiling everything (ROADMAP item 5's cold-start work is
  unmeasurable without it).
* **memory accounting** — :func:`sample_memory` reads per-device
  ``memory_stats()`` watermarks into gauges (TPU; on CPU backends the
  runtime returns no stats and the live-``jax.Array`` byte total stands
  in) and :func:`maybe_sample` throttles that into phase boundaries via
  ``tracing.phase_span``.
* **cost probes** — :func:`probe_executable` runs XLA's
  ``cost_analysis()`` / ``memory_analysis()`` over a lowered or compiled
  hot executable (deal/verify/sign) so bench lines carry estimated
  FLOPs/bytes next to measured seconds, keyed by a shape fingerprint.

Everything is OFF until :func:`install` runs.  The ``DKG_TPU_RUNTIMEOBS``
knob (``on``/``off`` via envknobs) arms implicit installation (the
scheduler installs when ``on``) and is the operator kill-switch: ``off``
wins even over ``install(force=True)`` (which is how the benches opt in
without the knob).  ``jax.monitoring`` has no per-listener unregister,
so the listeners stay registered for the life of the process and every
callback gates on the module's ``enabled`` flag — :func:`uninstall` is
cheap and exact.

Redaction: listener payloads and probe records carry ONLY stage names,
durations, shapes/dtypes, and byte/FLOP counts — never key material —
and every obslog emission goes through ``ObsLog.emit``'s sanitizer.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque

from . import envknobs, metrics, obslog

#: Compile-duration buckets: DEFAULT_BUCKETS tops out at 60 s, but a
#: cold stacked-lane or BLS compile runs minutes (ROADMAP: 222 s FLEET
#: warmup, 83.8 s cold BLS verify) — the tail the histogram exists to
#: expose must not collapse into one overflow bucket.
COMPILE_BUCKETS = metrics.DEFAULT_BUCKETS + (120.0, 300.0, 600.0)

#: jax.monitoring point events -> (counter name, labels).
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_EVENT_COUNTERS = {
    _CACHE_HIT_EVENT: ("jax_compile_cache_total", {"outcome": "hit"}),
    _CACHE_MISS_EVENT: ("jax_compile_cache_total", {"outcome": "miss"}),
}

#: jax.monitoring duration events -> jax_compile_seconds stage label.
#: ``backend_compile`` is the terminal stage — but JAX wraps the whole
#: ``compile_or_get_cached`` in it, so it also fires on a persistent
#: cache HIT.  Each hit emits a cache_hits point event first, so the
#: pairing in _on_duration claims one hit per terminal event and only
#: unclaimed terminal events count as executables actually built
#: (jax_compiles_total).
_TERMINAL_STAGE = "backend_compile"
_DURATION_STAGES = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": _TERMINAL_STAGE,
    "/jax/compilation_cache/cache_retrieval_time_sec": "cache_retrieval",
}

#: Bounded ring of per-compile event records kept for snapshot()/traces.
_RING_CAPACITY = 512
#: snapshot() carries at most this many trailing compile events.
_SNAPSHOT_EVENTS = 32
#: maybe_sample() floor between device-memory samples: phase_span runs
#: in per-round loops and a live_arrays() walk per span is real cost.
_MIN_SAMPLE_GAP_S = 1.0


class _State:
    """Process-wide listener state.  One instance, module-lifetime; the
    lock guards the aggregates, never the registry (which has its own)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.listeners_registered = False  # jax.monitoring hookup done
        self.enabled = False               # callbacks forwarding
        self.registry = metrics.REGISTRY
        self.log: obslog.ObsLog | None = None
        self.seq = 0
        self.compiles = 0                  # terminal events minus cache hits
        self.unclaimed_cache_hits = 0      # hits awaiting their terminal event
        self.stage_agg: dict[str, list] = {}      # stage -> [count, sum_s]
        self.event_counts: dict[str, int] = {}    # raw event -> count
        self.compile_events: deque[dict] = deque(maxlen=_RING_CAPACITY)
        self.executables: dict[str, dict] = {}    # name -> probe record
        self.peak_device_bytes: int | None = None
        self.peak_live_bytes = 0
        self.last_sample_mono = 0.0


_STATE = _State()


def _knob() -> str | None:
    return envknobs.choice(
        "DKG_TPU_RUNTIMEOBS",
        ("on", "off"),
        "JAX runtime introspection listeners (compile/cache/memory telemetry)",
    )


def _emit(kind: str, **fields) -> None:
    """Into the ambient recorder when one is bound (party/scheduler
    threads), else the log install() was handed, else drop."""
    log = obslog.current()
    if log is None:
        log = _STATE.log
    if log is not None:
        log.emit(kind, **fields)


# -- jax.monitoring callbacks (registered once, gated on enabled) ------------


def _on_event(event: str, **kw) -> None:
    st = _STATE
    if not st.enabled:
        return
    mapped = _EVENT_COUNTERS.get(event)
    if mapped is None:
        return
    name, labels = mapped
    st.registry.inc(name, **labels)
    with st.lock:
        st.event_counts[event] = st.event_counts.get(event, 0) + 1
        if event == _CACHE_HIT_EVENT:
            st.unclaimed_cache_hits += 1


def _on_duration(event: str, duration_s: float, **kw) -> None:
    st = _STATE
    if not st.enabled:
        return
    stage = _DURATION_STAGES.get(event)
    if stage is None:
        return
    st.registry.observe(
        "jax_compile_seconds", duration_s, COMPILE_BUCKETS, stage=stage
    )
    now = time.time()
    built = False
    with st.lock:
        agg = st.stage_agg.setdefault(stage, [0, 0.0])
        agg[0] += 1
        agg[1] += duration_s
        st.seq += 1
        rec = {
            "seq": st.seq,
            "stage": stage,
            "dur_s": round(duration_s, 6),
            "ts": now,
        }
        if stage == _TERMINAL_STAGE:
            # a terminal event preceded by an unclaimed cache_hits point
            # event is a persistent-cache retrieval, not a build
            if st.unclaimed_cache_hits > 0:
                st.unclaimed_cache_hits -= 1
                rec["cached"] = True
            else:
                st.compiles += 1
                built = True
        st.compile_events.append(rec)
    if built:
        st.registry.inc("jax_compiles_total")
    # the span starts dur_s ago by construction: the runtime fires the
    # event at stage completion, so ts0/mono0 back-date it for the trace
    _emit(
        "jax_compile",
        stage=stage,
        dur_s=duration_s,
        ts0=now - duration_s,
        mono0=time.monotonic() - duration_s,
        seq=rec["seq"],
    )


# -- lifecycle ----------------------------------------------------------------


def install(
    registry: metrics.MetricsRegistry | None = None,
    log: obslog.ObsLog | None = None,
    force: bool = False,
) -> bool:
    """Arm the runtime listeners; returns True when telemetry is live.

    Idempotent: the ``jax.monitoring`` registration happens at most once
    per process (there is no per-listener unregister), repeat calls just
    retarget ``registry``/``log`` and re-enable.  Gating:

    * ``DKG_TPU_RUNTIMEOBS=off`` — hard off, even with ``force`` (the
      operator kill-switch);
    * ``DKG_TPU_RUNTIMEOBS=on`` — on;
    * unset — on only for ``force=True`` callers (benches, tests);
      implicit installers (the scheduler) stay off by default.
    """
    knob = _knob()
    if knob == "off" or (knob is None and not force):
        return False
    st = _STATE
    with st.lock:
        if registry is not None:
            st.registry = registry
        if log is not None:
            st.log = log
        if not st.listeners_registered:
            import jax.monitoring

            jax.monitoring.register_event_listener(_on_event)
            jax.monitoring.register_event_duration_secs_listener(_on_duration)
            st.listeners_registered = True
        st.enabled = True
    return True


def uninstall() -> None:
    """Disable the callbacks and drop caller-provided targets.  The
    listeners stay registered (no jax.monitoring unregister) but cost
    one flag check per event while disabled."""
    st = _STATE
    with st.lock:
        st.enabled = False
        st.registry = metrics.REGISTRY
        st.log = None


def enabled() -> bool:
    return _STATE.enabled


def _reset_for_tests() -> None:
    """Uninstall and clear every aggregate (tests only — production
    telemetry is cumulative by design)."""
    st = _STATE
    uninstall()
    with st.lock:
        st.seq = 0
        st.compiles = 0
        st.unclaimed_cache_hits = 0
        st.stage_agg.clear()
        st.event_counts.clear()
        st.compile_events.clear()
        st.executables.clear()
        st.peak_device_bytes = None
        st.peak_live_bytes = 0
        st.last_sample_mono = 0.0


# -- memory accounting --------------------------------------------------------


def sample_memory(
    registry: metrics.MetricsRegistry | None = None,
    phase: str | None = None,
) -> dict:
    """One device-memory sample into the watermark gauges.

    TPU/GPU runtimes report allocator stats per device
    (``bytes_in_use`` / ``peak_bytes_in_use`` -> the
    ``jax_device_bytes_in_use`` / ``jax_device_peak_bytes`` gauges); the
    CPU backend returns None, so the live-``jax.Array`` byte total
    (``jax_live_buffer_bytes``/``_count``) is always sampled as the
    backend-independent floor.  Returns the sample dict; also emits
    ``counter_sample`` events the Chrome-trace export renders as counter
    tracks.
    """
    import jax

    st = _STATE
    reg = registry if registry is not None else st.registry
    per_dev: dict[str, dict] = {}
    in_use_total = 0
    peak = 0
    have_stats = False
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — stats are best-effort per backend
            ms = None
        if not ms:
            continue
        have_stats = True
        biu = int(ms.get("bytes_in_use", 0))
        pk = int(ms.get("peak_bytes_in_use", biu))
        per_dev[str(d.id)] = {"bytes_in_use": biu, "peak_bytes_in_use": pk}
        reg.set_gauge("jax_device_bytes_in_use", biu, device=str(d.id))
        reg.set_gauge("jax_device_peak_bytes", pk, device=str(d.id))
        in_use_total += biu
        peak = max(peak, pk)
    live = jax.live_arrays()
    live_bytes = int(sum(int(getattr(x, "nbytes", 0) or 0) for x in live))
    reg.set_gauge("jax_live_buffer_bytes", live_bytes)
    reg.set_gauge("jax_live_buffer_count", len(live))
    out = {
        "devices": per_dev,
        "peak_device_bytes": peak if have_stats else None,
        "live_buffer_bytes": live_bytes,
        "live_buffer_count": len(live),
    }
    with st.lock:
        if have_stats:
            st.peak_device_bytes = max(st.peak_device_bytes or 0, peak)
        st.peak_live_bytes = max(st.peak_live_bytes, live_bytes)
    _emit(
        "counter_sample",
        counter="jax_live_buffer_bytes",
        value=live_bytes,
        phase=phase,
    )
    if have_stats:
        _emit(
            "counter_sample",
            counter="jax_device_bytes_in_use",
            value=in_use_total,
            phase=phase,
        )
    return out


def maybe_sample(phase: str | None = None) -> None:
    """Throttled :func:`sample_memory` for hot callers (phase
    boundaries, convoy completions): no-op unless installed, at most one
    sample per :data:`_MIN_SAMPLE_GAP_S`."""
    st = _STATE
    if not st.enabled:
        return
    now = time.monotonic()
    with st.lock:
        if now - st.last_sample_mono < _MIN_SAMPLE_GAP_S:
            return
        st.last_sample_mono = now
    try:
        sample_memory(phase=phase)
    except Exception:  # noqa: BLE001 — a telemetry sample must never
        pass  # fail the ceremony phase it rides on


# -- cost probes --------------------------------------------------------------


def _shape_strs(obj) -> list[str]:
    """``"float32[8,64]"``-style strings for an executable's input avals
    (shapes and dtypes only — never values)."""
    avals = getattr(obj, "in_avals", None)
    if avals is None:
        return []
    flat: list = []
    args, kwargs = (avals if isinstance(avals, tuple) and len(avals) == 2
                    else (avals, {}))
    flat.extend(args if isinstance(args, (list, tuple)) else [args])
    if isinstance(kwargs, dict):
        flat.extend(kwargs.values())
    out = []
    for a in flat:
        dt = getattr(a, "dtype", None)
        shape = getattr(a, "shape", None)
        if shape is None:
            out.append(str(a))
        else:
            dims = ",".join(str(d) for d in shape)
            out.append(f"{getattr(dt, 'name', dt)}[{dims}]")
    return out


def probe_executable(name: str, obj, registry=None) -> dict:
    """XLA cost/memory analysis of a ``jax.stages`` Lowered or Compiled
    object, recorded into the executable registry and the
    ``jax_executable_*`` gauges.

    ``Lowered.cost_analysis()`` needs no backend compile, so probing a
    hot function is ~trace cost: ``probe_executable("verify",
    ce.verify_batch.lower(cfg, e, s, r, rho, bits, gt, ht))``.  A
    Compiled object additionally yields ``memory_analysis()`` byte
    footprints.  Works with telemetry disabled (the benches probe
    unconditionally); the record lands in :func:`snapshot` either way.
    """
    import jax

    st = _STATE
    reg = registry if registry is not None else st.registry
    info: dict = {"name": str(name)}
    shapes = _shape_strs(obj)
    if shapes:
        info["in_shapes"] = shapes
    try:
        info["backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001 — uninitialised backend is legal here
        pass
    h = hashlib.blake2b(digest_size=6)
    h.update(str(name).encode())
    for s in shapes:
        h.update(b"|" + s.encode())
    info["fingerprint"] = h.hexdigest()
    try:
        ca = obj.cost_analysis()
    except Exception:  # noqa: BLE001 — not every executable has costs
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = ca.get("flops")
        if isinstance(flops, (int, float)) and flops >= 0:
            info["flops"] = float(flops)
            reg.set_gauge("jax_executable_flops", float(flops), executable=str(name))
        nbytes = ca.get("bytes accessed")
        if isinstance(nbytes, (int, float)) and nbytes >= 0:
            info["bytes_accessed"] = float(nbytes)
            reg.set_gauge(
                "jax_executable_bytes_accessed", float(nbytes), executable=str(name)
            )
    mem_fn = getattr(obj, "memory_analysis", None)
    if callable(mem_fn):
        try:
            mem = mem_fn()
        except Exception:  # noqa: BLE001 — AOT surface varies per backend
            mem = None
        for src, dst in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            v = getattr(mem, src, None)
            if isinstance(v, int):
                info[dst] = v
    with st.lock:
        st.executables[str(name)] = info
    _emit("jax_cost_probe", **info)
    return info


def probe_jitted(name: str, fn, *args, registry=None, **kwargs) -> dict | None:
    """Lower a jitted ``fn`` at the given arguments and probe it; None
    when lowering fails (a probe must never fail the bench it rides
    in)."""
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception:  # noqa: BLE001 — best-effort decoration
        return None
    return probe_executable(name, lowered, registry=registry)


# -- snapshot -----------------------------------------------------------------


def snapshot() -> dict:
    """The ``runtime`` block bench/fleet/sign rounds embed: compile and
    cache totals, per-stage aggregates, memory peaks, the executable
    registry, and the trailing compile events.  Registry-independent
    (reads this module's own aggregates), so it composes with
    ``REGISTRY.reset()`` between bench legs."""
    st = _STATE
    with st.lock:
        term = st.stage_agg.get(_TERMINAL_STAGE, (0, 0.0))
        out = {
            "enabled": st.enabled,
            "compiles_total": int(st.compiles),
            "compile_seconds_sum": round(float(term[1]), 6),
            "cache_hits": st.event_counts.get(_CACHE_HIT_EVENT, 0),
            "cache_misses": st.event_counts.get(_CACHE_MISS_EVENT, 0),
            "stages": {
                k: {"count": int(v[0]), "sum_s": round(float(v[1]), 6)}
                for k, v in sorted(st.stage_agg.items())
            },
            "peak_device_bytes": st.peak_device_bytes,
            "peak_live_buffer_bytes": st.peak_live_bytes,
            "executables": {k: dict(v) for k, v in st.executables.items()},
            "events": [dict(e) for e in st.compile_events][-_SNAPSHOT_EVENTS:],
        }
    return out
