"""Pallas TPU kernels for the hot ops (XLA-path twins live in
fields/ and groups/; these are the hand-tiled Mosaic versions)."""

from . import pallas_field, pallas_point  # noqa: F401
