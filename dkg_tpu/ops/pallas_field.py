"""Pallas TPU kernel: batched modular multiply (Barrett) on 16-bit limbs.

The single hottest primitive in the framework: every ladder step of
every scalar multiplication (groups/device.py) bottoms out in
``fields.device.mul`` — a schoolbook limb product plus Barrett
reduction.  The XLA path materialises the (L, L) product grid and an
antidiagonal contraction per multiply; this kernel instead keeps one
(L, BLOCK) tile of each operand resident in VMEM and walks the
schoolbook columns with fully unrolled VPU multiply-accumulates, with
the batch axis riding the 128-wide lane dimension.

Layout contract: limbs on the sublane axis, batch on the lane axis —
the transpose of the (batch, L) layout used elsewhere; the ``mod_mul``
wrapper handles the (cheap, fused) transposes and pads the batch to the
block size.

All constants (p, the Barrett mu, their extended forms) are baked into
the kernel as Python-int immediates, so each field gets its own
specialised program — mirroring how the reference's dalek backend bakes
the curve25519 prime into field ops at compile time (reference:
src/groups.rs:11-53 delegating to curve25519-dalek's fixed-prime field).

Correctness invariants are the same as fields/device.py: limbs < 2**16
in uint32 lanes, column accumulators <= 2*L terms of < 2**16 products'
halves, Barrett remainder < 3p fixed by two conditional subtractions.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.spec import FieldSpec
from ..utils import metrics

BLOCK = 128  # lane width: one VPU register row of batch elements

try:  # pallas import is deferred-safe: CPU-only environments still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _mul_columns(rows_a, rows_b):
    """Schoolbook product columns of two unrolled limb-row lists.

    rows_* are Python lists of (1, BLOCK) uint32 tiles with values
    < 2**16.  Returns 2L unnormalised column tiles: col[c] =
    sum_{i+j=c} lo(a_i b_j) + sum_{i+j=c-1} hi(a_i b_j) < 2**21·2.
    """
    la, lb = len(rows_a), len(rows_b)
    cols = [None] * (la + lb)
    for i in range(la):
        for j in range(lb):
            prod = rows_a[i] * rows_b[j]  # 16x16 -> 32, exact in uint32
            lo = prod & jnp.uint32(0xFFFF)
            hi = prod >> 16
            c = i + j
            cols[c] = lo if cols[c] is None else cols[c] + lo
            cols[c + 1] = hi if cols[c + 1] is None else cols[c + 1] + hi
    return [jnp.zeros_like(rows_a[0]) if c is None else c for c in cols]


def _normalize(cols):
    """Carry-propagate column tiles into 16-bit limb tiles (same length)."""
    out = []
    carry = jnp.zeros_like(cols[0])
    for c in cols:
        s = c + carry
        out.append(s & jnp.uint32(0xFFFF))
        carry = s >> 16
    return out


def _sub_with_borrow(rows_x, rows_y):
    """Limbwise x - y with borrow chain; returns (rows, borrow_tile)."""
    out = []
    borrow = jnp.zeros_like(rows_x[0])
    for xi, yi in zip(rows_x, rows_y):
        s = xi - yi - borrow  # uint32 wraparound encodes the sign
        out.append(s & jnp.uint32(0xFFFF))
        borrow = s >> 31
    return out, borrow


def _cond_sub(rows_x, const_limbs):
    """Branchless x - m if x >= m else x, m a Python-int limb list."""
    rows_m = [jnp.full_like(rows_x[0], np.uint32(m)) for m in const_limbs]
    diff, borrow = _sub_with_borrow(rows_x, rows_m)
    keep = borrow != 0
    return [jnp.where(keep, xi, di) for xi, di in zip(rows_x, diff)]


def rows_mul_dispatch(fs: FieldSpec, interpret: bool = False) -> str:
    """Which multiply core the fused kernels chain: ``"mxu"`` (the
    fused multiply-reduce of ops/pallas_mxu.py, schoolbook columns
    folded through one exact f32 matmul) or ``"barrett"`` (the VPU
    schoolbook + Barrett core below).  Keyed on the same DKG_TPU_MUL
    knob as the XLA-leg dispatch (fields.device.mul_dispatch_mode), but
    in-kernel ``auto`` prefers the MXU core wherever the field admits
    ``fs.mulred`` — inside a kernel the operands are already
    VMEM-resident rows, so the matmul fold wins on exactly the backend
    (Mosaic) where the XLA auto rule keeps classic.  Exception:
    ``auto`` under INTERPRET mode keeps Barrett — the one-hot gather
    matmuls make the interpret lowering of multi-multiply kernels
    pathologically slow to XLA-compile on CPU (minutes for one point
    add); DKG_TPU_MUL=gemm still forces the MXU core there, which is
    how the slow-tier parity tests cover it.  Both cores are bit-exact;
    resolved at kernel trace time."""
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_MUL",
        ("auto", "gemm", "classic"),
        "fd.mul formulation: fused GEMM multiply-reduce vs classic",
    )
    if env == "classic":
        return "barrett"
    if env == "gemm":
        if fs.mulred is None:
            raise ValueError(f"{fs.name} does not admit the fused MXU mul")
        return "mxu"
    if fs.mulred is None or interpret:
        return "barrett"
    return "mxu"


#: trace-time stack of (fs, foldm_t, q2) loaded from kernel operands —
#: kernel tracing is synchronous, so a plain list is safe
_MXU_CONSTS: list = []


@contextlib.contextmanager
def rows_mul_context(fs: FieldSpec, const_refs):
    """Trace-time context: inside the block, ``mod_mul_rows`` for
    ``fs`` routes through the MXU fused core of ops/pallas_mxu.py.

    ``const_refs`` are the two kernel operand refs appended by
    :func:`mxu_operands` (empty when the Barrett core is selected —
    then this is a no-op).  Pallas kernels cannot capture array
    constants, so the fold matrices must flow in as operands and down
    to every chained multiply; this context threads them through the
    point-op row helpers without widening every signature.
    """
    if not const_refs:
        yield
        return
    fm_ref, q2_ref = const_refs
    _MXU_CONSTS.append((fs, fm_ref[...], q2_ref[...]))
    try:
        yield
    finally:
        _MXU_CONSTS.pop()


def mxu_operands(fs: FieldSpec, interpret: bool = False):
    """(arrays, BlockSpecs) a kernel builder appends to its operands to
    enable the MXU multiply core for ``fs`` — both empty when
    :func:`rows_mul_dispatch` selects the Barrett core, so call sites
    can splat them unconditionally."""
    if not HAVE_PALLAS or rows_mul_dispatch(fs, interpret) != "mxu":
        return [], []
    from .pallas_mxu import mxu_const_arrays

    fm_np, q2_np = mxu_const_arrays(fs)
    specs = [
        pl.BlockSpec(fm_np.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(q2_np.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    return [jnp.asarray(fm_np), jnp.asarray(q2_np)], specs


def mod_mul_rows(fs: FieldSpec, rows_a, rows_b):
    """Modular multiply on unrolled limb-row lists: L tiles in, L out.

    The reusable core of the kernel — the fused point-op kernels
    (ops/pallas_point.py) chain many of these without leaving VMEM.
    Routes through the MXU fused multiply-reduce core when the
    enclosing kernel provided the fold matrices via
    :func:`rows_mul_context`; the Barrett VPU core otherwise.
    """
    for cfs, foldm_t, q2 in reversed(_MXU_CONSTS):
        if cfs is fs:
            from .pallas_mxu import mxu_mul_rows

            return mxu_mul_rows(fs, rows_a, rows_b, foldm_t=foldm_t, q2=q2)
    return _barrett_mul_rows(fs, rows_a, rows_b)


def _barrett_mul_rows(fs: FieldSpec, rows_a, rows_b):
    """The VPU Barrett multiply core (HAC 14.42), base 2**16 — mirrors
    fields/device.py.  The fallback for fields without ``fs.mulred``
    and the DKG_TPU_MUL=classic leg."""
    L = fs.limbs
    mu = [int(v) for v in fs.barrett_mu]  # (L+1,) Python ints
    p_ext = [int(v) for v in fs.p_limbs_ext]  # (L+1,)
    x = _normalize(_mul_columns(rows_a, rows_b))  # 2L limb tiles
    q1 = x[L - 1 :]  # L+1 tiles
    mu_rows = [jnp.full_like(x[0], np.uint32(m)) for m in mu]
    q2 = _normalize(_mul_columns(q1, mu_rows))
    q3 = q2[L + 1 :]  # L+1 tiles
    pe_rows = [jnp.full_like(x[0], np.uint32(m)) for m in p_ext]
    r2 = _normalize(_mul_columns(q3, pe_rows))[: L + 1]
    r1 = x[: L + 1]
    r, _ = _sub_with_borrow(r1, r2)  # mod b**(L+1): r in [0, 3p)
    r = _cond_sub(r, p_ext)
    r = _cond_sub(r, p_ext)
    return r[:L]


def mod_add_rows(fs: FieldSpec, rows_a, rows_b):
    """Modular add on limb-row lists (L tiles in, L out)."""
    p_ext = [int(v) for v in fs.p_limbs_ext]
    # limb sums < 2**17; one extra carry limb needed before cond_sub
    carry = jnp.zeros_like(rows_a[0])
    out = []
    for a, b in zip(rows_a, rows_b):
        t = a + b + carry
        out.append(t & jnp.uint32(0xFFFF))
        carry = t >> 16
    out.append(carry)  # L+1 tiles
    out = _cond_sub(out, p_ext)
    return out[: fs.limbs]


def mod_sub_rows(fs: FieldSpec, rows_a, rows_b):
    """Modular subtract on limb-row lists: (a + p) - b, then reduce."""
    p_limbs = [int(v) for v in fs.p_limbs]
    p_ext = [int(v) for v in fs.p_limbs_ext]
    carry = jnp.zeros_like(rows_a[0])
    ap = []
    for a, p in zip(rows_a, p_limbs):
        t = a + jnp.uint32(p) + carry
        ap.append(t & jnp.uint32(0xFFFF))
        carry = t >> 16
    ap.append(carry)  # L+1 tiles, = a + p < 2p < b**(L+1)
    b_ext = list(rows_b) + [jnp.zeros_like(rows_b[0])]
    d, _ = _sub_with_borrow(ap, b_ext)  # in [0, 2p)
    d = _cond_sub(d, p_ext)
    return d[: fs.limbs]


def _make_kernel(fs: FieldSpec):
    L = fs.limbs

    def kernel(a_ref, b_ref, *rest):
        out_ref = rest[-1]
        rows_a = [a_ref[i : i + 1, :] for i in range(L)]
        rows_b = [b_ref[i : i + 1, :] for i in range(L)]
        with rows_mul_context(fs, rest[:-1]):
            r = mod_mul_rows(fs, rows_a, rows_b)
        for i in range(L):
            out_ref[i : i + 1, :] = r[i]

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 3))
def _mod_mul_tiles(fs: FieldSpec, a_t: jax.Array, b_t: jax.Array, interpret: bool):
    """(L, B) x (L, B) -> (L, B), B a multiple of BLOCK."""
    L, B = a_t.shape
    extra, extra_specs = mxu_operands(fs, interpret)
    return pl.pallas_call(
        _make_kernel(fs),
        grid=(B // BLOCK,),
        in_specs=[
            pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
        ]
        + extra_specs,
        out_specs=pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
        interpret=interpret,
    )(a_t, b_t, *extra)


def _make_madd_kernel(fs: FieldSpec):
    L = fs.limbs

    def kernel(a_ref, b_ref, c_ref, *rest):
        out_ref = rest[-1]
        rows_a = [a_ref[i : i + 1, :] for i in range(L)]
        rows_b = [b_ref[i : i + 1, :] for i in range(L)]
        rows_c = [c_ref[i : i + 1, :] for i in range(L)]
        with rows_mul_context(fs, rest[:-1]):
            r = mod_add_rows(fs, mod_mul_rows(fs, rows_a, rows_b), rows_c)
        for i in range(L):
            out_ref[i : i + 1, :] = r[i]

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 4))
def _mod_madd_tiles(fs: FieldSpec, a_t, b_t, c_t, interpret: bool):
    """(L, B) x3 -> (L, B): (a*b + c) mod p, one fused launch."""
    L, B = a_t.shape
    spec = pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM)
    extra, extra_specs = mxu_operands(fs, interpret)
    return pl.pallas_call(
        _make_madd_kernel(fs),
        grid=(B // BLOCK,),
        in_specs=[spec, spec, spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
        interpret=interpret,
    )(a_t, b_t, c_t, *extra)


def _want_interpret() -> bool:
    """Mosaic only exists on real TPU backends; interpret elsewhere."""
    from ..fields import device as fd

    return not fd._on_tpu()


def mod_mul(fs: FieldSpec, a: jax.Array, b: jax.Array, *, interpret: bool | None = None) -> jax.Array:
    """Batched (a * b) mod p via the Pallas kernel.

    a, b: (..., L) uint32 limb arrays (the framework-wide layout); the
    batch is flattened, padded to a BLOCK multiple, and mapped onto the
    lane axis.  Drop-in parity with ``fields.device.mul``.
    """
    if not HAVE_PALLAS:  # pragma: no cover
        from ..fields import device as fd

        return fd.mul(fs, a, b)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="mod_mul")
    a = jnp.asarray(a, jnp.uint32)
    b = jnp.asarray(b, jnp.uint32)
    a, b = jnp.broadcast_arrays(a, b)
    batch = a.shape[:-1]
    n = 1
    for d in batch:
        n *= int(d)
    m = max(BLOCK, ((n + BLOCK - 1) // BLOCK) * BLOCK)
    af = jnp.reshape(a, (n, fs.limbs))
    bf = jnp.reshape(b, (n, fs.limbs))
    if m != n:
        pad = [(0, m - n), (0, 0)]
        af = jnp.pad(af, pad)
        bf = jnp.pad(bf, pad)
    interp = _want_interpret() if interpret is None else interpret
    out_t = _mod_mul_tiles(fs, af.T, bf.T, interp)
    return jnp.reshape(out_t.T[:n], batch + (fs.limbs,))


def mod_madd(
    fs: FieldSpec,
    a: jax.Array,
    b: jax.Array,
    c: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """Batched (a * b + c) mod p in ONE fused kernel launch.

    The Horner-step primitive (acc <- acc·x + coeff) behind
    poly.device.eval_many — the reference's per-recipient evaluation
    loop (reference: src/dkg/committee.rs:163-186 ->
    src/polynomial.rs:68-74) collapsed to one launch per coefficient.
    """
    if not HAVE_PALLAS:  # pragma: no cover
        from ..fields import device as fd

        return fd.add(fs, fd.mul(fs, a, b), c)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="mod_madd")
    a, b, c = jnp.broadcast_arrays(
        jnp.asarray(a, jnp.uint32), jnp.asarray(b, jnp.uint32), jnp.asarray(c, jnp.uint32)
    )
    batch = a.shape[:-1]
    n = 1
    for d in batch:
        n *= int(d)
    m = max(BLOCK, ((n + BLOCK - 1) // BLOCK) * BLOCK)
    flat = [jnp.reshape(x, (n, fs.limbs)) for x in (a, b, c)]
    if m != n:
        flat = [jnp.pad(x, [(0, m - n), (0, 0)]) for x in flat]
    interp = _want_interpret() if interpret is None else interpret
    out_t = _mod_madd_tiles(fs, flat[0].T, flat[1].T, flat[2].T, interp)
    return jnp.reshape(out_t.T[:n], batch + (fs.limbs,))
