"""Fused Pallas point-operation kernels (Edwards AND Weierstrass a=0).

The scalar-mult ladder's hot loop is point add/double — each one is
~7-14 Barrett multiplies plus adds/subs.  The XLA path materialises
every intermediate field element in HBM between fused regions; these
kernels keep the WHOLE point operation (and multi-op sequences: the
n-double window step, the full small-scalar ladder) in VMEM:
coordinates ride the sublane axis as C·L limb rows, the batch rides
the 128-wide lane axis, and the multiplies chain through
ops.pallas_field.mod_mul_rows without ever leaving the core.

Curve coverage matches groups/device.py: twisted Edwards a=-1
(add-2008-hwcd-3 unified add, dbl-2008-hwcd doubling — complete for
ristretto255) and short Weierstrass a=0 (Renes-Costello-Batina 2015
algorithms 7 & 9 complete formulas — secp256k1, BLS12-381 G1).  These
mirror the role of dalek's backend in the reference (reference:
src/groups.rs:55-90 delegating point arithmetic to curve25519-dalek;
MSM seam src/traits.rs:234-237).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..groups.device import CurveSpec
from ..utils import metrics
from .pallas_field import (
    BLOCK,
    mod_add_rows,
    mod_mul_rows,
    mod_sub_rows,
    mxu_operands,
    rows_mul_context,
)

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _const_rows(fs, value: int, like):
    from ..fields.spec import int_to_limbs

    return [jnp.full_like(like, np.uint32(v)) for v in int_to_limbs(value % fs.modulus, fs.limbs)]


def _ed_add_rows(cs: CurveSpec, p_rows, q_rows):
    """Unified extended Edwards add on 4 coordinate row-lists each."""
    f = cs.field
    x1, y1, z1, t1 = p_rows
    x2, y2, z2, t2 = q_rows
    a = mod_mul_rows(f, mod_sub_rows(f, y1, x1), mod_sub_rows(f, y2, x2))
    b = mod_mul_rows(f, mod_add_rows(f, y1, x1), mod_add_rows(f, y2, x2))
    d2 = _const_rows(f, cs.const, x1[0])
    c = mod_mul_rows(f, mod_mul_rows(f, t1, d2), t2)
    d = mod_mul_rows(f, mod_add_rows(f, z1, z1), z2)
    e = mod_sub_rows(f, b, a)
    ff = mod_sub_rows(f, d, c)
    g = mod_add_rows(f, d, c)
    h = mod_add_rows(f, b, a)
    return (
        mod_mul_rows(f, e, ff),
        mod_mul_rows(f, g, h),
        mod_mul_rows(f, ff, g),
        mod_mul_rows(f, e, h),
    )


def _ed_double_rows(cs: CurveSpec, p_rows):
    """Dedicated doubling (dbl-2008-hwcd), a = -1."""
    f = cs.field
    x1, y1, z1, _ = p_rows
    a = mod_mul_rows(f, x1, x1)
    b = mod_mul_rows(f, y1, y1)
    zz = mod_mul_rows(f, z1, z1)
    c = mod_add_rows(f, zz, zz)
    zero = [jnp.zeros_like(x1[0]) for _ in range(f.limbs)]
    d = mod_sub_rows(f, zero, a)  # a = -1 => D = -A
    xy = mod_add_rows(f, x1, y1)
    e = mod_sub_rows(f, mod_sub_rows(f, mod_mul_rows(f, xy, xy), a), b)
    g = mod_add_rows(f, d, b)
    h = mod_sub_rows(f, d, b)
    ff = mod_sub_rows(f, g, c)
    return (
        mod_mul_rows(f, e, ff),
        mod_mul_rows(f, g, h),
        mod_mul_rows(f, ff, g),
        mod_mul_rows(f, e, h),
    )


def _ws_add_rows(cs: CurveSpec, p_rows, q_rows):
    """Complete projective add for y^2 = x^3 + b (RCB15 algorithm 7),
    the row-list twin of groups/device.py _ws_add."""
    f = cs.field
    x1, y1, z1 = p_rows
    x2, y2, z2 = q_rows
    b3 = _const_rows(f, cs.const, x1[0])
    t0 = mod_mul_rows(f, x1, x2)
    t1 = mod_mul_rows(f, y1, y2)
    t2 = mod_mul_rows(f, z1, z2)
    t3 = mod_mul_rows(f, mod_add_rows(f, x1, y1), mod_add_rows(f, x2, y2))
    t3 = mod_sub_rows(f, mod_sub_rows(f, t3, t0), t1)
    t4 = mod_mul_rows(f, mod_add_rows(f, y1, z1), mod_add_rows(f, y2, z2))
    t4 = mod_sub_rows(f, mod_sub_rows(f, t4, t1), t2)
    xz = mod_mul_rows(f, mod_add_rows(f, x1, z1), mod_add_rows(f, x2, z2))
    y3 = mod_sub_rows(f, mod_sub_rows(f, xz, t0), t2)
    x3 = mod_add_rows(f, mod_add_rows(f, t0, t0), t0)
    t2 = mod_mul_rows(f, b3, t2)
    z3 = mod_add_rows(f, t1, t2)
    t1 = mod_sub_rows(f, t1, t2)
    y3 = mod_mul_rows(f, b3, y3)
    x_out = mod_sub_rows(f, mod_mul_rows(f, t3, t1), mod_mul_rows(f, t4, y3))
    y_out = mod_add_rows(f, mod_mul_rows(f, t1, z3), mod_mul_rows(f, x3, y3))
    z_out = mod_add_rows(f, mod_mul_rows(f, z3, t4), mod_mul_rows(f, x3, t3))
    return (x_out, y_out, z_out)


def _ws_double_rows(cs: CurveSpec, p_rows):
    """Complete doubling for y^2 = x^3 + b (RCB15 algorithm 9)."""
    f = cs.field
    x, y, z = p_rows
    b3 = _const_rows(f, cs.const, x[0])
    t0 = mod_mul_rows(f, y, y)
    z3 = mod_add_rows(f, t0, t0)
    z3 = mod_add_rows(f, z3, z3)
    z3 = mod_add_rows(f, z3, z3)
    t1 = mod_mul_rows(f, y, z)
    t2 = mod_mul_rows(f, b3, mod_mul_rows(f, z, z))
    x3 = mod_mul_rows(f, t2, z3)
    y3 = mod_add_rows(f, t0, t2)
    z3 = mod_mul_rows(f, t1, z3)
    t1 = mod_add_rows(f, t2, t2)
    t2 = mod_add_rows(f, t1, t2)
    t0 = mod_sub_rows(f, t0, t2)
    y3 = mod_add_rows(f, x3, mod_mul_rows(f, t0, y3))
    x3 = mod_mul_rows(f, t0, mod_mul_rows(f, x, y))
    x3 = mod_add_rows(f, x3, x3)
    return (x3, y3, z3)


def _ed_madd_rows(cs: CurveSpec, p_rows, q_rows):
    """Mixed unified Edwards add: q affine (Z2 == 1) — the 2*Z1*Z2
    multiply collapses to 2*Z1 (see groups/device._ed_madd)."""
    f = cs.field
    x1, y1, z1, t1 = p_rows
    x2, y2, _, t2 = q_rows
    a = mod_mul_rows(f, mod_sub_rows(f, y1, x1), mod_sub_rows(f, y2, x2))
    b = mod_mul_rows(f, mod_add_rows(f, y1, x1), mod_add_rows(f, y2, x2))
    d2 = _const_rows(f, cs.const, x1[0])
    c = mod_mul_rows(f, mod_mul_rows(f, t1, d2), t2)
    d = mod_add_rows(f, z1, z1)
    e = mod_sub_rows(f, b, a)
    ff = mod_sub_rows(f, d, c)
    g = mod_add_rows(f, d, c)
    h = mod_add_rows(f, b, a)
    return (
        mod_mul_rows(f, e, ff),
        mod_mul_rows(f, g, h),
        mod_mul_rows(f, ff, g),
        mod_mul_rows(f, e, h),
    )


def _ws_madd_rows(cs: CurveSpec, p_rows, q_rows):
    """Mixed addition, q affine (RCB15 algorithm 8) — NOT valid for
    q = identity; callers mask zero digits (see groups/device._ws_madd)."""
    f = cs.field
    x1, y1, z1 = p_rows
    x2, y2, _ = q_rows
    b3 = _const_rows(f, cs.const, x1[0])
    t0 = mod_mul_rows(f, x1, x2)
    t1 = mod_mul_rows(f, y1, y2)
    t3 = mod_mul_rows(f, mod_add_rows(f, x1, y1), mod_add_rows(f, x2, y2))
    t3 = mod_sub_rows(f, mod_sub_rows(f, t3, t0), t1)
    t4 = mod_add_rows(f, mod_mul_rows(f, y2, z1), y1)
    y3 = mod_add_rows(f, mod_mul_rows(f, x2, z1), x1)
    x3 = mod_add_rows(f, mod_add_rows(f, t0, t0), t0)
    t2 = mod_mul_rows(f, b3, z1)
    z3 = mod_add_rows(f, t1, t2)
    t1 = mod_sub_rows(f, t1, t2)
    y3 = mod_mul_rows(f, b3, y3)
    x_out = mod_sub_rows(f, mod_mul_rows(f, t3, t1), mod_mul_rows(f, t4, y3))
    y_out = mod_add_rows(f, mod_mul_rows(f, t1, z3), mod_mul_rows(f, x3, y3))
    z_out = mod_add_rows(f, mod_mul_rows(f, z3, t4), mod_mul_rows(f, x3, t3))
    return (x_out, y_out, z_out)


def _madd_rows(cs: CurveSpec, p_rows, q_rows):
    if cs.kind == "edwards":
        return _ed_madd_rows(cs, p_rows, q_rows)
    return _ws_madd_rows(cs, p_rows, q_rows)


def _add_rows(cs: CurveSpec, p_rows, q_rows):
    if cs.kind == "edwards":
        return _ed_add_rows(cs, p_rows, q_rows)
    return _ws_add_rows(cs, p_rows, q_rows)


def _double_rows(cs: CurveSpec, p_rows):
    if cs.kind == "edwards":
        return _ed_double_rows(cs, p_rows)
    return _ws_double_rows(cs, p_rows)


def _identity_rows(cs: CurveSpec, like):
    """Constant identity point as coordinate row-lists."""
    f = cs.field
    zero = [jnp.zeros_like(like) for _ in range(f.limbs)]
    one = [jnp.full_like(like, np.uint32(1))] + [
        jnp.zeros_like(like) for _ in range(f.limbs - 1)
    ]
    if cs.kind == "edwards":  # (0, 1, 1, 0)
        return (zero, one, list(one), list(zero))
    return (zero, one, list(zero))  # (0, 1, 0)


def _select_rows(bit, a_rows, b_rows):
    """Per-lane select between two point row-lists; bit a (1, B) tile."""
    keep = bit != 0
    return tuple(
        [jnp.where(keep, ai, bi) for ai, bi in zip(ac, bc)]
        for ac, bc in zip(a_rows, b_rows)
    )


def _rows_in(ref, L: int, ncoords: int = 4):
    """(C·L, B) ref -> C coordinate row-lists of L tiles each."""
    return tuple(
        [ref[c * L + i : c * L + i + 1, :] for i in range(L)]
        for c in range(ncoords)
    )


def _rows_out(ref, rows, L: int):
    for c in range(len(rows)):
        for i in range(L):
            ref[c * L + i : c * L + i + 1, :] = rows[c][i]


def _point_spec(cs: CurveSpec):
    L = cs.field.limbs
    return pl.BlockSpec(
        (cs.ncoords * L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM
    )


@functools.partial(jax.jit, static_argnums=(0, 3))
def _add_call(cs: CurveSpec, p_t: jax.Array, q_t: jax.Array, interpret: bool):
    L, C = cs.field.limbs, cs.ncoords

    def kernel(p_ref, q_ref, *rest):
        with rows_mul_context(cs.field, rest[:-1]):
            _rows_out(
                rest[-1], _add_rows(cs, _rows_in(p_ref, L, C), _rows_in(q_ref, L, C)), L
            )

    B = p_t.shape[-1]
    spec = _point_spec(cs)
    extra, extra_specs = mxu_operands(cs.field, interpret)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((C * L, B), jnp.uint32),
        interpret=interpret,
    )(p_t, q_t, *extra)


@functools.partial(jax.jit, static_argnums=(0, 3))
def _madd_call(cs: CurveSpec, p_t: jax.Array, q_t: jax.Array, interpret: bool):
    L, C = cs.field.limbs, cs.ncoords

    def kernel(p_ref, q_ref, *rest):
        with rows_mul_context(cs.field, rest[:-1]):
            _rows_out(
                rest[-1], _madd_rows(cs, _rows_in(p_ref, L, C), _rows_in(q_ref, L, C)), L
            )

    B = p_t.shape[-1]
    spec = _point_spec(cs)
    extra, extra_specs = mxu_operands(cs.field, interpret)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((C * L, B), jnp.uint32),
        interpret=interpret,
    )(p_t, q_t, *extra)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def _double_call(cs: CurveSpec, p_t: jax.Array, n_doubles: int, interpret: bool):
    L, C = cs.field.limbs, cs.ncoords

    def kernel(p_ref, *rest):
        with rows_mul_context(cs.field, rest[:-1]):
            rows = _rows_in(p_ref, L, C)
            for _ in range(n_doubles):
                rows = _double_rows(cs, rows)
            _rows_out(rest[-1], rows, L)

    B = p_t.shape[-1]
    spec = _point_spec(cs)
    extra, extra_specs = mxu_operands(cs.field, interpret)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((C * L, B), jnp.uint32),
        interpret=interpret,
    )(p_t, *extra)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def _window_call(cs: CurveSpec, acc_t: jax.Array, n_doubles: int, interpret: bool, entry_t: jax.Array):
    """The fused ladder window step: n_doubles doublings then one add,
    all inside one kernel launch — the HBM-traffic killer for
    scalar_mul's scan body (groups/device.py _scalar_mul_core)."""
    L, C = cs.field.limbs, cs.ncoords

    def kernel(acc_ref, entry_ref, *rest):
        with rows_mul_context(cs.field, rest[:-1]):
            rows = _rows_in(acc_ref, L, C)
            for _ in range(n_doubles):
                rows = _double_rows(cs, rows)
            rows = _add_rows(cs, rows, _rows_in(entry_ref, L, C))
            _rows_out(rest[-1], rows, L)

    B = acc_t.shape[-1]
    spec = _point_spec(cs)
    extra, extra_specs = mxu_operands(cs.field, interpret)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((C * L, B), jnp.uint32),
        interpret=interpret,
    )(acc_t, entry_t, *extra)


@functools.partial(jax.jit, static_argnums=(0, 3, 4))
def _ladder_call(
    cs: CurveSpec,
    p_t: jax.Array,
    add_t: jax.Array,
    nbits: int,
    interpret: bool,
    bits_t: jax.Array,
):
    """out = x·P + A in ONE launch, x given per-lane as MSB-first bits.

    The whole double-and-select-add ladder (the Horner step of
    eval_point_poly, reference committee.rs:292-296's sum x^l E_l) runs
    VMEM-resident; the loop body is traced once via fori_loop so kernel
    code size stays ~2 point-ops regardless of nbits.
    """
    L, C = cs.field.limbs, cs.ncoords

    def kernel(p_ref, add_ref, bits_ref, *rest):
        p_rows = _rows_in(p_ref, L, C)

        def body(i, m_arr):
            rows = _rows_in(m_arr, L, C)
            rows = _double_rows(cs, rows)
            added = _add_rows(cs, rows, p_rows)
            bit = (
                bits_ref[i : i + 1, :]
                if isinstance(i, int)
                else bits_ref[pl.dslice(i, 1), :]
            )
            rows = _select_rows(bit, added, rows)
            return jnp.concatenate([r for coord in rows for r in coord], axis=0)

        m_arr = jnp.concatenate(
            [r for coord in _identity_rows(cs, p_ref[0:1, :]) for r in coord], axis=0
        )
        with rows_mul_context(cs.field, rest[:-1]):
            if interpret:
                # interpret-mode lowering of fori_loop over this body is
                # pathologically slow to compile; tests use tiny nbits, so
                # unroll instead.
                for i in range(nbits):
                    m_arr = body(i, m_arr)
            else:
                m_arr = jax.lax.fori_loop(0, nbits, body, m_arr)
            rows = _add_rows(cs, _rows_in(m_arr, L, C), _rows_in(add_ref, L, C))
        _rows_out(rest[-1], rows, L)

    B = p_t.shape[-1]
    spec = _point_spec(cs)
    bits_spec = pl.BlockSpec((nbits, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM)
    extra, extra_specs = mxu_operands(cs.field, interpret)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec, bits_spec] + extra_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((C * L, B), jnp.uint32),
        interpret=interpret,
    )(p_t, add_t, bits_t, *extra)


def _to_tiles(cs: CurveSpec, pts: jax.Array) -> tuple[jax.Array, tuple, int]:
    """(..., C, L) -> ((C·L, B_padded), batch_shape, n)."""
    L, C = cs.field.limbs, cs.ncoords
    batch = pts.shape[:-2]
    n = 1
    for d in batch:
        n *= int(d)
    m = max(BLOCK, ((n + BLOCK - 1) // BLOCK) * BLOCK)
    flat = jnp.reshape(pts, (n, C * L))
    if m != n:
        # pad with the identity so padding lanes stay on-curve
        ident = np.zeros((C, L), np.uint32)
        ident[1, 0] = 1
        if cs.kind == "edwards":
            ident[2, 0] = 1
        flat = jnp.concatenate(
            [flat, jnp.broadcast_to(jnp.asarray(ident.reshape(-1)), (m - n, C * L))]
        )
    return flat.T, batch, n


def _from_tiles(cs: CurveSpec, t: jax.Array, batch: tuple, n: int) -> jax.Array:
    L, C = cs.field.limbs, cs.ncoords
    return jnp.reshape(t.T[:n], batch + (C, L))


def _interp() -> bool:
    from ..fields import device as fd

    return not fd._on_tpu()


def pt_add(cs: CurveSpec, p: jax.Array, q: jax.Array, *, interpret: bool | None = None) -> jax.Array:
    """Fused-kernel twin of groups.device.add (both curve kinds).

    p, q: (..., C, L) projective/extended points (batches broadcast)."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        return gd._add_xla(cs, p, q)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="pt_add")
    p, q = jnp.broadcast_arrays(jnp.asarray(p, jnp.uint32), jnp.asarray(q, jnp.uint32))
    p_t, batch, n = _to_tiles(cs, p)
    q_t, _, _ = _to_tiles(cs, q)
    out = _add_call(cs, p_t, q_t, _interp() if interpret is None else interpret)
    return _from_tiles(cs, out, batch, n)


def pt_madd(cs: CurveSpec, p: jax.Array, q: jax.Array, *, interpret: bool | None = None) -> jax.Array:
    """Fused mixed add: q affine-normalised (Z = 1).  Weierstrass
    callers must not pass q = identity (see groups/device.madd)."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        return gd._madd_xla(cs, p, q)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="pt_madd")
    p, q = jnp.broadcast_arrays(jnp.asarray(p, jnp.uint32), jnp.asarray(q, jnp.uint32))
    p_t, batch, n = _to_tiles(cs, p)
    q_t, _, _ = _to_tiles(cs, q)
    out = _madd_call(cs, p_t, q_t, _interp() if interpret is None else interpret)
    return _from_tiles(cs, out, batch, n)


def pt_double(cs: CurveSpec, p: jax.Array, n_doubles: int = 1, *, interpret: bool | None = None) -> jax.Array:
    """Fused 2^n_doubles·P in one launch."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        for _ in range(n_doubles):
            p = gd._double_xla(cs, p)
        return p
    metrics.REGISTRY.inc("pallas_calls_total", kernel="pt_double")
    p = jnp.asarray(p, jnp.uint32)
    p_t, batch, n = _to_tiles(cs, p)
    out = _double_call(cs, p_t, n_doubles, _interp() if interpret is None else interpret)
    return _from_tiles(cs, out, batch, n)


def pt_window_step(
    cs: CurveSpec, acc: jax.Array, entry: jax.Array, n_doubles: int = 4, *, interpret: bool | None = None
) -> jax.Array:
    """acc <- 2^n_doubles · acc + entry, fused in one kernel launch."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        for _ in range(n_doubles):
            acc = gd._double_xla(cs, acc)
        return gd._add_xla(cs, acc, entry)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="pt_window_step")
    acc, entry = jnp.broadcast_arrays(
        jnp.asarray(acc, jnp.uint32), jnp.asarray(entry, jnp.uint32)
    )
    acc_t, batch, n = _to_tiles(cs, acc)
    entry_t, _, _ = _to_tiles(cs, entry)
    out = _window_call(
        cs, acc_t, n_doubles, _interp() if interpret is None else interpret, entry_t
    )
    return _from_tiles(cs, out, batch, n)


def pt_ladder_mul_add(
    cs: CurveSpec,
    p: jax.Array,
    addend: jax.Array,
    x: jax.Array,
    nbits: int,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """x·P + A for small public per-lane integers x < 2**nbits, fused.

    p, addend: (..., C, L); x: (...,) uint32.  One kernel launch runs
    the whole nbits-step ladder — this is eval_point_poly's Horner step
    (acc <- x·acc + E_l) collapsed from ~2·nbits XLA ops into one.
    """
    if not HAVE_PALLAS:  # pragma: no cover — XLA ladder, no re-dispatch
        from ..groups import device as gd

        bits = (
            jnp.asarray(x, jnp.uint32)[..., None]
            >> jnp.arange(nbits - 1, -1, -1, dtype=jnp.uint32)
        ) & 1
        acc = gd.identity(cs, jnp.asarray(p).shape[:-2])
        for i in range(nbits):
            acc = gd._double_xla(cs, acc)
            acc = gd.select(
                bits[..., i] != 0, gd._add_xla(cs, acc, p), acc
            )
        return gd._add_xla(cs, acc, addend)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="pt_ladder_mul_add")
    p, addend = jnp.broadcast_arrays(
        jnp.asarray(p, jnp.uint32), jnp.asarray(addend, jnp.uint32)
    )
    x = jnp.broadcast_to(jnp.asarray(x, jnp.uint32), p.shape[:-2])
    p_t, batch, n = _to_tiles(cs, p)
    a_t, _, _ = _to_tiles(cs, addend)
    B = p_t.shape[-1]
    xf = jnp.reshape(x, (n,))
    if B != n:
        xf = jnp.concatenate([xf, jnp.zeros((B - n,), jnp.uint32)])
    # MSB-first bit rows: bits_t[i] = bit (nbits-1-i) of x
    shifts = jnp.arange(nbits - 1, -1, -1, dtype=jnp.uint32)
    bits_t = (xf[None, :] >> shifts[:, None]) & jnp.uint32(1)
    out = _ladder_call(
        cs, p_t, a_t, nbits, _interp() if interpret is None else interpret, bits_t
    )
    return _from_tiles(cs, out, batch, n)


# Backwards-compatible Edwards aliases (round-1 API).
def ed_add(cs: CurveSpec, p: jax.Array, q: jax.Array, *, interpret: bool | None = None) -> jax.Array:
    return pt_add(cs, p, q, interpret=interpret)


def ed_window_step(
    cs: CurveSpec, acc: jax.Array, entry: jax.Array, n_doubles: int = 4, *, interpret: bool | None = None
) -> jax.Array:
    return pt_window_step(cs, acc, entry, n_doubles, interpret=interpret)
