"""Fused Pallas point-operation kernels (twisted Edwards, a = -1).

The scalar-mult ladder's hot loop is point add/double — each one is
~7-9 Barrett multiplies plus adds/subs.  The XLA path materialises
every intermediate field element in HBM between fused regions; these
kernels keep the WHOLE point operation (and the 4-double window step)
in VMEM: coordinates ride the sublane axis as 4L limb rows, the batch
rides the 128-wide lane axis, and the multiplies chain through
ops.pallas_field.mod_mul_rows without ever leaving the core.

Formulas mirror groups/device.py exactly (add-2008-hwcd-3 unified add,
dbl-2008-hwcd doubling — complete for ristretto255), which mirror the
role of dalek's backend in the reference (reference: src/groups.rs:55-90
delegating point arithmetic to curve25519-dalek).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..groups.device import CurveSpec
from . import pallas_field as pfk
from .pallas_field import BLOCK, mod_add_rows, mod_mul_rows, mod_sub_rows

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _const_rows(fs, value: int, like):
    from ..fields.spec import int_to_limbs

    return [jnp.full_like(like, np.uint32(v)) for v in int_to_limbs(value % fs.modulus, fs.limbs)]


def _ed_add_rows(cs: CurveSpec, p_rows, q_rows):
    """Unified extended Edwards add on 4 coordinate row-lists each."""
    f = cs.field
    x1, y1, z1, t1 = p_rows
    x2, y2, z2, t2 = q_rows
    a = mod_mul_rows(f, mod_sub_rows(f, y1, x1), mod_sub_rows(f, y2, x2))
    b = mod_mul_rows(f, mod_add_rows(f, y1, x1), mod_add_rows(f, y2, x2))
    d2 = _const_rows(f, cs.const, x1[0])
    c = mod_mul_rows(f, mod_mul_rows(f, t1, d2), t2)
    d = mod_mul_rows(f, mod_add_rows(f, z1, z1), z2)
    e = mod_sub_rows(f, b, a)
    ff = mod_sub_rows(f, d, c)
    g = mod_add_rows(f, d, c)
    h = mod_add_rows(f, b, a)
    return (
        mod_mul_rows(f, e, ff),
        mod_mul_rows(f, g, h),
        mod_mul_rows(f, ff, g),
        mod_mul_rows(f, e, h),
    )


def _ed_double_rows(cs: CurveSpec, p_rows):
    """Dedicated doubling (dbl-2008-hwcd), a = -1."""
    f = cs.field
    x1, y1, z1, _ = p_rows
    a = mod_mul_rows(f, x1, x1)
    b = mod_mul_rows(f, y1, y1)
    zz = mod_mul_rows(f, z1, z1)
    c = mod_add_rows(f, zz, zz)
    zero = [jnp.zeros_like(x1[0]) for _ in range(f.limbs)]
    d = mod_sub_rows(f, zero, a)  # a = -1 => D = -A
    xy = mod_add_rows(f, x1, y1)
    e = mod_sub_rows(f, mod_sub_rows(f, mod_mul_rows(f, xy, xy), a), b)
    g = mod_add_rows(f, d, b)
    h = mod_sub_rows(f, d, b)
    ff = mod_sub_rows(f, g, c)
    return (
        mod_mul_rows(f, e, ff),
        mod_mul_rows(f, g, h),
        mod_mul_rows(f, ff, g),
        mod_mul_rows(f, e, h),
    )


def _rows_in(ref, L: int):
    """(4L, B) ref -> 4 coordinate row-lists of L tiles each."""
    return tuple(
        [ref[c * L + i : c * L + i + 1, :] for i in range(L)] for c in range(4)
    )


def _rows_out(ref, rows, L: int):
    for c in range(4):
        for i in range(L):
            ref[c * L + i : c * L + i + 1, :] = rows[c][i]


@functools.partial(jax.jit, static_argnums=(0, 3))
def _ed_add_call(cs: CurveSpec, p_t: jax.Array, q_t: jax.Array, interpret: bool):
    L = cs.field.limbs

    def kernel(p_ref, q_ref, out_ref):
        _rows_out(out_ref, _ed_add_rows(cs, _rows_in(p_ref, L), _rows_in(q_ref, L)), L)

    B = p_t.shape[-1]
    spec = pl.BlockSpec((4 * L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((4 * L, B), jnp.uint32),
        interpret=interpret,
    )(p_t, q_t)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def _ed_window_call(cs: CurveSpec, acc_t: jax.Array, n_doubles: int, interpret: bool, entry_t: jax.Array):
    """The fused ladder window step: n_doubles doublings then one add,
    all inside one kernel launch — the HBM-traffic killer for
    scalar_mul's scan body (groups/device.py _scalar_mul_core)."""
    L = cs.field.limbs

    def kernel(acc_ref, entry_ref, out_ref):
        rows = _rows_in(acc_ref, L)
        for _ in range(n_doubles):
            rows = _ed_double_rows(cs, rows)
        rows = _ed_add_rows(cs, rows, _rows_in(entry_ref, L))
        _rows_out(out_ref, rows, L)

    B = acc_t.shape[-1]
    spec = pl.BlockSpec((4 * L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(B // BLOCK,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((4 * L, B), jnp.uint32),
        interpret=interpret,
    )(acc_t, entry_t)


def _to_tiles(cs: CurveSpec, pts: jax.Array) -> tuple[jax.Array, tuple, int]:
    """(..., 4, L) -> ((4L, B_padded), batch_shape, n)."""
    L = cs.field.limbs
    batch = pts.shape[:-2]
    n = 1
    for d in batch:
        n *= int(d)
    m = max(BLOCK, ((n + BLOCK - 1) // BLOCK) * BLOCK)
    flat = jnp.reshape(pts, (n, 4 * L))
    if m != n:
        # pad with the identity (0, 1, 1, 0) so padding lanes stay valid
        ident = np.zeros((4, L), np.uint32)
        ident[1, 0] = 1
        ident[2, 0] = 1
        flat = jnp.concatenate(
            [flat, jnp.broadcast_to(jnp.asarray(ident.reshape(-1)), (m - n, 4 * L))]
        )
    return flat.T, batch, n


def _from_tiles(cs: CurveSpec, t: jax.Array, batch: tuple, n: int) -> jax.Array:
    L = cs.field.limbs
    return jnp.reshape(t.T[:n], batch + (4, L))


def _interp() -> bool:
    return jax.default_backend() != "tpu"


def ed_add(cs: CurveSpec, p: jax.Array, q: jax.Array, *, interpret: bool | None = None) -> jax.Array:
    """Fused-kernel twin of groups.device.add for Edwards curves.

    p, q: (..., 4, L) extended points (same batch shape)."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        return gd.add(cs, p, q)
    p, q = jnp.broadcast_arrays(jnp.asarray(p, jnp.uint32), jnp.asarray(q, jnp.uint32))
    p_t, batch, n = _to_tiles(cs, p)
    q_t, _, _ = _to_tiles(cs, q)
    out = _ed_add_call(cs, p_t, q_t, _interp() if interpret is None else interpret)
    return _from_tiles(cs, out, batch, n)


def ed_window_step(
    cs: CurveSpec, acc: jax.Array, entry: jax.Array, n_doubles: int = 4, *, interpret: bool | None = None
) -> jax.Array:
    """acc <- 2^n_doubles * acc + entry, fused in one kernel launch."""
    if not HAVE_PALLAS:  # pragma: no cover
        from ..groups import device as gd

        for _ in range(n_doubles):
            acc = gd.double(cs, acc)
        return gd.add(cs, acc, entry)
    acc, entry = jnp.broadcast_arrays(
        jnp.asarray(acc, jnp.uint32), jnp.asarray(entry, jnp.uint32)
    )
    acc_t, batch, n = _to_tiles(cs, acc)
    entry_t, _, _ = _to_tiles(cs, entry)
    out = _ed_window_call(
        cs, acc_t, n_doubles, _interp() if interpret is None else interpret, entry_t
    )
    return _from_tiles(cs, out, batch, n)
