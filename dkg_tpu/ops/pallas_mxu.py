"""MXU-native Pallas kernels: fused multiply-reduce and bucket-accumulate.

Two kernels that move the hottest inner loops off the VPU schoolbook
tier (ops/pallas_field.py) and onto the matmul unit, the way the
AI-ASIC ZKP literature maps big-int arithmetic onto accelerator GEMMs —
limb products and reduction folds become small bounded-partial-sum f32
matmuls that are *exact* because every partial column sum stays below
float32's 2**24 integer range:

* :func:`mxu_mul_rows` / :func:`mxu_mod_mul` — the fused
  limb-mul + linear-reduce + lazy-carry modular multiply.  The
  schoolbook columns feed the ``fs.mulred`` byte-residue fold matrix
  directly (one ``jnp.dot`` on the MXU), the scan-free column folds
  squeeze the spill, and ONE carry normalize over L+1 limbs finishes —
  where the classic tier runs mul_wide's 2L-limb carry chain plus a
  separate reducer.  The quotient table is gathered with a two-level
  one-hot matmul (no dynamic gather inside the kernel).  Bit-exact
  against ``fields.device.mul``; the XLA twin of the same formulation
  is ``fields.device._mul_gemm`` (the CPU leg's win).
* :func:`bucket_accumulate` — the Pippenger scatter pass
  (groups/device.py msm_pippenger) with the bucket array VMEM-resident:
  per point, the current bucket per window is gathered with a one-hot
  matmul over the bucket lanes, added through the complete formulas
  (ops/pallas_point.py row cores), and written back with a branchless
  lane select.  The XLA leg's per-point ``(…, nw, entries)`` one-hot
  and whole-tensor ``jnp.where`` never materialize in HBM.

Layout contract matches ops/pallas_field.py: limbs on the sublane axis,
batch on the lane axis; all field/curve constants are baked Python-int
immediates, so each (field, shape) pair gets its own specialised
program.  Every numeric bound the kernels rely on is proved with exact
Python ints at field registration (spec._build_mulred); fields that
fail admission must use the Barrett row core.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.spec import FieldSpec
from ..utils import metrics
from .pallas_field import BLOCK, _cond_sub, _mul_columns, _normalize

try:  # pallas import is deferred-safe: CPU-only environments still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

#: lane width of the second-level quotient-table one-hot (one VPU row)
_QL = 128
#: interpret-mode bucket kernels unroll the point loop up to this m
#: (the fori_loop lowering is slow to build in interpret mode but keeps
#: trace size flat — the right trade only once the unroll gets large)
_BUCKET_UNROLL_MAX = 64


def _mask16(x):
    return x & jnp.uint32(0xFFFF)


@functools.lru_cache(maxsize=None)
def mxu_const_arrays(fs: FieldSpec) -> tuple[np.ndarray, np.ndarray]:
    """The two constant matrices the MXU core multiplies against, as
    host float32 arrays — Pallas kernels must take them as OPERANDS
    (captured array constants are rejected), so every kernel that
    chains :func:`mxu_mul_rows` appends these two inputs (see
    pallas_field.mxu_operands / rows_mul_context):

    * ``foldm_t`` (2L, 3L+1): transposed ``fs.mulred.foldm`` byte-
      residue fold matrix;
    * ``q2`` (``_QL``, qh): the quotient table reshaped for the
      two-level one-hot gather, Q[lo, hi] = qtable[hi*_QL+lo]
      (zero-padded).  Values < 2**16, so both matmul levels are exact
      in f32 (a single one per one-hot column).
    """
    mr = fs.mulred
    qlen = len(mr.qtable)
    qh = -(-qlen // _QL)
    qpad = np.zeros(qh * _QL, np.uint32)
    qpad[:qlen] = mr.qtable
    return mr.foldm.T.astype(np.float32), qpad.reshape(qh, _QL).T.astype(np.float32)


def mxu_mul_rows(fs: FieldSpec, rows_a, rows_b, foldm_t=None, q2=None):
    """Fused multiply-reduce on unrolled limb-row lists: L tiles in, L out.

    The MXU twin of ops.pallas_field's Barrett ``mod_mul_rows`` — same
    row-list contract, so the fused point kernels chain it without
    leaving VMEM.  Requires ``fs.mulred`` (every registered field
    admits it; spec._build_mulred proves the bounds).  Mirrors
    fields.device._mul_gemm limb for limb:

    1. unnormalized schoolbook columns (< 2**22 — the admission cap);
    2. the high half's three byte planes plus the P_{L-1} spill fold in
       ONE f32 matmul against the baked (2L, 3L+1) residue matrix;
    3. scan-free column folds, one lazy L+1-limb carry, a quotient from
       the two-level one-hot table matmul, and one conditional subtract.

    ``foldm_t``/``q2`` are the :func:`mxu_const_arrays` matrices; inside
    a Pallas kernel they MUST be loaded from kernel operands (captured
    array constants are rejected) — the defaults only work at XLA trace
    level.
    """
    mr = fs.mulred
    if mr is None:
        raise ValueError(f"{fs.name} does not admit the fused MXU mul")
    if foldm_t is None or q2 is None:
        fm_np, q2_np = mxu_const_arrays(fs)
        foldm_t = jnp.asarray(fm_np) if foldm_t is None else foldm_t
        q2 = jnp.asarray(q2_np) if q2 is None else q2
    L = fs.limbs
    cols = _mul_columns(rows_a, rows_b)  # 2L unnormalized column tiles
    plo, phi = cols[:L], cols[L:]
    digit_rows = (
        [r & jnp.uint32(0xFF) for r in phi]
        + [(r >> 8) & jnp.uint32(0xFF) for r in phi]
        + [r >> 16 for r in phi]
        + [plo[L - 1] >> 16]
    )
    digits = jnp.concatenate(digit_rows, axis=0).astype(jnp.float32)  # (3L+1, W)
    cols8 = jnp.dot(foldm_t, digits, preferred_element_type=jnp.float32)
    cols8 = cols8.astype(jnp.uint32)  # (2L, W), entries < 2**24
    new_cols = []
    for j in range(L):
        keep = plo[j] if j < L - 1 else _mask16(plo[L - 1])
        new_cols.append(
            keep + cols8[2 * j : 2 * j + 1, :] + (cols8[2 * j + 1 : 2 * j + 2, :] << 8)
        )
    c_l = [int(v) for v in mr.c_limbs]
    for _ in range(mr.n_split):
        los = [_mask16(cc) for cc in new_cols]
        his = [cc >> 16 for cc in new_cols]
        top = his[L - 1]
        new_cols = [
            los[j]
            + (his[j - 1] if j else jnp.zeros_like(top))
            + top * jnp.uint32(c_l[j])
            for j in range(L)
        ]
    v = _normalize(new_cols + [jnp.zeros_like(new_cols[0])])  # L+1 tiles, lazy carry
    u = (v[L - 1] >> mr.shift_e) | (v[L] << (16 - mr.shift_e))  # <= u_max < 2**13
    qh = q2.shape[1]
    w = u.shape[-1]
    oh_hi = (
        jax.lax.broadcasted_iota(jnp.uint32, (qh, w), 0) == (u >> 7)
    ).astype(jnp.float32)
    tmp = jnp.dot(q2, oh_hi, preferred_element_type=jnp.float32)
    oh_lo = (
        jax.lax.broadcasted_iota(jnp.uint32, (_QL, w), 0) == (u & jnp.uint32(127))
    ).astype(jnp.float32)
    q = jnp.sum(tmp * oh_lo, axis=0, keepdims=True).astype(jnp.uint32)  # (1, W)
    npl = [int(x) for x in mr.np_limbs]
    w_cols = [v[j] + q * jnp.uint32(npl[j]) for j in range(L + 1)]
    out = _cond_sub(_normalize(w_cols), [int(x) for x in fs.p_limbs_ext])
    return out[:L]


def _make_mxu_kernel(fs: FieldSpec):
    L = fs.limbs

    def kernel(a_ref, b_ref, fm_ref, q2_ref, out_ref):
        rows_a = [a_ref[i : i + 1, :] for i in range(L)]
        rows_b = [b_ref[i : i + 1, :] for i in range(L)]
        r = mxu_mul_rows(fs, rows_a, rows_b, foldm_t=fm_ref[...], q2=q2_ref[...])
        for i in range(L):
            out_ref[i : i + 1, :] = r[i]

    return kernel


def _const_spec(arr: np.ndarray):
    """A grid-invariant whole-array VMEM block for a constant operand."""
    return pl.BlockSpec(arr.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnums=(0, 3))
def _mxu_mul_tiles(fs: FieldSpec, a_t: jax.Array, b_t: jax.Array, interpret: bool):
    """(L, B) x (L, B) -> (L, B), B a multiple of BLOCK."""
    L, B = a_t.shape
    fm_np, q2_np = mxu_const_arrays(fs)
    return pl.pallas_call(
        _make_mxu_kernel(fs),
        grid=(B // BLOCK,),
        in_specs=[
            pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
            _const_spec(fm_np),
            _const_spec(q2_np),
        ],
        out_specs=pl.BlockSpec((L, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
        interpret=interpret,
    )(a_t, b_t, jnp.asarray(fm_np), jnp.asarray(q2_np))


def _want_interpret() -> bool:
    """Mosaic only exists on real TPU backends; interpret elsewhere."""
    from ..fields import device as fd

    return not fd._on_tpu()


def mxu_mod_mul(
    fs: FieldSpec, a: jax.Array, b: jax.Array, *, interpret: bool | None = None
) -> jax.Array:
    """Batched (a * b) mod p in ONE fused MXU kernel launch.

    a, b: (..., L) uint32 limb arrays (the framework-wide layout);
    drop-in parity with ``fields.device.mul``.  Falls back to the XLA
    twin of the same formulation when Pallas is unavailable.
    """
    if not HAVE_PALLAS:  # pragma: no cover
        from ..fields import device as fd

        return fd._mul_gemm(fs, a, b)
    metrics.REGISTRY.inc("pallas_calls_total", kernel="mxu_mod_mul")
    a = jnp.asarray(a, jnp.uint32)
    b = jnp.asarray(b, jnp.uint32)
    a, b = jnp.broadcast_arrays(a, b)
    batch = a.shape[:-1]
    n = 1
    for d in batch:
        n *= int(d)
    m = max(BLOCK, ((n + BLOCK - 1) // BLOCK) * BLOCK)
    af = jnp.reshape(a, (n, fs.limbs))
    bf = jnp.reshape(b, (n, fs.limbs))
    if m != n:
        pad = [(0, m - n), (0, 0)]
        af = jnp.pad(af, pad)
        bf = jnp.pad(bf, pad)
    interp = _want_interpret() if interpret is None else interpret
    out_t = _mxu_mul_tiles(fs, af.T, bf.T, interp)
    return jnp.reshape(out_t.T[:n], batch + (fs.limbs,))


# ---------------------------------------------------------------------------
# Pippenger bucket-accumulate
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5))
def _bucket_call(cs, pts_t, digs_t, window: int, nw: int, interpret: bool):
    """One grid step per (flattened) batch element; the whole
    (C·L, nw·2**window) bucket tile stays VMEM-resident across the
    m-point loop."""
    from . import pallas_field as pf
    from .pallas_point import _add_rows, _identity_rows

    L, C = cs.field.limbs, cs.ncoords
    entries = 1 << window
    lanes = nw * entries
    m_pad = pts_t.shape[-1]
    extra, extra_specs = pf.mxu_operands(cs.field, interpret)

    def kernel(pts_ref, digs_ref, *rest):
        out_ref = rest[-1]
        # one-hot layout constants from iota (Pallas kernels cannot
        # capture array constants): lane q holds bucket q % entries of
        # window q >> window_bits
        expand = (
            jax.lax.broadcasted_iota(jnp.uint32, (nw, lanes), 0)
            == (jax.lax.broadcasted_iota(jnp.uint32, (nw, lanes), 1) >> window)
        ).astype(jnp.float32)
        gather = (
            (jax.lax.broadcasted_iota(jnp.uint32, (lanes, nw), 0) >> window)
            == jax.lax.broadcasted_iota(jnp.uint32, (lanes, nw), 1)
        ).astype(jnp.float32)
        eid = (
            jax.lax.broadcasted_iota(jnp.uint32, (1, lanes), 1)
            & jnp.uint32(entries - 1)
        ).astype(jnp.float32)
        ident = _identity_rows(cs, jnp.zeros((1, lanes), jnp.uint32))
        for c in range(C):
            for i in range(L):
                out_ref[0, c * L + i : c * L + i + 1, :] = ident[c][i]

        def body(mm, carry):
            bt = out_ref[0]  # (C·L, lanes) uint32, limbs < 2**16
            if isinstance(mm, int):
                dig = digs_ref[0, mm : mm + 1, :]
                ptcol = pts_ref[0, :, mm : mm + 1]
            else:
                dig = digs_ref[0, pl.dslice(mm, 1), :]
                ptcol = pts_ref[0, :, pl.dslice(mm, 1)]
            # dig_exp[0, q] = digit of window q//entries — exact f32
            dig_exp = jnp.dot(
                dig.astype(jnp.float32), expand, preferred_element_type=jnp.float32
            )
            mask = eid == dig_exp  # (1, lanes): this point's bucket per window
            # gather the selected bucket per window: exactly one nonzero
            # per (row, window), limb values < 2**16 — exact f32 matmul
            cur = jnp.dot(
                bt.astype(jnp.float32) * mask.astype(jnp.float32),
                gather,
                preferred_element_type=jnp.float32,
            ).astype(jnp.uint32)  # (C·L, nw)
            cur_rows = tuple(
                [cur[c * L + i : c * L + i + 1, :] for i in range(L)] for c in range(C)
            )
            pt = jnp.broadcast_to(ptcol, (C * L, nw))
            pt_rows = tuple(
                [pt[c * L + i : c * L + i + 1, :] for i in range(L)] for c in range(C)
            )
            new_rows = _add_rows(cs, cur_rows, pt_rows)
            new_mat = jnp.concatenate(
                [r for coord in new_rows for r in coord], axis=0
            )  # (C·L, nw)
            # scatter back: expand each window's sum across its lanes,
            # commit only the masked lane (digit-0 lands in bucket 0,
            # ignored downstream exactly like the XLA scan leg)
            new_exp = jnp.dot(
                new_mat.astype(jnp.float32), expand, preferred_element_type=jnp.float32
            ).astype(jnp.uint32)
            out_ref[0] = jnp.where(mask, new_exp, bt)
            return carry

        with pf.rows_mul_context(cs.field, rest[:-1]):
            if interpret and m_pad <= _BUCKET_UNROLL_MAX:
                for i in range(m_pad):
                    body(i, 0)
            else:
                jax.lax.fori_loop(0, m_pad, body, 0)

    B = pts_t.shape[0]
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, C * L, m_pad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad, nw), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ]
        + extra_specs,
        out_specs=pl.BlockSpec(
            (1, C * L, lanes), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, C * L, lanes), jnp.uint32),
        interpret=interpret,
    )(pts_t, digs_t, *extra)


def bucket_accumulate(
    cs,
    points: jax.Array,
    digits: jax.Array,
    window: int,
    nw: int,
    *,
    interpret: bool | None = None,
) -> jax.Array | None:
    """Pippenger scatter pass with VMEM-resident buckets.

    points (..., m, C, L), digits (..., m, nw) ->
    buckets (..., nw, 2**window, C, L) — bit-identical to the XLA scan
    leg's bucket tensor (same add order through the same complete
    formulas), so groups.device's bucket-close and window-combine
    passes run unchanged on either leg.  Returns ``None`` when Pallas
    is unavailable (callers fall back to the scan leg).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        return None
    metrics.REGISTRY.inc("pallas_calls_total", kernel="bucket_accumulate")
    L, C = cs.field.limbs, cs.ncoords
    entries = 1 << window
    batch = points.shape[:-3]
    m = points.shape[-3]
    b = 1
    for d in batch:
        b *= int(d)
    pts = jnp.reshape(jnp.asarray(points, jnp.uint32), (b, m, C * L))
    pts = jnp.transpose(pts, (0, 2, 1))  # (B, C·L, m)
    digs = jnp.reshape(jnp.asarray(digits, jnp.int32), (b, m, nw))
    interp = _want_interpret() if interpret is None else interpret
    m_pad = m if interp else max(BLOCK, -(-m // BLOCK) * BLOCK)
    if m_pad != m:
        # sentinel digit == entries never matches a bucket lane, so the
        # padding points are computed but never committed
        pts = jnp.pad(pts, [(0, 0), (0, 0), (0, m_pad - m)])
        digs = jnp.pad(digs, [(0, 0), (0, m_pad - m), (0, 0)], constant_values=entries)
    out = _bucket_call(cs, pts, digs, window, nw, interp)  # (B, C·L, lanes)
    buckets = jnp.reshape(out, (b, C, L, nw, entries))
    buckets = jnp.transpose(buckets, (0, 3, 4, 1, 2))
    return jnp.reshape(buckets, batch + (nw, entries, C, L))
