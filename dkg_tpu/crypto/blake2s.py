"""Vectorized BLAKE2s compression (RFC 7693), numpy u32 lanes.

The host leg of the transcript Merkle tree (crypto/device_hash.py): on
CPU backends the XLA lowering of the device tree pays per-op dispatch
overhead on thousands of tiny uint32 ops — the same pathology that
motivated the host leg of ``groups.device.encode_batch`` — so the
digest dispatcher (``device_hash.digest_dispatch``) routes CPU
transcripts here instead.  One numpy dispatch per G-call covers every
node of a tree level at once: the whole (n, n) share tensor digests in
a handful of array ops.

Bit-exactness contract: :func:`row_digests_np` computes EXACTLY the
tree mode documented in ``device_hash`` (same IV/parameter words, same
leaf/interior/root domain separation, same padding) — the pure-Python
twin ``device_hash.tree_digest_host`` is the oracle, and
``tests/test_blake2s.py`` diffs both the raw compression function
(against ``device_hash._compress_py``) and whole trees on random
shapes.  This is the sibling of ``crypto/blake2.py`` (the u64 BLAKE2b
batch the DEM KDF and Fiat-Shamir rho derivation use); BLAKE2s keeps
its own file because the tree constants and 32-bit rotation schedule
are the transcript hash's spec, not a digest-size parameter.
"""

from __future__ import annotations

import numpy as np

# The tree-mode constants are owned by device_hash (the construction's
# spec lives in its module docstring); this module is numpy-only apart
# from this import, which device_hash defers at call time to avoid a
# cycle.
from .device_hash import IV, MASK32, P3_LEAF, P3_NODE, P_WORD0, SIGMA

_IV32 = np.asarray(IV, np.uint32)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _g(v: np.ndarray, a: int, b: int, c: int, d: int, x: np.ndarray, y: np.ndarray) -> None:
    """RFC 7693 §3.1 mixing function G on ``(N, 16)`` u32 work vectors
    (BLAKE2s rotation schedule: 16/12/8/7)."""
    v[:, a] += v[:, b] + x
    v[:, d] = _rotr(v[:, d] ^ v[:, a], 16)
    v[:, c] += v[:, d]
    v[:, b] = _rotr(v[:, b] ^ v[:, c], 12)
    v[:, a] += v[:, b] + y
    v[:, d] = _rotr(v[:, d] ^ v[:, a], 8)
    v[:, c] += v[:, d]
    v[:, b] = _rotr(v[:, b] ^ v[:, c], 7)


def compress_batch(h: np.ndarray, m: np.ndarray, t, f0: int) -> np.ndarray:
    """Batched BLAKE2s compression F: ``h`` (N, 8), ``m`` (N, 16),
    ``t`` scalar or (N,), ``f0`` scalar -> (N, 8).  All uint32; row i
    equals ``device_hash._compress_py(h[i], m[i], t[i], f0)``.

    t_hi is always 0 for our < 2^32-byte chunks (same contract as the
    device twin)."""
    h = np.asarray(h, np.uint32)
    m = np.asarray(m, np.uint32)
    n = h.shape[0]
    v = np.empty((n, 16), np.uint32)
    v[:, :8] = h
    v[:, 8:] = _IV32
    with np.errstate(over="ignore"):
        v[:, 12] ^= np.asarray(t, np.uint32)
        v[:, 14] ^= np.uint32(f0 & MASK32)
        for s in SIGMA:
            _g(v, 0, 4, 8, 12, m[:, s[0]], m[:, s[1]])
            _g(v, 1, 5, 9, 13, m[:, s[2]], m[:, s[3]])
            _g(v, 2, 6, 10, 14, m[:, s[4]], m[:, s[5]])
            _g(v, 3, 7, 11, 15, m[:, s[6]], m[:, s[7]])
            _g(v, 0, 5, 10, 15, m[:, s[8]], m[:, s[9]])
            _g(v, 1, 6, 11, 12, m[:, s[10]], m[:, s[11]])
            _g(v, 2, 7, 8, 13, m[:, s[12]], m[:, s[13]])
            _g(v, 3, 4, 9, 14, m[:, s[14]], m[:, s[15]])
        return h ^ v[:, :8] ^ v[:, 8:]


def _h_init(p3: int, n: int) -> np.ndarray:
    h = np.broadcast_to(_IV32, (n, 8)).copy()
    h[:, 0] ^= np.uint32(P_WORD0)
    h[:, 3] ^= np.uint32(p3)
    return h


def row_digests_np(words: np.ndarray, domain: int = 0) -> np.ndarray:
    """Independent Merkle digest per row: (R, W) uint32 -> (R, 8) uint32.

    Numpy twin of ``device_hash._tree_from_words`` — every tree level is
    ONE ``compress_batch`` over all of that level's nodes across all
    rows, so the op count is O(log blocks), not O(nodes)."""
    words = np.ascontiguousarray(words, np.uint32)
    r, w = words.shape
    nl = max(1, -(-w // 16))
    nl_pow2 = 1 << (nl - 1).bit_length()
    pad = nl_pow2 * 16 - w
    if pad:
        words = np.concatenate([words, np.zeros((r, pad), np.uint32)], axis=-1)
    blocks = words.reshape(r * nl_pow2, 16)
    t_leaf = np.tile(np.arange(nl_pow2, dtype=np.uint32) * 64, r)
    h = compress_batch(_h_init(P3_LEAF, r * nl_pow2), blocks, t_leaf, MASK32)
    h = h.reshape(r, nl_pow2, 8)
    level = 1
    while h.shape[1] > 1:
        k = h.shape[1] // 2
        pairs = h.reshape(r * k, 16)
        h = compress_batch(_h_init(P3_NODE, r * k), pairs, level, MASK32)
        h = h.reshape(r, k, 8)
        level += 1
    tail = np.zeros((r, 8), np.uint32)
    tail[:, 0] = np.uint32(w & MASK32)
    tail[:, 1] = np.uint32(domain & MASK32)
    root_block = np.concatenate([h[:, 0, :], tail], axis=-1)
    return compress_batch(_h_init(P3_NODE, r), root_block, 0, MASK32)


def tree_digest_np(words, domain: int = 0) -> np.ndarray:
    """Single-stream numpy twin of ``device_hash.tree_digest``:
    any uint32 array -> (8,) uint32."""
    flat = np.asarray(words, np.uint32).reshape(1, -1)
    return row_digests_np(flat, domain)[0]
