"""Device-resident transcript hashing: a BLAKE2s-compression Merkle tree.

Why: Fiat-Shamir batch randomizers must bind the COMPLETE round-1
transcript (commitments + share matrices).  Hashing on host means
shipping the full tensors over PCIe/tunnel — ~2.1 GB at n=4096 — so the
digest is computed where the data lives and only 32 bytes cross to the
host.  This is the device-side reduction the protocol layer
(dkg.ceremony.transcript_digest) uses on its hot path; the byte-level
host path remains for wire parity.

Construction (documented because it is a custom tree mode — public,
deterministic, recomputable by any verifier from the broadcast data):

* Input: any uint32 tensor, flattened to words, zero-padded to 16-word
  (64-byte) blocks, block count padded to a power of two.
* Leaf i: one BLAKE2s compression (RFC 7693 §3.2) of block i with
  h = IV ^ params(node_depth=0), t = 64*i (position binding), f0 = -1.
* Interior: compression of (left || right) digests with
  h = IV ^ params(node_depth=1), t = level, f0 = -1; fixed arity 2, so
  with domain-separated leaves this is a standard Merkle
  collision-resistance argument.
* Root: one final compression binding the ORIGINAL word count and a
  caller domain tag, so zero-padding and tree-height ambiguities cannot
  collide (interior compressions always carry t = level >= 1; the root
  carries t = 0, separating it from them).

The initial state is IV XOR the RFC 7693 §2.5 parameter block: word 0
packs digest_length=32 | key_length=0 | fanout=2 | depth=255
(P_WORD0), and word 3's node_depth byte (parameter-block byte 14) is 0
for leaves and 1 for interior/root compressions, with inner_length=32
(byte 15) — so leaf/interior domain separation is exactly the RFC's
tree-hashing node_depth mechanism.  Collision resistance reduces to
that of the BLAKE2s compression function.

The pure-Python twin (``tree_digest_host``) is the test oracle and the
multi-host fold reference.

Dispatch: the public entry points (:func:`tree_digest`,
:func:`row_digests`) are BACKEND-DISPATCHED.  The device leg runs the
whole tree as ONE jitted program per (shape, domain-arity) — rounds
roll up in a ``lax.fori_loop`` and the four column/diagonal G-calls of
each half-round vectorize over a 4-wide lane axis, so the traced graph
stays small and the per-op XLA dispatch that made the eager tree the
ceremony's slowest phase (BENCH_r06: 5.5 s at n=64 on CPU) disappears.
The host leg (``crypto.blake2s``) is the same tree in batched numpy —
on CPU backends XLA per-op overhead dominates the tiny uint32 ops
exactly as it did for point encoding (``groups.device.encode_batch``),
so ``digest_dispatch`` routes CPU transcripts there.  Both legs are
bit-identical; ``DKG_TPU_DIGEST=device|host|auto`` (validated) forces a
leg.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

# RFC 7693 §2.5 parameter words.  Word 0: digest_length=32 (byte 0),
# key_length=0 (byte 1), fanout=2 (byte 2), depth=255 (byte 3).
# Word 3: node_depth (byte 14 -> bits 16..23) 0 for leaves / 1 for
# interior+root, inner_length=32 (byte 15 -> bits 24..31).
P_WORD0 = 0xFF020020
P3_LEAF = 32 << 24
P3_NODE = (1 << 16) | (32 << 24)

SIGMA = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
)

MASK32 = 0xFFFFFFFF


# ---------------------------------------------------------------------------
# device (jnp) compression, batched over leading axes
# ---------------------------------------------------------------------------


def _ror(x, n):
    return (x >> n) | (x << (32 - n))


def _compress_dev(h, m, t, f0):
    """Batched BLAKE2s compression: h (..., 8), m (..., 16), t (...,) or
    scalar, f0 scalar -> (..., 8).  All uint32.

    Trace-size discipline (this runs INSIDE the jitted tree): the ten
    rounds roll up in a ``lax.fori_loop`` with the message schedule as a
    gathered (10, 16) constant, and each half-round's four independent
    G-calls run as ONE G over a 4-wide lane axis — the standard
    column/diagonal formulation (diagonals are lane-rolls of the state
    quarters).  The traced graph is ~2 G-bodies instead of 80, so a
    whole Merkle level compiles in milliseconds while the compiled code
    is identical arithmetic to the unrolled form."""
    t = jnp.asarray(t, jnp.uint32)
    batch = jnp.broadcast_shapes(h.shape[:-1], m.shape[:-1], t.shape)
    h = jnp.broadcast_to(h, batch + (8,))
    m = jnp.broadcast_to(m, batch + (16,))
    iv = jnp.asarray(np.asarray(IV, np.uint32))
    v = jnp.concatenate([h, jnp.broadcast_to(iv, h.shape)], axis=-1)
    v = v.at[..., 12].set(v[..., 12] ^ jnp.broadcast_to(t, batch))
    v = v.at[..., 14].set(v[..., 14] ^ jnp.uint32(f0))
    sigma = jnp.asarray(np.asarray(SIGMA, np.int32))

    def g(a, b, c, d, x, y):
        a = a + b + x  # uint32 wraps mod 2^32 natively
        d = _ror(d ^ a, 16)
        c = c + d
        b = _ror(b ^ c, 12)
        a = a + b + y
        d = _ror(d ^ a, 8)
        c = c + d
        b = _ror(b ^ c, 7)
        return a, b, c, d

    def round_body(rnd, v):
        ms = jnp.take(m, sigma[rnd], axis=-1)
        a, b, c, d = (v[..., 0:4], v[..., 4:8], v[..., 8:12], v[..., 12:16])
        # columns: G(v0,v4,v8,v12) .. G(v3,v7,v11,v15)
        a, b, c, d = g(a, b, c, d, ms[..., 0:8:2], ms[..., 1:8:2])
        # diagonals: G(v0,v5,v10,v15) .. G(v3,v4,v9,v14) == lane rolls
        b = jnp.roll(b, -1, axis=-1)
        c = jnp.roll(c, -2, axis=-1)
        d = jnp.roll(d, -3, axis=-1)
        a, b, c, d = g(a, b, c, d, ms[..., 8:16:2], ms[..., 9:16:2])
        b = jnp.roll(b, 1, axis=-1)
        c = jnp.roll(c, 2, axis=-1)
        d = jnp.roll(d, 3, axis=-1)
        return jnp.concatenate([a, b, c, d], axis=-1)

    v = lax.fori_loop(0, 10, round_body, v)
    return h ^ v[..., 0:8] ^ v[..., 8:16]


def _h_init(p3: int, batch: tuple) -> jax.Array:
    h = np.asarray(IV, np.uint32).copy()
    h[0] ^= np.uint32(P_WORD0)
    h[3] ^= np.uint32(p3)
    return jnp.broadcast_to(jnp.asarray(h), batch + (8,))


def _pad_blocks(words: jax.Array) -> jax.Array:
    """(..., W) words -> (..., NL, 16) blocks, NL a power of two."""
    w = words.shape[-1]
    nl = max(1, -(-w // 16))
    nl_pow2 = 1 << (nl - 1).bit_length()
    pad = nl_pow2 * 16 - w
    if pad:
        words = jnp.pad(words, [(0, 0)] * (words.ndim - 1) + [(0, pad)])
    return words.reshape(words.shape[:-1] + (nl_pow2, 16))


def digest_dispatch() -> str:
    """Which transcript-digest leg runs: ``"device"`` or ``"host"``.

    ``DKG_TPU_DIGEST=device|host|auto`` (validated via envknobs — a typo
    must fail loudly, not silently measure the wrong leg) forces it;
    ``auto``/unset picks the jitted device tree on TPU and the batched
    numpy tree (``crypto.blake2s``) elsewhere, where XLA per-op overhead
    on tiny uint32 ops dominates.  Both legs are bit-identical
    (tests/test_digest_dispatch.py), so the choice is pure performance —
    rho never depends on it.
    """
    from ..fields import device as fd
    from ..utils import envknobs

    mode = envknobs.choice(
        "DKG_TPU_DIGEST",
        ("device", "host", "auto"),
        "a typo would silently run the slow digest leg",
    )
    if mode is None or mode == "auto":
        return "device" if fd._on_tpu() else "host"
    return mode


def tree_digest(tensor, domain: int = 0, dispatch: str | None = None):
    """Merkle digest of a uint32 tensor's words -> (8,) uint32.

    Leading axes before the last are flattened into the word stream;
    use :func:`row_digests` to keep a batch axis independent.
    Backend-dispatched (see :func:`digest_dispatch`); ``dispatch``
    pins a leg (the cross-leg equality tests do).
    """
    if dispatch is None:
        dispatch = digest_dispatch()
    if dispatch == "host":
        from . import blake2s

        return blake2s.tree_digest_np(np.asarray(tensor), domain)
    words = jnp.asarray(tensor, jnp.uint32).reshape(-1)
    return _tree_from_words(words[None, :], domain)[0]


def row_digests(tensor, domain: int = 0, dispatch: str | None = None):
    """Independent Merkle digest per row: (R, ...) -> (R, 8) uint32.

    Each row's digest depends only on that row (and the shared shape),
    so dealer-sharded tensors hash shard-locally and only (R, 8) words
    ever need to cross hosts — the shard-foldable structure
    transcript hashing requires.  Backend-dispatched like
    :func:`tree_digest`; the host leg returns numpy, the device leg a
    jax array (every consumer folds through ``np.asarray`` anyway).
    """
    if dispatch is None:
        dispatch = digest_dispatch()
    if dispatch == "host":
        from . import blake2s

        t = np.asarray(tensor)
        return blake2s.row_digests_np(t.reshape(t.shape[0], -1), domain)
    t = jnp.asarray(tensor, jnp.uint32)
    return _tree_from_words(t.reshape(t.shape[0], -1), domain)


def _tree_from_words(words: jax.Array, domain: int) -> jax.Array:
    """Jit entry for the device tree: one compiled program per (R, W)
    shape, shared across domains (the domain tag rides in as a traced
    scalar, so the rows_a/rows_e calls of ``_dealer_rows_device`` — same
    shape, different domain — reuse one executable)."""
    return _tree_from_words_jit(
        jnp.asarray(words, jnp.uint32), jnp.uint32(int(domain) & MASK32)
    )


@jax.jit
def _tree_from_words_jit(words: jax.Array, domain: jax.Array) -> jax.Array:
    r, w = words.shape
    blocks = _pad_blocks(words)  # (R, NL, 16)
    nl = blocks.shape[-2]
    t_leaf = jnp.arange(nl, dtype=jnp.uint32) * 64
    h = _compress_dev(_h_init(P3_LEAF, (r, nl)), blocks, t_leaf[None, :], MASK32)
    level = 1
    while h.shape[-2] > 1:  # trace-time loop: log2(NL) compressions
        pairs = h.reshape(r, h.shape[-2] // 2, 16)
        h = _compress_dev(
            _h_init(P3_NODE, pairs.shape[:-1]), pairs, jnp.uint32(level), MASK32
        )
        level += 1
    tail = (
        jnp.zeros((8,), jnp.uint32)
        .at[0]
        .set(jnp.uint32(w & MASK32))
        .at[1]
        .set(domain)
    )
    root_block = jnp.concatenate(
        [h[:, 0, :], jnp.broadcast_to(tail, (r, 8))], axis=-1
    )
    return _compress_dev(_h_init(P3_NODE, (r,)), root_block, jnp.uint32(0), MASK32)


# ---------------------------------------------------------------------------
# pure-Python twin (test oracle + spec)
# ---------------------------------------------------------------------------


def _compress_py(h, m, t, f0):
    def ror(x, n):
        return ((x >> n) | (x << (32 - n))) & MASK32

    v = list(h) + list(IV)
    v[12] ^= t & MASK32
    v[14] ^= f0 & MASK32

    def g(a, b, c, d, x, y):
        a = (a + b + x) & MASK32
        d = ror(d ^ a, 16)
        c = (c + d) & MASK32
        b = ror(b ^ c, 12)
        a = (a + b + y) & MASK32
        d = ror(d ^ a, 8)
        c = (c + d) & MASK32
        b = ror(b ^ c, 7)
        return a, b, c, d

    for rnd in range(10):
        s = SIGMA[rnd]
        v[0], v[4], v[8], v[12] = g(v[0], v[4], v[8], v[12], m[s[0]], m[s[1]])
        v[1], v[5], v[9], v[13] = g(v[1], v[5], v[9], v[13], m[s[2]], m[s[3]])
        v[2], v[6], v[10], v[14] = g(v[2], v[6], v[10], v[14], m[s[4]], m[s[5]])
        v[3], v[7], v[11], v[15] = g(v[3], v[7], v[11], v[15], m[s[6]], m[s[7]])
        v[0], v[5], v[10], v[15] = g(v[0], v[5], v[10], v[15], m[s[8]], m[s[9]])
        v[1], v[6], v[11], v[12] = g(v[1], v[6], v[11], v[12], m[s[10]], m[s[11]])
        v[2], v[7], v[8], v[13] = g(v[2], v[7], v[8], v[13], m[s[12]], m[s[13]])
        v[3], v[4], v[9], v[14] = g(v[3], v[4], v[9], v[14], m[s[14]], m[s[15]])
    return [h[i] ^ v[i] ^ v[i + 8] for i in range(8)]


def tree_digest_host(words, domain: int = 0) -> list[int]:
    """Pure-Python twin of :func:`tree_digest` on a 1-D word list."""
    words = [int(x) & MASK32 for x in words]
    w = len(words)
    nl = max(1, -(-w // 16))
    nl_pow2 = 1 << (nl - 1).bit_length()
    words = words + [0] * (nl_pow2 * 16 - w)

    def h_init(p3):
        h = list(IV)
        h[0] ^= P_WORD0
        h[3] ^= p3
        return h

    level_nodes = [
        _compress_py(h_init(P3_LEAF), words[i * 16 : (i + 1) * 16], 64 * i, MASK32)
        for i in range(nl_pow2)
    ]
    level = 1
    while len(level_nodes) > 1:
        level_nodes = [
            _compress_py(
                h_init(P3_NODE),
                level_nodes[2 * i] + level_nodes[2 * i + 1],
                level,
                MASK32,
            )
            for i in range(len(level_nodes) // 2)
        ]
        level += 1
    root_block = level_nodes[0] + [w & MASK32, domain & MASK32, 0, 0, 0, 0, 0, 0]
    return _compress_py(h_init(P3_NODE), root_block, 0, MASK32)


def digest_to_bytes(digest) -> bytes:
    """(8,) uint32 digest -> 32 little-endian bytes.

    Host-side convenience for EXTERNAL verifiers serialising tree/row
    digests; the in-package transcript fold consumes the uint32 arrays
    directly (dkg.ceremony._fold_digest_device)."""
    return b"".join(int(x).to_bytes(4, "little") for x in np.asarray(digest))
