"""Batched DLEQ proof generation/verification for complaint storms.

The reference verifies each complaint's two DLEQ proofs one at a time
(reference: src/dkg/broadcast.rs:50-98 re-running zkp.rs:54-74 per
accusation — 4 serial scalar mults each).  In a large ceremony a storm
of k complaints means 4k scalar multiplications; here all of them run
as ONE batched device ladder call, and only the Blake2b Fiat-Shamir
transcript hashing (byte-level, off the hot path) stays host-side —
the same device/host split as hybrid encryption (SURVEY §7 step 4).

Proof convention matches crypto/dleq.py exactly: challenge
e = H(b1, b2, h1, h2, a1, a2), response z = w + e*x, verify by
recomputing a_i = b_i*z - h_i*e.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..fields import host as fh
from ..groups import device as gd
from ..groups import host as gh
from .dleq import DleqZkp, _challenge


def _pairs_to_device(cs, points_a, points_b):
    """Two length-k host point lists -> one (k, 2, C, L) device tensor."""
    k = len(points_a)
    interleaved = [p for pair in zip(points_a, points_b) for p in pair]
    dev = gd.from_host(cs, interleaved)
    return dev.reshape(k, 2, cs.ncoords, cs.field.limbs)


def generate_batch(
    group: gh.HostGroup,
    cs,
    statements: list[tuple],  # (base1, base2, point1, point2, dlog)
    rng,
    *,
    return_announcements: bool = False,
) -> list[DleqZkp] | tuple[list[DleqZkp], list[tuple]]:
    """Batched prover: all 2k announcement scalar-mults in one device
    call; challenges + responses finish host-side per proof.

    ``return_announcements=True`` additionally returns the per-proof
    announcement pairs ``[(a1, a2), ...]`` (host point tuples).  A
    verifier holding them can check ``z*b_i - e*h_i - a_i == 0`` as a
    random-linear-combination over many proofs at once instead of
    recomputing each announcement (sign.verify.rlc_verify) — the
    transcript already binds them through ``e``, so publishing them
    reveals nothing the proof did not.
    """
    if not statements:
        return ([], []) if return_announcements else []
    q = group.scalar_field.modulus
    ws = [group.random_scalar(rng) for _ in statements]
    bases = _pairs_to_device(cs, [s[0] for s in statements], [s[1] for s in statements])
    w_limbs = jnp.asarray(fh.encode(group.scalar_field, [[w, w] for w in ws]))
    ann = gd.to_host(cs, np.asarray(gd.scalar_mul(cs, w_limbs, bases)).reshape(-1, cs.ncoords, cs.field.limbs))
    out = []
    anns = []
    for i, (b1, b2, h1, h2, x) in enumerate(statements):
        a1, a2 = ann[2 * i], ann[2 * i + 1]
        e = _challenge(group, b1, b2, h1, h2, a1, a2)
        out.append(DleqZkp(e, (ws[i] + e * x) % q))
        anns.append((a1, a2))
    if return_announcements:
        return out, anns
    return out


def verify_batch(
    group: gh.HostGroup,
    cs,
    proofs: list[DleqZkp],
    statements: list[tuple],  # (base1, base2, point1, point2)
) -> np.ndarray:
    """Batched verifier -> boolean array, one entry per proof.

    Device work: a_i = b_i*z - h_i*e for every proof at once, as one
    batched m=2 MSM per (proof, leg) lane — scalars (z, q-e) against
    points (b_i, h_i), so the bucket/Straus kernel folds the negation
    and the combining add into the multi-scalar sum itself instead of
    two separate ladder calls plus a point subtraction.
    """
    if not proofs:
        return np.zeros((0,), dtype=bool)
    k = len(proofs)
    fs = group.scalar_field
    q = fs.modulus
    bases = _pairs_to_device(cs, [s[0] for s in statements], [s[1] for s in statements])
    points = _pairs_to_device(cs, [s[2] for s in statements], [s[3] for s in statements])
    z_limbs = jnp.asarray(fh.encode(fs, [[p.response] * 2 for p in proofs]))
    ne_limbs = jnp.asarray(
        fh.encode(fs, [[(q - p.challenge) % q] * 2 for p in proofs])
    )
    # (k, 2 legs, m=2, ...): MSM axis -3 holds the (b, h) pair
    scalars = jnp.stack([z_limbs, ne_limbs], axis=2)
    pts = jnp.stack([bases, points], axis=2)
    ann = gd.msm(cs, scalars, pts)
    ann_host = gd.to_host(cs, np.asarray(ann).reshape(-1, cs.ncoords, cs.field.limbs))
    ok = np.zeros((k,), dtype=bool)
    for i, (proof, (b1, b2, h1, h2)) in enumerate(zip(proofs, statements)):
        a1, a2 = ann_host[2 * i], ann_host[2 * i + 1]
        ok[i] = proof.challenge == _challenge(group, b1, b2, h1, h2, a1, a2)
    return ok
