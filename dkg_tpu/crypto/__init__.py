"""Cryptographic building blocks (reference: src/cryptography/mod.rs:1-5)."""

from .commitment import CommitmentKey, Open, commit, commit_with_random, verify  # noqa: F401
from .correct_decryption import CorrectHybridDecrKeyZkp  # noqa: F401
from .dleq import DleqZkp  # noqa: F401
from . import dleq_batch  # noqa: F401
from .elgamal import (  # noqa: F401
    Ciphertext,
    HybridCiphertext,
    Keypair,
    SymmetricKey,
    decrypt_point,
    encrypt,
    encrypt_point,
    hybrid_decrypt,
    hybrid_decrypt_with_key,
    hybrid_encrypt,
    recover_symmetric_key,
)
