"""Proof of correct hybrid-decryption-key disclosure.

Functional parity with the reference (reference:
src/cryptography/correct_hybrid_decryption_key/zkp.rs): a complainer
disclosing the KEM point D for a hybrid ciphertext (e1, payload) proves
D = e1*sk and pk = g*sk — one DLEQ over bases (g, e1) and points
(pk, D) — so any third party can re-decrypt the payload and re-check the
share (reference: zkp.rs:29-50; protocol use broadcast.rs:189-282).

Note: the canonical statement order is used here (docstring-vs-code swap
in the reference noted in SURVEY §5 quirk 2 — resolved deliberately to
the documented order; self-consistent on both generate and verify).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..groups.host import HostGroup
from .dleq import DleqZkp
from .elgamal import HybridCiphertext, SymmetricKey


@dataclass(frozen=True)
class CorrectHybridDecrKeyZkp:
    proof: DleqZkp

    @classmethod
    def generate(
        cls,
        group: HostGroup,
        c: HybridCiphertext,
        pk: tuple,
        symm_key: SymmetricKey,
        sk: int,
        rng,
    ) -> "CorrectHybridDecrKeyZkp":
        return cls(
            DleqZkp.generate(
                group, group.generator(), c.e1, pk, symm_key.point, sk, rng
            )
        )

    def verify(
        self,
        group: HostGroup,
        c: HybridCiphertext,
        pk: tuple,
        symm_key: SymmetricKey,
    ) -> bool:
        return self.proof.verify(
            group, group.generator(), c.e1, pk, symm_key.point
        )
