"""ChaCha20 stream cipher (RFC 8439), pure Python.

The DEM half of the hybrid encryption scheme (reference: elgamal.rs uses
the `chacha20` crate, Cargo.toml:13).  Byte-stream ciphers are a poor TPU
fit, so this stays host-side; share payloads are tiny (one scalar = 32
bytes).  What IS batchable is the n² DEM tail of a whole dealing round:
every sealed scalar fits one 64-byte keystream block, so the batched
entry points below run the identical quarter-round schedule over an
(N, 16)-u32 state array — one numpy dispatch per round instead of one
per (dealer, recipient) pair (SURVEY §7 step 4; docs/perf.md "Dealing
pipeline").

Implemented from the RFC, with numpy for the 16-lane state arithmetic.
The scalar and batched paths share ONE quarter-round definition
(:func:`_quarter` indexes the trailing axis), so they cannot drift.
"""

from __future__ import annotations

import numpy as np

_CONSTANTS = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)


def _rotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(state: np.ndarray, a: int, b: int, c: int, d: int) -> None:
    """One quarter round on ``state[..., 16]`` — shared by the scalar
    path ((16,) states) and the batched path ((N, 16) states)."""
    state[..., a] += state[..., b]
    state[..., d] = _rotl(state[..., d] ^ state[..., a], 16)
    state[..., c] += state[..., d]
    state[..., b] = _rotl(state[..., b] ^ state[..., c], 12)
    state[..., a] += state[..., b]
    state[..., d] = _rotl(state[..., d] ^ state[..., a], 8)
    state[..., c] += state[..., d]
    state[..., b] = _rotl(state[..., b] ^ state[..., c], 7)


def _double_rounds(working: np.ndarray) -> None:
    """The 10 ChaCha20 double rounds, in place on ``(..., 16)`` u32."""
    for _ in range(10):
        _quarter(working, 0, 4, 8, 12)
        _quarter(working, 1, 5, 9, 13)
        _quarter(working, 2, 6, 10, 14)
        _quarter(working, 3, 7, 11, 15)
        _quarter(working, 0, 5, 10, 15)
        _quarter(working, 1, 6, 11, 12)
        _quarter(working, 2, 7, 8, 13)
        _quarter(working, 3, 4, 9, 14)


def _block(key_words: np.ndarray, counter: int, nonce_words: np.ndarray) -> bytes:
    state = np.concatenate(
        [
            _CONSTANTS,
            key_words,
            np.array([counter], dtype=np.uint32),
            nonce_words,
        ]
    )
    working = state.copy()
    with np.errstate(over="ignore"):
        _double_rounds(working)
        working += state
    return working.astype("<u4").tobytes()


def chacha20_xor(key: bytes, nonce: bytes, data: bytes, counter: int = 0) -> bytes:
    """XOR ``data`` with the ChaCha20 keystream (encrypt == decrypt)."""
    if len(key) != 32:
        raise ValueError("key must be 32 bytes")
    if len(nonce) != 12:
        raise ValueError("nonce must be 12 bytes (IETF variant)")
    key_words = np.frombuffer(key, dtype="<u4").astype(np.uint32)
    nonce_words = np.frombuffer(nonce, dtype="<u4").astype(np.uint32)
    out = bytearray()
    for i in range(0, len(data), 64):
        ks = _block(key_words, counter + i // 64, nonce_words)
        chunk = data[i : i + 64]
        out.extend(b ^ k for b, k in zip(chunk, ks))
    return bytes(out)


# ---------------------------------------------------------------------------
# batched keystreams — N independent (key, nonce) lanes at once
# ---------------------------------------------------------------------------


def chacha20_block_batch(
    key_words: np.ndarray, counters: np.ndarray, nonce_words: np.ndarray
) -> np.ndarray:
    """One keystream block per lane: ``(N, 8)`` u32 keys, ``(N,)`` u32
    counters, ``(N, 3)`` u32 nonces -> ``(N, 64)`` u8 keystream.

    The whole batch is a single ``(N, 16)``-u32 state array run through
    the shared :func:`_quarter` schedule — identical bits to N calls of
    :func:`_block` (RFC 8439 vectors + equivalence in
    tests/test_dem_batch.py).
    """
    n = key_words.shape[0]
    state = np.empty((n, 16), dtype=np.uint32)
    state[:, 0:4] = _CONSTANTS
    state[:, 4:12] = key_words
    state[:, 12] = counters
    state[:, 13:16] = nonce_words
    working = state.copy()
    with np.errstate(over="ignore"):
        _double_rounds(working)
        working += state
    return np.ascontiguousarray(working.astype("<u4")).view(np.uint8)


def chacha20_xor_batch(
    keys: np.ndarray, nonces: np.ndarray, data: np.ndarray, counter: int = 0
) -> np.ndarray:
    """Batched :func:`chacha20_xor`: each row of ``data`` (``(N, mlen)``
    u8) is XORed with the keystream of its own ``(key, nonce)`` lane
    (``(N, 32)`` / ``(N, 12)`` u8).  Rows are independent messages; all
    share one length, the array shape.  Returns ``(N, mlen)`` u8.
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    nonces = np.ascontiguousarray(nonces, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    if keys.ndim != 2 or keys.shape[1] != 32:
        raise ValueError("keys must be (N, 32) bytes")
    if nonces.shape != (keys.shape[0], 12):
        raise ValueError("nonces must be (N, 12) bytes (IETF variant)")
    n, mlen = data.shape
    if n != keys.shape[0]:
        raise ValueError("data rows must match key lanes")
    if mlen == 0:
        return data.copy()
    key_words = keys.view("<u4")
    nonce_words = nonces.view("<u4")
    blocks = [
        chacha20_block_batch(
            key_words, np.full(n, counter + b, dtype=np.uint32), nonce_words
        )
        for b in range((mlen + 63) // 64)
    ]
    ks = blocks[0] if len(blocks) == 1 else np.concatenate(blocks, axis=1)
    return data ^ ks[:, :mlen]
