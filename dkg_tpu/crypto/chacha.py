"""ChaCha20 stream cipher (RFC 8439), pure Python.

The DEM half of the hybrid encryption scheme (reference: elgamal.rs uses
the `chacha20` crate, Cargo.toml:13).  Byte-stream ciphers are a poor TPU
fit and sit off the hot path (SURVEY §7 step 4), so this stays host-side;
share payloads are tiny (one scalar = 32 bytes).

Implemented from the RFC, with numpy for the 16-lane state arithmetic.
"""

from __future__ import annotations

import numpy as np

_CONSTANTS = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)


def _rotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(state: np.ndarray, a: int, b: int, c: int, d: int) -> None:
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 7)


def _block(key_words: np.ndarray, counter: int, nonce_words: np.ndarray) -> bytes:
    state = np.concatenate(
        [
            _CONSTANTS,
            key_words,
            np.array([counter], dtype=np.uint32),
            nonce_words,
        ]
    )
    working = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            _quarter(working, 0, 4, 8, 12)
            _quarter(working, 1, 5, 9, 13)
            _quarter(working, 2, 6, 10, 14)
            _quarter(working, 3, 7, 11, 15)
            _quarter(working, 0, 5, 10, 15)
            _quarter(working, 1, 6, 11, 12)
            _quarter(working, 2, 7, 8, 13)
            _quarter(working, 3, 4, 9, 14)
        working += state
    return working.astype("<u4").tobytes()


def chacha20_xor(key: bytes, nonce: bytes, data: bytes, counter: int = 0) -> bytes:
    """XOR ``data`` with the ChaCha20 keystream (encrypt == decrypt)."""
    if len(key) != 32:
        raise ValueError("key must be 32 bytes")
    if len(nonce) != 12:
        raise ValueError("nonce must be 12 bytes (IETF variant)")
    key_words = np.frombuffer(key, dtype="<u4").astype(np.uint32)
    nonce_words = np.frombuffer(nonce, dtype="<u4").astype(np.uint32)
    out = bytearray()
    for i in range(0, len(data), 64):
        ks = _block(key_words, counter + i // 64, nonce_words)
        chunk = data[i : i + 64]
        out.extend(b ^ k for b, k in zip(chunk, ks))
    return bytes(out)
