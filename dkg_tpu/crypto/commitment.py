"""Pedersen commitments over any HostGroup backend.

Functional parity with the reference (reference:
src/cryptography/commitment.rs): commitment key derived from a shared
string by hash-to-group (no trusted setup, :13-17), commit = g*m + h*r
(:24-26), verify (:54-57), Open (:60-64).  The batched device twin of
``commit`` lives in the ceremony engine (double fixed-base kernel,
SURVEY §2 table row 2).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..groups.host import HostGroup

DOMAIN_COMMITMENT_KEY = b"dkgtpu-ck"


@dataclass(frozen=True)
class CommitmentKey:
    """The second Pedersen base ``h`` (reference: commitment.rs:7-9)."""

    h: tuple

    @classmethod
    def generate(cls, group: HostGroup, shared_string: bytes) -> "CommitmentKey":
        """Deterministic from the ceremony's shared string — every party
        derives the same ``h`` (reference: commitment.rs:13-17)."""
        return cls(group.hash_to_group(shared_string, DOMAIN_COMMITMENT_KEY))


@dataclass(frozen=True)
class Open:
    """A commitment opening (m, r) (reference: commitment.rs:60-64)."""

    m: int
    r: int


def commit_with_random(group: HostGroup, ck: CommitmentKey, m: int, r: int):
    """g*m + h*r (reference: commitment.rs:24-26)."""
    return group.add(
        group.scalar_mul(m, group.generator()), group.scalar_mul(r, ck.h)
    )


def commit(group: HostGroup, ck: CommitmentKey, m: int, rng) -> tuple:
    """Commit with fresh randomness; returns (commitment, Open)."""
    r = group.random_scalar(rng)
    return commit_with_random(group, ck, m, r), Open(m, r)


def verify(group: HostGroup, ck: CommitmentKey, commitment, o: Open) -> bool:
    """Recompute-and-compare (reference: commitment.rs:54-57)."""
    return group.eq(commitment, commit_with_random(group, ck, o.m, o.r))
