"""Lifted ElGamal + hybrid (KEM/DEM) encryption over any HostGroup.

Functional parity with the reference (reference:
src/cryptography/elgamal.rs): keypairs (:52-131), lifted homomorphic
`Ciphertext` (:38-41, ops :219-283), and the hybrid scheme used to
deliver shares — ElGamal KEM to a symmetric point, Blake2b KDF to a
ChaCha20 key+nonce, stream-cipher DEM (:45-50, :134-193).

KEM scalar-mults are the device-batched hot half (SURVEY §2 table);
this module is the host oracle + per-message cold path.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..groups.host import HostGroup
from .chacha import chacha20_xor


@dataclass(frozen=True)
class Keypair:
    """sk, pk = g*sk (reference: elgamal.rs:52-80)."""

    sk: int
    pk: tuple

    @classmethod
    def generate(cls, group: HostGroup, rng) -> "Keypair":
        sk = group.random_scalar(rng)
        return cls(sk, group.scalar_mul(sk, group.generator()))

    @classmethod
    def from_secret(cls, group: HostGroup, sk: int) -> "Keypair":
        return cls(sk, group.scalar_mul(sk, group.generator()))


@dataclass(frozen=True)
class Ciphertext:
    """Lifted-ElGamal ciphertext (e1, e2) = (r*G, m*G + r*PK)
    (reference: elgamal.rs:38-41).

    Carries its group (compare-excluded) so the Python operators
    ``a + b``, ``a - b``, ``a * k`` / ``k * a`` work directly — the
    ergonomic twin of the reference's operator-forwarding macros over
    every borrow combination (reference: src/macros.rs:3-43,
    elgamal.rs:219-283).  Constructors thread the group automatically;
    the explicit ``add/sub/mul_scalar(group, ...)`` forms remain for
    group-free deserialized values.
    """

    e1: tuple
    e2: tuple
    group: "HostGroup | None" = None

    def add(self, group: HostGroup, other: "Ciphertext") -> "Ciphertext":
        """Homomorphic sum (reference: elgamal.rs:219-234)."""
        return Ciphertext(
            group.add(self.e1, other.e1), group.add(self.e2, other.e2), group
        )

    def sub(self, group: HostGroup, other: "Ciphertext") -> "Ciphertext":
        return Ciphertext(
            group.sub(self.e1, other.e1), group.sub(self.e2, other.e2), group
        )

    def mul_scalar(self, group: HostGroup, k: int) -> "Ciphertext":
        """Homomorphic scalar mult (reference: elgamal.rs:260-283)."""
        return Ciphertext(
            group.scalar_mul(k, self.e1), group.scalar_mul(k, self.e2), group
        )

    def _require_group(self) -> HostGroup:
        if self.group is None:
            raise TypeError(
                "operator form needs a group-carrying Ciphertext; use "
                ".add/.sub/.mul_scalar(group, ...) or "
                "dataclasses.replace(ct, group=g)"
            )
        return self.group

    def __add__(self, other):
        if not isinstance(other, Ciphertext):
            return NotImplemented
        return self.add(self._require_group(), other)

    def __sub__(self, other):
        if not isinstance(other, Ciphertext):
            return NotImplemented
        return self.sub(self._require_group(), other)

    def __mul__(self, k):
        if not isinstance(k, int):
            return NotImplemented
        return self.mul_scalar(self._require_group(), k)

    __rmul__ = __mul__

    def __eq__(self, other):  # group is context, not content
        if not isinstance(other, Ciphertext):
            return NotImplemented
        return self.e1 == other.e1 and self.e2 == other.e2

    def __hash__(self):
        return hash((self.e1, self.e2))


def encrypt_point(group: HostGroup, pk: tuple, m_point: tuple, rng) -> Ciphertext:
    """ElGamal on a group element (reference: elgamal.rs:97-105)."""
    r = group.random_scalar(rng)
    return encrypt_point_with_random(group, pk, m_point, r)


def encrypt_point_with_random(
    group: HostGroup, pk: tuple, m_point: tuple, r: int
) -> Ciphertext:
    e1 = group.scalar_mul(r, group.generator())
    e2 = group.add(m_point, group.scalar_mul(r, pk))
    return Ciphertext(e1, e2, group)


def encrypt(group: HostGroup, pk: tuple, m: int, rng) -> Ciphertext:
    """Lifted ElGamal: encrypts m*G (reference: elgamal.rs:107-115)."""
    return encrypt_point(group, pk, group.scalar_mul(m, group.generator()), rng)


def decrypt_point(group: HostGroup, sk: int, c: Ciphertext) -> tuple:
    """m*G = e2 - sk*e1 (reference: elgamal.rs:157-159)."""
    return group.sub(c.e2, group.scalar_mul(sk, c.e1))


# ---------------------------------------------------------------------------
# hybrid encryption (the share-delivery scheme, lib.rs:1-6)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HybridCiphertext:
    """(e1 = r*G, ChaCha20-encrypted payload) (reference: elgamal.rs:45-50)."""

    e1: tuple
    ciphertext: bytes


@dataclass(frozen=True)
class SymmetricKey:
    """The KEM group element pk*r == sk*e1 (reference: elgamal.rs:56-58)."""

    point: tuple


# KDF personalisation tags.  A (share, commitment-randomness) pair is
# sealed under ONE KEM point with distinct tags — one ElGamal
# exponentiation per recipient instead of the reference's two
# (elgamal.rs:134-145 is invoked twice per recipient from
# procedure_keys.rs:113-119); domain separation comes from the tag.
PERSON_SHARE = b"dkgtpu-kdf"
PERSON_RAND = b"dkgtpu-kd2"


def keystream_from_kem_bytes(kem_bytes: bytes, person: bytes) -> tuple[bytes, bytes]:
    """Blake2b-512(kem_bytes) -> (32-byte key, 12-byte nonce).  The ONE
    definition of the KDF layout — the batched device path
    (dkg.hybrid_batch) feeds precomputed point encodings through here
    too, so wire and batched paths cannot desynchronise."""
    digest = hashlib.blake2b(kem_bytes, digest_size=64, person=person).digest()
    return digest[:32], digest[32:44]


def _keystream_params(
    group: HostGroup, kem_point: tuple, person: bytes = PERSON_SHARE
) -> tuple[bytes, bytes]:
    """(reference: elgamal.rs:180-193 initialise_encryption)"""
    return keystream_from_kem_bytes(group.encode(kem_point), person)


def hybrid_encrypt(group: HostGroup, pk: tuple, message: bytes, rng) -> HybridCiphertext:
    """KEM: pk*r; DEM: ChaCha20 (reference: elgamal.rs:134-145)."""
    r = group.random_scalar(rng)
    return hybrid_encrypt_with_random(group, pk, message, r)


def hybrid_encrypt_with_random(
    group: HostGroup, pk: tuple, message: bytes, r: int, person: bytes = PERSON_SHARE
) -> HybridCiphertext:
    e1 = group.scalar_mul(r, group.generator())
    kem = group.scalar_mul(r, pk)
    key, nonce = _keystream_params(group, kem, person)
    return HybridCiphertext(e1, chacha20_xor(key, nonce, message))


def recover_symmetric_key(group: HostGroup, sk: int, c: HybridCiphertext) -> SymmetricKey:
    """sk*e1 (reference: elgamal.rs:161-168)."""
    return SymmetricKey(group.scalar_mul(sk, c.e1))


def hybrid_decrypt_with_key(
    group: HostGroup, symm: SymmetricKey, c: HybridCiphertext, person: bytes = PERSON_SHARE
) -> bytes:
    """Decrypt given a disclosed KEM key — the complaint-verification path
    (reference: elgamal.rs:147-155 + broadcast.rs:244-255)."""
    key, nonce = _keystream_params(group, symm.point, person)
    return chacha20_xor(key, nonce, c.ciphertext)


def hybrid_decrypt(
    group: HostGroup, sk: int, c: HybridCiphertext, person: bytes = PERSON_SHARE
) -> bytes:
    return hybrid_decrypt_with_key(group, recover_symmetric_key(group, sk, c), c, person)


# ---------------------------------------------------------------------------
# pair sealing — the canonical wire format for share delivery
# ---------------------------------------------------------------------------


def rand_person(group: HostGroup, share_ct: HybridCiphertext, rand_ct: HybridCiphertext) -> bytes:
    """KDF tag for the randomness ciphertext of a pair: PERSON_RAND when
    it shares the KEM point with the share ciphertext (the canonical
    sealed-pair format), PERSON_SHARE for independently-encrypted pairs
    (the reference's two-KEM layout, still accepted)."""
    return PERSON_RAND if group.eq(share_ct.e1, rand_ct.e1) else PERSON_SHARE


def seal_pair(
    group: HostGroup, pk: tuple, share_bytes: bytes, rand_bytes: bytes, rng
) -> tuple[HybridCiphertext, HybridCiphertext]:
    """Seal a (share, randomness) pair under one KEM exponentiation."""
    r = group.random_scalar(rng)
    e1 = group.scalar_mul(r, group.generator())
    kem = group.scalar_mul(r, pk)
    k1, n1 = _keystream_params(group, kem, PERSON_SHARE)
    k2, n2 = _keystream_params(group, kem, PERSON_RAND)
    return (
        HybridCiphertext(e1, chacha20_xor(k1, n1, share_bytes)),
        HybridCiphertext(e1, chacha20_xor(k2, n2, rand_bytes)),
    )


def open_pair(
    group: HostGroup, sk: int, share_ct: HybridCiphertext, rand_ct: HybridCiphertext
) -> tuple[bytes, bytes]:
    """Decrypt a pair, honouring either pair layout (see rand_person).

    The canonical shared-KEM layout costs ONE sk*e1 exponentiation for
    both payloads; the legacy two-KEM layout falls back to two.
    """
    kem1 = recover_symmetric_key(group, sk, share_ct)
    if group.eq(share_ct.e1, rand_ct.e1):
        kem2 = kem1
    else:
        kem2 = recover_symmetric_key(group, sk, rand_ct)
    return open_pair_with_kems(group, kem1, kem2, share_ct, rand_ct)


def open_pair_with_kems(
    group: HostGroup,
    kem1: SymmetricKey,
    kem2: SymmetricKey,
    share_ct: HybridCiphertext,
    rand_ct: HybridCiphertext,
) -> tuple[bytes, bytes]:
    """DEM half of :func:`open_pair`, with the KEM exponentiations
    (sk*e1 per distinct e1) supplied by the caller — the batched wire
    path (dkg.committee_batch) computes those on device in bulk."""
    pt1 = hybrid_decrypt_with_key(group, kem1, share_ct, PERSON_SHARE)
    pt2 = hybrid_decrypt_with_key(
        group, kem2, rand_ct, rand_person(group, share_ct, rand_ct)
    )
    return pt1, pt2
