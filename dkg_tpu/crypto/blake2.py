"""Vectorized BLAKE2b (RFC 7693), numpy u64 lanes.

The hybrid-encryption KDF hashes one fixed-length point encoding per
(dealer, recipient) pair — O(n²) ``hashlib.blake2b`` calls per dealing
round in the scalar path.  Here the compression function F runs over an
``(N, 16)``-u64 message batch instead: one numpy dispatch per G-call for
the whole round (docs/perf.md "Dealing pipeline").

``hashlib`` stays the bit-exactness oracle (tests/test_dem_batch.py
checks random lengths and personalisations); the layout of the derived
key/nonce split itself is still owned by
``crypto.elgamal.keystream_from_kem_bytes`` — :func:`kdf_batch` below is
its array-shaped twin and must match it byte for byte.

Scope: unkeyed, unsalted, sequential mode — digest_size + personal are
the only parameters the DKG uses (elgamal.rs KDF parity).  All rows of
a batch share one message length (they are fixed-width point encodings).
"""

from __future__ import annotations

import numpy as np

_IV = np.array(
    [
        0x6A09E667F3BCC908,
        0xBB67AE8584CAA73B,
        0x3C6EF372FE94F82B,
        0xA54FF53A5F1D36F1,
        0x510E527FADE682D1,
        0x9B05688C2B3E6C1F,
        0x1F83D9ABFB41BD6B,
        0x5BE0CD19137E2179,
    ],
    dtype=np.uint64,
)

_SIGMA = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint64(n)) | (x << np.uint64(64 - n))


def _g(v: np.ndarray, a: int, b: int, c: int, d: int, x: np.ndarray, y: np.ndarray) -> None:
    """RFC 7693 §3.1 mixing function G on ``(N, 16)`` u64 work vectors."""
    v[:, a] += v[:, b] + x
    v[:, d] = _rotr(v[:, d] ^ v[:, a], 32)
    v[:, c] += v[:, d]
    v[:, b] = _rotr(v[:, b] ^ v[:, c], 24)
    v[:, a] += v[:, b] + y
    v[:, d] = _rotr(v[:, d] ^ v[:, a], 16)
    v[:, c] += v[:, d]
    v[:, b] = _rotr(v[:, b] ^ v[:, c], 63)


def _compress(h: np.ndarray, m: np.ndarray, t: int, last: bool) -> None:
    """RFC 7693 §3.2 compression F, in place on ``h`` (``(N, 8)`` u64);
    ``m`` is the ``(N, 16)``-u64 message block batch, ``t`` the byte
    offset counter (shared by all rows — equal-length messages)."""
    n = h.shape[0]
    v = np.empty((n, 16), dtype=np.uint64)
    v[:, :8] = h
    v[:, 8:] = _IV
    v[:, 12] ^= np.uint64(t & 0xFFFFFFFFFFFFFFFF)
    v[:, 13] ^= np.uint64(t >> 64)
    if last:
        v[:, 14] = ~v[:, 14]
    for s in _SIGMA:
        _g(v, 0, 4, 8, 12, m[:, s[0]], m[:, s[1]])
        _g(v, 1, 5, 9, 13, m[:, s[2]], m[:, s[3]])
        _g(v, 2, 6, 10, 14, m[:, s[4]], m[:, s[5]])
        _g(v, 3, 7, 11, 15, m[:, s[6]], m[:, s[7]])
        _g(v, 0, 5, 10, 15, m[:, s[8]], m[:, s[9]])
        _g(v, 1, 6, 11, 12, m[:, s[10]], m[:, s[11]])
        _g(v, 2, 7, 8, 13, m[:, s[12]], m[:, s[13]])
        _g(v, 3, 4, 9, 14, m[:, s[14]], m[:, s[15]])
    h ^= v[:, :8] ^ v[:, 8:]


def blake2b_batch(
    msgs: np.ndarray, digest_size: int = 64, person: bytes = b""
) -> np.ndarray:
    """BLAKE2b over each row of ``msgs`` (``(N, mlen)`` u8): returns
    ``(N, digest_size)`` u8, row i == ``hashlib.blake2b(bytes(msgs[i]),
    digest_size=digest_size, person=person).digest()``.
    """
    if not 1 <= digest_size <= 64:
        raise ValueError("digest_size must be 1..64")
    if len(person) > 16:
        raise ValueError("person must be <= 16 bytes")
    msgs = np.ascontiguousarray(np.atleast_2d(msgs), dtype=np.uint8)
    n, mlen = msgs.shape
    h = np.broadcast_to(_IV, (n, 8)).copy()
    # parameter block (RFC 7693 §2.5): digest_length | key_length<<8 |
    # fanout<<16 | depth<<24 in word 0, personal in words 6-7
    h[:, 0] ^= np.uint64(digest_size | 0x01010000)
    pers = np.frombuffer(person.ljust(16, b"\0"), dtype="<u8")
    h[:, 6] ^= pers[0]
    h[:, 7] ^= pers[1]
    nblocks = max(1, (mlen + 127) // 128)
    padded = np.zeros((n, nblocks * 128), dtype=np.uint8)
    padded[:, :mlen] = msgs
    words = padded.view("<u8").astype(np.uint64).reshape(n, nblocks, 16)
    with np.errstate(over="ignore"):
        for b in range(nblocks - 1):
            _compress(h, words[:, b], (b + 1) * 128, last=False)
        _compress(h, words[:, nblocks - 1], mlen, last=True)
    return np.ascontiguousarray(h.astype("<u8")).view(np.uint8)[:, :digest_size]


def kdf_batch(kem_enc: np.ndarray, person: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Array twin of ``crypto.elgamal.keystream_from_kem_bytes``:
    ``(N, enc_len)`` u8 KEM-point encodings -> (``(N, 32)`` u8 ChaCha
    keys, ``(N, 12)`` u8 nonces), one lane per (dealer, recipient) pair.
    """
    digest = blake2b_batch(kem_enc, digest_size=64, person=person)
    return digest[:, :32], digest[:, 32:44]
