"""Chaum-Pedersen discrete-log-equality NIZK (Fiat-Shamir via Blake2b).

Functional parity with the reference (reference:
src/cryptography/dl_equality/zkp.rs and challenge_context.rs): proves
knowledge of x with point1 = base1*x and point2 = base2*x; proof is
(challenge, response) with the challenge recomputed on verify.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..groups.host import HostGroup

DOMAIN_DLEQ = b"dkgtpu-dleq"


def _challenge(
    group: HostGroup, base1, base2, point1, point2, a1, a2
) -> int:
    """Fiat-Shamir challenge over the full transcript (reference:
    challenge_context.rs:10-42 feeds bases, statement points, and both
    announcements into Blake2b)."""
    h = hashlib.blake2b(digest_size=64, person=DOMAIN_DLEQ)
    for p in (base1, base2, point1, point2, a1, a2):
        h.update(group.encode(p))
    return int.from_bytes(h.digest(), "little") % group.scalar_field.modulus


@dataclass(frozen=True)
class DleqZkp:
    """(challenge, response) (reference: zkp.rs:22-25)."""

    challenge: int
    response: int

    @classmethod
    def generate(
        cls, group: HostGroup, base1, base2, point1, point2, dlog: int, rng
    ) -> "DleqZkp":
        """Announce a_i = base_i*w, challenge e = H(transcript),
        response z = w + e*dlog (reference: zkp.rs:29-51)."""
        w = group.random_scalar(rng)
        a1 = group.scalar_mul(w, base1)
        a2 = group.scalar_mul(w, base2)
        e = _challenge(group, base1, base2, point1, point2, a1, a2)
        z = (w + e * dlog) % group.scalar_field.modulus
        return cls(e, z)

    def verify(self, group: HostGroup, base1, base2, point1, point2) -> bool:
        """Recompute announcements a_i = base_i*z - point_i*e and check the
        challenge matches (reference: zkp.rs:54-74).  Proof scalars are
        public, so verification is vartime like the reference's."""
        a1 = group.sub(
            group.scalar_mul_vartime(self.response, base1),
            group.scalar_mul_vartime(self.challenge, point1),
        )
        a2 = group.sub(
            group.scalar_mul_vartime(self.response, base2),
            group.scalar_mul_vartime(self.challenge, point2),
        )
        return self.challenge == _challenge(
            group, base1, base2, point1, point2, a1, a2
        )
