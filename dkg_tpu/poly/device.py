"""Batched polynomial kernels over limb arrays (JAX, TPU-first).

The DKG hot loops this replaces (SURVEY §2 parallelism table):

* per-recipient share generation — the reference evaluates each dealing
  polynomial serially per index (reference: committee.rs:163-186 →
  polynomial.rs:68-74, a powers-of-x dot product).  Here ``eval_many``
  is one Horner scan batched over (dealers × recipients) at once.
* index powers (1, i, i^2, ..., i^t) used by share verification
  (reference: committee.rs:287-290 via traits.rs:172-178 ``exp_iter``)
  — ``powers`` builds them as one scan, batched over all verifiers.
* Lagrange reconstruction at zero (reference: polynomial.rs:162-184,
  committee.rs:784-789) — ``lagrange_at_zero`` with Montgomery-trick
  batched inversion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..fields import device as fd
from ..fields.spec import FieldSpec
from .host import DuplicateEvaluationPoints

# HBM budget for eval_many's MXU Vandermonde (+ digit) temps; the point
# axis is chunked to stay under it.  Module-level so tests can shrink it
# to force the chunked path at toy sizes.
EVAL_VAND_BUDGET_BYTES = 1 << 30


def eval_many(fs: FieldSpec, coeffs: jax.Array, xs: jax.Array) -> jax.Array:
    """Evaluate polynomials at many points: Horner over the coeff axis.

    coeffs: (..., T, L) — T = degree+1 coefficients, low-order first.
    xs:     (..., N, L) — N evaluation points.
    returns (..., N, L) — values; batch axes broadcast.
    """
    from ..fields import matmul as fmm

    if (fmm.mxu_matmul_active() and coeffs.ndim == 3 and xs.ndim == 2
            and coeffs.shape[-2] <= fmm.MAX_K):
        # Vandermonde form on the MXU: one int8 systolic contraction over
        # the T coefficients instead of T sequential VPU field multiplies.
        # V[i, l] = x_i^l costs T muls over (N, L) — negligible vs the
        # (D, T) x (T, N) product it feeds.  The POINT axis is chunked:
        # the Vandermonde and its digit tensor are O(N * T * L) and the
        # TPU compiler rejected the full-N build at the BLS n=16384
        # shape (u32[16384,5462,32] = 10.7 GB + a 14.5 GB padded copy,
        # MEMPROOF_TPU_deal_error.txt); chunks ride a lax.map so temps
        # are reused, with a ragged tail as one smaller call.
        t_coef = coeffs.shape[-2]

        def mxu_eval(xc):
            return fmm.matmul_mod(fs, coeffs, powers(fs, xc, t_coef))

        n_pts = xs.shape[-2]
        per_point = t_coef * 3 * fs.limbs * 4  # vand + 2L digit columns
        chunk = max(1, EVAL_VAND_BUDGET_BYTES // per_point)
        chunk = 1 << (chunk.bit_length() - 1)
        if chunk >= n_pts:
            return mxu_eval(xs)
        k, rem = divmod(n_pts, chunk)
        head = k * chunk
        outs = lax.map(mxu_eval, xs[:head].reshape(k, chunk, fs.limbs))
        out = jnp.moveaxis(outs, 0, -3)  # (m, k, chunk, L)
        out = out.reshape(out.shape[:-3] + (head, fs.limbs))
        if rem:
            out = jnp.concatenate([out, mxu_eval(xs[head:])], axis=-2)
        return out

    # scan MSB-first over coefficients: acc = acc*x + c_k
    cs_rev = jnp.moveaxis(coeffs, -2, 0)[::-1]  # (T, ..., L)
    batch = jnp.broadcast_shapes(coeffs.shape[:-2], xs.shape[:-2])
    init = fd.zeros(fs, batch + (xs.shape[-2],))

    if fd.fused_kernels_active():
        from ..ops import pallas_field

        def step_fused(acc, c):
            # one launch per Horner step: acc <- acc*x + c
            return pallas_field.mod_madd(fs, acc, xs, c[..., None, :]), None

        acc, _ = lax.scan(step_fused, init, cs_rev)
        return acc

    def step(acc, c):
        # acc: (..., N, L); c: (..., L) broadcast over N
        acc = fd.mul(fs, acc, xs)
        return fd.add(fs, acc, c[..., None, :]), None

    acc, _ = lax.scan(step, init, cs_rev)
    return acc


def powers(fs: FieldSpec, x: jax.Array, count: int) -> jax.Array:
    """(1, x, x^2, ..., x^(count-1)): x (..., L) -> (..., count, L).

    Batched replacement for the reference's ``exp_iter``
    (reference: src/traits.rs:172-202)."""

    def step(acc, _):
        nxt = fd.mul(fs, acc, x)
        return nxt, acc

    init = jnp.broadcast_to(fd.ones(fs), x.shape)
    _, out = lax.scan(step, init, None, length=count)  # (count, ..., L)
    return jnp.moveaxis(out, 0, -2)


def _check_distinct_nodes_device(fs: FieldSpec, xs) -> None:
    """Eager duplicate-node guard for the Lagrange kernels.

    Compares limb rows, which is exact for canonically reduced limbs
    (the fields-layer contract: fh.encode and every fd op emit values
    < p).  Tracers are skipped — under jit the values are abstract."""
    if isinstance(xs, jax.core.Tracer):
        return
    arr = np.asarray(xs)
    m = arr.shape[-2]
    if m <= 1:
        return
    flat = arr.reshape(-1, m, arr.shape[-1])
    for b in range(flat.shape[0]):
        if len(np.unique(flat[b], axis=0)) != m:
            raise DuplicateEvaluationPoints(
                f"duplicate evaluation point among {m} Lagrange nodes "
                f"(batch {b})"
            )


def lagrange_at_zero_coeffs(fs: FieldSpec, xs: jax.Array) -> jax.Array:
    """Lagrange coefficients lambda_i(0) for nodes xs: (..., M, L) -> same.

    lambda_i(0) = prod_{j!=i} x_j / (x_j - x_i).  Numerators via masked
    full-product; denominators inverted with one batched Fermat inversion
    (Montgomery trick in fd.batch_inv).

    Duplicate nodes within one batch would put a zero factor in a
    denominator and make the Fermat inversion return garbage silently;
    eager (concrete) inputs therefore raise the typed
    :class:`~dkg_tpu.poly.host.DuplicateEvaluationPoints` up front.
    Inside a trace (jit/vmap) values are abstract and the check is
    skipped — jitted callers own node distinctness.
    """
    _check_distinct_nodes_device(fs, xs)
    m = xs.shape[-2]
    xi = xs[..., :, None, :]  # (..., M, 1, L)
    xj = xs[..., None, :, :]  # (..., 1, M, L)
    diff = fd.sub(fs, xj, xi)  # (..., M, M, L): x_j - x_i
    one = jnp.broadcast_to(fd.ones(fs), diff.shape)
    eye = jnp.eye(m, dtype=bool)
    eye = eye.reshape((1,) * (xs.ndim - 2) + (m, m))
    num_terms = fd.select(jnp.broadcast_to(eye, diff.shape[:-1]), one,
                          jnp.broadcast_to(xj, diff.shape))
    den_terms = fd.select(jnp.broadcast_to(eye, diff.shape[:-1]), one, diff)

    def prod_axis(terms):
        t = jnp.moveaxis(terms, -2, 0)  # (M, ..., M, L)

        def step(acc, v):
            return fd.mul(fs, acc, v), None

        init = jnp.broadcast_to(fd.ones(fs), t.shape[1:])
        acc, _ = lax.scan(step, init, t)
        return acc

    nums = prod_axis(num_terms)  # (..., M, L)
    dens = prod_axis(den_terms)  # (..., M, L)
    return fd.mul(fs, nums, fd.batch_inv(fs, dens, axis=-2))


def lagrange_at_zero(fs: FieldSpec, xs: jax.Array, ys: jax.Array) -> jax.Array:
    """Interpolate through (xs, ys) and evaluate at 0: (..., M, L) -> (..., L).

    The reconstruction step of the protocol (reference:
    committee.rs:784-789 → polynomial.rs:172-184), batched over leading
    axes (many reconstructed parties at once).
    """
    lam = lagrange_at_zero_coeffs(fs, xs)
    terms = fd.mul(fs, lam, ys)  # (..., M, L)
    t = jnp.moveaxis(terms, -2, 0)

    def step(acc, v):
        return fd.add(fs, acc, v), None

    acc, _ = lax.scan(step, fd.zeros(fs, terms.shape[:-2]), t)
    return acc
