"""Secret-sharing polynomial algebra (reference: src/polynomial.rs)."""

from .host import (  # noqa: F401
    Polynomial,
    interpolate,
    lagrange_coefficient,
    lagrange_interpolation,
)
