"""Host-side secret-sharing polynomials over a scalar field.

Oracle + cold-path twin of :mod:`dkg_tpu.poly.device`.  Functional parity
with the reference's `Polynomial` (reference: src/polynomial.rs:11-184):
random generation, evaluation, `at_zero`, add/mul, full interpolation and
scalar Lagrange interpolation.  Evaluation here is Horner (the reference
uses a powers-of-x dot product, polynomial.rs:68-74 — same function,
cheaper scheme).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..fields.spec import FieldSpec


@dataclass(frozen=True)
class Polynomial:
    """coeffs[k] is the x**k coefficient; degree = len(coeffs)-1."""

    fs: FieldSpec
    coeffs: tuple

    @classmethod
    def random(cls, fs: FieldSpec, degree: int, rng) -> "Polynomial":
        """Uniform degree-``degree`` polynomial (reference:
        polynomial.rs:59-65 — t+1 random coefficients)."""
        return cls(fs, tuple(fs.rand_int(rng) for _ in range(degree + 1)))

    @classmethod
    def from_ints(cls, fs: FieldSpec, coeffs) -> "Polynomial":
        return cls(fs, tuple(int(c) % fs.modulus for c in coeffs))

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> int:
        """Horner evaluation (reference: polynomial.rs:68-74)."""
        p, acc = self.fs.modulus, 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % p
        return acc

    def at_zero(self) -> int:
        """Constant term = the shared secret (reference: polynomial.rs:77-79)."""
        return self.coeffs[0]

    def __add__(self, other: "Polynomial") -> "Polynomial":
        p = self.fs.modulus
        n = max(len(self.coeffs), len(other.coeffs))
        a = list(self.coeffs) + [0] * (n - len(self.coeffs))
        b = list(other.coeffs) + [0] * (n - len(other.coeffs))
        return Polynomial(self.fs, tuple((x + y) % p for x, y in zip(a, b)))

    def __mul__(self, other: "Polynomial") -> "Polynomial":
        """Schoolbook product (reference: polynomial.rs:145-160)."""
        p = self.fs.modulus
        out = [0] * (len(self.coeffs) + len(other.coeffs) - 1)
        for i, a in enumerate(self.coeffs):
            for j, b in enumerate(other.coeffs):
                out[i + j] = (out[i + j] + a * b) % p
        return Polynomial(self.fs, tuple(out))


class DuplicateEvaluationPoints(ValueError):
    """Two Lagrange interpolation nodes coincide (mod p).

    The basis denominators prod (x_j - x_i) contain a zero factor, so
    the Fermat/Montgomery inversions silently return garbage instead of
    failing — every interpolation entry point (host and device) raises
    this typed error up front instead."""


def check_distinct_nodes(fs: FieldSpec, xs) -> None:
    """Raise :class:`DuplicateEvaluationPoints` unless all nodes are
    distinct mod p."""
    p = fs.modulus
    seen: dict[int, int] = {}
    for k, x in enumerate(xs):
        r = int(x) % p
        if r in seen:
            raise DuplicateEvaluationPoints(
                f"duplicate evaluation point x={r} at positions "
                f"{seen[r]} and {k}"
            )
        seen[r] = k


def lagrange_coefficient(fs: FieldSpec, eval_point: int, i: int, xs) -> int:
    """lambda_i(eval_point) = prod_{j != i} (x_j - e)/(x_j - x_i)
    (reference: polynomial.rs:162-170)."""
    check_distinct_nodes(fs, xs)
    p = fs.modulus
    num, den = 1, 1
    for j, xj in enumerate(xs):
        if j == i:
            continue
        num = num * (xj - eval_point) % p
        den = den * (xj - xs[i]) % p
    return num * pow(den, p - 2, p) % p


def lagrange_interpolation(fs: FieldSpec, eval_point: int, ys, xs) -> int:
    """Interpolate the unique degree-(m-1) polynomial through (xs, ys) and
    evaluate it at ``eval_point`` (reference: polynomial.rs:172-184).
    Protocol use: share reconstruction at 0 (committee.rs:784-789)."""
    if len(xs) != len(ys):
        raise ValueError("xs and ys must have equal length")
    check_distinct_nodes(fs, xs)
    p = fs.modulus
    acc = 0
    for i, yi in enumerate(ys):
        acc = (acc + yi * lagrange_coefficient(fs, eval_point, i, xs)) % p
    return acc


def interpolate(fs: FieldSpec, xs, ys) -> Polynomial:
    """Full polynomial interpolation (reference: polynomial.rs:92-110)."""
    if len(xs) != len(ys) or not xs:
        raise ValueError("need equal-length non-empty xs, ys")
    check_distinct_nodes(fs, xs)
    p = fs.modulus
    result = Polynomial(fs, (0,))
    for i, (xi, yi) in enumerate(zip(xs, ys)):
        term = Polynomial(fs, (yi % p,))
        for j, xj in enumerate(xs):
            if j == i:
                continue
            inv = pow((xi - xj) % p, p - 2, p)
            # factor (x - x_j)/(x_i - x_j)
            term = term * Polynomial(fs, ((-xj) * inv % p, inv))
        result = result + term
    return result
