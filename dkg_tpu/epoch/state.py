"""Per-party epoch state: the aggregate sharing a committee holds NOW.

An epoch is one lifetime of one (n, t) Shamir sharing of the master
secret.  Epoch 0 is the DKG ceremony's output; each successful refresh
or reshare operation produces epoch k+1.  The whole state is public
except ``share``: ``commitments`` are the Feldman commitments
(A_0..A_t) of the aggregate sharing polynomial F, so

* ``commitments[0] == g*F(0)`` is the master public key — bit-identical
  across epochs (the invariance argument in docs/resharing.md);
* ``g*share == eval(commitments, index)`` for every honest holder.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

from ..groups.host import HostGroup
from ..utils import serde

KIND_REFRESH = 1
KIND_RESHARE = 2
KIND_NAMES = {KIND_REFRESH: "refresh", KIND_RESHARE: "reshare"}


@dataclass(frozen=True)
class EpochState:
    """One party's view of the current epoch's sharing.

    ``index``/``share`` are None for observers (e.g. a joiner
    bootstrapping into a reshare, who holds no share of the CURRENT
    epoch); ``commitments`` is None only for a joiner before it has
    learned the current aggregate from the reshare deals.
    """

    epoch: int
    n: int
    t: int
    index: Optional[int]  # 1-based index in the current committee
    share: Optional[int]  # share of the aggregate polynomial F
    commitments: Optional[tuple]  # (t+1) aggregate bare commitments

    @property
    def master(self):
        """The master public key point (A_0), None for bootstrapping
        observers."""
        return self.commitments[0] if self.commitments else None

    @property
    def holds_share(self) -> bool:
        return self.index is not None and self.share is not None


def genesis_from_party_result(env, res) -> EpochState:
    """Epoch-0 state from a successful ceremony ``PartyResult``.

    Requires the aggregate commitments (net.party computes them when no
    dealer went through share reconstruction); raises EpochError
    otherwise — epoch operations need the commitments to verify deals
    against.
    """
    from .errors import EpochError

    if not res.ok or res.share is None:
        raise EpochError("NO_GENESIS", f"party {res.index} has no ceremony outcome")
    if res.commitments is None:
        raise EpochError(
            "NO_GENESIS",
            f"party {res.index} has no aggregate commitments "
            "(reconstruction-path ceremonies cannot seed epochs)",
        )
    return EpochState(
        epoch=0,
        n=env.nr_members,
        t=env.threshold,
        index=res.index,
        share=res.share.value,
        commitments=res.commitments,
    )


def confirm_digest(
    group: HostGroup, kind: int, epoch: int, n: int, t: int, commitments: tuple
) -> bytes:
    """16-byte digest every member of the NEW committee must agree on
    before an epoch op concludes: binds the op kind, the epoch number,
    the committee shape and the full aggregate commitment tuple (and
    therefore the master key)."""
    h = hashlib.blake2b(digest_size=16, person=b"dkgepoch")
    h.update(bytes([kind]))
    h.update(epoch.to_bytes(4, "little"))
    h.update(n.to_bytes(2, "little"))
    h.update(t.to_bytes(2, "little"))
    for c in commitments:
        h.update(group.encode(c))
    return h.digest()


def encode_epoch_state(group: HostGroup, st: EpochState) -> bytes:
    """Deterministic byte encoding (WAL confirm records pin this)."""
    w = serde.Writer(group)
    w.u32(st.epoch)
    w.u16(st.n)
    w.u16(st.t)
    w.u8(1 if st.index is not None else 0)
    if st.index is not None:
        w.u16(st.index)
    w.u8(1 if st.share is not None else 0)
    if st.share is not None:
        w.scalar(st.share)
    w.u8(1 if st.commitments is not None else 0)
    if st.commitments is not None:
        w.u16(len(st.commitments))
        for c in st.commitments:
            w.point(c)
    return w.bytes()


def decode_epoch_state(group: HostGroup, data: bytes) -> EpochState:
    """Inverse of :func:`encode_epoch_state`; raises ValueError on
    malformed bytes."""
    r = serde.Reader(group, data)
    epoch = r.u32()
    n = r.u16()
    t = r.u16()
    index = r.u16() if r.u8() else None
    share = r.scalar() if r.u8() else None
    commitments = None
    if r.u8():
        commitments = tuple(r.point() for _ in range(r.u16()))
    r.done()
    return EpochState(epoch, n, t, index, share, commitments)
