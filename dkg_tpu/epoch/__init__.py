"""Epoch subsystem: proactive share refresh + committee resharing.

The ceremony (dkg_tpu.dkg, driven by dkg_tpu.net.party) produces epoch
0: an (n, t) sharing of the master secret.  This package evolves that
sharing WITHOUT ever changing the master public key:

* :class:`EpochManager` — the networked protocol: 3 broadcast rounds
  per operation over the same channel/WAL as the ceremony, crash
  resumable, churn- and deadline-bounded (see epoch.manager).
* :mod:`~dkg_tpu.epoch.inprocess` — the service lane: same algebra as
  one batched device computation over a locally-held share vector.

See docs/resharing.md for the protocol and its invariance argument.
"""

from .errors import EpochError
from .manager import EPOCH_ROUND_BASE, ROUNDS_PER_OP, EpochManager, epoch_rounds
from .state import (
    KIND_NAMES,
    KIND_REFRESH,
    KIND_RESHARE,
    EpochState,
    confirm_digest,
    decode_epoch_state,
    encode_epoch_state,
    genesis_from_party_result,
)

__all__ = [
    "EPOCH_ROUND_BASE",
    "ROUNDS_PER_OP",
    "EpochError",
    "EpochManager",
    "EpochState",
    "KIND_NAMES",
    "KIND_REFRESH",
    "KIND_RESHARE",
    "confirm_digest",
    "decode_epoch_state",
    "encode_epoch_state",
    "epoch_rounds",
    "genesis_from_party_result",
]
