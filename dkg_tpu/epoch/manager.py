"""EpochManager: sequences refresh/reshare operations over a channel.

Epoch operations ride the SAME broadcast channel and WAL as the
ceremony, in rounds numbered after it: operation k (1-based, counted
across the party's lifetime) occupies channel rounds ``6 + 3*(k-1)``
(deal), ``+1`` (complaints) and ``+2`` (confirm).  That numbering means
every net-layer behavior — first-publish-wins, equivocation evidence,
fault injection (net.faults applies to ANY round number), retained
mailboxes — covers epochs with zero new transport code.

One operation, three steps per party:

1. **deal** — every CURRENT share-holder deals a polynomial via the
   batched ceremony kernels (epoch.dealing): zero-constant for a
   refresh, share-constant (degree t') for a reshare, sealed to the NEW
   committee's keys.  Written to the WAL *before* publishing (the deal
   consumes rng — the ceremony's write-ahead rule, net.party).
2. **complaints** — every NEW member decrypts its shares (one batched
   KEM recovery), checks them against the dealt bare commitments (one
   batched fixed-base mult + point-Horner), and broadcasts the dealers
   that failed.  Publicly invalid deals (bad shape, wrong kind/epoch,
   non-identity refresh constant, reshare constant not matching the
   previous aggregate) need no complaint: every honest party excludes
   them by the same deterministic rule.
3. **confirm** — apply the included deals, derive the new EpochState,
   and broadcast a 16-byte digest of it; the op concludes only when
   >= t'+1 members sent the same digest.  The confirm WAL record pins
   the resulting state, making a crashed party resumable mid-epoch.

Failure leaves ``self.state`` untouched (the previous epoch stays
live); see epoch.errors.  Churn (leave+join count) is bounded by the
``max_churn`` argument, defaulting to the DKG_TPU_EPOCH_MAX_CHURN env
knob; round timeouts default to DKG_TPU_EPOCH_DEADLINE_S.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..fields import host as fh
from ..net.checkpoint import PartyWal
from ..poly import device as poly_device
from ..utils import envknobs, obslog, serde
from ..utils.metrics import REGISTRY
from ..utils.tracing import phase_span
from . import dealing
from .errors import EpochError
from .messages import (
    EpochComplaints,
    EpochConfirm,
    EpochDeal,
    decode_epoch_complaints,
    decode_epoch_confirm,
    decode_epoch_deal,
    encode_epoch_complaints,
    encode_epoch_confirm,
    encode_epoch_deal,
)
from .state import (
    KIND_NAMES,
    KIND_REFRESH,
    KIND_RESHARE,
    EpochState,
    confirm_digest,
    encode_epoch_state,
)

EPOCH_ROUND_BASE = 6  # ceremony rounds are 1..5
ROUNDS_PER_OP = 3

_DECODE_ERRORS = (ValueError, struct.error, IndexError, OverflowError)


def epoch_rounds(op_seq: int) -> tuple[int, int, int]:
    """(deal, complaints, confirm) channel rounds of operation
    ``op_seq`` (1-based)."""
    base = EPOCH_ROUND_BASE + ROUNDS_PER_OP * (op_seq - 1)
    return base, base + 1, base + 2


class EpochManager:
    """Drives epoch operations for ONE party over a broadcast channel.

    ``state`` is the party's current :class:`EpochState` (epoch-0 state
    comes from ``state.genesis_from_party_result``); ``committee_pks``
    the byte-sorted communication keys of the CURRENT committee.  A
    joiner bootstrapping into a reshare passes an observer state
    (index/share/commitments None) plus ``ops_done`` = the number of
    epoch ops the committee already ran, so its round numbers line up.

    With ``checkpoint`` set (a path or PartyWal — the party's CEREMONY
    WAL is fine, epoch records carry their own magic and the two record
    streams skip each other), every step is journaled write-ahead and a
    restarted process replays: recorded publishes are re-published
    byte-identically, closed fetches are re-read from the retained
    mailboxes under the recorded present masks, and the op continues
    live from the first unfinished step.
    """

    def __init__(
        self,
        channel,
        group,
        state: EpochState,
        comm_key,
        committee_pks: list,
        rng,
        *,
        timeout: Optional[float] = None,
        first_fetch_timeout: Optional[float] = None,
        checkpoint=None,
        max_churn: Optional[int] = None,
        trace=None,
        ops_done: int = 0,
    ):
        self.channel = channel
        self.group = group
        self.state = state
        self.comm_key = comm_key
        self.pks = list(committee_pks)
        self.rng = rng
        self.trace = trace
        if timeout is None:
            timeout = envknobs.pos_float(
                "DKG_TPU_EPOCH_DEADLINE_S", "per-epoch-round fetch timeout (s)"
            )
        self.timeout = 30.0 if timeout is None else float(timeout)
        # one-shot deadline for this manager's first live fetch (joiner
        # bootstrap: must span every round preceding the one it joins at)
        self.first_fetch_timeout = first_fetch_timeout
        if max_churn is None:
            max_churn = envknobs.nonneg_int(
                "DKG_TPU_EPOCH_MAX_CHURN",
                "max leave+join churn per reshare; 0 refuses any churn",
            )
        self.max_churn = max_churn  # None = unbounded
        self.op_seq = int(ops_done)
        self.pub_seq = 0  # party-local publish ordinal (causal-flow key)
        self.finished = False  # True once this party has left the committee
        self.quarantined = 0
        self.resumed_steps = 0
        self.wal: Optional[PartyWal] = None
        self._replayed: dict[int, dict[int, serde.EpochRecord]] = {}
        if checkpoint is not None:
            self.wal = (
                checkpoint
                if isinstance(checkpoint, PartyWal)
                else PartyWal(checkpoint)
            )
            self._replayed = self._replay()
        if state.index is not None:
            me = self.comm_key.public().point
            if not (1 <= state.index <= len(self.pks)) or not group.eq(
                self.pks[state.index - 1].point, me
            ):
                raise EpochError(
                    "BAD_COMMITTEE", "state.index does not match committee_pks"
                )

    # -- public operations --------------------------------------------------

    def refresh(self) -> EpochState:
        """Proactive zero-share refresh: same committee, same (n, t),
        same master key, fresh shares.  Returns the new state."""
        if self.state.commitments is None:
            raise EpochError("NO_GENESIS", "refresh needs the current aggregate")
        return self._run_op(KIND_REFRESH, self.pks, self.state.t)

    def reshare(self, new_pks: list, new_t: int) -> Optional[EpochState]:
        """Reshare to a NEW committee (possibly different membership and
        threshold).  Returns the new state, or None when this party is
        not a member of the new committee (it dealt its share-of-share
        and is done)."""
        n_new = len(new_pks)
        if not (1 <= new_t < (n_new + 1) / 2):
            raise EpochError(
                "BAD_COMMITTEE", f"threshold {new_t} invalid for n'={n_new}"
            )
        enc = self.group.encode
        old = {enc(p.point) for p in self.pks}
        new = {enc(p.point) for p in new_pks}
        if len(new) != n_new:
            raise EpochError("BAD_COMMITTEE", "duplicate keys in new committee")
        churn = len(old - new) + len(new - old)
        if self.max_churn is not None and churn > self.max_churn:
            raise EpochError(
                "CHURN_LIMIT", f"churn {churn} exceeds limit {self.max_churn}"
            )
        ordered = sorted(new_pks, key=lambda p: p.sort_key(self.group))
        return self._run_op(KIND_RESHARE, ordered, new_t)

    # -- WAL plumbing -------------------------------------------------------

    def _replay(self) -> dict:
        """Epoch records in the WAL, grouped {op_seq: {step: record}}.
        Records of other layers (the ceremony's b"DKGR") are skipped by
        magic — the mirror image of net.party's replay."""
        out: dict[int, dict[int, serde.EpochRecord]] = {}
        for body in self.wal.replay():
            if not body.startswith(serde.EPOCH_RECORD_MAGIC):
                continue
            try:
                rec = serde.decode_epoch_record(self.group, body)
            except _DECODE_ERRORS:
                continue  # serde-level garbage inside an intact frame
            out.setdefault(rec.op_seq, {})[rec.step] = rec
        return out

    def _record(
        self, op: int, step: int, kind: int, payload: bytes, *,
        present=None, state_bytes=None,
    ) -> None:
        """Append one epoch WAL record.  MUST run before the step's
        publish (write-ahead: the deal step consumes rng, so recomputed
        bytes would equivocate under first-publish-wins)."""
        if self.wal is None:
            return
        body = serde.encode_epoch_record(
            self.group, op, step, kind, payload,
            present=present, state_bytes=state_bytes,
        )
        self.wal.append(body)
        obslog.emit_current("epoch_wal_record", op=op, step=step, bytes=len(body))

    # -- channel plumbing ---------------------------------------------------

    def _publish(self, round_no: int, sender: int, payload: bytes) -> None:
        # same correlation key as net.party publishes: (ceremony_id,
        # round, party, seq) — forensics and flow rendering parse the
        # epoch and ceremony streams with one schema.  Emitted after the
        # channel call, like net.party: the timestamp marks visibility.
        seq = self.pub_seq
        self.pub_seq += 1
        self.channel.publish(round_no, sender, payload)
        obslog.emit_current(
            "epoch_publish", round=round_no, bytes=len(payload), seq=seq
        )
        if self.trace is not None:
            self.trace.bump("net.wire_bytes_out", len(payload))

    def _fetch(self, round_no: int, expected: int, mask) -> dict[int, bytes]:
        """Fetch one epoch round; with a replayed present ``mask`` the
        retained mailbox is filtered to exactly the recorded view (late
        stragglers must not change a resumed step's inputs).

        The FIRST live fetch may use the longer ``first_fetch_timeout``:
        a joiner bootstrapping into a reshare has been waiting since
        before the committee even finished its ceremony, so its opening
        deadline must cover every preceding round, not just one."""
        timeout = self.timeout
        if self.first_fetch_timeout is not None:
            timeout = max(timeout, float(self.first_fetch_timeout))
            self.first_fetch_timeout = None
        if mask is not None:
            got = self.channel.fetch(round_no, len(mask), timeout)
            return {j: got[j] for j in mask if j in got}
        got = self.channel.fetch(round_no, expected, timeout)
        if self.trace is not None:
            self.trace.bump(
                "net.wire_bytes_in", sum(len(v) for v in got.values())
            )
        obslog.emit_current(
            "epoch_tail", round=round_no, present=len(got),
            senders=sorted(got), timed_out=len(got) < expected,
        )
        return got

    # -- the operation ------------------------------------------------------

    def _run_op(self, kind: int, new_pks: list, t_new: int):
        if self.finished:
            raise EpochError(
                "BAD_COMMITTEE", "this party left the committee in an earlier epoch"
            )
        op = self.op_seq + 1
        kname = KIND_NAMES[kind]
        t0 = time.monotonic()
        with phase_span(self.trace, f"epoch_{kname}_op{op}", annotate_device=False):
            try:
                st_new = self._op_body(kind, op, new_pks, t_new)
            except EpochError as e:
                REGISTRY.inc("epoch_ops_total", kind=kname, status=e.kind)
                obslog.emit_current("epoch_done", op=op, op_kind=kname, status=e.kind)
                raise
        REGISTRY.inc("epoch_ops_total", kind=kname, status="ok")
        REGISTRY.observe("epoch_op_seconds", time.monotonic() - t0, kind=kname)
        obslog.emit_current(
            "epoch_done", op=op, op_kind=kname, status="ok",
            epoch=None if st_new is None else st_new.epoch,
        )
        self.op_seq = op
        if st_new is None:
            self.finished = True  # leaver: dealt, holds nothing in the new epoch
        else:
            self.state = st_new
            self.pks = list(new_pks)
        return st_new

    def _op_body(self, kind: int, op: int, new_pks: list, t_new: int):
        group, fs = self.group, self.group.scalar_field
        ra, rb, rc = epoch_rounds(op)
        epoch_new = self.state.epoch + 1
        n_new, n_old, t_old = len(new_pks), self.state.n, self.state.t
        my_old = self.state.index
        me = group.encode(self.comm_key.public().point)
        my_new = next(
            (i + 1 for i, p in enumerate(new_pks) if group.encode(p.point) == me),
            None,
        )
        recs = self._replayed.get(op, {})
        if recs:
            self.resumed_steps += len(recs)
        kname = KIND_NAMES[kind]
        cfg = dealing.epoch_cfg(group, n_new, t_new)

        # ---- step 1: deal (current share-holders only) --------------------
        if self.state.holds_share:
            if 1 in recs:
                payload1 = recs[1].payload
            else:
                constant = 0 if kind == KIND_REFRESH else self.state.share
                comm, enc_shares = dealing.deal_epoch_poly(
                    group, cfg, constant, self.rng, new_pks
                )
                prev_claim = (
                    self.state.commitments if kind == KIND_RESHARE else ()
                )
                payload1 = encode_epoch_deal(
                    group,
                    EpochDeal(
                        kind, epoch_new, tuple(comm), tuple(enc_shares),
                        tuple(prev_claim),
                    ),
                )
                self._record(op, 1, kind, payload1)
            obslog.emit_current("epoch_head", round=ra, op=op, step=1, op_kind=kname)
            self._publish(ra, my_old, payload1)
        if my_new is None:
            # leaver: its share-of-share is dealt; nothing to receive.
            if serde.EPOCH_STEP_CONFIRM not in recs:
                self._record(op, serde.EPOCH_STEP_CONFIRM, kind, b"")
            return None

        # ---- tail 1: fetch + validate deals -------------------------------
        mask_a = recs[2].present if 2 in recs else None
        got = self._fetch(ra, n_old, mask_a)
        deals: dict[int, EpochDeal] = {}
        for j in sorted(got):
            payload = got[j]
            if not (1 <= j <= n_old) or not payload:
                continue
            try:
                d = decode_epoch_deal(group, payload)
            except _DECODE_ERRORS:
                self.quarantined += 1
                REGISTRY.inc("epoch_quarantined_total")
                obslog.emit_current("epoch_quarantine", round=ra, peer=j)
                continue
            if d.kind != kind or d.epoch != epoch_new:
                continue
            if len(d.commitments) != t_new + 1:
                continue
            if sorted(es.recipient_index for es in d.encrypted_shares) != list(
                range(1, n_new + 1)
            ):
                continue
            if kind == KIND_REFRESH and not group.eq(
                d.commitments[0], group.identity()
            ):
                continue  # non-zero constant would move the master key
            if kind == KIND_RESHARE and len(d.prev_commitments) != t_old + 1:
                continue
            deals[j] = d
        present_a = tuple(sorted(got))

        if kind == KIND_RESHARE:
            prev, deals = self._resolve_prev_commitments(deals, t_old)
        else:
            prev = self.state.commitments

        # ---- step 2: decrypt + verify my shares, broadcast complaints -----
        opened = dealing.open_my_shares(
            group, cfg, self.comm_key.sk, deals, my_new
        )
        valid_j = sorted(deals)
        check_j = [j for j in valid_j if opened.get(j) is not None]
        ok = dealing.check_bare_shares(
            group,
            [my_new] * len(check_j),
            [opened[j] for j in check_j],
            [deals[j].commitments for j in check_j],
        )
        accused = sorted(
            {j for j in valid_j if opened.get(j) is None}
            | {j for k, j in enumerate(check_j) if not ok[k]}
        )
        if 2 in recs:
            payload2 = recs[2].payload
        else:
            payload2 = encode_epoch_complaints(
                group, EpochComplaints(kind, epoch_new, tuple(accused))
            )
            self._record(op, 2, kind, payload2, present=present_a)
        obslog.emit_current("epoch_head", round=rb, op=op, step=2, op_kind=kname)
        self._publish(rb, my_new, payload2)

        # ---- tail 2: complaint union -> included dealer set ---------------
        mask_b = recs[3].present if 3 in recs else None
        got_b = self._fetch(rb, n_new, mask_b)
        union: set[int] = set()
        for j, payload in sorted(got_b.items()):
            if not (1 <= j <= n_new) or not payload:
                continue
            try:
                c = decode_epoch_complaints(group, payload)
            except _DECODE_ERRORS:
                self.quarantined += 1
                REGISTRY.inc("epoch_quarantined_total")
                obslog.emit_current("epoch_quarantine", round=rb, peer=j)
                continue
            if c.kind != kind or c.epoch != epoch_new:
                continue
            union |= {a for a in c.accused if 1 <= a <= n_old}
        included = [j for j in valid_j if j not in union]
        if kind == KIND_RESHARE and len(included) < t_old + 1:
            raise EpochError(
                "INSUFFICIENT_DEALERS",
                f"{len(included)} included dealers, need {t_old + 1}",
            )
        if kind == KIND_REFRESH and not included:
            raise EpochError("NO_DEALERS", "no valid refresh deals survived")
        missing = [j for j in included if opened.get(j) is None]
        if missing:
            # an included dealer's share failed only FOR ME and my
            # complaint did not land: liveness loss for this party alone
            raise EpochError(
                "MISSING_SHARE", f"no usable share from included dealers {missing}"
            )

        # ---- step 3: apply, confirm digest --------------------------------
        if kind == KIND_REFRESH:
            new_share = (
                self.state.share + sum(opened[j] for j in included)
            ) % fs.modulus
            new_comm = []
            for lvl in range(t_new + 1):
                acc = prev[lvl]
                for j in included:
                    acc = group.add(acc, deals[j].commitments[lvl])
                new_comm.append(acc)
            new_comm = tuple(new_comm)
        else:
            xs = jnp.asarray(fh.encode(fs, included))
            ys = jnp.asarray(fh.encode(fs, [opened[j] for j in included]))
            lam = poly_device.lagrange_at_zero_coeffs(fs, xs)
            new_share = int(
                fh.decode(fs, np.asarray(poly_device.lagrange_at_zero(fs, xs, ys)))
            )
            new_comm = dealing.combine_reshare_commitments(
                group, lam, [deals[j].commitments for j in included]
            )
        if not group.eq(new_comm[0], prev[0]):
            raise EpochError("MASTER_DRIFT", "new aggregate moved the master key")

        st_new = EpochState(epoch_new, n_new, t_new, my_new, new_share, new_comm)
        digest = confirm_digest(group, kind, epoch_new, n_new, t_new, new_comm)
        if 3 in recs:
            payload3 = recs[3].payload
        else:
            payload3 = encode_epoch_confirm(
                group, EpochConfirm(kind, epoch_new, digest)
            )
            self._record(
                op, 3, kind, payload3,
                present=tuple(sorted(got_b)),
                state_bytes=encode_epoch_state(group, st_new),
            )
        obslog.emit_current("epoch_head", round=rc, op=op, step=3, op_kind=kname)
        self._publish(rc, my_new, payload3)

        # ---- tail 3: digest agreement -------------------------------------
        got_c = self._fetch(rc, n_new, None)
        agree = 1  # my own digest
        for j, payload in sorted(got_c.items()):
            if j == my_new or not (1 <= j <= n_new) or not payload:
                continue
            try:
                c = decode_epoch_confirm(group, payload)
            except _DECODE_ERRORS:
                self.quarantined += 1
                continue
            if c.kind == kind and c.epoch == epoch_new and c.digest == digest:
                agree += 1
        if agree < t_new + 1:
            raise EpochError(
                "CONFIRM_DIVERGENCE",
                f"{agree} matching confirms, need {t_new + 1}",
            )
        return st_new

    def _resolve_prev_commitments(self, deals: dict, t_old: int):
        """The previous aggregate a reshare verifies against.

        Stayers hold it and drop dealers whose claim differs; joiners
        bootstrap by t+1-majority over the claims (<= t faulty dealers
        can never assemble a t+1 quorum on a false aggregate).  Then one
        batched check binds every dealer's constant A_{i,0} to
        eval(prev, i) — the step that makes the reshared secret provably
        the current one."""
        group = self.group
        if self.state.commitments is not None:
            prev = self.state.commitments
            prev_enc = tuple(group.encode(c) for c in prev)
            deals = {
                j: d
                for j, d in deals.items()
                if tuple(group.encode(c) for c in d.prev_commitments) == prev_enc
            }
        else:
            counts: dict[tuple, list[int]] = {}
            for j in sorted(deals):
                key = tuple(group.encode(c) for c in deals[j].prev_commitments)
                counts.setdefault(key, []).append(j)
            best = max(
                counts.items(), key=lambda kv: (len(kv[1]), kv[0]), default=None
            )
            if best is None or len(best[1]) < t_old + 1:
                raise EpochError(
                    "NO_PREV_COMMITMENTS",
                    "no t+1-majority claim of the current aggregate",
                )
            prev = deals[best[1][0]].prev_commitments
            deals = {j: deals[j] for j in best[1]}
        idxs = sorted(deals)
        ok = dealing.check_reshare_constants(
            group, prev, idxs, [deals[j].commitments[0] for j in idxs]
        )
        return prev, {j: deals[j] for k, j in enumerate(idxs) if ok[k]}
