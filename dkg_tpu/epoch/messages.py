"""Epoch wire messages + their deterministic byte codecs.

Three broadcast rounds per epoch operation (deal, complaints, confirm),
mirroring the ceremony's wire discipline: fixed-width little-endian
integers, length-prefixed bytes, group-backend point encodings, decode
of untrusted bytes never executes anything and any malformed input
raises ValueError (the manager quarantines it exactly like net.party
does for ceremony rounds).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..dkg.broadcast import EncryptedShares
from ..groups.host import HostGroup
from ..utils import serde
from .state import KIND_REFRESH, KIND_RESHARE

_KINDS = (KIND_REFRESH, KIND_RESHARE)


@dataclass(frozen=True)
class EpochDeal:
    """One dealer's epoch-round-1 broadcast.

    ``commitments`` are the BARE Feldman commitments (g*c_l) of the
    dealt polynomial — epochs never need the Pedersen hiding leg, the
    dealt values are already bound by the previous epoch's commitments.
    For a refresh the constant term commits to zero (identity point);
    for a reshare it commits to the dealer's share of the current
    aggregate, and ``prev_commitments`` carries the dealer's claim of
    that aggregate so JOINERS (who hold no state yet) can bootstrap by
    t+1-majority over the claims.
    """

    kind: int
    epoch: int  # the epoch this deal CREATES (state.epoch + 1)
    commitments: tuple  # (t'+1) bare commitment points
    encrypted_shares: tuple  # EncryptedShares, one per new-committee member
    prev_commitments: tuple = ()  # reshare only: claimed current aggregate

    def shares_for(self, index: int) -> Optional[EncryptedShares]:
        for es in self.encrypted_shares:
            if es.recipient_index == index:
                return es
        return None


@dataclass(frozen=True)
class EpochComplaints:
    """Epoch-round-2 broadcast: dealers (old-committee indices) whose
    sealed share failed this member's decryption or bare-commitment
    check.  Always published (possibly empty) by every member of the
    NEW committee, so the round never times out structurally."""

    kind: int
    epoch: int
    accused: tuple  # old-committee dealer indices


@dataclass(frozen=True)
class EpochConfirm:
    """Epoch-round-3 broadcast: 16-byte digest of the resulting epoch
    state (state.confirm_digest).  An op concludes only when >= t'+1
    members published the same digest — agreement on the new aggregate
    before anyone discards old-epoch material."""

    kind: int
    epoch: int
    digest: bytes


def encode_epoch_deal(group: HostGroup, b: EpochDeal) -> bytes:
    w = serde.Writer(group)
    w.u8(b.kind)
    w.u16(b.epoch)
    w.u16(len(b.commitments))
    for c in b.commitments:
        w.point(c)
    w.u16(len(b.encrypted_shares))
    for es in b.encrypted_shares:
        serde._w_shares(w, es)
    w.u16(len(b.prev_commitments))
    for c in b.prev_commitments:
        w.point(c)
    return w.bytes()


def decode_epoch_deal(group: HostGroup, data: bytes) -> EpochDeal:
    r = serde.Reader(group, data)
    kind = r.u8()
    if kind not in _KINDS:
        raise ValueError("unknown epoch deal kind")
    epoch = r.u16()
    commitments = tuple(r.point() for _ in range(r.u16()))
    shares = tuple(serde._r_shares(r) for _ in range(r.u16()))
    prev = tuple(r.point() for _ in range(r.u16()))
    r.done()
    return EpochDeal(kind, epoch, commitments, shares, prev)


def encode_epoch_complaints(group: HostGroup, b: EpochComplaints) -> bytes:
    w = serde.Writer(group)
    w.u8(b.kind)
    w.u16(b.epoch)
    w.u16(len(b.accused))
    for j in b.accused:
        w.u16(j)
    return w.bytes()


def decode_epoch_complaints(group: HostGroup, data: bytes) -> EpochComplaints:
    r = serde.Reader(group, data)
    kind = r.u8()
    if kind not in _KINDS:
        raise ValueError("unknown epoch complaints kind")
    epoch = r.u16()
    accused = tuple(r.u16() for _ in range(r.u16()))
    r.done()
    return EpochComplaints(kind, epoch, accused)


def encode_epoch_confirm(group: HostGroup, b: EpochConfirm) -> bytes:
    w = serde.Writer(group)
    w.u8(b.kind)
    w.u16(b.epoch)
    w.lp(b.digest)
    return w.bytes()


def decode_epoch_confirm(group: HostGroup, data: bytes) -> EpochConfirm:
    r = serde.Reader(group, data)
    kind = r.u8()
    if kind not in _KINDS:
        raise ValueError("unknown epoch confirm kind")
    epoch = r.u16()
    digest = r.lp()
    if len(digest) != 16:
        raise ValueError("epoch confirm digest must be 16 bytes")
    r.done()
    return EpochConfirm(kind, epoch, digest)
