"""Typed failures of the epoch subsystem (refresh / resharing).

Separate from :class:`~dkg_tpu.dkg.errors.DkgError`: a failed epoch op
leaves the PREVIOUS epoch's state fully intact (the manager mutates its
state only after the confirm step), so callers catch EpochError, keep
serving the old shares, and retry — a ceremony-level DkgError has no
such "keep the old key" recovery.
"""

from __future__ import annotations


class EpochError(RuntimeError):
    """One epoch operation (refresh or reshare) failed; the party's
    previous epoch state is untouched.  ``kind`` is a stable string
    (NO_DEALERS, INSUFFICIENT_DEALERS, CHURN_LIMIT, CONFIRM_DIVERGENCE,
    MASTER_DRIFT, NO_GENESIS, BAD_COMMITTEE, NO_PREV_COMMITMENTS,
    MISSING_SHARE)."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind
        self.detail = detail
