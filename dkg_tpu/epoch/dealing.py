"""Device legs of epoch operations — thin wrappers over the ceremony's
batched dealing/verify kernels.

Everything EC-expensive in an epoch op goes through the same entry
points the ceremony uses (lint rule DKG008 pins this):

* dealing: :func:`~dkg_tpu.dkg.ceremony.deal_chunked` (commitments +
  share rows in one batched call) and
  :func:`~dkg_tpu.dkg.hybrid_batch.seal_shares_pipeline` (KEM+DEM for
  all recipients at once), packaged by ``broadcasts_from_batch``;
* recipient-side decryption: ``open_shares_batch`` (one batched KEM
  recovery for all dealers);
* share verification: ``gd.fixed_base_mul`` + ``gd.eval_point_poly``
  over all (dealer, share) rows at once — the bare-commitment twin of
  complaints_batch.check_randomized_shares_limbs (epochs carry no
  Pedersen hiding leg, the dealt constants are already bound by the
  previous epoch's commitments).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..dkg.ceremony import CeremonyConfig, deal_chunked
from ..dkg.hybrid_batch import (
    broadcasts_from_batch,
    open_shares_batch,
    seal_shares_pipeline,
)
from ..fields import host as fh
from ..groups import device as gd
from ..groups import precompute
from ..groups import host as gh


def epoch_cfg(group: gh.HostGroup, n: int, t: int) -> CeremonyConfig:
    """Jit-static shape of one epoch dealing: the RECIPIENT committee's
    (n, t)."""
    return CeremonyConfig(group.name, n, t)


def deal_epoch_poly(
    group: gh.HostGroup,
    cfg: CeremonyConfig,
    constant: int,
    rng,
    recipient_pks: list,
) -> tuple[tuple, tuple]:
    """Deal one degree-``cfg.t`` polynomial with the given constant term
    to ``cfg.n`` recipients via the batched ceremony kernels.

    constant = 0 is a refresh deal (zero-constant, master-invariant);
    constant = the dealer's current share is a reshare deal
    (shares-of-the-share).  Returns ``(commitments, encrypted_shares)``
    — the (t+1) BARE commitment points and one sealed EncryptedShares
    per recipient.  The hiding polynomial is identically zero: epochs
    use bare Feldman commitments only.
    """
    cs, fs = cfg.cs, group.scalar_field
    coeffs = [constant % fs.modulus] + [fs.rand_int(rng) for _ in range(cfg.t)]
    coeffs_a = jnp.asarray(fh.encode(fs, [coeffs]))
    coeffs_b = jnp.zeros_like(coeffs_a)
    g_table = precompute.generator_table(cs)
    # zero hiding coefficients make the h-leg a no-op, so the g table
    # stands in for h — epochs need no commitment key at all
    bare, _rand, shares, hidings = deal_chunked(
        cfg, coeffs_a, coeffs_b, g_table, g_table
    )
    pks_dev = gd.from_host(cs, [p.point for p in recipient_pks])
    r_enc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(cfg.n)]])
    )
    sealed = seal_shares_pipeline(
        group, cfg, shares, hidings, pks_dev, r_enc, g_table
    )
    b = broadcasts_from_batch(group, cfg, np.asarray(bare), sealed)[0]
    return b.committed_coefficients, b.encrypted_shares


def open_my_shares(
    group: gh.HostGroup,
    cfg: CeremonyConfig,
    sk: int,
    deals: dict,
    my_index: int,
) -> dict:
    """Decrypt this member's sealed share from every deal in one
    batched KEM recovery: {dealer_index: share_int | None}."""
    order = sorted(deals)
    pairs = []
    for j in order:
        es = deals[j].shares_for(my_index)
        pairs.append((es.share_ct, es.randomness_ct))
    vals = open_shares_batch(group, cfg, sk, pairs)
    return {j: vals[k][0] for k, j in enumerate(order)}


def check_bare_shares(
    group: gh.HostGroup,
    indices: list[int],
    shares: list[int],
    coeffs_list: list[tuple],
) -> np.ndarray:
    """Batched g*s == sum_l idx^l A_l over k independent (dealer, share)
    rows — one fixed-base batch mult + one batched point-Horner."""
    if not indices:
        return np.zeros((0,), dtype=bool)
    cs = gd.ALL_CURVES[group.name]
    fs = group.scalar_field
    k, tp1 = len(indices), len(coeffs_list[0])
    s_limbs = jnp.asarray(fh.encode(fs, shares))
    flat = [c for coeffs in coeffs_list for c in coeffs]
    cpts = gd.from_host(cs, flat).reshape(k, tp1, cs.ncoords, cs.field.limbs)
    idx = jnp.asarray(indices, dtype=jnp.uint32)
    nbits = max(2, int(max(indices)).bit_length())
    lhs = gd.fixed_base_mul(cs, precompute.generator_table(cs), s_limbs)
    rhs = gd.eval_point_poly(cs, cpts, idx, nbits)
    return np.asarray(gd.eq(cs, lhs, rhs))


def check_reshare_constants(
    group: gh.HostGroup,
    prev_commitments: tuple,
    dealer_indices: list[int],
    claimed_constants: list,
) -> np.ndarray:
    """Batched A_{i,0} == eval(prev_commitments, i): a reshare dealer's
    constant term must commit to its ACTUAL share of the current
    aggregate — the binding that makes the reshared secret provably the
    old one."""
    if not dealer_indices:
        return np.zeros((0,), dtype=bool)
    cs = gd.ALL_CURVES[group.name]
    k, tp1 = len(dealer_indices), len(prev_commitments)
    prev = gd.from_host(cs, list(prev_commitments))
    cpts = jnp.broadcast_to(
        prev[None], (k, tp1, cs.ncoords, cs.field.limbs)
    )
    idx = jnp.asarray(dealer_indices, dtype=jnp.uint32)
    nbits = max(2, int(max(dealer_indices)).bit_length())
    lhs = gd.from_host(cs, list(claimed_constants))
    rhs = gd.eval_point_poly(cs, cpts, idx, nbits)
    return np.asarray(gd.eq(cs, lhs, rhs))


def combine_reshare_commitments(
    group: gh.HostGroup,
    lam_limbs: jnp.ndarray,  # (M, L) Lagrange-at-zero coefficients
    coeffs_list: list[tuple],  # M dealers' (t'+1) commitment tuples
) -> tuple:
    """New aggregate commitments C'_l = sum_i lambda_i * A_{i,l} as ONE
    batched scalar-mult over all M*(t'+1) points plus a point-add fold."""
    cs = gd.ALL_CURVES[group.name]
    m, tp1 = len(coeffs_list), len(coeffs_list[0])
    flat = [c for coeffs in coeffs_list for c in coeffs]
    pts = gd.from_host(cs, flat).reshape(m, tp1, cs.ncoords, cs.field.limbs)
    lam_b = jnp.broadcast_to(lam_limbs[:, None, :], (m, tp1, lam_limbs.shape[-1]))
    scaled = gd.scalar_mul(cs, lam_b, pts)
    acc = scaled[0]
    for i in range(1, m):
        acc = gd.add(cs, acc, scaled[i])
    return tuple(gd.to_host(cs, np.asarray(acc)))
