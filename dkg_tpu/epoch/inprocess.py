"""In-process epoch operations over a full share vector.

The service lane (service.scheduler) holds ALL final shares of a hosted
ceremony in one process, so refresh/reshare need no channel, no sealing
and no complaints — just the polynomial algebra, batched on device:

* refresh: every "dealer" row i contributes a zero-constant degree-t
  polynomial u_i; new_share_j = old_share_j + sum_i u_i(j).  The
  aggregate constant F(0) gains sum_i u_i(0) = 0, so the master key is
  untouched by construction.
* reshare: dealer row i deals a degree-t' polynomial h_i with
  h_i(0) = old_share_i; new_share_j = sum_i lambda_i * h_i(j) with
  lambda_i the Lagrange-at-zero coefficients of the OLD indices.  The
  new aggregate's constant is sum_i lambda_i * old_share_i = F(0).

Both are one :func:`~dkg_tpu.poly.device.eval_many` call (an (n,
t+1)-coefficient tensor evaluated at all recipient indices at once)
plus field-add folds — no per-pair scalar loops (lint rule DKG008).
tests/test_epoch_inprocess.py pins both against the poly.host oracle.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..fields import device as fd
from ..fields import host as fh
from ..fields.host import FieldSpec
from ..poly import device as poly_device


def _indices(fs: FieldSpec, n: int) -> jnp.ndarray:
    return jnp.asarray(fh.encode(fs, list(range(1, n + 1))))  # (n, L)


def _coeff_tensor(fs: FieldSpec, constants: list[int], ncoeffs: int, rng):
    """(rows, ncoeffs, L) coefficient tensor: column 0 holds
    ``constants``, the rest fresh CSPRNG scalars (host-side sampling,
    like the ceremony's batched_dealing)."""
    rows = [
        [c % fs.modulus] + [fs.rand_int(rng) for _ in range(ncoeffs - 1)]
        for c in constants
    ]
    return jnp.asarray(fh.encode(fs, rows))


def _fold_dealers(fs: FieldSpec, m: jnp.ndarray) -> jnp.ndarray:
    """Sum an (n_dealers, n_recipients, L) share matrix over dealers."""
    acc = m[0]
    for i in range(1, m.shape[0]):
        acc = fd.add(fs, acc, m[i])
    return acc


def refresh_shares(
    fs: FieldSpec, n: int, t: int, shares: list[int], rng
) -> list[int]:
    """Proactively refresh a full (n, t) share vector; the shared
    secret (and master key) is invariant.  Returns the new shares."""
    if len(shares) != n:
        raise ValueError(f"expected {n} shares, got {len(shares)}")
    coeffs = _coeff_tensor(fs, [0] * n, t + 1, rng)  # (n, t+1, L)
    deltas = poly_device.eval_many(fs, coeffs, _indices(fs, n))  # (n, n, L)
    old = jnp.asarray(fh.encode(fs, shares))
    new = fd.add(fs, old, _fold_dealers(fs, deltas))
    return [int(v) for v in fh.decode(fs, np.asarray(new))]


def reshare_shares(
    fs: FieldSpec,
    n: int,
    t: int,
    shares: list[int],
    n_new: int,
    t_new: int,
    rng,
) -> list[int]:
    """Reshare an (n, t) share vector into a fresh (n_new, t_new) one of
    the SAME secret.  Returns the new committee's shares (1..n_new)."""
    if len(shares) != n:
        raise ValueError(f"expected {n} shares, got {len(shares)}")
    if n < t + 1:
        raise ValueError(f"need at least t+1={t + 1} dealers, have {n}")
    if n_new < t_new + 1:
        raise ValueError(
            f"new committee of {n_new} cannot reconstruct at threshold "
            f"{t_new} (need n' >= t'+1)"
        )
    coeffs = _coeff_tensor(fs, shares, t_new + 1, rng)  # (n, t_new+1, L)
    m = poly_device.eval_many(fs, coeffs, _indices(fs, n_new))  # (n, n_new, L)
    lam = poly_device.lagrange_at_zero_coeffs(fs, _indices(fs, n))  # (n, L)
    lam_b = jnp.broadcast_to(lam[:, None, :], m.shape)
    new = _fold_dealers(fs, fd.mul(fs, lam_b, m))  # (n_new, L)
    return [int(v) for v in fh.decode(fs, np.asarray(new))]
