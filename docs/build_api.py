#!/usr/bin/env python
"""Generate the static API reference (docs/api/) from docstrings.

Dependency-free (stdlib inspect + html): walks the dkg_tpu package,
emits one HTML page per module with class/function signatures and
docstrings, KaTeX-enabled via docs/katex-header.html so $...$ math in
docstrings renders (the counterpart of the reference's rustdoc +
katex-header.html pipeline).

Usage:  python docs/build_api.py        (writes docs/api/*.html)
"""

from __future__ import annotations

import html
import importlib
import inspect
import pkgutil
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

STYLE = """
body { font: 15px/1.5 system-ui, sans-serif; max-width: 60rem;
       margin: 2rem auto; padding: 0 1rem; color: #1a1a1a; }
pre, code { background: #f4f4f5; border-radius: 4px; font-size: 0.92em; }
pre { padding: 0.7em 0.9em; overflow-x: auto; white-space: pre-wrap; }
h2 { border-bottom: 1px solid #ddd; padding-bottom: 0.2em; }
.sig { background: #eef2f7; padding: 0.5em 0.8em; border-radius: 4px;
       font-family: ui-monospace, monospace; font-size: 0.9em; }
.doc { margin: 0.5em 0 1.5em 1.5em; }
nav a { margin-right: 1em; }
"""


def _header() -> str:
    katex = (ROOT / "docs" / "katex-header.html").read_text()
    return f"<meta charset='utf-8'>{katex}<style>{STYLE}</style>"


def _doc_html(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    return f"<pre class='doc'>{html.escape(doc)}</pre>" if doc else ""


def _sig(obj) -> str:
    try:
        return html.escape(str(inspect.signature(obj)))
    except (ValueError, TypeError):
        return "(...)"


def render_module(modname: str) -> str:
    mod = importlib.import_module(modname)
    parts = [
        f"<!DOCTYPE html><html><head><title>{modname}</title>{_header()}</head><body>",
        "<nav><a href='index.html'>index</a><a href='../protocol.html'>protocol</a></nav>",
        f"<h1><code>{modname}</code></h1>",
        _doc_html(mod),
    ]
    members = [
        (name, obj)
        for name, obj in vars(mod).items()
        if not name.startswith("_")
        and (inspect.isclass(obj) or inspect.isfunction(obj))
        and getattr(obj, "__module__", None) == modname
    ]
    for name, obj in members:
        if inspect.isclass(obj):
            parts.append(f"<h2 id='{name}'>class <code>{name}</code></h2>")
            parts.append(_doc_html(obj))
            for mname, meth in vars(obj).items():
                func = meth.__func__ if isinstance(meth, classmethod) else meth
                if mname.startswith("_") or not inspect.isfunction(func):
                    continue
                parts.append(
                    f"<div class='sig'>{name}.{mname}{_sig(func)}</div>"
                )
                parts.append(_doc_html(func))
        else:
            parts.append(f"<h2 id='{name}'><code>{name}</code></h2>")
            parts.append(f"<div class='sig'>{name}{_sig(obj)}</div>")
            parts.append(_doc_html(obj))
    parts.append("</body></html>")
    return "\n".join(parts)


def main() -> None:
    import dkg_tpu

    outdir = ROOT / "docs" / "api"
    outdir.mkdir(parents=True, exist_ok=True)
    modules = ["dkg_tpu"]
    for info in pkgutil.walk_packages(dkg_tpu.__path__, prefix="dkg_tpu."):
        if ".native" in info.name:
            continue  # ctypes loader: importing may build the C library
        modules.append(info.name)
    written = []
    for m in sorted(modules):
        try:
            out = render_module(m)
        except Exception as exc:  # pragma: no cover — skip unimportables
            print(f"skip {m}: {exc}", file=sys.stderr)
            continue
        (outdir / f"{m}.html").write_text(out)
        written.append(m)
    index = [
        f"<!DOCTYPE html><html><head><title>dkg_tpu API</title>{_header()}</head><body>",
        "<h1>dkg_tpu API reference</h1>",
        "<p><a href='../protocol.html'>Protocol walkthrough (rendered math)</a></p>",
        "<ul>",
        *(f"<li><a href='{m}.html'><code>{m}</code></a></li>" for m in written),
        "</ul></body></html>",
    ]
    (outdir / "index.html").write_text("\n".join(index))
    print(f"wrote {len(written)} module pages to {outdir}")


if __name__ == "__main__":
    main()
